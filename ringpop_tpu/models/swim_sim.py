"""TPU SWIM simulation backend: the membership + dissemination layers as
vmapped epidemic-broadcast kernels over dense N x N view/state tensors.

This is the tensorized re-design of the reference's L3+L4
(lib/membership.js, lib/dissemination.js, lib/swim/*): instead of one
process per node exchanging JSON change lists over TChannel, every virtual
node's *view* of the cluster is one row of a dense tensor, and one jitted
``swim_step`` advances every node through one protocol period
simultaneously.  The "network" is a boolean delivery mask — packet loss,
partitions and suspended processes are all mask edits (the fault-injection
surface replacing tick-cluster.js signals).

Semantics parity map (reference file:line -> here):

* membership-update-rules.js:25-59  -> ``_lattice_key`` / ``_apply_mask``:
  the incarnation-precedence lattice is a total-order key
  ``inc * 8 + rank`` (rank: alive<suspect<faulty<leave) plus two masks for
  the non-total corners (leave is only ever overridden by a
  strictly-newer alive; membership.js first-sight takes any change).
* membership.js:243-254             -> refutation: any suspect/faulty rumor
  about self re-asserts alive with ``max(self_inc, rumor_inc) + 1``.
* dissemination.js:125-177          -> per-(viewer, subject) piggyback
  counts; a recorded change is issued while ``pb < max_piggyback``, where
  ``max_piggyback = factor * ceil(log10(server_count + 1))``
  (dissemination.js:38-55), and evicted past it.  A change's payload is
  always the viewer's current (status, incarnation) for the subject — the
  reference's change buffer is keyed by address and overwritten on every
  applied update, so only (pb, source, source_inc) need separate storage.
* dissemination.js:86-98            -> anti-echo: replies drop changes whose
  (source, sourceIncarnation) equal the ping sender's identity.
* dissemination.js:61-76,100-118    -> full sync: a receiver with nothing to
  piggyback but a checksum mismatch answers with its entire view row.
* swim/ping-sender.js, ping-handler -> phase 2/3/4 of ``swim_step``.
* swim/ping-req-sender.js:153-296   -> phase 5: k random witnesses, two-hop
  reachability, all-definite-failures => suspect.
* swim/suspicion.js                 -> per-(viewer, subject) deadline ticks;
  expiry declares faulty; alive stops the timer; re-suspect restarts it.
* membership-iterator.js            -> probe-target selection; the reference
  uses a reshuffled round-robin, the simulation samples uniformly among
  pingable members (distributionally equivalent; documented deviation).

Time model: one call to ``swim_step`` == one protocol period
(gossip.js:127-129, 200 ms) for every node at once.  Wall-clock timeouts
become tick counts (suspicion 5000 ms -> 25 ticks).  The reference's ping
timeout (1500 ms) spans periods; the simulation compresses
ping + ping-req + suspect-declaration into the probing tick.  Convergence
measured in ticks maps to wall-clock via ``period_ms``.

Documented intra-tick conventions (where the async reference has no
defined order):

* Concurrent inbound pings at one receiver are merged by the lattice's
  total-order key (the reference applies them in arrival order; both end
  at the lattice maximum except for contrived leave/suspect mixes).
* A receiver's reply piggyback counter advances by the number of inbound
  pings it served that tick, but all probers of the tick see the same
  issued set.
* The ping-req path probes reachability only; its piggyback exchange is
  omitted (convergence-neutral, traffic-level deviation).

Incarnation numbers are stored as int32 offsets from a host-side base
(``SimCluster`` keeps the absolute int ms base) so all device arithmetic is
x64-free; the lattice key needs ``inc * 8`` to fit int32, so relative
incarnations must stay below 2**27 (~37 hours of ms).
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp


# Status encoding: lattice rank == code - 1 (alive < suspect < faulty < leave,
# matching equal-incarnation precedence in membership-update-rules.js).
NONE = 0
ALIVE = 1
SUSPECT = 2
FAULTY = 3
LEAVE = 4

STATUS_NAMES = {ALIVE: "alive", SUSPECT: "suspect", FAULTY: "faulty", LEAVE: "leave"}

_KEY_MIN = jnp.iinfo(jnp.int32).min


class SwimParams(NamedTuple):
    """Protocol constants (reference defaults cited per field)."""

    period_ms: int = 200  # gossip.js:127-129 minProtocolPeriod
    suspicion_ticks: int = 25  # suspicion.js:110-112 (5000 ms / period)
    piggyback_factor: int = 15  # dissemination.js:133-136
    ping_req_size: int = 3  # index.js:99
    loss: float = 0.0  # iid per-message drop probability
    # Flap damping (EXTENSION; active only when the state carries damp
    # tensors — init_state(damping=True)).  Mirrors damping.py: penalty
    # per flap, exponential decay, suppress/reuse hysteresis.  Default
    # decay 0.5 ** (tick / half-life) with 60 s half-life @ 200 ms ticks.
    damp_penalty: float = 500.0
    damp_suppress: float = 2500.0
    damp_reuse: float = 500.0
    damp_decay_per_tick: float = 0.5 ** (0.2 / 60.0)


class ClusterState(NamedTuple):
    """Per-(viewer i, subject j) membership views + dissemination buffers.

    ``view_status[i, j]`` / ``view_inc[i, j]``: node i's belief about j
    (membership.js member records, one row per node).  ``pb[i, j]`` is the
    piggyback count of i's recorded change about j (-1: no change
    recorded); ``src``/``src_inc`` are the change's originator
    (dissemination.js change.source / sourceIncarnationNumber; -1 absent).
    ``suspect_at[i, j]``: tick when i started suspecting j (-1: no timer)
    — the tensor form of per-node Suspicion.timers (suspicion.js:27).
    """

    view_status: jax.Array  # int8[N, N]
    view_inc: jax.Array  # int32[N, N]
    pb: jax.Array  # int16[N, N]
    src: jax.Array  # int32[N, N]
    src_inc: jax.Array  # int32[N, N]
    suspect_at: jax.Array  # int32[N, N]
    tick: jax.Array  # int32[]
    # Flap-damping extension (None = disabled, zero cost): viewer i's damp
    # score for j and the hysteresis "currently damped" bit (damping.py).
    damp: jax.Array | None = None  # float16[N, N]
    damped: jax.Array | None = None  # bool[N, N]

    @property
    def n(self) -> int:
        return self.view_status.shape[0]


class NetState(NamedTuple):
    """The simulated network: the fault-injection surface.

    ``up``: process exists (kill -> False).  ``responsive``: process
    scheduled (SIGSTOP analog -> False; state is retained, the node just
    neither probes nor answers — tick-cluster.js:432-446).  ``adj``:
    directed connectivity; partitions are block masks.
    """

    up: jax.Array  # bool[N]
    responsive: jax.Array  # bool[N]
    adj: jax.Array  # bool[N, N]


def make_net(n: int) -> NetState:
    return NetState(
        up=jnp.ones((n,), dtype=bool),
        responsive=jnp.ones((n,), dtype=bool),
        adj=jnp.ones((n, n), dtype=bool),
    )


def init_state(
    n: int,
    inc: jax.Array | None = None,
    *,
    mode: str = "converged",
    damping: bool = False,
) -> ClusterState:
    """Fresh cluster state.

    ``mode='converged'``: every node already knows every node alive (the
    post-bootstrap fixture for churn/fault benchmarks).  ``mode='self'``:
    each node knows only itself (pre-join; discover via ``admin_join``).
    ``inc``: initial incarnation per node (relative ms), default 0.
    """
    if inc is None:
        inc = jnp.zeros((n,), dtype=jnp.int32)
    inc = jnp.asarray(inc, dtype=jnp.int32)
    eye = jnp.eye(n, dtype=bool)
    if mode == "converged":
        status = jnp.full((n, n), ALIVE, dtype=jnp.int8)
        view_inc = jnp.broadcast_to(inc[None, :], (n, n)).astype(jnp.int32)
    elif mode == "self":
        status = jnp.where(eye, ALIVE, NONE).astype(jnp.int8)
        view_inc = jnp.where(eye, inc[None, :], 0).astype(jnp.int32)
    else:
        raise ValueError(f"unknown init mode: {mode}")
    return ClusterState(
        view_status=status,
        view_inc=view_inc,
        pb=jnp.full((n, n), -1, dtype=jnp.int16),
        src=jnp.full((n, n), -1, dtype=jnp.int32),
        src_inc=jnp.full((n, n), -1, dtype=jnp.int32),
        suspect_at=jnp.full((n, n), -1, dtype=jnp.int32),
        tick=jnp.zeros((), dtype=jnp.int32),
        damp=jnp.zeros((n, n), dtype=jnp.float16) if damping else None,
        damped=jnp.zeros((n, n), dtype=bool) if damping else None,
    )


# ---------------------------------------------------------------------------
# lattice (membership-update-rules.js as uint arithmetic)
# ---------------------------------------------------------------------------


def _lattice_key(status: jax.Array, inc: jax.Array) -> jax.Array:
    """Total-order key of a (status, incarnation) claim; NONE -> minimum.

    ``inc * 8 + rank + 1`` realizes: alive overrides at strictly newer
    incarnation; suspect/faulty/leave override lower ranks at equal
    incarnation and anything at newer incarnation.  The two places the
    real lattice is *not* this total order are handled by ``_apply_mask``.
    """
    key = inc.astype(jnp.int32) * 8 + status.astype(jnp.int32)
    return jnp.where(status == NONE, _KEY_MIN, key)


def _apply_mask(
    cur_status: jax.Array,
    cur_key: jax.Array,
    in_status: jax.Array,
    in_key: jax.Array,
) -> jax.Array:
    """Does the incoming claim override the current view entry?

    key-greater, except: an existing ``leave`` entry is only overridden by
    ``alive`` (is_leave/suspect/faulty_override exclude leave members —
    membership-update-rules.js:31-42,54-59), while a first-sighted member
    (cur NONE, key minimum) takes any change wholesale
    (membership.js:230-247).
    """
    beats = in_key > cur_key
    leave_guard = (cur_status == LEAVE) & (in_status != ALIVE)
    return beats & ~leave_guard & (in_status != NONE)


def _view_hash(state: ClusterState) -> jax.Array:
    """Cheap commutative per-node view digest, uint32[N].

    Stands in for the membership checksum *inside the protocol* (the
    full-sync trigger needs only equality, dissemination.js:100-118).
    Reported/parity checksums are the real farmhash over the reference's
    string format — see models/checksum.py.
    """
    s = state.view_status.astype(jnp.uint32)
    i = state.view_inc.astype(jnp.uint32)
    h = (i ^ (s * jnp.uint32(0x9E3779B9))) * jnp.uint32(0x85EBCA6B)
    h = (h ^ (h >> jnp.uint32(13))) * jnp.uint32(0xC2B2AE35)
    h = h ^ (h >> jnp.uint32(16))
    idx = jnp.arange(state.n, dtype=jnp.uint32) * jnp.uint32(0x27D4EB2F)
    h = jnp.where(state.view_status != NONE, h ^ idx, jnp.uint32(0))
    return jnp.sum(h, axis=1, dtype=jnp.uint32)


def _max_piggyback(state: ClusterState, factor: int) -> jax.Array:
    """``factor * ceil(log10(server_count + 1))`` per node, exactly
    (dissemination.js:38-55); server count ~ members the node would have
    in its ring (alive + suspect — suspects stay in the ring,
    membership-update-listener.js:34-45)."""
    sc = jnp.sum(
        (state.view_status == ALIVE) | (state.view_status == SUSPECT),
        axis=1,
        dtype=jnp.int32,
    )
    x = sc + 1
    digits = jnp.zeros_like(x)
    p = jnp.int32(1)
    for _ in range(10):
        digits = digits + (x > p).astype(jnp.int32)
        p = p * 10
    return factor * digits


def _pingable(state: ClusterState) -> jax.Array:
    """pingable = alive|suspect and not self (membership.js:135-139)."""
    ok = (state.view_status == ALIVE) | (state.view_status == SUSPECT)
    eye = jnp.eye(state.n, dtype=bool)
    return ok & ~eye


def _choose_targets(pingable: jax.Array, key: jax.Array) -> tuple[jax.Array, jax.Array]:
    """One probe target per node, uniform among its pingable members.

    The reference walks a per-round shuffled round-robin
    (membership-iterator.js:33-52); uniform sampling keeps the same
    distribution over targets without N x N iterator state.

    Selection is an exact rank pick: one uniform per node chooses the
    k-th pingable member via a row cumsum — O(N^2) cheap integer work
    instead of an N x N counter-based-PRNG matrix (threefry bits were
    half the tick's cost)."""
    n = pingable.shape[0]
    count = jnp.sum(pingable, axis=1, dtype=jnp.int32)
    u = jax.random.uniform(key, (n,))
    kth = jnp.floor(u * count).astype(jnp.int32)  # uniform in [0, count)
    csum = jnp.cumsum(pingable.astype(jnp.int32), axis=1)
    hit = pingable & (csum == (kth + 1)[:, None])
    target = jnp.argmax(hit, axis=1).astype(jnp.int32)
    has = count > 0
    return jnp.where(has, target, -1), has


def _rand_scores(key: jax.Array, n: int) -> jax.Array:
    """uint32[N, N] statistical-quality random scores from one scalar
    draw + an integer mix per element.  Replaces an N x N threefry
    tensor for witness sampling: the protocol needs unbiased *selection*,
    not cryptographic bits, and threefry dominated the step cost."""
    seed = jax.random.bits(key, dtype=jnp.uint32)
    i = jnp.arange(n, dtype=jnp.uint32)
    h = seed ^ (i[:, None] * jnp.uint32(0x9E3779B1)) ^ (
        i[None, :] * jnp.uint32(0x85EBCA77)
    )
    h = (h ^ (h >> jnp.uint32(15))) * jnp.uint32(0xC2B2AE3D)
    h = (h ^ (h >> jnp.uint32(13))) * jnp.uint32(0x27D4EB2F)
    return h ^ (h >> jnp.uint32(16))


def _choose_witnesses(
    pingable: jax.Array, target: jax.Array, k: int, key: jax.Array
) -> tuple[jax.Array, jax.Array]:
    """k distinct random pingable members excluding the probe target
    (ping-req-sender.js:292-295 / membership.getRandomPingableMembers)."""
    n = pingable.shape[0]
    cols = jnp.arange(n, dtype=jnp.int32)
    mask = pingable & (cols[None, :] != jnp.where(target < 0, n, target)[:, None])
    # 31-bit non-negative scores; invalid entries are -1.  k is tiny and
    # static, so k argmax-and-mask passes select the top-k (lax.top_k on
    # int32 hits a pathologically slow path: ~100x argmax).
    score = jnp.where(
        mask, (_rand_scores(key, n) >> jnp.uint32(1)).astype(jnp.int32), -1
    )
    picks = []
    valids = []
    for _ in range(k):
        idx = jnp.argmax(score, axis=1).astype(jnp.int32)
        picks.append(idx)
        valids.append(jnp.take_along_axis(score, idx[:, None], axis=1)[:, 0] >= 0)
        score = jnp.where(cols[None, :] == idx[:, None], -1, score)
    return jnp.stack(picks, axis=1), jnp.stack(valids, axis=1)


def _drop(key: jax.Array, shape: tuple, loss: float) -> jax.Array:
    """Per-message Bernoulli loss draw (True = dropped)."""
    if loss <= 0.0:
        return jnp.zeros(shape, dtype=bool)
    return jax.random.uniform(key, shape) < loss


class _Merge(NamedTuple):
    """Result of applying a batch of incoming changes at each receiver."""

    state: ClusterState
    applied: jax.Array  # bool[N, N] — change applied (incl. refutations)
    refuted: jax.Array  # bool[N] — receiver re-asserted itself alive
    flapped: jax.Array  # bool[N, N] — applied status transition touching alive


def _merge_incoming(
    state: ClusterState,
    in_status: jax.Array,  # int8[N, N]: claim about j arriving at receiver r
    in_inc: jax.Array,  # int32[N, N]
    in_src: jax.Array,  # int32[N, N]
    in_src_inc: jax.Array,  # int32[N, N]
    active: jax.Array,  # bool[N]: receiver r processes input this tick
) -> _Merge:
    """Apply one batch of incoming changes at every receiver.

    Implements membership.update's per-change evaluation
    (membership.js:208-313) vectorized: first-sight wholesale, the
    refutation fast-path for self rumors, then the override lattice.
    Applied changes are recorded into the receiver's dissemination buffer
    with piggyback count 0 (membership-update-listener.js:47 ->
    dissemination.recordChange).
    """
    n = state.n
    eye = jnp.eye(n, dtype=bool)

    in_key = _lattice_key(in_status, in_inc)
    cur_key = _lattice_key(state.view_status, state.view_inc)

    # Refutation (membership.js:243-254): any suspect/faulty rumor about
    # self — regardless of incarnation — re-asserts alive with an
    # incarnation beating both the rumor and the current self view.
    rumor_self = (
        eye
        & active[:, None]
        & ((in_status == SUSPECT) | (in_status == FAULTY))
        & (in_status != NONE)
    )
    refuted = jnp.any(rumor_self, axis=1)
    self_inc = jnp.diagonal(state.view_inc)
    rumor_inc = jnp.where(rumor_self, in_inc, _KEY_MIN).max(axis=1)
    new_self_inc = jnp.maximum(self_inc, rumor_inc) + 1

    apply = (
        _apply_mask(state.view_status, cur_key, in_status, in_key)
        & active[:, None]
        & ~eye  # self entries only change via refutation / local ops
    )

    # Flap: an applied transition between alive and suspect/faulty in
    # either direction (damping.py _FLAP_SET semantics; extension).
    was = state.view_status
    flapped = apply & (
        ((was == ALIVE) & ((in_status == SUSPECT) | (in_status == FAULTY)))
        | (((was == SUSPECT) | (was == FAULTY)) & (in_status == ALIVE))
    )

    view_status = jnp.where(apply, in_status, state.view_status)
    view_inc = jnp.where(apply, in_inc, state.view_inc)
    src = jnp.where(apply, in_src, state.src)
    src_inc = jnp.where(apply, in_src_inc, state.src_inc)
    pb = jnp.where(apply, jnp.int16(0), state.pb)

    # Refutation writes the diagonal and records a self-sourced alive change.
    ids = jnp.arange(n, dtype=jnp.int32)
    diag_status = jnp.where(refuted, ALIVE, jnp.diagonal(view_status)).astype(jnp.int8)
    diag_inc = jnp.where(refuted, new_self_inc, jnp.diagonal(view_inc))
    view_status = _set_diag(view_status, diag_status)
    view_inc = _set_diag(view_inc, diag_inc)
    src = _set_diag(src, jnp.where(refuted, ids, jnp.diagonal(src)))
    src_inc = _set_diag(src_inc, jnp.where(refuted, new_self_inc, jnp.diagonal(src_inc)))
    pb = _set_diag(pb, jnp.where(refuted, jnp.int16(0), jnp.diagonal(pb)))

    applied = apply | (eye & refuted[:, None])

    # Suspicion timers (suspicion.js:45-69 via update-listener:34-45):
    # applied suspect (re)starts the deadline; applied alive stops it.
    suspect_at = jnp.where(
        applied & (view_status == SUSPECT), state.tick, state.suspect_at
    )
    suspect_at = jnp.where(applied & (view_status == ALIVE), -1, suspect_at)

    return _Merge(
        state._replace(
            view_status=view_status,
            view_inc=view_inc,
            pb=pb,
            src=src,
            src_inc=src_inc,
            suspect_at=suspect_at,
        ),
        applied,
        refuted,
        flapped,
    )


def _set_diag(mat: jax.Array, d: jax.Array) -> jax.Array:
    n = mat.shape[0]
    ids = jnp.arange(n)
    return mat.at[ids, ids].set(d.astype(mat.dtype))


def _declare(
    state: ClusterState,
    viewer_mask: jax.Array,  # bool[N]
    subject: jax.Array,  # int32[N] (index per viewer; clipped where invalid)
    new_status: int,
) -> tuple[ClusterState, jax.Array]:
    """Local declaration (makeSuspect / makeFaulty, membership.js:141-156):
    viewer i re-labels ``subject[i]`` with its currently-known incarnation,
    applying only where the lattice admits it, and records a self-sourced
    change."""
    n = state.n
    ids = jnp.arange(n, dtype=jnp.int32)
    subj = jnp.clip(subject, 0, n - 1)
    cur_s = state.view_status[ids, subj]
    cur_i = state.view_inc[ids, subj]
    in_key = _lattice_key(jnp.full((n,), new_status, jnp.int8), cur_i)
    cur_key = _lattice_key(cur_s, cur_i)
    ok = (
        viewer_mask
        & (subj != ids)
        & _apply_mask(cur_s, cur_key, jnp.full((n,), new_status, jnp.int8), in_key)
    )
    self_inc = jnp.diagonal(state.view_inc)
    vs = state.view_status.at[ids, subj].set(
        jnp.where(ok, jnp.int8(new_status), cur_s).astype(jnp.int8)
    )
    pb = state.pb.at[ids, subj].set(jnp.where(ok, jnp.int16(0), state.pb[ids, subj]))
    src = state.src.at[ids, subj].set(jnp.where(ok, ids, state.src[ids, subj]))
    src_inc = state.src_inc.at[ids, subj].set(
        jnp.where(ok, self_inc, state.src_inc[ids, subj])
    )
    sus = state.suspect_at
    if new_status == SUSPECT:
        sus = sus.at[ids, subj].set(
            jnp.where(ok, state.tick, sus[ids, subj]).astype(jnp.int32)
        )
    state = state._replace(view_status=vs, pb=pb, src=src, src_inc=src_inc, suspect_at=sus)
    return state, ok


# ---------------------------------------------------------------------------
# the protocol period
# ---------------------------------------------------------------------------


def swim_step_impl(
    state: ClusterState, net: NetState, key: jax.Array, params: SwimParams
) -> tuple[ClusterState, dict[str, jax.Array]]:
    """One synchronized protocol period for every virtual node.

    Phases (intra-tick order convention, see module docstring):
      1. probe-target selection          (membership-iterator.js)
      2. sender piggyback issue          (dissemination.issueAsSender)
      3. ping delivery + receiver merge  (ping-handler.js:34)
      4. receiver reply (+ full sync) + sender merge  (ping-handler.js:36-39)
      5. failed probes -> ping-req two-hop -> suspect  (ping-req-sender.js)
      6. suspicion deadlines -> faulty   (suspicion.js:66-69)
    """
    n = state.n
    k_target, k_loss1, k_loss2, k_wit, k_loss3 = jax.random.split(key, 5)
    ids = jnp.arange(n, dtype=jnp.int32)
    maxpb = _max_piggyback(state, params.piggyback_factor)  # int32[N]
    h_pre = _view_hash(state)  # sender checksum claim in the ping body
    self_inc0 = jnp.diagonal(state.view_inc)  # sender identity claim

    # -- phase 1: who probes whom ------------------------------------------
    own_status = jnp.diagonal(state.view_status)
    gossiping = (
        net.up & net.responsive & ((own_status == ALIVE) | (own_status == SUSPECT))
    )
    target, has_target = _choose_targets(_pingable(state), k_target)
    sends = gossiping & has_target
    t_safe = jnp.where(sends, target, 0)

    # -- phase 2: sender issues its active changes -------------------------
    has_change = state.pb >= 0
    pb_next = jnp.where(has_change & sends[:, None], state.pb + 1, state.pb)
    issued_s = has_change & sends[:, None] & (pb_next <= maxpb[:, None].astype(jnp.int16))
    # eviction past the budget, only on issue attempts (dissemination.js:
    # 147-151; counted even if the packet is then lost in the network)
    pb_next = jnp.where(
        sends[:, None] & (pb_next > maxpb[:, None].astype(jnp.int16)),
        jnp.int16(-1),
        pb_next,
    )
    state = state._replace(pb=pb_next)

    # -- phase 3: delivery + receiver-side merge ---------------------------
    resp = net.up & net.responsive
    fwd_ok = (
        sends
        & net.adj[ids, t_safe]
        & ~_drop(k_loss1, (n,), params.loss)
        & resp[t_safe]
    )
    # scatter-max incoming claims into receiver rows; ties share the key,
    # payload (src, src_inc) resolved by two more masked scatter-maxes.
    key_out = jnp.where(
        issued_s & fwd_ok[:, None],
        _lattice_key(state.view_status, state.view_inc),
        _KEY_MIN,
    )
    best = jnp.full((n, n), _KEY_MIN, dtype=jnp.int32).at[t_safe].max(key_out)
    winner = (key_out > _KEY_MIN) & (key_out == best[t_safe])
    best_src = (
        jnp.full((n, n), -1, dtype=jnp.int32)
        .at[t_safe]
        .max(jnp.where(winner, state.src, -1))
    )
    src_winner = winner & (state.src == best_src[t_safe])
    best_src_inc = (
        jnp.full((n, n), -1, dtype=jnp.int32)
        .at[t_safe]
        .max(jnp.where(src_winner, state.src_inc, -1))
    )
    in_exists = best > _KEY_MIN
    in_status = jnp.where(in_exists, (best % 8).astype(jnp.int8), jnp.int8(NONE))
    in_inc = jnp.where(in_exists, best // 8, 0).astype(jnp.int32)
    inbound = jnp.zeros((n,), jnp.int32).at[t_safe].add(fwd_ok.astype(jnp.int32))
    got_ping = inbound > 0

    merged = _merge_incoming(state, in_status, in_inc, best_src, best_src_inc, got_ping)
    state = merged.state
    ping_applied = jnp.sum(merged.applied, dtype=jnp.int32)

    # -- phase 4: receiver replies; sender merges the ack ------------------
    maxpb2 = _max_piggyback(state, params.piggyback_factor)
    has_change2 = state.pb >= 0
    # issue-as-receiver: one issued set per tick; counter advances by the
    # number of pings served (documented tick-model convention).
    rep_issuable = has_change2 & got_ping[:, None] & (
        (state.pb + 1).astype(jnp.int32) <= maxpb2[:, None]
    )
    pb_after = jnp.where(
        has_change2 & got_ping[:, None],
        state.pb + inbound[:, None].astype(jnp.int16),
        state.pb,
    )
    pb_after = jnp.where(
        got_ping[:, None] & (pb_after.astype(jnp.int32) > maxpb2[:, None]),
        jnp.int16(-1),
        pb_after,
    )
    state = state._replace(pb=pb_after)

    h_post = _view_hash(state)
    # per-(sender i, receiver t) view of the reply: anti-echo filters
    # changes i itself originated (dissemination.js:86-98)
    rep_row = rep_issuable[t_safe]  # bool[N(sender), N(subject)]
    echo = (state.src[t_safe] == ids[:, None]) & (
        state.src_inc[t_safe] == self_inc0[:, None]
    )
    rep_row = rep_row & ~echo
    # full sync (dissemination.js:100-118): nothing to say but checksums
    # disagree -> entire view row, self-sourced, no source incarnation
    full_sync = (
        fwd_ok & ~jnp.any(rep_row, axis=1) & (h_post[t_safe] != h_pre)
    )
    exists_row = state.view_status[t_safe] != NONE
    send_row = jnp.where(full_sync[:, None], exists_row, rep_row)

    bwd_ok = fwd_ok & net.adj[t_safe, ids] & ~_drop(k_loss2, (n,), params.loss)
    ack = bwd_ok

    in2_mask = send_row & ack[:, None]
    in2_status = jnp.where(in2_mask, state.view_status[t_safe], jnp.int8(NONE))
    in2_inc = jnp.where(in2_mask, state.view_inc[t_safe], 0)
    in2_src = jnp.where(
        in2_mask,
        jnp.where(full_sync[:, None], t_safe[:, None], state.src[t_safe]),
        -1,
    )
    in2_src_inc = jnp.where(
        in2_mask,
        jnp.where(full_sync[:, None], -1, state.src_inc[t_safe]),
        -1,
    )
    merged2 = _merge_incoming(state, in2_status, in2_inc, in2_src, in2_src_inc, ack)
    state = merged2.state
    ack_applied = jnp.sum(merged2.applied, dtype=jnp.int32)

    # -- phase 5: ping-req for failed probes (ping-req-sender.js) ----------
    failed = sends & ~ack
    wit, wit_valid = _choose_witnesses(_pingable(state), target, params.ping_req_size, k_wit)
    k_a, k_b, k_c, k_d = jax.random.split(k_loss3, 4)
    kshape = (n, params.ping_req_size)
    wit_safe = jnp.clip(wit, 0, n - 1)
    req_ok = (
        failed[:, None]
        & wit_valid
        & net.adj[ids[:, None], wit_safe]
        & ~_drop(k_a, kshape, params.loss)
        & resp[wit_safe]
    )
    wt_ok = (
        req_ok
        & net.adj[wit_safe, t_safe[:, None]]
        & ~_drop(k_b, kshape, params.loss)
        & resp[t_safe][:, None]
        & net.adj[t_safe[:, None], wit_safe]
        & ~_drop(k_c, kshape, params.loss)
    )
    relay_ok = net.adj[wit_safe, ids[:, None]] & ~_drop(k_d, kshape, params.loss)
    any_success = jnp.any(wt_ok & relay_ok, axis=1)
    # all witnesses answered "target unreachable" and none succeeded ->
    # suspect (ping-req-sender.js:238-267); no witness response at all is
    # inconclusive (:268-282)
    definite_fail = jnp.any(req_ok & ~wt_ok & relay_ok, axis=1)
    declare_suspect = failed & ~any_success & definite_fail
    was_alive_at_target = state.view_status[ids, jnp.clip(t_safe, 0, n - 1)] == ALIVE
    state, declared = _declare(state, declare_suspect, t_safe, SUSPECT)

    # -- phase 6: suspicion deadlines fire -> faulty (suspicion.js:66-69) --
    expired = (
        (state.suspect_at >= 0)
        & (state.tick - state.suspect_at >= params.suspicion_ticks)
        & (state.view_status == SUSPECT)
        & gossiping[:, None]  # a stopped/dead process fires no timers
    )
    vs = jnp.where(expired, jnp.int8(FAULTY), state.view_status)
    pb = jnp.where(expired, jnp.int16(0), state.pb)
    src = jnp.where(expired, ids[:, None], state.src)
    src_inc = jnp.where(expired, jnp.diagonal(state.view_inc)[:, None], state.src_inc)
    sus = jnp.where(expired, -1, state.suspect_at)
    state = state._replace(
        view_status=vs, pb=pb, src=src, src_inc=src_inc, suspect_at=sus
    )

    # -- damping extension (active only with damp tensors present) ---------
    n_damped = jnp.int32(0)
    if state.damp is not None:
        flaps = merged.flapped | merged2.flapped
        # a viewer that itself declares alive->suspect flaps too (the host
        # library scores these via the membership 'updated' event)
        declare_flap = declared & was_alive_at_target
        flaps = flaps.at[ids, jnp.clip(t_safe, 0, n - 1)].max(declare_flap)
        damp = (
            state.damp.astype(jnp.float32) * params.damp_decay_per_tick
            + jnp.where(flaps, jnp.float32(params.damp_penalty), 0.0)
        ).astype(jnp.float16)
        damped = jnp.where(
            damp > params.damp_suppress,
            True,
            jnp.where(damp < params.damp_reuse, False, state.damped),
        )
        state = state._replace(damp=damp, damped=damped)
        n_damped = jnp.sum(damped, dtype=jnp.int32)

    state = state._replace(tick=state.tick + 1)
    metrics = {
        "pings_sent": jnp.sum(sends, dtype=jnp.int32),
        "acks": jnp.sum(ack, dtype=jnp.int32),
        "ping_changes_applied": ping_applied,
        "ack_changes_applied": ack_applied,
        "full_syncs": jnp.sum(full_sync, dtype=jnp.int32),
        "ping_reqs": jnp.sum(failed, dtype=jnp.int32),
        "suspects_declared": jnp.sum(declare_suspect, dtype=jnp.int32),
        "faulty_declared": jnp.sum(expired, dtype=jnp.int32),
        "damped_pairs": n_damped,
    }
    return state, metrics


def swim_run_impl(
    state: ClusterState, net: NetState, key: jax.Array, params: SwimParams, ticks: int
) -> tuple[ClusterState, dict[str, jax.Array]]:
    """``ticks`` protocol periods under lax.scan (one compiled program)."""

    def body(carry, subkey):
        st, _ = carry
        st, m = swim_step_impl(st, net, subkey, params)
        return (st, m), None

    keys = jax.random.split(key, ticks)
    st0, m0 = swim_step_impl(state, net, keys[0], params)
    (state, metrics), _ = jax.lax.scan(body, (st0, m0), keys[1:])
    return state, metrics


# Jitted entry points; ``state`` is donated so long scans run in-place in HBM.
swim_step = jax.jit(swim_step_impl, static_argnames=("params",), donate_argnums=(0,))
swim_run = jax.jit(
    swim_run_impl, static_argnames=("params", "ticks"), donate_argnums=(0,)
)


# ---------------------------------------------------------------------------
# host-side membership ops (join / leave / revive — the admin surface)
# ---------------------------------------------------------------------------


def admin_join(state: ClusterState, joiner: int, seed: int) -> ClusterState:
    """Bootstrap join against a seed (join-sender.js + join-handler.js):
    the seed marks the joiner alive and answers with a full membership
    sync; the joiner adopts it wholesale and both record the changes."""
    vs, vi = state.view_status, state.view_inc
    j_inc = vi[joiner, joiner]
    j_status = vs[joiner, joiner]

    # seed: makeAlive(joiner) (join-handler.js:90)
    cur_key = _lattice_key(vs[seed, joiner], vi[seed, joiner])
    in_key = _lattice_key(jnp.int8(ALIVE), j_inc)
    ok = _apply_mask(vs[seed, joiner], cur_key, jnp.int8(ALIVE), in_key)
    vs = vs.at[seed, joiner].set(jnp.where(ok, ALIVE, vs[seed, joiner]).astype(jnp.int8))
    vi = vi.at[seed, joiner].set(jnp.where(ok, j_inc, vi[seed, joiner]))
    pb = state.pb.at[seed, joiner].set(
        jnp.where(ok, 0, state.pb[seed, joiner]).astype(jnp.int16)
    )
    src = state.src.at[seed, joiner].set(jnp.where(ok, seed, state.src[seed, joiner]))
    src_inc = state.src_inc.at[seed, joiner].set(
        jnp.where(ok, vi[seed, seed], state.src_inc[seed, joiner])
    )

    # joiner: adopt the seed's row (full sync), keep own self entry, and
    # record everything learned (membership-set-listener.js:33-47)
    row_s = vs[seed]
    row_i = vi[seed]
    learned = (row_s != NONE) & (jnp.arange(state.n) != joiner)
    vs = vs.at[joiner].set(jnp.where(learned, row_s, vs[joiner]).astype(jnp.int8))
    vi = vi.at[joiner].set(jnp.where(learned, row_i, vi[joiner]))
    vs = vs.at[joiner, joiner].set(jnp.where(j_status == NONE, ALIVE, j_status).astype(jnp.int8))
    pb = pb.at[joiner].set(jnp.where(learned, 0, pb[joiner]).astype(jnp.int16))
    src = src.at[joiner].set(jnp.where(learned, seed, src[joiner]))
    src_inc = src_inc.at[joiner].set(jnp.where(learned, row_i[seed], src_inc[joiner]))
    return state._replace(view_status=vs, view_inc=vi, pb=pb, src=src, src_inc=src_inc)


def admin_leave(state: ClusterState, node: int) -> ClusterState:
    """makeLeave(self) (admin-leave-handler.js:48-52): the node marks
    itself leave (stopping its gossip via the own-status gate) and records
    the change for dissemination by peers that ping it."""
    vs = state.view_status.at[node, node].set(LEAVE)
    pb = state.pb.at[node, node].set(0)
    src = state.src.at[node, node].set(node)
    src_inc = state.src_inc.at[node, node].set(state.view_inc[node, node])
    return state._replace(view_status=vs, pb=pb, src=src, src_inc=src_inc)


def revive(state: ClusterState, node: int, inc: int) -> ClusterState:
    """A killed process restarts fresh (tick-cluster.js:418-430): wipe its
    row to self-only with a new (higher) incarnation; re-entry to the
    cluster is an ``admin_join``."""
    n = state.n
    row = jnp.where(jnp.arange(n) == node, ALIVE, NONE).astype(jnp.int8)
    inc_row = jnp.where(jnp.arange(n) == node, jnp.int32(inc), 0)
    state = state._replace(
        view_status=state.view_status.at[node].set(row),
        view_inc=state.view_inc.at[node].set(inc_row),
        pb=state.pb.at[node].set(-1),
        src=state.src.at[node].set(-1),
        src_inc=state.src_inc.at[node].set(-1),
        suspect_at=state.suspect_at.at[node].set(-1),
    )
    if state.damp is not None:  # a fresh process has no damp memory
        state = state._replace(
            damp=state.damp.at[node].set(jnp.float16(0)),
            damped=state.damped.at[node].set(False),
        )
    return state

"""Reference-format membership checksums for simulation view rows.

The reference checksum (lib/membership.js:41-93) is farmhash32 of the
member list sorted by address, each entry ``addr + status + incarnation``,
entries joined by ';'.  The host library (membership.py) produces it per
node; this module produces it for *simulation* state — node i's checksum
is a function of row i of the view tensors — so sim convergence can be
asserted bit-identical to the host library / reference.

The hot path packs each requested row into the ``addr\\0status\\0inc\\0``
layout consumed by the C extension's ``rp_membership_checksum``
(ops/_farmhash.c), falling back to pure Python automatically.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from ringpop_tpu.models.swim_sim import NONE, STATUS_NAMES
from ringpop_tpu.ops import farmhash


def default_addresses(n: int, host: str = "127.0.0.1", base_port: int = 10000) -> list[str]:
    """Address book matching the host harness (harness.py Cluster)."""
    return [f"{host}:{base_port + i}" for i in range(n)]


class AddressBook:
    """Static per-simulation address table + the precomputed sort order.

    Addresses never change during a simulation (dynamic membership is the
    NONE status), so the checksum's sort-by-address (membership.js:70-93)
    is a precomputed permutation.
    """

    def __init__(self, addresses: Sequence[str]):
        self.addresses = list(addresses)
        self.sorted_order = np.argsort(np.array(self.addresses, dtype=object), kind="stable")
        self._addr_bytes = [a.encode() for a in self.addresses]
        self.index = {a: i for i, a in enumerate(self.addresses)}
        # Flat tables for the C batch kernel (rp_view_checksums).
        self.addr_buf = b"".join(self._addr_bytes)
        self.addr_off = np.zeros(len(self.addresses) + 1, dtype=np.int64)
        np.cumsum([len(b) for b in self._addr_bytes], out=self.addr_off[1:])

    def __len__(self) -> int:
        return len(self.addresses)


_STATUS_BYTES = {code: name.encode() for code, name in STATUS_NAMES.items()}

# Status-name table for the C kernel, indexed by status code.
_MAX_CODE = max(max(STATUS_NAMES), NONE)
_STATUS_TABLE = [_STATUS_BYTES.get(code, b"") for code in range(_MAX_CODE + 1)]
_STATUS_BUF = b"".join(_STATUS_TABLE)
_STATUS_OFF = np.zeros(len(_STATUS_TABLE) + 1, dtype=np.int64)
np.cumsum([len(b) for b in _STATUS_TABLE], out=_STATUS_OFF[1:])


def row_checksum(
    book: AddressBook,
    row_status: np.ndarray,
    row_inc: np.ndarray,
    base_inc: int,
) -> int:
    """Reference checksum of one node's view row (uint32)."""
    parts = []
    count = 0
    for j in book.sorted_order:
        s = int(row_status[j])
        if s == NONE:
            continue
        inc = base_inc + int(row_inc[j])
        parts.append(b"%s\x00%s\x00%d\x00" % (book._addr_bytes[j], _STATUS_BYTES[s], inc))
        count += 1
    return farmhash.membership_checksum_packed(b"".join(parts), count)


def view_checksums(
    book: AddressBook,
    view_status: np.ndarray,
    view_inc: np.ndarray,
    base_inc: int,
    indices: Sequence[int] | None = None,
) -> dict[int, int]:
    """Checksums of the given (default: all) nodes' views.

    Uses the threaded C batch kernel when available — the per-row Python
    loop is O(N) interpreter work per row, which makes whole-cluster
    parity checks O(N^2) and dominates large-sim drivers."""
    if indices is None:
        indices = range(view_status.shape[0])
    rows = np.fromiter((int(i) for i in indices), dtype=np.int64)
    n_rows_total = view_status.shape[0]
    # NumPy-style negative indexing, validated BEFORE the indices reach
    # C pointer arithmetic (which has no bounds checks).
    rows = np.where(rows < 0, rows + n_rows_total, rows)
    if ((rows < 0) | (rows >= n_rows_total)).any():
        raise IndexError(f"row index out of range for {n_rows_total} rows")
    if len(rows):
        native = farmhash.view_checksums_native(
            np.asarray(view_status, dtype=np.int8),
            np.asarray(view_inc, dtype=np.int32),
            base_inc,
            np.asarray(book.sorted_order, dtype=np.int64),
            book.addr_buf,
            book.addr_off,
            _STATUS_BUF,
            _STATUS_OFF,
            NONE,
            rows,
        )
        if native is not None:
            return {int(i): int(c) for i, c in zip(rows, native)}
    return {
        int(i): row_checksum(book, view_status[i], view_inc[i], base_inc)
        for i in rows
    }


def view_checksums_packed(
    book: AddressBook, keys_rows: np.ndarray, base_inc: int
) -> np.ndarray:
    """Checksums of packed ``view_key`` rows (swim_sim layout), in row
    order — the single unpack point for every host-side caller."""
    keys_rows = np.asarray(keys_rows)
    out = view_checksums(
        book,
        (keys_rows & 7).astype(np.int8),
        keys_rows >> 3,
        base_inc,
        np.arange(keys_rows.shape[0]),
    )
    return np.array([out[i] for i in range(keys_rows.shape[0])], dtype=np.uint32)


def row_members(
    book: AddressBook,
    row_status: np.ndarray,
    row_inc: np.ndarray,
    base_inc: int,
) -> list[dict]:
    """A view row as the reference's member-list JSON (getStats dump,
    membership.js:122-129: sorted by address)."""
    out = []
    for j in book.sorted_order:
        s = int(row_status[j])
        if s == NONE:
            continue
        out.append(
            {
                "address": book.addresses[j],
                "status": STATUS_NAMES[s],
                "incarnationNumber": base_inc + int(row_inc[j]),
            }
        )
    return out

"""TPU simulation backends (the jax/XLA compute path of the framework).

``swim_sim`` is the flagship model: the reference's SWIM membership +
dissemination layers (lib/membership.js, lib/dissemination.js,
lib/swim/*) as one jitted tick-synchronous kernel over dense N x N view
tensors.  ``cluster.SimCluster`` is its host driver (the tick-cluster
analog); ``checksum`` renders view rows into reference-format
farmhash32 membership checksums for parity checks.
"""

from ringpop_tpu.models.swim_sim import (  # noqa: F401
    ClusterState,
    NetState,
    SwimParams,
    init_state,
    make_net,
    swim_run,
    swim_step,
)
from ringpop_tpu.models.cluster import SimCluster  # noqa: F401

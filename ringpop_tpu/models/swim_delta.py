"""Delta-from-base SWIM simulation backend: O(N * C) per tick, no N x N state.

The dense backend (swim_sim.py) stores every (viewer, subject) pair —
6 bytes/pair caps one 16 GB chip near N=40k and makes every tick an
O(N^2) HBM sweep.  But a *converged* SWIM cluster is the degenerate
case: all N views are equal.  This backend stores that shared view once
(``base_key: int32[N]``) plus, per viewer, a bounded sorted table of
the entries where that viewer currently *disagrees* with the base (or
holds an active dissemination/suspicion record):

    view(i, j) = d_key[i, c]   if d_subj[i, c] == j for some slot c
               = base_key[j]   otherwise

Divergence in SWIM is exactly the rumor front: a kill, join, leave,
flap or loss burst touches O(churn) subjects per viewer, not O(N).
With capacity C slots per viewer the whole state is ~10 * N * C bytes —
a 65,536-node cluster at C=256 is 167 MB (vs 26 GB dense), and a
1,048,576-node cluster still fits one chip.

TPU-first design rules (learned from measuring the alternatives):

* **No point scatters.**  ``x.at[rows, cols].set`` with gathered index
  pairs lowers to a serial scatter loop on TPU (measured 18x slower
  than the dense N^2 sweep it was meant to avoid).  Every update here
  is an elementwise pass over the [N, C] tables; every data movement is
  a sort, a (vmapped) ``searchsorted``, or a row gather — all fast.
* **Pick the searchsorted lowering by shape.**  Row-wise (vmapped)
  lookups: the default "scan" lowers to a serial fori loop of per-row
  gathers (measured 12x slower on a v5e at [65536, 256] tables);
  narrow query sets (<= ``_WIDE_QUERY`` per row) use ``compare_all``
  (fused compare+sum) — inside the full step program XLA materializes
  wide [N, K, C] compare cubes to HBM instead of fusing them, so wider
  query sets take the ``_WIDE_METHOD`` lowering (default
  ``scan_unrolled``: log2(C) batched bisection gathers; override with
  ``RINGPOP_WIDE_METHOD`` — see ``_row_searchsorted``).  Flat 1-D
  lookups KEEP the default scan:
  ~20 dependent but fully vectorized gather steps, measured 1000x
  cheaper than sorting the concat at [1M] x [65k].  ``jnp.sort`` over
  rows is ~8 ms at [65536, 256] — cheap enough to be the universal
  compaction primitive.
* **Claim routing by sort, alignment by searchsorted+gather.**  Pings
  carry compact ``(subject, key)`` change lists; the per-tick claim
  traffic is a flat [N * W] record array sorted by (receiver, subject)
  (``lax.sort`` with two int32 keys — no uint32 packing, no x64), then
  re-aligned into an [N, K] per-receiver grid by binary search into
  the run starts.  The sort runs under a ``lax.cond`` and is skipped
  entirely on quiet ticks.
* **Selection without N^2.**  The probe/witness draw needs "the r-th
  pingable member of viewer i".  Pingability differs from the base
  only at delta slots, so the rank function
  ``rank(j) = bp_rank[j] - #removed(<j) + #added(<j)`` is monotone and
  O(log) per query: a vectorized binary search replaces the dense
  backend's N x N cumsum.

Protocol semantics are the dense step's, phase for phase (see
swim_sim.py's parity map into the reference: membership.js,
membership-update-rules.js, dissemination.js, swim/*.js).  Given ample
caps (wire_cap / claim_grid / capacity larger than any burst) the
trajectory is **bit-identical** to ``swim_step`` from the same PRNG key
(tests/test_swim_delta.py drives both and compares densified state per
tick).  At production caps the deviations are explicitly bounded-
resource semantics, each surfaced in ``metrics``:

* a ping/ack carries at most ``wire_cap`` changes (entries past the
  window neither bump nor evict their piggyback counter — they ship on
  later pings; the window start rotates by tick so a backlog wider
  than the wire cycles fairly, ``_rotating_window``), mirroring
  SwimParams.sparse_cap;
* a receiver consumes at most ``claim_grid`` distinct claims per tick,
  row-granularly — at most ``2 * ceil(claim_grid / wire_cap)`` sender
  rows, then ``claim_grid`` of their merged claims (rest dropped = late
  packets; ``claims_dropped``; see ``_route_claims_multi``);
* a viewer tracks at most ``capacity`` divergent subjects (insertions
  past that are dropped = lost updates repaired by later gossip /
  full sync; ``overflow_drops``).

Scope: scenarios whose divergence is bounded — steady state, loss,
kills, suspends, joins/leaves, bounded flaps (the BASELINE config 3/5
family and the 65k north star) — plus block netsplits via the int32[N]
group-id form of ``NetState.adj`` (connected iff same group; dense
bool[N, N] masks stay dense-only).  A 50/50 netsplit's *transition* is
dense by construction — every viewer accumulates other-side
suspicion/faulty records, so peak per-viewer divergence reaches ~N/2
and ``capacity`` must be sized for it (state 10 * N * (N/2 + slack)
bytes: 32k fits one 16 GB chip, 65k needs the row-sharded mesh path or
a capacity-bounded run whose overflow drops are repaired by full
syncs).  Bootstrapping N nodes from mode='self' is likewise inherently
dense.

Rebase: divergence relative to the base only shrinks again when gossip
reconverges; ``compact`` drops slots that match the base again, and
``rebase`` (host-side, rare) folds any unanimous column into
``base_key`` so long-running simulations return to the all-base fast
path regardless of accumulated churn.
"""

from __future__ import annotations

import os
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ringpop_tpu.obs import annotate
from ringpop_tpu.ops import bitpack
from ringpop_tpu.models.swim_sim import (
    ALIVE,
    FAULTY,
    LEAVE,
    SUSPECT,
    ClusterState,
    NetState,
    SwimKnobs,
    SwimParams,
    _adj,
    _apply_mask,
    _check_inc,
    _distinct_ranks,
    _drop_net,
    _gather_rows,
    _message_delay,
    _on_ring,
    _stagger_send_gate,
    _sweep_divisor,
    _validate_params,
)

SENTINEL = jnp.iinfo(jnp.int32).max  # empty delta slot (sorts to the end)


class DeltaParams(NamedTuple):
    """Static configuration: protocol constants + the resource caps."""

    swim: SwimParams = SwimParams()
    wire_cap: int = 16  # max changes per ping/ack (W)
    claim_grid: int = 64  # max distinct inbound claims consumed per tick (K)


class DeltaState(NamedTuple):
    """Shared base view + per-viewer bounded divergence tables.

    ``base_key[j]``: the baseline lattice key for subject j (see
    swim_sim.py for the ``inc * 8 + status`` encoding; 0 = nonexistent).
    ``bp_*``: pingability rank structures derived from ``base_key``
    (recomputed only by init/compact/rebase — the base is immutable
    inside ``delta_step``).

    Delta tables, each [N, C], rows sorted by ``d_subj`` with SENTINEL
    padding: ``d_key`` the viewer's belief, ``d_pb`` the piggyback
    count (-1 = no recorded change), ``d_sl`` the suspicion countdown
    (-1 = no timer).  A slot is live iff ``d_subj < SENTINEL``; a live
    slot may redundantly equal the base (until ``compact``).

    **Sided mode** (``side is not None`` — the structured-netsplit
    representation): ``base_key``/``bp_*`` carry one row per base GROUP
    ([G, N] / [G, N]), ``side[i]`` names viewer i's base row, and a
    cross-side full sync flips the receiver to
    ``merge_to[own_side, sender_side]`` — a host-precomputed row whose
    base is the lattice merge of the two (``make_sides`` /
    ``merge_base_rows``).  A 50/50 netsplit then keeps O(N * C) state:
    each side's consensus lives in its base row, the merged consensus
    in a third, and per-viewer tables hold only the rumor front.
    ``side=None`` is the single-base fast path, bit-identical to the
    pre-sided backend.
    """

    base_key: jax.Array  # int32[N] | int32[G, N] (sided)
    # base-pingable (alive|suspect), BIT-PACKED at rest (ops/bitpack.py
    # layout: bit j of word i = member i*32+j, zero pad bits) — point
    # queries go through bp_mask_at (word gather + shift), totals
    # through popcount; nothing ever unpacks the whole plane
    bp_mask: jax.Array  # uint32[ceil(N/32)] | [G, ceil(N/32)]
    bp_rank: jax.Array  # int32[N] | [G, N] exclusive prefix count of bp_mask
    bp_list: jax.Array  # int32[N] | [G, N] base-pingable subjects ascending
    d_subj: jax.Array  # int32[N, C]
    d_key: jax.Array  # int32[N, C]
    d_pb: jax.Array  # int8[N, C]
    d_sl: jax.Array  # int8[N, C]
    tick: jax.Array  # int32[]
    overflow_drops: jax.Array  # int32[] cumulative table-capacity drops
    side: jax.Array | None = None  # int32[N] viewer's base row (sided mode)
    merge_to: jax.Array | None = None  # int32[G, G] full-sync flip table
    # Rolling per-viewer view digest (uint32[N]) — the incremental twin
    # of the reference's membership checksum, which is UPDATED on each
    # membership change rather than recomputed per ping
    # (membership.js:43-55 computeChecksum on change).  Recomputing it
    # from scratch was the single largest cost of a converged tick
    # (~22 ms of a 27 ms quiet tick at n=8,192 on CPU: two [N, C] hash
    # passes plus base gathers, every tick).  Maintained at every d_key/
    # base mutation: _merge_claims adds per-claim hash deltas (uint32
    # wrap-around sums commute), phase-6 expiries adjust in their cond,
    # and the rare full-sync flip/absorb branch recomputes wholesale.
    # init_delta/make_sides/rebase/compact/sparsify populate it;
    # compute_digest() is the from-scratch oracle (invariant-tested).
    digest: jax.Array | None = None  # uint32[N]
    # Per-slot snapshots of the base pingability structures at each
    # slot's subject — the carried form of ``bp_mask_at(d_subj)`` /
    # ``bp_rank_at(d_subj)``, whose [N, C] random gathers were the
    # other half of the converged tick's phase-0/selection cost.  They
    # change ONLY when a slot's subject changes (insertion, reorder,
    # base rebuild) — never on value updates — so the step maintains
    # them with [N, K]-sized gathers under the insert cond instead of
    # [N, C] gathers every tick.  SENTINEL slots hold (False, 0).
    # compute_slot_base() is the from-scratch oracle (bool [N, C]);
    # the CARRIED form is bit-packed along the slot axis (bitpack
    # layout), unpacked only at the few consuming sites.
    d_bpmask: jax.Array | None = None  # uint32[N, ceil(C/32)] packed bits
    d_bprank: jax.Array | None = None  # int32[N, C]
    # Latency extension (None = disabled, zero cost): the delta
    # backend's in-flight claim representation for per-link delay
    # (NetState.link_d/link_j — scenarios/faults.py), replacing the
    # dense backend's [D, N, N] claim matrix with per-arrival-slot
    # claim LANES: a message delayed by d ticks at tick t parks its
    # [W]-wide claim list (the windowed wire payload it would have
    # merged in-tick) in slot ``(t + d) % D``, lane ``2*(d-1) + kind``
    # (kind 0 = phase-3 ping payload, 1 = phase-4 ack payload), with
    # its receiver in ``pend_recv``.  Within one maturity window every
    # writing tick has a distinct d for a given slot, so the
    # (slot, lane, sender) cells never collide — no scatter-max over
    # [N, N] needed.  Slot ``tick % D`` matures at tick start: its
    # lanes route through ``_route_claims_multi`` (the phase-5
    # machinery) and merge via ``_merge_claims``; receivers that are
    # down/suspended lose their matured claims (dense convention).
    # O(D^2 * W * N) memory — O(N) in the cluster size, the flagship-
    # scale form the dense [D, N, N] buffer cannot reach.  Presence
    # widens the per-tick key split (two jitter streams), exactly like
    # ``ClusterState.pending``; install via ``install_pending`` /
    # ``SimCluster.enable_delay`` from tick 0.  Network-resident:
    # kill/revive do NOT clear it.  Documented deviation from dense:
    # the full-sync path (a structural base flip, not a claim payload)
    # applies in-tick even over a delayed link.
    pend_subj: jax.Array | None = None  # int32[D, 2(D-1), N, W]
    pend_key: jax.Array | None = None  # int32[D, 2(D-1), N, W]
    pend_recv: jax.Array | None = None  # int32[D, 2(D-1), N] (n = none)

    @property
    def n(self) -> int:
        return self.base_key.shape[-1]

    @property
    def delay_depth(self) -> int:
        return 0 if self.pend_subj is None else self.pend_subj.shape[0]

    @property
    def capacity(self) -> int:
        return self.d_subj.shape[1]

    @property
    def groups(self) -> int:
        return 1 if self.side is None else self.base_key.shape[0]

    # -- side-indexed base accessors (single-base: plain indexing) -------

    def base_at(self, q: jax.Array) -> jax.Array:
        """base view of subject ``q`` ([N] or [N, K], row-aligned)."""
        qc = jnp.clip(q, 0, self.n - 1)
        if self.side is None:
            return self.base_key[qc]
        s = self.side if q.ndim == 1 else self.side[:, None]
        return self.base_key[s, qc]

    def bp_mask_at(self, q: jax.Array) -> jax.Array:
        qc = jnp.clip(q, 0, self.n - 1)
        if self.side is None:
            return bitpack.bit_gather(self.bp_mask, qc)
        s = self.side if q.ndim == 1 else self.side[:, None]
        return bitpack.bit_gather(self.bp_mask, qc, s)

    def bp_rank_at(self, q: jax.Array) -> jax.Array:
        qc = jnp.clip(q, 0, self.n - 1)
        if self.side is None:
            return self.bp_rank[qc]
        s = self.side if q.ndim == 1 else self.side[:, None]
        return self.bp_rank[s, qc]

    def bp_list_at(self, r: jax.Array) -> jax.Array:
        """r-th base-pingable subject per viewer row (r [N] or [N, K])."""
        if self.side is None:
            return self.bp_list[r]
        s = self.side if r.ndim == 1 else self.side[:, None]
        return self.bp_list[s, r]


def _base_rank_structs(
    base_key: jax.Array,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Pingability rank structures; shape-polymorphic over [N] (single
    base) and [G, N] (sided mode, one row per base group)."""
    n = base_key.shape[-1]
    status = base_key & 7
    bp_mask = (status == ALIVE) | (status == SUSPECT)
    bp_rank = jnp.cumsum(bp_mask.astype(jnp.int32), axis=-1) - bp_mask.astype(
        jnp.int32
    )
    ids = jnp.broadcast_to(
        jnp.arange(n, dtype=jnp.int32), base_key.shape
    )
    bp_list = jnp.sort(jnp.where(bp_mask, ids, n), axis=-1)
    return bitpack.pack_bits(bp_mask), bp_rank, bp_list


def init_delta(
    n: int,
    inc: jax.Array | None = None,
    *,
    capacity: int = 256,
    mode: str = "converged",
) -> DeltaState:
    """Fresh delta state (the dense ``init_state`` twin).

    ``mode='converged'``: every view equals the all-alive base, tables
    empty.  ``mode='self'``: pre-join bootstrap — the base is
    all-nonexistent (0) and each viewer holds one slot: its own alive
    entry (dense parity: ``init_state(mode='self')``).  A whole-cluster
    bootstrap's divergence grows toward the discovered cluster size, so
    size ``capacity`` for the join wave (~n at full discovery) and fold
    the converged all-alive consensus into the base with ``rebase``.
    """
    if inc is None:
        inc = jnp.zeros((n,), dtype=jnp.int32)
    inc = jnp.asarray(inc, dtype=jnp.int32)
    _check_inc(inc)
    alive_key = inc * 8 + ALIVE
    c = capacity
    d_subj = jnp.full((n, c), SENTINEL, dtype=jnp.int32)
    d_key = jnp.zeros((n, c), dtype=jnp.int32)
    if mode == "converged":
        base_key = alive_key
    elif mode == "self":
        base_key = jnp.zeros((n,), dtype=jnp.int32)
        d_subj = d_subj.at[:, 0].set(jnp.arange(n, dtype=jnp.int32))
        d_key = d_key.at[:, 0].set(alive_key)
    else:
        raise ValueError(f"unknown init mode: {mode}")
    bp_mask, bp_rank, bp_list = _base_rank_structs(base_key)
    st = DeltaState(
        base_key=base_key,
        bp_mask=bp_mask,
        bp_rank=bp_rank,
        bp_list=bp_list,
        d_subj=d_subj,
        d_key=d_key,
        d_pb=jnp.full((n, c), -1, dtype=jnp.int8),
        d_sl=jnp.full((n, c), -1, dtype=jnp.int8),
        tick=jnp.zeros((), dtype=jnp.int32),
        overflow_drops=jnp.zeros((), dtype=jnp.int32),
    )
    return refresh_carried(st)


def install_pending(state: DeltaState, depth: int, wire_cap: int) -> DeltaState:
    """Install the in-flight claim lanes for per-link delay (see the
    ``DeltaState.pend_*`` docstring).  ``depth`` is the ring depth
    (``faults.delay_depth``); lane width is the step's effective wire
    window ``min(wire_cap, capacity)``.  Must happen before the first
    delayed tick on BOTH the compiled-scan and host-loop sides — the
    buffer's presence widens the per-tick key split."""
    if depth < 2:
        raise ValueError(f"delay depth must be >= 2 (got {depth})")
    if state.pend_subj is not None:
        if state.pend_subj.shape[0] != depth:
            raise ValueError(
                f"in-flight lanes of depth {state.pend_subj.shape[0]} are "
                f"already installed (wanted {depth})"
            )
        return state
    n = state.n
    w_eff = min(int(wire_cap), state.capacity)
    lanes = 2 * (depth - 1)
    return state._replace(
        pend_subj=jnp.full((depth, lanes, n, w_eff), SENTINEL, jnp.int32),
        pend_key=jnp.zeros((depth, lanes, n, w_eff), jnp.int32),
        pend_recv=jnp.full((depth, lanes, n), n, jnp.int32),
    )


def _pend_write(
    st: DeltaState,
    kind: int,
    d: jax.Array,  # int32[N] per-sender delay (0 = in-tick, not parked)
    dly: jax.Array,  # bool[N] sender's message is delayed
    subj_rows: jax.Array,  # int32[N, W] claim subjects (SENTINEL pad)
    key_rows: jax.Array,  # int32[N, W]
    valid_rows: jax.Array,  # bool[N, W]
    recv: jax.Array,  # int32[N] receiver per sender row
) -> DeltaState:
    """Park one phase's delayed claim rows in their (slot, lane) cells.

    Slot ``(tick + d) % D`` with lane ``2*(d-1) + kind`` is collision-
    free by construction (each writing tick owns a distinct d per slot
    within a maturity window), so plain scatters suffice; non-delayed
    rows aim at the out-of-bounds slot D and drop."""
    n = st.n
    dd = st.pend_subj.shape[0]
    lanes = st.pend_subj.shape[1]
    ids = jnp.arange(n, dtype=jnp.int32)
    slot = jnp.where(dly, (st.tick + d) % jnp.int32(dd), jnp.int32(dd))
    lane = jnp.clip(2 * (d - 1) + kind, 0, lanes - 1)
    keep = valid_rows & dly[:, None]
    subj = jnp.where(keep, subj_rows, SENTINEL)
    keyv = jnp.where(keep, key_rows, 0)
    recv_v = jnp.where(dly & jnp.any(keep, axis=1), recv, jnp.int32(n))
    return st._replace(
        pend_subj=st.pend_subj.at[slot, lane, ids].set(subj, mode="drop"),
        pend_key=st.pend_key.at[slot, lane, ids].set(keyv, mode="drop"),
        pend_recv=st.pend_recv.at[slot, lane, ids].set(recv_v, mode="drop"),
    )


# ---------------------------------------------------------------------------
# lookups (vmapped binary search over the sorted tables)
# ---------------------------------------------------------------------------

# method="compare_all": the default "scan" method lowers to a serial
# fori loop of gathers — measured 12x slower on TPU (106 ms vs 8.8 ms
# for [65536,256] tables x 16 queries/row); the branch-free compare+sum
# streams at full vector width and XLA fuses the [N, K, C] compare into
# the reduction — but ONLY for narrow query sets.  Inside the full step
# program the wide-query instances (K = 64-grid consumption, K = C
# full-sync row lookups) materialize the [N, K, C] cube to HBM instead
# of fusing it (StableHLO shows 65536x256x256 / 65536x256x272 /
# 65536x64x256 intermediates; the compiled tick ran 20-100x slower
# than its own primitives — the [N,16]x[N,256] instance measured 723 ms
# in-program vs 8.8 ms standalone).  Past ``_WIDE_QUERY`` queries per
# row two cube-free lowerings exist:
#
# * merge (method="sort"): one [R, C+K] row sort of the concat — PLUS,
#   inside jnp.searchsorted, an argsort of the query block.  An HLO
#   census of the full 65k step (benchmarks/hlo_census.py) showed 13
#   such instances summing ~340M row-sorted int32 elements per tick;
#   a TPU row sort is O(log^2 width) full passes, so the merge
#   lowering dominated the compiled tick (~1.4 s/tick at 32k, 0.14x
#   real time).
# * unrolled bisection (method="scan_unrolled"): log2(C) data-dependent
#   but fully batched [R, K]-from-[R, C] gathers — ~8 passes of K-wide
#   reads instead of ~36 sort passes of (C+K)-wide read+writes, and no
#   query argsort.
#
# ``_WIDE_METHOD`` selects the wide lowering; scan_unrolled is the
# default.  "pallas" uses the hand-fused VPU compare-count kernel
# (ops/searchsorted_pallas.py) — cube-free by construction, candidate
# replacement pending the on-chip race.  Correctness of every choice is
# pinned by the densified bit-parity suite (tests/test_swim_delta.py
# runs the grid).  RINGPOP_WIDE_METHOD overrides at import for on-chip
# A/B of whole compiled steps without a code edit (it is read at trace
# time, so set it before the process starts).
_WIDE_QUERY = 4
_WIDE_METHOD = os.environ.get("RINGPOP_WIDE_METHOD", "scan_unrolled")
if _WIDE_METHOD not in ("sort", "scan", "scan_unrolled", "compare_all", "pallas"):
    raise ValueError(f"RINGPOP_WIDE_METHOD={_WIDE_METHOD!r} is not a lowering")

# ``_MERGE_METHOD`` selects the insert-merge lowering inside
# ``_merge_claims``: "sorted" (default) is the searchsorted + gather
# inversion below; "pallas" streams row blocks through the fused VMEM
# kernel (ops/delta_merge_pallas.py — the delta backend's first Pallas
# kernel, interpret mode off-TPU).  Bit-parity across both is pinned by
# tests/test_swim_delta.py's merge-method grid.  Like
# RINGPOP_WIDE_METHOD, the env override is read at trace time.
_MERGE_METHOD = os.environ.get("RINGPOP_DELTA_MERGE", "sorted")
if _MERGE_METHOD not in ("sorted", "pallas"):
    raise ValueError(f"RINGPOP_DELTA_MERGE={_MERGE_METHOD!r} is not a lowering")


def _row_searchsorted(a: jax.Array, v: jax.Array, side: str = "left") -> jax.Array:
    if v.shape[-1] > _WIDE_QUERY and _WIDE_METHOD == "pallas":
        from ringpop_tpu.ops.searchsorted_pallas import row_searchsorted_pallas

        # Mosaic kernels only compile for TPU; every other backend
        # (cpu, gpu, ...) degrades to interpret mode so the env knob
        # never hard-fails off-TPU.
        return row_searchsorted_pallas(
            a, v, side=side, interpret=jax.default_backend() != "tpu"
        )
    method = "compare_all" if v.shape[-1] <= _WIDE_QUERY else _WIDE_METHOD
    return jax.vmap(
        lambda ar, vr: jnp.searchsorted(ar, vr, side=side, method=method)
    )(a, v)


def _lookup_pos(d_subj: jax.Array, q: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Per-row positions of subjects ``q`` (same leading dim); q may be
    [N] or [N, K].  Returns (pos clipped in-range, found mask)."""
    squeeze = q.ndim == 1
    if squeeze:
        q = q[:, None]
    pos = _row_searchsorted(d_subj, q)
    pos_c = jnp.minimum(pos, d_subj.shape[1] - 1)
    found = jnp.take_along_axis(d_subj, pos_c, axis=1) == q
    if squeeze:
        return pos_c[:, 0], found[:, 0]
    return pos_c, found


def view_lookup(state: DeltaState, q: jax.Array) -> jax.Array:
    """view(i, q[i]) (or view(i, q[i, k])): delta if present else base."""
    pos, found = _lookup_pos(state.d_subj, q)
    dk = jnp.take_along_axis(state.d_key, pos if q.ndim > 1 else pos[:, None], axis=1)
    dk = dk if q.ndim > 1 else dk[:, 0]
    return jnp.where(found, dk, state.base_at(q))


def densify(state: DeltaState) -> ClusterState:
    """Materialize the equivalent dense ClusterState (tests / hand-off
    to the dense backend; O(N^2) memory — small N only)."""
    n, c = state.n, state.capacity
    base_rows = (
        jnp.broadcast_to(state.base_key[None, :], (n, n))
        if state.side is None
        else state.base_key[state.side]
    )
    vk = base_rows.astype(jnp.int32)
    pb = jnp.full((n, n), -1, dtype=jnp.int8)
    sl = jnp.full((n, n), -1, dtype=jnp.int8)
    live = state.d_subj < SENTINEL
    subj_safe = jnp.where(live, state.d_subj, 0)
    onehot = (
        jnp.arange(n, dtype=jnp.int32)[None, None, :] == subj_safe[:, :, None]
    ) & live[:, :, None]  # [N, C, N]
    vk = jnp.where(jnp.any(onehot, axis=1),
                   jnp.sum(jnp.where(onehot, state.d_key[:, :, None], 0), axis=1),
                   vk)
    pb = jnp.where(jnp.any(onehot, axis=1),
                   jnp.sum(jnp.where(onehot, state.d_pb[:, :, None].astype(jnp.int32), 0),
                           axis=1).astype(jnp.int8),
                   pb)
    sl = jnp.where(jnp.any(onehot, axis=1),
                   jnp.sum(jnp.where(onehot, state.d_sl[:, :, None].astype(jnp.int32), 0),
                           axis=1).astype(jnp.int8),
                   sl)
    return ClusterState(
        view_key=vk, pb=pb, suspect_left=sl, tick=state.tick, damp=None, damped=None
    )


def sparsify(
    dense: ClusterState, base_key: jax.Array, capacity: int
) -> DeltaState:
    """Delta representation of a dense state against ``base_key``
    (tests; host-side).  Raises if any row diverges beyond capacity."""
    vk = np.asarray(dense.view_key)
    pb = np.asarray(dense.pb)
    sl = np.asarray(dense.suspect_left)
    base = np.asarray(base_key)
    n = vk.shape[0]
    need = (vk != base[None, :]) | (pb >= 0) | (sl >= 0)
    counts = need.sum(axis=1)
    if counts.max(initial=0) > capacity:
        raise ValueError(f"divergence {counts.max()} exceeds capacity {capacity}")
    d_subj = np.full((n, capacity), int(SENTINEL), dtype=np.int32)
    d_key = np.zeros((n, capacity), dtype=np.int32)
    d_pb = np.full((n, capacity), -1, dtype=np.int8)
    d_sl = np.full((n, capacity), -1, dtype=np.int8)
    for i in range(n):
        js = np.nonzero(need[i])[0]
        d_subj[i, : len(js)] = js
        d_key[i, : len(js)] = vk[i, js]
        d_pb[i, : len(js)] = pb[i, js]
        d_sl[i, : len(js)] = sl[i, js]
    bp_mask, bp_rank, bp_list = _base_rank_structs(jnp.asarray(base))
    st = DeltaState(
        base_key=jnp.asarray(base),
        bp_mask=bp_mask,
        bp_rank=bp_rank,
        bp_list=bp_list,
        d_subj=jnp.asarray(d_subj),
        d_key=jnp.asarray(d_key),
        d_pb=jnp.asarray(d_pb),
        d_sl=jnp.asarray(d_sl),
        tick=dense.tick,
        overflow_drops=jnp.zeros((), jnp.int32),
    )
    return refresh_carried(st)


# ---------------------------------------------------------------------------
# phase 0: per-viewer stats from base aggregates + delta corrections
# ---------------------------------------------------------------------------


def _hash1(key: jax.Array, idx: jax.Array) -> jax.Array:
    """Per-entry term of the commutative view digest — must match
    swim_sim._view_hash bit for bit (uint32 sums commute, so the
    base/delta decomposition is exact)."""
    k = key.astype(jnp.uint32)
    h = (k * jnp.uint32(0x85EBCA6B)) ^ (k >> jnp.uint32(7))
    h = (h ^ (h >> jnp.uint32(13))) * jnp.uint32(0xC2B2AE35)
    h = h ^ (h >> jnp.uint32(16))
    salt = idx.astype(jnp.uint32) * jnp.uint32(0x27D4EB2F)
    return jnp.where(key > 0, h ^ salt, jnp.uint32(0))


class _Stats(NamedTuple):
    live: jax.Array  # bool[N, C] slot occupied
    ping_now: jax.Array  # bool[N, C] slot subject pingable in viewer's view
    ping_base: jax.Array  # bool[N, C] slot subject pingable in the base
    ping_count: jax.Array  # int32[N] pingable members per viewer
    server_count: jax.Array  # int32[N] alive|suspect members (incl. self)
    digest: jax.Array  # uint32[N] == dense _view_hash of the materialized view
    own_key: jax.Array  # int32[N] view(i, i)


def compute_slot_base(state: DeltaState) -> tuple[jax.Array, jax.Array]:
    """(bool[N, C], int32[N, C]) base pingability mask/rank at each
    slot's subject — the from-scratch oracle for the carried
    ``d_bpmask``/``d_bprank`` (SENTINEL slots hold (False, 0))."""
    live = state.d_subj < SENTINEL
    subj_safe = jnp.where(live, state.d_subj, 0)
    return (
        state.bp_mask_at(subj_safe) & live,
        jnp.where(live, state.bp_rank_at(subj_safe), 0),
    )


def compute_digest(state: DeltaState) -> jax.Array:
    """uint32[N] view digest from scratch — the oracle for the carried
    ``state.digest`` (base hash total corrected by the delta slots)."""
    n = state.n
    ids = jnp.arange(n, dtype=jnp.int32)
    live = state.d_subj < SENTINEL
    subj_safe = jnp.where(live, state.d_subj, 0)
    if state.side is None:
        h_base_total = jnp.broadcast_to(
            jnp.sum(_hash1(state.base_key, ids), dtype=jnp.uint32), (n,)
        )
    else:
        h_base_total = jnp.sum(
            _hash1(state.base_key, ids[None, :]), axis=1, dtype=jnp.uint32
        )[state.side]
    h_corr = jnp.sum(
        jnp.where(
            live,
            _hash1(state.d_key, subj_safe)
            - _hash1(state.base_at(subj_safe), subj_safe),
            jnp.uint32(0),
        ),
        axis=1,
        dtype=jnp.uint32,
    )
    return h_base_total + h_corr


def refresh_carried(state: DeltaState) -> DeltaState:
    """Recompute every carried derivative from the oracles — the one
    call that makes any hand-mutated/rebuilt state step-ready.

    The rolling digest is always carried (clear win on every platform).
    The slot-base snapshots are an A/B lowering knob like
    RINGPOP_WIDE_METHOD: they trade the per-tick [N, C] base gathers
    for extra cond-carry volume on the active paths — measured a ~2%
    LOSS on single-core CPU (151,269 vs 154,637 idle node-rounds/s at
    n=8,192, both idle-box with narrowed cond carries) but aimed at
    TPU, where random gathers cost far more
    relative to elementwise; RINGPOP_CARRY_SLOTBASE=1 enables them for
    the on-chip race.  Read at state-BUILD time only — inside the step
    the carry configuration is a property of the state (see
    _refresh_in_step)."""
    state = state._replace(digest=compute_digest(state))
    # the env enables the carry for fresh builds; a state that ALREADY
    # carries the snapshots keeps them (a mid-run rebase must not
    # silently drop a forced/loaded carry)
    if (
        os.environ.get("RINGPOP_CARRY_SLOTBASE", "0") == "1"
        or state.d_bpmask is not None
    ):
        bpm, bpr = compute_slot_base(state)
        return state._replace(d_bpmask=bitpack.pack_bits(bpm), d_bprank=bpr)
    return state._replace(d_bpmask=None, d_bprank=None)


@annotate.scoped("delta.refresh")
def _refresh_in_step(state: DeltaState) -> DeltaState:
    """Wholesale recompute of the carried derivatives INSIDE the step
    (the full-sync flip path).  Keys the slot-base recompute on the
    STATE's carry configuration, never the env var: a traced lax.cond
    branch must return the same pytree structure as its sibling, and
    the env can legitimately disagree with a loaded state's carry."""
    state = state._replace(digest=compute_digest(state))
    if state.d_bpmask is not None:
        bpm, bpr = compute_slot_base(state)
        return state._replace(d_bpmask=bitpack.pack_bits(bpm), d_bprank=bpr)
    return state


def _phase0_stats(state: DeltaState) -> _Stats:
    n = state.n
    ids = jnp.arange(n, dtype=jnp.int32)
    live = state.d_subj < SENTINEL
    subj_safe = jnp.where(live, state.d_subj, 0)
    d_status = state.d_key & 7
    ping_now = live & ((d_status == ALIVE) | (d_status == SUSPECT))
    ping_base = (
        bitpack.unpack_bits(state.d_bpmask, state.capacity)
        if state.d_bpmask is not None
        else live & state.bp_mask_at(subj_safe)
    )

    # counts: base total corrected by the delta slots (self excluded for
    # pingability, included for the ring-ish server count); per base
    # row in sided mode ([G] totals gathered by each viewer's side)
    if state.side is None:
        p_total = bitpack.popcount_bits(state.bp_mask)
    else:
        p_total = bitpack.popcount_bits(state.bp_mask, axis=1)[state.side]
    corr = jnp.sum(ping_now.astype(jnp.int32) - ping_base.astype(jnp.int32), axis=1)
    own_pos, own_found = _lookup_pos(state.d_subj, ids)
    own_key = jnp.where(
        own_found, jnp.take_along_axis(state.d_key, own_pos[:, None], axis=1)[:, 0],
        state.base_at(ids),
    )
    own_status = own_key & 7
    self_pingable_in_view = (own_status == ALIVE) | (own_status == SUSPECT)
    server_count = p_total + corr
    ping_count = server_count - self_pingable_in_view.astype(jnp.int32)

    # digest: the carried rolling value when present (the step path —
    # maintained at every mutation), else the from-scratch oracle
    # (host tools, states built before the carry existed)
    digest = state.digest if state.digest is not None else compute_digest(state)
    return _Stats(live, ping_now, ping_base, ping_count, server_count, digest, own_key)


def _max_piggyback_1d(server_count: jax.Array, factor: int) -> jax.Array:
    """factor * ceil(log10(count + 1)), the dissemination.js:38-55 budget
    (dense twin: swim_sim._max_piggyback, here from the O(N) count)."""
    x = server_count + 1
    digits = jnp.zeros_like(x)
    p = jnp.int32(1)
    for _ in range(10):
        digits = digits + (x > p).astype(jnp.int32)
        p = p * 10
    return jnp.minimum(factor * digits, 126)


# ---------------------------------------------------------------------------
# phase 1: probe/witness selection by rank (binary search, no cumsum)
# ---------------------------------------------------------------------------


def _compact_true(mask: jax.Array, width: int) -> jax.Array:
    """Column indices of the first ``width`` True per row of a [N, C]
    mask, SENTINEL-padded, order preserved.  One row sort: True columns
    (masked to their index, False to SENTINEL) sort to the front in
    column order.  (The previous per-output-slot reduction loop did
    ``width`` full [N, C] passes — the sort is one.)"""
    c = mask.shape[1]
    cols = jnp.broadcast_to(jnp.arange(c, dtype=jnp.int32)[None, :], mask.shape)
    return jnp.sort(jnp.where(mask, cols, SENTINEL), axis=1)[:, :width]


def _row_searchsorted_right(a: jax.Array, v: jax.Array) -> jax.Array:
    return _row_searchsorted(a, v, side="right")


def _windowed_changes(
    state: DeltaState, within: jax.Array, w: int
) -> tuple[jax.Array, jax.Array]:
    """(subject, key) lists of each row's windowed changes, [N, W].

    The compaction is a [N, C] row sort (_compact_true) — one of the
    two unconditionally-reached sorts of a tick — so a tick with no
    issuable changes anywhere (converged cluster, budgets exhausted)
    skips it entirely under the cond."""
    n = within.shape[0]
    w = min(w, within.shape[1])  # _compact_true caps the width at C

    def compacted(_):
        cols = _compact_true(within, w)
        safe = jnp.minimum(cols, state.capacity - 1)
        subj = jnp.where(
            cols < SENTINEL,
            jnp.take_along_axis(state.d_subj, safe, axis=1),
            SENTINEL,
        )
        return subj, jnp.take_along_axis(state.d_key, safe, axis=1)

    def quiet(_):
        return (
            jnp.full((n, w), SENTINEL, jnp.int32),
            jnp.zeros((n, w), jnp.int32),
        )

    return jax.lax.cond(jnp.any(within), compacted, quiet, None)


@annotate.scoped("delta.select")
def _selection(
    state: DeltaState,
    stats: _Stats,
    net: NetState,
    k_sel: jax.Array,
    params: DeltaParams,
    knobs: SwimKnobs | None = None,
):
    """Probe target + witnesses, RNG-identical to the dense phase 1
    (same _distinct_ranks stream, same rank -> subject mapping).

    Rank -> subject without an N-wide cumsum OR a per-pick bisection:
    pingability differs from the base only at delta slots, so build the
    per-row sorted correction list (subject, d) with d = +1 (pingable in
    view, not in base), -1 (vice versa, incl. self), and evaluate
    ``G(s_k) = #pingable < s_k = bp_rank[s_k] + prefix(d)`` at every
    correction.  G is nondecreasing, so ONE right-searchsorted locates
    each target rank's region: the answer is the correction subject
    itself when it is an added entry landing exactly on the rank, else
    the (rank - prefix)-th entry of the global base-pingable list (a
    gather).  An earlier bisection did 2 searchsorteds x 17 rounds x
    (k+1) picks; this does one [N, C+1] sort + one searchsorted total.
    """
    sw = params.swim
    n = state.n
    ids = jnp.arange(n, dtype=jnp.int32)
    k = sw.ping_req_size

    own_status = stats.own_key & 7
    gossiping = (
        net.up & net.responsive & ((own_status == ALIVE) | (own_status == SUSPECT))
    )

    # corrections vs the base pingable set, in slot (= subject) order.
    # Self is never pingable: a base-pingable self is a removal, via its
    # slot when it has one, else by shifting ranks at/past self (below).
    live, ping_now, ping_base = stats.live, stats.ping_now, stats.ping_base
    is_self = state.d_subj == ids[:, None]
    added = ping_now & ~ping_base & ~is_self
    removed = (ping_base & ~ping_now & ~is_self) | (is_self & live & ping_base)
    d_slot = added.astype(jnp.int32) - removed.astype(jnp.int32)
    self_in_delta = jnp.any(is_self & live, axis=1)
    self_extra = state.bp_mask_at(ids) & ~self_in_delta

    # ``d_subj`` is subject-sorted, so slot order IS subject order: the
    # correction prefix/rank arrays need no argsort (a [N, C+1] row sort
    # per tick before this rewrite).  Quiet slots (d == 0) take the next
    # correction's F by a log-step suffix-min, which restores row
    # monotonicity for the binary search and — because a filled slot
    # duplicates the value of a LATER live slot — can never themselves
    # be the last index <= rank.
    corr_live = d_slot != 0
    cpd = jnp.cumsum(d_slot, axis=1)  # inclusive prefix, subject order
    big = jnp.int32(1 << 30)
    slot_rank = (
        state.d_bprank
        if state.d_bprank is not None
        else state.bp_rank_at(jnp.where(live, state.d_subj, 0))
    )
    F = jnp.where(
        corr_live,
        slot_rank + (cpd - d_slot),
        big,
    )
    # suffix-min in one fused pass (the doubling loop did log2(C) padded
    # copies of the [N, C] array per tick)
    cc = F.shape[1]
    F = jax.lax.cummin(F, axis=1, reverse=True)

    ranks, valid = _distinct_ranks(stats.ping_count, k + 1, k_sel)
    r_clip = jnp.clip(
        ranks, 0, jnp.maximum(stats.ping_count - 1, 0)[:, None]
    )  # [N, k+1]

    # Self removal when self has no slot: ranks landing at/after self in
    # the self-included list shift up by one (G_with(s) = G_without(s)
    # - [s > i], so rank r maps to the without-self answer at r + 1
    # exactly when that answer would be >= self).  "Answer >= self" is
    # decidable before the search: the without-self answer at rank r is
    # >= i iff r >= G_without(i) = bp_rank[i] + #corrections below i,
    # so ONE search with pre-shifted ranks replaces answer-then-redo.
    own_pos, _ = _lookup_pos(state.d_subj, ids)
    corr_below_self = jnp.where(
        own_pos > 0,
        jnp.take_along_axis(cpd, jnp.maximum(own_pos - 1, 0)[:, None], axis=1)[:, 0],
        0,
    )
    # own_pos is clipped to C-1; a self landing past every slot must
    # still take the full correction sum
    corr_below_self = jnp.where(
        state.d_subj[:, -1] < ids, cpd[:, -1], corr_below_self
    )
    g_self = state.bp_rank_at(ids) + corr_below_self
    r_eff = r_clip + (
        self_extra[:, None] & (r_clip >= g_self[:, None])
    ).astype(jnp.int32)

    kstar = _row_searchsorted_right(F, r_eff) - 1
    ks_safe = jnp.clip(kstar, 0, cc - 1)
    in_corr = kstar >= 0
    F_at = jnp.take_along_axis(F, ks_safe, axis=1)
    d_at = jnp.take_along_axis(d_slot, ks_safe, axis=1)
    su_at = jnp.take_along_axis(state.d_subj, ks_safe, axis=1)
    cpd_at = jnp.where(in_corr, jnp.take_along_axis(cpd, ks_safe, axis=1), 0)
    added_answer = in_corr & (d_at == 1) & (F_at == r_eff)
    rprime = jnp.clip(r_eff - cpd_at, 0, n - 1)
    picks = jnp.where(added_answer, su_at, state.bp_list_at(rprime))  # [N, k+1]

    target = jnp.where(valid[:, 0], picks[:, 0], -1)
    has_target = valid[:, 0]
    wit = picks[:, 1:]
    wit_valid = valid[:, 1:]
    phase_mod = sw.phase_mod if knobs is None else knobs.phase_mod
    if knobs is not None:
        # capacity-padded effective k (the dense _phase01_select mask):
        # draws stay at the static k_max shape, masked tail slots fall
        # out of every phase-5 delivery column
        wit_valid = wit_valid & (
            jnp.arange(k, dtype=jnp.int32)[None, :] < knobs.ping_req_size
        )

    # staggered protocol periods (the swim_sim phase-1 port, VERDICT
    # item 4): static phase_mod = P gates probe initiation to one
    # residue class per tick; the per-node NetState.period tensor (the
    # gray-failure model, scenarios/faults.py) generalizes the divisor
    # and phase per node — a row of P reproduces phase_mod = P value
    # for value.  P == 1 with no period tensor traces the literal
    # lockstep program (bit-parity with the pre-port backend).
    per = (
        jnp.maximum(net.period, 1) if net.period is not None else None
    )
    if sw.probe == "sweep":
        import math

        mult = 0x9E37
        while math.gcd(mult, n) != 1:
            mult += 1
        start = (ids * jnp.int32(mult)) % jnp.int32(n)
        div = _sweep_divisor(phase_mod, per)
        if div is not None:
            swept = (start + state.tick // div) % jnp.int32(n)
        else:
            # literal lockstep expression: bit-parity with the
            # pre-phase_mod-port delta program
            swept = (start + state.tick) % jnp.int32(n)
        swept_key = view_lookup(state, swept)
        sst = swept_key & 7
        ok = ((sst == ALIVE) | (sst == SUSPECT)) & (swept != ids)
        target = jnp.where(ok, swept, target)
        has_target = has_target | ok
        wit_valid = wit_valid & (wit != target[:, None])
    elif sw.probe != "uniform":
        raise ValueError(f"unknown probe policy: {sw.probe!r}")

    sends = _stagger_send_gate(
        gossiping & has_target, state.tick, n, phase_mod, per
    )
    t_safe = jnp.where(sends, target, 0)
    return gossiping, sends, t_safe, wit, wit_valid


# ---------------------------------------------------------------------------
# claim merge: matched updates elementwise, insertions by sorted merge
# ---------------------------------------------------------------------------


class _MergeOut(NamedTuple):
    state: DeltaState
    applied_points: jax.Array  # int32[] lattice applications (incl. refutations)
    refuted: jax.Array  # bool[N]
    dropped: jax.Array  # int32[] claims lost to table capacity


@annotate.scoped("delta.merge_claims")
def _merge_claims(
    state: DeltaState,
    c_subj: jax.Array,  # int32[N, K] subject per claim, ascending per row, SENTINEL pad
    c_key: jax.Array,  # int32[N, K] claim lattice keys (pre-deduped per subject)
    valid: jax.Array,  # bool[N, K]
    sl_start: int | jax.Array,
) -> _MergeOut:
    """Apply per-row claim lists (the sparse _merge_incoming).

    Claims must be subject-sorted and deduped per row (dedup at the
    plain key max — the dense backend's scatter-max convention).  The
    self claim follows membership.js:243-254: any suspect/faulty rumor
    about the receiver re-asserts alive at ``max(incs) + 1``; other
    self claims are ignored (the dense ``apply`` masks out the eye).
    """
    n, cap = state.n, state.capacity
    kk = c_subj.shape[1]
    ids = jnp.arange(n, dtype=jnp.int32)

    is_self = valid & (c_subj == ids[:, None])
    c_status = c_key & 7
    rumor = is_self & ((c_status == SUSPECT) | (c_status == FAULTY))
    refuted = jnp.any(rumor, axis=1)
    rumor_inc = jnp.max(jnp.where(rumor, c_key >> 3, -1), axis=1)

    # current belief at each claimed subject
    subj_q = jnp.where(valid, c_subj, 0)
    pos, found = _lookup_pos(state.d_subj, subj_q)
    found = found & valid
    cur = jnp.where(
        found,
        jnp.take_along_axis(state.d_key, pos, axis=1),
        state.base_at(subj_q),
    )
    applies = valid & ~is_self & _apply_mask(cur, c_key)

    # --- matched updates: invert (claim -> slot) into (slot -> claim) --
    # a slot's updating claim, if any, is located by searching the
    # claim subjects for the slot's subject (claims are sorted too).
    s_pos = _row_searchsorted(
        c_subj,
        jnp.where(stats_live := (state.d_subj < SENTINEL),
                  state.d_subj, SENTINEL),
    )
    s_pos_c = jnp.minimum(s_pos, kk - 1)
    s_claim_subj = jnp.take_along_axis(c_subj, s_pos_c, axis=1)
    s_hit = stats_live & (s_claim_subj == state.d_subj)
    s_applies = s_hit & jnp.take_along_axis(applies, s_pos_c, axis=1)
    s_new_key = jnp.take_along_axis(c_key, s_pos_c, axis=1)

    d_key = jnp.where(s_applies, s_new_key, state.d_key)
    d_pb = jnp.where(s_applies, jnp.int8(0), state.d_pb)
    new_status = d_key & 7
    d_sl = jnp.where(
        s_applies & (new_status == SUSPECT), jnp.int8(sl_start), state.d_sl
    )
    d_sl = jnp.where(s_applies & (new_status != SUSPECT), jnp.int8(-1), d_sl)

    # --- refutation: self slot (matched or inserted) ------------------
    self_cur_inc = jnp.where(
        jnp.any((state.d_subj == ids[:, None]) & stats_live, axis=1),
        jnp.max(jnp.where((state.d_subj == ids[:, None]) & stats_live, state.d_key, 0), axis=1),
        state.base_at(ids),
    ) >> 3
    new_self_key = (jnp.maximum(self_cur_inc, rumor_inc) + 1) * 8 + ALIVE
    self_slot = (state.d_subj == ids[:, None]) & stats_live
    has_self_slot = jnp.any(self_slot, axis=1)
    upd_self = self_slot & refuted[:, None]
    d_key = jnp.where(upd_self, new_self_key[:, None], d_key)
    d_pb = jnp.where(upd_self, jnp.int8(0), d_pb)
    d_sl = jnp.where(upd_self, jnp.int8(-1), d_sl)

    # rolling digest (see DeltaState.digest): claim-aligned hash deltas
    # for the matched updates (old value ``cur`` is already in hand) and
    # the self refutation at an existing slot; insertions add theirs
    # under the insert cond below.  uint32 wrap-around sums commute, so
    # the increments compose in any order with the base decomposition.
    if state.digest is not None:
        d_matched = jnp.sum(
            jnp.where(
                applies & found,
                _hash1(c_key, subj_q) - _hash1(cur, subj_q),
                jnp.uint32(0),
            ),
            axis=1,
            dtype=jnp.uint32,
        )
        old_self_key = jnp.max(
            jnp.where(self_slot, state.d_key, 0), axis=1
        )  # the (unique) self slot's pre-update value
        d_self = jnp.where(
            refuted & has_self_slot,
            _hash1(new_self_key, ids) - _hash1(old_self_key, ids),
            jnp.uint32(0),
        )
        digest = state.digest + d_matched + d_self
    else:
        digest = None

    state = state._replace(d_key=d_key, d_pb=d_pb, d_sl=d_sl, digest=digest)

    # --- insertions: applying claims whose subject has no slot --------
    ins = applies & ~found
    # self refutation needing a fresh slot
    self_ins = refuted & ~has_self_slot
    ins_count = jnp.sum(ins, axis=1) + self_ins.astype(jnp.int32)
    any_ins = jnp.any(ins_count > 0)

    applied_points = jnp.sum(applies, dtype=jnp.int32) + jnp.sum(
        refuted, dtype=jnp.int32
    )

    free = cap - jnp.sum(stats_live.astype(jnp.int32), axis=1)

    def _insert_tail(st, m_subj, m_key, m_pb, m_sl, m_bpm, m_bpr,
                     keep, keep_self, dropped):
        """Digest update + state replace shared by both insert-merge
        lowerings (the digest reads pre-merge quantities only)."""
        if st.digest is not None:
            # KEPT insertions only (dropped claims never reach the
            # table); the old view value at a not-found subject is its
            # base — which is exactly ``cur`` where ~found
            d_ins = jnp.sum(
                jnp.where(
                    keep,
                    _hash1(c_key, subj_q) - _hash1(cur, subj_q),
                    jnp.uint32(0),
                ),
                axis=1,
                dtype=jnp.uint32,
            ) + jnp.where(
                keep_self,
                _hash1(new_self_key, ids) - _hash1(st.base_at(ids), ids),
                jnp.uint32(0),
            )
            digest2 = st.digest + d_ins
        else:
            digest2 = None
        return (
            st._replace(
                d_subj=m_subj,
                d_key=m_key,
                d_pb=m_pb,
                d_sl=m_sl,
                digest=digest2,
                d_bpmask=m_bpm,
                d_bprank=m_bpr,
            ),
            dropped,
        )

    def do_insert(st: DeltaState) -> tuple[DeltaState, jax.Array]:
        # drop insertions beyond each row's free slots (claims lost =
        # packet loss semantics; counted).  Order: self first, then
        # subject order — deterministic.
        order_rank = jnp.cumsum(ins.astype(jnp.int32), axis=1) - ins.astype(jnp.int32)
        order_rank = order_rank + self_ins.astype(jnp.int32)[:, None]
        keep = ins & (order_rank < free[:, None])
        keep_self = self_ins & (free > 0)
        dropped = jnp.sum(ins & ~keep, dtype=jnp.int32) + jnp.sum(
            self_ins & ~keep_self, dtype=jnp.int32
        )

        ins_key = jnp.where(keep, c_key, 0)
        ins_subj = jnp.where(keep, c_subj, SENTINEL)

        # self insertion rides as one extra column (pb/sl are
        # recomputed at the merged output below, so only subj/key ride)
        ins_subj = jnp.concatenate(
            [ins_subj, jnp.where(keep_self, ids, SENTINEL)[:, None]], axis=1
        )
        ins_key = jnp.concatenate(
            [ins_key, jnp.where(keep_self, new_self_key, 0)[:, None]], axis=1
        )

        # sorted merge WITHOUT the [N, C+K+1] concat + argsort the r05
        # census blamed for the flagship's biggest temp class: sort only
        # the [N, K+1] insert list, locate each insert's merged position
        # by binary search, and invert the merge per output slot with
        # two [N, C]-wide gathers.  Existing-vs-inserted subject ties
        # cannot happen (``ins`` requires ~found, ``self_ins`` requires
        # ~has_self_slot), and insertions fit in ``free``, so the
        # interleave is a plain two-sorted-sequence merge; SENTINEL
        # pads of both sequences carry identical payloads, so tie order
        # among pads is irrelevant.
        s_ins_subj, s_ins_key = jax.lax.sort((ins_subj, ins_key), num_keys=1)
        ki = s_ins_subj.shape[1]  # K + 1
        if _MERGE_METHOD == "pallas" and st.d_bpmask is None:
            # fused VMEM merge (the carried-slot-base planes keep the
            # sorted lowering: their payloads need state-level lookups
            # the standalone kernel deliberately does not know about)
            from ringpop_tpu.ops.delta_merge_pallas import merge_insert_pallas

            m_subj, m_key, m_pb, m_sl = merge_insert_pallas(
                st.d_subj, st.d_key, st.d_pb, st.d_sl,
                s_ins_subj, s_ins_key,
                sl_start=int(sl_start), suspect=SUSPECT,
                interpret=jax.default_backend() != "tpu",
            )
            m_bpm = None
            m_bpr = None
            return _insert_tail(st, m_subj, m_key, m_pb, m_sl,
                                m_bpm, m_bpr, keep, keep_self, dropped)
        # merged position of insert k: k existing-inserts before it plus
        # the existing live slots with a smaller subject.  SENTINEL tail
        # entries land at live_count + k >= every occupied output slot,
        # and the sequence stays strictly increasing, so the position
        # search below never selects them for an occupied j.
        pos_ins = _row_searchsorted(st.d_subj, s_ins_subj) + jnp.arange(
            ki, dtype=jnp.int32
        )
        out_j = jnp.broadcast_to(jnp.arange(cap, dtype=jnp.int32), (n, cap))
        e = _row_searchsorted(pos_ins, out_j)  # inserts before slot j
        e_c = jnp.minimum(e, ki - 1)
        is_ins = jnp.take_along_axis(pos_ins, e_c, axis=1) == out_j
        x = jnp.minimum(out_j - e, cap - 1)  # existing slot feeding j
        m_subj = jnp.where(
            is_ins,
            jnp.take_along_axis(s_ins_subj, e_c, axis=1),
            jnp.take_along_axis(st.d_subj, x, axis=1),
        )
        m_key = jnp.where(
            is_ins,
            jnp.take_along_axis(s_ins_key, e_c, axis=1),
            jnp.take_along_axis(st.d_key, x, axis=1),
        )
        # inserted pb/sl are pure functions of validity + key (pb 0,
        # sl only for fresh suspects; the self column's key is ALIVE),
        # so they are recomputed at the output instead of sorted along
        ins_at_j = is_ins & (m_subj < SENTINEL)
        m_pb = jnp.where(
            is_ins,
            jnp.where(ins_at_j, jnp.int8(0), jnp.int8(-1)),
            jnp.take_along_axis(st.d_pb, x, axis=1),
        )
        m_sl = jnp.where(
            is_ins,
            jnp.where(
                ins_at_j & ((m_key & 7) == SUSPECT),
                jnp.int8(sl_start),
                jnp.int8(-1),
            ),
            jnp.take_along_axis(st.d_sl, x, axis=1),
        )
        if st.d_bpmask is not None:
            # carried base-pingability snapshots: recomputed at the
            # inserted subjects (base structs are merge-invariant),
            # gathered through the same merge inversion for the rest
            m_subj_safe = jnp.where(ins_at_j, m_subj, 0)
            m_bpm = jnp.where(
                is_ins,
                ins_at_j & state.bp_mask_at(m_subj_safe),
                jnp.take_along_axis(
                    bitpack.unpack_bits(st.d_bpmask, cap), x, axis=1
                ),
            )
            m_bpm = bitpack.pack_bits(m_bpm)
            m_bpr = jnp.where(
                is_ins,
                jnp.where(ins_at_j, state.bp_rank_at(m_subj_safe), 0),
                jnp.take_along_axis(st.d_bprank, x, axis=1),
            )
        else:
            m_bpm = None
            m_bpr = None
        return _insert_tail(st, m_subj, m_key, m_pb, m_sl, m_bpm, m_bpr,
                            keep, keep_self, dropped)

    def no_insert(st: DeltaState) -> tuple[DeltaState, jax.Array]:
        return st, jnp.int32(0)

    state, dropped = jax.lax.cond(any_ins, do_insert, no_insert, state)
    return _MergeOut(
        state._replace(overflow_drops=state.overflow_drops + dropped),
        applied_points,
        refuted,
        dropped,
    )


# ---------------------------------------------------------------------------
# claim routing: sender lists -> per-receiver grids (sort + searchsorted)
# ---------------------------------------------------------------------------


def _run_bounds(sorted_vals: jax.Array, n: int) -> tuple[jax.Array, jax.Array]:
    """(starts, ends) of the value-runs 0..n-1 in a sorted int array.

    1-D searchsorted keeps the default "scan" (binary search): ~20
    dependent but fully vectorized gather steps — measured 1000x
    cheaper than the merge lowering at [1M] tables x [65k] queries
    (0.4 ms vs 441 ms; sorting the concat dwarfs 20 gathers).  For
    integer values, run i's end == run i+1's start, so one searchsorted
    over arange(n+1) yields both boundaries."""
    bounds = jnp.searchsorted(
        sorted_vals, jnp.arange(n + 1, dtype=jnp.int32), side="left"
    )
    return bounds[:-1], bounds[1:]


@annotate.scoped("delta.route_claims")
def _route_claims(
    n: int,
    send_subj: jax.Array,  # int32[N, W] sender's claim subjects (SENTINEL pad)
    send_key: jax.Array,  # int32[N, W]
    send_valid: jax.Array,  # bool[N, W]
    recv_of_sender: jax.Array,  # int32[N]
    grid: int,
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Flat-sort claims by (receiver, subject) and realign as an
    [N, grid] per-receiver grid (subjects ascending, duplicate subjects
    merged at the key max).  Returns (subj, key, valid, dropped)."""
    return _route_claims_multi(
        n, [(send_subj, send_key, send_valid, recv_of_sender)], grid
    )


def _route_claims_multi(
    n: int,
    segments: list[tuple[jax.Array, jax.Array, jax.Array, jax.Array]],
    grid: int,
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """_route_claims over several sender segments in one routing pass.

    Each segment is (subj [N, W], key [N, W], valid [N, W], recv [N]) —
    the phase-5 exchange routes one segment per witness slot in a
    single pass, preserving the one-merge-per-stage convention the
    dense step pins.

    Routing is by ROWS, not claims: a segment row's claims share one
    receiver, so grouping needs only a [S * N] sort of row-records
    (receiver keys), a gather of up to R = 2 * ceil(grid / W) sender
    rows per receiver, and ONE [N, R * W] row sort to merge/dedup by
    subject.  The earlier flat form sorted all S * N * W claim records
    every routed tick — at 8k nodes the phase-5 stages' 3 * N * 16
    sorts made the exchange ~15x the rest of the tick; this form is
    ~their phase-3 cost.

    Consumption is row-granular: a receiver consumes at most R sender
    rows (the 2x margin over grid/W covers partially-filled rows), then
    at most ``grid`` claims of their merge — excess rows/claims drop as
    late packets (counted in ``dropped``).  The ample-cap / bit-parity
    condition is therefore ``grid >= max_inbound_rows * W`` (for the
    phase-5 stages max_inbound_rows is ping_req_size * N in the
    adversarial worst case; tests use grid = 3 * n * wire_cap).

    Invariant: every segment shares ONE width W — the jnp.concatenate
    of the [N, W] row blocks requires it, and the R = 2 * ceil(grid/W)
    rows-per-receiver bound is computed from that single W.  A caller
    with narrower segments must pad them to the common width with
    SENTINEL subjects."""
    w = segments[0][0].shape[1]
    if any(s[0].shape[1] != w for s in segments):
        raise ValueError(
            "_route_claims_multi segments must share one claim width; got "
            f"{[s[0].shape[1] for s in segments]} — pad narrower segments "
            "to the common width with SENTINEL"
        )
    nrows = n * len(segments)
    row_recv = jnp.concatenate(
        [
            jnp.where(jnp.any(valid, axis=1), recv, n)
            for _, _, valid, recv in segments
        ]
    )  # int32[S*N]; n = silent row, sorts last
    rows_subj = jnp.concatenate(
        [jnp.where(valid, subj, SENTINEL) for subj, _, valid, _ in segments]
    )  # [S*N, W]
    rows_key = jnp.concatenate(
        [jnp.where(valid, key, 0) for _, key, valid, _ in segments]
    )
    rows_nvalid = jnp.sum(
        (rows_subj < SENTINEL).astype(jnp.int32), axis=1
    )  # valid-claim count per row

    order = jnp.argsort(row_recv, stable=True)
    recv_s = row_recv[order]
    starts, ends = _run_bounds(recv_s, n)
    counts = ends - starts  # sending rows per receiver
    r = min(2 * -(-grid // w), nrows)  # rows consumed per receiver
    idx = jnp.minimum(
        starts[:, None] + jnp.arange(r, dtype=jnp.int32)[None, :], nrows - 1
    )  # [N, R]
    row_ok = jnp.arange(r, dtype=jnp.int32)[None, :] < counts[:, None]
    src = jnp.where(row_ok, order[idx], 0)  # [N, R] source row ids
    if _on_ring():
        # p2p form: the [S*N, W] row table never replicates — each
        # segment's [N, W] block circulates the ring separately and a
        # receiver keeps only the <= R rows addressed to it.  The
        # row-id arithmetic (sort, run bounds, src) is rank-1 and
        # stays replicated; only the claim PAYLOAD rows ride the ring.
        seg_i = src // n
        snd = src - seg_i * n  # [N, R] sender row within its segment
        g_subj = jnp.full((n, r, w), SENTINEL, jnp.int32)
        g_key = jnp.zeros((n, r, w), jnp.int32)
        for s, (subj, key, valid, _) in enumerate(segments):
            pick = row_ok & (seg_i == s)
            snd_s = jnp.where(pick, snd, 0)
            f_subj = _gather_rows(jnp.where(valid, subj, SENTINEL), snd_s)
            f_key = _gather_rows(jnp.where(valid, key, 0), snd_s)
            g_subj = jnp.where(pick[:, :, None], f_subj, g_subj)
            g_key = jnp.where(pick[:, :, None], f_key, g_key)
        g_subj = g_subj.reshape(n, r * w)
        g_key = g_key.reshape(n, r * w)
    else:
        g_subj = jnp.where(
            row_ok[:, :, None], rows_subj[src], SENTINEL
        ).reshape(n, r * w)
        g_key = jnp.where(row_ok[:, :, None], rows_key[src], 0).reshape(n, r * w)
    kept = jnp.sum(jnp.where(row_ok, rows_nvalid[src], 0), dtype=jnp.int32)
    dropped = jnp.sum(rows_nvalid, dtype=jnp.int32) - kept

    # merge the gathered rows: subject-sort, dedup at the key max,
    # repack (SENTINEL holes would break _merge_claims' binary search)
    g_subj, g_key, g_valid = _sort_claim_rows(g_subj, g_key, g_subj < SENTINEL)
    if r * w > grid:
        # claims past the grid width are late packets (counted)
        dropped = dropped + jnp.sum(
            g_valid[:, grid:].astype(jnp.int32), dtype=jnp.int32
        )
        g_subj, g_key, g_valid = (
            g_subj[:, :grid],
            g_key[:, :grid],
            g_valid[:, :grid],
        )
    return g_subj, g_key, g_valid, dropped


# ---------------------------------------------------------------------------
# the protocol period
# ---------------------------------------------------------------------------


def _rotating_window(issuable: jax.Array, w: int, tick: jax.Array) -> jax.Array:
    """The wire window: ``w`` of a row's issuable entries, rotated by
    ``tick`` so a backlog wider than the wire cycles through fairly.

    The plain first-``w``-in-slot-order window starves the tail of a
    wide backlog: the front entries re-issue every tick until their
    piggyback budgets evict them (maxpb issues each) before the next
    block gets wire time — a netsplit-heal refutation storm of ~N fresh
    changes drained at maxpb * C/w ticks (measured: n=256 storm, wire
    16, stalled past 400 ticks).  Rotating the window start by
    ``tick * w`` positions makes the backlog cycle in ~C/w-tick rounds
    (measured: the same storm merges in ~30 ticks).  Identical to the
    plain window whenever the backlog fits the wire (<= w issuable
    entries per row) — the ample-cap bit-parity contract."""
    rank = jnp.cumsum(issuable.astype(jnp.int32), axis=1)  # inclusive, 1-based
    total = jnp.maximum(rank[:, -1:], 1)
    # uint32 product: tick * w overflows int32 after ~2^31/w ticks,
    # which would make the rotation sequence jump discontinuously on
    # very long horizons; unsigned arithmetic keeps the start advancing
    # by w (mod total) per tick for the full uint32 period
    start = (
        (tick.astype(jnp.uint32) * jnp.uint32(w)) % total.astype(jnp.uint32)
    ).astype(jnp.int32)
    return issuable & (((rank - 1 - start) % total) < w)


def _stage_issue_delta(
    st: DeltaState, nserve: jax.Array, maxpb: jax.Array, w: int
) -> tuple[DeltaState, jax.Array]:
    """One phase-5 exchange stage's issue bookkeeping (the delta twin of
    dense _stage_issue, plus the wire window): a node serving ``nserve``
    requests issues its first ``w`` active in-budget changes, advances
    served counters by ``nserve``, evicts past the budget; past-window
    entries keep their budget (the phase-2 rule).  Returns
    (state, within bool[N, C])."""
    has = st.d_pb >= 0
    ns8 = jnp.minimum(nserve, 127).astype(jnp.int8)[:, None]
    issuable = has & (ns8 > 0) & (st.d_pb + jnp.int8(1) <= maxpb[:, None])
    within = _rotating_window(issuable, w, st.tick)
    served = has & (ns8 > 0) & ~(issuable & ~within)
    evict = served & (st.d_pb > maxpb[:, None] - ns8)
    d_pb = jnp.where(
        evict, jnp.int8(-1), jnp.where(served, st.d_pb + ns8, st.d_pb)
    )
    return st._replace(d_pb=d_pb), within


def delta_step_impl(
    state: DeltaState, net: NetState, key: jax.Array, params: DeltaParams,
    upto: int = 7, knobs: SwimKnobs | None = None, prov: bool = False,
) -> tuple[DeltaState, dict[str, jax.Array]]:
    """One synchronized protocol period — the dense ``swim_step_impl``
    phase for phase (see its docstring for the reference parity map),
    over the delta representation.

    ``upto`` (static) truncates the step after the given phase — an
    on-device profiling aid (benchmarks/profile_delta.py): each prefix
    compiles as one executable, so consecutive differences attribute
    genuine device time per phase with no dispatch noise.  7 = the full
    step (production value; anything else returns partial metrics).

    ``prov`` (static) exports the delivery-evidence bundle for the
    provenance plane (``obs.provenance.EVIDENCE_KEYS``) — metrics-only,
    the state trajectory and PRNG stream stay bit-identical.  The hop
    masks already live outside the exchange conds here, so the export
    is a relabeling, not a recompute (cf. the dense step's CSE note).
    One documented deviation from the dense bundle: the full-sync base
    flip stays in-tick even over a delayed ack link (it is a structural
    flip, not a lane payload), so ``pv_ack`` includes ``fs_apply``."""

    def cut(st, **extra):
        m = {"pings_sent": jnp.zeros((), jnp.int32)}
        m.update(extra)
        return st, m

    if prov and upto != 7:
        raise ValueError(
            "provenance evidence spans every phase; prov requires the "
            "full step (upto=7)"
        )

    if net.adj is not None and net.adj.ndim != 1:
        raise NotImplementedError(
            "delta backend partitions take the int32[N] group-id form of "
            "NetState.adj (connected iff same group — block netsplits, "
            "swim_sim._adj); dense bool[N, N] masks (arbitrary topologies) "
            "need the dense backend"
        )
    sw = params.swim
    if state.digest is None:
        raise ValueError(
            "delta_step requires the rolling digest (DeltaState.digest); "
            "init_delta/make_sides/sparsify populate it — for a hand-built "
            "state use swim_delta.refresh_carried(state)"
        )
    if (state.d_bpmask is None) != (state.d_bprank is None):
        raise ValueError(
            "DeltaState.d_bpmask/d_bprank must be carried together "
            "(refresh_carried populates or clears both)"
        )
    if sw.sparse_cap:
        raise ValueError("sparse_cap is a dense-backend knob; use wire_cap here")
    if sw.relay_full_sync:
        raise ValueError(
            "relay_full_sync is the dense-step fidelity experiment "
            "(SwimParams docstring); the delta relay carries changes only"
        )
    if net.link_d is not None and state.pend_subj is None:
        raise ValueError(
            "per-link delay needs the in-flight claim lanes "
            "(DeltaState.pend_*): install them from tick 0 via "
            "SimCluster.enable_delay / swim_delta.install_pending"
        )
    if net.period is not None and sw.phase_mod != 1:
        raise ValueError(
            "per-node periods (NetState.period) do not compose with the "
            "static phase_mod stagger: a row of P subsumes phase_mod=P"
        )
    if knobs is not None and _MERGE_METHOD == "pallas":
        raise ValueError(
            "RINGPOP_DELTA_MERGE=pallas bakes the suspicion countdown "
            "into the fused merge kernel as a compile-time constant; "
            "traced knobs (SwimKnobs) need the sorted merge lowering"
        )
    n = state.n
    w = params.wire_cap
    ids = jnp.arange(n, dtype=jnp.int32)
    sl_start: int | jax.Array = _validate_params(n, sw)
    if knobs is not None:
        # traced countdown start (the dense convention); the delta
        # backend has no damping plane and rejects relay_full_sync, so
        # those knobs are host-pinned to their defaults upstream
        # (scenarios/sweep.py param_knob_axes validates per backend)
        sl_start = knobs.suspicion_ticks + jnp.int32(1)
    has_delay = state.pend_subj is not None
    if has_delay:
        # the two extra streams draw per-message jitter; split width is
        # keyed on the LANES' presence (not rule activity), mirroring
        # the dense step, so host-loop and compiled-scan ticks consume
        # keys identically (scenarios/faults.py HostPlan)
        k_sel, k_loss1, k_loss2, k_loss3, k_j1, k_j2 = jax.random.split(
            key, 6
        )
    else:
        k_sel, k_loss1, k_loss2, k_loss3 = jax.random.split(key, 4)

    # -- in-flight claims mature (latency model) ----------------------------
    # Slot ``tick % D`` lands at the START of the tick (the dense
    # convention): matured claims shape this tick's selection, digests,
    # and refutations exactly like claims merged last tick.  Down or
    # suspended receivers lose their matured claims, and the slot is
    # consumed either way.
    mat_applied = jnp.int32(0)
    mat_late = jnp.int32(0)
    if has_delay:
        dd = state.pend_subj.shape[0]
        slot0 = state.tick % jnp.int32(dd)
        m_subj = state.pend_subj[slot0]  # [L, N, W]
        m_key = state.pend_key[slot0]
        m_recv = state.pend_recv[slot0]  # [L, N]
        can_recv = net.up & net.responsive

        def _mature(st: DeltaState):
            segs = []
            for lane in range(m_subj.shape[0]):
                recv_l = m_recv[lane]
                recv_c = jnp.clip(recv_l, 0, n - 1)
                ok = (recv_l < n) & can_recv[recv_c]
                segs.append(
                    (
                        m_subj[lane],
                        m_key[lane],
                        (m_subj[lane] < SENTINEL) & ok[:, None],
                        recv_c,
                    )
                )
            g = _route_claims_multi(n, segs, params.claim_grid)
            out = _merge_claims(st, g[0], g[1], g[2], sl_start)
            return out.state, out.applied_points, g[3]

        def _no_mature(st: DeltaState):
            return st, jnp.int32(0), jnp.int32(0)

        state, mat_applied, mat_late = jax.lax.cond(
            jnp.any(m_subj < SENTINEL), _mature, _no_mature, state
        )
        state = state._replace(
            pend_subj=state.pend_subj.at[slot0].set(SENTINEL),
            pend_key=state.pend_key.at[slot0].set(0),
            pend_recv=state.pend_recv.at[slot0].set(n),
        )

    # -- phases 0-1 ---------------------------------------------------------
    stats = _phase0_stats(state)
    pb_factor = sw.piggyback_factor if knobs is None else knobs.piggyback_factor
    maxpb = _max_piggyback_1d(stats.server_count, pb_factor).astype(jnp.int8)
    h_pre = stats.digest
    if upto <= 0:
        return cut(state, _t=stats.digest.astype(jnp.int32) + maxpb.astype(jnp.int32))
    gossiping, sends, t_safe, wit, wit_valid = _selection(
        state, stats, net, k_sel, params, knobs
    )
    if upto <= 1:
        return cut(state, _t=t_safe + wit[:, 0] + stats.digest.astype(jnp.int32))

    # -- phase 2: sender issues up to W changes -----------------------------
    # window + budget bookkeeping under a has-claims cond: a tick where
    # no SENDER holds an active change (the converged common case) pays
    # two [N, C] mask passes for the pred instead of the rotating
    # window's cumsum + where chain
    has_change = state.d_pb >= 0
    bump = has_change & sends[:, None]

    # The cond carries ONLY the field this phase can change (d_pb): a
    # whole-state carry makes the cond's output buffers copy every
    # [N, C] table per tick — measured as the dominant cost of adding
    # state fields, since XLA does not reliably alias identity branches.
    def p2_issue(d_pb: jax.Array) -> tuple[jax.Array, jax.Array]:
        pb1_ok = bump & (d_pb + jnp.int8(1) <= maxpb[:, None])
        within = _rotating_window(pb1_ok, w, state.tick)  # fair wire window
        bump_eff = bump & ~(pb1_ok & ~within)  # past-window entries keep budget
        pb_next = jnp.where(bump_eff, d_pb + jnp.int8(1), d_pb)
        pb_next = jnp.where(
            bump_eff & (pb_next > maxpb[:, None]), jnp.int8(-1), pb_next
        )
        return pb_next, within

    def p2_quiet(d_pb: jax.Array) -> tuple[jax.Array, jax.Array]:
        return d_pb, jnp.zeros(d_pb.shape, bool)

    d_pb2, within = jax.lax.cond(jnp.any(bump), p2_issue, p2_quiet, state.d_pb)
    state = state._replace(d_pb=d_pb2)
    send_subj, send_key = _windowed_changes(state, within, w)
    if upto <= 2:
        # anchor phase-1 outputs too: without t_safe/wit in the live set
        # XLA DCEs the whole selection and the 2-vs-1 delta goes negative
        return cut(
            state,
            _t=jnp.sum(send_key) + jnp.sum(send_subj)
            + jnp.sum(t_safe) + jnp.sum(wit),
        )

    # -- phase 3: delivery + receiver merge ---------------------------------
    resp = net.up & net.responsive
    fwd_ok = (
        sends
        & _adj(net, ids, t_safe)
        & ~_drop_net(k_loss1, (n,), sw.loss, net, ids, t_safe)
        & resp[t_safe]
    )
    # the delivered set (anti-echo reference): a DELAYED claim still
    # counts as delivered — it is in the network (dense convention)
    sent_valid = (send_subj < SENTINEL) & fwd_ok[:, None]
    delayed_claims = jnp.int32(0)
    if has_delay:
        # latency slows INFORMATION, not liveness: the ping/ack RTT
        # stays in-tick (fwd_ok/ack/inbound all count every delivered
        # message) while the claim payload of a delayed link parks in
        # the lanes and merges d ticks later
        d3 = _message_delay(net, k_j1, ids, t_safe, (n,))
        dly3 = fwd_ok & (d3 > 0)
        sent_merge = (send_subj < SENTINEL) & (fwd_ok & ~dly3)[:, None]
        delayed_claims = delayed_claims + jnp.sum(
            sent_valid & dly3[:, None], dtype=jnp.int32
        )

        def park3(st: DeltaState) -> DeltaState:
            return _pend_write(
                st, 0, d3, dly3, send_subj, send_key, sent_valid, t_safe
            )

        state = jax.lax.cond(
            jnp.any(sent_valid & dly3[:, None]), park3, lambda st: st, state
        )
    else:
        sent_merge = sent_valid

    any_claims = jnp.any(sent_merge)

    def ping_merge(st: DeltaState) -> tuple[DeltaState, jax.Array, jax.Array]:
        g_subj, g_key, g_valid, late = _route_claims(
            n, send_subj, send_key, sent_merge, t_safe, params.claim_grid
        )
        out = _merge_claims(st, g_subj, g_key, g_valid, sl_start)
        return out.state, out.applied_points, late

    def ping_skip(st: DeltaState) -> tuple[DeltaState, jax.Array, jax.Array]:
        return st, jnp.int32(0), jnp.int32(0)

    state, ping_applied, claims_dropped = jax.lax.cond(
        any_claims, ping_merge, ping_skip, state
    )
    claims_dropped = claims_dropped + mat_late
    if upto <= 3:
        return cut(state, _t=ping_applied)

    # -- phase 4: receiver replies; sender merges the ack -------------------
    # (post phase-3 state: reply content includes changes just applied;
    # same has-claims gate as phase 2 — a no-receiver-holds-changes tick
    # skips the window and the serve/evict bookkeeping.  The inbound
    # ping count — an [N] sort — rides INSIDE the cond: it is consumed
    # only here, and the conservative pred ``any change & any delivered
    # ping`` is a superset of the exact ``any(rep_possible)``, so the
    # skipped branch is still a provable no-op while the converged tick
    # skips the sort too.)
    has_change2 = state.d_pb >= 0

    def p4_issue(d_pb: jax.Array) -> tuple[jax.Array, jax.Array]:
        # inbound ping count per receiver, scatter-free (sorted senders)
        tgt_sorted = jnp.sort(jnp.where(fwd_ok, t_safe, n))
        starts, ends = _run_bounds(tgt_sorted, n)
        inbound = (ends - starts).astype(jnp.int32)
        rep_possible2 = has_change2 & (inbound > 0)[:, None]
        rep_issuable = rep_possible2 & (d_pb + jnp.int8(1) <= maxpb[:, None])
        within_rep = _rotating_window(rep_issuable, w, state.tick)
        # receiver pb bookkeeping: advance by pings served, evict past
        # budget; windowed-out entries untouched (dense phase-4a + the
        # sparse-path window rule)
        inb8 = jnp.minimum(inbound, 127).astype(jnp.int8)[:, None]
        served = rep_possible2 & ~(rep_issuable & ~within_rep)
        evict = served & (d_pb > maxpb[:, None] - inb8)
        pb_after = jnp.where(
            evict, jnp.int8(-1), jnp.where(served, d_pb + inb8, d_pb)
        )
        return pb_after, within_rep

    def p4_quiet(d_pb: jax.Array) -> tuple[jax.Array, jax.Array]:
        return d_pb, jnp.zeros(d_pb.shape, bool)

    d_pb4, within_rep = jax.lax.cond(
        jnp.any(has_change2) & jnp.any(fwd_ok), p4_issue, p4_quiet, state.d_pb
    )
    state = state._replace(d_pb=d_pb4)

    # receiver digests after merge: the rolling digest IS the post-merge
    # value — the phase-3 merge maintained it per claim, p2/p4 touch
    # budgets only (no hash pass at all; the dense step recomputes its
    # [N, N] view hash here)
    h_post = state.digest

    rep_subj, rep_key = _windowed_changes(state, within_rep, w)

    # ack claims for sender s = reply list of its receiver (pure gather)
    ack = (
        fwd_ok
        & _adj(net, t_safe, ids)
        & ~_drop_net(k_loss2, (n,), sw.loss, net, t_safe, ids)
    )
    a_subj = _gather_rows(rep_subj, t_safe)  # [N, W]
    a_key = _gather_rows(rep_key, t_safe)
    a_subj_q = jnp.where(a_subj < SENTINEL, a_subj, 0)

    # anti-echo (value form, dense phase 4): drop reply claims about a
    # subject this sender delivered this tick whose value equals the
    # sender's CURRENT belief (post phase-3 merge — the dense step
    # compares against state.view_key after the receiver-side merge).
    # Gated: with no reply claims anywhere (a_subj all SENTINEL, the
    # converged case) a_raw is False regardless of echo, so the
    # delivered-set lookup and the [N, W] view search are skipped.
    def _echo(_):
        sent_sorted = jnp.where(sent_valid, send_subj, SENTINEL)
        _, sent_hit = _lookup_pos(sent_sorted, a_subj_q)
        cur_at_a = view_lookup(state, a_subj_q)
        return sent_hit & (a_key == cur_at_a)

    echo = jax.lax.cond(
        jnp.any(a_subj < SENTINEL),
        _echo,
        lambda _: jnp.zeros(a_subj.shape, bool),
        None,
    )

    # full sync (dissemination.js:100-118): receiver had nothing
    # issuable for this sender (all claims echoed or none) but the
    # digests disagree -> sender adopts the receiver's entire view.
    # Detection keys on delivery (fwd_ok), application on the ack
    # surviving the return path — exactly the dense step's masks.
    a_raw = (a_subj < SENTINEL) & ~echo
    rep_any = jnp.any(a_raw, axis=1)
    full_sync = fwd_ok & ~rep_any & (h_post[t_safe] != h_pre)
    fs_apply = full_sync & ack
    if has_delay:
        # the reply claims ride the receiver->sender link: delayed ack
        # payloads park keyed by their own (sender) row and merge d
        # ticks later; the ack bit itself still lands in-tick.  The
        # full-sync flip (fs_apply) stays in-tick even over a delayed
        # link — the documented delta deviation (it is a structural
        # base flip, not a claim payload the lanes can carry).
        d4 = _message_delay(net, k_j2, t_safe, ids, (n,))
        dly4 = ack & (d4 > 0)
        a_valid = a_raw & (ack & ~dly4)[:, None]
        a_park = a_raw & dly4[:, None]
        delayed_claims = delayed_claims + jnp.sum(a_park, dtype=jnp.int32)

        def park4(st: DeltaState) -> DeltaState:
            return _pend_write(st, 1, d4, dly4, a_subj, a_key, a_raw, ids)

        state = jax.lax.cond(
            jnp.any(a_park), park4, lambda st: st, state
        )
    else:
        a_valid = a_raw & ack[:, None]
    any_fs = jnp.any(fs_apply)
    any_ack_claims = jnp.any(a_valid) | any_fs

    def ack_merge(st: DeltaState) -> tuple[DeltaState, jax.Array]:
        def normal(st2):
            out = _merge_claims(st2, *_sort_claim_rows(a_subj, a_key, a_valid), sl_start)
            return out.state, out.applied_points

        def with_fs(st2):
            # receiver's delta table is its entire divergence from ITS
            # base: full sync = those claims + base claims at sender
            # slots the receiver doesn't override (+ in sided mode the
            # base FLIP below, which covers the receiver-base-vs-
            # sender-base bulk without materializing it as claims).
            #
            # Provider snapshot taken BEFORE the flip/absorb pass: a
            # provider that itself flips as an adopter this tick
            # compacts slots into its merged base — shipping the
            # post-flip table alongside the pre-flip base row
            # (fs_provider_side) would draw the sync from two
            # inconsistent snapshots and omit values the provider's
            # served view actually held.  One consistent pre-flip
            # snapshot (table + side + base) is the view the provider
            # held when it answered the ping.
            fs_subj0 = _gather_rows(st2.d_subj, t_safe)  # [N, C]
            fs_key0 = _gather_rows(st2.d_key, t_safe)
            fs_provider_side = None
            if st2.side is not None:
                # Sided mode: the full-sync PROVIDER is the ping
                # receiver (t_safe); the adopter is the ping sender
                # (this viewer row).  A cross-side sync flips the
                # adopter onto the merge row — its base becomes the
                # lattice merge of both bases (host invariant of
                # merge_to), so every UNSLOTTED entry adopts
                # lmerge(base_s, base_r) wholesale.  Flip before the
                # claim merges: provider slots then apply against the
                # post-flip view.  Sided deviation (documented):
                # flip-adopted entries get no pb records — peers learn
                # them via their own syncs.
                fs_provider_side = st2.side[t_safe]
                flip = fs_apply & (fs_provider_side != st2.side)
                st2 = st2._replace(
                    side=jnp.where(
                        flip, st2.merge_to[st2.side, fs_provider_side], st2.side
                    )
                )
                # Absorb the merged base: slots the new base already
                # covers (slot value does not beat M) drop — the view
                # rises monotonically to M, stale slots stop masking
                # the better base value, and the row drains so the
                # refutation below always has a free slot.  Dropped
                # slots' pb duty is forfeited (flip semantics); their
                # suspicion timers are void (status superseded).
                # STALE FROM HERE: this compaction permutes/drops slots
                # without maintaining the d_bpmask/d_bprank digest
                # tensors — they keep their pre-absorb layout until the
                # wholesale _refresh_in_step at the end of this branch.
                # Do not read them between those two points.
                live2 = st2.d_subj < SENTINEL
                subj2 = jnp.where(live2, st2.d_subj, 0)
                m_at = st2.base_at(subj2)
                is_self_slot = st2.d_subj == ids[:, None]
                keep = live2 & (
                    ~flip[:, None]
                    | _apply_mask(m_at, st2.d_key)
                    | is_self_slot  # permanent (see make_sides)
                )
                # a kept self slot superseded by M adopts M's value so
                # the view still rises (refutation below then sees it)
                lift_self = live2 & is_self_slot & flip[:, None] & ~_apply_mask(
                    m_at, st2.d_key
                ) & (m_at > st2.d_key)
                st2 = st2._replace(
                    d_key=jnp.where(lift_self, m_at, st2.d_key),
                    d_pb=jnp.where(lift_self, jnp.int8(-1), st2.d_pb),
                    d_sl=jnp.where(lift_self, jnp.int8(-1), st2.d_sl),
                )
                f_subj = jnp.where(keep, st2.d_subj, SENTINEL)
                order_f = jnp.argsort(f_subj, axis=1)
                st2 = st2._replace(
                    d_subj=jnp.take_along_axis(f_subj, order_f, axis=1),
                    d_key=jnp.take_along_axis(
                        jnp.where(keep, st2.d_key, 0), order_f, axis=1
                    ),
                    d_pb=jnp.take_along_axis(
                        jnp.where(keep, st2.d_pb, jnp.int8(-1)), order_f, axis=1
                    ),
                    d_sl=jnp.take_along_axis(
                        jnp.where(keep, st2.d_sl, jnp.int8(-1)), order_f, axis=1
                    ),
                )
            fs_valid0 = (fs_subj0 < SENTINEL) & fs_apply[:, None]
            # merge the W-wide ack list into the C-wide claim set (the
            # non-full-sync senders still apply their normal claims)
            m_subj = jnp.concatenate(
                [jnp.where(a_valid, a_subj, SENTINEL),
                 jnp.where(fs_valid0, fs_subj0, SENTINEL)], axis=1)
            m_key = jnp.concatenate(
                [jnp.where(a_valid, a_key, 0),
                 jnp.where(fs_valid0, fs_key0, 0)], axis=1)
            m_valid = jnp.concatenate([a_valid, fs_valid0], axis=1)
            out = _merge_claims(
                st2, *_sort_claim_rows(m_subj, m_key, m_valid), sl_start
            )
            st3 = out.state
            # base claims at sender-side slots absent from the
            # receiver's table (receiver's view there == its base) —
            # checked against the SAME pre-flip snapshot the claims
            # came from
            live3 = st3.d_subj < SENTINEL
            subj_safe3 = jnp.where(live3, st3.d_subj, 0)
            rpos, rfound = _lookup_pos(fs_subj0, subj_safe3)
            if st3.side is None:
                base_claim = st3.base_key[subj_safe3]
            else:
                # the PROVIDER's base: its view at its unslotted
                # subjects is exactly its base row
                base_claim = st3.base_key[fs_provider_side[:, None], subj_safe3]
            applies_b = (
                live3
                & fs_apply[:, None]
                & ~rfound
                & (st3.d_subj != ids[:, None])
                & _apply_mask(st3.d_key, base_claim)
            )
            d_key = jnp.where(applies_b, base_claim, st3.d_key)
            d_pb = jnp.where(applies_b, jnp.int8(0), st3.d_pb)
            nst = d_key & 7
            d_sl = jnp.where(
                applies_b & (nst == SUSPECT), jnp.int8(sl_start), st3.d_sl
            )
            d_sl = jnp.where(applies_b & (nst != SUSPECT), jnp.int8(-1), d_sl)
            st4 = st3._replace(d_key=d_key, d_pb=d_pb, d_sl=d_sl)
            applied_b = out.applied_points + jnp.sum(applies_b, dtype=jnp.int32)
            if st4.side is not None:
                # a flip can adopt a suspect/faulty claim about the
                # sender ITSELF through the merged base (the dense full
                # sync would refute in the same merge) — refute now
                own_now = view_lookup(st4, ids)
                own_st = own_now & 7
                need_ref = fs_apply & ((own_st == SUSPECT) | (own_st == FAULTY))
                out2 = _merge_claims(
                    st4,
                    ids[:, None],
                    own_now[:, None],
                    need_ref[:, None],
                    sl_start,
                )
                st4 = out2.state
                applied_b = applied_b + out2.applied_points
            # The flip/absorb compaction and the direct base-claim
            # writes above bypass _merge_claims' rolling-digest
            # accounting — recompute wholesale (this branch only runs
            # when a full sync fired somewhere, already the heavy path)
            st4 = _refresh_in_step(st4)
            return st4, applied_b

        # the absorb branch only runs when a full sync fired somewhere;
        # the profiler scopes make the heavy path legible in a trace
        return jax.lax.cond(
            any_fs,
            annotate.scoped("delta.fs_absorb")(with_fs),
            annotate.scoped("delta.ack_merge")(normal),
            st,
        )

    def ack_skip(st: DeltaState) -> tuple[DeltaState, jax.Array]:
        return st, jnp.int32(0)

    state, ack_applied = jax.lax.cond(any_ack_claims, ack_merge, ack_skip, state)
    if upto <= 4:
        return cut(state, _t=ack_applied)

    # -- phase 5: ping-req relay with the piggyback exchange ----------------
    # Hop deliveries and the four stage merges mirror the dense
    # _phase5_pingreq exactly (same k_a..k_d draw shapes, same stage
    # conventions — see its docstring); the routed-claim form adds only
    # the wire window (past-window entries keep budget, phase-2 rule)
    # and the claim-grid bound, both ample-cap-invisible.
    failed = sends & ~ack
    k_a, k_b, k_c, k_d = jax.random.split(k_loss3, 4)
    kshape = (n, sw.ping_req_size)
    kk = sw.ping_req_size
    wit_safe = jnp.clip(wit, 0, n - 1)
    req_del = (
        failed[:, None]
        & wit_valid
        & _adj(net, ids[:, None], wit_safe)
        & ~_drop_net(k_a, kshape, sw.loss, net, ids[:, None], wit_safe)
        & resp[wit_safe]
    )
    ping_del = (
        req_del
        & _adj(net, wit_safe, t_safe[:, None])
        & ~_drop_net(k_b, kshape, sw.loss, net, wit_safe, t_safe[:, None])
        & resp[t_safe][:, None]
    )
    ack_del = (
        ping_del
        & _adj(net, t_safe[:, None], wit_safe)
        & ~_drop_net(k_c, kshape, sw.loss, net, t_safe[:, None], wit_safe)
    )
    resp_del = (
        req_del
        & _adj(net, wit_safe, ids[:, None])
        & ~_drop_net(k_d, kshape, sw.loss, net, wit_safe, ids[:, None])
    )
    any_success = jnp.any(ack_del & resp_del, axis=1)
    definite_fail = jnp.any(req_del & ~ack_del & resp_del, axis=1)
    declare_suspect = failed & ~any_success & definite_fail

    def _role_counts(recv2d: jax.Array, mask2d: jax.Array) -> jax.Array:
        """int32[N] delivered-request count per receiver over all slots
        (sort + run bounds; the delta twin of dense _slot_counts)."""
        flat = jnp.sort(jnp.where(mask2d, recv2d, n).reshape(-1))
        s_, e_ = _run_bounds(flat, n)
        return (e_ - s_).astype(jnp.int32)

    def _stage(st, acc, pred, build_segs):
        """Route + merge one exchange stage under a has-claims cond: in
        the converged steady state (the 65k headline) failed probes
        happen every tick but NOBODY holds an active change, so every
        stage's claim set is empty and the whole stage body — segment
        building (anti-echo lookups), routing, merging — must cost
        nothing.  ``pred`` is the conservative any-windowed-change bit
        (claims can only shrink from there, via delivery masks and
        anti-echo), so a skipped stage is provably a no-op."""
        applied, late = acc

        def go(st2):
            g = _route_claims_multi(n, build_segs(st2), params.claim_grid)
            out = _merge_claims(st2, g[0], g[1], g[2], sl_start)
            return out.state, out.applied_points, g[3]

        def skip(st2):
            return st2, jnp.int32(0), jnp.int32(0)

        st, ap, lt = jax.lax.cond(pred, go, skip, st)
        return st, (applied + ap, late + lt)

    # skip-branch stand-ins for windowed (subject, key) lists; width
    # must match _windowed_changes' min(w, C) cap or the cond branches
    # disagree on shape
    w_eff = min(w, state.capacity)
    w_empty = (
        jnp.full((n, w_eff), SENTINEL, jnp.int32),
        jnp.zeros((n, w_eff), jnp.int32),
    )

    def exchange(st: DeltaState) -> tuple[DeltaState, jax.Array, jax.Array]:
        # Each stage runs under a claims-on-the-hop-path cond: the stage
        # (its issue/serve bookkeeping, role-count sorts, window
        # compaction, routing, merging) is a provable no-op unless some
        # node that ISSUES in that stage holds an active change — a
        # node with no d_pb >= 0 row has nothing to issue, serve, or
        # evict.  The preds are cheap gathers of a per-node has-change
        # bit (refreshed between stages: a 5a merge can hand the
        # witness fresh changes to relay in 5b).  Round-4 ran the
        # bookkeeping passes whenever ANY node held a change anywhere
        # (~20% of the quiet tick at n=8,192); the per-stage preds
        # additionally require that node to sit on this tick's hop
        # path.
        acc = (jnp.int32(0), jnp.int32(0))

        # -- 5a: the ping-req body carries the source's changes ---------
        def go_a(st2):
            nreq = jnp.sum(failed[:, None] & wit_valid, axis=1, dtype=jnp.int32)
            st2, win_a = _stage_issue_delta(st2, nreq, maxpb, w)
            sa = _windowed_changes(st2, win_a, w)
            st2, acc2 = _stage(
                st2,
                (jnp.int32(0), jnp.int32(0)),
                jnp.any(win_a),
                lambda st3: [
                    (
                        sa[0],
                        sa[1],
                        (sa[0] < SENTINEL) & req_del[:, m][:, None],
                        wit_safe[:, m],
                    )
                    for m in range(kk)
                ],
            )
            return st2, acc2[0], acc2[1], sa[0]

        def skip_a(st2):
            return st2, jnp.int32(0), jnp.int32(0), w_empty[0]

        st, ap, lt, sa_subj = jax.lax.cond(
            jnp.any((st.d_pb >= 0) & failed[:, None]), go_a, skip_a, st
        )
        acc = (acc[0] + ap, acc[1] + lt)

        # -- 5b: the witness relay-pings the target with its changes ----
        hc_b = jnp.any(st.d_pb >= 0, axis=1)

        def go_b(st2):
            nsrv = _role_counts(wit_safe, req_del)
            st2, win_b = _stage_issue_delta(st2, nsrv, maxpb, w)
            sb_subj, sb_key = _windowed_changes(st2, win_b, w)
            nping_del = _role_counts(wit_safe, ping_del)

            def segs_b(st3):
                segs = []
                for m in range(kk):
                    b_subj = _gather_rows(sb_subj, wit_safe[:, m])
                    b_key = _gather_rows(sb_key, wit_safe[:, m])
                    segs.append(
                        (
                            b_subj,
                            b_key,
                            (b_subj < SENTINEL) & ping_del[:, m][:, None],
                            t_safe,
                        )
                    )
                return segs

            st2, acc2 = _stage(
                st2,
                (jnp.int32(0), jnp.int32(0)),
                jnp.any(win_b),
                segs_b,
            )
            # the witness's delivered set (5c anti-echo): its windowed
            # list, where it made at least one delivered relay ping
            wit_sent = jnp.where((nping_del > 0)[:, None], sb_subj, SENTINEL)
            return st2, acc2[0], acc2[1], wit_sent

        def skip_b(st2):
            return st2, jnp.int32(0), jnp.int32(0), w_empty[0]

        st, ap, lt, wit_sent_subj = jax.lax.cond(
            jnp.any(req_del & hc_b[wit_safe]), go_b, skip_b, st
        )
        acc = (acc[0] + ap, acc[1] + lt)

        # -- 5c: the target's ack carries its changes back --------------
        hc_c = jnp.any(st.d_pb >= 0, axis=1)

        def go_c(st2):
            ntgt = _role_counts(
                jnp.broadcast_to(t_safe[:, None], kshape), ping_del
            )
            st2, win_c = _stage_issue_delta(st2, ntgt, maxpb, w)
            sc_subj, sc_key = _windowed_changes(st2, win_c, w)

            def segs_c(st3):
                segs = []
                subj = _gather_rows(sc_subj, t_safe)
                key_c = _gather_rows(sc_key, t_safe)
                subj_q = jnp.where(subj < SENTINEL, subj, 0)
                for m in range(kk):
                    w_m = wit_safe[:, m]
                    # anti-echo: the witness delivered this subject in
                    # 5b and its current belief equals the claim
                    _, in_sent = _lookup_pos(
                        _gather_rows(wit_sent_subj, w_m), subj_q
                    )
                    pos_w, found_w = _lookup_pos(
                        _gather_rows(st3.d_subj, w_m), subj_q
                    )
                    if st3.side is None:
                        base_w = st3.base_key[subj_q]
                    else:
                        # the WITNESS's base row (its view is being
                        # probed), not the source viewer's
                        base_w = st3.base_key[st3.side[w_m][:, None], subj_q]
                    cur_w = jnp.where(
                        found_w,
                        jnp.take_along_axis(
                            _gather_rows(st3.d_key, w_m), pos_w, axis=1
                        ),
                        base_w,
                    )
                    echo = in_sent & (key_c == cur_w)
                    segs.append(
                        (
                            subj,
                            key_c,
                            (subj < SENTINEL) & ack_del[:, m][:, None] & ~echo,
                            w_m,
                        )
                    )
                return segs

            st2, acc2 = _stage(
                st2, (jnp.int32(0), jnp.int32(0)), jnp.any(win_c), segs_c
            )
            return st2, acc2[0], acc2[1]

        def skip_c(st2):
            return st2, jnp.int32(0), jnp.int32(0)

        st, ap, lt = jax.lax.cond(
            jnp.any(ping_del & hc_c[t_safe][:, None]), go_c, skip_c, st
        )
        acc = (acc[0] + ap, acc[1] + lt)

        # -- 5d: the witness response carries its (fresh) changes -------
        # issue set from the post-5c state: what the witness just learned
        # from the target ships straight back — the implicit-alive path
        hc_d = jnp.any(st.d_pb >= 0, axis=1)

        def go_d(st2):
            nsrv = _role_counts(wit_safe, req_del)
            st2, win_d = _stage_issue_delta(st2, nsrv, maxpb, w)
            sd_subj, sd_key = _windowed_changes(st2, win_d, w)
            src_sent_subj = jnp.where(
                jnp.any(req_del, axis=1)[:, None], sa_subj, SENTINEL
            )

            def segs_d(st3):
                segs = []
                for m in range(kk):
                    w_m = wit_safe[:, m]
                    subj = _gather_rows(sd_subj, w_m)
                    key_d = _gather_rows(sd_key, w_m)
                    subj_q = jnp.where(subj < SENTINEL, subj, 0)
                    _, in_sent = _lookup_pos(src_sent_subj, subj_q)
                    cur_s = view_lookup(st3, subj_q)
                    echo = in_sent & (key_d == cur_s)
                    segs.append(
                        (
                            subj,
                            key_d,
                            (subj < SENTINEL) & resp_del[:, m][:, None] & ~echo,
                            ids,
                        )
                    )
                return segs

            st2, acc2 = _stage(
                st2, (jnp.int32(0), jnp.int32(0)), jnp.any(win_d), segs_d
            )
            return st2, acc2[0], acc2[1]

        st, ap, lt = jax.lax.cond(
            jnp.any(req_del & hc_d[wit_safe]), go_d, skip_c, st
        )
        acc = (acc[0] + ap, acc[1] + lt)
        return st, acc[0], acc[1]

    def no_exchange(st: DeltaState) -> tuple[DeltaState, jax.Array, jax.Array]:
        return st, jnp.int32(0), jnp.int32(0)

    # With zero active changes cluster-wide the whole exchange is a
    # proven no-op (no claims -> no merges -> no refutations -> no new
    # changes), and in the converged steady state that is every tick —
    # the common case must skip even the bookkeeping passes.
    state, pingreq_applied, pingreq_late = jax.lax.cond(
        jnp.any(req_del) & jnp.any(state.d_pb >= 0),
        exchange,
        no_exchange,
        state,
    )
    claims_dropped = claims_dropped + pingreq_late

    # the declaration sees the post-exchange view (dense convention);
    # the view lookup rides inside the cond — declarations are rare
    # (every witness path must definitely fail), the quiet tick must
    # not pay the [N] table search
    dec_valid = declare_suspect & (t_safe != ids)
    any_dec = jnp.any(dec_valid)

    def dec_merge(st: DeltaState) -> DeltaState:
        cur_t = view_lookup(st, t_safe)
        dec_key = jnp.where(cur_t > 0, (cur_t >> 3) * 8 + SUSPECT, 0)
        out = _merge_claims(
            st, t_safe[:, None], dec_key[:, None], dec_valid[:, None], sl_start
        )
        return out.state

    state = jax.lax.cond(any_dec, dec_merge, lambda st: st, state)
    if upto <= 5:
        return cut(state, _t=jnp.sum(dec_valid.astype(jnp.int32)))

    # -- phase 6: suspicion countdowns fire -> faulty -----------------------
    # (gated: with no live countdown anywhere — the converged common
    # case — decrement, expiry test, and rewrites are all no-ops)
    # narrow carry: this phase can only change (d_key, d_pb, d_sl,
    # digest) — the tables/snapshots pass AROUND the cond uncopied
    def p6_countdown(args):
        key0, pb0, sl0, dg0 = args
        sl1 = jnp.where(sl0 > 0, sl0 - 1, sl0)
        expired = (
            (sl1 == 0)
            & ((key0 & 7) == SUSPECT)
            & gossiping[:, None]
            & (state.d_subj < SENTINEL)
        )
        d_key = jnp.where(expired, (key0 >> 3) * 8 + FAULTY, key0)
        d_pb = jnp.where(expired, jnp.int8(0), pb0)
        sl1 = jnp.where(expired, jnp.int8(-1), sl1)
        subj_e = jnp.where(expired, state.d_subj, 0)
        digest = dg0 + jnp.sum(
            jnp.where(
                expired,
                _hash1(d_key, subj_e) - _hash1(key0, subj_e),
                jnp.uint32(0),
            ),
            axis=1,
            dtype=jnp.uint32,
        )
        return (d_key, d_pb, sl1, digest), jnp.sum(expired, dtype=jnp.int32)

    def p6_quiet(args):
        return args, jnp.int32(0)

    (key6, pb6, sl6, dg6), n_expired = jax.lax.cond(
        jnp.any(state.d_sl >= 0),
        p6_countdown,
        p6_quiet,
        (state.d_key, state.d_pb, state.d_sl, state.digest),
    )
    state = state._replace(d_key=key6, d_pb=pb6, d_sl=sl6, digest=dg6)
    state = state._replace(tick=state.tick + 1)

    metrics = {
        "pings_sent": jnp.sum(sends, dtype=jnp.int32),
        "acks": jnp.sum(ack, dtype=jnp.int32),
        "ping_changes_applied": ping_applied,
        "ack_changes_applied": ack_applied,
        "full_syncs": jnp.sum(full_sync, dtype=jnp.int32),
        "ping_reqs": jnp.sum(failed, dtype=jnp.int32),
        "pingreq_changes_applied": pingreq_applied,
        "suspects_declared": jnp.sum(declare_suspect, dtype=jnp.int32),
        "faulty_declared": n_expired,
        "claims_dropped": claims_dropped,
        "overflow_drops": state.overflow_drops,
        "max_occupancy": jnp.max(
            jnp.sum((state.d_subj < SENTINEL).astype(jnp.int32), axis=1)
        ),
    }
    if has_delay:
        metrics["delayed_claims"] = delayed_claims
        metrics["matured_applied"] = mat_applied
    if prov:
        metrics.update(
            pv_tgt=t_safe,
            pv_send=sends,
            # in-tick payload deliveries only (delayed claims park in
            # the lanes; their eventual arrival has no in-tick edge)
            pv_ping=(fwd_ok & ~dly3) if has_delay else fwd_ok,
            # the full-sync flip applies in-tick even over a delayed
            # link (see docstring) — fs_apply joins the ack edge set
            pv_ack=((ack & ~dly4) | fs_apply) if has_delay else ack,
            pv_wit=wit_safe,
            pv_witv=wit_valid,
            pv_req=req_del,
            pv_rping=ping_del,
            pv_rack=ack_del,
            pv_resp=resp_del,
            # ATTEMPTED declarations (the dense export is the applied
            # mask); prov_update's post-view status gate filters the
            # lattice-refused ones identically on both backends
            pv_decl=dec_valid,
        )
    return state, metrics


def _sort_claim_rows(
    subj: jax.Array, key: jax.Array, valid: jax.Array
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Sort claim rows by subject and dedup at the key max (claims from
    mixed sources — ack + full-sync lists — may repeat a subject)."""
    subj = jnp.where(valid, subj, SENTINEL)
    key = jnp.where(valid, key, 0)
    # Two-key sort (subject asc, key DESC via negation — view keys are
    # non-negative) puts each subject run's lattice max in its first
    # slot, so the dedup is one elementwise compare against the left
    # neighbor instead of the former argsort + gathers + log2(kk)
    # shift-combine passes (each materializing two padded [N, kk]
    # temporaries — a top flagship temp in the r05 census).
    kk = subj.shape[1]
    subj, neg_key = jax.lax.sort((subj, -key), num_keys=2)
    first = jnp.pad(subj, ((0, 0), (1, 0)), constant_values=-1)[:, :kk] != subj
    valid = first & (subj < SENTINEL)
    subj = jnp.where(valid, subj, SENTINEL)
    key = jnp.where(valid, -neg_key, 0)
    # Re-pack (see _route_claims): dedup holes break the sortedness that
    # _merge_claims' binary search relies on.
    subj, key = jax.lax.sort((subj, key), num_keys=1)
    return subj, key, subj < SENTINEL


delta_step = jax.jit(
    delta_step_impl,
    static_argnames=("params", "upto", "prov"),
    donate_argnums=(0,),
)


def delta_run_impl(
    state: DeltaState,
    net: NetState,
    key: jax.Array,
    params: DeltaParams,
    ticks: int,
    knobs: SwimKnobs | None = None,
) -> tuple[DeltaState, dict[str, jax.Array]]:
    """``ticks`` periods under lax.scan (one compiled program).  Traced
    knobs ride as scan constants, not carry entries (the dense
    swim_run_impl convention — CARRY_BUDGETS stays knob-invariant)."""

    def body(st, subkey):
        return delta_step_impl(st, net, subkey, params, knobs=knobs)

    keys = jax.random.split(key, ticks)
    state, ms = jax.lax.scan(body, state, keys)
    return state, jax.tree_util.tree_map(lambda x: x[-1], ms)


delta_run = jax.jit(
    delta_run_impl, static_argnames=("params", "ticks"), donate_argnums=(0,)
)


# ---------------------------------------------------------------------------
# row materialization + exact convergence (device-side, no densify)
# ---------------------------------------------------------------------------


def materialize_rows(state: DeltaState, idx: jax.Array) -> jax.Array:
    """int32[len(idx), N] view rows for the requested viewers: the base
    with each viewer's delta slots scattered in (subjects are unique per
    row, so the scatter is conflict-free).  O(len(idx) * N) — the
    whole-cluster densify stays O(N^2) and is for tests only."""
    idx = jnp.asarray(idx, dtype=jnp.int32)
    n = state.n
    subj = state.d_subj[idx]  # [K, C]
    keyv = state.d_key[idx]
    live = subj < SENTINEL
    if state.side is None:
        rows = jnp.broadcast_to(state.base_key[None, :], (idx.shape[0], n))
    else:
        rows = state.base_key[state.side[idx]]
    k_ids = jnp.arange(idx.shape[0], dtype=jnp.int32)[:, None]
    # NOT unique_indices: every empty slot maps to the same dropped
    # column n, so the index array repeats n whenever a row has two or
    # more free slots.
    return rows.at[k_ids, jnp.where(live, subj, n)].set(
        jnp.where(live, keyv, 0), mode="drop"
    )


@jax.jit
def _converged_impl(
    state: DeltaState, up: jax.Array, responsive: jax.Array
) -> jax.Array:
    """Exact view agreement among live (gossiping) viewers — the delta
    twin of cluster._converged_impl, O(N * C) with no densify:
    viewer i's row equals the reference row iff (a) every live slot of i
    carries the reference's value at that subject and (b) i holds a slot
    at every subject where the reference row diverges from the base."""
    n, c = state.n, state.capacity
    ids = jnp.arange(n, dtype=jnp.int32)
    own = view_lookup(state, ids) & 7
    live = up & responsive & ((own == ALIVE) | (own == SUSPECT))
    ref = jnp.argmax(live)

    ref_subj = state.d_subj[ref]  # [C]
    ref_key = state.d_key[ref]
    ref_live = ref_subj < SENTINEL
    ref_base = (
        state.base_key if state.side is None else state.base_key[state.side[ref]]
    )
    ref_row = ref_base.at[jnp.where(ref_live, ref_subj, n)].set(
        jnp.where(ref_live, ref_key, 0), mode="drop"
    )

    slots_live = state.d_subj < SENTINEL
    subj_safe = jnp.where(slots_live, state.d_subj, 0)
    ok_slots = jnp.all(
        jnp.where(slots_live, state.d_key == ref_row[subj_safe], True), axis=1
    )
    # viewer i must hold a slot wherever the ref row diverges from i's
    # OWN base.  Single base: one divergence set, checked by lookup.
    # Sided: the set differs per base row — count i's slots at its
    # side's divergence subjects and require all of them present
    # (exact, O(N * C + G * N); slot VALUES are checked by ok_slots).
    if state.side is None:
        div_ref = ref_live & (ref_key != ref_base[jnp.clip(ref_subj, 0, n - 1)])
        q = jnp.broadcast_to(jnp.where(div_ref, ref_subj, 0)[None, :], (n, c))
        _, found = _lookup_pos(state.d_subj, q)
        ok_cover = jnp.all(jnp.where(div_ref[None, :], found, True), axis=1)
    else:
        need_cover = state.base_key != ref_row[None, :]  # bool[G, N]
        need_count = jnp.sum(need_cover, axis=1, dtype=jnp.int32)[state.side]
        have = jnp.sum(
            slots_live & need_cover[state.side[:, None], subj_safe],
            axis=1,
            dtype=jnp.int32,
        )
        ok_cover = have == need_count
    row_same = ok_slots & ok_cover
    return jnp.all(jnp.where(live, row_same, True)) | (jnp.sum(live) <= 1)


# ---------------------------------------------------------------------------
# maintenance: compact (in-jit) and rebase (host)
# ---------------------------------------------------------------------------


@jax.jit
@annotate.scoped("delta.compact")
def compact(state: DeltaState) -> DeltaState:
    """Drop slots that match the base again with no active pb/suspicion
    (divergence healed by gossip); keeps rows sorted."""
    live = state.d_subj < SENTINEL
    subj_safe = jnp.where(live, state.d_subj, 0)
    needed = live & (
        (state.d_key != state.base_at(subj_safe))
        | (state.d_pb >= 0)
        | (state.d_sl >= 0)
    )
    if state.side is not None:
        # sided mode keeps permanent self slots (see make_sides)
        needed = needed | (
            live
            & (state.d_subj == jnp.arange(state.n, dtype=jnp.int32)[:, None])
        )
    d_subj = jnp.where(needed, state.d_subj, SENTINEL)
    order = jnp.argsort(d_subj, axis=1)
    return state._replace(
        d_subj=jnp.take_along_axis(d_subj, order, axis=1),
        d_key=jnp.take_along_axis(jnp.where(needed, state.d_key, 0), order, axis=1),
        d_pb=jnp.take_along_axis(
            jnp.where(needed, state.d_pb, jnp.int8(-1)), order, axis=1
        ),
        d_sl=jnp.take_along_axis(
            jnp.where(needed, state.d_sl, jnp.int8(-1)), order, axis=1
        ),
        # dropped slots matched the base, so the digest is invariant;
        # the carried slot-base snapshots just ride the reorder
        d_bpmask=None
        if state.d_bpmask is None
        else bitpack.pack_bits(
            jnp.take_along_axis(
                jnp.where(
                    needed,
                    bitpack.unpack_bits(state.d_bpmask, state.capacity),
                    False,
                ),
                order,
                axis=1,
            )
        ),
        d_bprank=None
        if state.d_bprank is None
        else jnp.take_along_axis(
            jnp.where(needed, state.d_bprank, 0), order, axis=1
        ),
    )


def rebase(state: DeltaState, anti_entropy: bool = False) -> DeltaState:
    """Fold majority divergence into the base (host-side, rare).

    For each subject, if most viewers have converged on one new value
    (e.g. the whole cluster declared a killed node faulty), that value
    becomes the base and the convergent slots are dropped; the minority
    — typically dead/stale rows that will never update — get small
    compensating slots carrying the old base value.  A subject folds
    only when it nets slots back (drops > inserts) and no affected row
    would overflow.  Returns a state whose materialized views are
    identical but whose tables only carry true disagreement — the
    long-running fast path regardless of accumulated churn.
    """
    state = compact(state)
    n, cap = state.n, state.capacity
    d_subj = np.asarray(state.d_subj).copy()
    d_key = np.asarray(state.d_key).copy()
    d_pb = np.asarray(state.d_pb).copy()
    d_sl = np.asarray(state.d_sl).copy()
    base = np.asarray(state.base_key).copy()

    if state.side is None:
        _fold_group(
            d_subj, d_key, d_pb, d_sl, base, np.arange(n), cap,
            anti_entropy=anti_entropy,
        )
    else:
        side = np.asarray(state.side)
        for g in range(base.shape[0]):
            members = np.flatnonzero(side == g)
            if members.size:
                _fold_group(
                    d_subj, d_key, d_pb, d_sl, base[g], members, cap,
                    anti_entropy=anti_entropy,
                )
        # Refresh merge-target rows: a flip must never regress the
        # adopter's view, so every merge row is lifted to the lattice
        # merge of itself and its source rows after per-side folds.
        mt = np.asarray(state.merge_to)
        for g1 in range(mt.shape[0]):
            for g2 in range(mt.shape[1]):
                m = int(mt[g1, g2])
                if m != g1 or m != g2:
                    base[m] = _lmerge_np(
                        base[m], _lmerge_np(base[g1], base[g2])
                    )

    order2 = np.argsort(d_subj, axis=1)
    d_subj = np.take_along_axis(d_subj, order2, axis=1)
    d_key = np.where(
        d_subj < int(SENTINEL), np.take_along_axis(d_key, order2, axis=1), 0
    )
    d_pb = np.where(
        d_subj < int(SENTINEL), np.take_along_axis(d_pb, order2, axis=1), -1
    )
    d_sl = np.where(
        d_subj < int(SENTINEL), np.take_along_axis(d_sl, order2, axis=1), -1
    )

    bp_mask, bp_rank, bp_list = _base_rank_structs(jnp.asarray(base))
    state = state._replace(
        base_key=jnp.asarray(base),
        bp_mask=bp_mask,
        bp_rank=bp_rank,
        bp_list=bp_list,
        d_subj=jnp.asarray(d_subj),
        d_key=jnp.asarray(d_key),
        d_pb=jnp.asarray(d_pb),
        d_sl=jnp.asarray(d_sl),
    )
    # plain folds preserve every view (digest invariant), but the
    # anti-entropy fold advances views to the side's lattice-max —
    # refresh the rolling digest either way (host-side, rare)
    return refresh_carried(state)


def make_sides(state: DeltaState, gid: np.ndarray | jax.Array) -> DeltaState:
    """Enter sided mode for a block netsplit (host-side, at split time).

    ``gid[i]`` in 0..G-1 assigns every viewer a side.  Creates G + 1
    base rows — one per side (each a copy of the current base) plus ONE
    merge row (their lattice merge — initially identical) — and the
    ``merge_to`` flip table: ``merge_to[g, g] = g``; any cross pair
    flips to the merge row.  Per-side `rebase` then lets each side's
    consensus (e.g. "the other side is faulty") fold into its own row
    while the merge row tracks the lattice merge of all — the
    structured-netsplit representation that keeps a 50/50 split at
    O(N * C).  Use with the matching group-id ``NetState.adj``."""
    if state.side is not None:
        raise ValueError("already sided; fold_to_single first")
    gid = np.asarray(gid, dtype=np.int32)
    g = int(gid.max()) + 1 if gid.size else 1
    base = np.asarray(state.base_key)
    rows = np.broadcast_to(base, (g + 1, base.shape[0])).copy()
    merge_to = np.full((g + 1, g + 1), g, dtype=np.int32)
    np.fill_diagonal(merge_to, np.arange(g + 1))
    bp_mask, bp_rank, bp_list = _base_rank_structs(jnp.asarray(rows))
    state = state._replace(
        base_key=jnp.asarray(rows),
        bp_mask=bp_mask,
        bp_rank=bp_rank,
        bp_list=bp_list,
        side=jnp.asarray(gid),
        merge_to=jnp.asarray(merge_to),
    )
    # Permanent self slots: in sided mode every viewer always holds its
    # own entry, so the self-refutation (membership.js:243-254) is an
    # in-place update that can NEVER be starved by a full table — a
    # dropped refutation leaves the member believing itself faulty and
    # silent forever (measured: 12 permanently-silent members at n=64
    # before this).  compact / folds / flips all preserve them.
    # One vectorized pass: viewers lacking a self slot write
    # (i, base[i], -1, -1) into their first free column, then re-sort.
    n = state.n
    d_subj = np.asarray(state.d_subj).copy()
    d_key = np.asarray(state.d_key).copy()
    d_pb = np.asarray(state.d_pb).copy()
    d_sl = np.asarray(state.d_sl).copy()
    ids = np.arange(n)
    has_self = (d_subj == ids[:, None]).any(axis=1)
    need = ~has_self
    if need.any():
        free_col = np.argmax(d_subj == int(SENTINEL), axis=1)
        if not (d_subj[need, free_col[need]] == int(SENTINEL)).all():
            raise ValueError("make_sides: no free slot for a self entry")
        r = ids[need]
        c = free_col[need]
        d_subj[r, c] = r
        d_key[r, c] = base[r]
        d_pb[r, c] = -1
        d_sl[r, c] = -1
        order = np.argsort(d_subj, axis=1)
        d_subj = np.take_along_axis(d_subj, order, axis=1)
        d_key = np.take_along_axis(d_key, order, axis=1)
        d_pb = np.take_along_axis(d_pb, order, axis=1)
        d_sl = np.take_along_axis(d_sl, order, axis=1)
        state = state._replace(
            d_subj=jnp.asarray(d_subj),
            d_key=jnp.asarray(d_key),
            d_pb=jnp.asarray(d_pb),
            d_sl=jnp.asarray(d_sl),
        )
    # views are preserved (self slots adopt base values) but the base
    # decomposition changed shape — refresh the rolling digest
    return refresh_carried(state)


def fold_to_single(state: DeltaState) -> DeltaState:
    """Leave sided mode (host-side, after the remerge converges).

    The single base becomes the lattice merge of all rows; viewers
    whose own base row still differs from it at some subject get
    compensating slots (their views must not move).  Call after
    `rebase` has drained the merge — the residual diffs are then ~0."""
    if state.side is None:
        return state
    base_rows = np.asarray(state.base_key)
    side = np.asarray(state.side)
    merged = base_rows[0].copy()
    for gr in range(1, base_rows.shape[0]):
        merged = _lmerge_np(merged, base_rows[gr])
    d_subj = np.asarray(state.d_subj).copy()
    d_key = np.asarray(state.d_key).copy()
    d_pb = np.asarray(state.d_pb).copy()
    d_sl = np.asarray(state.d_sl).copy()
    n, cap = state.n, state.capacity
    for i in range(n):
        own = base_rows[side[i]]
        diff = np.flatnonzero(own != merged)
        if diff.size == 0:
            continue
        row = d_subj[i]
        have = set(row[row < int(SENTINEL)].tolist())
        need = [j for j in diff if j not in have]
        free = np.flatnonzero(row == int(SENTINEL))
        if len(need) > free.size:
            raise ValueError(
                f"viewer {i}: {len(need)} compensating slots exceed free "
                f"capacity {free.size}; rebase before fold_to_single"
            )
        for c, j in zip(free, need):
            d_subj[i, c] = j
            d_key[i, c] = own[j]
            d_pb[i, c] = -1
            d_sl[i, c] = -1
        order = np.argsort(d_subj[i])
        d_subj[i] = d_subj[i][order]
        d_key[i] = np.where(d_subj[i] < int(SENTINEL), d_key[i][order], 0)
        d_pb[i] = np.where(d_subj[i] < int(SENTINEL), d_pb[i][order], -1)
        d_sl[i] = np.where(d_subj[i] < int(SENTINEL), d_sl[i][order], -1)
    bp_mask, bp_rank, bp_list = _base_rank_structs(jnp.asarray(merged))
    state = state._replace(
        base_key=jnp.asarray(merged),
        bp_mask=bp_mask,
        bp_rank=bp_rank,
        bp_list=bp_list,
        d_subj=jnp.asarray(d_subj),
        d_key=jnp.asarray(d_key),
        d_pb=jnp.asarray(d_pb),
        d_sl=jnp.asarray(d_sl),
        side=None,
        merge_to=None,
    )
    return refresh_carried(state)


def _lmerge_np(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Pointwise lattice merge of two base rows (the host twin of
    _apply_mask: numeric max, except leave is only beaten by alive)."""
    beats = (b > a) & ~(((a & 7) == LEAVE) & ((b & 7) != ALIVE)) & (b > 0)
    return np.where(beats, b, a)


def _fold_group(
    d_subj: np.ndarray,
    d_key: np.ndarray,
    d_pb: np.ndarray,
    d_sl: np.ndarray,
    base_row: np.ndarray,
    members: np.ndarray,
    cap: int,
    anti_entropy: bool = False,
) -> None:
    """The rebase fold over one viewer group, in place.

    Default (view-preserving): for each subject, if the group's
    droppable (non-busy) slots mostly agree on one value, fold it into
    ``base_row``; group members WITHOUT a slot get compensating slots
    carrying the old base value (their views must not move).  A subject
    folds only when it nets slots back and no compensating insert would
    overflow.  Non-member rows are untouched (their views live against
    other base rows).

    ``anti_entropy=True`` (sided netsplit maintenance): fold each
    subject to the group's LATTICE-MAX value and drop superseded slots
    (value <= fold) without compensation — members' views advance
    monotonically to a value a peer genuinely holds, i.e. the
    dissemination layer's full-sync delivery applied in bulk at
    maintenance time (dissemination.js:61-76 semantics on a schedule).
    This is what keeps a capacity-bounded heal moving: the refutation
    storm's per-viewer divergence exceeds any bounded table, and the
    view-preserving fold wedges on never-unanimous columns (measured:
    n=256/C=64 heal pinned at 256 digests with ~8k drops/tick).
    Subjects involving leave-status values are skipped (numeric max is
    not the lattice join across the leave guard).  Active pb records on
    dropped slots are forfeited (their duty passes to the base
    consensus) — a documented bounded-resource deviation."""
    if anti_entropy:
        _fold_group_anti_entropy(d_subj, d_key, d_pb, d_sl, base_row, members)
        return
    nm = members.size
    n = base_row.shape[0]
    ds = d_subj[members]
    dk = d_key[members]
    dpb = d_pb[members]
    dsl = d_sl[members]

    live = ds < int(SENTINEL)
    rows, cols = np.nonzero(live)
    if rows.size == 0:
        return
    subs = ds[rows, cols]
    busy = (dpb[rows, cols] >= 0) | (dsl[rows, cols] >= 0)
    cnt = np.bincount(subs, minlength=n)  # member slot-holders per subject

    dr = ~busy
    if not dr.any():
        return
    s_d, k_d = subs[dr], dk[rows, cols][dr]
    order = np.lexsort((k_d, s_d))
    s_s, k_s = s_d[order], k_d[order]
    new_run = np.ones(len(s_s), dtype=bool)
    new_run[1:] = (s_s[1:] != s_s[:-1]) | (k_s[1:] != k_s[:-1])
    run_ids = np.cumsum(new_run) - 1
    run_counts = np.bincount(run_ids)
    run_subj = s_s[new_run]
    run_key = k_s[new_run]
    gains = run_counts - (nm - cnt[run_subj])
    best = np.lexsort((gains, run_subj))
    last_of_subj = np.ones(len(best), dtype=bool)
    last_of_subj[:-1] = run_subj[best][1:] != run_subj[best][:-1]
    pick = best[last_of_subj]
    pick = pick[gains[pick] > 0]
    if pick.size == 0:
        return

    occ = live.sum(axis=1)
    for p in pick[np.argsort(-gains[pick])]:
        j = int(run_subj[p])
        v = int(run_key[p])
        has_slot = np.zeros((nm,), dtype=bool)
        has_slot[rows[subs == j]] = True
        need_insert_idx = np.flatnonzero(~has_slot)
        if np.any(occ[need_insert_idx] >= cap):
            continue  # a compensating insert would overflow; skip
        drop_mask = live & (ds == j) & (dk == v) & (dpb < 0) & (dsl < 0)
        ds[drop_mask] = int(SENTINEL)
        for i in need_insert_idx:
            free = np.flatnonzero(ds[i] == int(SENTINEL))
            c = free[0]
            ds[i, c] = j
            dk[i, c] = base_row[j]
            dpb[i, c] = -1
            dsl[i, c] = -1
        base_row[j] = v
        live = ds < int(SENTINEL)
        occ = live.sum(axis=1)
        rows, cols = np.nonzero(live)
        subs = ds[rows, cols]

    d_subj[members] = ds
    d_key[members] = dk
    d_pb[members] = dpb
    d_sl[members] = dsl


def _fold_group_anti_entropy(
    d_subj: np.ndarray,
    d_key: np.ndarray,
    d_pb: np.ndarray,
    d_sl: np.ndarray,
    base_row: np.ndarray,
    members: np.ndarray,
) -> None:
    """Lattice-max fold (see _fold_group's anti_entropy doc), in place,
    fully vectorized: one lexsort over the group's live slots."""
    ds = d_subj[members]
    dk = d_key[members]
    live = ds < int(SENTINEL)
    rows, cols = np.nonzero(live)
    if rows.size == 0:
        return
    subs = ds[rows, cols]
    keys = dk[rows, cols]
    order = np.lexsort((keys, subs))
    s_s, k_s = subs[order], keys[order]
    starts = np.ones(len(s_s), dtype=bool)
    starts[1:] = s_s[1:] != s_s[:-1]
    run_subj = s_s[starts]
    # ascending key sort per run -> run max is the last element
    ends = np.flatnonzero(np.append(starts[1:], True))
    run_max = k_s[ends]
    has_leave = (
        np.add.reduceat((k_s & 7) == LEAVE, np.flatnonzero(starts)) > 0
    )
    fold = (
        (run_max > base_row[run_subj])
        & ~has_leave
        & ((base_row[run_subj] & 7) != LEAVE)
        # never fold SUSPECT values: their suspicion timers live in
        # slots, so a base-resident suspect would neither expire to
        # faulty nor ever be re-disseminated — a frozen consensus the
        # protocol cannot leave (measured: one column stuck suspect
        # forever at n=64).  Suspects stay in bounded tables; only the
        # stable alive/faulty states fold.
        & ((run_max & 7) != SUSPECT)
    )
    if not fold.any():
        return
    v_of = base_row.copy()
    v_of[run_subj[fold]] = run_max[fold]
    folded = np.zeros(base_row.shape[0], dtype=bool)
    folded[run_subj[fold]] = True
    # drop superseded member slots (value <= the fold), keep newer ones;
    # self slots are permanent (sided mode, see make_sides) — lift their
    # value to the fold instead so the view still advances
    subs_all = np.where(live, ds, 0)
    is_self_slot = live & (ds == members[:, None])
    superseded = live & folded[subs_all] & (dk <= v_of[subs_all])
    drop = superseded & ~is_self_slot
    lift = superseded & is_self_slot
    ds[drop] = int(SENTINEL)
    dkm = d_key[members]
    dpm = d_pb[members]
    dsm = d_sl[members]
    dkm[drop] = 0
    dpm[drop] = -1
    dsm[drop] = -1
    dkm[lift] = v_of[subs_all][lift]
    dpm[lift] = -1
    dsm[lift] = -1
    base_row[folded] = v_of[folded]

    # Refutation (membership.js:243-254 applied to the bulk delivery):
    # a fold may carry a suspect/faulty rumor about a MEMBER of this
    # very side — without the refutation the member's own view of
    # itself goes non-alive and it stops gossiping forever (the dense
    # path refutes on every such arrival).  Re-assert alive at
    # rumor_inc + 1 with a fresh dissemination record, unless a
    # surviving self slot already overrides the folded value.
    folded_self = folded[members] & np.isin(
        v_of[members] & 7, (SUSPECT, FAULTY)
    )
    for li in np.flatnonzero(folded_self):
        i = int(members[li])
        row = ds[li]
        hit = np.flatnonzero(row == i)
        new_key = ((int(v_of[i]) >> 3) + 1) * 8 + ALIVE
        if hit.size:
            if int(dkm[li, hit[0]]) > int(v_of[i]):
                continue  # already refuted past the rumor
            c = int(hit[0])
        else:
            free = np.flatnonzero(row == int(SENTINEL))
            if not free.size:
                continue  # full row: the gossip path will refute later
            c = int(free[0])
            ds[li, c] = i
        dkm[li, c] = new_key
        dpm[li, c] = 0
        dsm[li, c] = -1

    d_subj[members] = ds
    d_key[members] = dkm
    d_pb[members] = dpm
    d_sl[members] = dsm


# ---------------------------------------------------------------------------
# admin surface (host-side point ops — small states or rare events)
# ---------------------------------------------------------------------------


def _set_entry(
    state: DeltaState, viewer: int, subject: int, key: int, pb: int, sl: int
) -> DeltaState:
    """Host-side single-slot upsert (admin ops; not a hot path)."""
    d_subj = np.asarray(state.d_subj).copy()
    d_key = np.asarray(state.d_key).copy()
    d_pb = np.asarray(state.d_pb).copy()
    d_sl = np.asarray(state.d_sl).copy()
    row = d_subj[viewer]
    hit = np.nonzero(row == subject)[0]
    if hit.size:
        c = int(hit[0])
    else:
        free = np.nonzero(row == int(SENTINEL))[0]
        if not free.size:
            raise ValueError(f"viewer {viewer} delta table full")
        c = int(free[0])
        d_subj[viewer, c] = subject
    d_key[viewer, c] = key
    d_pb[viewer, c] = pb
    d_sl[viewer, c] = sl
    order = np.argsort(d_subj[viewer])
    st = state._replace(
        d_subj=jnp.asarray(d_subj).at[viewer].set(jnp.asarray(d_subj[viewer][order])),
        d_key=jnp.asarray(d_key).at[viewer].set(jnp.asarray(d_key[viewer][order])),
        d_pb=jnp.asarray(d_pb).at[viewer].set(jnp.asarray(d_pb[viewer][order])),
        d_sl=jnp.asarray(d_sl).at[viewer].set(jnp.asarray(d_sl[viewer][order])),
    )
    return st


def _base_row_np(state: DeltaState, viewer: int) -> np.ndarray:
    """Viewer's base row as numpy (side-aware)."""
    base = np.asarray(state.base_key)
    if state.side is None:
        return base
    return base[int(np.asarray(state.side)[viewer])]


def view_of(state: DeltaState, viewer: int, subject: int) -> int:
    row = np.asarray(state.d_subj[viewer])
    hit = np.nonzero(row == subject)[0]
    if hit.size:
        return int(np.asarray(state.d_key[viewer])[hit[0]])
    return int(_base_row_np(state, viewer)[subject])


def _materialize_row(state: DeltaState, i: int):
    """Dense (vk, pb, sl) of viewer ``i`` (host-side numpy)."""
    n = state.n
    vk = _base_row_np(state, i).copy()
    pb = np.full(n, -1, np.int8)
    sl = np.full(n, -1, np.int8)
    subj = np.asarray(state.d_subj[i])
    live = subj < int(SENTINEL)
    vk[subj[live]] = np.asarray(state.d_key[i])[live]
    pb[subj[live]] = np.asarray(state.d_pb[i])[live]
    sl[subj[live]] = np.asarray(state.d_sl[i])[live]
    return vk, pb, sl


def _write_row(
    state: DeltaState,
    i: int,
    vk: np.ndarray,
    pb: np.ndarray,
    sl: np.ndarray,
    *,
    elide_redundant: bool = False,
) -> DeltaState:
    """Re-sparsify a dense row against the base and store it as viewer
    ``i``'s table.  When the divergence exceeds capacity, base-valued
    entries (slots needed only for their pb/sl records, not their view)
    are dropped first — dropping a divergent entry would corrupt the
    view itself.  ``elide_redundant=True`` (the join path) drops those
    base-valued pb-records *silently*: a joiner re-announcing members
    everyone already agrees on is redundant traffic, not capacity
    pressure, so it must not pollute ``overflow_drops`` (at 65k nodes a
    single join would otherwise add ~n to the metric)."""
    n, cap = state.n, state.capacity
    base = _base_row_np(state, i)
    need = (vk != base) | (pb >= 0) | (sl >= 0)
    subs = np.flatnonzero(need)
    dropped = 0
    if len(subs) > cap:
        divergent = vk[subs] != base[subs]
        if divergent.sum() > cap:
            raise ValueError(
                f"viewer {i}: view divergence {int(divergent.sum())} exceeds "
                f"table capacity {cap}"
            )
        order = np.argsort(~divergent, kind="stable")  # divergent first
        kept = subs[order][:cap]
        cut = subs[order][cap:]
        if elide_redundant:
            # only cuts that lose real state (diverging view, or an
            # active suspicion record) count as overflow
            dropped = int(((vk[cut] != base[cut]) | (sl[cut] >= 0)).sum())
        else:
            dropped = len(cut)
        subs = np.sort(kept)
    row_subj = np.full(cap, int(SENTINEL), np.int32)
    row_key = np.zeros(cap, np.int32)
    row_pb = np.full(cap, -1, np.int8)
    row_sl = np.full(cap, -1, np.int8)
    row_subj[: len(subs)] = subs
    row_key[: len(subs)] = vk[subs]
    row_pb[: len(subs)] = pb[subs]
    row_sl[: len(subs)] = sl[subs]
    return state._replace(
        d_subj=state.d_subj.at[i].set(jnp.asarray(row_subj)),
        d_key=state.d_key.at[i].set(jnp.asarray(row_key)),
        d_pb=state.d_pb.at[i].set(jnp.asarray(row_pb)),
        d_sl=state.d_sl.at[i].set(jnp.asarray(row_sl)),
        overflow_drops=state.overflow_drops + jnp.int32(dropped),
    )


def admin_join(state: DeltaState, joiner: int, seed: int) -> DeltaState:
    """join-sender.js + join-handler.js over deltas: the seed marks the
    joiner alive (recording the change, preserving any running suspicion
    countdown), and the joiner adopts the seed's **entire** view with
    every adopted member recorded as a change (pb=0) — the reference
    records all bootstrap entries into dissemination
    (membership-set-listener.js:33-47).  Bit-exact to the dense
    ``swim_sim.admin_join`` when ``capacity >= n - 1``; at production
    caps the joiner's redundant re-announcements of base-valued members
    are elided instead (see ``_write_row``) — the documented
    bounded-resource deviation.  Host-side dense row ops: admin joins
    are rare, O(N) is fine."""
    n = state.n
    svk, spb, ssl = _materialize_row(state, seed)
    jvk, jpb, jsl = _materialize_row(state, joiner)

    # seed: makeAlive(joiner) (join-handler.js:90)
    j_key = int(jvk[joiner])
    in_key = (j_key >> 3) * 8 + ALIVE
    if bool(_apply_mask(jnp.int32(int(svk[joiner])), jnp.int32(in_key))):
        svk[joiner] = in_key
        spb[joiner] = 0
        state = _write_row(state, seed, svk, spb, ssl)

    # joiner: full-sync adoption of the seed's row; self entry kept
    learned = (svk > 0) & (np.arange(n) != joiner)
    jvk = np.where(learned, svk, jvk)
    jpb = np.where(learned, np.int8(0), jpb)
    jvk[joiner] = ALIVE if j_key == 0 else j_key
    if state.side is not None:
        # a cross-side join is a full-sync adoption: flip the joiner to
        # the merge row first so the re-sparsification below happens
        # against a base that already carries both sides' consensus
        side = np.asarray(state.side).copy()
        j_g, s_g = int(side[joiner]), int(side[seed])
        if j_g != s_g:
            side[joiner] = int(np.asarray(state.merge_to)[j_g, s_g])
            state = state._replace(side=jnp.asarray(side))
    state = _write_row(state, joiner, jvk, jpb, jsl, elide_redundant=True)
    # admin ops are rare host-side O(N) paths — refresh the rolling
    # digest wholesale rather than threading per-entry deltas
    return refresh_carried(state)


def admin_leave(state: DeltaState, node: int) -> DeltaState:
    """makeLeave(self) (admin-leave-handler.js:48-52)."""
    inc = view_of(state, node, node) >> 3
    state = _set_entry(state, node, node, inc * 8 + LEAVE, 0, -1)
    return refresh_carried(state)


def _wipe_row(state: DeltaState, node: int) -> DeltaState:
    cap = state.capacity
    return state._replace(
        d_subj=state.d_subj.at[node].set(jnp.full((cap,), SENTINEL, jnp.int32)),
        d_key=state.d_key.at[node].set(jnp.zeros((cap,), jnp.int32)),
        d_pb=state.d_pb.at[node].set(jnp.full((cap,), -1, jnp.int8)),
        d_sl=state.d_sl.at[node].set(jnp.full((cap,), -1, jnp.int8)),
    )


def revive(state: DeltaState, node: int, inc: int) -> DeltaState:
    """A killed process restarts fresh (the dense ``swim_sim.revive``):
    wipe its row to self-only with a new (higher) incarnation; re-entry
    is an ``admin_join``.  pb=-1: the restarted node does not record its
    own aliveness — the seed records it during the join."""
    _check_inc(inc)
    state = _wipe_row(state, node)
    state = _set_entry(state, node, node, int(inc) * 8 + ALIVE, -1, -1)
    return refresh_carried(state)


def revive_and_join(state: DeltaState, node: int, inc: int, seed: int) -> DeltaState:
    """tick-cluster 'K': restart a killed process with a fresh higher
    incarnation and immediately bootstrap it against ``seed``.

    (A revived-but-unjoined node knows *nobody* — that is N-1 entries
    of divergence, which the delta representation cannot bound; the
    reference's tick-cluster revives and rejoins in one operation
    anyway, tick-cluster.js:418-430.)"""
    return admin_join(revive(state, node, inc), node, seed)

"""SimCluster — the host-side driver of the TPU SWIM simulation.

The simulation analog of the reference's tick-cluster harness
(scripts/tick-cluster.js) and of this repo's host ``harness.Cluster``:
drive protocol periods, group live nodes by membership checksum
(tick-cluster.js:88-115 — the convergence metric), and inject faults —
kill / suspend / revive (tick-cluster.js:418-471), partitions and packet
loss (the netsplit testing the reference stubbed out in
test/lib/partition-cluster.js:59-61) — as mask edits on ``NetState``.

All protocol state lives on device; the driver only pulls rows back for
reference-format checksums and stats.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ringpop_tpu.hashring import HashRing
from ringpop_tpu.models import checksum as cksum
from ringpop_tpu.models import swim_delta as sdelta
from ringpop_tpu.models import swim_sim as sim
from ringpop_tpu.obs import bridge as obs_bridge
from ringpop_tpu.obs.ledger import default_ledger
from ringpop_tpu.ops import checksum_device as ckdev
from ringpop_tpu.models.swim_sim import NetState, SwimParams

DEFAULT_BASE_INC = 1_400_000_000_000  # host clock epoch (clock.SimScheduler)


# the predicate itself lives in swim_sim (shared with the scenario scan)
_converged_impl = jax.jit(sim.converged_impl)


@partial(jax.jit, static_argnames=("window",))
def _lookup_batch_jit(ring_hashes, ring_owners, bufs, lens, in_ring, *, window):
    """One dispatch: hash the key strings on device, then resolve each
    through the masked global ring.  The walk is windowed (a full-ring
    window would gather O(M x 100N) — gigabytes at the batch sizes this
    exists for); the host caller resolves the geometrically-rare
    ``found=False`` residue through the host ring.  ``in_ring`` is the
    single viewer's bool[N] row — broadcast to the kernel's [M, N] form
    INSIDE the jit, where XLA fuses it into the gather instead of
    materializing an M x N buffer."""
    from ringpop_tpu.ops.farmhash_jax import farmhash32_batch_jax
    from ringpop_tpu.traffic import engine as tengine

    hashes = farmhash32_batch_jax(bufs, lens)
    mask = jnp.broadcast_to(in_ring[None, :], (bufs.shape[0], in_ring.shape[0]))
    return tengine.lookup_masked_idx(
        ring_hashes, ring_owners, hashes, mask, window=window
    )


def groups_to_gid(groups: Sequence[Sequence[int]], n: int) -> np.ndarray:
    """int32[N] group-id vector (-1 = ungrouped) from member lists —
    the single gid constructor shared by ``partition``/``split_sides``
    and the scenario compiler (scenarios/compile.py)."""
    gid = np.full(n, -1, dtype=np.int32)
    for g, members in enumerate(groups):
        gid[np.asarray(list(members), dtype=np.int32)] = g
    return gid


class SimCluster:
    def __init__(
        self,
        n: int,
        params: SwimParams = SwimParams(),
        *,
        seed: int = 0,
        addresses: Sequence[str] | None = None,
        base_inc: int = DEFAULT_BASE_INC,
        inc: Sequence[int] | None = None,
        init: str = "converged",
        device: Any | None = None,
        damping: bool = False,
        backend: str = "dense",
        capacity: int = 256,
        wire_cap: int = 16,
        claim_grid: int = 64,
        stats_emitter: Any | None = None,
        stats_prefix: str = obs_bridge.DEFAULT_PREFIX,
    ):
        """``backend='dense'``: the N x N state (swim_sim.py) — every
        scenario incl. partitions and mode='self' bootstrap.
        ``backend='delta'``: the O(N * C) delta-from-base state
        (swim_delta.py) — bounded-divergence scenarios (loss/kill/
        suspend/join/leave churn) at 65k+ nodes per chip, plus group-id
        netsplits and init='self' bootstraps when ``capacity`` is sized
        for their ~n-wide transitions;
        ``capacity``/``wire_cap``/``claim_grid`` are its resource
        caps.  ``stats_emitter`` (any ``increment/gauge/timing`` sink,
        obs/emitters.py) receives every tick's protocol counters and
        every scenario trace under reference-parity statsd key names
        via the Trace→stats bridge (obs/bridge.py)."""
        if backend not in ("dense", "delta"):
            raise ValueError(f"unknown backend: {backend!r}")
        if backend == "delta" and damping:
            raise ValueError("the delta backend does not support damping tensors")
        if backend == "delta" and params.sparse_cap:
            raise ValueError(
                "sparse_cap is a dense-backend knob; the delta backend "
                "bounds messages with wire_cap"
            )
        self.backend = backend
        self.params = params
        self.dparams = sdelta.DeltaParams(
            swim=params, wire_cap=wire_cap, claim_grid=claim_grid
        )
        self.book = cksum.AddressBook(addresses or cksum.default_addresses(n))
        if len(self.book) != n:
            raise ValueError("addresses must have length n")
        self.base_inc = base_inc
        rel = np.zeros(n, dtype=np.int32) if inc is None else (
            np.asarray(inc, dtype=np.int64) - base_inc
        ).astype(np.int32)
        if backend == "delta":
            self.state: Any = sdelta.init_delta(
                n, jnp.asarray(rel), capacity=capacity, mode=init
            )
        else:
            self.state = sim.init_state(
                n, jnp.asarray(rel), mode=init, damping=damping
            )
        self.net: NetState = sim.make_net(n)
        self.key = jax.random.PRNGKey(seed)
        self.metrics_log: list[dict[str, int]] = []
        self.traces: list[Any] = []  # scenarios.Trace per run_scenario
        self.stats_sink = (
            obs_bridge.StatSink(stats_emitter, stats_prefix)
            if stats_emitter is not None
            else None
        )
        self._device_book = None  # lazy ckdev.DeviceBook (device checksums)
        self._traffic_ring = None  # lazy global DeviceRing (traffic plane)
        # streaming-soak cursor (checkpoint v5): set by checkpoint.load
        # when the checkpoint was written mid-stream (scenarios/stream.py)
        self.stream_cursor: dict[str, Any] | None = None
        if device is not None:
            self.state = jax.device_put(self.state, device)
            self.net = jax.device_put(self.net, device)

    @property
    def n(self) -> int:
        return len(self.book)

    # -- time ---------------------------------------------------------------

    def _split(self) -> jax.Array:
        self.key, sub = jax.random.split(self.key)
        return sub

    def tick(self, ticks: int = 1) -> dict[str, int]:
        """Advance every node ``ticks`` protocol periods.

        Dispatches route through the obs ledger (a call-through while
        it is disabled, the default); with a ``stats_emitter`` the
        returned counters also stream out under reference statsd keys.
        """
        led = default_ledger()
        meta = {"backend": self.backend, "n": self.n, "ticks": ticks,
                "replicas": 1}
        if self.backend == "delta":
            if ticks == 1:
                self.state, metrics = led.dispatch(
                    "delta_step", sdelta.delta_step,
                    self.state, self.net, self._split(),
                    params=self.dparams, _meta=meta,
                )
            else:
                self.state, metrics = led.dispatch(
                    "delta_run", sdelta.delta_run,
                    self.state, self.net, self._split(),
                    params=self.dparams, ticks=ticks, _meta=meta,
                )
        elif ticks == 1:
            self.state, metrics = led.dispatch(
                "swim_step", sim.swim_step,
                self.state, self.net, self._split(),
                params=self.params, _meta=meta,
            )
        else:
            self.state, metrics = led.dispatch(
                "swim_run", sim.swim_run,
                self.state, self.net, self._split(),
                params=self.params, ticks=ticks, _meta=meta,
            )
        out = {k: int(v) for k, v in metrics.items()}
        # multi-tick entries report only the LAST tick's counters (the
        # scan discards the rest); record how many ticks the entry
        # spans so the log is unambiguous (use run_scenario for a full
        # per-tick time series)
        out["ticks"] = int(ticks)
        self.metrics_log.append(out)
        if self.stats_sink is not None:
            obs_bridge.emit_counters(
                out, self.stats_sink, live=len(self.live_indices())
            )
        return out

    def run_scenario(
        self,
        spec,
        traffic: Any | None = None,
        *,
        segment_ticks: int | None = None,
        store: str | None = None,
        checkpoint_path: str | None = None,
        checkpoint_every: int = 1,
        assemble: bool = True,
        pipeline: bool = True,
        policy: Any | None = None,
        param_knobs: dict[str, float | int] | None = None,
    ) -> Any:
        """Run a declarative fault timeline as ONE jitted call.

        ``spec`` is a ``scenarios.ScenarioSpec`` (or its dict form, or
        a path to its JSON file): kill/revive/suspend/resume at tick
        t, group-partitions and heals, stepwise loss schedules — all
        compiled to device-resident event tensors applied inside the
        scan (scenarios/), with per-tick telemetry stacked into the
        returned ``Trace`` (also appended to ``self.traces`` and
        checkpointed).  The PRNG key schedule is segment-exact, so the
        trajectory is bit-identical to the equivalent host sequence of
        ``kill()``/``partition()``/``tick()`` calls — minus the
        per-fault dispatch round-trips.

        ``traffic`` (a ``traffic.WorkloadSpec``, its dict/JSON-path/
        shorthand form, or a pre-lowered ``CompiledTraffic``) co-runs a
        batched key workload inside the same compiled program: every
        tick's keys are served through per-viewer device rings derived
        from that tick's views, adding lookup/forward/misroute counters
        to the trace.  The workload PRNG is its own stream — the
        protocol trajectory stays bit-identical to a traffic-free run.

        ``segment_ticks=S`` streams the run instead (scenarios/
        stream.py): ceil(ticks / S) pipelined dispatches of one
        compiled S-tick segment, telemetry draining per segment into
        ``store`` / the stats bridge, and a v5 checkpoint every
        ``checkpoint_every`` segments when ``checkpoint_path`` is
        given — bit-identical trajectory and trace to the unsegmented
        call, but host trace memory is O(segment) (``assemble=False``
        returns the ``SegmentStore`` instead of a whole-run ``Trace``)
        and a killed soak resumes via ``scenarios.stream.resume``.

        ``policy`` arms a remediation policy (``ringpop_tpu.policies``):
        a name string (``"admission"``, optionally with ``:knob=v``
        overrides), a cursor dict, or a pre-compiled ``CompiledPolicy``.
        Requires ``traffic``; the policy's per-tick fold rides the same
        scan carry as the overload feedback loop, and its final state
        persists on ``self.net.po_*`` (``clear_policy()`` drops it).

        ``param_knobs`` overrides traced PROTOCOL knobs for this run
        (``{"suspicion_ticks": 9, "piggyback_factor": 2, ...}`` — the
        ``sim.SwimKnobs`` names): same compiled program as the
        defaults, different scalar operands, so a knob change never
        recompiles.  Values are host-validated against the backend and
        scenario (``runner.validate_param_knobs``).  Not available
        streamed (``segment_ticks``).
        """
        from ringpop_tpu.scenarios import compile as scompile
        from ringpop_tpu.scenarios import runner as srunner
        from ringpop_tpu.scenarios.spec import ScenarioSpec
        from ringpop_tpu.scenarios.trace import Trace

        if segment_ticks is not None:
            if param_knobs is not None:
                raise ValueError(
                    "param_knobs is not wired through the streamed "
                    "runner yet; run unsegmented (drop segment_ticks)"
                )
            from ringpop_tpu.scenarios import stream as sstream

            return sstream.run_streamed(
                self,
                spec,
                segment_ticks=segment_ticks,
                traffic=traffic,
                store=store,
                checkpoint_path=checkpoint_path,
                checkpoint_every=checkpoint_every,
                assemble=assemble,
                pipeline=pipeline,
                policy=policy,
            )
        if store is not None or checkpoint_path is not None or not assemble:
            raise ValueError(
                "store/checkpoint_path/assemble are streaming options; "
                "pass segment_ticks to stream the run"
            )
        if isinstance(spec, str):
            spec = ScenarioSpec.load(spec)
        elif isinstance(spec, dict):
            spec = ScenarioSpec.from_dict(spec)
        spec.validate(self.n)
        if traffic is not None:
            traffic = self.compile_traffic(traffic)
        compiled = scompile.compile_spec(
            spec, self.n, base_loss=self.params.loss
        )
        params = self.dparams if self.backend == "delta" else self.params
        # static rejections BEFORE drawing keys: a failed call must not
        # advance self.key (it would silently desynchronize reruns);
        # precheck also hands back the normalized adjacency so the
        # mask-form host sync runs once per run, not again per dispatch
        adj = srunner.precheck(self.state, self.net, compiled, params)
        srunner.precheck_overload(compiled, traffic, self.net)
        if policy is not None and traffic is not None:
            from ringpop_tpu.policies import core as pol

            policy = pol.compile_policy(
                policy, n=self.n, m=traffic.static.m
            )
        srunner.precheck_policy(policy, traffic, self.net)
        srunner.precheck_prov(compiled, self.net, params)
        if param_knobs is not None:
            # knob validation is a static rejection too: it must fire
            # before the key draw (same no-desync contract as precheck)
            srunner.validate_param_knobs(
                self.n,
                params.swim if self.backend == "delta" else params,
                {k: [v] for k, v in param_knobs.items()},
                backend=self.backend,
                period_active=(self.net.period is not None
                               or compiled.has_gray
                               or compiled.overload is not None),
                damping=getattr(self.state, "damp", None) is not None,
            )
        keys = scompile.key_schedule(self._split, compiled)
        start_tick = int(self.state.tick)
        self.state, self.net, ys = srunner.run_compiled(
            self.state, self.net, keys, compiled, params, traffic=traffic,
            adj=adj, policy=policy, param_knobs=param_knobs,
        )
        self.set_loss(float(compiled.loss[-1]))  # host mirror of the schedule
        stacks = {k: np.asarray(v) for k, v in ys.items()}
        spec_dict = spec.to_dict()
        if traffic is not None:
            # provenance rides along in the trace (ScenarioSpec.from_dict
            # ignores unknown keys, so the npz round trip is unaffected)
            spec_dict["traffic"] = traffic.spec.to_dict()
        if policy is not None:
            from ringpop_tpu.policies import core as pol

            spec_dict["policy"] = pol.to_dict(policy)
        trace = Trace(
            metrics={
                k: v
                for k, v in stacks.items()
                if k not in ("converged", "live", "loss") and v.ndim == 1
            },
            # vector outputs (the [ticks, B] latency histogram rows the
            # SLO plane stacks) ride as planes, not scalar metrics
            planes={k: v for k, v in stacks.items() if v.ndim == 2},
            converged=stacks["converged"],
            live=stacks["live"],
            loss=stacks["loss"],
            n=self.n,
            backend=self.backend,
            start_tick=start_tick,
            spec=spec_dict,
        ).validate()
        self.traces.append(trace)
        entry = {k: int(v[-1]) for k, v in trace.metrics.items()}
        entry["ticks"] = spec.ticks
        self.metrics_log.append(entry)
        if self.stats_sink is not None:
            # replay the whole per-tick series under reference statsd
            # keys, closing with the post-run membership checksum gauge
            # (one live row through the host kernel — cheap)
            live = self.live_indices()
            checksum = None
            if live.size:
                checksum = self.checksums(indices=[int(live[0])])[
                    self.book.addresses[int(live[0])]
                ]
            obs_bridge.replay_trace(
                trace,
                self.stats_sink.emitter,
                prefix=self.stats_sink.prefix,
                checksum=checksum,
            )
        return trace

    def run_sweep(
        self,
        spec,
        replicas: int,
        *,
        loss_scales: Sequence[float] | None = None,
        kill_jitter: Sequence[int] | None = None,
        flap_jitter: Sequence[int] | None = None,
        traffic: Any | None = None,
        shard: bool = False,
        segment_ticks: int | None = None,
        store: str | None = None,
        assemble: bool = True,
        pipeline: bool = True,
        policy: Any | None = None,
        policy_axes: dict[str, Any] | None = None,
        param_axes: dict[str, Any] | None = None,
        program_tag: str | None = None,
    ) -> Any:
        """Run R replicas of a scenario as ONE vmapped jitted call.

        Each replica starts from a fresh broadcast copy of the current
        state and draws its own replica key from the cluster key, so
        replica r is bit-identical to a standalone ``run_scenario``
        from that key (``scenarios/sweep.py`` docstring; the optional
        per-replica ``loss_scales``/``kill_jitter`` vary the scenario
        within one compiled program; ``shard=True`` splits the replica
        axis across the local devices).  Returns a ``SweepTrace`` with
        [R, ticks] telemetry stacks plus the final per-replica states
        attached in memory (``final_states``/``final_nets``).

        Unlike ``run_scenario``, the cluster itself does NOT advance:
        the sweep is a statistical measurement fan-out, not the
        cluster's own trajectory — only the cluster key moves (R
        draws), and nothing is appended to ``metrics_log``/``traces``
        (checkpoints round-trip ``Trace`` objects only).

        ``traffic`` (a ``traffic.WorkloadSpec`` or its dict/JSON/
        shorthand/pre-lowered form) co-runs the key workload in every
        replica — one shared workload stream, so replica r's serving
        counters are exactly a standalone ``run_scenario(spec_r,
        traffic=...)``'s, and the SweepTrace answers per-replica
        serving questions in one dispatch
        (``SweepTrace.serving_summary``).

        ``segment_ticks=S`` streams the sweep (scenarios/stream.py):
        [R, S] telemetry slabs drain per pipelined segment dispatch
        into ``store`` — host sweep telemetry O(R x segment) — with
        every replica still bit-identical to the whole-horizon call,
        and composes with ``shard=True`` (the carry stays sharded
        across segments; bit-identical to the unsegmented sharded
        sweep).  ``flap_jitter`` shifts replica r's flap windows by
        ``flap_jitter[r]`` ticks (per-replica storm phases in one
        compiled program).

        ``policy`` arms a remediation policy in every replica, and
        ``policy_axes`` sweeps its knobs: ``{"admit_capacity": [2, 4,
        8, 16]}`` gives replica r the r-th value — knobs are traced
        batch axes, so the whole knob grid shares one compiled program,
        and replica r stays bit-identical to a standalone
        ``run_scenario(policy=sweep.replica_policy(...))``.

        ``param_axes`` sweeps traced PROTOCOL knobs the same way:
        ``{"suspicion_ticks": [3, 6, 9, 12]}`` gives replica r the r-th
        value (``sim.SwimKnobs`` names — suspicion timeout, piggyback
        factor, ping-req fanout, phase_mod, relay_full_sync, damp
        thresholds), one compiled program for the whole knob grid, and
        replica r bit-identical to a standalone ``run_scenario(
        param_knobs=sweep.replica_param_knobs(param_axes, r))``.
        Composes with every other axis (and ``policy_axes``) in the
        same dispatch.  ``program_tag`` names this dispatch's ledger
        program ``run_sweep:<tag>`` so a multi-arm tuner's shape-
        distinct arms don't read as recompiles of one another.
        """
        from ringpop_tpu.scenarios import runner as srunner
        from ringpop_tpu.scenarios import sweep as ssweep
        from ringpop_tpu.scenarios.spec import ScenarioSpec

        if segment_ticks is not None:
            if param_axes:
                raise ValueError(
                    "param_axes is not wired through the streamed "
                    "sweep yet; run unsegmented (drop segment_ticks)"
                )
            from ringpop_tpu.scenarios import stream as sstream

            return sstream.run_sweep_streamed(
                self,
                spec,
                replicas,
                segment_ticks=segment_ticks,
                loss_scales=loss_scales,
                kill_jitter=kill_jitter,
                flap_jitter=flap_jitter,
                traffic=traffic,
                store=store,
                assemble=assemble,
                pipeline=pipeline,
                shard=shard,
                policy=policy,
                policy_axes=policy_axes,
            )
        if store is not None or not assemble:
            raise ValueError(
                "store/assemble are streaming options; pass segment_ticks "
                "to stream the sweep"
            )
        if isinstance(spec, str):
            spec = ScenarioSpec.load(spec)
        elif isinstance(spec, dict):
            spec = ScenarioSpec.from_dict(spec)
        spec.validate(self.n)
        if traffic is not None:
            traffic = self.compile_traffic(traffic)
        cs = ssweep.compile_sweep(
            spec,
            self.n,
            replicas=replicas,
            base_loss=self.params.loss,
            loss_scales=loss_scales,
            kill_jitter=kill_jitter,
            flap_jitter=flap_jitter,
        )
        params = self.dparams if self.backend == "delta" else self.params
        # static rejections BEFORE drawing keys (run_scenario contract)
        srunner.precheck(self.state, self.net, cs.base, params)
        srunner.precheck_overload(cs.base, traffic, self.net)
        if policy is not None and traffic is not None:
            from ringpop_tpu.policies import core as pol

            policy = pol.compile_policy(
                policy, n=self.n, m=traffic.static.m
            )
        srunner.precheck_policy(policy, traffic, self.net)
        srunner.precheck_prov(cs.base, self.net, params)
        if shard:
            ssweep.precheck_shard(replicas)
        if param_axes:
            # static rejection before the R key draws (no-desync
            # contract): shape + range + composition checks; the device
            # arrays this builds are rebuilt inside run_sweep_compiled
            ssweep.param_knob_axes(
                params, param_axes, replicas, n=self.n,
                backend=self.backend,
                period_active=(self.net.period is not None
                               or cs.base.has_gray
                               or cs.base.overload is not None),
                damping=getattr(self.state, "damp", None) is not None,
            )
        replica_keys = [self._split() for _ in range(replicas)]
        keys = ssweep.sweep_key_schedule(replica_keys, cs)
        states, nets, ys = ssweep.run_sweep_compiled(
            self.state, self.net, keys, cs, params, shard=shard,
            traffic=traffic, policy=policy, policy_axes=policy_axes,
            param_axes=param_axes, program_tag=program_tag,
        )
        stacks = {k: np.asarray(v) for k, v in ys.items()}
        trace = ssweep.SweepTrace(
            metrics={
                k: v
                for k, v in stacks.items()
                if k not in ("converged", "live", "loss") and v.ndim == 2
            },
            planes={k: v for k, v in stacks.items() if v.ndim == 3},
            converged=stacks["converged"],
            live=stacks["live"],
            loss=stacks["loss"],
            n=self.n,
            backend=self.backend,
            replica_keys=np.stack([np.asarray(k) for k in replica_keys]),
            loss_scales=cs.loss_scales,
            kill_jitter=cs.kill_jitter,
            flap_jitter=cs.flap_jitter,
            start_tick=int(self.state.tick),
            spec=spec.to_dict(),
        ).validate()
        trace.final_states = states
        trace.final_nets = nets
        return trace

    def run_until_converged(self, max_ticks: int = 1000, check_every: int = 5) -> int:
        """Ticks until convergence (or -1); the tick-cluster 't' loop."""
        done = 0
        while done < max_ticks:
            step = min(check_every, max_ticks - done)
            self.tick(step)
            done += step
            if self.converged():
                return done
        return -1

    # -- convergence (tick-cluster.js:88-115) --------------------------------

    def _own_keys(self) -> np.ndarray:
        """int32[N]: each node's view of itself (the gossip gate)."""
        if self.backend == "delta":
            ids = jnp.arange(self.n, dtype=jnp.int32)
            return np.asarray(sdelta.view_lookup(self.state, ids))
        return np.asarray(jnp.diagonal(self.state.view_key))

    def _view_rows(self, idx: np.ndarray) -> np.ndarray:
        """int32[len(idx), N] materialized view rows (host copies)."""
        if self.backend == "delta":
            return np.asarray(
                sdelta.materialize_rows(self.state, jnp.asarray(idx))
            )
        return np.asarray(self.state.view_key[jnp.asarray(idx)])

    def live_indices(self) -> np.ndarray:
        up = np.asarray(self.net.up) & np.asarray(self.net.responsive)
        # Diagonal first, then unpack: the view_status property would
        # materialize the full N x N unpacked tensor.
        own = self._own_keys() & 7
        gossiping = up & ((own == sim.ALIVE) | (own == sim.SUSPECT))
        return np.flatnonzero(gossiping)

    def converged(self) -> bool:
        """Exact view agreement among live nodes (stronger than checksum
        equality — no hash involved).  Fixed-shape masked compare on
        device: a gather by the (variable-length) live set would force an
        XLA recompile every time the live count changes."""
        if self.backend == "delta":
            return bool(
                sdelta._converged_impl(
                    self.state, self.net.up, self.net.responsive
                )
            )
        return bool(_converged_impl(self.state, self.net))

    def checksums(
        self,
        indices: Sequence[int] | None = None,
        backend: str = "host",
    ) -> dict[str, int]:
        """Reference-format membership checksum per (live) node address.

        ``backend='host'``: threaded C kernel over pulled rows (default).
        ``backend='device'``: string assembly + farmhash entirely on
        device (ops/checksum_device.py) — only the uint32 results leave
        HBM; the right choice for whole-cluster sweeps at large N.
        """
        idx = self.live_indices() if indices is None else np.asarray(indices)
        if backend == "device":
            if self._device_book is None:
                self._device_book = ckdev.DeviceBook(
                    self.book.addresses, self.base_inc
                )
            if self.backend == "delta":
                rows = sdelta.materialize_rows(self.state, jnp.asarray(idx))
            else:
                rows = self.state.view_key[jnp.asarray(idx)]
            sums = np.asarray(ckdev.view_checksums_device(self._device_book, rows))
            return {self.book.addresses[i]: int(c) for i, c in zip(idx, sums)}
        # Pull only the requested rows, unpacking on host (row-sized work;
        # the view_status/view_inc properties would unpack all N x N).
        keys = self._view_rows(idx)
        sums = cksum.view_checksums_packed(self.book, keys, self.base_inc)
        return {self.book.addresses[i]: int(c) for i, c in zip(idx, sums)}

    def checksum_groups(self) -> dict[int, list[str]]:
        groups: dict[int, list[str]] = {}
        for addr, c in self.checksums().items():
            groups.setdefault(c, []).append(addr)
        return groups

    def members(self, viewer: int) -> list[dict]:
        """The viewer's member list, reference getStats shape."""
        row = self._view_rows(np.asarray([viewer]))[0]
        return cksum.row_members(self.book, row & 7, row >> 3, self.base_inc)

    # -- lookup (ring derived from a node's view, lib/ring.js) ---------------

    def ring_for(self, viewer: int) -> HashRing:
        ring = HashRing()
        # alive members are added and faulty/leave removed; suspects stay
        # in the ring (membership-update-listener.js:34-45); damped
        # members are quarantined from the ring (damping extension)
        damped_row = (
            np.asarray(self.state.damped[viewer])
            if getattr(self.state, "damped", None) is not None
            else None
        )
        servers = [
            m["address"]
            for m in self.members(viewer)
            if m["status"] in ("alive", "suspect")
            and (damped_row is None or not damped_row[self.book.index[m["address"]]])
        ]
        ring.add_remove_servers(servers, [])
        return ring

    def damped_pairs(self) -> int:
        """Total (viewer, subject) damped entries (damping extension)."""
        if getattr(self.state, "damped", None) is None:
            return 0
        return int(jnp.sum(self.state.damped))

    def lookup(self, key: str, viewer: int = 0) -> str | None:
        return self.ring_for(viewer).lookup(key)

    # -- batched device lookups (traffic plane, ops/ring_ops.py) -------------

    def traffic_ring(self):
        """The cluster's GLOBAL device ring — every address's replica
        points, sorted; per-viewer rings are masks over it (the traffic
        engine's representation).  The address book is immutable, so
        this is built once and cached."""
        if self._traffic_ring is None:
            from ringpop_tpu.ops import ring_ops

            self._traffic_ring = ring_ops.build_ring(self.book.addresses)
        return self._traffic_ring

    def compile_traffic(self, spec: Any) -> Any:
        """Lower a ``traffic.WorkloadSpec`` (or its dict/JSON/shorthand
        form) against this cluster's address book, reusing the cached
        global ring.  A pre-lowered ``CompiledTraffic`` passes through
        only if it was lowered against a cluster of the same size —
        foreign viewer indices and ring tables would otherwise clamp
        silently inside jitted gathers and report bogus counters."""
        from ringpop_tpu.traffic import workloads as tworkloads

        if isinstance(spec, tworkloads.CompiledTraffic):
            if spec.n != self.n:
                raise ValueError(
                    f"CompiledTraffic was lowered for n={spec.n}, "
                    f"this cluster has n={self.n}; re-compile the spec"
                )
            return spec
        spec = tworkloads.WorkloadSpec.from_spec(spec)
        if spec.latency_buckets:
            # the SLO plane's tick->ms conversion (link delays, the
            # RETRY_SCHEDULE backoff tick offsets) is THIS cluster's
            # protocol period — a workload lowered against a cluster
            # must not keep the spec default (a pre-lowered
            # CompiledTraffic above keeps whatever it was built with)
            spec = spec._replace(period_ms=self.params.period_ms)
        return tworkloads.compile_traffic(
            spec, self.n, self.book.addresses, ring=self.traffic_ring()
        )

    def lookup_batch(
        self, keys: Sequence[str], viewer: int = 0
    ) -> list[str | None]:
        """Resolve a whole batch of keys through ``viewer``'s ring in
        ONE device dispatch — the batched replacement for looping
        ``lookup()`` one key at a time: keys are hashed on device
        (farmhash kernel) and resolved by a masked walk of the cached
        global ring, bit-identical to ``ring_for(viewer).lookup``
        (tests/test_traffic.py pins it) — including the empty-ring case,
        which yields ``None`` per key like the host path.  The walk is
        windowed (memory-bounded at any batch size); keys it cannot
        settle — geometrically rare unless the viewer's ring is nearly
        empty — fall back to the host ring."""
        from ringpop_tpu.ops import ring_ops
        from ringpop_tpu.traffic import engine as tengine
        from ringpop_tpu.traffic.workloads import DEFAULT_WINDOW

        keys = list(keys)
        if not keys:
            return []
        ring = self.traffic_ring()
        row = jnp.asarray(self._view_rows(np.asarray([viewer]))[0])
        in_ring = tengine.in_ring_from_rows(row)
        if getattr(self.state, "damped", None) is not None:
            # damped members are quarantined from the ring (ring_for)
            in_ring = in_ring & ~self.state.damped[viewer]
        bufs, lens = ring_ops.encode_strings(keys)
        owners, found = _lookup_batch_jit(
            ring.hashes,
            ring.owners,
            jnp.asarray(bufs),
            jnp.asarray(lens),
            in_ring,
            window=min(ring.size, DEFAULT_WINDOW),
        )
        owners = np.asarray(owners)
        found = np.asarray(found)
        out: list[str | None] = [
            self.book.addresses[int(o)] if ok else None
            for o, ok in zip(owners, found)
        ]
        if not found.all():
            host_ring = self.ring_for(viewer)
            for i in np.flatnonzero(~found):
                out[i] = host_ring.lookup(keys[i])
        return out

    # -- fault injection (tick-cluster.js:418-471; partitions via masks) -----

    def kill(self, i: int) -> None:
        self.net = self.net._replace(up=self.net.up.at[i].set(False))

    def suspend(self, i: int) -> None:
        self.net = self.net._replace(responsive=self.net.responsive.at[i].set(False))

    def resume(self, i: int) -> None:
        self.net = self.net._replace(responsive=self.net.responsive.at[i].set(True))

    def revive(self, i: int, inc: int | None = None, seed: int | None = None) -> None:
        """Restart a killed node as a fresh process and re-join it
        (tick-cluster.js:418-430 -> admin-join-handler.js:47-51)."""
        if inc is None:
            # max(view_key) >> 3 == max(view_inc): the key is monotone in
            # inc (status occupies only the low 3 bits).
            if self.backend == "delta":
                inc = int(
                    max(jnp.max(self.state.base_key), jnp.max(self.state.d_key))
                    >> 3
                ) + 1000
            else:
                inc = int(jnp.max(self.state.view_key) >> 3) + 1000
        else:
            inc = inc - self.base_inc
        if self.backend == "delta":
            self.state = sdelta.revive(self.state, i, inc)
        else:
            self.state = sim.revive(self.state, i, inc)
        self.net = self.net._replace(
            up=self.net.up.at[i].set(True),
            responsive=self.net.responsive.at[i].set(True),
        )
        if seed is None:
            live = [j for j in self.live_indices() if j != i]
            if not live:
                return
            seed = int(live[0])
        self.join(i, seed)

    def join(self, joiner: int, seed: int) -> None:
        if self.backend == "delta":
            self.state = sdelta.admin_join(self.state, joiner, seed)
        else:
            self.state = sim.admin_join(self.state, joiner, seed)

    def leave(self, i: int) -> None:
        if self.backend == "delta":
            self.state = sdelta.admin_leave(self.state, i)
        else:
            self.state = sim.admin_leave(self.state, i)

    def partition(self, groups: Sequence[Sequence[int]]) -> None:
        """Disconnect the given groups from each other (block adjacency).

        Full-coverage partitions (every node in some group) take the
        int32[N] group-id form — O(N) memory, the only form the delta
        backend accepts (its step evaluates connectivity at gathered
        index pairs; a bool[N, N] mask would reintroduce the N^2 it
        exists to avoid).  Partial groupings (ungrouped nodes stay
        connected to everyone) need the dense mask form.  Layout
        continuity: once this net carries a bool[N, N] mask (a previous
        partial partition on the dense backend), later full-coverage
        partitions keep the mask form — a step compiled against the
        mask layout (sharded_step's in_shardings, or any traced jit)
        must never see the adj flip to a different ndim mid-run."""
        gid = groups_to_gid(groups, self.n)
        keep_mask = self.net.adj is not None and self.net.adj.ndim == 2
        if (gid >= 0).all() and not keep_mask:
            self.net = self.net._replace(adj=jnp.asarray(gid))
            return
        if self.backend == "delta":
            raise NotImplementedError(
                "delta-backend partitions must cover every node (group-id "
                "adjacency); partial groupings need the dense mask form"
            )
        same = (gid[:, None] == gid[None, :]) | (gid[:, None] < 0) | (gid[None, :] < 0)
        self.net = self.net._replace(adj=jnp.asarray(same))

    def heal_partition(self) -> None:
        # Keep the pytree structure stable: a net that has carried an
        # adjacency mask heals to an all-ones mask, a group-id vector to
        # all-one-group (a compiled sharded_step's in_shardings would
        # otherwise mismatch on adj array -> None); a never-partitioned
        # net stays adj=None.
        if self.net.adj is None:
            return
        if self.net.adj.ndim == 1:
            self.net = self.net._replace(adj=jnp.zeros((self.n,), jnp.int32))
        else:
            self.net = self.net._replace(
                adj=jnp.ones((self.n, self.n), dtype=bool)
            )

    def set_loss(self, p: float) -> None:
        self.params = self.params._replace(loss=float(p))
        self.dparams = self.dparams._replace(swim=self.params)

    # -- failure model (scenarios/faults.py: asymmetric links, latency,
    # gray periods — the host surface the scenario host-loop oracle
    # drives and operators script directly) ---------------------------------

    def set_link_rules(
        self,
        src,
        dst,
        p,
        d=None,
        j=None,
    ) -> None:
        """Install K directed link rules: messages from a node with
        ``src[k]`` to a node with ``dst[k]`` drop with extra
        probability ``p[k]`` (composing over rules) and are delayed
        ``d[k] + U{0..j[k]}`` ticks (dense backend, needs
        ``enable_delay`` first).  ``src``/``dst`` are bool[K, N];
        ``None`` d/j install loss-only rules.  Asymmetry is the point:
        a rule severs src->dst while dst->src flows freely."""
        src = jnp.asarray(src, dtype=bool)
        dst = jnp.asarray(dst, dtype=bool)
        p = jnp.asarray(p, dtype=jnp.float32)
        if src.ndim != 2 or src.shape != dst.shape or p.shape != src.shape[:1]:
            raise ValueError(
                "link rules need src/dst bool[K, N] and p float[K] "
                f"(got {src.shape}, {dst.shape}, {p.shape})"
            )
        if src.shape[1] != self.n:
            raise ValueError(f"link rule masks are not n={self.n} wide")
        kw = {}
        if d is not None or j is not None:
            d = np.zeros(src.shape[0], np.int32) if d is None else np.asarray(d)
            j = np.zeros(src.shape[0], np.int32) if j is None else np.asarray(j)
            if self.backend == "delta":
                depth = self.state.delay_depth
            else:
                depth = (
                    0
                    if self.state.pending is None
                    else self.state.pending.shape[0]
                )
            if int(d.max(initial=0) + j.max(initial=0)) >= max(depth, 1):
                raise ValueError(
                    f"delay rules need enable_delay(depth > max(d + j)) "
                    f"first (depth={depth})"
                )
            kw = {
                "link_d": jnp.asarray(d, jnp.int32),
                "link_j": jnp.asarray(j, jnp.int32),
            }
        else:
            kw = {"link_d": None, "link_j": None}
        self.net = self.net._replace(
            link_src=src, link_dst=dst, link_p=p, **kw
        )

    def clear_link_rules(self) -> None:
        self.net = self.net._replace(
            link_src=None, link_dst=None, link_p=None, link_d=None, link_j=None
        )

    def clear_overload(self) -> None:
        """Drop overload feedback state a finished ``overload`` run
        left on the net (``NetState.ov_cnt``/``ov_gray``) — required
        before a FRESH overload scenario on the same cluster (the
        pressure would otherwise silently seed the new run; resume
        keeps it on purpose)."""
        self.net = self.net._replace(ov_cnt=None, ov_gray=None)

    def clear_policy(self) -> None:
        """Drop remediation policy state a finished ``policy=`` run
        left on the net (``NetState.po_*``) — required before a FRESH
        policy-armed run on the same cluster (leftover pressure /
        hysteresis flags / amp windows would silently seed the new
        run's meters; resume keeps them on purpose)."""
        self.net = self.net._replace(
            po_press=None, po_shed=None, po_quar=None,
            po_sends_w=None, po_deliv_w=None, po_retry_cap=None,
        )

    def clear_provenance(self) -> None:
        """Drop tracked-rumor state a finished ``trace_rumors`` run left
        on the net (``NetState.pv_*``) — required before a FRESH traced
        run on the same cluster (armed slots would otherwise silently
        extend the old wavefronts; resume keeps them on purpose)."""
        self.net = self.net._replace(
            pv_slot=None, pv_tickv=None, pv_wits=None,
            pv_first=None, pv_parent=None, pv_knows=None,
        )

    def provenance_report(self) -> dict:
        """The host-side provenance report from the last traced run's
        planes on the net: per tracked rumor, the propagation tree
        (first_heard/parent), the detection-causality chain, and the
        infection-time stats vs the paper's log2(N) bound
        (``obs.provenance.build_report``)."""
        from ringpop_tpu.obs import provenance as pvn

        if self.net.pv_slot is None:
            raise ValueError(
                "no provenance state on the net: run a scenario with "
                "trace_rumors > 0 first"
            )
        return pvn.build_report(
            self.net.pv_slot, self.net.pv_tickv, self.net.pv_wits,
            self.net.pv_first, self.net.pv_parent, self.net.pv_knows,
            self.n,
        )

    def set_period(self, period) -> None:
        """Per-node protocol periods (int[N]; the gray-failure model):
        node i initiates probes every ``period[i]``-th tick but answers
        pings and witness duties every tick.  ``None`` restores
        lockstep.  Subsumes ``SwimParams.phase_mod`` (a row of P is
        phase_mod=P, both backends)."""
        if period is None:
            self.net = self.net._replace(period=None)
            return
        period = jnp.asarray(period, dtype=jnp.int32)
        if period.shape != (self.n,):
            raise ValueError(f"period must be int[{self.n}]")
        if self.params.phase_mod > 1:
            raise ValueError(
                "per-node periods do not compose with phase_mod > 1 "
                "(a period row of P subsumes it)"
            )
        self.net = self.net._replace(period=period)

    def enable_delay(self, depth: int) -> None:
        """Install the in-flight claim buffer so per-link delay rules
        can defer claims up to ``depth - 1`` ticks: the dense backend's
        ``[D, N, N]`` claim matrix, or the delta backend's O(N)-in-
        cluster-size claim lanes (``swim_delta.install_pending``).
        Must run before the first delayed tick: the buffer's presence
        widens the per-tick PRNG split, so the compiled-scan and
        host-loop sides both install it at run start
        (scenarios/faults.py HostPlan / runner.prepare_faults)."""
        if self.backend == "delta":
            self.state = sdelta.install_pending(
                self.state, depth, self.dparams.wire_cap
            )
            return
        if depth < 2:
            raise ValueError(f"delay depth must be >= 2 (got {depth})")
        if self.state.pending is not None:
            if self.state.pending.shape[0] != depth:
                raise ValueError(
                    f"an in-flight buffer of depth "
                    f"{self.state.pending.shape[0]} is already installed"
                )
            return
        self.state = self.state._replace(
            pending=jnp.zeros((depth, self.n, self.n), jnp.int32)
        )

    # -- delta maintenance (no-ops on the dense backend) ---------------------

    def compact(self) -> None:
        """Drop delta slots healed back to the base (swim_delta.compact)."""
        if self.backend == "delta":
            self.state = sdelta.compact(self.state)

    def rebase(self, anti_entropy: bool = False) -> None:
        """Fold majority divergence into the base (swim_delta.rebase;
        per-side in sided mode; anti_entropy=True applies the bulk
        full-sync fold — see _fold_group)."""
        if self.backend == "delta":
            self.state = sdelta.rebase(self.state, anti_entropy=anti_entropy)

    def split_sides(self, groups: Sequence[Sequence[int]]) -> None:
        """Enter the delta backend's sided mode for a block netsplit
        (swim_delta.make_sides) AND partition the network to match.
        Keeps a 50/50 split at O(N * C): each side's consensus folds
        into its own base row via the periodic ``rebase``."""
        if self.backend != "delta":
            raise ValueError("split_sides is a delta-backend operation")
        gid = groups_to_gid(groups, self.n)
        if (gid < 0).any():
            raise ValueError("split_sides groups must cover every node")
        self.state = sdelta.make_sides(self.state, gid)
        self.net = self.net._replace(adj=jnp.asarray(gid))

    def fold_sides(self) -> None:
        """Leave sided mode after the remerge converges
        (swim_delta.fold_to_single); rebase first to drain residue."""
        if self.backend == "delta" and self.state.side is not None:
            self.state = sdelta.fold_to_single(self.state)

    # -- stats ---------------------------------------------------------------

    def status_counts(self, viewer: int) -> dict[str, int]:
        vs = self._view_rows(np.asarray([viewer]))[0] & 7
        return {
            name: int((vs == code).sum()) for code, name in sim.STATUS_NAMES.items()
        }

"""Member value record and status enum (reference: lib/member.js)."""

from __future__ import annotations

from typing import Any


class Status:
    alive = "alive"
    faulty = "faulty"
    leave = "leave"
    suspect = "suspect"

    ALL = (alive, faulty, leave, suspect)


class Member:
    __slots__ = ("address", "status", "incarnation_number")

    def __init__(self, address: str, status: str, incarnation_number: int):
        self.address = address
        self.status = status
        self.incarnation_number = incarnation_number

    def to_change(self) -> dict[str, Any]:
        return {
            "address": self.address,
            "status": self.status,
            "incarnationNumber": self.incarnation_number,
        }

    def __repr__(self) -> str:  # pragma: no cover
        return f"Member({self.address!r}, {self.status!r}, {self.incarnation_number})"

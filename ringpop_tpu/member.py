"""Member value record and status enum (reference: lib/member.js)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any


class Status:
    alive = "alive"
    faulty = "faulty"
    leave = "leave"
    suspect = "suspect"

    ALL = (alive, faulty, leave, suspect)


@dataclass(frozen=True)
class MemberUpdate:
    """Shape of a disseminated membership change (reference:
    lib/member-update.js — a documentation-value record there too; the
    wire shape is the dict produced by ``Member.to_change`` plus the
    provenance fields stamped in membership.make_update,
    dissemination.js:169-176)."""

    id: str | None = None
    source: str | None = None
    source_incarnation_number: int | None = None
    address: str | None = None
    status: str | None = None
    incarnation_number: int | None = None
    timestamp: float | None = None


class Member:
    __slots__ = ("address", "status", "incarnation_number")

    def __init__(self, address: str, status: str, incarnation_number: int):
        self.address = address
        self.status = status
        self.incarnation_number = incarnation_number

    def to_change(self) -> dict[str, Any]:
        return {
            "address": self.address,
            "status": self.status,
            "incarnationNumber": self.incarnation_number,
        }

    def __repr__(self) -> str:  # pragma: no cover
        return f"Member({self.address!r}, {self.status!r}, {self.incarnation_number})"

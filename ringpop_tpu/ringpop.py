"""The RingPop facade: full API parity with the reference's index.js.

Wires Membership + Dissemination + HashRing + SWIM engine + RequestProxy
behind one object (index.js:57-154), exposing bootstrap, lookup/lookupN,
handleOrProxy(All), proxyReq, getStats, whoami, admin ops and events.

Time, randomness and transport are injected (``clock``, ``rng``,
``channel``) so the same code runs deterministically under the in-process
harness and in real asyncio/TCP deployments — and so the TPU simulation
backend (models/swim_sim.py) can be validated against it.
"""

from __future__ import annotations

import collections
import os
import random
from typing import Any, Callable

from ringpop_tpu import errors
from ringpop_tpu.clock import SimScheduler
from ringpop_tpu.dissemination import Dissemination
from ringpop_tpu.gossip import Gossip
from ringpop_tpu.hashring import HashRing
from ringpop_tpu.iterator import MembershipIterator
from ringpop_tpu.listeners import (
    create_event_forwarder,
    create_membership_set_listener,
    create_membership_update_listener,
)
from ringpop_tpu.membership import Membership
from ringpop_tpu.request_proxy.head import raw_head
from ringpop_tpu.request_proxy.http import ProxyResponse
from ringpop_tpu.request_proxy.proxy import RequestProxy
from ringpop_tpu.rollup import MembershipUpdateRollup
from ringpop_tpu.server import create_server
from ringpop_tpu.stats import Histogram, Meter
from ringpop_tpu.suspicion import Suspicion
from ringpop_tpu.swim.join_sender import join_cluster
from ringpop_tpu.swim.ping_req_sender import send_ping_req
from ringpop_tpu.swim.ping_sender import send_ping
from ringpop_tpu.utils.misc import safe_parse, to_json
from ringpop_tpu.utils.nulls import NullLogger, NullStatsd
from ringpop_tpu.utils.events import EventEmitter
from ringpop_tpu import __version__

MAX_JOIN_DURATION = 300000  # index.js:53
MEMBERSHIP_UPDATE_FLUSH_INTERVAL = 5000  # index.js:54
PROXY_REQ_PROPS = ("keys", "dest", "req", "res")


class RingPop(EventEmitter):
    def __init__(
        self,
        app: str = None,
        host_port: str = None,
        channel: Any = None,
        clock: Any = None,
        rng: random.Random | None = None,
        logger: Any = None,
        statsd: Any = None,
        bootstrap_file: Any = None,
        join_size: int | None = None,
        ping_req_timeout: float | None = None,
        ping_timeout: float | None = None,
        join_timeout: float | None = None,
        proxy_req_timeout: float | None = None,
        max_join_duration: float | None = None,
        min_protocol_period: float | None = None,
        suspicion_timeout: float | None = None,
        membership_update_flush_interval: float | None = None,
        request_proxy_max_retries: int | None = None,
        request_proxy_retry_schedule: list[float] | None = None,
        enforce_consistency: bool | None = None,
        faulty_probe_period: int | None = 10,
        damping_enabled: bool = False,
        damping_options: dict[str, float] | None = None,
    ):
        super().__init__()

        # Option validation (index.js:62-85)
        if not isinstance(app, str) or len(app) == 0:
            raise errors.AppRequiredError()
        parts = host_port.split(":") if isinstance(host_port, str) else None
        is_colon_separated = parts is not None and len(parts) == 2
        is_port = is_colon_separated and parts[1].isdigit()
        if not isinstance(host_port, str) or not is_colon_separated or not is_port:
            reason = (
                "a string"
                if not isinstance(host_port, str)
                else "a valid hostPort pattern"
                if not is_colon_separated
                else "a valid port"
            )
            raise errors.HostPortRequiredError(host_port=host_port, reason=reason)

        self.app = app
        self.host_port = host_port
        self.channel = channel
        self.clock = clock or SimScheduler()
        self.rng = rng or random.Random()
        self.logger = logger or NullLogger()
        # Only an emitter WE build (from a spec string) is ours to close
        # on destroy(); a caller-injected object may be shared by other
        # nodes (the harness cluster passes one emitter to every node).
        self._owns_statsd = isinstance(statsd, str)
        if self._owns_statsd:
            # emitter spec string ("statsd://HOST:PORT", a .jsonl path,
            # "-", "capture") — the obs subsystem's sink forms
            from ringpop_tpu.obs.emitters import make_emitter

            statsd = make_emitter(statsd)
        self.statsd = statsd or NullStatsd()
        self.bootstrap_file = bootstrap_file

        self.is_ready = False
        self.is_denying_joins = False

        self.debug_flags: dict[str, bool] = {}
        self.join_size = join_size
        self.ping_req_size = 3  # ping-req fanout (index.js:99)
        self.ping_req_timeout = ping_req_timeout or 5000
        self.ping_timeout = ping_timeout or 1500
        self.join_timeout = join_timeout or 1000
        self.proxy_req_timeout = proxy_req_timeout or 30000
        self.max_join_duration = max_join_duration or MAX_JOIN_DURATION
        self.membership_update_flush_interval = (
            membership_update_flush_interval or MEMBERSHIP_UPDATE_FLUSH_INTERVAL
        )

        self.damping = None  # set after wiring; listeners null-check it

        self.request_proxy = RequestProxy(
            self,
            max_retries=request_proxy_max_retries,
            retry_schedule=request_proxy_retry_schedule,
            enforce_consistency=enforce_consistency,
        )
        self.ring = HashRing()
        self.dissemination = Dissemination(self)
        self.membership = Membership(self)
        self.membership.on("set", create_membership_set_listener(self))
        self.membership.on("updated", create_membership_update_listener(self))
        self.member_iterator = MembershipIterator(self)
        self.gossip = Gossip(self, min_protocol_period=min_protocol_period)
        self.suspicion = Suspicion(self, suspicion_timeout=suspicion_timeout)
        self.membership_update_rollup = MembershipUpdateRollup(
            self, flush_interval=self.membership_update_flush_interval
        )
        create_event_forwarder(self)

        # EXTENSION: flap damping — documented by the reference
        # (docs/architecture_design.md:73-82) but never implemented there
        # (SURVEY §5.3).  Off by default for strict reference behavior.
        if damping_enabled:
            from ringpop_tpu.damping import MemberDamping

            self.damping = MemberDamping(self, **(damping_options or {}))

        # rates tick on the injected clock so virtual-time runs stay
        # deterministic (Meter defaults to wall time otherwise)
        now_s = lambda: self.clock.now() / 1000.0  # noqa: E731
        self.client_rate = Meter(now_fn=now_s)
        self.server_rate = Meter(now_fn=now_s)
        self.total_rate = Meter(now_fn=now_s)

        # 10.30.8.26:20600 -> 10_30_8_26_20600 (index.js:141-145)
        self.stat_host_port = self.host_port.replace(".", "_").replace(":", "_")
        self.stat_prefix = f"ringpop.{self.stat_host_port}"
        self.stat_keys: dict[str, str] = {}
        self.stats_hooks: dict[str, Any] = {}
        # every timing stat also feeds a local reservoir so /admin/stats
        # can answer with p50/p95/p99 aggregates (the reference's
        # protocol timing percentiles, gossip.js:33) even when statsd is
        # a fire-and-forget UDP sink
        self.timing_histograms: dict[str, Histogram] = {}

        self.destroyed = False
        self.joiner = None
        self.is_pinging = False
        self.bootstrap_hosts: list[str] | None = None

        # EXTENSION over the reference: every Nth protocol period, probe a
        # random faulty member instead of the iterator's pick.  The
        # reference never pings faulty members (membership.js:135-139), so
        # a fully-partitioned cluster whose sides declared each other
        # faulty can never auto-merge after the split heals — its netsplit
        # test helper was left unfinished (test/lib/partition-cluster.js).
        # This is the standard SWIM gossip-to-dead anti-entropy fix; the
        # exchange triggers full syncs + refutation and the split merges.
        # Set faulty_probe_period=None to get strict reference behavior.
        self.faulty_probe_period = faulty_probe_period
        self._protocol_period_count = 0

        self.start_time = self.clock.now()

    # -- lifecycle ----------------------------------------------------------

    def setup_channel(self) -> None:
        create_server(self, self.channel)

    def destroy(self) -> None:
        self.destroyed = True
        if not self.gossip.is_stopped:
            self.gossip.stop()
        self.suspicion.stop_all()
        self.membership_update_rollup.destroy()
        self.request_proxy.destroy()
        if self.joiner is not None:
            self.joiner.destroy()
        if self.channel is not None and not self.channel.destroyed:
            self.channel.close()
        if self._owns_statsd:
            close = getattr(self.statsd, "close", None)
            if close is not None:
                close()  # flush file-backed emitters (obs.emitters)

    def whoami(self) -> str:
        return self.host_port

    # -- bootstrap (index.js:200-292) ---------------------------------------

    def bootstrap(self, opts: Any = None, callback: Callable[..., None] | None = None) -> None:
        bootstrap_file = None
        join_parallelism_factor = None
        if callable(opts):
            callback = opts
        elif isinstance(opts, dict):
            bootstrap_file = opts.get("bootstrapFile")
            join_parallelism_factor = opts.get("joinParallelismFactor")
        elif opts is not None:
            bootstrap_file = opts

        if self.is_ready:
            msg = "ringpop is already ready"
            self.logger.warn(msg, {"address": self.host_port})
            if callback:
                callback(Exception(msg))
            return

        bootstrap_time = self.clock.now()
        self.seed_bootstrap_hosts(bootstrap_file)

        if not isinstance(self.bootstrap_hosts, list) or not self.bootstrap_hosts:
            msg = (
                "ringpop cannot be bootstrapped without bootstrap hosts."
                " make sure you specify a valid bootstrap hosts file to the"
                " ringpop constructor or have a valid hosts.json file in the"
                " current working directory."
            )
            self.logger.warn(msg)
            if callback:
                callback(Exception(msg))
            return

        self.check_for_missing_bootstrap_host()

        # Add local member (stashed until set(), index.js:235).
        self.membership.make_alive(self.whoami(), int(self.clock.now()))

        def on_join(err: Any, nodes_joined: Any = None) -> None:
            if err:
                self.logger.error(
                    "ringpop bootstrap failed",
                    {"error": str(err), "address": self.host_port},
                )
                if callback:
                    callback(err)
                return
            if self.destroyed:
                msg2 = "ringpop was destroyed during bootstrap"
                self.logger.error(msg2, {"address": self.host_port})
                if callback:
                    callback(Exception(msg2))
                return

            # Atomic apply of stashed changes, then go live.
            self.membership.set()
            self.gossip.start()
            self.is_ready = True

            self.logger.debug(
                "ringpop is ready",
                {
                    "address": self.host_port,
                    "memberCount": self.membership.get_member_count(),
                    "bootstrapTime": self.clock.now() - bootstrap_time,
                },
            )
            self.emit("ready")
            if callback:
                callback(None, nodes_joined)

        self.joiner = join_cluster(
            self,
            on_join,
            max_join_duration=self.max_join_duration,
            join_size=self.join_size,
            parallelism_factor=join_parallelism_factor,
            join_timeout=self.join_timeout,
        )

    def check_for_missing_bootstrap_host(self) -> bool:
        if self.host_port not in self.bootstrap_hosts:
            self.logger.warn(
                "bootstrap hosts does not include the host/port of the local node",
                {"address": self.host_port},
            )
            return False
        return True

    def read_hosts_file(self, file: Any) -> Any:
        if not file:
            return False
        if not os.path.exists(file):
            self.logger.warn("bootstrap hosts file does not exist", {"file": file})
            return False
        try:
            with open(file) as f:
                return safe_parse(f.read())
        except OSError as e:
            self.logger.warn(
                "failed to read bootstrap hosts file", {"error": str(e), "file": file}
            )
            return False

    def seed_bootstrap_hosts(self, file: Any) -> None:
        if isinstance(file, list):
            self.bootstrap_hosts = file
        else:
            self.bootstrap_hosts = (
                self.read_hosts_file(file)
                or self.read_hosts_file(self.bootstrap_file)
                or self.read_hosts_file("./hosts.json")
                or None
            )

    def reload(self, file: Any, callback: Callable[..., None]) -> None:
        self.seed_bootstrap_hosts(file)
        callback()

    # -- SWIM round driver (index.js:458-515) -------------------------------

    def ping_member_now(self, callback: Callable[..., None] | None = None) -> None:
        callback = callback or (lambda *a: None)

        if self.damping is not None:
            # a quiet cluster must still reinstate decayed members
            self.damping.decay_tick()

        if self.is_pinging:
            self.logger.warn("aborting ping because one is in progress")
            return callback()
        if not self.is_ready:
            self.logger.warn("ping started before ring initialized")
            return callback()

        self._protocol_period_count += 1
        member = None
        if (
            self.faulty_probe_period
            and self._protocol_period_count % self.faulty_probe_period == 0
        ):
            faulty = [
                m
                for m in self.membership.members
                if m.status == "faulty" and m.address != self.whoami()
            ]
            if faulty:
                member = faulty[int(self.rng.random() * len(faulty))]
        if member is None:
            member = self.member_iterator.next()
        if member is None:
            self.logger.warn("no usable nodes at protocol period")
            return callback()

        self.is_pinging = True
        start = self.clock.now()

        def on_ping(is_ok: bool, body: Any) -> None:
            self.stat("timing", "ping", self.clock.now() - start)
            if is_ok:
                self.is_pinging = False
                self.membership.update(body.get("changes", []))
                return callback()

            if self.destroyed:
                return callback(Exception("destroyed whilst pinging"))

            ping_req_start = self.clock.now()

            def on_ping_req(*args: Any) -> None:
                self.stat("timing", "ping-req", self.clock.now() - ping_req_start)
                self.is_pinging = False
                callback(*args)

            send_ping_req(self, member, self.ping_req_size, on_ping_req)

        send_ping(self, member, on_ping)

    def handle_tick(self, cb: Callable[..., None]) -> None:
        def on_pinged(*_args: Any) -> None:
            cb(None, to_json({"checksum": self.membership.checksum}))

        self.ping_member_now(on_pinged)

    # -- lookup (index.js:409-446) ------------------------------------------

    def lookup(self, key: Any) -> str:
        start = self.clock.now()
        dest = self.ring.lookup(str(key))
        # timing stat + local histogram (same path as ping/ping-req), so
        # get_stats()["lookup"] answers p50/p95/p99 without a collector
        self.stat("timing", "lookup", self.clock.now() - start)
        self.emit("lookup", {"timing": self.clock.now() - start})
        if not dest:
            self.logger.debug("could not find destination for a key", {"key": key})
            return self.whoami()
        return dest

    def lookup_n(self, key: Any, n: int) -> list[str]:
        start = self.clock.now()
        dests = self.ring.lookup_n(str(key), n)
        self.stat("timing", "lookupn", self.clock.now() - start)
        self.emit("lookupN", {"timing": self.clock.now() - start})
        if not dests:
            self.logger.debug("could not find destinations for a key", {"key": key})
            return [self.whoami()]
        return dests

    # -- forwarding (index.js:577-694) --------------------------------------

    def proxy_req(self, opts: dict[str, Any]) -> None:
        if not opts:
            raise errors.OptionsRequiredError("proxyReq")
        self.validate_props(opts, PROXY_REQ_PROPS)
        self.request_proxy.proxy_req(opts)

    def handle_or_proxy(
        self, key: Any, req: Any, res: Any, opts: dict[str, Any] | None = None
    ) -> bool | None:
        dest = self.lookup(key)
        if self.whoami() == dest:
            return True
        merged = dict(opts or {})
        merged.update({"keys": [key], "dest": dest, "req": req, "res": res})
        self.proxy_req(merged)
        return None

    def handle_or_proxy_all(
        self, opts: dict[str, Any], cb: Callable[..., None] | None = None
    ) -> None:
        keys = opts["keys"]
        req = opts.get("req")
        whoami = self.whoami()

        keys_by_dest: dict[str, list[Any]] = collections.defaultdict(list)
        for key in keys:
            keys_by_dest[self.lookup(key)].append(key)

        dests = list(keys_by_dest.keys())
        state = {"pending": len(dests), "done": False}
        responses: list[dict[str, Any]] = []

        if state["pending"] == 0 and cb:
            return cb(None, responses)

        def on_response(err: Any, resp: Any, dest: str) -> None:
            responses.append(
                {"res": resp, "dest": dest, "keys": keys_by_dest[dest]}
            )
            state["pending"] -= 1
            if (state["pending"] == 0 or err) and cb and not state["done"]:
                state["done"] = True
                cb(err, responses)

        for dest in dests:
            dest_keys = keys_by_dest[dest]
            res = ProxyResponse(
                lambda err, resp, d=dest: on_response(err, resp, d)
            )
            if whoami == dest:
                head = raw_head(req, self.membership.checksum, dest_keys)
                self.emit("request", req, res, head)
            else:
                merged = dict(opts)
                merged.update(
                    {"keys": dest_keys, "req": req, "res": res, "dest": dest}
                )
                self.proxy_req(merged)

    # -- stats / debug (index.js:348-405,547-605) ---------------------------

    def get_stats(self) -> dict[str, Any]:
        timestamp = self.clock.now()
        stats = {
            "damping": self.damping.get_stats() if self.damping else None,
            "hooks": self.get_stats_hooks_stats(),
            "membership": self.membership.get_stats(),
            "process": {"pid": os.getpid()},
            "protocol": {
                "timing": self.gossip.protocol_timing.print_obj(),
                "protocolRate": self.gossip.compute_protocol_rate(),
                "clientRate": self.client_rate.print_obj()["m1"],
                "serverRate": self.server_rate.print_obj()["m1"],
                "totalRate": self.total_rate.print_obj()["m1"],
                # per-operation aggregates of the timing stats emitted at
                # ping_member_now (the reference ships these only to
                # statsd; /admin/stats answering locally means a cluster
                # with no collector still has its percentiles)
                "ping": self.timing_stats("ping"),
                "pingReq": self.timing_stats("ping-req"),
            },
            "ring": list(self.ring.servers.keys()),
            # serving-layer timing aggregates (the lookup/lookupn stats
            # emitted above; tick-cluster's `p` command prints them)
            "lookup": self.timing_stats("lookup"),
            "lookupN": self.timing_stats("lookupn"),
            "version": __version__,
            "timestamp": timestamp,
            "uptime": timestamp - self.start_time,
        }
        return stats

    def timing_stats(self, key: str) -> dict[str, Any]:
        """Histogram aggregate (count/min/max/median/p95/p99 ...) of a
        timing stat key, zeros-shaped before the first sample."""
        hist = self.timing_histograms.get(key)
        return (hist or Histogram()).print_obj()

    def get_stats_hooks_stats(self) -> dict[str, Any] | None:
        if not self.stats_hooks:
            return None
        return {name: hook.get_stats() for name, hook in self.stats_hooks.items()}

    def is_stats_hook_registered(self, name: str) -> bool:
        return name in self.stats_hooks

    def register_stats_hook(self, hook: Any) -> None:
        if not hook:
            raise errors.ArgumentRequiredError("hook")
        name = getattr(hook, "name", None) or (
            hook.get("name") if isinstance(hook, dict) else None
        )
        if not name:
            raise errors.FieldRequiredError("hook", "name")
        get_stats = getattr(hook, "get_stats", None) or (
            hook.get("get_stats") if isinstance(hook, dict) else None
        )
        if not callable(get_stats):
            raise errors.MethodRequiredError("hook", "getStats")
        if self.is_stats_hook_registered(name):
            raise errors.DuplicateHookError(name)
        if isinstance(hook, dict):
            hook = type("StatsHook", (), {"name": name, "get_stats": staticmethod(get_stats)})()
        self.stats_hooks[name] = hook

    def set_debug_flag(self, flag: str) -> None:
        self.debug_flags[flag] = True

    def clear_debug_flags(self) -> None:
        self.debug_flags = {}

    def debug_log(self, msg: str, flag: str = None) -> None:
        if self.debug_flags and self.debug_flags.get(flag):
            self.logger.info(msg)

    def stat(self, type_: str, key: str, value: Any = None) -> None:
        if key not in self.stat_keys:
            self.stat_keys[key] = f"{self.stat_prefix}.{key}"
        fq_key = self.stat_keys[key]
        if type_ == "increment":
            self.statsd.increment(fq_key, value)
        elif type_ == "gauge":
            self.statsd.gauge(fq_key, value)
        elif type_ == "timing":
            self.statsd.timing(fq_key, value)
            hist = self.timing_histograms.get(key)
            if hist is None:
                hist = self.timing_histograms[key] = Histogram(seed=0)
            if value is not None:
                hist.update(value)

    # -- test hooks (index.js:696-704) --------------------------------------

    def allow_joins(self) -> None:
        self.is_denying_joins = False

    def deny_joins(self) -> None:
        self.is_denying_joins = True

    def validate_props(self, opts: dict[str, Any], props: tuple) -> None:
        for prop in props:
            if not opts.get(prop):
                raise errors.PropertyRequiredError(prop)

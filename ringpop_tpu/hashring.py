"""Consistent hash ring: 100 replica points per server, farmhash32 placement.

Reference: lib/ring.js + lib/rbtree.js.  The reference stores replica points
in a red-black tree; the behavior contract is only the lookup semantics
(ring.js:138-182): ``lookup(key)`` returns the owner of the first replica
with hash >= farmhash32(key) (rbtree upperBound includes equality,
rbtree.js:262-271), wrapping to the minimum; ``lookupN`` walks successive
unique owners with wraparound.  A sorted array + binary search gives the
same O(log R) with far better constants and maps directly onto the
vectorized ``searchsorted`` device kernel (ops/ring_ops.py).

Tie-break on (astronomically rare) 32-bit hash collisions is by server name
— deterministic, unlike the reference's insertion-order-dependent tree.
"""

from __future__ import annotations

import bisect
from typing import Callable

from ringpop_tpu.ops.farmhash import farmhash32
from ringpop_tpu.utils.events import EventEmitter

DEFAULT_REPLICA_POINTS = 100


class HashRing(EventEmitter):
    def __init__(
        self,
        replica_points: int = DEFAULT_REPLICA_POINTS,
        hash_func: Callable[[str], int] | None = None,
    ):
        super().__init__()
        self.replica_points = replica_points
        self.hash_func = hash_func or farmhash32
        # Sorted list of (replica_hash, server) pairs.
        self._entries: list[tuple[int, str]] = []
        self.servers: dict[str, bool] = {}
        self.checksum: int | None = None
        # server -> tuple of replica hashes; remove re-uses what add
        # computed, and churn re-adds recently removed servers.
        self._replica_cache: dict[str, tuple[int, ...]] = {}

    def _replicas(self, server: str) -> tuple[int, ...]:
        hashes = self._replica_cache.get(server)
        if hashes is None:
            hashes = tuple(
                self.hash_func(f"{server}{i}") for i in range(self.replica_points)
            )
            if len(self._replica_cache) > 4 * max(len(self.servers), 1000):
                self._replica_cache.clear()
            self._replica_cache[server] = hashes
        return hashes

    # -- mutation (ring.js:39-94) -------------------------------------------

    def add_server(self, name: str) -> None:
        if self.has_server(name):
            return
        self._add_server_replicas(name)
        self.compute_checksum()
        self.emit("added", name)

    def remove_server(self, name: str) -> None:
        if not self.has_server(name):
            return
        self._remove_server_replicas(name)
        self.compute_checksum()
        self.emit("removed", name)

    def add_remove_servers(
        self,
        servers_to_add: list[str] | None = None,
        servers_to_remove: list[str] | None = None,
    ) -> bool:
        """Batch add/remove with a single checksum recompute (ring.js:60-94).

        One filter + one sort for the whole batch — per-replica bisect
        insertion is O(replicas x ring-size) per server, which made
        bootstrap-sized batches (1000+ servers via the membership
        listener) quadratic."""
        # Dedupe within the batch: the membership listener builds these
        # lists from raw update batches where an address can repeat, and a
        # double add would insert duplicate replica entries that a later
        # remove only half-deletes.  An address in both lists resolves to
        # its final state the way sequential add-then-remove would.
        removing = set(servers_to_remove or [])
        to_add = [
            s for s in dict.fromkeys(servers_to_add or [])
            if not self.has_server(s) and s not in removing
        ]
        to_remove = [s for s in dict.fromkeys(removing) if self.has_server(s)]
        # An absent server in both lists nets out, but sequential
        # add-then-remove (ring.js:60-94) still counts as a change —
        # checksum recomputed, True returned.  Match that.
        transient = any(
            s in removing and not self.has_server(s) for s in (servers_to_add or [])
        )
        if not to_add and not to_remove:
            if transient:
                self.compute_checksum()
                return True
            return False
        entries = self._entries
        if to_remove:
            for server in to_remove:
                del self.servers[server]
            dead = {
                (h, server) for server in to_remove for h in self._replicas(server)
            }
            entries = [e for e in entries if e not in dead]
        if to_add:
            for server in to_add:
                self.servers[server] = True
            entries = entries + [
                (h, server) for server in to_add for h in self._replicas(server)
            ]
            entries.sort()
        self._entries = entries
        self.compute_checksum()
        return True

    def _add_server_replicas(self, server: str) -> None:
        self.servers[server] = True
        for h in self._replicas(server):
            bisect.insort(self._entries, (h, server))

    def _remove_server_replicas(self, server: str) -> None:
        del self.servers[server]
        for h in self._replicas(server):
            idx = bisect.bisect_left(self._entries, (h, server))
            if idx < len(self._entries) and self._entries[idx] == (h, server):
                del self._entries[idx]

    # -- checksum (ring.js:96-105) ------------------------------------------

    def compute_checksum(self) -> None:
        server_name_str = ";".join(sorted(self.servers.keys()))
        self.checksum = self.hash_func(server_name_str)
        self.emit("checksumComputed")

    # -- queries (ring.js:107-182) ------------------------------------------

    def get_server_count(self) -> int:
        return len(self.servers)

    def has_server(self, name: str) -> bool:
        return name in self.servers

    def lookup(self, key: str) -> str | None:
        if not self._entries:
            return None
        h = self.hash_func(key)
        idx = bisect.bisect_left(self._entries, (h, ""))
        if idx == len(self._entries):
            idx = 0  # wrap to min (ring.js:142-145)
        return self._entries[idx][1]

    def lookup_n(self, key: str, n: int) -> list[str]:
        """Preference list: up to n unique successor owners (ring.js:150-182)."""
        n = min(n, self.get_server_count())
        if n <= 0 or not self._entries:
            return []
        h = self.hash_func(key)
        start = bisect.bisect_left(self._entries, (h, ""))
        result: list[str] = []
        seen: set[str] = set()
        for k in range(len(self._entries)):
            server = self._entries[(start + k) % len(self._entries)][1]
            if server not in seen:
                seen.add(server)
                result.append(server)
                if len(result) == n:
                    break
        return result

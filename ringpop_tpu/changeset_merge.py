"""Merge multiple changesets, max-incarnation-wins, excluding self
(reference: lib/membership-changeset-merge.js)."""

from __future__ import annotations

from typing import Any


def merge_membership_changesets(
    local_address: str, changesets: list[list[dict[str, Any]]]
) -> list[dict[str, Any]]:
    merge_index: dict[str, dict[str, Any]] = {}

    for changes in changesets:
        for change in changes:
            address = change.get("address")
            if address == local_address:
                continue
            existing = merge_index.get(address)
            if existing is None or existing.get("incarnationNumber") < change.get(
                "incarnationNumber"
            ):
                merge_index[address] = change

    return list(merge_index.values())

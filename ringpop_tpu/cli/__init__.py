"""CLI & tooling (reference: main.js, scripts/tick-cluster.js,
scripts/generate-hosts.js — SURVEY §2.2).

* ``python -m ringpop_tpu worker --listen H:P --hosts hosts.json`` — one
  real node over the TCP transport (main.js parity).
* ``python -m ringpop_tpu tick-cluster -n 5`` — multi-process cluster
  harness + fault injector (tick-cluster.js parity), with a ``--sim``
  mode that drives the in-process deterministic cluster instead.
* ``python -m ringpop_tpu generate-hosts`` — hosts.json generator.
"""

"""``worker`` subcommand: run one real ringpop node over TCP.

Reference: main.js — builds a channel, constructs RingPop, listens,
bootstraps from a hosts file (main.js:24-61).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys
import time
from typing import Any


class StdoutLogger:
    """Line-per-event JSON logger (the reference injects winston here)."""

    def __init__(self, name: str, level: str = "info"):
        self.name = name
        self.level = level
        self._levels = {"trace": 0, "debug": 1, "info": 2, "warn": 3, "error": 4}

    def _log(self, level: str, msg: str, extra: Any = None) -> None:
        if self._levels[level] < self._levels.get(self.level, 2):
            return
        record = {"ts": round(time.time(), 3), "name": self.name, "level": level, "msg": msg}
        if extra is not None:
            record["extra"] = extra
        try:
            print(json.dumps(record), flush=True)
        except (TypeError, ValueError):
            print(json.dumps({**record, "extra": repr(extra)}), flush=True)

    def trace(self, msg: str, extra: Any = None) -> None:
        self._log("trace", msg, extra)

    def debug(self, msg: str, extra: Any = None) -> None:
        self._log("debug", msg, extra)

    def info(self, msg: str, extra: Any = None) -> None:
        self._log("info", msg, extra)

    def warn(self, msg: str, extra: Any = None) -> None:
        self._log("warn", msg, extra)

    def error(self, msg: str, extra: Any = None) -> None:
        self._log("error", msg, extra)


def add_args(parser: argparse.ArgumentParser) -> None:
    parser.add_argument(
        "--listen", "-l", required=True, metavar="HOST:PORT",
        help="address to listen on (main.js --listen)",
    )
    parser.add_argument(
        "--hosts", "-f", default="./hosts.json", metavar="FILE",
        help="bootstrap hosts json file (main.js --hosts)",
    )
    parser.add_argument("--app", default="ringpop", help="app/service name")
    parser.add_argument("--log-level", default="info",
                        choices=["trace", "debug", "info", "warn", "error"])


async def run_node(args: argparse.Namespace) -> None:
    from ringpop_tpu.clock import AsyncioScheduler
    from ringpop_tpu.ringpop import RingPop
    from ringpop_tpu.transport.tcp import TcpChannel

    loop = asyncio.get_event_loop()
    logger = StdoutLogger(args.listen, level=args.log_level)
    channel = TcpChannel(args.listen, loop)
    ringpop = RingPop(
        app=args.app,
        host_port=args.listen,
        channel=channel,
        clock=AsyncioScheduler(loop),
        logger=logger,
    )
    ringpop.setup_channel()
    await channel.listen()
    logger.info("ringpop listening", {"address": args.listen})

    done: asyncio.Future = loop.create_future()

    def on_bootstrap(err: Any, nodes_joined: Any = None) -> None:
        if err:
            logger.error("bootstrap failed", {"error": str(err)})
            if not done.done():
                done.set_exception(SystemExit(1))
            return
        logger.info("ringpop ready", {"nodesJoined": nodes_joined})

    ringpop.bootstrap(args.hosts, on_bootstrap)
    try:
        await done  # runs forever unless bootstrap hard-fails
    finally:
        ringpop.destroy()


def main(argv: list[str] | None = None) -> None:
    parser = argparse.ArgumentParser(prog="ringpop-tpu worker")
    add_args(parser)
    args = parser.parse_args(argv)
    try:
        asyncio.run(run_node(args))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main(sys.argv[1:])

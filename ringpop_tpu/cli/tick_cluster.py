"""``tick-cluster`` subcommand: multi-node cluster harness & fault injector.

Reference: scripts/tick-cluster.js — spawns N child processes of a ringpop
program (tick-cluster.js:352-416), generates hosts.json (:486), and drives
them over ``/admin/*`` requests with keyboard commands (:250-331):

  j join-all   t tick-all (checksum-convergence groups, :88-115)
  s membership stats by checksum (:117-149)   p protocol timing (:167-190)
  g start gossip   d/D debug set/clear
  l suspend (SIGSTOP, :432-446)  L resume  k kill (SIGKILL, :448-462)
  K revive (:418-430)   q quit

Three execution backends (``--backend``):
* **proc** (default) — real OS processes (``python -m ringpop_tpu worker``)
  over the TCP transport, signals for fault injection: the reference's shape.
* **host-sim** (``--sim``) — the deterministic in-process
  ``harness.Cluster`` on virtual time: same commands, instant and
  reproducible.
* **tpu-sim** — the tensor simulation (``models/cluster.py``) behind the
  same command surface: tens of thousands of virtual nodes on one chip,
  with ``--loss`` (packet loss) and ``--damping`` (flap-damping
  extension).

Non-interactive automation: ``--script "j,w3000,t,t,q"`` runs comma-
separated commands (``wN`` = wait N ms) and exits — used by the
integration tests and benchmarks.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import time
from typing import Any

from ringpop_tpu.cli.admin_client import AdminRequestError, admin_request
from ringpop_tpu.cli.generate_hosts import generate


def print_op_percentiles(stats: dict[str, Any], indent: str = "    ") -> None:
    """The per-operation p50/p95/p99 lines of the `p` command, shared
    by the proc and host-sim drivers (full get_stats() shape): the
    protocol timings plus the serving-layer lookup/lookupN aggregates."""
    protocol = stats.get("protocol", {})
    ops = [
        ("ping", protocol.get("ping")),
        ("pingReq", protocol.get("pingReq")),
        ("lookup", stats.get("lookup")),
        ("lookupN", stats.get("lookupN")),
    ]
    for op, agg in ops:
        if agg and agg.get("count"):
            print(
                f"{indent}{op}: p50={agg['median']:.1f}"
                f" p95={agg['p95']:.1f} p99={agg['p99']:.1f}"
                f" count={agg['count']}"
            )


def group_by_checksum(checksums: dict[str, Any]) -> dict[Any, list[str]]:
    """tick-cluster.js:100-113: hosts grouped by membership checksum."""
    groups: dict[Any, list[str]] = {}
    for host, checksum in checksums.items():
        groups.setdefault(checksum, []).append(host)
    return groups


def format_groups(groups: dict[Any, list[str]], elapsed_ms: float) -> str:
    sizes = " ".join(str(len(v)) for v in groups.values())
    state = "CONVERGED" if len(groups) == 1 else f"{len(groups)} groups"
    return f"tick: {state} [{sizes}] in {elapsed_ms:.0f}ms"


class ClusterDriver:
    """Common command surface over either backend."""

    def cmd(self, ch: str) -> None:
        dispatch = {
            "j": self.join_all,
            "g": self.gossip_all,
            "t": self.tick_all,
            "s": self.stats,
            "p": self.protocol_stats,
            "d": lambda: self.debug_set("p"),
            "D": self.debug_clear,
            "l": self.suspend_next,
            "L": self.resume_all,
            "k": self.kill_next,
            "K": self.revive_next,
        }
        fn = dispatch.get(ch)
        if fn is None:
            print(f"unknown command {ch!r}")
        else:
            fn()

    # subclass responsibilities
    def join_all(self) -> None: ...
    def gossip_all(self) -> None: ...
    def tick_all(self) -> None: ...
    def stats(self) -> None: ...
    def protocol_stats(self) -> None: ...
    def debug_set(self, flag: str) -> None: ...
    def debug_clear(self) -> None: ...
    def suspend_next(self) -> None: ...
    def resume_all(self) -> None: ...
    def kill_next(self) -> None: ...
    def revive_next(self) -> None: ...
    def wait(self, ms: float) -> None: ...
    def shutdown(self) -> None: ...


class ProcCluster(ClusterDriver):
    """Real process-per-node cluster (tick-cluster.js mode)."""

    def __init__(self, size: int, base_port: int, host: str = "127.0.0.1",
                 log_level: str = "warn"):
        self.host_ports = generate([host], base_port, size)
        self.workdir = tempfile.mkdtemp(prefix="ringpop-tick-")
        self.hosts_file = os.path.join(self.workdir, "hosts.json")
        with open(self.hosts_file, "w") as f:
            json.dump(self.host_ports, f)
        self.log_level = log_level
        self.procs: dict[str, subprocess.Popen] = {}
        self.suspended: list[str] = []
        for host_port in self.host_ports:
            self.procs[host_port] = self._spawn(host_port)

    def _spawn(self, host_port: str) -> subprocess.Popen:
        log_path = os.path.join(self.workdir, host_port.replace(":", "_") + ".log")
        log_file = open(log_path, "a")
        try:
            return subprocess.Popen(
                [sys.executable, "-m", "ringpop_tpu", "worker",
                 "--listen", host_port, "--hosts", self.hosts_file,
                 "--log-level", self.log_level],
                stdout=log_file, stderr=subprocess.STDOUT,
            )
        finally:
            log_file.close()  # the child holds its inherited copy

    def live(self) -> list[str]:
        return [
            hp for hp, p in self.procs.items()
            if p.poll() is None and hp not in self.suspended
        ]

    def _each(self, endpoint: str, body: Any = None) -> dict[str, Any]:
        """Fan the request out concurrently (the reference drives all
        nodes in parallel; serial round-trips would distort the reported
        tick/convergence timings)."""
        from concurrent.futures import ThreadPoolExecutor

        hosts = self.live()
        if not hosts:
            return {}

        def one(host_port: str) -> Any:
            try:
                return admin_request(host_port, endpoint, body)
            except (AdminRequestError, OSError) as e:
                return f"error: {e}"

        with ThreadPoolExecutor(max_workers=min(32, len(hosts))) as pool:
            return dict(zip(hosts, pool.map(one, hosts)))

    def join_all(self) -> None:
        responses = self._each("/admin/join")
        errors = [hp for hp, r in responses.items()
                  if isinstance(r, str) and r.startswith("error")]
        print(f"join: {len(responses) - len(errors)} nodes joined"
              + (f", {len(errors)} errors {errors}" if errors else ""))

    def gossip_all(self) -> None:
        self._each("/admin/gossip")
        print("gossip started on all nodes")

    def tick_all(self) -> None:
        t0 = time.perf_counter()
        responses = self._each("/admin/tick")
        checksums = {hp: r.get("checksum") for hp, r in responses.items()
                     if isinstance(r, dict)}
        errors = [hp for hp in responses if hp not in checksums]
        line = format_groups(group_by_checksum(checksums),
                             (time.perf_counter() - t0) * 1000)
        if errors:
            line += f"  ({len(errors)} errors: {errors})"
        print(line)

    def stats(self) -> None:
        responses = self._each("/admin/stats")
        checksums = {
            hp: (r.get("membership", {}).get("checksum")
                 if isinstance(r, dict) else r)
            for hp, r in responses.items()
        }
        for checksum, hosts in group_by_checksum(checksums).items():
            print(f"  checksum {checksum}: {len(hosts)} nodes {sorted(hosts)}")

    def protocol_stats(self) -> None:
        for hp, r in self._each("/admin/stats").items():
            if isinstance(r, dict):
                timing = r["protocol"]["timing"]
                print(
                    f"  {hp}: rate={r['protocol']['protocolRate']:.1f}ms"
                    f" p50={timing['median']:.1f} p95={timing['p95']:.1f}"
                    f" p99={timing['p99']:.1f} count={timing['count']}"
                )
                print_op_percentiles(r)
            else:
                print(f"  {hp}: {r}")

    def debug_set(self, flag: str) -> None:
        self._each("/admin/debugSet", {"debugFlag": flag})
        print(f"debug flag {flag!r} set on all nodes")

    def debug_clear(self) -> None:
        self._each("/admin/debugClear")
        print("debug flags cleared on all nodes")

    def suspend_next(self) -> None:
        live = self.live()
        if not live:
            return print("no live node to suspend")
        target = live[-1]
        self.procs[target].send_signal(signal.SIGSTOP)
        self.suspended.append(target)
        print(f"suspended {target}")

    def resume_all(self) -> None:
        for host_port in self.suspended:
            if self.procs[host_port].poll() is None:
                self.procs[host_port].send_signal(signal.SIGCONT)
        print(f"resumed {len(self.suspended)} nodes")
        self.suspended.clear()

    def kill_next(self) -> None:
        live = self.live()
        if not live:
            return print("no live node to kill")
        target = live[-1]
        self.procs[target].kill()
        self.procs[target].wait()
        print(f"killed {target}")

    def revive_next(self) -> None:
        dead = [hp for hp, p in self.procs.items() if p.poll() is not None]
        if not dead:
            return print("no dead node to revive")
        target = dead[0]
        self.procs[target] = self._spawn(target)
        print(f"revived {target}")

    def wait(self, ms: float) -> None:
        time.sleep(ms / 1000.0)

    def wait_healthy(self, timeout_s: float = 60.0) -> None:
        """Block until every worker answers /health (startup can be slow:
        each spawned interpreter pays the site-level jax import)."""
        deadline = time.time() + timeout_s
        waiting = set(self.host_ports)
        while waiting and time.time() < deadline:
            for host_port in list(waiting):
                try:
                    admin_request(host_port, "/health", timeout_s=1.0)
                    waiting.discard(host_port)
                except (AdminRequestError, OSError):
                    pass
            if waiting:
                time.sleep(0.25)
        if waiting:
            print(f"warning: nodes never became healthy: {sorted(waiting)}")

    def shutdown(self) -> None:
        self.resume_all()
        for proc in self.procs.values():
            if proc.poll() is None:
                proc.terminate()
        deadline = time.time() + 5
        for proc in self.procs.values():
            try:
                proc.wait(timeout=max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                proc.kill()


class SimCluster(ClusterDriver):
    """Deterministic in-process cluster on virtual time (--sim)."""

    def __init__(self, size: int, base_port: int, seed: int = 1):
        from ringpop_tpu.harness import Cluster

        self.cluster = Cluster(size=size, base_port=base_port, seed=seed)
        self.cluster.bootstrap_all()
        self._suspended: list[int] = []
        self._killed: list[int] = []

    def join_all(self) -> None:
        print(f"join: {len(self.cluster.live_nodes())} nodes bootstrapped")

    def gossip_all(self) -> None:
        for node in self.cluster.live_nodes():
            node.gossip.start()
        print("gossip started on all nodes")

    def tick_all(self) -> None:
        t0 = time.perf_counter()
        self.cluster.tick_all()
        print(format_groups(self.cluster.checksum_groups(),
                            (time.perf_counter() - t0) * 1000))

    def stats(self) -> None:
        for checksum, hosts in self.cluster.checksum_groups().items():
            print(f"  checksum {checksum}: {len(hosts)} nodes {sorted(hosts)}")

    def protocol_stats(self) -> None:
        for node in self.cluster.live_nodes():
            stats = node.get_stats()
            timing = stats["protocol"]["timing"]
            print(
                f"  {node.host_port}: p50={timing['median']:.1f}"
                f" p95={timing['p95']:.1f} count={timing['count']}"
            )
            print_op_percentiles(stats)

    def debug_set(self, flag: str) -> None:
        for node in self.cluster.live_nodes():
            node.set_debug_flag(flag)

    def debug_clear(self) -> None:
        for node in self.cluster.live_nodes():
            node.clear_debug_flags()

    def suspend_next(self) -> None:
        live = [i for i, n in enumerate(self.cluster.nodes)
                if i not in self._suspended and i not in self._killed]
        if not live:
            return print("no live node to suspend")
        self.cluster.suspend(live[-1])
        self._suspended.append(live[-1])
        print(f"suspended {self.cluster.host_ports[live[-1]]}")

    def resume_all(self) -> None:
        for index in self._suspended:
            self.cluster.resume(index)
        print(f"resumed {len(self._suspended)} nodes")
        self._suspended.clear()

    def kill_next(self) -> None:
        live = [i for i, n in enumerate(self.cluster.nodes)
                if i not in self._suspended and i not in self._killed]
        if not live:
            return print("no live node to kill")
        self.cluster.kill(live[-1])
        self._killed.append(live[-1])
        print(f"killed {self.cluster.host_ports[live[-1]]}")

    def revive_next(self) -> None:
        if not self._killed:
            return print("no dead node to revive")
        index = self._killed.pop(0)
        self.cluster.revive(index)
        print(f"revived {self.cluster.host_ports[index]}")

    def wait(self, ms: float) -> None:
        self.cluster.run(ms)

    def shutdown(self) -> None:
        self.cluster.destroy_all()


def _pin_platform() -> None:
    """Honor JAX_PLATFORMS before any backend initializes.

    The environment may pre-register a TPU plugin and pin
    jax_platforms at the config level; honor JAX_PLATFORMS if the
    operator set it (e.g. =cpu to drive the sim without a chip)."""
    import jax

    platform = os.environ.get("JAX_PLATFORMS")
    current = getattr(jax.config, "jax_platforms", None)
    if platform and platform != current:
        # The config must be restricted BEFORE touching devices() —
        # otherwise backend discovery initializes every registered
        # plugin, including a possibly-unreachable TPU tunnel.
        jax.config.update("jax_platforms", platform)
        try:
            # Bare get_backend() (first device_put) can still route to
            # a pre-registered TPU plugin; pin the default device too.
            jax.config.update(
                "jax_default_device", jax.devices(platform.split(",")[0])[0]
            )
        except RuntimeError as e:
            jax.config.update("jax_platforms", current)  # revert
            print(
                f"warning: JAX_PLATFORMS={platform!r} failed to"
                f" initialize ({e}); continuing with {current!r}",
                file=sys.stderr,
            )


def print_final_checksums(cluster, groups: dict[int, list[str]] | None = None) -> None:
    """Deterministic end-of-run line: the distinct membership checksums
    among live nodes, sorted — what the CI soak-resume smoke greps to
    compare a killed+resumed run against its uninterrupted twin.
    ``groups`` (a ``checksum_groups()`` result) skips recomputing the
    per-node checksum pass when the caller already ran it."""
    sums = sorted(groups) if groups is not None else sorted(
        set(cluster.checksums().values())
    )
    print("final checksums: " + " ".join(str(s) for s in sums))


class TpuSimCluster(ClusterDriver):
    """The TPU simulation backend behind the same command surface
    (models/cluster.py SimCluster): tens of thousands of virtual nodes
    on one chip.  ``wN`` advances N ms of protocol time
    (= N / period_ms ticks)."""

    def __init__(self, size: int, seed: int = 1, loss: float = 0.0,
                 damping: bool = False, sparse_cap: int = 0,
                 probe: str = "sweep", layout: str = "dense",
                 capacity: int = 256, stats_out: str | None = None):
        _pin_platform()

        from ringpop_tpu.models import swim_sim as sim
        from ringpop_tpu.models.cluster import SimCluster
        from ringpop_tpu.obs.emitters import make_emitter

        self.sim = sim
        self.stats_emitter = make_emitter(stats_out) if stats_out else None
        self.cluster = SimCluster(
            size,
            sim.SwimParams(loss=loss, sparse_cap=sparse_cap, probe=probe),
            seed=seed,
            damping=damping,
            backend=layout,
            capacity=capacity,
            stats_emitter=self.stats_emitter,
        )
        # an identically-seeded sibling cluster: the --policy control
        # arm replays the same incident (same key stream) without the
        # policy, so the before/after line is a true A/B
        self._mk_cluster = lambda: SimCluster(
            size,
            sim.SwimParams(loss=loss, sparse_cap=sparse_cap, probe=probe),
            seed=seed,
            damping=damping,
            backend=layout,
            capacity=capacity,
        )
        self._suspended: list[int] = []
        self._killed: list[int] = []

    def join_all(self) -> None:
        print(f"join: {len(self.cluster.live_indices())} virtual nodes live")

    def gossip_all(self) -> None:
        print("gossip is implicit: every tick is one protocol period per node")

    def tick_all(self) -> None:
        t0 = time.perf_counter()
        metrics = self.cluster.tick()
        groups = self.cluster.checksum_groups()
        line = format_groups(groups, (time.perf_counter() - t0) * 1000)
        print(f"{line}  (pings={metrics['pings_sent']}"
              f" full_syncs={metrics['full_syncs']})")

    def stats(self) -> None:
        groups = self.cluster.checksum_groups()
        for checksum, addrs in sorted(groups.items(), key=lambda g: -len(g[1])):
            sample = ", ".join(sorted(addrs)[:3])
            more = f" (+{len(addrs) - 3} more)" if len(addrs) > 3 else ""
            print(f"  checksum {checksum}: {len(addrs)} nodes [{sample}{more}]")

    def protocol_stats(self) -> None:
        log = self.cluster.metrics_log[-5:]
        for i, metrics in enumerate(log):
            print(f"  t-{len(log) - i}: {metrics}")
        # request-latency percentiles next to the protocol counters:
        # the latest SLO-latency-enabled traffic trace's histogram
        # plane (traffic/latency.py), whole-run aggregate
        from ringpop_tpu.traffic.latency import plane_stats

        for trace in reversed(self.cluster.traces):
            agg = plane_stats(trace)
            if agg is not None:
                print(
                    f"  requestProxy.send: p50={agg['median']:.0f}ms "
                    f"p95={agg['p95']:.0f}ms p99={agg['p99']:.0f}ms "
                    f"count={agg['count']}"
                )
                break

    def debug_set(self, flag: str) -> None:
        print("debug flags are a host-library feature; use metrics_log")

    def debug_clear(self) -> None:
        pass

    def _live(self) -> list[int]:
        return [int(i) for i in self.cluster.live_indices()]

    def suspend_next(self) -> None:
        live = [i for i in self._live() if i not in self._suspended]
        if not live:
            return print("no live node to suspend")
        self.cluster.suspend(live[-1])
        self._suspended.append(live[-1])
        print(f"suspended node {live[-1]}")

    def resume_all(self) -> None:
        for index in self._suspended:
            self.cluster.resume(index)
        print(f"resumed {len(self._suspended)} nodes")
        self._suspended.clear()

    def kill_next(self) -> None:
        live = self._live()
        if not live:
            return print("no live node to kill")
        self.cluster.kill(live[-1])
        self._killed.append(live[-1])
        print(f"killed node {live[-1]}")

    def revive_next(self) -> None:
        if not self._killed:
            return print("no dead node to revive")
        index = self._killed.pop(0)
        self.cluster.revive(index)
        print(f"revived node {index}")

    def wait(self, ms: float) -> None:
        ticks = max(1, int(ms / self.cluster.params.period_ms))
        self.cluster.tick(ticks)

    def shutdown(self) -> None:
        if self.stats_emitter is not None:
            self.stats_emitter.close()

    def run_scenario(
        self,
        path: str | None,
        trace_out: str | None = None,
        sweep: int = 0,
        sweep_loss_scales: list[float] | None = None,
        sweep_kill_jitter: list[int] | None = None,
        sweep_flap_jitter: list[int] | None = None,
        sweep_param_axes: dict[str, list[float | int]] | None = None,
        traffic: str | None = None,
        latency_buckets: int = 0,
        segment_ticks: int | None = None,
        checkpoint: str | None = None,
        checkpoint_every: int = 1,
        segment_store: str | None = None,
        incident: str | None = None,
        policy: str | None = None,
        trace_rumors: int = 0,
        spans_out: str | None = None,
    ) -> None:
        """Run a JSON scenario spec as ONE jitted call (scenarios/);
        with ``sweep=R`` run R replicas in one vmapped dispatch; with
        ``traffic`` co-run a key workload (spec shorthand like
        ``zipf:512``, or a JSON workload file) inside the same
        compiled program and report the serving counters; with
        ``segment_ticks=S`` stream the run as pipelined S-tick segment
        dispatches (one compile), checkpointing every
        ``checkpoint_every`` segments when ``checkpoint`` is given —
        a killed soak continues with ``--resume``.

        ``incident=NAME`` replays a named outage from the incident
        library (scenarios/library.py) at this cluster's size instead
        of a spec file: the incident supplies both the fault timeline
        and its latency-coupled workload, the run streams by default
        (segments of 32), and the detect/heal/serve summary prints at
        the end — the same summary the golden regression lane pins.

        ``policy=NAME[:k=v,...]`` arms a remediation policy
        (ringpop_tpu/policies); with ``incident`` a no-policy CONTROL
        arm replays first on an identically-seeded sibling cluster, and
        the before/after goodput + amplification line prints under the
        summary.

        ``trace_rumors=K`` arms the provenance plane with K rumor
        slots (obs/provenance.py; composes with ``incident``: the
        incident's own declarations auto-arm slots), prints the
        per-rumor dissemination report, and with ``spans_out=FILE``
        writes the Perfetto-openable trace-event JSON
        (obs/spans.py)."""
        from ringpop_tpu.scenarios.spec import ScenarioSpec

        incident_name = incident
        if incident_name is not None:
            from ringpop_tpu.scenarios import library as ilib

            spec, traffic = ilib.build_incident(
                incident_name, self.cluster.n,
                backend=self.cluster.backend,
            )
            if segment_ticks is None:
                # incidents stream by default: one compile, O(segment)
                # host telemetry, and the same bit-identical trace
                segment_ticks = min(32, spec.ticks)
        else:
            spec = ScenarioSpec.load(path)
        if trace_rumors:
            # arm the provenance plane on top of whatever the spec (or
            # the incident) already says — a spec-file trace_rumors
            # stands unless the flag overrides it
            spec = spec._replace(trace_rumors=int(trace_rumors))
        if traffic and latency_buckets and incident_name is None:
            # enable the SLO latency plane on the parsed workload
            # (compile_traffic pins the tick->ms period to the cluster)
            from ringpop_tpu.traffic.workloads import WorkloadSpec

            traffic = WorkloadSpec.from_spec(traffic)._replace(
                latency_buckets=int(latency_buckets)
            )
        if sweep:
            self._run_sweep(
                spec, trace_out, sweep, sweep_loss_scales, sweep_kill_jitter,
                flap_jitter=sweep_flap_jitter, traffic=traffic,
                segment_ticks=segment_ticks, segment_store=segment_store,
                policy=policy, param_axes=sweep_param_axes,
            )
            return
        control = None
        if policy is not None and incident_name is not None:
            from ringpop_tpu.scenarios import library as ilib

            ctrl_trace = self._mk_cluster().run_scenario(
                spec, traffic=traffic, segment_ticks=segment_ticks
            )
            control = ilib.incident_summary(ctrl_trace)
        t0 = time.perf_counter()
        if segment_ticks:
            trace = self.cluster.run_scenario(
                spec,
                traffic=traffic,
                segment_ticks=segment_ticks,
                checkpoint_path=checkpoint,
                checkpoint_every=checkpoint_every,
                store=segment_store,
                policy=policy,
            )
        else:
            trace = self.cluster.run_scenario(
                spec, traffic=traffic, policy=policy
            )
        wall_ms = (time.perf_counter() - t0) * 1000
        state = (
            "CONVERGED" if trace.converged[-1]
            else f"NOT converged ({int(trace.live[-1])} live)"
        )
        if segment_ticks:
            from ringpop_tpu.scenarios.stream import segment_bounds

            segments = len(segment_bounds(trace.ticks, segment_ticks))
            print(
                f"scenario: {trace.ticks} ticks streamed as {segments} "
                f"segments of {segment_ticks} (pipelined, one compile) in "
                f"{wall_ms:.0f}ms — {state}, first converged tick "
                f"{trace.first_converged_tick()}, "
                f"live {int(trace.live[-1])}/{self.cluster.n}"
            )
            if checkpoint:
                print(f"checkpoint (resume with --resume) -> {checkpoint}")
        else:
            print(
                f"scenario: {trace.ticks} ticks, {len(spec.events)} events, "
                f"one dispatch in {wall_ms:.0f}ms — {state}, first converged "
                f"tick {trace.first_converged_tick()}, "
                f"live {int(trace.live[-1])}/{self.cluster.n}"
            )
        groups = self.cluster.checksum_groups()
        print(format_groups(groups, wall_ms))
        if segment_ticks:
            print_final_checksums(self.cluster, groups=groups)
        if traffic and "lookups" in trace.metrics:
            m = trace.metrics
            lookups = int(m["lookups"].sum())
            misroutes = int(m["misroutes"].sum())
            peak = int(m["misroutes"].argmax())
            hops = {
                k[4:]: int(v.sum())
                for k, v in sorted(
                    m.items(),
                    key=lambda kv: int(kv[0][4:]) if kv[0][4:].isdigit() else 0,
                )
                if k.startswith("hops") and v.sum()
            }
            print(
                f"traffic: {lookups} lookups served, "
                f"{int(m['delivered'].sum())} delivered, "
                f"{misroutes} misroutes (peak {int(m['misroutes'][peak])} "
                f"at tick {peak}), {int(m['proxy_retries'].sum())} retries, "
                f"{int(m['proxy_failed'].sum())} failed; "
                f"forward hops {hops}"
            )
            from ringpop_tpu.traffic.latency import plane_stats

            agg = plane_stats(trace)
            if agg is not None:
                from ringpop_tpu.traffic.engine import total_sends

                delivered = max(int(m["delivered"].sum()), 1)
                sends = total_sends(m)
                print(
                    f"latency: p50={agg['median']:.0f}ms "
                    f"p95={agg['p95']:.0f}ms p99={agg['p99']:.0f}ms "
                    f"over {agg['count']} delivered; "
                    f"retry amplification {sends / delivered:.2f} "
                    f"sends/delivered, "
                    f"{int(m['gray_timeouts'].sum())} gray timeouts"
                )
        prov_report = None
        if spec.trace_rumors:
            from ringpop_tpu.obs import spans as obs_spans

            prov_report = self.cluster.provenance_report()
            rumors = prov_report["rumors"]
            print(
                f"provenance: {len(rumors)}/{spec.trace_rumors} rumor "
                f"slots armed (log2(n) bound {prov_report['log2_n']} ticks)"
            )
            res_name = {0: "pending", 1: "refuted", 2: "confirmed"}
            for r in rumors:
                res = res_name.get(r["resolution"], "?")
                at = (f"@t{r['resolution_tick']}"
                      if r["resolution_tick"] >= 0 else "")
                print(
                    f"  slot {r['slot']}: n{r['subject']} key {r['key']} — "
                    f"origin n{r['origin']}@t{r['origin_tick']}, {res}{at}, "
                    f"infected {r['infected']}/{prov_report['n']} "
                    f"(depth {r['depth_max']}, p50/p95/p99 "
                    f"{r['infection_p50']}/{r['infection_p95']}/"
                    f"{r['infection_p99']} ticks, "
                    f"{r['stragglers']} stragglers), "
                    f"witnesses {r['witnesses']}"
                )
            if spans_out:
                nev = obs_spans.write_spans(prov_report, spans_out)
                print(f"spans ({nev} trace events, Perfetto-openable) "
                      f"-> {spans_out}")
            if self.cluster.stats_sink is not None:
                from ringpop_tpu.obs import bridge as obs_bridge

                sink = self.cluster.stats_sink
                obs_bridge.emit_provenance(
                    prov_report, sink.emitter, prefix=sink.prefix
                )
        if incident_name is not None:
            from ringpop_tpu.scenarios import library as ilib

            summary = ilib.incident_summary(trace, prov=prov_report)
            print(ilib.format_summary(incident_name, summary))
            if control is not None and control.get("lookups"):
                g0 = 100.0 * control["delivered"] / control["lookups"]
                g1 = 100.0 * summary["delivered"] / max(summary["lookups"], 1)
                a0 = control["sends"] / max(control["delivered"], 1)
                a1 = summary["sends"] / max(summary["delivered"], 1)
                print(
                    f"policy {policy}: goodput {g0:.1f}% -> {g1:.1f}%, "
                    f"amplification {a0:.2f} -> {a1:.2f} "
                    f"(control arm vs policy arm, same seed)"
                )
        if trace_out:
            trace.save(trace_out)
            print(f"trace ({trace.ticks} ticks x "
                  f"{len(trace.metrics) + 3} series) -> {trace_out}")

    def _run_sweep(self, spec, trace_out, replicas, loss_scales, kill_jitter,
                   flap_jitter=None, traffic=None, segment_ticks=None,
                   segment_store=None, policy=None, param_axes=None):
        t0 = time.perf_counter()
        strace = self.cluster.run_sweep(
            spec, replicas,
            loss_scales=loss_scales, kill_jitter=kill_jitter,
            flap_jitter=flap_jitter, traffic=traffic,
            segment_ticks=segment_ticks, store=segment_store,
            policy=policy, param_axes=param_axes,
        )
        wall_ms = (time.perf_counter() - t0) * 1000
        summary = strace.summary()
        rep = summary["replicas"]
        det, heal = summary["detect_tick"], summary["heal_tick"]

        def dist(d, hit):
            if not hit:
                return "-"
            return (f"min={d['min']:.0f} p50={d['median']:.0f} "
                    f"p95={d['p95']:.0f} max={d['max']:.0f}")

        how = (
            f"streamed in segments of {segment_ticks}"
            if segment_ticks else "one vmapped dispatch"
        )
        print(
            f"sweep: {replicas} replicas x {strace.ticks} ticks, "
            f"{how} in {wall_ms:.0f}ms — "
            f"converged {rep['converged_final']}/{replicas}"
        )
        print(f"  detect tick ({rep['detected']}/{replicas} detected): "
              f"{dist(det, rep['detected'])}")
        print(f"  heal tick ({rep['healed']}/{replicas} healed): "
              f"{dist(heal, rep['healed'])}")
        serving = strace.serving_summary()
        if serving is not None:
            # per-replica serving scorecards: the traffic-coupled sweep's
            # one-dispatch answer (SweepTrace.serving_summary)
            for row in serving:
                line = (
                    f"  replica {row['replica']}: goodput "
                    f"{100 * row['goodput']:.1f}%, "
                    f"{row['misroutes']} misroutes, "
                    f"amplification {row['amplification']:.2f}"
                )
                if "lat_p99_ms" in row:
                    line += (f", lat p50/p95/p99 {row['lat_p50_ms']:.0f}/"
                             f"{row['lat_p95_ms']:.0f}/"
                             f"{row['lat_p99_ms']:.0f}ms")
                if "ov_gray_peak" in row:
                    line += f", peak overload-gray {row['ov_gray_peak']}"
                print(line)
        if trace_out:
            strace.save(trace_out)
            print(
                f"sweep trace ({replicas} x {strace.ticks} x "
                f"{len(strace.metrics) + 3} series) -> {trace_out}"
            )
        if self.cluster.stats_sink is not None:
            # run_sweep is a measurement fan-out, not the cluster's own
            # trajectory, so SimCluster does not bridge it; stream one
            # representative replica so --stats-out still observes it.
            # The cluster state did not advance, so its current
            # checksum (the sweep's shared starting point) is the
            # honest value for the checksum gauge.
            from ringpop_tpu.obs import bridge as obs_bridge

            checksum = None
            live = self.cluster.live_indices()
            if live.size:
                first = int(live[0])
                checksum = self.cluster.checksums(indices=[first])[
                    self.cluster.book.addresses[first]
                ]
            sink = self.cluster.stats_sink
            obs_bridge.replay_trace(
                strace.replica(0), sink.emitter, prefix=sink.prefix,
                checksum=checksum,
            )
            print("stats: bridged sweep replica 0 to --stats-out")


MENU = """commands:
  j join-all    g gossip-all   t tick (convergence)   s stats by checksum
  p protocol timing   d/D debug set/clear
  l suspend   L resume-all   k kill   K revive   q quit"""


def run_script(driver: ClusterDriver, script: str) -> None:
    for op in script.split(","):
        op = op.strip()
        if not op:
            continue
        if op[0] == "w":
            driver.wait(float(op[1:]))
        elif op == "q":
            break
        else:
            driver.cmd(op)


def run_interactive(driver: ClusterDriver) -> None:
    import termios
    import tty

    print(MENU)
    fd = sys.stdin.fileno()
    old = termios.tcgetattr(fd)
    try:
        tty.setcbreak(fd)
        while True:
            ch = sys.stdin.read(1)
            if ch in ("q", "\x03"):
                break
            driver.cmd(ch)
    finally:
        termios.tcsetattr(fd, termios.TCSADRAIN, old)


def add_args(parser: argparse.ArgumentParser) -> None:
    parser.add_argument("-n", "--size", type=int, default=5,
                        help="number of nodes (tick-cluster.js:32 default 5)")
    parser.add_argument("--base-port", type=int, default=3000)
    parser.add_argument("--sim", action="store_true",
                        help="in-process deterministic cluster on virtual time")
    parser.add_argument("--backend", choices=["proc", "host-sim", "tpu-sim"],
                        default=None,
                        help="proc: real processes; host-sim: in-process "
                             "host library (= --sim); tpu-sim: the tensor "
                             "simulation (scales to tens of thousands)")
    parser.add_argument("--loss", type=float, default=0.0,
                        help="tpu-sim: iid packet-loss probability")
    parser.add_argument("--sparse-cap", type=int, default=0,
                        help="tpu-sim: cap changes per message (sparse "
                             "dissemination fast path; 0 = dense)")
    parser.add_argument("--probe", choices=["uniform", "sweep"],
                        default="sweep",
                        help="tpu-sim: probe-target policy (sweep = "
                             "round-robin per-round coverage guarantee, "
                             "the SwimParams default)")
    parser.add_argument("--layout", choices=["dense", "delta"],
                        default="dense",
                        help="tpu-sim state layout: dense N x N views, or "
                             "the O(N*C) delta-from-base tables "
                             "(models/swim_delta.py) for 65k+ nodes")
    parser.add_argument("--capacity", type=int, default=256,
                        help="tpu-sim --layout delta: divergence slots "
                             "per viewer (C)")
    parser.add_argument("--damping", action="store_true",
                        help="tpu-sim: enable the flap-damping extension")
    parser.add_argument("--script", default=None,
                        help='non-interactive command list, e.g. "j,w3000,t,q"')
    parser.add_argument("--scenario", default=None, metavar="FILE",
                        help="tpu-sim: run a JSON scenario spec (compiled "
                             "fault timeline, one jitted dispatch; see "
                             "docs/simulation.md) instead of --script")
    parser.add_argument("--incident", default=None, metavar="NAME",
                        help="tpu-sim: replay a named outage from the "
                             "incident library (scenarios/library.py; "
                             "docs/incidents.md) at this cluster size — "
                             "fault timeline plus its latency-coupled "
                             "workload, streamed by default, with the "
                             "detect/heal/serve summary printed (the "
                             "golden-lane summary); see --list-incidents")
    parser.add_argument("--list-incidents", action="store_true",
                        help="print the incident catalog and exit")
    parser.add_argument("--policy", default=None, metavar="NAME[:k=v,...]",
                        help="tpu-sim: arm a remediation policy "
                             "(ringpop_tpu/policies; docs/incidents.md) in "
                             "the compiled scenario scan — admission "
                             "load-shedding, adaptive retry budgets, "
                             "serve-side quarantine, or all three "
                             "(combined), with optional integer knob "
                             "overrides.  Needs a serve workload "
                             "(--incident or --traffic); with --incident a "
                             "no-policy control arm replays first and the "
                             "before/after goodput + amplification line "
                             "prints; see --list-policies")
    parser.add_argument("--list-policies", action="store_true",
                        help="print the policy catalog (with concrete "
                             "default knobs at this --size) and exit")
    parser.add_argument("--trace-out", default=None, metavar="FILE",
                        help="with --scenario: write the per-tick telemetry "
                             "trace (.npz) here")
    parser.add_argument("--trace-rumors", type=int, default=0, metavar="K",
                        help="with --scenario/--incident: arm the gossip "
                             "provenance plane with K rumor slots "
                             "(obs/provenance.py) — per-rumor infection "
                             "wavefronts and suspect→faulty/refute "
                             "causality chains recorded INSIDE the "
                             "compiled scan; the dissemination report "
                             "(depth, infection-time percentiles vs the "
                             "paper's log2(N) bound) prints at the end")
    parser.add_argument("--spans-out", default=None, metavar="FILE",
                        help="with --trace-rumors: write the run's "
                             "provenance as Chrome trace-event JSON "
                             "(obs/spans.py) — open in ui.perfetto.dev "
                             "or chrome://tracing; one track per rumor, "
                             "detection window spans + infection flow "
                             "arrows")
    parser.add_argument("--traffic", default=None, metavar="SPEC",
                        help="with --scenario: co-run a key workload in "
                             "the same compiled program — SPEC is "
                             "kind:M[:pool] shorthand (uniform/zipf/"
                             "tenant, M keys per tick) or a JSON "
                             "workload file (traffic/workloads.py); "
                             "serving counters (lookup, requestProxy.*, "
                             "misroutes, forward hops) join the trace "
                             "and the --stats-out stream")
    parser.add_argument("--latency-buckets", type=int, default=0, metavar="B",
                        help="with --traffic: enable the SLO latency plane "
                             "(traffic/latency.py) — per-request latency "
                             "(link RTTs + RETRY_SCHEDULE backoff, gray "
                             "holders time out off their duty phase) lands "
                             "in B log2 buckets per tick; request-latency "
                             "p50/p95/p99 join the serving summary, the "
                             "'p' command, and the requestProxy.send "
                             "timing stream of --stats-out (0 = off)")
    parser.add_argument("--segment-ticks", type=int, default=None, metavar="S",
                        help="with --scenario: stream the run as pipelined "
                             "S-tick segment dispatches of ONE compiled "
                             "executable (scenarios/stream.py) — per-segment "
                             "telemetry drain overlaps the next segment's "
                             "device compute, host trace memory is "
                             "O(segment), and the run can checkpoint/resume "
                             "at segment granularity")
    parser.add_argument("--checkpoint", default=None, metavar="FILE",
                        help="with --segment-ticks: write a v5 checkpoint "
                             "(state + stream cursor) every "
                             "--checkpoint-every segments; segment slabs "
                             "persist next to it (FILE.segments/) so "
                             "--resume reproduces the full trace")
    parser.add_argument("--checkpoint-every", type=int, default=1, metavar="K",
                        help="with --checkpoint: checkpoint cadence in "
                             "completed segments (default 1: every segment)")
    parser.add_argument("--segment-store", default=None, metavar="DIR",
                        help="with --segment-ticks: write per-segment "
                             "telemetry slabs (.npz + JSONL manifest) here "
                             "instead of/as well as the in-memory trace")
    parser.add_argument("--resume", default=None, metavar="FILE",
                        help="continue a killed streamed soak from its "
                             "checkpoint (bit-identical to the "
                             "uninterrupted run) and print the final "
                             "summary; no other cluster flags needed")
    parser.add_argument("--sweep", type=int, default=0, metavar="R",
                        help="with --scenario: run R replicas of the "
                             "scenario in ONE vmapped jitted dispatch "
                             "(per-replica PRNG seeds; scenarios/sweep.py), "
                             "reporting detection/heal-tick distributions")
    parser.add_argument("--sweep-loss-scales", default=None, metavar="S,S,...",
                        help="with --sweep: comma list of R per-replica "
                             "loss multipliers (every loss value of the "
                             "spec, base included, scales per replica)")
    parser.add_argument("--sweep-kill-jitter", default=None, metavar="J,J,...",
                        help="with --sweep: comma list of R per-replica "
                             "tick offsets applied to the spec's kill "
                             "events")
    parser.add_argument("--sweep-flap-jitter", default=None, metavar="J,J,...",
                        help="with --sweep: comma list of R per-replica "
                             "tick offsets applied to the spec's flap "
                             "windows (at AND until move together, so "
                             "every replica keeps the same duty cycle at "
                             "a different storm phase)")
    parser.add_argument("--sweep-param-axes", default=None,
                        metavar="K=V,V,..;K=V,..",
                        help="with --sweep: semicolon list of traced "
                             "protocol knob axes, each a comma list of R "
                             "per-replica values (e.g. "
                             "suspicion_ticks=6,12,25) — one compiled "
                             "program serves the whole knob grid "
                             "(docs/simulation.md, 'Traced protocol "
                             "knobs')")
    parser.add_argument("--stats-out", default=None, metavar="SPEC",
                        help="tpu-sim: stream protocol stats under "
                             "reference statsd keys (obs/bridge.py key "
                             "table) to SPEC — a JSON-lines file path, "
                             "'-' (stdout), or statsd://HOST:PORT (UDP "
                             "line protocol); ticks stream as they run, "
                             "--scenario replays its whole trace")
    parser.add_argument("--profile-dir", default=None, metavar="DIR",
                        help="tpu-sim: bracket the run with a jax "
                             "profiler trace written to DIR "
                             "(TensorBoard/Perfetto-loadable, protocol "
                             "phases named via obs/annotate.py scopes)")
    parser.add_argument("--script-to-scenario", default=None, metavar="FILE",
                        help="compile --script into a scenario spec JSON at "
                             "FILE and exit (no cluster is started)")
    parser.add_argument("--seed", type=int, default=1)
    parser.add_argument("--log-level", default="warn")
    parser.add_argument("--startup-timeout-s", type=float, default=60,
                        help="proc mode: max wait for workers to answer /health")


def main(argv: list[str] | None = None) -> None:
    parser = argparse.ArgumentParser(prog="ringpop-tpu tick-cluster")
    add_args(parser)
    args = parser.parse_args(argv)

    if args.list_incidents:
        from ringpop_tpu.scenarios.library import format_catalog

        print(format_catalog())
        return

    if args.list_policies:
        from ringpop_tpu.policies import format_catalog as policy_catalog

        # the incident workloads serve 8n keys/tick, so show the
        # defaults a --incident run at this --size would compile
        print(policy_catalog(args.size, 8 * args.size))
        return

    if args.script_to_scenario:
        if not args.script:
            parser.error("--script-to-scenario needs --script")
        from ringpop_tpu.scenarios.spec import script_to_spec

        spec = script_to_spec(args.script, args.size)
        spec.save(args.script_to_scenario)
        print(
            f"compiled {len(spec.events)} events over {spec.ticks} ticks "
            f"-> {args.script_to_scenario}"
        )
        return

    if args.resume:
        _pin_platform()
        import time as _time

        from ringpop_tpu.scenarios import stream as sstream

        t0 = _time.perf_counter()
        cluster, result = sstream.resume(args.resume)
        wall_ms = (_time.perf_counter() - t0) * 1000
        trace = (
            result if not isinstance(result, sstream.SegmentStore)
            else result.assemble()
        )
        state = (
            "CONVERGED" if trace.converged[-1]
            else f"NOT converged ({int(trace.live[-1])} live)"
        )
        print(
            f"resumed soak: {trace.ticks} ticks complete in {wall_ms:.0f}ms "
            f"— {state}, live {int(trace.live[-1])}/{cluster.n}"
        )
        print_final_checksums(cluster)
        if args.trace_out:
            trace.save(args.trace_out)
            print(f"trace ({trace.ticks} ticks x "
                  f"{len(trace.metrics) + 3} series) -> {args.trace_out}")
        return

    backend = args.backend or ("host-sim" if args.sim else "proc")
    has_run = bool(args.scenario or args.incident)
    if has_run and backend != "tpu-sim":
        parser.error("--scenario/--incident need --backend tpu-sim (the "
                     "compiled scenario engine is a tensor-simulation "
                     "feature)")
    if args.incident and args.scenario:
        parser.error("--incident replays a library outage; it does not "
                     "compose with --scenario (the incident IS the spec)")
    if args.incident and args.traffic:
        parser.error("--incident brings its own latency-coupled workload; "
                     "drop --traffic (edit the library builder to vary it)")
    if args.sweep and not has_run:
        parser.error("--sweep needs --scenario/--incident (it replicates a "
                     "compiled scenario, not an interactive session)")
    if args.traffic and not args.scenario:
        parser.error("--traffic needs --scenario (the workload co-runs "
                     "inside the compiled scenario scan)")
    if args.policy:
        if not (args.incident or args.traffic):
            parser.error("--policy meters the serve plane (per-node sends "
                         "+ delivered): pair it with --incident or "
                         "--scenario + --traffic")
        from ringpop_tpu.policies import parse_policy_arg

        try:
            parse_policy_arg(args.policy)
        except ValueError as e:
            parser.error(str(e))
    if args.latency_buckets and not args.traffic:
        parser.error("--latency-buckets needs --traffic (it extends the "
                     "serving workload with the SLO latency plane)")
    if args.trace_rumors and not has_run:
        parser.error("--trace-rumors needs --scenario/--incident (the "
                     "provenance plane records inside a compiled "
                     "scenario run)")
    if args.trace_rumors and args.sweep:
        parser.error("--trace-rumors does not compose with --sweep on the "
                     "CLI (the per-replica reports are a library feature: "
                     "run_sweep + final_nets.pv_*)")
    if args.trace_rumors and args.sparse_cap:
        parser.error("--trace-rumors needs --sparse-cap 0 (the plane "
                     "reads the dense delivery evidence)")
    if args.spans_out and not args.trace_rumors:
        parser.error("--spans-out needs --trace-rumors (it exports the "
                     "provenance plane's report)")
    if args.segment_ticks is not None and not has_run:
        parser.error("--segment-ticks needs --scenario/--incident (it "
                     "segments a compiled scenario run)")
    if args.segment_ticks is not None and args.segment_ticks < 1:
        # the run_scenario plumbing treats a falsy segment_ticks as
        # "unsegmented", which would silently drop --checkpoint
        parser.error("--segment-ticks must be >= 1")
    if (
        (args.checkpoint or args.segment_store)
        and args.segment_ticks is None
        and not args.incident  # incidents stream by default
    ):
        parser.error("--checkpoint/--segment-store need --segment-ticks "
                     "(they are streaming-run options)")
    if args.checkpoint and args.sweep:
        parser.error("--checkpoint does not compose with --sweep "
                     "(sweeps are measurement fan-outs; re-run them)")
    if (args.stats_out or args.profile_dir) and backend != "tpu-sim":
        parser.error("--stats-out/--profile-dir need --backend tpu-sim "
                     "(the obs bridge and profiler scopes instrument the "
                     "tensor simulation; proc nodes inject a statsd "
                     "emitter via RingPop(statsd=...))")
    sweep_scales = sweep_jitter = sweep_fjitter = sweep_paxes = None
    if args.sweep_loss_scales is not None:
        sweep_scales = [float(x) for x in args.sweep_loss_scales.split(",")]
    if args.sweep_kill_jitter is not None:
        sweep_jitter = [int(x) for x in args.sweep_kill_jitter.split(",")]
    if args.sweep_flap_jitter is not None:
        sweep_fjitter = [int(x) for x in args.sweep_flap_jitter.split(",")]
    if args.sweep_param_axes is not None:
        # knob names and per-replica counts are validated host-side by
        # the sweep (before any key draw), with loud errors there —
        # the CLI only splits the grid syntax
        sweep_paxes = {}
        for part in args.sweep_param_axes.split(";"):
            name, sep, vals = part.partition("=")
            if not sep or not vals:
                parser.error("--sweep-param-axes entries look like "
                             "knob=v1,v2,... (semicolon-separated)")
            sweep_paxes[name.strip()] = [
                float(x) if "." in x else int(x) for x in vals.split(",")
            ]
    if backend == "host-sim":
        driver: ClusterDriver = SimCluster(args.size, args.base_port,
                                           seed=args.seed)
    elif backend == "tpu-sim":
        driver = TpuSimCluster(args.size, seed=args.seed, loss=args.loss,
                               sparse_cap=args.sparse_cap, probe=args.probe,
                               damping=args.damping, layout=args.layout,
                               capacity=args.capacity,
                               stats_out=args.stats_out)
    else:
        cluster = ProcCluster(args.size, args.base_port,
                              log_level=args.log_level)
        cluster.wait_healthy(args.startup_timeout_s)
        driver = cluster

    import contextlib

    profile_ctx: Any = contextlib.nullcontext()
    if args.profile_dir:
        from ringpop_tpu.obs.annotate import profile_trace

        profile_ctx = profile_trace(args.profile_dir)
    try:
        with profile_ctx:
            if args.scenario or args.incident:
                driver.run_scenario(
                    args.scenario, args.trace_out, sweep=args.sweep,
                    sweep_loss_scales=sweep_scales,
                    sweep_kill_jitter=sweep_jitter,
                    sweep_flap_jitter=sweep_fjitter,
                    sweep_param_axes=sweep_paxes,
                    traffic=args.traffic,
                    latency_buckets=args.latency_buckets,
                    segment_ticks=args.segment_ticks,
                    checkpoint=args.checkpoint,
                    checkpoint_every=args.checkpoint_every,
                    segment_store=args.segment_store,
                    incident=args.incident,
                    policy=args.policy,
                    trace_rumors=args.trace_rumors,
                    spans_out=args.spans_out,
                )
            elif args.script:
                run_script(driver, args.script)
            else:
                run_interactive(driver)
        if args.profile_dir:
            print(f"profiler trace -> {args.profile_dir}")
    finally:
        driver.shutdown()


if __name__ == "__main__":
    main(sys.argv[1:])

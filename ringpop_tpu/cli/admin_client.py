"""Blocking admin-endpoint client for drivers and operators.

tick-cluster.js drives nodes purely over TChannel ``/admin/*`` requests
(tick-cluster.js:518-551); this is the equivalent: one short-lived TCP
connection per request, speaking the transport's newline-JSON framing
(transport/tcp.py).
"""

from __future__ import annotations

import json
import socket
from typing import Any

from ringpop_tpu.transport.tcp import parse_host_port


class AdminRequestError(Exception):
    pass


def admin_request(
    host_port: str,
    endpoint: str,
    body: Any = None,
    head: Any = None,
    timeout_s: float = 5.0,
    source: str = "admin-client",
) -> Any:
    """Send one request; return the parsed res2 body (or raise)."""
    host, port = parse_host_port(host_port)
    frame = {
        "t": "req",
        "id": 1,
        "ep": endpoint,
        "src": source,
        "head": head,
        "body": json.dumps(body) if body is not None else None,
    }
    with socket.create_connection((host, port), timeout=timeout_s) as sock:
        sock.settimeout(timeout_s)
        sock.sendall(json.dumps(frame).encode() + b"\n")
        buf = b""
        while b"\n" not in buf:
            chunk = sock.recv(65536)
            if not chunk:
                raise AdminRequestError(f"{host_port} closed connection")
            buf += chunk
    response = json.loads(buf.split(b"\n", 1)[0])
    if response.get("err"):
        raise AdminRequestError(
        f"{endpoint} @ {host_port}: {response['err'].get('type')}:"
            f" {response['err'].get('message')}"
        )
    res2 = response.get("res2")
    if isinstance(res2, (str, bytes)) and res2:
        try:
            return json.loads(res2)
        except ValueError:
            return res2
    return res2

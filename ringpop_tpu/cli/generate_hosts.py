"""``generate-hosts`` subcommand (reference: scripts/generate-hosts.js).

Writes a hosts.json containing the cross product
``hosts × [base_port, base_port + num_ports)`` (generate-hosts.js:24-57).
"""

from __future__ import annotations

import argparse
import json
import sys


def generate(hosts: list[str], base_port: int, num_ports: int) -> list[str]:
    return [f"{h}:{base_port + i}" for h in hosts for i in range(num_ports)]


def add_args(parser: argparse.ArgumentParser) -> None:
    parser.add_argument("--hosts", default="127.0.0.1",
                        help="comma-separated host IPs")
    parser.add_argument("--base-port", type=int, default=3000)
    parser.add_argument("--num-ports", "-n", type=int, default=5)
    parser.add_argument("--output", "-o", default="./hosts.json")


def main(argv: list[str] | None = None) -> None:
    parser = argparse.ArgumentParser(prog="ringpop-tpu generate-hosts")
    add_args(parser)
    args = parser.parse_args(argv)
    host_ports = generate(args.hosts.split(","), args.base_port, args.num_ports)
    with open(args.output, "w") as f:
        json.dump(host_ports, f, indent=2)
    print(f"wrote {len(host_ports)} hosts to {args.output}")


if __name__ == "__main__":
    main(sys.argv[1:])

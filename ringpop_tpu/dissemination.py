"""Piggyback rumor buffer with O(log n) dissemination budget.

Reference: lib/dissemination.js.  Each applied membership update is recorded
as a change keyed by member address; every issue (as ping sender or
receiver) bumps its piggyback count, and changes are evicted once issued
more than ``piggyback_factor * ceil(log10(server_count + 1))`` times.  When
a receiver has nothing to piggyback but checksums disagree, it falls back
to a full sync (entire membership as changes).
"""

from __future__ import annotations

import math
from typing import Any, Callable

from ringpop_tpu.utils.events import EventEmitter

DEFAULT_MAX_PIGGYBACK_COUNT = 1
DEFAULT_PIGGYBACK_FACTOR = 15  # lower factor => more full syncs


class Dissemination(EventEmitter):
    def __init__(self, ringpop: Any):
        super().__init__()
        self.ringpop = ringpop
        self.ringpop.on("ringChanged", self.on_ring_changed)
        self.changes: dict[str, dict[str, Any]] = {}
        self.max_piggyback_count = DEFAULT_MAX_PIGGYBACK_COUNT
        self.piggyback_factor = DEFAULT_PIGGYBACK_FACTOR

    def adjust_max_piggyback_count(self) -> None:
        server_count = self.ringpop.ring.get_server_count()
        prev = self.max_piggyback_count
        new = self.piggyback_factor * math.ceil(math.log10(server_count + 1))
        if prev != new:
            self.max_piggyback_count = new
            self.ringpop.stat("gauge", "max-piggyback", new)
            self.ringpop.logger.debug(
                "adjusted max piggyback count",
                {
                    "newPiggybackCount": new,
                    "oldPiggybackCount": prev,
                    "piggybackFactor": self.piggyback_factor,
                    "serverCount": server_count,
                },
            )
            self.emit("maxPiggybackCountAdjusted")

    def clear_changes(self) -> None:
        self.changes = {}

    def full_sync(self) -> list[dict[str, Any]]:
        return [
            {
                "source": self.ringpop.whoami(),
                "address": member.address,
                "status": member.status,
                "incarnationNumber": member.incarnation_number,
            }
            for member in self.ringpop.membership.members
        ]

    def issue_as_sender(self) -> list[dict[str, Any]]:
        return self._issue_as(None, lambda changes: changes)

    def issue_as_receiver(
        self,
        sender_addr: str,
        sender_incarnation_number: int,
        sender_checksum: int,
    ) -> list[dict[str, Any]]:
        def filter_change(change: dict[str, Any]) -> bool:
            # Anti-echo: drop changes the sender itself originated
            # (dissemination.js:91-98).
            return bool(
                sender_addr
                and sender_incarnation_number
                and change.get("source")
                and change.get("sourceIncarnationNumber")
                and sender_addr == change.get("source")
                and sender_incarnation_number == change.get("sourceIncarnationNumber")
            )

        def map_changes(changes: list[dict[str, Any]]) -> list[dict[str, Any]]:
            if changes:
                return changes
            if self.ringpop.membership.checksum != sender_checksum:
                self.ringpop.stat("increment", "full-sync")
                self.ringpop.logger.info(
                    "full sync",
                    {
                        "local": self.ringpop.whoami(),
                        "localChecksum": self.ringpop.membership.checksum,
                        "dest": sender_addr,
                        "destChecksum": sender_checksum,
                    },
                )
                return self.full_sync()
            return []

        return self._issue_as(filter_change, map_changes)

    def _issue_as(
        self,
        filter_change: Callable[[dict[str, Any]], bool] | None,
        map_changes: Callable[[list[dict[str, Any]]], list[dict[str, Any]]],
    ) -> list[dict[str, Any]]:
        issuable: list[dict[str, Any]] = []

        for address in list(self.changes.keys()):
            change = self.changes[address]

            if "piggybackCount" not in change:
                change["piggybackCount"] = 0

            if filter_change is not None and filter_change(change):
                self.ringpop.stat("increment", "filtered-change")
                continue

            # NOTE (as in the reference, dissemination.js:147-151): the count
            # is bumped whether or not delivery succeeds.
            change["piggybackCount"] += 1

            if change["piggybackCount"] > self.max_piggyback_count:
                del self.changes[address]
                continue

            issuable.append(
                {
                    "id": change.get("id"),
                    "source": change.get("source"),
                    "sourceIncarnationNumber": change.get("sourceIncarnationNumber"),
                    "address": change.get("address"),
                    "status": change.get("status"),
                    "incarnationNumber": change.get("incarnationNumber"),
                }
            )

        self.ringpop.stat("gauge", "changes.disseminate", len(issuable))
        return map_changes(issuable)

    def on_ring_changed(self) -> None:
        self.adjust_max_piggyback_count()

    def record_change(self, change: dict[str, Any]) -> None:
        self.changes[change["address"]] = dict(change)

    def reset_max_piggyback_count(self) -> None:
        self.max_piggyback_count = DEFAULT_MAX_PIGGYBACK_COUNT

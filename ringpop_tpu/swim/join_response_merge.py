"""Merge join responses (reference: lib/swim/join-response-merge.js).

If all responses carry the same checksum, take the first member list
verbatim; otherwise fall back to the max-incarnation changeset merge.
"""

from __future__ import annotations

from typing import Any

from ringpop_tpu.changeset_merge import merge_membership_changesets


def _has_same_checksums(join_responses: list[dict[str, Any]]) -> bool:
    last = None
    for response in join_responses:
        checksum = response.get("checksum")
        if not checksum or (last is not None and last != checksum):
            return False
        last = checksum
    return True


def merge_join_responses(
    local_address: str, join_responses: list[dict[str, Any]]
) -> list[dict[str, Any]]:
    if not join_responses:
        return []
    if _has_same_checksums(join_responses):
        return join_responses[0]["members"]
    return merge_membership_changesets(
        local_address, [r["members"] for r in join_responses]
    )

"""Bootstrap join (reference: lib/swim/join-sender.js).

Selects random groups of bootstrap hosts (preferring other physical
hosts), sends ``/protocol/join``, retries rounds with backoff until
``join_size`` nodes have been joined, bounded by attempts and duration.
Responses are merged once at the end and applied to membership.
"""

from __future__ import annotations

from typing import Any, Callable

from ringpop_tpu import errors
from ringpop_tpu.swim.join_response_merge import merge_join_responses
from ringpop_tpu.utils.misc import capture_host, is_empty_array, num_or_default, safe_parse, to_json

JOIN_RETRY_DELAY = 100
JOIN_SIZE = 3
JOIN_TIMEOUT = 1000
# The aim is for a join to take no more than 1s under normal conditions
# (join-sender.js:51-67).
MAX_JOIN_DURATION = 120000
MAX_JOIN_ATTEMPTS = 50
PARALLELISM_FACTOR = 2


def _is_single_node_cluster(ringpop: Any) -> bool:
    hosts = ringpop.bootstrap_hosts
    return isinstance(hosts, list) and len(hosts) == 1 and hosts[0] == ringpop.host_port


class JoinCluster:
    def __init__(
        self,
        ringpop: Any,
        join_size: int | None = None,
        parallelism_factor: float | None = None,
        join_timeout: float | None = None,
        max_join_duration: float | None = None,
        max_join_attempts: int | None = None,
        join_retry_delay: float | None = None,
    ):
        if ringpop is None:
            raise errors.OptionRequiredError("ringpop")
        if is_empty_array(ringpop.bootstrap_hosts) or ringpop.bootstrap_hosts is None:
            raise errors.InvalidOptionError(
                "ringpop", "`bootstrapHosts` is expected to be an array of size 1 or more"
            )

        self.ringpop = ringpop
        self.host = capture_host(ringpop.host_port)
        self.join_timeout = num_or_default(join_timeout, JOIN_TIMEOUT)
        self.parallelism_factor = num_or_default(parallelism_factor, PARALLELISM_FACTOR)
        self.max_join_duration = num_or_default(max_join_duration, MAX_JOIN_DURATION)
        self.max_join_attempts = num_or_default(max_join_attempts, MAX_JOIN_ATTEMPTS)
        self.join_retry_delay = num_or_default(join_retry_delay, JOIN_RETRY_DELAY)

        self.potential_nodes = self.collect_potential_nodes([])
        self.preferred_nodes: list[str] | None = None
        self.non_preferred_nodes: list[str] | None = None

        join_size = int(num_or_default(join_size, JOIN_SIZE))
        self.join_size = min(join_size, len(self.potential_nodes))

        self.round_preferred_nodes: list[str] | None = None
        self.round_non_preferred_nodes: list[str] | None = None

        self.join_responses: list[dict[str, Any]] | None = []
        self.destroyed = False

    def destroy(self) -> None:
        self.destroyed = True

    # -- node selection (join-sender.js:155-197,449-487) --------------------

    def collect_potential_nodes(self, nodes_joined: list[str]) -> list[str]:
        return [
            host
            for host in self.ringpop.bootstrap_hosts
            if host != self.ringpop.host_port and host not in nodes_joined
        ]

    def collect_preferred_nodes(self) -> list[str]:
        """Nodes on other physical hosts."""
        return [h for h in self.potential_nodes if capture_host(h) != self.host]

    def collect_non_preferred_nodes(self) -> list[str]:
        if is_empty_array(self.preferred_nodes):
            return self.potential_nodes
        return [h for h in self.potential_nodes if h not in self.preferred_nodes]

    def init(self, nodes_joined: list[str]) -> None:
        self.potential_nodes = self.collect_potential_nodes(nodes_joined)
        self.preferred_nodes = self.collect_preferred_nodes()
        self.non_preferred_nodes = self.collect_non_preferred_nodes()
        self.round_preferred_nodes = list(self.preferred_nodes)
        self.round_non_preferred_nodes = list(self.non_preferred_nodes)

    def select_group(self, nodes_joined: list[str]) -> list[str]:
        if is_empty_array(self.round_preferred_nodes) and is_empty_array(
            self.round_non_preferred_nodes
        ):
            self.init(nodes_joined)

        preferred = self.round_preferred_nodes
        non_preferred = self.round_non_preferred_nodes
        num_nodes_left = self.join_size - len(nodes_joined)
        group: list[str] = []

        def take_node(hosts: list[str]) -> str:
            index = int(self.ringpop.rng.random() * len(hosts))
            return hosts.pop(index)

        while (
            len(group) != num_nodes_left * self.parallelism_factor
            and len(preferred) + len(non_preferred) > 0
        ):
            if preferred:
                group.append(take_node(preferred))
            elif non_preferred:
                group.append(take_node(non_preferred))

        return group

    # -- join rounds (join-sender.js:199-388) -------------------------------

    def join(self, callback: Callable[..., None]) -> None:
        if self.ringpop.destroyed:
            self.ringpop.clock.call_soon(
                lambda: callback(errors.JoinAbortedError("joiner was destroyed"))
            )
            return

        if _is_single_node_cluster(self.ringpop):
            self.ringpop.logger.info(
                "ringpop received a single node cluster join",
                {"local": self.ringpop.whoami()},
            )
            self.ringpop.clock.call_soon(lambda: callback(None, []))
            return

        nodes_joined: list[str] = []
        state = {"num_joined": 0, "num_failed": 0, "num_groups": 0, "called_back": False}
        start_time = self.ringpop.clock.now()

        def on_join(err: Any, nodes: dict[str, list[str]] | None = None) -> None:
            if state["called_back"]:
                return
            if self.ringpop.destroyed or self.destroyed:
                state["called_back"] = True
                callback(errors.JoinAbortedError("joiner was destroyed"))
                return
            if err:
                state["called_back"] = True
                callback(err)
                return

            nodes_joined.extend(nodes["successes"])
            state["num_joined"] += len(nodes["successes"])
            state["num_failed"] += len(nodes["failures"])
            state["num_groups"] += 1

            if state["num_joined"] >= self.join_size:
                join_time = self.ringpop.clock.now() - start_time
                updates = merge_join_responses(
                    self.ringpop.whoami(), self.join_responses or []
                )
                # Update membership only once, when join completes.
                self.ringpop.membership.update(updates)
                self.join_responses = None
                self.ringpop.stat("timing", "join", join_time)
                self.ringpop.stat("increment", "join.complete")
                state["called_back"] = True
                callback(None, nodes_joined)
            elif state["num_failed"] >= self.max_join_attempts:
                self.ringpop.logger.warn(
                    "ringpop max join attempts exceeded",
                    {"local": self.ringpop.whoami(), "numFailed": state["num_failed"]},
                )
                state["called_back"] = True
                callback(
                    errors.JoinAttemptsExceededError(
                        state["num_failed"], int(self.max_join_attempts)
                    )
                )
            else:
                join_duration = self.ringpop.clock.now() - start_time
                if join_duration > self.max_join_duration:
                    self.ringpop.logger.warn(
                        "ringpop max join duration exceeded",
                        {"local": self.ringpop.whoami(), "joinDuration": join_duration},
                    )
                    state["called_back"] = True
                    callback(
                        errors.JoinDurationExceededError(
                            join_duration, self.max_join_duration
                        )
                    )
                    return
                self.ringpop.clock.call_later(
                    self.join_retry_delay,
                    lambda: self.join_group(nodes_joined, on_join),
                )

        self.join_group(nodes_joined, on_join)

    def join_group(
        self, total_nodes_joined: list[str], callback: Callable[..., None]
    ) -> None:
        group = self.select_group(total_nodes_joined)
        self.ringpop.logger.debug(
            "ringpop selected join group",
            {"local": self.ringpop.whoami(), "group": group},
        )

        nodes_joined: list[str] = []
        nodes_failed: list[str] = []
        num_nodes_left = self.join_size - len(total_nodes_joined)
        state = {"called_back": False}

        if not group:
            # Nothing available to try this round; report an empty group so
            # the round loop applies its duration/attempt limits.
            self.ringpop.clock.call_soon(
                lambda: callback(None, {"successes": [], "failures": []})
            )
            return

        def on_join(err: Any, node: str | None = None) -> None:
            if state["called_back"]:
                return
            if err:
                nodes_failed.append(node)
            else:
                nodes_joined.append(node)
            num_completed = len(nodes_joined) + len(nodes_failed)
            if len(nodes_joined) >= num_nodes_left or num_completed >= len(group):
                state["called_back"] = True
                callback(None, {"successes": nodes_joined, "failures": nodes_failed})

        for node in group:
            self.join_node(node, on_join)

    def join_node(self, node: str, callback: Callable[..., None]) -> None:
        join_body = to_json(
            {
                "app": self.ringpop.app,
                "source": self.ringpop.whoami(),
                "incarnationNumber": self.ringpop.membership.local_member.incarnation_number,
            }
        )

        def on_send(err: Any, res1: Any = None, res2: Any = None) -> None:
            if err:
                return callback(err, node)
            body_obj = safe_parse(res2)
            # join_responses is None once the join completed; late
            # responses are dropped (join-sender.js:432-441).
            if body_obj and self.join_responses is not None:
                self.join_responses.append(
                    {
                        "checksum": body_obj.get("membershipChecksum"),
                        "members": body_obj.get("membership"),
                    }
                )
            callback(None, node)

        self.ringpop.channel.request(
            node, "/protocol/join", None, join_body, self.join_timeout, on_send
        )


def create_joiner(ringpop: Any, **opts: Any) -> JoinCluster:
    return JoinCluster(ringpop, **opts)


def join_cluster(ringpop: Any, callback: Callable[..., None], **opts: Any) -> JoinCluster:
    joiner = create_joiner(ringpop, **opts)
    joiner.join(callback)
    return joiner

"""One SWIM direct probe (reference: lib/swim/ping-sender.js).

Body: ``{checksum, changes, source, sourceIncarnationNumber}`` sent to
``/protocol/ping``; on OK the returned changes are applied to membership.
"""

from __future__ import annotations

from typing import Any, Callable

from ringpop_tpu.utils.misc import safe_parse, to_json


class PingSender:
    def __init__(self, ringpop: Any, member: Any, callback: Callable[..., None]):
        self.ringpop = ringpop
        self.address = getattr(member, "address", None) or member
        self.callback: Callable[..., None] | None = callback

    def send(self) -> None:
        changes = self.ringpop.dissemination.issue_as_sender()
        body = to_json(
            {
                "checksum": self.ringpop.membership.checksum,
                "changes": changes,
                "source": self.ringpop.whoami(),
                "sourceIncarnationNumber": self.ringpop.membership.get_incarnation_number(),
            }
        )
        self.ringpop.debug_log(
            f"ping send member={self.address} changes={to_json(changes)}", "p"
        )
        self.ringpop.channel.request(
            self.address,
            "/protocol/ping",
            None,
            body,
            self.ringpop.ping_timeout,
            self.on_ping,
        )

    def on_ping(self, err: Any, res1: Any = None, res2: Any = None) -> None:
        if err:
            self.ringpop.debug_log(
                f"ping failed member={self.address} err={err}", "p"
            )
            return self.do_callback(False)

        body_obj = safe_parse(res2)
        if body_obj and "changes" in body_obj:
            self.ringpop.membership.update(body_obj["changes"])
            return self.do_callback(True, body_obj)
        self.ringpop.logger.warn(
            f"ping failed member={self.address} bad response body={res2}"
        )
        return self.do_callback(False)

    def do_callback(self, is_ok: bool, body_obj: Any = None) -> None:
        """Single-fire guard (ping-sender.js:46-55)."""
        body_obj = body_obj or {}
        if self.callback is not None:
            cb = self.callback
            self.callback = None
            cb(is_ok, body_obj)


def send_ping(ringpop: Any, target: Any, callback: Callable[..., None]) -> None:
    ringpop.stat("increment", "ping.send")
    PingSender(ringpop, target, callback).send()

"""SWIM protocol engine: gossip loop, probes, indirect probes, joins.

Reference layer: lib/swim/* (gossip.js, suspicion.js, ping-sender.js,
ping-req-sender.js, join-sender.js, join-response-merge.js).
"""

from ringpop_tpu.swim.ping_sender import send_ping
from ringpop_tpu.swim.ping_req_sender import send_ping_req
from ringpop_tpu.swim.join_sender import join_cluster, create_joiner

__all__ = ["send_ping", "send_ping_req", "join_cluster", "create_joiner"]

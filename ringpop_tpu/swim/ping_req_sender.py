"""SWIM indirect probe (reference: lib/swim/ping-req-sender.js).

Fans ``/protocol/ping-req`` out to k random pingable witnesses.  First
witness that reaches the target ends the probe; if every witness responds
but reports the target unreachable, the target is declared suspect; if the
witnesses themselves fail, the probe is inconclusive.
"""

from __future__ import annotations

from typing import Any, Callable

from ringpop_tpu import errors
from ringpop_tpu.utils.misc import safe_parse, to_json


class PingReqSender:
    def __init__(self, ringpop: Any, member: Any, target: Any, callback: Callable[..., None]):
        self.ringpop = ringpop
        self.member = member
        self.target = target
        self.callback = callback

    def send(self) -> None:
        body = to_json(
            {
                "checksum": self.ringpop.membership.checksum,
                "changes": self.ringpop.dissemination.issue_as_sender(),
                "source": self.ringpop.whoami(),
                "sourceIncarnationNumber": self.ringpop.membership.get_incarnation_number(),
                "target": self.target.address,
            }
        )
        self.ringpop.channel.request(
            self.member.address,
            "/protocol/ping-req",
            None,
            body,
            self.ringpop.ping_req_timeout,
            self.on_ping_req,
        )

    def on_ping_req(self, err: Any, res1: Any = None, res2: Any = None) -> None:
        if err:
            self.ringpop.logger.warn(
                "bad response to ping-req",
                {"address": self.member.address, "error": str(err)},
            )
            self.callback(errors.PingReqPingError(str(err)))
            return

        body_obj = safe_parse(res2)
        if not body_obj or "changes" not in body_obj or "pingStatus" not in body_obj:
            self.ringpop.logger.warn(
                "bad response body in ping-req", {"address": self.member.address}
            )
            self.callback(
                errors.BadPingReqRespBodyError(
                    selected=self.member.address,
                    target=self.target.address,
                    body=res2,
                )
            )
            return

        self.ringpop.membership.update(body_obj["changes"])
        self.ringpop.debug_log(
            f"ping-req recv peer={self.member.address} "
            f"target={self.target.address} isOk={body_obj['pingStatus']}",
            "p",
        )

        if not body_obj["pingStatus"]:
            self.callback(
                errors.BadPingReqPingStatusError(
                    selected=self.member.address,
                    target=self.target.address,
                    ping_status=body_obj["pingStatus"],
                )
            )
            return

        self.callback(None)


def send_ping_req(
    ringpop: Any,
    unreachable_member: Any,
    ping_req_size: int,
    callback: Callable[..., None],
) -> None:
    ringpop.stat("increment", "ping-req.send")

    ping_req_members = ringpop.membership.get_random_pingable_members(
        ping_req_size, [unreachable_member.address]
    )
    ringpop.stat("timing", "ping-req.other-members", len(ping_req_members))

    if not ping_req_members:
        callback(errors.NoMembersError())
        return

    addrs = [m.address for m in ping_req_members]
    state = {"called_back": False}
    errs: list[Exception] = []

    def make_handler(ping_req_member: Any) -> Callable[..., None]:
        def on_ping_req(err: Any = None) -> None:
            if state["called_back"]:
                return

            # A reachable target is not explicitly marked alive here; that
            # happens through the piggybacked updates on the ping-req
            # exchange (ping-req-sender.js:201-215).
            if not err:
                state["called_back"] = True
                callback(
                    None,
                    {
                        "pingReqAddrs": addrs,
                        "pingReqSuccess": {"address": ping_req_member.address},
                    },
                )
                return

            errs.append(err)
            if len(errs) < len(ping_req_members):
                return  # keep waiting

            num_status_errs = sum(
                1
                for e in errs
                if getattr(e, "type", None) == "ringpop.ping-req.bad-ping-status"
            )
            if num_status_errs > 0:
                ringpop.logger.warn(
                    "ringpop ping-req determined member is unreachable",
                    {"local": ringpop.whoami(), "target": unreachable_member.address},
                )
                ringpop.membership.make_suspect(
                    unreachable_member.address,
                    unreachable_member.incarnation_number,
                )
                state["called_back"] = True
                callback(None, {"pingReqAddrs": addrs, "pingReqErrs": errs})
            else:
                ringpop.logger.warn(
                    "ringpop ping-req inconclusive due to errors",
                    {"local": ringpop.whoami(), "target": unreachable_member.address},
                )
                state["called_back"] = True
                callback(errors.PingReqInconclusiveError())

        return on_ping_req

    for member in ping_req_members:
        ringpop.debug_log(
            f"ping-req send peer={member.address} target={unreachable_member.address}",
            "p",
        )
        PingReqSender(
            ringpop, member, unreachable_member, make_handler(member)
        ).send()

"""Serving kernels: masked ring lookups + the handle-or-forward chain.

Per-viewer rings never materialize.  The GLOBAL ring — every address's
replica points, sorted by (hash, name-rank) exactly like the host
``HashRing``'s (hash, server) entry order — is one pair of [R] tables,
and a viewer's ring is a boolean mask over servers (its view's
alive|suspect members, membership-update-listener.js:34-45).  Because a
filtered ring is a subsequence of the global sorted table, ``lookup``
on the viewer's ring is: ``searchsorted`` into the global table, then
walk clockwise to the first replica whose owner is in the viewer's
mask.  The walk scans a static ``window`` of successive replicas
(geometrically certain to suffice; ``found=False`` reports the
residue), so a batch of M keys is one [M, W] gather — no sorts, no
per-viewer state.

``serve_tick`` simulates the reference's forwarding fabric on top:
each key arrives at a viewer, resolves through the viewer's masked
ring (lookup), and — when the owner is remote — follows the
handle-or-forward chain (index.js handleOrProxy → request_proxy): the
holder re-resolves through its OWN view, a disagreement forwards again
(``requestProxy.retry.attempted``) up to the retry cap.  Against the
ground-truth ring (the actually-gossiping nodes) this yields per-tick
misroute counts, the forward-hop distribution, and a ring-divergence
gauge — the serving-plane observables during kills/partitions/heals.
"""

from __future__ import annotations

from functools import partial
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ringpop_tpu.models.swim_sim import ALIVE, SUSPECT, _link_delay_bounds
from ringpop_tpu.ops import gossip_remote_copy as _grc
from ringpop_tpu.ops.ring_ops import DeviceRing, lookup_n_idx
from ringpop_tpu.traffic import latency as tlat


class TrafficStatic(NamedTuple):
    """The jit-static facts of a compiled workload (hashable)."""

    m: int  # keys per traffic tick
    max_retries: int  # forward-chain retry cap (request_proxy budget)
    window: int  # masked-walk width over the global ring
    every: int  # serve on ticks where tick % every == 0
    lookup_n: int  # >0: also resolve n-wide preference lists
    # SLO latency plane (traffic/latency.py).  0 = off: the compiled
    # program (and every counter) is bit-identical to the pre-latency
    # engine.  B > 0 accumulates per-request end-to-end latency into a
    # [B] log2-bucket histogram per tick, charges RETRY_SCHEDULE
    # backoff per consumed retry, and makes GRAY holders time out when
    # a send lands off their duty phase (period row) — the retry-storm
    # mechanism.
    latency_buckets: int = 0
    period_ms: int = 200  # tick -> ms conversion for link delays/backoff
    # Per-node send-load accounting (the overload feedback's input,
    # scenarios/faults.OverloadConfig).  0 = off: the compiled program
    # is unchanged.  1 adds an int32[N] ``node_sends`` output — send
    # attempts landing on each node this tick (local handling at the
    # arrival viewer + every forward-chain attempt at its holder,
    # retries included) — which the scenario scan consumes for the
    # pressure update and never stacks into the trace.
    track_load: int = 0
    # Remediation policy plane (ringpop_tpu/policies).  0 = off: the
    # compiled program and the counter schema are unchanged.  1 adds
    # the ``policy_shed`` counter and threads the per-tick policy
    # planes (shed mask, quarantine mask, traced retry cap) through
    # both serve chains; the scenario scan supplies them from the
    # policy carry.
    track_policy: int = 0


class TrafficTensors(NamedTuple):
    """The device-resident half: key pool, sampler, ring tables, PRNG."""

    pool: jax.Array  # uint32[K] pre-hashed key pool
    logits: jax.Array  # float32[K] sampler log-weights
    viewers: jax.Array  # int32[V] arrival nodes
    ring_hashes: jax.Array  # uint32[R] global ring, sorted
    ring_owners: jax.Array  # int32[R] owner per replica
    key: jax.Array  # uint32[2] workload PRNG key


def sample_tick(
    tensors: TrafficTensors, t: jax.Array, m: int
) -> tuple[jax.Array, jax.Array]:
    """(pool index int32[M], viewer int32[M]) for traffic tick ``t`` —
    pure function of (workload key, t): replaying a tick resamples the
    identical batch, on device or host (the oracle's sampling path)."""
    kk, kv = jax.random.split(jax.random.fold_in(tensors.key, t))
    idx = jax.random.categorical(kk, tensors.logits, shape=(m,)).astype(
        jnp.int32
    )
    viewer = tensors.viewers[
        jax.random.randint(kv, (m,), 0, tensors.viewers.shape[0])
    ]
    return idx, viewer


def in_ring_from_rows(rows_key: jax.Array) -> jax.Array:
    """bool in-ring mask from packed view-key rows: alive and suspect
    members are ring members (the host ``ring_for`` filter)."""
    s = rows_key & 7
    return (s == ALIVE) | (s == SUSPECT)


def lookup_masked_idx(
    ring_hashes: jax.Array,
    ring_owners: jax.Array,
    key_hashes: jax.Array,
    in_ring: jax.Array,
    *,
    window: int,
) -> tuple[jax.Array, jax.Array]:
    """Owner per key on a per-key-masked ring.

    ``in_ring`` is bool[M, S]: key m resolves as if the ring contained
    only servers with ``in_ring[m, s]`` — bit-identical to a host
    ``HashRing`` built from exactly that server subset (the filtered
    entries are a subsequence of the global (hash, name-rank) order, so
    the first in-mask replica at or after ``searchsorted`` IS the
    filtered ring's lookup, wraparound included).  Returns
    ``(owner int32[M] — -1 where not found, found bool[M])``;
    ``found[m]`` is False when no in-mask replica fell inside the
    ``window``-wide walk (escalate: larger window, or the host ring).
    """
    r = ring_hashes.shape[0]
    w = min(window, r)
    m = key_hashes.shape[0]
    start = jnp.searchsorted(ring_hashes, key_hashes, side="left")
    offs = (start[:, None] + jnp.arange(w)[None, :]) % r
    owners = ring_owners[offs]  # int32[M, W]
    ok = jnp.take_along_axis(in_ring, owners, axis=1)  # bool[M, W]
    j = jnp.argmax(ok, axis=1)
    found = jnp.any(ok, axis=1)
    owner = owners[jnp.arange(m), j]
    return jnp.where(found, owner, -1).astype(jnp.int32), found


def lookup_n_masked_idx(
    ring_hashes: jax.Array,
    ring_owners: jax.Array,
    key_hashes: jax.Array,
    in_ring: jax.Array,
    n: int,
    *,
    window: int,
) -> tuple[jax.Array, jax.Array]:
    """Preference list per key on a per-key-masked ring: the first
    ``n`` distinct in-mask owners walking clockwise (ring.js:150-182
    lookupN over the viewer's ring) — ``ring_ops.lookup_n_idx`` with
    its ``in_ring`` mask, one copy of the dedup machinery.  Returns
    ``(owners int32[M, n] -1-padded, complete bool[M])``."""
    return lookup_n_idx(
        DeviceRing(hashes=ring_hashes, owners=ring_owners),
        key_hashes,
        n,
        window=window,
        in_ring=in_ring,
    )


def total_sends(metrics: dict) -> int:
    """The retry-amplification NUMERATOR — every send the serve plane
    issued: local handling at the arrival viewer + first proxy sends +
    consumed retries.  One definition shared by the sweep scorecards,
    the incident summaries, and the CLI serving line (host-side trace
    series: sums whole [T] arrays or single-tick rows alike)."""
    sends = (
        int(np.sum(metrics["handled_local"]))
        + int(np.sum(metrics["proxy_sends"]))
        + int(np.sum(metrics["proxy_retries"]))
    )
    if "policy_shed" in metrics:
        # a shed request still landed ONE arrival send on its pressured
        # holder before being rejected — the same unit every other term
        # counts, so amplification stays honest under admission control
        sends += int(np.sum(metrics["policy_shed"]))
    return sends


def counter_names(static: TrafficStatic) -> tuple[str, ...]:
    """The per-tick traffic counter series, in emission order — the
    trace schema for one compiled workload shape."""
    names = [
        "lookups",
        "dropped",
        "handled_local",
        "proxy_sends",
        "proxy_retries",
        "proxy_failed",
        "delivered",
        "misroutes",
        "delivered_misroutes",
        "unresolved",
        "ring_divergence",
    ]
    names += [f"hops{h}" for h in range(static.max_retries + 2)]
    if static.track_policy:
        # requests dropped by admission control at a shedding holder
        # (policies/core.py); rides only policy-armed programs so a
        # policy-off trace keeps the exact legacy schema
        names += ["policy_shed"]
    if static.lookup_n:
        names += ["lookupns", "lookupn_incomplete"]
    if static.latency_buckets:
        # the SLO scalars ride only latency-enabled programs so a
        # latency-off trace keeps the exact legacy schema
        names += ["send_errors", "retry_succeeded", "gray_timeouts",
                  "lat_count", "lat_sum_ms", "lat_max_ms"]
    return tuple(names)


def plane_names(static: TrafficStatic) -> tuple[tuple[str, int], ...]:
    """The per-tick VECTOR series (``(name, width)``) a workload adds to
    the telemetry stacks — the trace-plane schema ([ticks, width] after
    the scan stacks them; scenarios/trace.py carries them as planes)."""
    if static.latency_buckets:
        return (("lat_hist_ms", static.latency_buckets),)
    return ()


def _viewer_rows(mask_all: jax.Array, req_idx: jax.Array) -> jax.Array:
    """``mask_all[req_idx]`` — per-request viewer rows of the [N, N]
    ring mask.  Under an ambient gossip ring the row-sharded membership
    plane resolves the (replicated, request-aligned) viewer ids hop by
    hop instead of being all-gathered — the traffic plane serves from
    sharded membership truth."""
    if _grc.active_ring() is not None:
        return _grc.ring_fetch_global(mask_all, req_idx)
    return mask_all[req_idx]


def _self_in_ring(mask_all: jax.Array) -> jax.Array:
    """The ``mask_all[i, i]`` diagonal (does i's own view hold i) —
    row-local under the gossip ring, so no index tensor replicates."""
    n = mask_all.shape[0]
    ids = jnp.arange(n, dtype=jnp.int32)
    if _grc.active_ring() is not None:
        return _grc.ring_take_per_row(mask_all, ids)
    return mask_all[ids, ids]


def _serve_impl(view_rows, up, responsive, tensors, t, static, damped=None,
                net=None, period=None, policy=None):
    n = view_rows.shape[0]
    ids = jnp.arange(n, dtype=jnp.int32)
    rh, ro = tensors.ring_hashes, tensors.ring_owners
    w = static.window

    mask_all = in_ring_from_rows(view_rows)  # bool[N, N]
    # the gossip predicate (truth ring + served arrivals) is pure
    # liveness — a member damped out of everyone's ring still serves
    # the requests that land on it
    gossip = up & responsive & _self_in_ring(mask_all)  # ground-truth ring
    if damped is not None:
        # damped members are quarantined from the viewer's RING, same
        # as the host ring_for (damping extension)
        mask_all = mask_all & ~damped
    if policy is not None:
        # the policy plane from LAST tick's fold: shed flags (admission
        # control), ring quarantine (steered out of every viewer's ring
        # like damped — liveness truth untouched, so misroutes-vs-truth
        # inflate while a node is steered around), and the traced retry
        # cap the amplification governor set
        po_shed, po_quar, po_cap = policy
        mask_all = mask_all & ~po_quar[None, :]
    kidx, viewer = sample_tick(tensors, t, static.m)
    khash = tensors.pool[kidx]

    # a request landing on a dead/suspended node is dropped, not served
    served = gossip[viewer]
    truth_mask = jnp.broadcast_to(gossip[None, :], (static.m, n))
    truth_owner, truth_found = lookup_masked_idx(
        rh, ro, khash, truth_mask, window=w
    )
    owner0, found0 = lookup_masked_idx(
        rh, ro, khash, _viewer_rows(mask_all, viewer), window=w
    )
    resolved = served & found0
    handled_local = resolved & (owner0 == viewer)
    unresolved = served & ~found0
    shed_req = None
    if policy is not None:
        # admission control: a request whose first resolved holder is
        # shedding is rejected AT ARRIVAL — one landed send on that
        # holder (the rejection still costs its inbox), zero retries,
        # never settled — instead of grinding duty-phase timeouts
        shed_req = resolved & po_shed[jnp.clip(owner0, 0, n - 1)]
        handled_local = handled_local & ~shed_req

    # handle-or-forward chain: a LIVE holder re-resolves through its OWN
    # view, a disagreement forwards again (reroute); a send to a DEAD
    # holder fails and the origin's retry re-resolves the same frozen
    # view — same owner, so the holder stays put and the retry budget
    # drains (request_proxy/send.py's schedule, collapsed to one tick).
    # Trip count max_retries+1: the holder reached by the last allowed
    # retry still gets its settle check.
    active = resolved & ~handled_local
    if shed_req is not None:
        active = active & ~shed_req
    # the retry cap the chains compare against: the static budget, or
    # (policy-armed) its minimum with the traced adaptive cap — the
    # fori trip count stays static, only the comparison moves
    cap = static.max_retries
    if policy is not None:
        cap = jnp.minimum(jnp.int32(static.max_retries), po_cap)
    lat_extras: dict[str, jax.Array] = {}
    track = bool(static.track_load)
    # send attempts landing per node (track_load): the arrival viewer
    # absorbs locally handled requests; each forward-chain iteration
    # below adds its attempt at the holder it targets (dead/off-duty
    # holders included — the send still lands on that node's inbox,
    # which is exactly the load the overload feedback meters).  Shed
    # requests land their ONE rejected arrival on the shedding holder,
    # so admission keeps feeding the pressure meter it is gated on.
    loads = (
        jnp.zeros((n,), jnp.int32).at[viewer].add(
            handled_local.astype(jnp.int32)
        )
        if track
        else None
    )
    if track and shed_req is not None:
        loads = loads.at[jnp.clip(owner0, 0, n - 1)].add(
            shed_req.astype(jnp.int32)
        )
    if not static.latency_buckets:
        carry = (
            jnp.where(active, owner0, viewer),  # current holder
            handled_local,  # settled
            active,
            jnp.where(handled_local, viewer, -1),  # final handler
            jnp.zeros(static.m, dtype=jnp.int32),  # retries consumed
            active.astype(jnp.int32),  # forwards sent (first send counted)
            unresolved,
            loads,
        )

        def hop(_, c):
            h, settled, act, final, retries, forwards, unres, lds = c
            hc = jnp.clip(h, 0, n - 1)
            if track:
                lds = lds.at[hc].add(act.astype(jnp.int32))
            has_retry = retries < cap
            alive_h = gossip[hc]
            retry_dead = act & ~alive_h & has_retry  # failed send, re-sent
            nxt, f = lookup_masked_idx(rh, ro, khash, _viewer_rows(mask_all, hc), window=w)
            done = act & alive_h & f & (nxt == h)
            settled = settled | done
            final = jnp.where(done, h, final)
            unres = unres | (act & alive_h & ~f)
            go = act & alive_h & f & (nxt != h) & has_retry  # reroute
            stepped = (go | retry_dead).astype(jnp.int32)
            retries = retries + stepped
            forwards = forwards + stepped
            h = jnp.where(go, nxt, h)
            return (h, settled, go | retry_dead, final, retries, forwards,
                    unres, lds)

        h, settled, act, final, retries, forwards, unresolved, loads = (
            jax.lax.fori_loop(0, static.max_retries + 1, hop, carry)
        )
    else:
        # -- the SLO latency chain (traffic/latency.py) -------------------
        # Same forward-chain topology as the plain loop (without gray
        # holders or delay rules the retry/settle decisions are
        # identical), plus: per-attempt one-way link latency, the
        # reference RETRY_SCHEDULE backoff per consumed retry, and gray
        # timeouts — a send landing on a gray holder OFF its duty phase
        # (evaluated at the request's backoff-advanced effective tick)
        # fails like a dead send, holds the holder, and drains budget.
        b = static.latency_buckets
        a_max = static.max_retries + 1  # send attempts per request
        kf, kr = jax.random.split(tlat.latency_key(tensors.key, t))
        u_fwd = jax.random.uniform(kf, (a_max, static.m))
        u_ret = jax.random.uniform(kr, (static.m,))
        bo_ms = jnp.asarray(tlat.backoff_ms_schedule(static.max_retries))
        bo_ticks = jnp.asarray(
            tlat.backoff_tick_offsets(static.max_retries, static.period_ms)
        )

        def oneway(src, dst, u):
            """One-way link latency in ms: the active delay rules'
            (base, jitter) maxima at the (src, dst) pair, one uniform
            jitter draw — zero when the run has no delay rules."""
            if net is None or net.link_d is None:
                return jnp.zeros(jnp.shape(u), jnp.int32)
            base, bound = _link_delay_bounds(net, src, dst)
            return tlat.jitter_ms(u, base, bound, static.period_ms)

        lat0 = jnp.where(
            active, oneway(viewer, jnp.clip(owner0, 0, n - 1), u_fwd[0]), 0
        )
        carry = (
            jnp.where(active, owner0, viewer),  # current holder
            handled_local,  # settled (local handling has zero latency)
            active,
            jnp.where(handled_local, viewer, -1),  # final handler
            jnp.zeros(static.m, dtype=jnp.int32),  # retries consumed
            active.astype(jnp.int32),  # forwards sent (first send counted)
            unresolved,
            jnp.where(active, viewer, -1),  # sender of the in-flight attempt
            lat0,  # accumulated latency, ms
            jnp.int32(0),  # gray timeouts (events)
            jnp.int32(0),  # failed send attempts (dead + gray)
            loads,
        )

        def hop_lat(i, c):
            (h, settled, act, final, retries, forwards, unres, sender, lat,
             gray_to, send_err, lds) = c
            hc = jnp.clip(h, 0, n - 1)
            if track:
                lds = lds.at[hc].add(act.astype(jnp.int32))
            has_retry = retries < cap
            alive_h = gossip[hc]
            # effective tick: the serve tick advanced by the backoff the
            # request has already slept through — a gray holder's duty
            # phase is re-evaluated there, so a backed-off retry can
            # land on-duty (the drain path of a gray retry storm)
            te = t + bo_ticks[jnp.clip(retries, 0, static.max_retries)]
            on_duty = tlat.duty_on(hc, te, period)
            serves = act & alive_h & on_duty
            timeout = act & alive_h & ~on_duty
            dead = act & ~alive_h
            gray_to = gray_to + jnp.sum(timeout, dtype=jnp.int32)
            send_err = send_err + jnp.sum(dead | timeout, dtype=jnp.int32)
            nxt, f = lookup_masked_idx(rh, ro, khash, _viewer_rows(mask_all, hc), window=w)
            done = serves & f & (nxt == h)
            settled = settled | done
            final = jnp.where(done, h, final)
            unres = unres | (serves & ~f)
            go = serves & f & (nxt != h) & has_retry  # reroute
            retry_same = (dead | timeout) & has_retry  # frozen view resend
            stepping = go | retry_same
            # the consumed retry: schedule-slot backoff + the new
            # attempt's forward leg (reroutes forward from the holder,
            # same-dest retries resend over the same link, fresh draw)
            bo = bo_ms[jnp.clip(retries, 0, bo_ms.shape[0] - 1)]
            new_sender = jnp.where(go, h, sender)
            new_holder = jnp.where(go, nxt, h)
            fwd = oneway(
                jnp.clip(new_sender, 0, n - 1),
                jnp.clip(new_holder, 0, n - 1),
                u_fwd[jnp.minimum(i + 1, a_max - 1)],
            )
            lat = lat + jnp.where(stepping, bo + fwd, 0)
            stepped = stepping.astype(jnp.int32)
            retries = retries + stepped
            forwards = forwards + stepped
            h = jnp.where(stepping, new_holder, h)
            sender = jnp.where(stepping, new_sender, sender)
            return (h, settled, stepping, final, retries, forwards, unres,
                    sender, lat, gray_to, send_err, lds)

        (h, settled, act, final, retries, forwards, unresolved, sender, lat,
         gray_to, send_err, loads) = jax.lax.fori_loop(
            0, static.max_retries + 1, hop_lat, carry
        )
        # delivered proxied requests pay the return leg from their final
        # handler back to the arrival viewer (one draw per request)
        proxied_done = settled & ~handled_local
        ret = oneway(jnp.clip(final, 0, n - 1), viewer, u_ret)
        lat = jnp.where(proxied_done, lat + ret, lat)
        lat = jnp.where(settled, lat, 0)
        lat_extras = {
            "send_errors": send_err,
            "retry_succeeded": jnp.sum(
                settled & (retries > 0), dtype=jnp.int32
            ),
            "gray_timeouts": gray_to,
            "lat_count": jnp.sum(settled, dtype=jnp.int32),
            "lat_sum_ms": jnp.sum(jnp.where(settled, lat, 0), dtype=jnp.int32),
            "lat_max_ms": jnp.max(jnp.where(settled, lat, 0), initial=0),
            "lat_hist_ms": tlat.bucket_counts(lat, settled, b),
        }

    def count(mask):
        return jnp.sum(mask, dtype=jnp.int32)

    failed = served & ~settled & ~unresolved
    if shed_req is not None:
        failed = failed & ~shed_req
    out = {
        "lookups": count(served),
        "dropped": jnp.int32(static.m) - count(served),
        "handled_local": count(handled_local),
        "proxy_sends": count(active),
        "proxy_retries": jnp.sum(retries, dtype=jnp.int32),
        "proxy_failed": count(failed),
        "delivered": count(settled),
        "misroutes": count(resolved & truth_found & (owner0 != truth_owner)),
        "delivered_misroutes": count(
            settled & truth_found & (final != truth_owner)
        ),
        "unresolved": count(unresolved),
        "ring_divergence": count(
            gossip & jnp.any(mask_all != gossip[None, :], axis=1)
        ),
    }
    for hp in range(static.max_retries + 2):
        out[f"hops{hp}"] = count(settled & (forwards == hp))
    if static.track_policy:
        out["policy_shed"] = (
            count(shed_req) if shed_req is not None else jnp.int32(0)
        )
    if static.lookup_n:
        # the preference walk builds an [M, W, W] dedup cube, so its
        # window uses lookup_n_idx's n-scaled heuristic rather than the
        # single-lookup residue window (256 would cube to GBs at large
        # M); the incomplete residue is counted, not silently padded
        wn = min(w, 32 + 8 * static.lookup_n)
        _, complete = lookup_n_masked_idx(
            rh, ro, khash, _viewer_rows(mask_all, viewer), static.lookup_n, window=wn
        )
        out["lookupns"] = count(served)
        out["lookupn_incomplete"] = count(served & ~complete)
    out.update(lat_extras)
    if track:
        out["node_sends"] = loads
    return out


def _zero_counters(static: TrafficStatic, n: int) -> dict[str, jax.Array]:
    """The off-cadence tick's outputs: scalar zeros per counter plus a
    zero row per histogram plane (shapes must match the served branch)."""
    zeros: dict[str, jax.Array] = {
        k: jnp.int32(0) for k in counter_names(static)
    }
    for name, width in plane_names(static):
        zeros[name] = jnp.zeros((width,), jnp.int32)
    if static.track_load:
        # not a trace series (the scan consumes and pops it), but the
        # cond branches must agree on structure
        zeros["node_sends"] = jnp.zeros((n,), jnp.int32)
    return zeros


def serve_tick(
    view_rows: jax.Array,
    up: jax.Array,
    responsive: jax.Array,
    tensors: TrafficTensors,
    t: jax.Array,
    *,
    static: TrafficStatic,
    damped: jax.Array | None = None,
    net: Any | None = None,
    period: jax.Array | None = None,
    policy: tuple | None = None,
) -> dict[str, jax.Array]:
    """One traffic tick's counters (int32 scalars, ``counter_names``
    schema, plus the ``plane_names`` histogram rows when the latency
    plane is on) against the given membership views.  Traceable:
    composes into the scenario scan (scenarios/runner.py) or jits
    standalone (``serve_once``).

    ``view_rows`` is the int32[N, N] packed view table, or a zero-arg
    callable producing it — pass a callable when the rows are derived
    (the delta backend's O(N^2) ``materialize_rows``): it is traced
    INSIDE the on-cadence branch, so off-cadence ticks
    (``t % every != 0``) report zeros without materializing anything.
    ``damped`` (bool[N, N] or None) quarantines flap-damped members
    from per-viewer rings, matching the host ``ring_for``.

    ``net`` (the tick's ``NetState`` with its ACTIVE link rules) and
    ``period`` (the int32[N] per-node period row, or None) feed the SLO
    latency plane only — with ``static.latency_buckets == 0`` they are
    ignored and the program is exactly the legacy one.

    ``policy`` is the remediation plane from the LAST tick's policy
    fold — ``(shed bool[N], quarantine bool[N], retry_cap i32 scalar)``
    — or None; with ``static.track_policy == 0`` and ``policy=None``
    the program and counter schema are exactly the legacy ones."""
    get_rows = view_rows if callable(view_rows) else (lambda: view_rows)
    if static.every == 1:
        return _serve_impl(
            get_rows(), up, responsive, tensors, t, static, damped,
            net=net, period=period, policy=policy,
        )
    zeros = _zero_counters(static, up.shape[0])
    return jax.lax.cond(
        t % static.every == 0,
        lambda _: _serve_impl(
            get_rows(), up, responsive, tensors, t, static, damped,
            net=net, period=period, policy=policy,
        ),
        lambda _: zeros,
        None,
    )


@partial(jax.jit, static_argnames=("static",))
def serve_once(
    view_rows: jax.Array,
    up: jax.Array,
    responsive: jax.Array,
    tensors: TrafficTensors,
    t: jax.Array,
    *,
    static: TrafficStatic,
    damped: jax.Array | None = None,
    net: Any | None = None,
    period: jax.Array | None = None,
    policy: tuple | None = None,
) -> dict[str, jax.Array]:
    """The standalone jitted entry: ONE dispatch serves one traffic
    tick against a snapshot of membership state (benchmarks, ad-hoc
    serving against a live ``SimCluster``)."""
    return serve_tick(
        view_rows, up, responsive, tensors, t, static=static, damped=damped,
        net=net, period=period, policy=policy,
    )

"""Device-resident traffic plane: compiled key workloads served against
per-viewer hash rings derived from (simulated) membership state.

The reference stack above membership — L2 hashring, L5 request_proxy,
L6 RingPop — resolves one key at a time on the host.  This package is
its data-parallel form: shape-static workload generators producing
pre-hashed key tensors (``workloads``), vmapped masked ring lookups and
the handle-or-forward chain simulation (``engine``), co-run with the
scenario scan (scenarios/runner.py) so lookups happen *under churn* and
an entire chaos experiment plus its traffic is one jitted dispatch.
"""

from ringpop_tpu.traffic.workloads import (  # noqa: F401
    CompiledTraffic,
    WorkloadSpec,
    compile_traffic,
)
from ringpop_tpu.traffic.engine import (  # noqa: F401
    TrafficStatic,
    TrafficTensors,
    counter_names,
    in_ring_from_rows,
    lookup_masked_idx,
    lookup_n_masked_idx,
    plane_names,
    sample_tick,
    serve_once,
    serve_tick,
)
from ringpop_tpu.traffic import latency  # noqa: F401

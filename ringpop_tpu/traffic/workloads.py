"""Shape-static key workloads: seeded, replayable, pre-hashed.

A workload is a distribution over a fixed pool of K distinct keys plus
an arrival policy (which node each request lands on).  Everything the
compiled engine consumes is a fixed-shape tensor: the pool is hashed
ONCE on device (``farmhash32_batch_jax`` over the encoded key strings —
bit-identical to the host ring's farmhash32, so host-ring oracles
resolve the very same keys), and each traffic tick samples ``M`` pool
indices and ``M`` arrival viewers from a PRNG key derived by
``fold_in(workload_key, tick)`` — the same replayable-schedule
discipline as the scenario PRNG (scenarios/compile.key_schedule), and
deliberately a SEPARATE key stream: adding traffic to a scenario must
not perturb the protocol trajectory (pinned in tests/test_traffic.py).

Three kinds:

* ``uniform`` — every pool key equally likely;
* ``zipf`` — pool rank r drawn with p ∝ (r+1)^-s (hot-key skew; s is
  ``zipf_s``);
* ``tenant`` — keys belong round-robin to T tenants, tenant t weighted
  ∝ (t+1)^-s, uniform within a tenant (per-tenant skew: a few tenants
  dominate the traffic while each key stays individually cold).
"""

from __future__ import annotations

import json
import os
from typing import Any, NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ringpop_tpu.ops import ring_ops
from ringpop_tpu.ops.farmhash_jax import farmhash32_batch_jax
from ringpop_tpu.traffic.engine import (
    TrafficStatic,
    TrafficTensors,
    sample_tick,  # noqa: F401  (re-export: the oracle's sampling path)
)

# forward chain cap: the request_proxy's default retry budget
# (request_proxy/send.py RETRY_SCHEDULE has 3 slots, send.js:49)
DEFAULT_MAX_RETRIES = 3

# masked-walk width when the spec leaves it unset: the chance that W
# consecutive global replicas ALL belong to out-of-ring servers decays
# geometrically (dead_fraction^W); 256 puts even a 90%-dead cluster at
# ~2e-12 per key, and the engine still reports the residue (unresolved)
DEFAULT_WINDOW = 256


class WorkloadSpec(NamedTuple):
    """Declarative traffic workload (the serving twin of ScenarioSpec)."""

    kind: str = "uniform"  # uniform | zipf | tenant
    keys_per_tick: int = 256  # M requests per traffic tick
    pool: int = 4096  # K distinct keys ("key-0" .. f"key-{K-1}")
    seed: int = 0  # workload PRNG stream (independent of protocol)
    zipf_s: float = 1.1  # skew exponent (zipf ranks / tenant weights)
    tenants: int = 16  # tenant count (kind="tenant")
    viewers: tuple[int, ...] | None = None  # arrival nodes; None = all
    lookup_n: int = 0  # >0: also resolve n-wide preference lists
    max_retries: int = DEFAULT_MAX_RETRIES  # forward-chain retry cap
    window: int | None = None  # masked-walk width; None = heuristic
    every: int = 1  # serve on ticks where tick % every == 0
    # SLO latency plane (traffic/latency.py): log2 histogram bucket
    # count.  0 (default) = off — the compiled serving program and all
    # its counters are bit-identical to the pre-latency engine.
    latency_buckets: int = 0
    period_ms: int = 200  # protocol period ms (tick->ms for the plane)

    # -- parsing ------------------------------------------------------------

    @classmethod
    def from_spec(cls, spec: Any) -> "WorkloadSpec":
        """Accept a WorkloadSpec, a dict, a JSON file path, or the CLI
        shorthand ``kind:M[:pool]`` (e.g. ``zipf:512``)."""
        if isinstance(spec, cls):
            return spec
        if isinstance(spec, str):
            if os.path.exists(spec) or spec.endswith(".json"):
                with open(spec) as f:
                    spec = json.load(f)
            else:
                parts = spec.split(":")
                out = {"kind": parts[0]}
                if len(parts) > 1:
                    out["keys_per_tick"] = int(parts[1])
                if len(parts) > 2:
                    out["pool"] = int(parts[2])
                spec = out
        if isinstance(spec, dict):
            if "viewers" in spec and spec["viewers"] is not None:
                spec = {**spec, "viewers": tuple(spec["viewers"])}
            return cls(**spec)
        raise TypeError(f"cannot build a WorkloadSpec from {type(spec)}")

    def to_dict(self) -> dict[str, Any]:
        d = self._asdict()
        if d["viewers"] is not None:
            d["viewers"] = list(d["viewers"])
        return d

    def validate(self, n: int) -> "WorkloadSpec":
        if self.kind not in ("uniform", "zipf", "tenant"):
            raise ValueError(f"unknown workload kind {self.kind!r}")
        if self.keys_per_tick < 1:
            raise ValueError("keys_per_tick must be >= 1")
        if self.pool < 1:
            raise ValueError("pool must be >= 1")
        if self.kind == "tenant" and not (1 <= self.tenants <= self.pool):
            raise ValueError("tenants must be in [1, pool]")
        if self.lookup_n < 0 or self.max_retries < 0:
            raise ValueError("lookup_n and max_retries must be >= 0")
        if self.every < 1:
            raise ValueError("every must be >= 1")
        if self.viewers is not None:
            if not self.viewers:
                raise ValueError("viewers must be non-empty when given")
            if any(not (0 <= v < n) for v in self.viewers):
                raise ValueError(f"viewers out of range for n={n}")
        if self.window is not None and self.window < 1:
            raise ValueError("window must be >= 1 when given")
        from ringpop_tpu.traffic.latency import MAX_BUCKETS

        if not 0 <= self.latency_buckets <= MAX_BUCKETS:
            raise ValueError(
                f"latency_buckets must be in [0, {MAX_BUCKETS}] "
                f"(got {self.latency_buckets})"
            )
        if self.latency_buckets and self.latency_buckets < 2:
            raise ValueError("latency_buckets needs >= 2 buckets when on")
        if self.period_ms < 1:
            raise ValueError(f"period_ms must be >= 1 (got {self.period_ms})")
        return self

    # -- the pool (shared with host-side oracles) ---------------------------

    def pool_keys(self) -> list[str]:
        """The K distinct key strings; ``pool_hashes[i]`` is exactly
        ``farmhash32(pool_keys()[i])`` — host ring oracles resolve these."""
        return [f"key-{i}" for i in range(self.pool)]

    def logits(self) -> np.ndarray:
        """float32[K] unnormalized log-probabilities per pool key."""
        k = self.pool
        if self.kind == "uniform":
            return np.zeros(k, dtype=np.float32)
        if self.kind == "zipf":
            return (-self.zipf_s * np.log(np.arange(1, k + 1))).astype(
                np.float32
            )
        # tenant: key i belongs to tenant i % T; tenant weight is zipf
        # over tenants, split uniformly across that tenant's keys
        t = np.arange(k) % self.tenants
        per_tenant = np.bincount(t, minlength=self.tenants).astype(np.float64)
        w = (np.arange(1, self.tenants + 1) ** -self.zipf_s) / per_tenant
        return np.log(w[t]).astype(np.float32)


class CompiledTraffic(NamedTuple):
    """A workload lowered against one cluster's address book: the static
    shape facts (jit-static), the device tensors (pool hashes, sampler
    logits, viewer list, global ring tables, workload key), the spec
    for provenance, and the cluster size it was lowered against
    (viewer indices and ring owners are meaningless on any other)."""

    static: TrafficStatic
    tensors: TrafficTensors
    spec: WorkloadSpec
    n: int


def compile_traffic(
    spec: Any,
    n: int,
    addresses: Sequence[str],
    *,
    ring: ring_ops.DeviceRing | None = None,
) -> CompiledTraffic:
    """Lower a workload spec against a cluster of ``n`` nodes.

    The GLOBAL ring — every address's replica points, sorted — is built
    once (host batched C farmhash; pass a cached ``ring`` to skip the
    rebuild); per-viewer rings never materialize, they are masks over
    this table (engine.lookup_masked_idx).  The key pool is encoded and
    hashed on device in one ``farmhash32_batch_jax`` call.
    """
    spec = WorkloadSpec.from_spec(spec).validate(n)
    if len(addresses) != n:
        raise ValueError("addresses must have length n")
    if ring is None:
        ring = ring_ops.build_ring(addresses)
    bufs, lens = ring_ops.encode_strings(spec.pool_keys())
    pool_hashes = farmhash32_batch_jax(jnp.asarray(bufs), jnp.asarray(lens))
    viewers = (
        np.arange(n, dtype=np.int32)
        if spec.viewers is None
        else np.asarray(spec.viewers, dtype=np.int32)
    )
    window = spec.window if spec.window is not None else DEFAULT_WINDOW
    static = TrafficStatic(
        m=spec.keys_per_tick,
        max_retries=spec.max_retries,
        window=min(window, ring.size),
        every=spec.every,
        lookup_n=spec.lookup_n,
        latency_buckets=spec.latency_buckets,
        period_ms=spec.period_ms,
    )
    tensors = TrafficTensors(
        pool=pool_hashes,
        logits=jnp.asarray(spec.logits()),
        viewers=jnp.asarray(viewers),
        ring_hashes=ring.hashes,
        ring_owners=ring.owners,
        key=jax.random.PRNGKey(spec.seed),
    )
    return CompiledTraffic(static=static, tensors=tensors, spec=spec, n=n)

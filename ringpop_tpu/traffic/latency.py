"""The SLO latency model: request latency, retry backoff, log2 buckets.

The serving engine (``traffic/engine.py``) answers *where* requests
went; this module defines *how long they took*.  A request's
end-to-end latency is accumulated INSIDE the jitted serve chain from
the only latency sources the simulation models:

* **per-link one-way delays** drawn from the failure model's
  delay/jitter rules (``NetState.link_d``/``link_j``,
  scenarios/faults.py): every send attempt from ``a`` to ``b`` adds
  ``period_ms * (base(a, b) + U{0..jitter(a, b)})`` milliseconds, and
  a delivered request adds one return leg from its final handler back
  to the arrival viewer;
* **retry backoff** per the reference request proxy
  (``request_proxy/send.py`` ``RETRY_SCHEDULE`` = 0 / 1 / 3.5 s,
  retries past the schedule reuse its last slot): every consumed retry
  — a reroute, a failed send to a dead holder, or a gray holder's
  timeout — adds its schedule slot in milliseconds, and advances the
  request's *effective tick* by the cumulative backoff (so a retry
  against a gray holder lands on a later duty phase, the mechanism
  that lets retry storms against gray nodes eventually drain).

Latencies are exact int32 milliseconds and land in fixed ``[B]``
log2-bucket counter tensors (bucket 0 holds exactly-zero latency,
bucket b >= 1 holds ``2^(b-1) <= ms < 2^b``, the last bucket is
open-ended) — no per-request host lists, so a million-key tick costs
one [B] row of trace output.  Bucketization is integer compares
against power-of-two edges, which is what makes the compiled
histogram bit-identical to the host-oracle walk (tests/test_latency.py).

The per-tick jitter draws come from their own PRNG stream derived from
the WORKLOAD key (``latency_key``) — like the workload sampler itself,
adding the latency plane can never perturb the protocol trajectory.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ringpop_tpu.request_proxy.send import RETRY_SCHEDULE

# domain-separation tag of the latency PRNG stream (folded into the
# workload key before the tick fold — never collides with sample_tick's
# per-tick stream, which folds the tick directly)
_LATENCY_STREAM_TAG = 0x5A10

# the open-ended top bucket must fit int32 millisecond values
MAX_BUCKETS = 32


def backoff_ms_schedule(max_retries: int) -> np.ndarray:
    """int32[max(max_retries, 1)]: the backoff (ms) charged by retry i
    (0-indexed) — ``RETRY_SCHEDULE`` seconds, last slot repeated for
    retries past the schedule (send.py ``max_retry_timeout``)."""
    slots = max(int(max_retries), 1)
    sched = [
        int(RETRY_SCHEDULE[min(i, len(RETRY_SCHEDULE) - 1)] * 1000)
        for i in range(slots)
    ]
    return np.asarray(sched, dtype=np.int32)


def backoff_tick_offsets(max_retries: int, period_ms: int) -> np.ndarray:
    """int32[max_retries + 1]: a request's effective-tick offset after
    consuming r retries — cumulative backoff milliseconds floored to
    protocol ticks.  Entry 0 (no retry yet) is 0."""
    ms = backoff_ms_schedule(max_retries)
    cum = np.concatenate([[0], np.cumsum(ms)]).astype(np.int64)
    return (cum[: max(int(max_retries), 0) + 1] // max(int(period_ms), 1)).astype(
        np.int32
    )


def bucket_edges_ms(buckets: int) -> np.ndarray:
    """int64[buckets - 1] lower edges of buckets 1.. (bucket 0 is the
    exactly-zero bucket): 1, 2, 4, ... 2^(B-2)."""
    return 2 ** np.arange(int(buckets) - 1, dtype=np.int64)


def bucket_index(ms: Any, buckets: int) -> Any:
    """Bucket per value: 0 for ms <= 0, else ``floor(log2(ms)) + 1``
    clamped to ``buckets - 1`` — computed as integer compares against
    the power-of-two edges (exact on device and host alike)."""
    edges = bucket_edges_ms(buckets).astype(np.int32)
    if isinstance(ms, jax.Array):
        return jnp.sum(
            ms[..., None] >= jnp.asarray(edges), axis=-1, dtype=jnp.int32
        )
    ms = np.asarray(ms, dtype=np.int64)
    return np.sum(ms[..., None] >= edges, axis=-1).astype(np.int32)


def bucket_counts(ms: jax.Array, valid: jax.Array, buckets: int) -> jax.Array:
    """int32[buckets]: histogram of the valid entries' millisecond
    values (one-hot sum — a fixed counter tensor, no host lists)."""
    idx = bucket_index(ms, buckets)
    onehot = (
        idx[:, None] == jnp.arange(int(buckets), dtype=jnp.int32)[None, :]
    ) & valid[:, None]
    return jnp.sum(onehot, axis=0, dtype=jnp.int32)


def latency_key(workload_key: jax.Array, t: jax.Array) -> jax.Array:
    """The tick's latency PRNG key: a stream separated from the
    sampler's ``fold_in(key, t)`` by a domain tag, so enabling the
    plane never changes which keys/viewers a tick samples."""
    return jax.random.fold_in(
        jax.random.fold_in(workload_key, jnp.int32(_LATENCY_STREAM_TAG)), t
    )


def jitter_ms(u: jax.Array, base: jax.Array, bound: jax.Array,
              period_ms: int) -> jax.Array:
    """int32 one-way link latency in ms from a uniform draw ``u`` and
    the (base, jitter-bound) tick maxima of the active delay rules —
    ``swim_sim._message_delay``'s draw arithmetic (float32 multiply,
    floor, clamp), scaled to milliseconds."""
    extra = jnp.minimum(
        (u * (bound + 1).astype(jnp.float32)).astype(jnp.int32), bound
    )
    return (base + extra) * jnp.int32(period_ms)


def duty_on(holder: jax.Array, tick: jax.Array,
            period: jax.Array | None) -> jax.Array:
    """Is the holder on protocol duty at (effective) ``tick``?  Gray
    nodes (period > 1) serve requests only on their duty phase — the
    same affine phase assignment as ``swim_sim._stagger_send_gate`` —
    so a request landing off-phase times out and retries.  ``None``
    period = everyone serves every tick."""
    if period is None:
        return jnp.ones(jnp.shape(holder), dtype=bool)
    per = jnp.maximum(period[holder], 1)
    phase = (holder * jnp.int32(0x9E37 | 1)) % per
    return tick % per == phase


# ---------------------------------------------------------------------------
# host-side histogram readouts (percentiles from log2 buckets)
# ---------------------------------------------------------------------------


def hist_stats(counts: np.ndarray) -> dict[str, float]:
    """Percentile/summary estimates of an aggregated [B] log2-bucket
    histogram, in ``stats.Histogram.print_obj`` key shape.  A bucket's
    representative value is its LOWER edge (0 for bucket 0, else
    2^(b-1)) — a deterministic floor estimate, so p50/p95/p99 answer
    in the same units the buckets were counted in (ms)."""
    counts = np.asarray(counts, dtype=np.int64)
    total = int(counts.sum())
    reps = np.concatenate([[0], bucket_edges_ms(len(counts))])
    if total == 0:
        return {"count": 0, "min": 0.0, "max": 0.0, "sum": 0.0, "mean": 0.0,
                "median": 0.0, "p75": 0.0, "p95": 0.0, "p99": 0.0}
    cum = np.cumsum(counts)

    def pct(p: float) -> float:
        rank = int(np.ceil(p * total))
        return float(reps[int(np.searchsorted(cum, max(rank, 1)))])

    nz = np.flatnonzero(counts)
    est_sum = float((counts * reps).sum())
    return {
        "count": total,
        "min": float(reps[nz[0]]),
        "max": float(reps[nz[-1]]),
        "sum": est_sum,
        "mean": est_sum / total,
        "median": pct(0.5),
        "p75": pct(0.75),
        "p95": pct(0.95),
        "p99": pct(0.99),
    }


def plane_stats(trace: Any, name: str = "lat_hist_ms") -> dict[str, float] | None:
    """``hist_stats`` of a trace plane aggregated over every tick (and
    every replica for a SweepTrace plane), or None when absent."""
    planes = getattr(trace, "planes", None) or {}
    if name not in planes:
        return None
    arr = np.asarray(planes[name], dtype=np.int64)
    return hist_stats(arr.reshape(-1, arr.shape[-1]).sum(axis=0))

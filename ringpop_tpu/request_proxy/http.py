"""Lightweight HTTP-ish request/response shims.

The reference forwards real Node HTTP requests and reconstructs them with
PassThrough + uber-hammock mocks (lib/request-proxy/index.js:189-204).  In
this rebuild the app-facing surface is duck-typed: anything with
``url/method/headers/body`` works as a request; responses collect status,
headers and body and fire a completion callback.
"""

from __future__ import annotations

from typing import Any, Callable


class ProxyRequest:
    def __init__(
        self,
        url: str = "/",
        method: str = "GET",
        headers: dict[str, str] | None = None,
        body: bytes | str = b"",
        http_version: str = "1.1",
    ):
        self.url = url
        self.method = method
        self.headers = headers or {}
        self.body = body if isinstance(body, (bytes, str)) else b""
        self.http_version = http_version


class ProxyResponse:
    """Collects a response; calls ``on_complete(err, self)`` on end()."""

    def __init__(self, on_complete: Callable[[Any, "ProxyResponse"], None] | None = None):
        self.status_code = 200
        self.headers: dict[str, str] = {}
        self.body: Any = None
        self.ended = False
        self._on_complete = on_complete

    def set_header(self, key: str, value: str) -> None:
        self.headers[key] = value

    def end(self, body: Any = None) -> None:
        if self.ended:
            return
        self.ended = True
        self.body = body
        if self._on_complete is not None:
            self._on_complete(None, self)

    def error(self, err: Any) -> None:
        if self.ended:
            return
        self.ended = True
        if self._on_complete is not None:
            self._on_complete(err, self)

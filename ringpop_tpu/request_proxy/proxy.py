"""Forwarding middleware (reference: lib/request-proxy/index.js).

Sender side: ``proxy_req`` ships the request to the key owner with retries.
Receiver side: ``handle_request`` enforces ring-checksum consistency and
re-emits the request locally as a ``request`` event.
"""

from __future__ import annotations

from typing import Any, Callable

from ringpop_tpu import errors
from ringpop_tpu.request_proxy.http import ProxyRequest, ProxyResponse
from ringpop_tpu.request_proxy.send import send_request
from ringpop_tpu.utils.misc import num_or_default, safe_parse, to_json


class RequestProxy:
    def __init__(
        self,
        ringpop: Any,
        max_retries: int | None = None,
        retry_schedule: list[float] | None = None,
        enforce_consistency: bool | None = None,
    ):
        self.ringpop = ringpop
        self.max_retries = max_retries
        self.retry_schedule = retry_schedule
        self.enforce_consistency = (
            True if enforce_consistency is None else enforce_consistency
        )
        self.sends: list[Any] = []

    def destroy(self) -> None:
        for send in self.sends:
            send.destroy()
        self.sends = []

    def remove_send(self, send: Any) -> None:
        if send in self.sends:
            self.sends.remove(send)
        send.destroy()

    # -- sender side (index.js:74-162) --------------------------------------

    def proxy_req(self, opts: dict[str, Any]) -> None:
        keys = opts["keys"]
        dest = opts["dest"]
        req = opts["req"]
        res = opts["res"]
        endpoint = opts.get("endpoint", "/proxy/req")
        timeout = opts.get("timeout") or self.ringpop.proxy_req_timeout

        raw_body = getattr(req, "body", b"")

        def on_proxy(err: Any, res1: Any = None, res2: Any = None) -> None:
            self.remove_send(send)
            if err:
                self.ringpop.stat("increment", "requestProxy.send.error")
                self.ringpop.logger.warn(
                    "requestProxy got error from channel",
                    {"error": str(err), "url": getattr(req, "url", None)},
                )
                return _send_error(res, err)
            self.ringpop.stat("increment", "requestProxy.send.success")
            response_head = safe_parse(res1) or {}
            for key, value in (response_head.get("headers") or {}).items():
                res.set_header(key, value)
            res.status_code = response_head.get("statusCode", 200)
            res.end(res2)

        send = send_request(
            self.ringpop,
            self,
            keys,
            {"host": dest, "timeout": timeout, "endpoint": endpoint},
            {"obj": req, "body": raw_body},
            {
                "max": num_or_default(opts.get("maxRetries"), self.max_retries)
                if opts.get("maxRetries") is not None or self.max_retries is not None
                else None,
                "schedule": opts.get("retrySchedule") or self.retry_schedule,
            },
            on_proxy,
        )
        self.sends.append(send)

    # -- receiver side (index.js:164-227) -----------------------------------

    def handle_request(
        self, head: dict[str, Any], body: Any, cb: Callable[..., None]
    ) -> None:
        ringpop = self.ringpop
        checksum = head.get("ringpopChecksum")

        if checksum != ringpop.ring.checksum:
            err = errors.InvalidCheckSumError(
                expected=ringpop.ring.checksum, actual=checksum
            )
            ringpop.logger.warn(
                "handleRequest got invalid checksum",
                {"url": head.get("url"), "enforceConsistency": self.enforce_consistency},
            )
            ringpop.emit("requestProxy.checksumsDiffer")
            ringpop.stat("increment", "requestProxy.checksumsDiffer")
            if self.enforce_consistency:
                return cb(err)

        http_request = ProxyRequest(
            url=head.get("url"),
            method=head.get("method"),
            headers=head.get("headers"),
            body=body,
            http_version=head.get("httpVersion", "1.1"),
        )

        def on_response(err: Any, resp: ProxyResponse) -> None:
            if err:
                ringpop.logger.warn(
                    "handleRequest got response error",
                    {"error": str(err), "url": head.get("url")},
                )
                return cb(err)
            response_head = to_json(
                {"statusCode": resp.status_code, "headers": resp.headers}
            )
            cb(None, response_head, resp.body)

        http_response = ProxyResponse(on_response)
        ringpop.emit("request", http_request, http_response, head)


def _send_error(res: Any, err: Any) -> None:
    res.status_code = getattr(err, "statusCode", None) or 500
    res.end(str(err))

"""Single proxied request with retry/reroute (reference: lib/request-proxy/send.js).

Retry schedule defaults to [0, 1, 3.5] seconds.  Before each retry the keys
are re-looked-up: if destinations diverged to more than one node the retry
aborts; if the destination moved, the request reroutes (including a local
loopback to handle_request when the key now belongs to this node).
"""

from __future__ import annotations

from typing import Any, Callable

from ringpop_tpu import errors
from ringpop_tpu.request_proxy.head import raw_head, str_head
from ringpop_tpu.utils.misc import num_or_default

RETRY_SCHEDULE = [0, 1, 3.5]  # seconds (send.js:49)


class RequestProxySend:
    def __init__(
        self,
        ringpop: Any,
        request_proxy: Any,
        keys: list[str],
        channel_opts: dict[str, Any],
        request: dict[str, Any],
        retries: dict[str, Any],
    ):
        self.ringpop = ringpop
        self.request_proxy = request_proxy
        self.keys = keys
        self.channel_opts = channel_opts
        self.request = request
        self.retry_schedule = retries.get("schedule") or RETRY_SCHEDULE
        self.max_retries = int(num_or_default(retries.get("max"), len(self.retry_schedule)))
        self.max_retry_timeout = self.retry_schedule[-1] * 1000
        self.destinations = [channel_opts["host"]]
        self.errors: list[Exception] = []
        self.num_retries = 0
        self.timeout_timer = None

    def destroy(self) -> None:
        self.ringpop.clock.cancel(self.timeout_timer)

    def get_raw_head(self) -> dict[str, Any]:
        return raw_head(self.request["obj"], self.ringpop.ring.checksum, self.keys)

    def get_str_head(self) -> str:
        return str_head(self.request["obj"], self.ringpop.ring.checksum, self.keys)

    def lookup_keys(self) -> list[str]:
        dests: dict[str, bool] = {}
        for key in self.keys:
            dests[self.ringpop.lookup(key)] = True
        return list(dests.keys())

    def send(self, channel_opts: dict[str, Any], callback: Callable[..., None]) -> None:
        if self.ringpop.channel.destroyed:
            self.ringpop.clock.call_soon(
                lambda: callback(errors.ChannelDestroyedError())
            )
            return

        def on_send(err: Any, res1: Any = None, res2: Any = None) -> None:
            if self.max_retries == 0:
                callback(err, res1 if not err else None, res2 if not err else None)
                return
            if not err:
                self._handle_success(res1, res2, callback)
                return
            self.errors.append(err)
            if self.num_retries >= self.max_retries:
                self._handle_max_retries_exceeded(callback)
                return
            self._schedule_retry(callback)

        self.ringpop.channel.request(
            channel_opts["host"],
            channel_opts.get("endpoint", "/proxy/req"),
            self.get_str_head(),
            self.request["body"],
            channel_opts.get("timeout", 5000),
            on_send,
        )
        self.ringpop.emit("requestProxy.requestProxied")

    def _handle_success(self, res1: Any, res2: Any, callback: Callable[..., None]) -> None:
        if self.num_retries > 0:
            self.ringpop.stat("increment", "requestProxy.retry.succeeded")
            self.ringpop.emit("requestProxy.retrySucceeded")
        callback(None, res1, res2)

    def _handle_max_retries_exceeded(self, callback: Callable[..., None]) -> None:
        self.ringpop.stat("increment", "requestProxy.retry.failed")
        self.ringpop.emit("requestProxy.retryFailed")
        callback(errors.MaxRetriesExceededError(self.max_retries))

    def _schedule_retry(self, callback: Callable[..., None]) -> None:
        if self.num_retries < len(self.retry_schedule):
            delay = self.retry_schedule[self.num_retries] * 1000
        else:
            delay = self.max_retry_timeout
        self.timeout_timer = self.ringpop.clock.call_later(
            delay, lambda: self._attempt_retry(callback)
        )
        self.ringpop.emit("requestProxy.retryScheduled")

    def _attempt_retry(self, callback: Callable[..., None]) -> None:
        self.num_retries += 1
        dests = self.lookup_keys()
        if len(dests) > 1:
            self._abort_on_key_divergence(dests, callback)
            return
        self.ringpop.stat("increment", "requestProxy.retry.attempted")
        self.ringpop.emit("requestProxy.retryAttempted")
        new_dest = dests[0]
        if new_dest == self.channel_opts["host"]:
            self.send(self.channel_opts, callback)
            return
        self._reroute_retry(new_dest, callback)

    def _abort_on_key_divergence(self, dests: list[str], callback: Callable[..., None]) -> None:
        self.ringpop.stat("increment", "requestProxy.retry.aborted")
        self.ringpop.emit("requestProxy.retryAborted")
        callback(errors.KeysDivergedError(keys=self.keys))

    def _reroute_retry(self, new_dest: str, callback: Callable[..., None]) -> None:
        self.destinations.append(new_dest)
        self.ringpop.emit("requestProxy.retryRerouted", self.channel_opts["host"], new_dest)
        if new_dest == self.ringpop.whoami():
            self.ringpop.stat("increment", "requestProxy.retry.reroute.local")
            self.request_proxy.handle_request(
                self.get_raw_head(), self.request["body"], callback
            )
            return
        self.ringpop.stat("increment", "requestProxy.retry.reroute.remote")
        self.send(
            {
                "host": new_dest,
                "timeout": self.channel_opts.get("timeout", 5000),
                "endpoint": self.channel_opts.get("endpoint", "/proxy/req"),
            },
            callback,
        )


def send_request(
    ringpop: Any,
    request_proxy: Any,
    keys: list[str],
    channel_opts: dict[str, Any],
    request: dict[str, Any],
    retries: dict[str, Any],
    callback: Callable[..., None],
) -> RequestProxySend:
    sender = RequestProxySend(
        ringpop, request_proxy, keys, channel_opts, request, retries
    )
    sender.send(channel_opts, callback)
    return sender

"""Forwarded-request envelope codec (reference: lib/request-proxy/util.js)."""

from __future__ import annotations

from typing import Any

from ringpop_tpu.utils.misc import to_json


def raw_head(req: Any, checksum: int | None, keys: list[str]) -> dict[str, Any]:
    return {
        "url": getattr(req, "url", None),
        "headers": getattr(req, "headers", None),
        "method": getattr(req, "method", None),
        "httpVersion": getattr(req, "http_version", "1.1"),
        "ringpopChecksum": checksum,
        "ringpopKeys": keys,
    }


def str_head(req: Any, checksum: int | None, keys: list[str]) -> str:
    return to_json(raw_head(req, checksum, keys))

"""Request forwarding ("handle-or-forward") — reference: lib/request-proxy/."""

from ringpop_tpu.request_proxy.proxy import RequestProxy
from ringpop_tpu.request_proxy.head import raw_head, str_head
from ringpop_tpu.request_proxy.http import ProxyRequest, ProxyResponse

__all__ = ["RequestProxy", "raw_head", "str_head", "ProxyRequest", "ProxyResponse"]

"""Endpoint table + protocol/admin handlers.

Reference: server/index.js (14 endpoints) plus server/{join,ping,ping-req,
admin-join,admin-leave,admin-lookup,proxy-req}-handler.js.  Handlers take
``(head, body, host_info, respond)`` where respond(err, res1, res2) mirrors
sendNotOk/sendOk.
"""

from __future__ import annotations

from typing import Any, Callable

from ringpop_tpu import errors
from ringpop_tpu.swim.join_sender import join_cluster
from ringpop_tpu.swim.ping_sender import send_ping
from ringpop_tpu.utils.misc import safe_parse, to_json

Respond = Callable[..., None]


class RingpopServer:
    """Registers all endpoints on the node's channel (server/index.js:32-75)."""

    COMMANDS = {
        "/health": "health",
        "/admin/stats": "admin_stats",
        "/admin/ledger": "admin_ledger",
        "/admin/debugSet": "admin_debug_set",
        "/admin/debugClear": "admin_debug_clear",
        "/admin/gossip": "admin_gossip",
        "/admin/leave": "admin_leave",
        "/admin/lookup": "admin_lookup",
        "/admin/join": "admin_join",
        "/admin/reload": "admin_reload",
        "/admin/tick": "admin_tick",
        "/protocol/join": "protocol_join",
        "/protocol/ping": "protocol_ping",
        "/protocol/ping-req": "protocol_ping_req",
        "/proxy/req": "proxy_req",
    }

    def __init__(self, ringpop: Any, channel: Any):
        self.ringpop = ringpop
        self.channel = channel
        endpoints = {
            url: getattr(self, method) for url, method in self.COMMANDS.items()
        }
        channel.register(endpoints)

    # -- basic --------------------------------------------------------------

    def health(self, head: Any, body: Any, host_info: str, cb: Respond) -> None:
        cb(None, None, "ok")

    def admin_stats(self, head: Any, body: Any, host_info: str, cb: Respond) -> None:
        cb(None, None, to_json(self.ringpop.get_stats()))

    def admin_ledger(self, head: Any, body: Any, host_info: str, cb: Respond) -> None:
        """Dispatch-ledger summary of this process (obs/ledger.py) — an
        extension endpoint: per-program compile/execute aggregates and
        peak bytes for any jitted work the node has run (empty when the
        ledger is disabled or the process never dispatched)."""
        from ringpop_tpu.obs.ledger import default_ledger

        ledger = default_ledger()
        cb(
            None,
            None,
            to_json(
                {
                    "enabled": ledger.enabled,
                    "path": ledger.path,
                    "dispatches": len(ledger.rows),
                    "summary": ledger.summary(),
                }
            ),
        )

    def admin_debug_set(self, head: Any, body: Any, host_info: str, cb: Respond) -> None:
        parsed = safe_parse(body)
        if parsed and parsed.get("debugFlag"):
            self.ringpop.set_debug_flag(parsed["debugFlag"])
        cb(None, None, "ok")

    def admin_debug_clear(self, head: Any, body: Any, host_info: str, cb: Respond) -> None:
        self.ringpop.clear_debug_flags()
        cb(None, None, "ok")

    def admin_gossip(self, head: Any, body: Any, host_info: str, cb: Respond) -> None:
        self.ringpop.gossip.start()
        cb(None, None, "ok")

    def admin_lookup(self, head: Any, body: Any, host_info: str, cb: Respond) -> None:
        key = body if isinstance(body, str) else (body or b"").decode()
        cb(None, None, to_json({"dest": self.ringpop.lookup(key)}))

    def admin_reload(self, head: Any, body: Any, host_info: str, cb: Respond) -> None:
        parsed = safe_parse(body)
        if parsed and parsed.get("file"):
            self.ringpop.reload(parsed["file"], lambda err=None: cb(err))
        else:
            cb(None)

    def admin_tick(self, head: Any, body: Any, host_info: str, cb: Respond) -> None:
        self.ringpop.handle_tick(lambda err, resp: cb(err, None, resp))

    # -- admin join/leave (server/admin-{join,leave}-handler.js) ------------

    def admin_join(self, head: Any, body: Any, host_info: str, cb: Respond) -> None:
        ringpop = self.ringpop
        if ringpop.membership.local_member is None:
            ringpop.clock.call_soon(lambda: cb(errors.InvalidLocalMemberError()))
            return
        if ringpop.membership.local_member.status == "leave":
            # Rejoin after leave: re-assert alive, restart gossip, reenable
            # suspicion (admin-join-handler.js:36-45).
            ringpop.membership.make_alive(ringpop.whoami(), int(ringpop.clock.now()))
            ringpop.gossip.start()
            ringpop.suspicion.reenable()
            cb(None, None, "rejoined")
            return

        def on_join(err: Any, candidate_hosts: Any = None) -> None:
            if err:
                return cb(err)
            cb(None, None, to_json({"candidateHosts": candidate_hosts}))

        join_cluster(
            ringpop,
            on_join,
            max_join_duration=ringpop.max_join_duration,
            join_size=ringpop.join_size,
        )

    def admin_leave(self, head: Any, body: Any, host_info: str, cb: Respond) -> None:
        ringpop = self.ringpop
        if ringpop.membership.local_member is None:
            ringpop.clock.call_soon(lambda: cb(errors.InvalidLocalMemberError()))
            return
        if ringpop.membership.local_member.status == "leave":
            ringpop.clock.call_soon(lambda: cb(errors.RedundantLeaveError()))
            return
        ringpop.membership.make_leave(
            ringpop.whoami(), ringpop.membership.local_member.incarnation_number
        )
        ringpop.gossip.stop()
        ringpop.suspicion.stop_all()
        ringpop.clock.call_soon(lambda: cb(None, None, "ok"))

    # -- protocol (server/{join,ping,ping-req}-handler.js) ------------------

    def protocol_join(self, head: Any, body: Any, host_info: str, cb: Respond) -> None:
        parsed = safe_parse(body)
        if parsed is None:
            return cb(Exception("need JSON req body with source and incarnationNumber"))
        app = parsed.get("app")
        source = parsed.get("source")
        incarnation_number = parsed.get("incarnationNumber")
        if app is None or source is None or incarnation_number is None:
            return cb(Exception("need req body with app, source and incarnationNumber"))

        ringpop = self.ringpop
        ringpop.stat("increment", "join.recv")
        # Validations (server/join-handler.js:44-74)
        if ringpop.is_denying_joins:
            return cb(errors.DenyJoinError())
        if source == ringpop.whoami():
            return cb(errors.InvalidJoinSourceError(actual=source))
        if app != ringpop.app:
            return cb(errors.InvalidJoinAppError(expected=ringpop.app, actual=app))

        ringpop.server_rate.mark()
        ringpop.total_rate.mark()
        ringpop.membership.make_alive(source, incarnation_number)
        cb(
            None,
            None,
            to_json(
                {
                    "app": ringpop.app,
                    "coordinator": ringpop.whoami(),
                    "membership": ringpop.dissemination.full_sync(),
                    "membershipChecksum": ringpop.membership.checksum,
                }
            ),
        )

    def protocol_ping(self, head: Any, body: Any, host_info: str, cb: Respond) -> None:
        parsed = safe_parse(body)
        if (
            parsed is None
            or not parsed.get("source")
            or parsed.get("changes") is None
            or not parsed.get("checksum")
        ):
            return cb(Exception("need req body with source, changes, and checksum"))

        ringpop = self.ringpop
        ringpop.stat("increment", "ping.recv")
        ringpop.server_rate.mark()
        ringpop.total_rate.mark()
        ringpop.membership.update(parsed["changes"])
        cb(
            None,
            None,
            to_json(
                {
                    "changes": ringpop.dissemination.issue_as_receiver(
                        parsed["source"],
                        parsed.get("sourceIncarnationNumber"),
                        parsed["checksum"],
                    )
                }
            ),
        )

    def protocol_ping_req(self, head: Any, body: Any, host_info: str, cb: Respond) -> None:
        parsed = safe_parse(body)
        if (
            parsed is None
            or not parsed.get("source")
            or not parsed.get("target")
            or parsed.get("changes") is None
            or not parsed.get("checksum")
        ):
            return cb(Exception("need req body with source, target, changes, and checksum"))

        ringpop = self.ringpop
        ringpop.stat("increment", "ping-req.recv")
        source = parsed["source"]
        source_inc = parsed.get("sourceIncarnationNumber")
        target = parsed["target"]
        ringpop.server_rate.mark()
        ringpop.total_rate.mark()
        ringpop.membership.update(parsed["changes"])
        ringpop.debug_log(f"ping-req send ping source={source} target={target}", "p")

        def on_ping(is_ok: bool, ping_body: Any) -> None:
            ringpop.debug_log(
                f"ping-req recv ping source={source} target={target} isOk={is_ok}", "p"
            )
            if is_ok:
                ringpop.membership.update(ping_body.get("changes", []))
            cb(
                None,
                None,
                to_json(
                    {
                        "changes": ringpop.dissemination.issue_as_receiver(
                            source, source_inc, parsed["checksum"]
                        ),
                        "pingStatus": is_ok,
                        "target": target,
                    }
                ),
            )

        send_ping(ringpop, target, on_ping)

    # -- forwarding (server/proxy-req-handler.js) ---------------------------

    def proxy_req(self, head: Any, body: Any, host_info: str, cb: Respond) -> None:
        header = safe_parse(head)
        if header is None:
            return cb(Exception("need header to exist"))
        self.ringpop.request_proxy.handle_request(header, body, cb)


def create_server(ringpop: Any, channel: Any) -> RingpopServer:
    return RingpopServer(ringpop, channel)

"""``python -m ringpop_tpu`` — CLI dispatcher.

Subcommands (reference §2.2: main.js, scripts/tick-cluster.js,
scripts/generate-hosts.js):

  worker          run one node (main.js parity)
  tick-cluster    multi-node harness & fault injector
  generate-hosts  write a hosts.json
  obs-ledger      summarize a dispatch-ledger .jsonl (obs/ledger.py)
  audit           trace-contract auditor: machine-check the compiled
                  programs' invariants (analysis/; --fail-on gating)
"""

from __future__ import annotations

import sys


def main() -> None:
    argv = sys.argv[1:]
    command = argv[0] if argv else None
    rest = argv[1:]
    if command == "worker":
        from ringpop_tpu.cli.main import main as worker_main

        worker_main(rest)
    elif command == "tick-cluster":
        from ringpop_tpu.cli.tick_cluster import main as tick_main

        tick_main(rest)
    elif command == "generate-hosts":
        from ringpop_tpu.cli.generate_hosts import main as hosts_main

        hosts_main(rest)
    elif command == "obs-ledger":
        from ringpop_tpu.obs.ledger import main as ledger_main

        ledger_main(rest)
    elif command == "audit":
        from ringpop_tpu.analysis.cli import main as audit_main

        audit_main(rest)
    else:
        print(__doc__)
        sys.exit(0 if command in (None, "-h", "--help") else 1)


if __name__ == "__main__":
    main()

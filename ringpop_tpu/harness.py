"""In-process multi-node cluster harness.

Mirrors the reference's two harness shapes (SURVEY §4):
* ``test_ringpop`` — one real RingPop with no channel, forced ready
  (test/lib/test-ringpop.js:25-64);
* ``Cluster`` — N real RingPops in one process wired through the
  deterministic in-process transport, with a pre-bootstrap ``tap`` hook for
  sabotage (test/lib/test-ringpop-cluster.js:122-138) and tick-cluster's
  fault injection (kill/suspend/revive/partition) as first-class methods.

Because time is virtual, "wait for convergence" is ``run_until_converged``:
advance the shared scheduler until all nodes report one membership checksum.
"""

from __future__ import annotations

import random
from typing import Any, Callable

from ringpop_tpu.clock import SimScheduler
from ringpop_tpu.ringpop import RingPop
from ringpop_tpu.transport.inproc import InProcessChannel, InProcessNetwork


def test_ringpop(
    app: str = "test",
    host_port: str = "127.0.0.1:3000",
    make_alive: bool = True,
    clock: SimScheduler | None = None,
    seed: int = 1,
    **opts: Any,
) -> RingPop:
    """A single ready RingPop with no channel (unit-test fixture)."""
    clock = clock or SimScheduler()
    rp = RingPop(
        app=app, host_port=host_port, clock=clock, rng=random.Random(seed), **opts
    )
    rp.is_ready = True
    if make_alive:
        rp.membership.make_alive(rp.whoami(), int(clock.now()))
    return rp


class Cluster:
    def __init__(
        self,
        size: int = 3,
        app: str = "test",
        base_port: int = 10000,
        host: str = "127.0.0.1",
        latency_ms: float = 1.0,
        seed: int = 1,
        tap: Callable[[list[RingPop]], None] | None = None,
        **node_opts: Any,
    ):
        self.scheduler = SimScheduler()
        self.rng = random.Random(seed)
        self.network = InProcessNetwork(
            self.scheduler, latency_ms=latency_ms, rng=random.Random(seed + 1)
        )
        self.host_ports = [f"{host}:{base_port + i}" for i in range(size)]
        self.nodes: list[RingPop] = []
        for i, host_port in enumerate(self.host_ports):
            channel = InProcessChannel(self.network, host_port)
            node = RingPop(
                app=app,
                host_port=host_port,
                channel=channel,
                clock=self.scheduler,
                rng=random.Random(seed + 100 + i),
                **node_opts,
            )
            node.setup_channel()
            self.nodes.append(node)
        if tap is not None:
            tap(self.nodes)

    # -- lifecycle ----------------------------------------------------------

    def bootstrap_all(
        self, run: bool = True, max_ms: float = 60000
    ) -> list[Any]:
        results: list[Any] = [None] * len(self.nodes)

        for i, node in enumerate(self.nodes):
            def on_bootstrap(err: Any, nodes_joined: Any = None, i: int = i) -> None:
                results[i] = err or (nodes_joined if nodes_joined is not None else [])

            node.bootstrap(list(self.host_ports), on_bootstrap)

        if run:
            self.run(max_ms)
        return results

    def destroy_all(self) -> None:
        for node in self.nodes:
            if not node.destroyed:
                node.destroy()

    # -- time control --------------------------------------------------------

    def run(self, ms: float) -> None:
        self.scheduler.advance(ms)

    def run_until_converged(
        self, max_ms: float = 120000, step_ms: float = 200
    ) -> bool:
        elapsed = 0.0
        while elapsed < max_ms:
            if self.is_converged():
                return True
            self.run(step_ms)
            elapsed += step_ms
        return self.is_converged()

    # -- convergence (tick-cluster.js:88-115) --------------------------------

    def live_nodes(self) -> list[RingPop]:
        return [
            n
            for n in self.nodes
            if not n.destroyed
            and n.host_port not in self.network.killed
            and n.host_port not in self.network.paused
        ]

    def checksums(self) -> dict[str, int | None]:
        return {n.host_port: n.membership.checksum for n in self.live_nodes()}

    def checksum_groups(self) -> dict[int | None, list[str]]:
        groups: dict[int | None, list[str]] = {}
        for host, checksum in self.checksums().items():
            groups.setdefault(checksum, []).append(host)
        return groups

    def is_converged(self) -> bool:
        live = self.live_nodes()
        if not live:
            return True
        groups = self.checksum_groups()
        return len(groups) == 1 and None not in groups

    # -- fault injection (tick-cluster.js:418-471 analogs) -------------------

    def kill(self, index: int) -> None:
        """SIGKILL analog: the process dies — destroy the node AND refuse
        its connections (a killed process cannot keep gossiping)."""
        node = self.nodes[index]
        if not node.destroyed:
            node.destroy()
        self.network.kill(self.host_ports[index])

    def revive(self, index: int) -> None:
        """Bring a killed node back as a fresh process that re-joins."""
        host_port = self.host_ports[index]
        self.network.revive(host_port)
        channel = InProcessChannel(self.network, host_port)
        node = RingPop(
            app=self.nodes[index].app,
            host_port=host_port,
            channel=channel,
            clock=self.scheduler,
            rng=random.Random(self.rng.randrange(1 << 30)),
        )
        node.setup_channel()
        self.nodes[index] = node
        node.bootstrap(list(self.host_ports), lambda *a: None)

    def suspend(self, index: int) -> None:
        self.network.pause(self.host_ports[index])

    def resume(self, index: int) -> None:
        self.network.resume(self.host_ports[index])

    def partition(self, groups: list[list[int]]) -> None:
        mapping: dict[str, int] = {}
        for gid, members in enumerate(groups):
            for index in members:
                mapping[self.host_ports[index]] = gid
        self.network.partition(mapping)

    def heal_partition(self) -> None:
        self.network.heal_partition()

    # -- driving ticks (admin/tick analog) -----------------------------------

    def tick_all(self) -> dict[str, Any]:
        """Force one protocol round per node, return checksum per node."""
        out: dict[str, Any] = {}
        for node in self.live_nodes():
            def on_tick(err: Any, resp: Any = None, node=node) -> None:
                out[node.host_port] = resp

            node.handle_tick(on_tick)
        self.scheduler.advance(50)
        return out

"""Per-member suspect timers: suspect -> (timeout) -> faulty.

Reference: lib/swim/suspicion.js.  Timers run on the injected scheduler so
tests control time deterministically.
"""

from __future__ import annotations

from typing import Any

DEFAULT_SUSPICION_TIMEOUT = 5000  # ms (suspicion.js:110-112)


class Suspicion:
    def __init__(self, ringpop: Any, suspicion_timeout: float | None = None):
        self.ringpop = ringpop
        self.period = suspicion_timeout or DEFAULT_SUSPICION_TIMEOUT
        self.is_stopped_all: bool | None = None
        self.timers: dict[str, Any] = {}

    def reenable(self) -> None:
        if self.is_stopped_all is not True:
            self.ringpop.logger.warn(
                "cannot reenable suspicion protocol because it was never disabled",
                {"local": self.ringpop.whoami()},
            )
            return
        self.is_stopped_all = None

    def start(self, member: Any) -> None:
        """member: Member or change dict with address/incarnationNumber."""
        address = getattr(member, "address", None) or member.get("address")
        incarnation = (
            getattr(member, "incarnation_number", None)
            if not isinstance(member, dict)
            else member.get("incarnationNumber")
        )

        if self.is_stopped_all is True:
            self.ringpop.logger.debug(
                "cannot start a suspect period because suspicion has not been reenabled",
                {"local": self.ringpop.whoami()},
            )
            return

        if address == self.ringpop.whoami():
            self.ringpop.logger.debug(
                "cannot start a suspect period for the local member",
                {"local": self.ringpop.whoami(), "suspect": address},
            )
            return

        if address in self.timers:
            self.stop_address(address)

        def on_expiry() -> None:
            self.ringpop.membership.make_faulty(address, incarnation)

        self.timers[address] = self.ringpop.clock.call_later(self.period, on_expiry)
        self.ringpop.logger.debug(
            "started suspect period",
            {"local": self.ringpop.whoami(), "suspect": address},
        )

    def stop(self, member: Any) -> None:
        address = getattr(member, "address", None) or member.get("address")
        self.stop_address(address)

    def stop_address(self, address: str) -> None:
        timer = self.timers.pop(address, None)
        if timer is not None:
            self.ringpop.clock.cancel(timer)

    def stop_all(self) -> None:
        self.is_stopped_all = True
        for address in list(self.timers):
            self.stop_address(address)

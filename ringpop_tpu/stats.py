"""Meters and histograms (replacing the reference's `metrics` npm dep,
index.js:137-139, lib/swim/gossip.js:33)."""

from __future__ import annotations

import math
import random
import time
from typing import Callable


class Meter:
    """Exponentially-weighted 1/5/15-minute rates, metrics-library style."""

    _INTERVAL = 5.0  # seconds per tick bucket

    def __init__(self, now_fn: Callable[[], float] | None = None):
        self._now = now_fn or time.time
        self._count = 0
        self._uncounted = 0
        self._start = self._now()
        self._last_tick = self._start
        self._m1 = 0.0
        self._m5 = 0.0
        self._m15 = 0.0
        self._initialized = False

    def mark(self, n: int = 1) -> None:
        self._tick_if_needed()
        self._count += n
        self._uncounted += n

    def _tick_if_needed(self) -> None:
        now = self._now()
        elapsed = now - self._last_tick
        ticks = int(elapsed / self._INTERVAL)
        for _ in range(ticks):
            self._tick()
        if ticks:
            self._last_tick += ticks * self._INTERVAL

    def _tick(self) -> None:
        rate = self._uncounted / self._INTERVAL
        self._uncounted = 0
        a1 = 1 - math.exp(-self._INTERVAL / 60.0)
        a5 = 1 - math.exp(-self._INTERVAL / 300.0)
        a15 = 1 - math.exp(-self._INTERVAL / 900.0)
        if not self._initialized:
            self._m1 = self._m5 = self._m15 = rate
            self._initialized = True
        else:
            self._m1 += a1 * (rate - self._m1)
            self._m5 += a5 * (rate - self._m5)
            self._m15 += a15 * (rate - self._m15)

    def print_obj(self) -> dict:
        self._tick_if_needed()
        elapsed = max(self._now() - self._start, 1e-9)
        return {
            "count": self._count,
            "m1": self._m1,
            "m5": self._m5,
            "m15": self._m15,
            "mean": self._count / elapsed,
        }

    def stop(self) -> None:  # parity with metrics.Meter.mNRate.stop()
        pass


class Histogram:
    """Uniform-reservoir histogram with percentiles (metrics.Histogram)."""

    def __init__(self, sample_size: int = 1028, seed: int | None = None):
        self._sample_size = sample_size
        self._values: list[float] = []
        self._count = 0
        self._min: float | None = None
        self._max: float | None = None
        self._sum = 0.0
        self._rng = random.Random(seed)

    def update(self, value: float) -> None:
        self._count += 1
        self._sum += value
        self._min = value if self._min is None else min(self._min, value)
        self._max = value if self._max is None else max(self._max, value)
        if len(self._values) < self._sample_size:
            self._values.append(value)
        else:
            idx = self._rng.randrange(self._count)
            if idx < self._sample_size:
                self._values[idx] = value

    def percentiles(self, ps: list[float]) -> dict:
        values = sorted(self._values)
        out: dict = {}
        for p in ps:
            if not values:
                out[str(p)] = 0.0
                continue
            pos = p * (len(values) + 1)
            if pos < 1:
                out[str(p)] = values[0]
            elif pos >= len(values):
                out[str(p)] = values[-1]
            else:
                lower = values[int(pos) - 1]
                upper = values[int(pos)]
                out[str(p)] = lower + (pos - int(pos)) * (upper - lower)
        return out

    def print_obj(self) -> dict:
        pct = self.percentiles([0.5, 0.75, 0.95, 0.99])
        return {
            "count": self._count,
            "min": self._min,
            "max": self._max,
            "sum": self._sum,
            "mean": self._sum / self._count if self._count else 0.0,
            "median": pct["0.5"],
            "p75": pct["0.75"],
            "p95": pct["0.95"],
            "p99": pct["0.99"],
        }

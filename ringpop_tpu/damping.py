"""Flap damping: detect and quarantine erratically-flapping members.

The reference *documents* this subsystem but never implemented it —
docs/architecture_design.md:73-82 describe penalty scores, decay, a
suppress limit, and ring eviction, yet no damping code exists in lib/
(SURVEY §5.3).  This module implements that documented design as an
opt-in extension (``RingPop(damping_enabled=True)``); disabled, behavior
is exactly the reference's.

Model (per the reference's own description):

* every node keeps a **damp score** for every other member;
* each *flap* — a disseminated status transition touching ``alive``
  (alive→suspect/faulty or suspect/faulty→alive) — adds ``penalty``;
* scores **decay exponentially** with half-life ``decay_half_life_ms``
  ("if the damp score goes down and then decays, the problem is fixed");
* a score above ``suppress_limit`` marks the member **damped**: it is
  removed from the hash ring (protecting lookups from shaky ownership)
  and reported via the ``memberDamped`` event + stats;
* once the decayed score falls below ``reuse_limit`` the member is
  reinstated (``memberUndamped``) and, if alive, returns to the ring.

The reference sketch also describes a damp-req fanout subprotocol
(confirming scores with k random members before damping).  In the
tick-synchronous rebuild every node evaluates the same disseminated
update stream, so local scores already agree cluster-wide up to
propagation delay; the fanout adds RPC round-trips without changing the
steady state and is intentionally omitted.
"""

from __future__ import annotations

from typing import Any

from ringpop_tpu.member import Status

DEFAULT_PENALTY = 500.0
DEFAULT_SUPPRESS_LIMIT = 2500.0
DEFAULT_REUSE_LIMIT = 500.0
DEFAULT_DECAY_HALF_LIFE_MS = 60_000.0

_FLAP_SET = {Status.alive, Status.suspect, Status.faulty}


class MemberDamping:
    def __init__(
        self,
        ringpop: Any,
        penalty: float = DEFAULT_PENALTY,
        suppress_limit: float = DEFAULT_SUPPRESS_LIMIT,
        reuse_limit: float = DEFAULT_REUSE_LIMIT,
        decay_half_life_ms: float = DEFAULT_DECAY_HALF_LIFE_MS,
    ):
        self.ringpop = ringpop
        self.penalty = penalty
        self.suppress_limit = suppress_limit
        self.reuse_limit = reuse_limit
        self.decay_half_life_ms = decay_half_life_ms
        # address -> (score at `stamp`, stamp ms, last seen status)
        self._scores: dict[str, tuple[float, float, str | None]] = {}
        self.damped: set[str] = set()

    # -- scorekeeping --------------------------------------------------------

    def _decayed(self, score: float, stamp: float, now: float) -> float:
        if score <= 0.0:
            return 0.0
        return score * 0.5 ** ((now - stamp) / self.decay_half_life_ms)

    def score_of(self, address: str) -> float:
        entry = self._scores.get(address)
        if entry is None:
            return 0.0
        return self._decayed(entry[0], entry[1], self.ringpop.clock.now())

    def record_updates(self, updates: list[dict[str, Any]]) -> None:
        """Feed applied membership updates; flaps accumulate penalty."""
        now = self.ringpop.clock.now()
        local = self.ringpop.whoami()
        for update in updates:
            address = update.get("address")
            status = update.get("status")
            if address is None or address == local:
                continue
            score, stamp, prev = self._scores.get(address, (0.0, now, None))
            score = self._decayed(score, stamp, now)
            is_flap = (
                prev is not None
                and prev != status
                and prev in _FLAP_SET
                and status in _FLAP_SET
                and (prev == Status.alive or status == Status.alive)
            )
            if is_flap:
                score += self.penalty
                self.ringpop.stat("increment", "damping.flap")
            self._scores[address] = (score, now, status)
            self._evaluate(address, score, status)

    def decay_tick(self) -> None:
        """Re-evaluate damped members.  Called on every applied update
        batch (listeners.py) AND every protocol period
        (ringpop.ping_member_now) so a quiet cluster still reinstates
        members whose scores have decayed."""
        for address in list(self.damped):
            entry = self._scores.get(address)
            if entry is None:
                continue
            self._evaluate(address, self.score_of(address), entry[2])

    # -- transitions ---------------------------------------------------------

    def _evaluate(self, address: str, score: float, status: str | None) -> None:
        if address not in self.damped and score > self.suppress_limit:
            self.damped.add(address)
            self.ringpop.stat("increment", "damping.damped")
            self.ringpop.logger.warn(
                "member damped for excessive flapping",
                {"member": address, "score": score},
            )
            if self.ringpop.ring.has_server(address):
                self.ringpop.ring.remove_server(address)
                self.ringpop.emit("ringChanged")
            self.ringpop.emit("memberDamped", address)
        elif address in self.damped and score < self.reuse_limit:
            self.damped.discard(address)
            self.ringpop.stat("increment", "damping.undamped")
            member = self.ringpop.membership.find_member_by_address(address)
            if member is not None and member.status in (Status.alive, Status.suspect):
                self.ringpop.ring.add_server(address)
                self.ringpop.emit("ringChanged")
            self.ringpop.emit("memberUndamped", address)

    def is_damped(self, address: str) -> bool:
        return address in self.damped

    def get_stats(self) -> dict[str, Any]:
        now = self.ringpop.clock.now()
        decayed = (
            (address, self._decayed(score, stamp, now))
            for address, (score, stamp, _) in self._scores.items()
        )
        return {
            "damped": sorted(self.damped),
            "scores": {a: round(s, 1) for a, s in decayed if s > 1.0},
        }

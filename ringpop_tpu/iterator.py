"""Round-robin ping-target selection with per-round reshuffle
(reference: lib/membership-iterator.js)."""

from __future__ import annotations

from typing import Any

from ringpop_tpu.member import Member


class MembershipIterator:
    def __init__(self, ringpop: Any):
        self.ringpop = ringpop
        self.current_index = -1
        self.current_round = 0

    def next(self) -> Member | None:
        visited: set[str] = set()
        max_to_visit = self.ringpop.membership.get_member_count()

        while len(visited) < max_to_visit:
            self.current_index += 1

            if self.current_index >= self.ringpop.membership.get_member_count():
                self.current_index = 0
                self.current_round += 1
                self.ringpop.membership.shuffle()

            member = self.ringpop.membership.get_member_at(self.current_index)
            visited.add(member.address)

            if self.ringpop.membership.is_pingable(member):
                return member

        return None

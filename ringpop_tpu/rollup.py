"""Membership-update rollup: batches update logs, flushing after a quiet
interval (reference: lib/membership-update-rollup.js)."""

from __future__ import annotations

from typing import Any

from ringpop_tpu.utils.events import EventEmitter

MAX_NUM_UPDATES = 250


class MembershipUpdateRollup(EventEmitter):
    def __init__(self, ringpop: Any, flush_interval: float, max_num_updates: int = MAX_NUM_UPDATES):
        super().__init__()
        self.ringpop = ringpop
        self.flush_interval = flush_interval
        self.max_num_updates = max_num_updates
        self.buffer: dict[str, list[dict[str, Any]]] = {}
        self.first_update_time: float | None = None
        self.last_flush_time: float | None = None
        self.last_update_time: float | None = None
        self.flush_timer = None

    def add_updates(self, updates: list[dict[str, Any]]) -> None:
        ts = self.ringpop.clock.now()
        for update in updates:
            entry = dict(update)
            entry["ts"] = ts
            self.buffer.setdefault(update["address"], []).append(entry)

    def destroy(self) -> None:
        self.ringpop.clock.cancel(self.flush_timer)

    def flush_buffer(self) -> None:
        if not self.buffer:
            return
        now = self.ringpop.clock.now()
        num_updates = self.get_num_updates()
        self.ringpop.logger.debug(
            "ringpop flushed membership update buffer",
            {
                "local": self.ringpop.whoami(),
                "checksum": self.ringpop.membership.checksum,
                "numUpdates": num_updates,
                "updates": self.buffer if num_updates < self.max_num_updates else None,
            },
        )
        self.buffer = {}
        self.first_update_time = None
        self.last_update_time = None
        self.last_flush_time = now
        self.emit("flushed")

    def get_num_updates(self) -> int:
        return sum(len(v) for v in self.buffer.values())

    def renew_flush_timer(self) -> None:
        self.ringpop.clock.cancel(self.flush_timer)
        self.flush_timer = self.ringpop.clock.call_later(
            self.flush_interval, self.flush_buffer
        )

    def track_updates(self, updates: list[dict[str, Any]]) -> None:
        if not updates:
            return
        now = self.ringpop.clock.now()
        if (
            self.last_update_time is not None
            and now - self.last_update_time >= self.flush_interval
        ):
            self.flush_buffer()
        if self.first_update_time is None:
            self.first_update_time = now
        self.renew_flush_timer()
        self.add_updates(updates)
        self.last_update_time = now

"""Deterministic scheduler / virtual clock.

The reference is built on Node's event loop with wall-clock timers
(``setTimeout`` injectable for tests, index.js:93; fake timers in
test/lib/alloc-ringpop.js:47-58).  This rebuild goes further: the whole
host library is written against a ``Scheduler`` so that

* unit and cluster tests run on a fully deterministic virtual clock
  (``SimScheduler`` — a discrete-event loop with millisecond time), and
* real deployments drive the same code from asyncio wall-clock timers
  (``AsyncioScheduler``).

This is the host-side mirror of the simulation core's tick-synchronous
time model (models/swim_sim.py).
"""

from __future__ import annotations

import heapq
import itertools
import time
from typing import Any, Callable


class Timer:
    __slots__ = ("when", "seq", "fn", "cancelled")

    def __init__(self, when: float, seq: int, fn: Callable[[], Any]):
        self.when = when
        self.seq = seq
        self.fn = fn
        self.cancelled = False

    def cancel(self) -> None:
        self.cancelled = True

    def __lt__(self, other: "Timer") -> bool:
        return (self.when, self.seq) < (other.when, other.seq)


class SimScheduler:
    """Single-threaded discrete-event scheduler with virtual ms time."""

    def __init__(self, start_ms: float = 1_400_000_000_000.0):
        # Default epoch mirrors the reference's Date.now() incarnation
        # numbers (ms since epoch), so checksum strings look alike.
        self._now = float(start_ms)
        self._heap: list[Timer] = []
        self._seq = itertools.count()

    def now(self) -> float:
        """Current virtual time in ms."""
        return self._now

    def call_later(self, delay_ms: float, fn: Callable[[], Any]) -> Timer:
        timer = Timer(self._now + max(0.0, delay_ms), next(self._seq), fn)
        heapq.heappush(self._heap, timer)
        return timer

    def call_soon(self, fn: Callable[[], Any]) -> Timer:
        """Mirror of process.nextTick: runs before any delayed timer."""
        return self.call_later(0.0, fn)

    def cancel(self, timer: Timer | None) -> None:
        if timer is not None:
            timer.cancel()

    # -- test/driver controls ------------------------------------------------

    def advance(self, ms: float) -> int:
        """Run all timers due within the next ``ms`` virtual milliseconds."""
        deadline = self._now + ms
        fired = 0
        while self._heap and self._heap[0].when <= deadline:
            timer = heapq.heappop(self._heap)
            if timer.cancelled:
                continue
            self._now = max(self._now, timer.when)
            timer.fn()
            fired += 1
        self._now = deadline
        return fired

    def run_until_idle(self, max_timers: int = 1_000_000) -> int:
        """Run until no timers remain (or the safety cap trips)."""
        fired = 0
        while self._heap and fired < max_timers:
            timer = heapq.heappop(self._heap)
            if timer.cancelled:
                continue
            self._now = max(self._now, timer.when)
            timer.fn()
            fired += 1
        return fired

    def pending(self) -> int:
        return sum(1 for t in self._heap if not t.cancelled)


class AsyncioScheduler:
    """Wall-clock scheduler on top of an asyncio loop (real deployments)."""

    def __init__(self, loop=None):
        import asyncio

        self._loop = loop or asyncio.get_event_loop()

    def now(self) -> float:
        return time.time() * 1000.0

    def call_later(self, delay_ms: float, fn: Callable[[], Any]):
        # asyncio handles already expose .cancel(), the only method used
        return self._loop.call_later(max(0.0, delay_ms) / 1000.0, fn)

    def call_soon(self, fn: Callable[[], Any]):
        return self._loop.call_soon(fn)

    def cancel(self, timer) -> None:
        if timer is not None:
            timer.cancel()

"""Protocol-period driver: self-rescheduling gossip loop with adaptive
delay (reference: lib/swim/gossip.js)."""

from __future__ import annotations

from typing import Any

from ringpop_tpu.stats import Histogram

DEFAULT_MIN_PROTOCOL_PERIOD = 200  # ms (gossip.js:127-129)


class Gossip:
    def __init__(self, ringpop: Any, min_protocol_period: float | None = None):
        self.ringpop = ringpop
        self.min_protocol_period = min_protocol_period or DEFAULT_MIN_PROTOCOL_PERIOD

        self.is_stopped = True
        self.last_protocol_period = self.ringpop.clock.now()
        self.last_protocol_rate = 0.0
        self.num_protocol_periods = 0
        self.protocol_period_timer = None
        self.protocol_rate_timer = None
        self.protocol_timing = Histogram(seed=0)
        self.protocol_timing.update(self.min_protocol_period)

    def compute_protocol_delay(self) -> float:
        if self.num_protocol_periods:
            target = self.last_protocol_period + self.last_protocol_rate
            return max(target - self.ringpop.clock.now(), self.min_protocol_period)
        # First tick is staggered randomly in [0, minProtocolPeriod].
        return int(self.ringpop.rng.random() * (self.min_protocol_period + 1))

    def compute_protocol_rate(self) -> float:
        observed = self.protocol_timing.percentiles([0.5])["0.5"] * 2
        return max(observed, self.min_protocol_period)

    def run(self) -> None:
        protocol_delay = self.compute_protocol_delay()
        self.ringpop.stat("timing", "protocol.delay", protocol_delay)
        start_time = self.ringpop.clock.now()

        def on_gossip_timer() -> None:
            ping_start = self.ringpop.clock.now()

            def on_member_pinged(*_args: Any) -> None:
                now = self.ringpop.clock.now()
                self.last_protocol_period = now
                self.num_protocol_periods += 1
                self.ringpop.stat("timing", "protocol.frequency", now - start_time)
                self.protocol_timing.update(now - ping_start)
                if self.is_stopped:
                    self.ringpop.logger.debug(
                        "stopped recurring gossip loop",
                        {"local": self.ringpop.whoami()},
                    )
                    return
                self.run()

            self.ringpop.ping_member_now(on_member_pinged)

        self.protocol_period_timer = self.ringpop.clock.call_later(
            protocol_delay, on_gossip_timer
        )

    def start(self) -> None:
        if not self.is_stopped:
            self.ringpop.logger.debug(
                "gossip has already started", {"local": self.ringpop.whoami()}
            )
            return
        self.ringpop.membership.shuffle()
        self.is_stopped = False
        self.run()
        self._start_protocol_rate_timer()
        self.ringpop.logger.debug(
            "started gossip protocol", {"local": self.ringpop.whoami()}
        )

    def _start_protocol_rate_timer(self) -> None:
        def on_rate_timer() -> None:
            if self.is_stopped:
                return
            self.last_protocol_rate = self.compute_protocol_rate()
            self.protocol_rate_timer = self.ringpop.clock.call_later(
                1000, on_rate_timer
            )

        self.protocol_rate_timer = self.ringpop.clock.call_later(1000, on_rate_timer)

    def stop(self) -> None:
        if self.is_stopped:
            self.ringpop.logger.warn(
                "gossip is already stopped", {"local": self.ringpop.whoami()}
            )
            return
        self.ringpop.clock.cancel(self.protocol_rate_timer)
        self.protocol_rate_timer = None
        self.ringpop.clock.cancel(self.protocol_period_timer)
        self.protocol_period_timer = None
        self.is_stopped = True

"""Membership-event glue: ring add/remove, suspicion start/stop, rumor
recording (reference: lib/membership-set-listener.js,
lib/membership-update-listener.js, lib/event-forwarder.js)."""

from __future__ import annotations

from typing import Any

from ringpop_tpu.member import Status


def create_membership_set_listener(ringpop: Any):
    """Bootstrap-time variant: alive -> ring add, suspect -> suspicion
    (membership-set-listener.js:24-48)."""

    def on_membership_set(updates: list[dict[str, Any]]) -> None:
        servers_to_add = []
        for update in updates:
            ringpop.stat(
                "increment", f"membership-set.{update.get('status', 'unknown')}"
            )
            if update.get("status") == Status.alive:
                servers_to_add.append(update["address"])
            elif update.get("status") == Status.suspect:
                ringpop.suspicion.start(update)
            ringpop.dissemination.record_change(update)
        if servers_to_add:
            ringpop.ring.add_remove_servers(servers_to_add, [])

    return on_membership_set


def create_membership_update_listener(ringpop: Any):
    """Steady-state variant (membership-update-listener.js:25-75)."""

    def on_membership_updated(updates: list[dict[str, Any]]) -> None:
        servers_to_add = []
        servers_to_remove = []
        for update in updates:
            status = update.get("status")
            ringpop.stat("increment", f"membership-update.{status or 'unknown'}")
            if status == Status.alive:
                servers_to_add.append(update["address"])
                ringpop.suspicion.stop(update)
            elif status == Status.suspect:
                ringpop.suspicion.start(update)
            elif status == Status.faulty:
                servers_to_remove.append(update["address"])
                ringpop.suspicion.stop(update)
            elif status == Status.leave:
                servers_to_remove.append(update["address"])
                ringpop.suspicion.stop(update)
            ringpop.dissemination.record_change(update)

        if ringpop.damping is not None:
            ringpop.damping.record_updates(updates)
            ringpop.damping.decay_tick()
            # damped members stay out of the ring until reinstated
            servers_to_add = [
                s for s in servers_to_add if not ringpop.damping.is_damped(s)
            ]

        if servers_to_add or servers_to_remove:
            ring_changed = ringpop.ring.add_remove_servers(
                servers_to_add, servers_to_remove
            )
            if ring_changed:
                ringpop.emit("ringChanged")

        ringpop.membership_update_rollup.track_updates(updates)
        ringpop.stat("gauge", "num-members", ringpop.membership.get_member_count())
        ringpop.stat("timing", "updates", len(updates))
        ringpop.emit("membershipChanged")
        ringpop.emit("changed")  # deprecated

    return on_membership_updated


def create_event_forwarder(ringpop: Any) -> None:
    """Re-emit internal membership/ring events publicly (event-forwarder.js)."""

    def on_membership_checksum_computed() -> None:
        ringpop.stat("increment", "membership.checksum-computed")
        ringpop.emit("membershipChecksumComputed")

    def on_ring_checksum_computed() -> None:
        ringpop.stat("increment", "ring.checksum-computed")
        ringpop.emit("ringChecksumComputed")

    def on_ring_server_added(_name: str = None) -> None:
        ringpop.stat("increment", "ring.server-added")
        ringpop.emit("ringServerAdded")

    def on_ring_server_removed(_name: str = None) -> None:
        ringpop.stat("increment", "ring.server-removed")
        ringpop.emit("ringServerRemoved")

    ringpop.membership.on("checksumComputed", on_membership_checksum_computed)
    ringpop.ring.on("added", on_ring_server_added)
    ringpop.ring.on("removed", on_ring_server_removed)
    ringpop.ring.on("checksumComputed", on_ring_checksum_computed)

"""The partitioning contracts: collectives, sharding survival, bytes.

PR 13's five contracts audit what the TRACER emits; these three audit
what the PARTITIONER and the compiler emit — the layer where "SPMD"
programs silently degenerate.  XLA will happily lower a sharded gossip
step that all-gathers every [N, N] plane back to every chip, and a
widened carry that blows the n=65,536 footprint is invisible until the
TPU worker dies on first dispatch.  All three checks run on a CPU host
against virtual devices (``--xla_force_host_platform_device_count``),
so the contract gates in CI before any chip sees the program.

1. **collective-census** (``collective_census`` + ``check_collectives``)
   — walk the post-SPMD optimized HLO for ``all-gather`` /
   ``all-reduce`` / ``collective-permute`` / ``all-to-all`` /
   ``reduce-scatter`` / DMA-flavored ``custom-call`` ops; attribute op
   count and bytes-moved per collective, mapped to protocol phases via
   the PR 5 ``jax.named_scope`` annotations that survive into HLO
   ``op_name`` metadata.  Every all-gather whose output rebuilds a
   full member-axis tensor is a **member-gather**: replication where
   gossip should be point-to-point.  The census diffs against the
   pinned per-(entry, backend, mesh) budget
   (``budgets.COLLECTIVE_BUDGETS``), and entries that declare
   ``p2p_only`` (the contract ROADMAP item 1's remote-copy gossip
   builder must assert) fail on ANY member-gather.

2. **sharding-propagation** (``check_sharding_propagation``) — the
   declared input ``NamedSharding``s must SURVIVE propagation to the
   outputs without an explicit out-shardings crutch: any output leaf
   still carrying the member axis that comes back fully replicated
   (or partitioned on a different axis) is flagged with its shape,
   dtype and flat position.  The registry audits the UNCONSTRAINED
   lowering (``mesh.sharded_step_jit(constrain_outputs=False)``): if
   row sharding only survives because an output constraint re-shards
   it, a hidden gather/slice pair pays for every step.

3. **byte-budget** (``check_byte_budget``) — XLA ``memory_analysis``
   footprints (argument / output / temp / peak bytes, the
   ``obs.ledger.memory_row`` field set) compared against pinned
   per-(entry, backend, n) rows with a tolerance band
   (``budgets.BYTE_BUDGETS``): over-band is a regression gate for
   ROADMAP item 2's "drive compiled bytes DOWN", under-band is a
   prompt to re-pin and lock the reduction in.

Budget comparisons are partitioner/compiler output, so they assume the
pinned jax build (``ringpop_tpu.utils.jaxpin``); under a different
version they downgrade to one warning instead of bit-diffing a
different compiler's decisions.
"""

from __future__ import annotations

import math
import re
from collections import Counter
from typing import Any

import jax

from ringpop_tpu.analysis import budgets
from ringpop_tpu.analysis.findings import Finding
from ringpop_tpu.utils.jaxpin import PINNED_JAX_VERSION, jax_version_matches

# The cross-chip data movers in optimized (post-SPMD) HLO.
COLLECTIVE_OPS = (
    "all-gather",
    "all-reduce",
    "reduce-scatter",
    "collective-permute",
    "all-to-all",
)

# One HLO instruction line: "%name = <result type(s)> <op>(...)", with
# the result possibly a tuple for variadic collectives.
_COLL_LINE_RE = re.compile(
    r"=\s+(?P<rtype>\(?[a-z0-9]+\[[0-9,]*\][^=]*?)\s+"
    r"(?P<op>" + "|".join(COLLECTIVE_OPS) + r")(?:-start)?\("
)
_TYPE_RE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\]")
_OPNAME_RE = re.compile(r'op_name="([^"]*)"')
# DMA-flavored custom calls (Pallas/Mosaic remote copies arrive as
# tpu_custom_call; explicit DMA targets name themselves) — the op
# family ROADMAP item 1's ring gossip is supposed to lower to.
_DMA_CALL_RE = re.compile(r'custom_call_target="(?P<tgt>[^"]*)"')
_DMA_TARGETS = ("tpu_custom_call", "dma", "SendDone", "RecvDone")

_HLO_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

# A named_scope path component: "swim.recv_merge", "delta.route_claims",
# "traffic.serve" — lowercase dotted, no parens (jit(...)/transpose(...)
# wrappers and primitive names never match).
_SCOPE_RE = re.compile(r"^[a-z][a-z0-9_]*\.[a-z0-9_.]+$")


def _phase(op_name: str) -> str:
    """The innermost protocol-phase scope on one HLO op's metadata
    path, or 'unscoped' — the PR 5 annotations survive lowering as
    op_name components."""
    scopes = [p for p in op_name.split("/") if _SCOPE_RE.match(p)]
    return scopes[-1] if scopes else "unscoped"


def _result_components(rtype: str) -> list[tuple[str, tuple[int, ...]]]:
    """(dtype, shape) per component of an HLO result type string
    (tuple results of variadic collectives yield several)."""
    out = []
    for dt, dims in _TYPE_RE.findall(rtype):
        shape = tuple(int(d) for d in dims.split(",") if d) if dims else ()
        out.append((dt, shape))
    return out


def collective_census(
    hlo_text: str, *, dims: dict[str, int], member_dim: str = "N"
) -> list[dict[str, Any]]:
    """Census rows over one optimized-HLO module's collectives, grouped
    by (op, dtype, shape, phase): count, bytes-moved-each (full output
    footprint — the replication cost an all-gather pays per chip), the
    named-dim tag, and whether the op rebuilds a member-axis tensor
    (``member`` — an [N, *]-class output on all-gather)."""
    n = dims.get(member_dim, 0)
    grouped: dict[tuple, dict[str, Any]] = {}
    for line in hlo_text.splitlines():
        m = _COLL_LINE_RE.search(line)
        if m is None:
            dm = _DMA_CALL_RE.search(line)
            if dm is None or not any(
                t in dm.group("tgt") for t in _DMA_TARGETS
            ):
                continue
            # scope the result type to the text between "=" and the op:
            # XLA's default instruction naming puts the opcode in the
            # NAME too ("%custom-call.7 = s32[...] custom-call(...)"),
            # and the tail of the line holds operand types and metadata
            kind = "custom-call:" + dm.group("tgt")
            after = line.split(" = ", 1)
            rtype = (after[1] if len(after) == 2 else line).split(
                "custom-call", 1
            )[0]
        else:
            kind, rtype = m.group("op"), m.group("rtype")
        comps = _result_components(rtype)
        if not comps:
            continue
        dtype, shape = comps[0]
        bytes_each = sum(
            math.prod(s) * _HLO_DTYPE_BYTES.get(d, 4) for d, s in comps
        )
        om = _OPNAME_RE.search(line)
        phase = _phase(om.group(1)) if om else "unscoped"
        # [N, *]-class only: rebuilding a member ROW TENSOR is the
        # replication the contract bans; [N] vectors are the O(N)
        # replicated-by-design plumbing (mesh.py's layout doc)
        member = (
            kind == "all-gather" and n > 1 and len(shape) >= 2
            and any(d == n for d in shape)
        )
        key = (kind, dtype, shape, phase, member)
        row = grouped.get(key)
        if row is None:
            grouped[key] = row = {
                "op": kind,
                "dtype": dtype,
                "shape": list(shape),
                "tag": "x".join(_tag(d, dims) for d in shape) or "scalar",
                "phase": phase,
                "member": member,
                "count": 0,
                "bytes_each": bytes_each,
            }
        row["count"] += 1
    rows = sorted(
        grouped.values(),
        key=lambda r: (-r["member"], -r["bytes_each"] * r["count"], r["op"]),
    )
    return rows


def _tag(d: int, dims: dict[str, int]) -> str:
    matches = [name for name, val in dims.items() if d == val]
    return "|".join(matches) if matches else str(d)


def collective_counts(rows: list[dict[str, Any]]) -> dict[str, int]:
    """The budget-table multiset for a census: per-op-kind instruction
    counts plus the headline ``member-gather`` count."""
    counts: Counter = Counter()
    for r in rows:
        counts[r["op"]] += r["count"]
        if r["member"]:
            counts["member-gather"] += r["count"]
    return dict(sorted(counts.items()))


def _version_guard(entry: str, what: str) -> list[Finding]:
    if jax_version_matches():
        return []
    return [
        Finding(
            contract=what,
            severity="warning",
            entry=entry,
            message=(
                f"jax {jax.__version__} != pinned {PINNED_JAX_VERSION}: "
                f"the pinned {what} budget reflects the pinned "
                "partitioner/compiler — comparison skipped; re-pin via "
                "tools/pin_budgets.py on an intentional bump"
            ),
        )
    ]


def check_collectives(
    built, rows: list[dict[str, Any]], *, n: int
) -> list[Finding]:
    """Contract 6 (collective-census): p2p-only entries admit no
    member-gather; every sharded entry's collective counts match the
    pinned per-(entry, backend, mesh) budget at the pinned shape."""
    findings: list[Finding] = []
    member_rows = [r for r in rows if r["member"]]
    if built.p2p_only:
        for r in member_rows:
            findings.append(
                Finding(
                    contract="collective-census",
                    severity="error",
                    entry=built.name,
                    message=(
                        f"member-tensor all-gather in a point-to-point "
                        f"gossip path: {r['dtype']}{r['shape']} "
                        f"[{r['tag']}] x{r['count']} in phase "
                        f"'{r['phase']}' ({r['bytes_each']} bytes each) "
                        "— inter-shard traffic must be remote-copy / "
                        "permute, not replication"
                    ),
                    where=r["phase"],
                )
            )
    pinned = budgets.collective_budget(built.name, built.backend,
                                       built.mesh_size)
    actual = collective_counts(rows)
    if pinned is None:
        findings.append(
            Finding(
                contract="collective-census",
                severity="warning",
                entry=built.name,
                message=(
                    f"no pinned collective budget for ({built.name}, "
                    f"{built.backend}, mesh {built.mesh_size}); actual at "
                    f"n={n}: {budgets.format_multiset(actual)} — pin it "
                    "in analysis/budgets.py (tools/pin_budgets.py)"
                ),
            )
        )
        return findings
    if pinned.get("n") != n:
        findings.append(
            Finding(
                contract="collective-census",
                severity="info",
                entry=built.name,
                message=(
                    f"collective budget pinned at n={pinned.get('n')}, "
                    f"audited at n={n}: partitioner decisions are "
                    "shape-dependent, counts not compared"
                ),
            )
        )
        return findings
    guard = _version_guard(built.name, "collective-census")
    if guard:
        return findings + guard
    if Counter(pinned["counts"]) != Counter(actual):
        findings.append(
            Finding(
                contract="collective-census",
                severity="error",
                entry=built.name,
                message=(
                    "collective budget drift at mesh "
                    f"{built.mesh_size}, n={n}: pinned "
                    f"{budgets.format_multiset(pinned['counts'])} but the "
                    f"partitioned HLO holds "
                    f"{budgets.format_multiset(actual)} — a new "
                    "collective (or a lost one) must be justified and "
                    "re-pinned in analysis/budgets.py"
                ),
            )
        )
    return findings


def check_sharding_propagation(built, compiled, closed) -> list[Finding]:
    """Contract 7 (sharding-propagation): every output leaf still
    carrying the member axis must come out of UNCONSTRAINED propagation
    partitioned over the declared mesh axis — an implicitly replicated
    (or re-axised) member tensor means XLA gave up on the declared
    layout and every step pays the resharding."""
    findings: list[Finding] = []
    n = built.dims.get("N", 0)
    try:
        out_sh = jax.tree_util.tree_leaves(compiled.output_shardings)
    except Exception as e:  # noqa: BLE001 — backends without the API
        return [
            Finding(
                contract="sharding-propagation",
                severity="warning",
                entry=built.name,
                message=f"compiled output shardings unavailable: {e}",
            )
        ]
    outvars = closed.jaxpr.outvars
    if len(out_sh) != len(outvars):
        return [
            Finding(
                contract="sharding-propagation",
                severity="warning",
                entry=built.name,
                message=(
                    f"output sharding leaves ({len(out_sh)}) do not align "
                    f"with jaxpr outputs ({len(outvars)}); propagation "
                    "not checked"
                ),
            )
        ]
    for i, (var, sh) in enumerate(zip(outvars, out_sh)):
        aval = getattr(var, "aval", None)
        if aval is None or not hasattr(aval, "shape"):
            continue
        shape = tuple(int(d) for d in aval.shape)
        if len(shape) < 2 or n <= 1 or n not in shape:
            # scalar telemetry, no member axis, or a rank-1 [N] vector
            # (O(N) replicated-by-design plumbing — same class the
            # census's member-gather rule exempts): replication is fine
            continue
        if getattr(sh, "is_fully_replicated", False):
            findings.append(
                Finding(
                    contract="sharding-propagation",
                    severity="error",
                    entry=built.name,
                    message=(
                        f"member-axis output leaf #{i} "
                        f"{aval.dtype}{list(shape)} came back FULLY "
                        f"REPLICATED from propagation — the declared "
                        f"'{built.mesh_axis}' sharding did not survive "
                        "lowering (XLA inserted an all-gather and kept "
                        "the result everywhere)"
                    ),
                    where=f"output[{i}]",
                )
            )
            continue
        spec = getattr(sh, "spec", None)
        if spec is not None and built.mesh_axis:
            dim0 = spec[0] if len(spec) else None
            axes = dim0 if isinstance(dim0, tuple) else (dim0,)
            if built.mesh_axis not in axes:
                findings.append(
                    Finding(
                        contract="sharding-propagation",
                        severity="error",
                        entry=built.name,
                        message=(
                            f"member-axis output leaf #{i} "
                            f"{aval.dtype}{list(shape)} was RESHARDED: "
                            f"declared leading-axis '{built.mesh_axis}' "
                            f"partitioning, propagation produced "
                            f"{spec} — the layout changed under the "
                            "program"
                        ),
                        where=f"output[{i}]",
                    )
                )
    return findings


def check_byte_budget(
    built, mem: dict[str, int], *, n: int, ticks: int
) -> list[Finding]:
    """Contract 8 (byte-budget): the compiled footprint against the
    pinned per-(entry, backend, n) row, within ``BYTE_TOLERANCE``."""
    pinned = budgets.byte_budget(built.name, built.backend, n)
    if pinned is None:
        return []  # bytes are pinned at flagship shapes only
    if pinned.get("ticks") != ticks:
        return [
            Finding(
                contract="byte-budget",
                severity="info",
                entry=built.name,
                message=(
                    f"byte budget for n={n} pinned at ticks="
                    f"{pinned.get('ticks')}, audited at ticks={ticks}: "
                    "output bytes scale with the horizon, not compared"
                ),
            )
        ]
    guard = _version_guard(built.name, "byte-budget")
    if guard:
        return guard
    findings: list[Finding] = []
    tol = budgets.BYTE_TOLERANCE
    for field, want in pinned.items():
        if field == "ticks":
            continue
        have = int(mem.get(field, 0))
        if have > want * (1 + tol):
            findings.append(
                Finding(
                    contract="byte-budget",
                    severity="error",
                    entry=built.name,
                    message=(
                        f"compiled {field} at n={n} grew past the pinned "
                        f"budget: {have:,} > {want:,} (+{tol:.0%} band) — "
                        "the footprint regressed; shrink it or justify "
                        "and re-pin (tools/pin_budgets.py)"
                    ),
                )
            )
        elif have < want * (1 - tol):
            findings.append(
                Finding(
                    contract="byte-budget",
                    severity="info",
                    entry=built.name,
                    message=(
                        f"compiled {field} at n={n} dropped below the "
                        f"pinned band: {have:,} < {want:,} (-{tol:.0%}) — "
                        "re-pin to lock the reduction in as the new "
                        "ceiling"
                    ),
                )
            )
    return findings

"""The audited entry points: every jitted program the repo ships.

Each entry is a named builder producing a ``Built`` — the jitted
callable, a small concrete fixture (argument arrays + static kwargs,
modeled on ``benchmarks/mem_census.py``'s census fixtures), and the
contract metadata the checks need: which flat argument leaves are PRNG
key roots, whether the program donates its carry, and the element
threshold above which an intermediate lands in the temporary-tensor
census.

Builders import the heavy model modules lazily (the mem_census idiom)
so ``python -m ringpop_tpu audit --list`` costs nothing.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, NamedTuple

from ringpop_tpu.analysis.jaxpr_walk import tree_flat_index_of


class Built(NamedTuple):
    """One lowerable entry point plus its contract metadata."""

    name: str
    backend: str
    jitted: Any  # the jax.jit-wrapped callable
    args: tuple  # concrete positional arguments
    statics: dict[str, Any]  # static keyword arguments
    key_roots: dict[str, list[int]]  # stream name -> flat arg leaf idx
    donates: bool  # program declares donate_argnums
    min_aliased: int  # pinned floor of tf.aliasing_output params
    census_min_elems: int  # census threshold (>= [N, C]-class)
    dims: dict[str, int]  # named dims for shape tagging (N, C, ...)
    # --- partitioning contracts (sharded entries only) ---
    mesh_size: int = 0  # devices in the entry's mesh (0 = unsharded)
    mesh_axis: str = ""  # the mesh axis member tensors shard over
    p2p_only: bool = False  # forbid ANY member-tensor all-gather (the
    #   contract item 1's remote-copy gossip builder must declare)
    trace_context: Any = None  # zero-arg ctx-manager factory wrapped
    #   around trace/lower (e.g. forcing the SPMD-safe recv-merge form)


class EntryUnavailable(RuntimeError):
    """The fixture cannot build in this environment — e.g. a sharded
    entry needs more local devices than the host exposes.  The audit
    records an info finding and moves on (set
    ``XLA_FLAGS=--xla_force_host_platform_device_count=D`` to audit
    mesh entries on any CPU host)."""


@dataclasses.dataclass(frozen=True)
class EntrySpec:
    name: str
    backends: tuple[str, ...]
    build: Callable[..., Built]
    doc: str


def _dense_fixture(n: int):
    from ringpop_tpu.models import swim_sim as sim

    params = sim.SwimParams(loss=0.01)
    return sim.init_state(n), sim.make_net(n), params


def _delta_fixture(n: int, capacity: int):
    from ringpop_tpu.models import swim_delta as sd
    from ringpop_tpu.models import swim_sim as sim

    params = sd.DeltaParams(
        swim=sim.SwimParams(loss=0.01), wire_cap=16, claim_grid=64
    )
    return sd.init_delta(n, capacity=capacity), sim.make_net(n), params


def _build_run(backend: str, *, n: int, ticks: int, capacity: int) -> Built:
    """swim_run / delta_run: the plain multi-tick scan."""
    import jax

    key = jax.random.PRNGKey(0)
    if backend == "delta":
        from ringpop_tpu.models import swim_delta as sd

        state, net, params = _delta_fixture(n, capacity)
        jitted, name = sd.delta_run, "delta_run"
    else:
        from ringpop_tpu.models import swim_sim as sim

        state, net, params = _dense_fixture(n)
        jitted, name = sim.swim_run, "swim_run"
    args = (state, net, key)
    return Built(
        name=name,
        backend=backend,
        jitted=jitted,
        args=args,
        statics=dict(params=params, ticks=ticks),
        key_roots={"protocol": tree_flat_index_of(args, key)},
        donates=True,
        min_aliased=1,
        census_min_elems=n * (capacity if backend == "delta" else n),
        dims=dict(N=n, C=capacity) if backend == "delta" else dict(N=n),
    )


def _scenario_parts(backend: str, n: int, ticks: int, capacity: int,
                    latency_buckets: int = 0):
    import jax
    import jax.numpy as jnp

    from ringpop_tpu.scenarios.compile import compile_spec
    from ringpop_tpu.scenarios.spec import ScenarioSpec

    if backend == "delta":
        state, net, params = _delta_fixture(n, capacity)
        base_loss = params.swim.loss
    else:
        state, net, params = _dense_fixture(n)
        base_loss = params.loss
    spec = ScenarioSpec.from_dict(
        {
            "ticks": ticks,
            "events": [
                {"at": min(max(ticks // 4, 1), ticks - 1),
                 "op": "kill", "node": 0},
                {"at": min(max(ticks // 2, 1), ticks - 1),
                 "op": "loss", "p": 0.05},
            ],
        }
    )
    compiled = compile_spec(spec, n, base_loss=base_loss)
    keys = jax.random.split(jax.random.PRNGKey(0), ticks)
    ct = None
    if latency_buckets:
        from ringpop_tpu.models import checksum as cksum
        from ringpop_tpu.traffic.workloads import compile_traffic

        m = min(4 * n, 128)
        ct = compile_traffic(
            {"keys_per_tick": m, "pool": 4 * m,
             "latency_buckets": latency_buckets},
            n,
            cksum.default_addresses(n),
        )
    return state, net, params, compiled, jnp.asarray(keys), ct


def _build_scenario(backend: str, *, n: int, ticks: int, capacity: int,
                    latency_buckets: int = 0) -> Built:
    """run_scenario's jitted scan (runner._scenario_scan); with
    ``latency_buckets`` the traffic + SLO-latency-coupled variant."""
    import jax.numpy as jnp

    from ringpop_tpu.scenarios import runner

    state, net, params, compiled, keys, ct = _scenario_parts(
        backend, n, ticks, capacity, latency_buckets
    )
    args = (
        state,
        net.up,
        net.responsive,
        jnp.zeros((n,), jnp.int32),
        None,  # period
        compiled.ev_tick,
        compiled.ev_kind,
        compiled.ev_node,
        compiled.p_tick,
        compiled.p_gid,
        compiled.loss,
        keys,
        ct.tensors if ct is not None else None,
        None,  # tick0
        compiled.faults,
    )
    key_roots = {"protocol": tree_flat_index_of(args, keys)}
    if ct is not None:
        key_roots["workload"] = tree_flat_index_of(args, ct.tensors.key)
    name = "run_scenario+traffic" if latency_buckets else "run_scenario"
    dims = dict(N=n)
    if backend == "delta":
        dims["C"] = capacity
    if ct is not None:
        dims["M"] = ct.static.m
        dims["B"] = latency_buckets
    return Built(
        name=name,
        backend=backend,
        jitted=runner._scenario_scan,
        args=args,
        statics=dict(
            params=params,
            has_revive=compiled.has_revive,
            traffic=ct.static if ct is not None else None,
        ),
        key_roots=key_roots,
        donates=True,
        min_aliased=1,
        census_min_elems=n * (capacity if backend == "delta" else n),
        dims=dims,
    )


def _build_incident_scenario(backend: str, *, n: int, ticks: int,
                             capacity: int, latency_buckets: int = 8) -> Built:
    """run_scenario's jitted scan in its INCIDENT shape (the
    cascading_overload fixture): traffic + SLO latency plane +
    load-coupled overload feedback — the program that carries the
    per-node pressure/gray state through the scan, so its carry-dtype
    budget and PRNG lineage are audited next to the plain variants."""
    import jax.numpy as jnp

    from ringpop_tpu.models import checksum as cksum
    from ringpop_tpu.scenarios import runner
    from ringpop_tpu.scenarios.compile import compile_spec
    from ringpop_tpu.scenarios.spec import ScenarioSpec
    from ringpop_tpu.traffic.workloads import compile_traffic

    import jax

    if backend == "delta":
        state, net, params = _delta_fixture(n, capacity)
        base_loss = params.swim.loss
    else:
        state, net, params = _dense_fixture(n)
        base_loss = params.loss
    t_kill = min(max(ticks // 4, 1), ticks - 1)
    spec = ScenarioSpec.from_dict(
        {
            "ticks": ticks,
            "events": [
                {"at": t_kill, "op": "kill", "node": 0},
                {"at": 0, "op": "overload", "until": ticks, "capacity": 2,
                 "threshold": 8, "recover": 2, "factor": 4},
            ],
        }
    )
    compiled = compile_spec(spec, n, base_loss=base_loss)
    keys = jax.random.split(jax.random.PRNGKey(0), ticks)
    m = min(4 * n, 128)
    ct = compile_traffic(
        {"kind": "zipf", "keys_per_tick": m, "pool": 4 * m,
         "latency_buckets": latency_buckets},
        n,
        cksum.default_addresses(n),
    )
    ct = runner.overload_traffic(ct, compiled)
    _, period, ov = runner.prepare_faults(state, net, compiled, params)
    args = (
        state,
        net.up,
        net.responsive,
        jnp.zeros((n,), jnp.int32),
        period,
        compiled.ev_tick,
        compiled.ev_kind,
        compiled.ev_node,
        compiled.p_tick,
        compiled.p_gid,
        compiled.loss,
        jnp.asarray(keys),
        ct.tensors,
        None,  # tick0
        compiled.faults,
        ov,
    )
    dims = dict(N=n, M=ct.static.m, B=latency_buckets)
    if backend == "delta":
        dims["C"] = capacity
    return Built(
        name="run_scenario+incident",
        backend=backend,
        jitted=runner._scenario_scan,
        args=args,
        statics=dict(
            params=params,
            has_revive=compiled.has_revive,
            traffic=ct.static,
            overload=compiled.overload,
        ),
        key_roots={
            "protocol": tree_flat_index_of(args, args[11]),
            "workload": tree_flat_index_of(args, ct.tensors.key),
        },
        donates=True,
        min_aliased=1,
        census_min_elems=n * (capacity if backend == "delta" else n),
        dims=dims,
    )


def _build_policy_scenario(backend: str, *, n: int, ticks: int,
                           capacity: int, latency_buckets: int = 8) -> Built:
    """run_scenario's jitted scan in its POLICY shape: the incident
    fixture plus the remediation policy carry (pressure meter, packed
    shed/quarantine planes, amp windows, retry cap) and its traced
    knob scalars — the widest carry the scan ships, audited so a knob
    can never silently become a compile-time static again."""
    import jax.numpy as jnp

    from ringpop_tpu.models import checksum as cksum
    from ringpop_tpu.policies import core as pol
    from ringpop_tpu.scenarios import runner
    from ringpop_tpu.scenarios.compile import compile_spec
    from ringpop_tpu.scenarios.spec import ScenarioSpec
    from ringpop_tpu.traffic.workloads import compile_traffic

    import jax

    if backend == "delta":
        state, net, params = _delta_fixture(n, capacity)
        base_loss = params.swim.loss
    else:
        state, net, params = _dense_fixture(n)
        base_loss = params.loss
    t_kill = min(max(ticks // 4, 1), ticks - 1)
    spec = ScenarioSpec.from_dict(
        {
            "ticks": ticks,
            "events": [
                {"at": t_kill, "op": "kill", "node": 0},
                {"at": 0, "op": "overload", "until": ticks, "capacity": 2,
                 "threshold": 8, "recover": 2, "factor": 4},
            ],
        }
    )
    compiled = compile_spec(spec, n, base_loss=base_loss)
    keys = jax.random.split(jax.random.PRNGKey(0), ticks)
    m = min(4 * n, 128)
    ct = compile_traffic(
        {"kind": "zipf", "keys_per_tick": m, "pool": 4 * m,
         "latency_buckets": latency_buckets},
        n,
        cksum.default_addresses(n),
    )
    cp = pol.compile_policy("combined", n=n, m=m)
    ct = runner.overload_traffic(ct, compiled)
    ct = runner.policy_traffic(ct, cp)
    _, period, ov = runner.prepare_faults(state, net, compiled, params)
    po = runner.prepare_policy(cp, net, n, ct.static.max_retries)
    args = (
        state,
        net.up,
        net.responsive,
        jnp.zeros((n,), jnp.int32),
        period,
        compiled.ev_tick,
        compiled.ev_kind,
        compiled.ev_node,
        compiled.p_tick,
        compiled.p_gid,
        compiled.loss,
        jnp.asarray(keys),
        ct.tensors,
        None,  # tick0
        compiled.faults,
        ov,
        po,
        pol.knob_arrays(cp),
    )
    dims = dict(N=n, M=ct.static.m, B=latency_buckets,
                W=cp.config.amp_window)
    if backend == "delta":
        dims["C"] = capacity
    return Built(
        name="run_scenario+policy",
        backend=backend,
        jitted=runner._scenario_scan,
        args=args,
        statics=dict(
            params=params,
            has_revive=compiled.has_revive,
            traffic=ct.static,
            overload=compiled.overload,
            policy=cp.config,
        ),
        key_roots={
            "protocol": tree_flat_index_of(args, args[11]),
            "workload": tree_flat_index_of(args, ct.tensors.key),
        },
        donates=True,
        min_aliased=1,
        census_min_elems=n * (capacity if backend == "delta" else n),
        dims=dims,
    )


def _build_provenance_scenario(backend: str, *, n: int, ticks: int,
                               capacity: int, trace_rumors: int = 4) -> Built:
    """run_scenario's jitted scan in its PROVENANCE shape: a kill
    timeline with the rumor-tracing plane armed (obs/provenance.py) —
    the program that carries per-rumor first-heard/parent/knows planes
    through the scan, audited so the tracing carry stays bit-packed
    (ZERO bool leaves) and its dtype multiset stays pinned next to the
    legacy shapes (the prov-off program is the run_scenario entry
    itself: same scan, pv=None, prov=None)."""
    import jax
    import jax.numpy as jnp

    from ringpop_tpu.scenarios import runner
    from ringpop_tpu.scenarios.compile import compile_spec
    from ringpop_tpu.scenarios.spec import ScenarioSpec

    if backend == "delta":
        state, net, params = _delta_fixture(n, capacity)
        base_loss = params.swim.loss
    else:
        state, net, params = _dense_fixture(n)
        base_loss = params.loss
    t_kill = min(max(ticks // 4, 1), ticks - 1)
    spec = ScenarioSpec.from_dict(
        {
            "ticks": ticks,
            "trace_rumors": trace_rumors,
            "events": [
                {"at": t_kill, "op": "kill", "node": 0},
                {"at": 0, "op": "track", "node": 1},
            ],
        }
    )
    compiled = compile_spec(spec, n, base_loss=base_loss)
    keys = jax.random.split(jax.random.PRNGKey(0), ticks)
    pv, pv_at, pv_node = runner.prepare_prov(compiled, net, params)
    args = (
        state,
        net.up,
        net.responsive,
        jnp.zeros((n,), jnp.int32),
        None,  # period
        compiled.ev_tick,
        compiled.ev_kind,
        compiled.ev_node,
        compiled.p_tick,
        compiled.p_gid,
        compiled.loss,
        jnp.asarray(keys),
        None,  # tr_tensors
        None,  # tick0
        compiled.faults,
        None,  # ov
        None,  # po
        None,  # po_knobs
        None,  # sw_knobs
        pv,
        pv_at,
        pv_node,
    )
    return Built(
        name="run_scenario+provenance",
        backend=backend,
        jitted=runner._scenario_scan,
        args=args,
        statics=dict(
            params=params,
            has_revive=compiled.has_revive,
            prov=compiled.trace_rumors,
        ),
        key_roots={"protocol": tree_flat_index_of(args, args[11])},
        donates=True,
        min_aliased=1,
        census_min_elems=n * (capacity if backend == "delta" else n),
        dims=dict(N=n, K=trace_rumors,
                  **(dict(C=capacity) if backend == "delta" else {})),
    )


def _build_sweep(backend: str, *, n: int, ticks: int, capacity: int,
                 replicas: int) -> Built:
    """run_sweep's jitted vmapped scan (sweep._sweep_scan)."""
    import jax
    import jax.numpy as jnp

    from ringpop_tpu.scenarios import sweep as ssweep
    from ringpop_tpu.scenarios.spec import ScenarioSpec

    if backend == "delta":
        state, net, params = _delta_fixture(n, capacity)
        base_loss = params.swim.loss
    else:
        state, net, params = _dense_fixture(n)
        base_loss = params.loss
    spec = ScenarioSpec.from_dict(
        {"ticks": ticks,
         "events": [{"at": min(max(ticks // 4, 1), ticks - 1),
                     "op": "kill", "node": 0}]}
    )
    cs = ssweep.compile_sweep(spec, n, replicas=replicas, base_loss=base_loss)
    rkeys = list(jax.random.split(jax.random.PRNGKey(0), replicas))
    keys = ssweep.sweep_key_schedule(rkeys, cs)
    args = (
        ssweep._broadcast_replicas(state, replicas),
        ssweep._broadcast_replicas(net.up, replicas),
        ssweep._broadcast_replicas(net.responsive, replicas),
        ssweep._broadcast_replicas(jnp.zeros((n,), jnp.int32), replicas),
        None,  # period
        cs.ev_tick,
        cs.ev_kind,
        cs.ev_node,
        cs.base.p_tick,
        cs.base.p_gid,
        cs.loss,
        keys,
    )
    return Built(
        name="run_sweep",
        backend=backend,
        jitted=ssweep._sweep_scan,
        args=args,
        statics=dict(params=params, has_revive=cs.base.has_revive),
        key_roots={"protocol": tree_flat_index_of(args, keys)},
        donates=True,
        min_aliased=1,
        census_min_elems=replicas * n
        * (capacity if backend == "delta" else n),
        dims=dict(N=n, R=replicas, **(dict(C=capacity)
                                      if backend == "delta" else {})),
    )


def _build_param_sweep(backend: str, *, n: int, ticks: int, capacity: int,
                       replicas: int) -> Built:
    """``run_sweep(param_axes=...)``'s program: the vmapped sweep scan
    with the traced protocol knobs batched [R] (``sim.SwimKnobs`` — a
    suspicion_ticks axis here, every other knob broadcast from the
    fixture params).  One extra leading-replica-axis operand on the
    same scan: the knob grid must change NEITHER the carry multiset
    (knobs close over the body as scan constants) nor any other pinned
    contract of the plain run_sweep entry."""
    from ringpop_tpu.scenarios import sweep as ssweep

    base = _build_sweep(backend, n=n, ticks=ticks, capacity=capacity,
                        replicas=replicas)
    sw_knobs = ssweep.param_knob_axes(
        base.statics["params"],
        {"suspicion_ticks": [3 + 2 * r for r in range(replicas)]},
        replicas, n=n, backend=backend, period_active=False, damping=False,
    )
    # positional tail of _sweep_scan_impl up to sw_knobs:
    # tick0, faults, tr_tensors, ov, po, po_knobs
    args = base.args + (None, None, None, None, None, None, sw_knobs)
    return base._replace(
        name="run_sweep+param_axes",
        args=args,
        key_roots={"protocol": tree_flat_index_of(args, args[11])},
    )


def _build_recv_merge(backend: str, *, n: int, **_ignored) -> Built:
    """The Pallas receiver-merge kernel's host-level jit wrapper
    (interpret mode — the Mosaic kernel itself needs a TPU to compile,
    but the jaxpr contracts are lowering-independent)."""
    import jax
    import jax.numpy as jnp

    from ringpop_tpu.ops import recv_merge_pallas as rmp

    key = jax.random.PRNGKey(0)
    t_safe = jax.random.randint(key, (n,), 0, n, dtype=jnp.int32)
    fwd_ok = jnp.ones((n,), bool)
    claims = jnp.zeros((n, n), jnp.int32)
    args = (t_safe, fwd_ok, claims)
    return Built(
        name="recv_merge_pallas",
        backend=backend,
        jitted=rmp._recv_merge_pallas_jit,
        args=args,
        statics=dict(interpret=True),
        key_roots={},
        donates=False,
        min_aliased=0,
        census_min_elems=n * n,
        dims=dict(N=n),
    )


def _build_delta_merge(backend: str, *, n: int, capacity: int = 64,
                       **_ignored) -> Built:
    """The delta insert-merge Pallas kernel's jit wrapper (interpret
    mode — same contract as recv_merge_pallas: jaxpr invariants are
    lowering-independent, the Mosaic compile needs a TPU)."""
    import jax
    import jax.numpy as jnp

    from ringpop_tpu.ops import delta_merge_pallas as dmp

    ki = 17  # claim_grid=16 + the self column, the audit-fixture shape
    d_subj = jnp.full((n, capacity), dmp.SENTINEL, jnp.int32)
    d_key = jnp.zeros((n, capacity), jnp.int32)
    d_pb = jnp.full((n, capacity), -1, jnp.int8)
    d_sl = jnp.full((n, capacity), -1, jnp.int8)
    ins_subj = jnp.full((n, ki), dmp.SENTINEL, jnp.int32)
    ins_key = jnp.zeros((n, ki), jnp.int32)
    args = (d_subj, d_key, d_pb, d_sl, ins_subj, ins_key)
    return Built(
        name="delta_merge_pallas",
        backend=backend,
        jitted=dmp.merge_insert_pallas,
        args=args,
        statics=dict(sl_start=10, suspect=2, interpret=True),
        key_roots={},
        donates=False,
        min_aliased=0,
        census_min_elems=n * capacity,
        dims=dict(N=n, C=capacity),
    )


def _require_devices(mesh: int, entry: str) -> None:
    import jax

    have = len(jax.devices())
    if have < mesh:
        raise EntryUnavailable(
            f"{entry} needs a {mesh}-device mesh but only {have} local "
            f"device(s) exist — run under XLA_FLAGS="
            f"--xla_force_host_platform_device_count={mesh} (CPU virtual "
            "devices; the CI audit job does)"
        )


def _build_sharded_step(backend: str, *, n: int, mesh: int = 2,
                        gossip: str = "ring", **_ignored: Any) -> Built:
    """The viewer-row sharded dense step (parallel/mesh.py) at a fixed
    mesh size, lowered UNCONSTRAINED (no out_shardings) so the
    sharding-propagation contract checks what XLA actually decides.
    The partitioned HLO of this program is the collective census's
    subject.  The default ``ring`` gossip plane routes inter-shard
    claims as neighbor-exchange hops (ops/gossip_remote_copy.py) and
    carries ``p2p_only=True`` — a member-plane all-gather is an audit
    ERROR, with the budget row pinned at zero.  ``gossip="gather"``
    builds the PR-15 all-gather lowering (entry ``sharded_step+gather``)
    so the legacy shape stays measurable for the multichip bench."""
    import jax

    from ringpop_tpu.models import swim_sim as sim
    from ringpop_tpu.parallel import mesh as pmesh

    _require_devices(mesh, f"sharded_step (mesh {mesh})")
    if n % mesh:
        raise EntryUnavailable(
            f"sharded_step needs n divisible by the mesh ({n} % {mesh})"
        )
    m = pmesh.make_mesh(mesh)
    state, net, params = _dense_fixture(n)
    state, net = pmesh.shard_cluster(state, net, m)
    key = jax.random.PRNGKey(0)
    jitted = pmesh.sharded_step_jit(m, constrain_outputs=False)
    # params rides positionally: a pjit with in_shardings rejects
    # kwargs outright (static_argnames still applies by signature).
    # It trails the key, so the PRNG root's flat index is unaffected.
    args = (state, net, key, params)
    if gossip == "gather":
        name = "sharded_step+gather"
    else:
        name = "sharded_step" if mesh == 2 else f"sharded_step@{mesh}"
    return Built(
        name=name,
        backend=backend,
        jitted=jitted,
        args=args,
        statics={},
        key_roots={"protocol": tree_flat_index_of(args, key)},
        donates=True,
        min_aliased=1,
        census_min_elems=n * n,
        dims=dict(N=n),
        mesh_size=mesh,
        mesh_axis=pmesh.AXIS,
        p2p_only=(gossip == "ring"),
        trace_context=lambda: pmesh._mesh_gossip(m, gossip),
    )


def _build_sharded_delta_step(backend: str, *, n: int, capacity: int,
                              mesh: int = 2, gossip: str = "ring",
                              **_ignored: Any) -> Built:
    """The row-sharded delta step on a fixed mesh — the scale
    flagship's production gossip path.  Same contracts as
    ``sharded_step``: unconstrained lowering, ring gossip plane,
    ``p2p_only=True`` with the member-gather budget pinned at zero."""
    import jax

    from ringpop_tpu.parallel import mesh as pmesh

    _require_devices(mesh, f"sharded_delta_step (mesh {mesh})")
    if n % mesh:
        raise EntryUnavailable(
            f"sharded_delta_step needs n divisible by the mesh ({n} % {mesh})"
        )
    m = pmesh.make_mesh(mesh)
    state, net, params = _delta_fixture(n, capacity)
    state = pmesh.shard_delta(state, m)
    net = jax.device_put(net, pmesh.net_sharding(m, like=net))
    key = jax.random.PRNGKey(0)
    jitted = pmesh.sharded_delta_step_jit(m, constrain_outputs=False)
    args = (state, net, key, params)
    return Built(
        name="sharded_delta_step",
        backend=backend,
        jitted=jitted,
        args=args,
        statics={},
        key_roots={"protocol": tree_flat_index_of(args, key)},
        donates=True,
        min_aliased=1,
        census_min_elems=n * capacity,
        dims=dict(N=n, C=capacity),
        mesh_size=mesh,
        mesh_axis=pmesh.AXIS,
        p2p_only=(gossip == "ring"),
        trace_context=lambda: pmesh._mesh_gossip(m, gossip),
    )


def _build_sharded_sweep(backend: str, *, n: int, ticks: int,
                         capacity: int, replicas: int, mesh: int = 2,
                         **_ignored: Any) -> Built:
    """``run_sweep(shard=True)``'s program: the vmapped scenario scan
    with every replica-batched arg device_put onto a replica-axis mesh
    (scenarios/sweep.py `_replica_sharding`, here at a fixed mesh size
    so the budget rows are host-independent).  Replicas are
    data-parallel by construction, so the ONLY sanctioned collectives
    are the scalar-telemetry all-reduces: a member-gather here means
    the replica axis broke."""
    import jax
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    _require_devices(mesh, f"run_sweep+shard (mesh {mesh})")
    if replicas % mesh:
        raise EntryUnavailable(
            f"run_sweep+shard needs replicas divisible by the mesh "
            f"({replicas} % {mesh})"
        )
    base = _build_sweep(backend, n=n, ticks=ticks, capacity=capacity,
                        replicas=replicas)
    rmesh = Mesh(np.asarray(jax.devices()[:mesh]), ("replicas",))
    rsh = NamedSharding(rmesh, PartitionSpec("replicas"))
    args = tuple(
        jax.tree_util.tree_map(lambda a: jax.device_put(a, rsh), arg)
        if i in (0, 1, 2, 3, 11) else arg
        for i, arg in enumerate(base.args)
    )
    keys = args[11]
    return base._replace(
        name="run_sweep+shard",
        args=args,
        key_roots={"protocol": tree_flat_index_of(args, keys)},
        mesh_size=mesh,
        mesh_axis="replicas",
        # data-parallel: any member-tensor all-gather is a bug, not a
        # lowering strategy — the strictest contract holds already
        p2p_only=True,
    )


ENTRY_POINTS: dict[str, EntrySpec] = {
    "swim_run": EntrySpec(
        "swim_run", ("dense",), _build_run,
        "the dense multi-tick scan (models/swim_sim.py)"),
    "delta_run": EntrySpec(
        "delta_run", ("delta",), _build_run,
        "the delta multi-tick scan (models/swim_delta.py)"),
    "run_scenario": EntrySpec(
        "run_scenario", ("dense", "delta"), _build_scenario,
        "the compiled fault-timeline scan (scenarios/runner.py)"),
    "run_scenario+traffic": EntrySpec(
        "run_scenario+traffic", ("dense", "delta"),
        lambda backend, **kw: _build_scenario(
            backend, latency_buckets=kw.pop("latency_buckets", 8), **kw),
        "the scenario scan co-running a key workload with the SLO "
        "latency plane (traffic/engine.py + traffic/latency.py)"),
    "run_scenario+incident": EntrySpec(
        "run_scenario+incident", ("dense", "delta"),
        _build_incident_scenario,
        "the scenario scan in its incident shape: traffic + SLO "
        "latency + the load-coupled overload feedback carry "
        "(scenarios/library.py cascading_overload)"),
    "run_scenario+policy": EntrySpec(
        "run_scenario+policy", ("dense", "delta"),
        _build_policy_scenario,
        "the scenario scan in its policy shape: the incident fixture "
        "plus the remediation policy carry and traced knob scalars "
        "(ringpop_tpu/policies)"),
    "run_scenario+provenance": EntrySpec(
        "run_scenario+provenance", ("dense", "delta"),
        _build_provenance_scenario,
        "the scenario scan with the gossip provenance plane armed: "
        "per-rumor infection wavefronts + detection-causality chains "
        "carried bit-packed through the scan (obs/provenance.py)"),
    "run_sweep": EntrySpec(
        "run_sweep", ("dense", "delta"), _build_sweep,
        "the vmapped R-replica sweep scan (scenarios/sweep.py)"),
    "run_sweep+param_axes": EntrySpec(
        "run_sweep+param_axes", ("dense", "delta"), _build_param_sweep,
        "run_sweep with the traced protocol knobs batched [R] "
        "(sim.SwimKnobs: a suspicion_ticks axis) — the compile-once "
        "knob-grid program (scenarios/sweep.py param_knob_axes)"),
    "recv_merge_pallas": EntrySpec(
        "recv_merge_pallas", ("dense",), _build_recv_merge,
        "the Pallas receiver-merge kernel wrapper "
        "(ops/recv_merge_pallas.py, interpret lowering)"),
    "delta_merge_pallas": EntrySpec(
        "delta_merge_pallas", ("delta",), _build_delta_merge,
        "the fused insert-merge kernel for the delta tables "
        "(ops/delta_merge_pallas.py, interpret lowering)"),
    "sharded_step": EntrySpec(
        "sharded_step", ("dense",),
        lambda backend, **kw: _build_sharded_step(
            backend, mesh=kw.pop("mesh", 2), **kw),
        "the viewer-row sharded dense step on a 2-device mesh, ring "
        "gossip plane (parallel/mesh.py; p2p partitioning contracts)"),
    "sharded_step@4": EntrySpec(
        "sharded_step@4", ("dense",),
        lambda backend, **kw: _build_sharded_step(
            backend, mesh=kw.pop("mesh", 4), **kw),
        "the viewer-row sharded dense step on a 4-device mesh"),
    "sharded_step+gather": EntrySpec(
        "sharded_step+gather", ("dense",),
        lambda backend, **kw: _build_sharded_step(
            backend, mesh=kw.pop("mesh", 2), gossip="gather", **kw),
        "the PR-15 all-gather lowering of the sharded dense step — the "
        "legacy baseline the multichip bench races the ring plane "
        "against (not p2p_only; its 75 member-gathers are pinned as "
        "the measured cost, not outlawed)"),
    "sharded_delta_step": EntrySpec(
        "sharded_delta_step", ("delta",),
        lambda backend, **kw: _build_sharded_delta_step(
            backend, mesh=kw.pop("mesh", 2), **kw),
        "the row-sharded delta step on a 2-device mesh, ring gossip "
        "plane (parallel/mesh.py; p2p partitioning contracts)"),
    "run_sweep+shard": EntrySpec(
        "run_sweep+shard", ("dense", "delta"),
        lambda backend, **kw: _build_sharded_sweep(
            backend, mesh=kw.pop("mesh", 2), **kw),
        "run_sweep(shard=True): the replica-axis-sharded sweep scan on "
        "a 2-device mesh (scenarios/sweep.py)"),
}

def build_entry(name: str, backend: str, *, n: int = 64, ticks: int = 4,
                capacity: int = 64, replicas: int = 2,
                **extra: Any) -> Built:
    """Materialize one (entry, backend) fixture at the given shape."""
    spec = ENTRY_POINTS[name]
    if backend not in spec.backends:
        raise ValueError(f"{name} has no {backend} backend "
                         f"(has {spec.backends})")
    kw: dict[str, Any] = dict(n=n, ticks=ticks, capacity=capacity, **extra)
    if name.startswith("run_sweep"):
        kw["replicas"] = replicas
    return spec.build(backend, **kw)


def iter_entries(names=None, backends=None):
    """Yield every requested (entry name, backend) pair."""
    for name, spec in ENTRY_POINTS.items():
        if names is not None and name not in names:
            continue
        for backend in spec.backends:
            if backends is not None and backend not in backends:
                continue
            yield name, backend

"""``python -m ringpop_tpu audit`` — the trace-contract auditor CLI.

Audits every registered entry point (or a selection) on the current
host — tracing only, CPU is fine — and exits non-zero when any finding
reaches ``--fail-on`` severity.  The CI audit job runs
``audit --fail-on error`` on every push; a perf PR runs it before
benching to know the program it is about to measure still honors the
pinned contracts.

Examples:

    python -m ringpop_tpu audit
    python -m ringpop_tpu audit --entry delta_run --n 4096 --census \\
        --no-compile --json
    python -m ringpop_tpu audit --entry run_scenario+traffic \\
        --backend delta --print-budget
    python -m ringpop_tpu audit --lint-only
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import Counter
from pathlib import Path

from ringpop_tpu.analysis.findings import SEVERITY_RANK, at_least
from ringpop_tpu.analysis.lint import lint_paths
from ringpop_tpu.analysis.registry import ENTRY_POINTS


def _parse(argv):
    ap = argparse.ArgumentParser(
        prog="python -m ringpop_tpu audit",
        description=__doc__.splitlines()[0],
    )
    ap.add_argument("--entry", default=None,
                    help="comma list of entry points (default: all; "
                         "see --list)")
    ap.add_argument("--backend", choices=("dense", "delta", "both"),
                    default="both")
    ap.add_argument("--n", type=int, default=64)
    ap.add_argument("--ticks", type=int, default=4)
    ap.add_argument("--capacity", type=int, default=64)
    ap.add_argument("--replicas", type=int, default=2)
    ap.add_argument("--fail-on", choices=("error", "warning", "info",
                                          "never"),
                    default="error",
                    help="exit 1 when any finding reaches this severity")
    ap.add_argument("--json", action="store_true",
                    help="one JSON object per entry report (machine lane)")
    ap.add_argument("--census", action="store_true",
                    help="print the temporary-tensor census rows")
    ap.add_argument("--census-min-elems", type=int, default=None,
                    help="census threshold override (default: the "
                         "entry's [N, C]-class floor)")
    ap.add_argument("--no-compile", action="store_true",
                    help="skip StableHLO lowering (faster big-n census; "
                         "donation check degrades to a skip)")
    ap.add_argument("--no-lint", action="store_true",
                    help="skip the AST lint layer")
    ap.add_argument("--lint-only", action="store_true",
                    help="run only the AST lint layer (no tracing)")
    ap.add_argument("--print-budget", action="store_true",
                    help="print the carry-budget rows for "
                         "analysis/budgets.py pinning")
    ap.add_argument("--list", action="store_true",
                    help="list registered entry points and exit")
    return ap.parse_args(argv)


def main(argv: list[str] | None = None) -> None:
    args = _parse(argv)

    if args.list:
        for name, spec in ENTRY_POINTS.items():
            print(f"{name:24s} [{'/'.join(spec.backends)}] {spec.doc}")
        return

    findings = []
    reports = []

    if not args.lint_only:
        from ringpop_tpu.analysis.contracts import audit_all
        from ringpop_tpu.analysis.registry import iter_entries

        names = args.entry.split(",") if args.entry else None
        backends = (None if args.backend == "both" else (args.backend,))
        # a typo'd --entry (or an entry/backend pair matching nothing)
        # must not fail OPEN: auditing zero programs is an error, and
        # unknown names are named
        if names is not None:
            unknown = [n for n in names if n not in ENTRY_POINTS]
            if unknown:
                sys.exit(f"audit: unknown entry point(s) {unknown}; "
                         f"--list shows the registry")
        if not list(iter_entries(names, backends)):
            sys.exit("audit: the --entry/--backend selection matches no "
                     "registered (entry, backend) pair")
        reports, audit_findings = audit_all(
            names,
            backends,
            n=args.n,
            ticks=args.ticks,
            capacity=args.capacity,
            replicas=args.replicas,
            compile_programs=not args.no_compile,
            census_min_elems=args.census_min_elems,
        )
        findings += audit_findings

    lint_ran = args.lint_only or not args.no_lint
    if lint_ran:
        findings += lint_paths(Path(__file__).resolve().parent.parent)

    if args.json:
        for r in reports:
            print(json.dumps({"kind": "entry", **r.to_json()}))
        for f in findings:
            if not any(f in r.findings for r in reports):
                print(json.dumps({"kind": "finding", **f.to_json()}))
    else:
        for r in reports:
            sev = Counter(f.severity for f in r.findings)
            status = ("clean" if not r.findings else
                      " ".join(f"{v} {k}" for k, v in sorted(sev.items())))
            print(
                f"{r.entry} [{r.backend}] n={r.n}: {status}; "
                f"{len(r.census)} census rows, aliased={r.aliased_outputs}, "
                f"prng roots={r.prng.get('roots', {})}"
            )
            if args.census:
                for row in r.census:
                    print(
                        f"    [{row['tag']}] {row['dtype']}"
                        f"{row['shape']} x{row['count']} via "
                        f"{row['primitive']} @ {row['path']} "
                        f"({row['bytes_each'] / 1e6:.2f} MB each)"
                    )
            if args.print_budget:
                ms = Counter()
                for leaves in r.carries.values():
                    for leaf in leaves:
                        ms[leaf.split("[")[0]] += 1
                print(f"    (\"{r.entry}\", \"{r.backend}\"): "
                      f"{dict(sorted(ms.items()))},")
        lint_findings = [f for f in findings
                         if f.contract.startswith("lint:")]
        shown = [f for f in findings
                 if SEVERITY_RANK[f.severity] >= SEVERITY_RANK["warning"]
                 or f.contract.startswith("lint:")]
        for f in shown:
            print(str(f))
        total = Counter(f.severity for f in findings)
        lint_part = (f"{len(lint_findings)} lint findings"
                     if lint_ran else "lint skipped")
        print(
            f"audit: {len(reports)} programs, {lint_part}, "
            f"{total.get('error', 0)} errors / "
            f"{total.get('warning', 0)} warnings / "
            f"{total.get('info', 0)} info"
        )

    if args.fail_on != "never" and at_least(findings, args.fail_on):
        sys.exit(1)


if __name__ == "__main__":
    main()

"""``python -m ringpop_tpu audit`` — the trace-contract auditor CLI.

Audits every registered entry point (or a selection) on the current
host — tracing only, CPU is fine — and exits non-zero when any finding
reaches ``--fail-on`` severity.  The CI audit job runs
``audit --fail-on error`` on every push; a perf PR runs it before
benching to know the program it is about to measure still honors the
pinned contracts.

The sharded entries (``sharded_step``, ``sharded_step@4``,
``run_sweep+shard``) audit the PARTITIONED programs and need a
multi-device mesh; on a CPU host the CLI provisions virtual devices
automatically (``--xla_force_host_platform_device_count=4``, set
before the backend initializes) so the partitioning contracts gate on
any machine.

Examples:

    python -m ringpop_tpu audit
    python -m ringpop_tpu audit --entry delta_run --n 4096 --census \\
        --no-compile --json
    python -m ringpop_tpu audit --entry run_scenario+traffic \\
        --backend delta --print-budget
    python -m ringpop_tpu audit --entry sharded_step --collectives
    python -m ringpop_tpu audit --entry run_scenario --n 4096  # byte gate
    python -m ringpop_tpu audit --lint-only
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import Counter
from pathlib import Path

# Virtual CPU devices for the sharded entries: must land in the
# environment before the first backend initialization (harmless later —
# the flag only shapes the CPU platform, and an already-initialized
# backend simply ignores it, leaving the mesh entries to skip with an
# info finding naming the flag).
from ringpop_tpu.utils import provision_virtual_devices

provision_virtual_devices(4)

from ringpop_tpu.analysis.findings import SEVERITY_RANK, at_least  # noqa: E402
from ringpop_tpu.analysis.lint import lint_paths  # noqa: E402
from ringpop_tpu.analysis.registry import ENTRY_POINTS  # noqa: E402


def _parse(argv):
    ap = argparse.ArgumentParser(
        prog="python -m ringpop_tpu audit",
        description=__doc__.splitlines()[0],
    )
    ap.add_argument("--entry", default=None,
                    help="comma list of entry points (default: all; "
                         "see --list)")
    ap.add_argument("--backend", choices=("dense", "delta", "both"),
                    default="both")
    ap.add_argument("--n", type=int, default=64)
    ap.add_argument("--ticks", type=int, default=4)
    ap.add_argument("--capacity", type=int, default=64)
    ap.add_argument("--replicas", type=int, default=2)
    ap.add_argument("--mesh", type=int, default=None,
                    help="device count for the sharded entries' mesh "
                         "(default: each entry's registered size; the "
                         "CLI provisions 4 CPU virtual devices)")
    ap.add_argument("--fail-on", choices=("error", "warning", "info",
                                          "never"),
                    default="error",
                    help="exit 1 when any finding reaches this severity")
    ap.add_argument("--json", action="store_true",
                    help="one JSON object per entry report (machine lane)")
    ap.add_argument("--census", action="store_true",
                    help="print the temporary-tensor census rows")
    ap.add_argument("--collectives", action="store_true",
                    help="print the collective-census rows of the "
                         "partitioned HLO (sharded entries)")
    ap.add_argument("--census-min-elems", type=int, default=None,
                    help="census threshold override (default: the "
                         "entry's [N, C]-class floor)")
    ap.add_argument("--no-compile", action="store_true",
                    help="skip StableHLO lowering (faster big-n census; "
                         "donation check degrades to a skip)")
    ap.add_argument("--no-lint", action="store_true",
                    help="skip the AST lint layer")
    ap.add_argument("--lint-only", action="store_true",
                    help="run only the AST lint layer (no tracing)")
    ap.add_argument("--print-budget", action="store_true",
                    help="print ready-to-paste analysis/budgets.py rows "
                         "(carry dtypes always; collective counts for "
                         "sharded entries; byte footprints — forces a "
                         "compile; see also tools/pin_budgets.py)")
    ap.add_argument("--list", action="store_true",
                    help="list registered entry points and exit")
    return ap.parse_args(argv)


def main(argv: list[str] | None = None) -> None:
    args = _parse(argv)

    if args.list:
        for name, spec in ENTRY_POINTS.items():
            print(f"{name:24s} [{'/'.join(spec.backends)}] {spec.doc}")
        return

    findings = []
    reports = []

    if not args.lint_only:
        from ringpop_tpu.analysis.contracts import audit_all
        from ringpop_tpu.analysis.registry import iter_entries

        names = args.entry.split(",") if args.entry else None
        backends = (None if args.backend == "both" else (args.backend,))
        # a typo'd --entry (or an entry/backend pair matching nothing)
        # must not fail OPEN: auditing zero programs is an error, and
        # unknown names are named
        if names is not None:
            unknown = [n for n in names if n not in ENTRY_POINTS]
            if unknown:
                sys.exit(f"audit: unknown entry point(s) {unknown}; "
                         f"--list shows the registry")
        if not list(iter_entries(names, backends)):
            sys.exit("audit: the --entry/--backend selection matches no "
                     "registered (entry, backend) pair")
        extra = {}
        if args.mesh is not None:
            # forwarded through build_entry's **extra; only the mesh
            # entries consume it (their builders pop it), so the flag
            # composes with --entry selections that include them
            extra["mesh"] = args.mesh
        reports, audit_findings = audit_all(
            names,
            backends,
            n=args.n,
            ticks=args.ticks,
            capacity=args.capacity,
            replicas=args.replicas,
            compile_programs=not args.no_compile,
            census_min_elems=args.census_min_elems,
            force_compile=args.print_budget,
            **extra,
        )
        findings += audit_findings

    lint_ran = args.lint_only or not args.no_lint
    if lint_ran:
        findings += lint_paths(Path(__file__).resolve().parent.parent)

    if args.json:
        for r in reports:
            print(json.dumps({"kind": "entry", **r.to_json()}))
        for f in findings:
            if not any(f in r.findings for r in reports):
                print(json.dumps({"kind": "finding", **f.to_json()}))
    else:
        for r in reports:
            sev = Counter(f.severity for f in r.findings)
            status = ("clean" if not r.findings else
                      " ".join(f"{v} {k}" for k, v in sorted(sev.items())))
            mesh_part = ""
            if r.mesh_size:
                from ringpop_tpu.analysis.partitioning import (
                    collective_counts,
                )

                cc = collective_counts(r.collectives)
                mesh_part = (f", mesh={r.mesh_size} collectives="
                             f"{sum(cc.values()) - cc.get('member-gather', 0)}"
                             f" member-gathers={cc.get('member-gather', 0)}")
            print(
                f"{r.entry} [{r.backend}] n={r.n}: {status}; "
                f"{len(r.census)} census rows, aliased={r.aliased_outputs}, "
                f"prng roots={r.prng.get('roots', {})}{mesh_part}"
            )
            if args.census:
                for row in r.census:
                    print(
                        f"    [{row['tag']}] {row['dtype']}"
                        f"{row['shape']} x{row['count']} via "
                        f"{row['primitive']} @ {row['path']} "
                        f"({row['bytes_each'] / 1e6:.2f} MB each)"
                    )
            if args.collectives:
                for row in r.collectives:
                    star = " MEMBER" if row["member"] else ""
                    print(
                        f"    [{row['tag']}]{star} {row['op']} "
                        f"{row['dtype']}{row['shape']} x{row['count']} "
                        f"@ {row['phase']} "
                        f"({row['bytes_each'] / 1e3:.1f} kB each)"
                    )
            if args.print_budget:
                ms = Counter()
                for leaves in r.carries.values():
                    for leaf in leaves:
                        ms[leaf.split("[")[0]] += 1
                print(f"    (\"{r.entry}\", \"{r.backend}\"): "
                      f"{dict(sorted(ms.items()))},")
                if r.mesh_size:
                    from ringpop_tpu.analysis.partitioning import (
                        collective_counts,
                    )

                    cc = collective_counts(r.collectives)
                    print(f"    (\"{r.entry}\", \"{r.backend}\", "
                          f"{r.mesh_size}): {{\"n\": {r.n}, \"counts\": "
                          f"{cc}}},")
                    # the p2p headline: a remote-copy entry pins this
                    # to zero by omission, so print it explicitly
                    print(f"    # member-gathers: "
                          f"{cc.get('member-gather', 0)}")
                if r.mem_bytes is not None:
                    fields = {k: int(r.mem_bytes[k])
                              for k in ("argument_bytes", "output_bytes",
                                        "temp_bytes", "peak_bytes")
                              if k in r.mem_bytes}
                    print(f"    (\"{r.entry}\", \"{r.backend}\", {r.n}): "
                          f"{{\"ticks\": {args.ticks}, "
                          + ", ".join(f"\"{k}\": {v}"
                                      for k, v in fields.items())
                          + "},")
        lint_findings = [f for f in findings
                         if f.contract.startswith("lint:")]
        shown = [f for f in findings
                 if SEVERITY_RANK[f.severity] >= SEVERITY_RANK["warning"]
                 or f.contract.startswith("lint:")]
        for f in shown:
            print(str(f))
        total = Counter(f.severity for f in findings)
        lint_part = (f"{len(lint_findings)} lint findings"
                     if lint_ran else "lint skipped")
        print(
            f"audit: {len(reports)} programs, {lint_part}, "
            f"{total.get('error', 0)} errors / "
            f"{total.get('warning', 0)} warnings / "
            f"{total.get('info', 0)} info"
        )

    # fail CLOSED on capability gaps too: a selection that matched
    # registered pairs but audited ZERO programs (every fixture skipped
    # — e.g. mesh entries on a host whose backend initialized with too
    # few devices) must not green-light the push
    if not args.lint_only and not reports:
        sys.exit(
            "audit: 0 programs audited — every selected entry was "
            "skipped in this environment (the info findings above name "
            "what each one needs)"
        )

    if args.fail_on != "never" and at_least(findings, args.fail_on):
        sys.exit(1)


if __name__ == "__main__":
    main()

"""AST-level lint for repo-specific hazards in library source.

ruff covers generic Python; these checks encode hazards ruff cannot
know about — patterns that are fine in host/bench code but break (or
silently serialize) the compiled paths:

* **RPL001 host-sync-in-library** — ``.block_until_ready()`` in a
  compiled-path module: a hidden drain point that serializes the
  dispatch pipeline (the streaming runner's 97% overlap depends on
  draining exactly once, at the drain site it owns);
* **RPL002 np-on-traced** — ``np.asarray`` / ``np.array`` /
  ``np.<ufunc>`` inside a traced context: on a tracer it raises at
  best and silently concretizes at worst;
* **RPL003 traced-bool-if** — a Python ``if``/``while`` whose test
  calls ``bool()`` / ``.item()`` / ``.any()`` / ``.all()`` or a
  ``jnp.*`` reduction inside a traced context: a traced boolean forced
  to a host value is a device→host sync per trace (use ``lax.cond`` /
  ``jnp.where``);
* **RPL004 wallclock-in-traced** — ``time.time`` / ``perf_counter`` /
  ``datetime.now`` inside a traced context: wall-clock reads bake a
  constant into the compiled program ("Date-free scan bodies");
* **RPL005 implicit-replication** — in the SHARDING-path modules
  (``parallel/``, ``scenarios/``): a ``jax.device_put`` with no
  placement argument, or a ``shard_map`` without explicit
  ``in_specs``/``out_specs``.  A bare ``device_put`` commits the
  array replicated (or to device 0) and every later sharded consumer
  pays a silent reshard; spec-less ``shard_map`` leaves the layout to
  inference — the partitioning contracts
  (``analysis/partitioning.py``) can only audit layouts somebody
  DECLARED.

**Traced contexts** are functions the compiler traces: any function
named ``*_impl``, any function decorated with ``jax.jit`` (bare or via
``functools.partial``), and every function nested inside one (scan
bodies, cond branches).  Everything else is host code where these
patterns are legitimate, so the walk stays quiet there — except
RPL001, which applies module-wide in compiled-path modules (the
``COMPILED_PATH_DIRS`` set) because a drain is a drain wherever the
call sits.

Suppress a true-but-intended hit with a trailing ``# audit: allow``
comment (optionally ``# audit: allow=RPL001``).
"""

from __future__ import annotations

import ast
import dataclasses
import re
from pathlib import Path
from typing import Iterable

from ringpop_tpu.analysis.findings import Finding

# Library modules whose every line is compiled-path-adjacent: a host
# sync here stalls the dispatch pipeline no matter which function it
# sits in.  obs/ and cli/ are host-side by design (the ledger's drain
# IS its job) and are not scanned by default.
COMPILED_PATH_DIRS = ("models", "scenarios", "traffic", "ops", "parallel")

# Modules that place arrays onto meshes: the implicit-replication rule
# (RPL005) applies here — everywhere else bare device_put is host code
# moving a result around, not a layout decision.
SHARDING_PATH_DIRS = ("parallel", "scenarios")

_ALLOW_RE = re.compile(r"#\s*audit:\s*allow(?:=(?P<codes>[\w,]+))?")

_WALLCLOCK_CALLS = {
    ("time", "time"),
    ("time", "perf_counter"),
    ("time", "monotonic"),
    ("time", "process_time"),
    ("datetime", "now"),
    ("datetime", "utcnow"),
}

_SYNC_METHODS = {"item", "any", "all", "tolist"}


@dataclasses.dataclass
class _Ctx:
    traced: bool
    func: str


def _is_jit_decorator(dec: ast.expr) -> bool:
    """jax.jit / partial(jax.jit, ...) / functools.partial(jax.jit,...)."""
    target = dec
    if isinstance(dec, ast.Call):
        fname = _dotted(dec.func)
        if fname and fname.split(".")[-1] == "partial" and dec.args:
            target = dec.args[0]
        else:
            target = dec.func
    name = _dotted(target)
    return bool(name) and name.split(".")[-1] == "jit"


def _dotted(node: ast.expr) -> str | None:
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class _Linter(ast.NodeVisitor):
    def __init__(self, path: str, source: str, compiled_path: bool,
                 sharding_path: bool = False):
        self.path = path
        self.lines = source.splitlines()
        self.compiled_path = compiled_path
        self.sharding_path = sharding_path
        self.findings: list[Finding] = []
        self.stack: list[_Ctx] = []

    # -- helpers ------------------------------------------------------------

    def _allowed(self, node: ast.AST, code: str) -> bool:
        # the pragma may sit on any line the node spans (a wrapped call
        # naturally carries it after the closing paren)
        first = node.lineno
        last = getattr(node, "end_lineno", None) or first
        for ln in range(first, min(last, len(self.lines)) + 1):
            m = _ALLOW_RE.search(self.lines[ln - 1])
            if m:
                codes = m.group("codes")
                return codes is None or code in codes.split(",")
        return False

    def _emit(self, node: ast.AST, code: str, message: str) -> None:
        if self._allowed(node, code):
            return
        self.findings.append(
            Finding(
                contract=f"lint:{code}",
                severity="error",
                entry=self.path,
                message=message,
                where=f"{self.path}:{node.lineno}",
            )
        )

    @property
    def _in_traced(self) -> bool:
        return any(c.traced for c in self.stack)

    # -- scope tracking -----------------------------------------------------

    def _visit_func(self, node) -> None:
        traced = node.name.endswith("_impl") or any(
            _is_jit_decorator(d) for d in node.decorator_list
        )
        self.stack.append(_Ctx(traced=traced or self._in_traced, func=node.name))
        self.generic_visit(node)
        self.stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self.stack.append(_Ctx(traced=self._in_traced, func="<lambda>"))
        self.generic_visit(node)
        self.stack.pop()

    # -- checks -------------------------------------------------------------

    def visit_Call(self, node: ast.Call) -> None:
        name = _dotted(node.func)
        if self.sharding_path and name:
            tail = name.split(".")[-1]
            if tail == "device_put":
                kwargs = {kw.arg for kw in node.keywords}
                if len(node.args) < 2 and not kwargs & {"device", "sharding"}:
                    self._emit(
                        node, "RPL005",
                        "device_put without a placement argument in a "
                        "sharding-path module: the array commits "
                        "replicated/device-0 and sharded consumers pay "
                        "a silent reshard — pass a NamedSharding, or "
                        "mark '# audit: allow=RPL005'",
                    )
            elif tail == "shard_map":
                # shard_map(f, mesh, in_specs, out_specs): either spec
                # may arrive positionally or by keyword — mixed calls
                # are fully explicit too
                kwargs = {kw.arg for kw in node.keywords}
                has_in = "in_specs" in kwargs or len(node.args) >= 3
                has_out = "out_specs" in kwargs or len(node.args) >= 4
                if not (has_in and has_out):
                    self._emit(
                        node, "RPL005",
                        "shard_map without explicit in_specs/out_specs "
                        "in a sharding-path module: inferred layouts "
                        "are exactly what the partitioning auditor "
                        "cannot hold to a declared contract — spell "
                        "the specs out, or mark '# audit: allow=RPL005'",
                    )
        if isinstance(node.func, ast.Attribute):
            if node.func.attr == "block_until_ready" and self.compiled_path:
                self._emit(
                    node, "RPL001",
                    "block_until_ready() in a compiled-path module: a "
                    "hidden drain point that serializes dispatch "
                    "pipelining — drain in the caller that owns the "
                    "pacing, or mark '# audit: allow'",
                )
        if self._in_traced and name:
            head, _, tail = name.partition(".")
            if head in ("np", "numpy") and tail and tail not in (
                "ndarray", "dtype", "int32", "int64", "float32", "bool_",
                "uint32", "int8", "uint8", "int16", "uint16", "newaxis",
            ):
                self._emit(
                    node, "RPL002",
                    f"{name}() inside traced context "
                    f"'{self.stack[-1].func}': numpy on a traced value "
                    "concretizes (host sync) or raises — use jnp",
                )
            if (head, tail) in _WALLCLOCK_CALLS or name in (
                "perf_counter", "datetime.datetime.now"
            ):
                self._emit(
                    node, "RPL004",
                    f"wall-clock read {name}() inside traced context "
                    f"'{self.stack[-1].func}': the value is baked into "
                    "the compiled program at trace time",
                )
        self.generic_visit(node)

    def _check_test(self, node: ast.stmt, test: ast.expr) -> None:
        if not self._in_traced:
            return
        for sub in ast.walk(test):
            if not isinstance(sub, ast.Call):
                continue
            name = _dotted(sub.func)
            if isinstance(sub.func, ast.Attribute) and sub.func.attr in _SYNC_METHODS:
                self._emit(
                    node, "RPL003",
                    f".{sub.func.attr}() in a Python branch condition "
                    f"inside traced context '{self.stack[-1].func}': a "
                    "traced boolean forced to host — use lax.cond / "
                    "jnp.where",
                )
            elif name and name.split(".")[0] in ("jnp",) and name.split(
                "."
            )[-1] in ("any", "all", "sum", "max", "min"):
                self._emit(
                    node, "RPL003",
                    f"{name}(...) in a Python branch condition inside "
                    f"traced context '{self.stack[-1].func}': the "
                    "branch concretizes a traced boolean — use "
                    "lax.cond / jnp.where",
                )
            elif name == "bool":
                self._emit(
                    node, "RPL003",
                    "bool(...) in a Python branch condition inside a "
                    "traced context forces a traced value to host",
                )

    def visit_If(self, node: ast.If) -> None:
        self._check_test(node, node.test)
        self.generic_visit(node)

    def visit_While(self, node: ast.While) -> None:
        self._check_test(node, node.test)
        self.generic_visit(node)


def lint_source(source: str, path: str = "<string>",
                compiled_path: bool = True,
                sharding_path: bool = False) -> list[Finding]:
    """Lint one module's source text; ``compiled_path`` enables the
    module-wide RPL001 host-sync rule, ``sharding_path`` the RPL005
    implicit-replication rule."""
    tree = ast.parse(source, filename=path)
    linter = _Linter(path, source, compiled_path, sharding_path)
    linter.visit(tree)
    return linter.findings


def lint_paths(root: str | Path,
               dirs: Iterable[str] = COMPILED_PATH_DIRS) -> list[Finding]:
    """Lint every .py under ``root/<dir>`` for each compiled-path dir
    (plus root-level modules, which host several ``*_impl``-free but
    traced-adjacent helpers — they get the traced-context rules only)."""
    root = Path(root)
    findings: list[Finding] = []
    seen: set[Path] = set()
    for d in dirs:
        for p in sorted((root / d).rglob("*.py")):
            seen.add(p)
            findings += lint_source(
                p.read_text(), str(p.relative_to(root.parent)),
                compiled_path=True,
                sharding_path=d in SHARDING_PATH_DIRS,
            )
    for p in sorted(root.glob("*.py")):
        if p not in seen:
            findings += lint_source(
                p.read_text(), str(p.relative_to(root.parent)),
                compiled_path=False,
            )
    return findings

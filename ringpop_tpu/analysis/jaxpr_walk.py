"""Recursive jaxpr traversal: sub-jaxprs, scan carries, key lineage.

Everything here is pure trace-level analysis — no compilation, no
execution — so it runs in seconds on a CPU host even for programs
whose compiled form needs a TPU (the Mosaic kernel entry point) or
crashes one (the round-5 n=65,536 delta program).

Three layers:

* ``iter_eqns``        — depth-first equation iteration through every
  sub-jaxpr a primitive carries (pjit ``jaxpr``, scan/while bodies,
  cond ``branches``, custom_* ``call_jaxpr``), with a readable path
  string ("scan/cond/pjit") per equation;
* ``primary_scans``    — the scan equations NOT nested inside another
  scan: the tick loops whose carries are the HBM-resident state the
  dtype budget pins (inner searchsorted/fori scans are sub-kernels);
* ``KeyLineageAnalysis`` — a forward dataflow over PRNG key material:
  which declared key roots reach which derive/draw sites, whether two
  roots ever mix, and whether any single key value is consumed by more
  than one bit-drawing equation (classic key reuse).
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Any, Iterator

import jax

from ringpop_tpu.analysis.findings import Finding

# ---------------------------------------------------------------------------
# generic traversal
# ---------------------------------------------------------------------------


def _sub_jaxprs(eqn) -> Iterator[Any]:
    """Every inner (closed or open) jaxpr an equation's params carry."""
    for val in eqn.params.values():
        vals = val if isinstance(val, (list, tuple)) else (val,)
        for item in vals:
            if hasattr(item, "jaxpr") and hasattr(item, "consts"):
                yield item.jaxpr  # ClosedJaxpr
            elif hasattr(item, "eqns"):
                yield item  # open Jaxpr


def iter_eqns(jaxpr, path: str = "") -> Iterator[tuple[str, Any]]:
    """Yield ``(path, eqn)`` depth-first over ``jaxpr`` and every
    sub-jaxpr.  ``path`` lists the enclosing primitives ("scan/cond");
    the top level is ""."""
    inner = getattr(jaxpr, "jaxpr", jaxpr)  # accept ClosedJaxpr
    for eqn in inner.eqns:
        yield path, eqn
        sub_path = f"{path}/{eqn.primitive.name}" if path else eqn.primitive.name
        for sub in _sub_jaxprs(eqn):
            yield from iter_eqns(sub, sub_path)


def primary_scans(jaxpr) -> list[tuple[str, Any]]:
    """The ``scan`` equations not nested inside another scan — the
    tick loops whose carries ride in HBM across the whole horizon."""
    return [
        (path, eqn)
        for path, eqn in iter_eqns(jaxpr)
        if eqn.primitive.name == "scan" and "scan" not in path.split("/")
    ]


def scan_carry_avals(eqn) -> list[Any]:
    """The carry avals of one scan equation (consts excluded)."""
    nc = eqn.params["num_consts"]
    ncar = eqn.params["num_carry"]
    return [v.aval for v in eqn.invars[nc : nc + ncar]]


def all_avals(jaxpr) -> Iterator[tuple[str, str, Any]]:
    """Every equation output aval in the program: ``(path, primitive,
    aval)`` — the temporary-tensor census's raw stream."""
    for path, eqn in iter_eqns(jaxpr):
        for v in eqn.outvars:
            aval = getattr(v, "aval", None)
            if aval is not None and hasattr(aval, "shape"):
                yield path, eqn.primitive.name, aval


# ---------------------------------------------------------------------------
# PRNG key-lineage dataflow
# ---------------------------------------------------------------------------

# Primitives that GENERATE bits from a key — the consumption sites the
# reuse rule counts.  (With typed keys, uniform/randint draw through
# random_bits; the raw-key legacy path bottoms out in threefry2x32.)
DRAW_PRIMS = frozenset({"random_bits", "threefry2x32"})

# Primitives that DERIVE new, statistically independent keys from a
# key.  Fan-out through these is the sanctioned idiom (split streams,
# fold_in domain tags) and is never flagged by itself.
DERIVE_PRIMS = frozenset({"random_split", "random_fold_in", "random_seed"})

# Value-preserving plumbing: the output IS the input key (re-typed,
# re-laid-out, or copied) — same value id, same roots.
PASSTHROUGH_PRIMS = frozenset(
    {
        "random_wrap",
        "random_unwrap",
        "convert_element_type",
        "bitcast_convert_type",
        "reshape",
        "squeeze",
        "broadcast_in_dim",
        "copy",
        "device_put",
        "optimization_barrier",
        "stop_gradient",
    }
)

# Indexing: the output is a sub-key of a stacked key tensor (a row of
# the per-tick schedule, one of split's children).  Key material with
# the same roots, but a DISTINCT value per call site.
INDEX_PRIMS = frozenset(
    {"slice", "dynamic_slice", "gather", "transpose", "concatenate", "rev"}
)


@dataclasses.dataclass
class _KeyVal:
    """Key material flowing through one var: which declared roots it
    descends from, and a value identity (creation-site token) shared
    only by vars provably holding the same key value."""

    roots: frozenset[str]
    vid: int


class KeyLineageAnalysis:
    """Forward dataflow over a closed jaxpr tracking PRNG key material.

    ``roots`` maps a root-stream name ("protocol", "workload") to the
    set of top-level flat input indices holding that stream's key
    tensor(s).  The analysis propagates (root-set, value-id) through
    passthrough/index/derive primitives, unions root-sets through
    arithmetic that combines key material, and records every draw /
    derive site per value id.

    Violations:

    * ``prng-mixing``  (error): a derive or draw consumes key material
      descended from two different declared roots — the streams share
      a lineage;
    * ``prng-reuse``   (error): the same key value feeds two distinct
      bit-drawing equations — two "independent" streams are reading
      the same bits;
    * ``prng-draw-and-derive`` (warning): a key value is both drawn
      from and used to derive children — the children correlate with
      the drawn bits (JAX's key-reuse doctrine).

    Scan carries iterate to a root-set fixpoint (a key threaded
    through the carry picks up every root it ever held).
    """

    def __init__(self, closed_jaxpr, roots: dict[str, list[int]]):
        self.closed = closed_jaxpr
        self.roots = roots
        self.findings: list[Finding] = []
        self.draw_sites: dict[int, list[str]] = {}
        self.derive_sites: dict[int, list[str]] = {}
        self.root_draws: dict[str, int] = {name: 0 for name in roots}
        self._vid = itertools.count(1)
        self._site_vids: dict[tuple[int, int], int] = {}

    # -- plumbing -----------------------------------------------------------

    def _fresh(self, roots: frozenset[str], site: tuple[int, int]) -> _KeyVal:
        """A derived key value: new value id per (eqn site, out slot),
        stable across fixpoint re-visits of the same equation."""
        vid = self._site_vids.setdefault(site, next(self._vid))
        return _KeyVal(roots=roots, vid=vid)

    @staticmethod
    def _read(env: dict, var) -> _KeyVal | None:
        if type(var).__name__ == "Literal":
            return None
        return env.get(var)

    def _record_use(self, kind: str, kv: _KeyVal, path: str) -> None:
        store = self.draw_sites if kind == "draw" else self.derive_sites
        store.setdefault(kv.vid, []).append(path)
        if kind == "draw":
            for r in kv.roots:
                self.root_draws[r] = self.root_draws.get(r, 0) + 1

    # -- the walk -----------------------------------------------------------

    def run(self, entry: str) -> list[Finding]:
        inner = self.closed.jaxpr
        env: dict[Any, _KeyVal] = {}
        for name, idxs in self.roots.items():
            for i in idxs:
                if i < len(inner.invars):
                    env[inner.invars[i]] = _KeyVal(
                        roots=frozenset({name}), vid=next(self._vid)
                    )
        self._walk(inner, env, path="", entry=entry)
        self._finalize(entry)
        return self.findings

    def _walk(self, jaxpr, env: dict, path: str, entry: str) -> None:
        for eqn_i, eqn in enumerate(jaxpr.eqns):
            name = eqn.primitive.name
            in_kvs = [self._read(env, v) for v in eqn.invars]
            key_ins = [kv for kv in in_kvs if kv is not None]
            sub_path = f"{path}/{name}" if path else name

            if key_ins:
                roots = frozenset().union(*(kv.roots for kv in key_ins))
                if name in DRAW_PRIMS or name in DERIVE_PRIMS:
                    if len(roots) > 1:
                        self.findings.append(
                            Finding(
                                contract="prng-lineage",
                                severity="error",
                                entry=entry,
                                message=(
                                    f"prng-mixing: {name} consumes key "
                                    f"material from roots "
                                    f"{sorted(roots)} — the streams "
                                    "share a lineage"
                                ),
                                where=sub_path,
                            )
                        )
                    for kv in key_ins:
                        self._record_use(
                            "draw" if name in DRAW_PRIMS else "derive",
                            kv,
                            sub_path,
                        )

            subs = list(_sub_jaxprs(eqn))
            if subs:
                self._walk_call(eqn, subs, env, in_kvs, sub_path, entry)
                continue

            # propagate key material to outputs
            if not key_ins or name in DRAW_PRIMS:
                continue  # drawn bits are data, not key material
            roots = frozenset().union(*(kv.roots for kv in key_ins))
            for out_i, ov in enumerate(eqn.outvars):
                if name in PASSTHROUGH_PRIMS and len(key_ins) == 1:
                    env[ov] = key_ins[0]
                else:
                    # derive / index / arithmetic combination: key
                    # material with a fresh value per site
                    env[ov] = self._fresh(roots, (id(eqn), out_i))

    # -- call-like primitives (pjit / scan / cond / while / custom_*) -------

    def _walk_call(self, eqn, subs, env, in_kvs, sub_path, entry) -> None:
        name = eqn.primitive.name
        if name == "scan":
            nc = eqn.params["num_consts"]
            ncar = eqn.params["num_carry"]
            body = subs[0]
            inner = getattr(body, "jaxpr", body)
            # consts + carry map 1:1; xs rows are indexed sub-keys
            inner_env: dict[Any, _KeyVal] = {}
            for i, v in enumerate(inner.invars):
                kv = in_kvs[i] if i < len(in_kvs) else None
                if kv is None:
                    continue
                if i < nc + ncar:
                    inner_env[v] = kv
                else:
                    inner_env[v] = self._fresh(kv.roots, (id(eqn), -1 - i))
            # fixpoint over the carry root-sets (2 passes suffice for a
            # monotone union lattice of this depth)
            for _ in range(3):
                probe = dict(inner_env)
                self._walk_quiet(inner, probe, sub_path, entry)
                changed = False
                for ci in range(ncar):
                    ov = inner.outvars[ci]
                    okv = self._read(probe, ov)
                    iv = inner.invars[nc + ci]
                    ikv = inner_env.get(iv)
                    if okv is None:
                        continue
                    merged = okv.roots | (ikv.roots if ikv else frozenset())
                    if ikv is None or merged != ikv.roots:
                        inner_env[iv] = self._fresh(merged, (id(eqn), -100 - ci))
                        changed = True
                if not changed:
                    break
            # final accounted pass
            final = dict(inner_env)
            self._walk(inner, final, sub_path, entry)
            # the classic scan reuse: a key threaded UNCHANGED through
            # the carry (same value id in as out) and drawn inside the
            # body draws identical bits every iteration — per-site
            # counting alone cannot see it (one site, T draws of one
            # value), so the carry loop is checked explicitly
            for ci in range(ncar):
                ikv = final.get(inner.invars[nc + ci])
                okv = self._read(final, inner.outvars[ci])
                if (
                    ikv is not None
                    and okv is not None
                    and okv.vid == ikv.vid
                    and ikv.vid in self.draw_sites
                ):
                    self.findings.append(
                        Finding(
                            contract="prng-lineage",
                            severity="error",
                            entry=entry,
                            message=(
                                "prng-reuse: a key threaded unchanged "
                                "through the scan carry is drawn inside "
                                "the body — every iteration reads the "
                                "same bits (fold_in the tick, or split "
                                "the carry key)"
                            ),
                            where=sub_path,
                        )
                    )
            for ci, ov in enumerate(eqn.outvars):
                okv = self._read(final, inner.outvars[ci])
                if okv is not None:
                    env[ov] = self._fresh(okv.roots, (id(eqn), 1000 + ci))
        elif name in ("cond", "switch"):
            # invars = predicate + operands shared by every branch.
            # Branches are MUTUALLY EXCLUSIVE: a key drawn once in each
            # branch is drawn once at runtime, so each branch's
            # draw/derive sites are collected in isolation and merged
            # per value-id with the MAX across branches (a single
            # branch drawing twice still trips the reuse rule).
            out_roots: list[frozenset | None] = [None] * len(eqn.outvars)
            branch_draws: list[dict[int, list[str]]] = []
            branch_derives: list[dict[int, list[str]]] = []
            branch_roots: list[dict[str, int]] = []
            for branch in subs:
                inner = getattr(branch, "jaxpr", branch)
                inner_env = {
                    v: kv
                    for v, kv in zip(inner.invars, in_kvs[1:])
                    if kv is not None
                }
                saved = (self.draw_sites, self.derive_sites,
                         self.root_draws)
                self.draw_sites, self.derive_sites = {}, {}
                self.root_draws = dict.fromkeys(saved[2], 0)
                try:
                    self._walk(inner, inner_env, sub_path, entry)
                    branch_draws.append(self.draw_sites)
                    branch_derives.append(self.derive_sites)
                    branch_roots.append(self.root_draws)
                finally:
                    (self.draw_sites, self.derive_sites,
                     self.root_draws) = saved
                for oi, ov in enumerate(inner.outvars):
                    okv = self._read(inner_env, ov)
                    if okv is not None:
                        out_roots[oi] = (out_roots[oi] or frozenset()) | okv.roots
            for store, per_branch in ((self.draw_sites, branch_draws),
                                      (self.derive_sites, branch_derives)):
                for vid in {v for b in per_branch for v in b}:
                    heaviest = max(
                        (b.get(vid, []) for b in per_branch), key=len
                    )
                    store.setdefault(vid, []).extend(heaviest)
            for root in self.root_draws:
                self.root_draws[root] += max(
                    (b.get(root, 0) for b in branch_roots), default=0
                )
            for oi, roots in enumerate(out_roots):
                if roots:
                    env[eqn.outvars[oi]] = self._fresh(roots, (id(eqn), oi))
        elif name == "while":
            # cond_jaxpr/body_jaxpr over cond_consts + body_consts + carry
            body = eqn.params.get("body_jaxpr")
            ncc = eqn.params.get("cond_nconsts", 0)
            nbc = eqn.params.get("body_nconsts", 0)
            if body is None:
                return
            inner = body.jaxpr
            carry_kvs = in_kvs[ncc + nbc :]
            inner_env = {}
            for i, v in enumerate(inner.invars):
                kv = (in_kvs[ncc + i] if i < nbc else
                      carry_kvs[i - nbc] if i - nbc < len(carry_kvs) else None)
                if kv is not None:
                    inner_env[v] = kv
            self._walk(inner, inner_env, sub_path, entry)
            for oi, ov in enumerate(eqn.outvars):
                okv = self._read(inner_env, inner.outvars[oi])
                if okv is not None:
                    env[ov] = self._fresh(okv.roots, (id(eqn), oi))
        else:
            # pjit / closed_call / custom_jvp / remat: operands map 1:1
            inner = getattr(subs[0], "jaxpr", subs[0])
            inner_env = {
                v: kv for v, kv in zip(inner.invars, in_kvs) if kv is not None
            }
            self._walk(inner, inner_env, sub_path, entry)
            for ov, iv in zip(eqn.outvars, inner.outvars):
                okv = self._read(inner_env, iv)
                if okv is not None:
                    env[ov] = self._fresh(okv.roots, (id(eqn), id(ov)))

    def _walk_quiet(self, jaxpr, env, path, entry) -> None:
        """A probe pass that records nothing: used to reach the scan
        carry fixpoint before the single accounted pass."""
        saved = (self.findings, self.draw_sites, self.derive_sites,
                 self.root_draws)
        self.findings, self.draw_sites, self.derive_sites, self.root_draws = (
            [], {}, {}, dict.fromkeys(self.root_draws, 0)
        )
        try:
            self._walk(jaxpr, env, path, entry)
        finally:
            (self.findings, self.draw_sites, self.derive_sites,
             self.root_draws) = saved

    # -- verdicts -----------------------------------------------------------

    def _finalize(self, entry: str) -> None:
        for vid, sites in self.draw_sites.items():
            if len(sites) > 1:
                self.findings.append(
                    Finding(
                        contract="prng-lineage",
                        severity="error",
                        entry=entry,
                        message=(
                            f"prng-reuse: one key value feeds "
                            f"{len(sites)} bit-drawing sites — the "
                            "streams read the same bits"
                        ),
                        where="; ".join(sorted(set(sites))[:4]),
                    )
                )
            elif vid in self.derive_sites:
                self.findings.append(
                    Finding(
                        contract="prng-lineage",
                        severity="warning",
                        entry=entry,
                        message=(
                            "prng-draw-and-derive: a key value is both "
                            "drawn from and split/folded — derived "
                            "children correlate with the drawn bits"
                        ),
                        where="; ".join(
                            sorted(set(sites + self.derive_sites[vid]))[:4]
                        ),
                    )
                )
        for name, count in self.root_draws.items():
            if count == 0:
                self.findings.append(
                    Finding(
                        contract="prng-lineage",
                        severity="info",
                        entry=entry,
                        message=(
                            f"declared key root '{name}' never reaches "
                            "a bit-drawing site in this program"
                        ),
                    )
                )

    def summary(self) -> dict[str, Any]:
        """Machine-readable lineage stats: per-root draw counts and the
        fan-out shape (derive/draw site totals)."""
        return {
            "roots": dict(self.root_draws),
            "draw_values": len(self.draw_sites),
            "derive_values": len(self.derive_sites),
        }


def key_lineage(closed_jaxpr, roots: dict[str, list[int]], entry: str):
    """Run the lineage analysis; returns ``(findings, summary)``."""
    an = KeyLineageAnalysis(closed_jaxpr, roots)
    findings = an.run(entry)
    return findings, an.summary()


def tree_flat_index_of(args: tuple, target: Any) -> list[int]:
    """Flat leaf indices (under ``jax.tree_util.tree_flatten(args)``)
    of every leaf that IS ``target`` — how the registry names a key
    root without hard-coding pytree layouts."""
    leaves, _ = jax.tree_util.tree_flatten(args)
    return [i for i, leaf in enumerate(leaves) if leaf is target]

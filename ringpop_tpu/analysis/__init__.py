"""Trace-contract auditor: static analysis of the compiled programs.

Every guarantee the repo's performance story rests on — one XLA
compile per signature, no host syncs inside compiled paths, buffer
donation actually applied, bounded carry dtypes, independent PRNG
streams — was historically enforced by runtime oracles that catch
violations AFTER an expensive run (or after a TPU worker crash, round
5).  This package machine-checks those invariants at trace time, on
CPU, in seconds:

* ``registry``  — the audited entry points (swim_run, delta_run,
  run_scenario, run_sweep, the traffic+latency-coupled scan,
  recv_merge_pallas, and the SHARDED fixtures: the mesh-2/4 dense
  step and the replica-sharded sweep), each with a small lowerable
  fixture;
* ``jaxpr_walk`` — recursive jaxpr traversal: sub-jaxpr iteration,
  primary-scan carry extraction, PRNG key-lineage dataflow;
* ``contracts`` — the five trace-contract checks over a lowered entry
  point (host transfers, donation, carry dtypes, key lineage,
  temporary-tensor census);
* ``partitioning`` — the three compiled-level contracts over the
  post-SPMD HLO of the sharded entries (collective census with
  per-phase bytes and the member-gather rule, sharding-propagation
  survival, pinned byte budgets) — audited against CPU virtual
  devices, no chip required;
* ``budgets``  — the pinned budget tables: per-entry carry dtype
  multisets, per-(entry, mesh) collective censuses, per-(entry, n)
  compiled-byte footprints (a widened slot / new collective / bytes
  regression fails the audit; re-pin via tools/pin_budgets.py);
* ``lint``     — the AST-level lint layer for repo hazards in library
  source (host syncs, ``np.asarray`` on traced values, Python ``if``
  on traced booleans, wall-clock reads in scan bodies);
* ``cli``      — ``python -m ringpop_tpu audit`` with ``--fail-on``
  severity gating.

See docs/analysis.md for the contract definitions and report format.
"""

from ringpop_tpu.analysis.findings import (  # noqa: F401
    SEVERITY_RANK,
    Finding,
    max_severity,
)
from ringpop_tpu.analysis.lint import lint_paths, lint_source  # noqa: F401

__all__ = [
    "Finding",
    "SEVERITY_RANK",
    "max_severity",
    "lint_paths",
    "lint_source",
]

"""Pinned budgets per compiled program: carry dtypes, collectives, bytes.

Three tables, one review-gate idea: the auditor compares what the
trace/lowering ACTUALLY produces against what a human explicitly
ALLOWED, so a silent regression (a widened carry slot, a new
all-gather in a sharded program, a compiled-bytes jump) fails the
audit until the change is justified and the row re-pinned.

* ``CARRY_BUDGETS``      — the multiset of primary-scan carry dtypes
  per (entry, backend); shape-independent, one pin covers every n
  (``contracts.check_carry_dtypes``).
* ``COLLECTIVE_BUDGETS`` — the census of collective ops in the
  PARTITIONED HLO per (entry, backend, mesh size): op-kind counts
  plus the member-gather count (all-gathers that rebuild a full
  member-axis tensor — the replication traffic ROADMAP item 1's
  remote-copy gossip must drive to zero).  Counts are partitioner
  decisions, so each row records the fixture ``n`` it was pinned at
  and is only compared at that shape
  (``partitioning.check_collectives``).
* ``BYTE_BUDGETS``       — XLA ``memory_analysis`` footprints per
  (entry, backend, n) at a pinned tick count, with a tolerance band:
  over-band fails (the 65k wall got closer), under-band by more than
  the tolerance is a prompt to re-pin and LOCK IN the reduction
  (``partitioning.check_byte_budget``).

Regenerate after an intentional change with::

    python tools/pin_budgets.py            # all three tables
    python -m ringpop_tpu audit --entry NAME --backend B --print-budget

All three tables assume the pinned jax build
(``ringpop_tpu.utils.jaxpin``): a version bump makes them stale, and
the partitioning checks downgrade to a warning instead of bit-diffing
a different partitioner's output.
"""

from __future__ import annotations

from collections import Counter

# (entry, backend) -> {dtype name: carry-leaf count}.  Pinned from the
# audit of the seed fixtures (n is immaterial; the multiset is
# shape-independent).  The dense carry is view_key int32[N, N] + the
# int8 lattice planes + the scan-threaded net bits; the delta carry is
# the windowed claim state (int32 slots + uint32 hash row + the
# bit-packed base plane, uint32 words since r06); run_scenario adds
# the net carry (up/responsive packed to uint32 words, gid int32,
# period int16 — the r06 narrowings: the bool[N] planes ride the scan
# as ceil(N/32) uint32 words, the period row fits int16 after a loud
# host-side range check); run_scenario+traffic is carry-identical to
# run_scenario (the serving plane stacks ys, it carries nothing);
# recv_merge_pallas's two int32 scans are the searchsorted lowering
# inside the wrapper.  ZERO bool leaves is now the pin: a bool
# reappearing in any scan carry means a plane escaped the packing.
CARRY_BUDGETS: dict[tuple[str, str], dict[str, int]] = {
    ("swim_run", "dense"): {"int32": 2, "int8": 2},
    ("delta_run", "delta"): {"int32": 7, "int8": 2, "uint32": 2},
    ("run_scenario", "dense"): {"int32": 3, "int8": 2, "uint32": 2},
    ("run_scenario", "delta"): {"int32": 8, "int8": 2, "uint32": 4},
    ("run_scenario+traffic", "dense"): {"int32": 3, "int8": 2, "uint32": 2},
    ("run_scenario+traffic", "delta"): {"int32": 8, "int8": 2, "uint32": 4},
    # the incident shape adds the overload feedback carry on top of
    # run_scenario+traffic — ov_gray (packed uint32 words), ov_cnt
    # (int32[N], left wide: unbounded accumulation) — plus the period
    # row the overload fixture always materializes (int16 since r06)
    ("run_scenario+incident", "dense"): {"int16": 1, "int32": 4, "int8": 2,
                                         "uint32": 3},
    ("run_scenario+incident", "delta"): {"int16": 1, "int32": 9, "int8": 2,
                                         "uint32": 5},
    # the policy shape adds the remediation carry on top of the
    # incident rows: pressure + amp windows + retry cap (4 x int32)
    # and the bit-packed shed/quarantine planes (2 x uint32) —
    # bools never ride the carry unpacked (the PR 16 packing rule)
    ("run_scenario+policy", "dense"): {"int16": 1, "int32": 8, "int8": 2,
                                       "uint32": 5},
    ("run_scenario+policy", "delta"): {"int16": 1, "int32": 13, "int8": 2,
                                       "uint32": 7},
    # the provenance shape adds the rumor-tracing carry on top of
    # run_scenario — slot/wits/parent (3 x int32), tickv/first
    # (2 x int16: ticks are bounded MAX_TICKS host-side), and the
    # bit-packed knows plane (1 x uint32) — ZERO bool leaves, like
    # every plane since PR 16; the legacy rows above are the
    # prov-off pin: arming must not change THEM
    ("run_scenario+provenance", "dense"): {"int16": 2, "int32": 6,
                                           "int8": 2, "uint32": 3},
    ("run_scenario+provenance", "delta"): {"int16": 2, "int32": 11,
                                           "int8": 2, "uint32": 5},
    ("run_sweep", "dense"): {"int32": 3, "int8": 2, "uint32": 2},
    ("run_sweep", "delta"): {"int32": 8, "int8": 2, "uint32": 4},
    # the knob-grid sweep carries EXACTLY the run_sweep rows: the traced
    # protocol knobs (sim.SwimKnobs) close over the scan body as
    # constants — a knob leaking into the carry would surface here
    ("run_sweep+param_axes", "dense"): {"int32": 3, "int8": 2, "uint32": 2},
    ("run_sweep+param_axes", "delta"): {"int32": 8, "int8": 2, "uint32": 4},
    ("recv_merge_pallas", "dense"): {"int32": 2},
    # the fused delta insert-merge kernel is scan-free: its merge
    # inversion is pure VPU arithmetic (compare-reduces + lane rolls),
    # no lax.scan anywhere in the lowering — the empty multiset IS the
    # pin
    ("delta_merge_pallas", "delta"): {},
    # the sharded step has no tick scan: its "carries" are the int32
    # loop state of the step's inner sort/fori kernels (primary at this
    # program's top level).  The ring gossip plane re-pins the dense
    # rows 44 -> 24: the sorted receiver-merge's Hillis-Steele
    # while_loops vanish with the merge (ring_recv_merge is loop-free
    # scatter-max over hops), taking 20 int32 loop slots with them.
    # The +gather entry keeps the legacy 44 — it IS the PR-15 lowering.
    # The sharded sweep's carry is bit-identical to the unsharded
    # run_sweep rows — sharding the replica axis must never change WHAT
    # the scan carries, only where it lives.
    ("sharded_step", "dense"): {"int32": 24},
    ("sharded_step@4", "dense"): {"int32": 24},
    ("sharded_step+gather", "dense"): {"int32": 44},
    ("sharded_delta_step", "delta"): {"int32": 110},
    ("run_sweep+shard", "dense"): {"int32": 3, "int8": 2, "uint32": 2},
    ("run_sweep+shard", "delta"): {"int32": 8, "int8": 2, "uint32": 4},
}


def expected(entry: str, backend: str) -> dict[str, int] | None:
    return CARRY_BUDGETS.get((entry, backend))


# (entry, backend, mesh size) -> {"n": fixture n the row was pinned at,
# "counts": {collective kind: op count}}.  "member-gather" counts the
# all-gathers whose output rebuilds a full member-axis tensor (an
# [N, *]-class plane re-replicated across the mesh) — the current
# viewer-row sharded step pays dozens of them per tick, which is
# exactly why ROADMAP item 1 wants remote-copy gossip; this table is
# the regression gate AND the progress ledger for that rebuild (the
# pinned member-gather count must only ever go DOWN).  run_sweep+shard
# is data-parallel by construction: its only collectives are the
# scalar-telemetry all-reduces, and any member-gather appearing there
# is a broken replica axis.  Pinned via tools/pin_budgets.py.
COLLECTIVE_BUDGETS: dict[tuple[str, str, int], dict] = {
    # the ring gossip plane (ops/gossip_remote_copy.py): the 75
    # member-gathers of the PR-15 lowering are GONE — claims, acks, and
    # the per-row index tensors all move as neighbor-exchange permutes
    # (collective-permute 36 -> 71: D-1 hops per circulated plane),
    # and the residual all-gathers are rank-1 [N] rows (status bits,
    # run bounds) the census exempts by design.  These entries declare
    # p2p_only, so a member-gather is an ERROR before the count is
    # even compared; the pinned zero (by omission) is the tentpole's
    # claim.  The pre-ring census for the record: {"all-gather": 143,
    # "all-reduce": 58, "collective-permute": 36, "member-gather": 75}
    # — kept live (and pinned below) under the sharded_step+gather
    # entry, the bench baseline.
    ("sharded_step", "dense", 2): {
        "n": 64,
        "counts": {"all-gather": 13, "all-reduce": 25,
                   "collective-permute": 71},
    },
    # mesh 4 re-partitions the same program: identical gather/reduce
    # structure, the permute lanes scale with the hop count (D-1 hops
    # per ring primitive call)
    ("sharded_step@4", "dense", 4): {
        "n": 64,
        "counts": {"all-gather": 13, "all-reduce": 25,
                   "collective-permute": 187},
    },
    # the delta claim routing over the ring: segment rows circulate as
    # permute hops (route_claims' [S*N, W] row table never replicates),
    # the all-reduces are the stage preds' jnp.any gates
    ("sharded_delta_step", "delta", 2): {
        "n": 64,
        "counts": {"all-gather": 25, "all-reduce": 106,
                   "collective-permute": 77},
    },
    # the PR-15 all-gather lowering, kept live as the multichip bench's
    # baseline: this row IS the pre-ring census for the record.  Not
    # p2p_only — the 75 member-gathers are its measured cost, compared
    # here rather than outlawed.
    ("sharded_step+gather", "dense", 2): {
        "n": 64,
        "counts": {"all-gather": 143, "all-reduce": 58,
                   "collective-permute": 36, "member-gather": 75},
    },
    # the replica-sharded sweeps are data-parallel by construction:
    # dense reduces its 10 scalar telemetry sums, delta is fully local
    # (every reduction already lives inside a replica's rows) — both
    # entries also declare p2p_only, so ANY member-gather is an error
    # before the count is even compared
    ("run_sweep+shard", "dense", 2): {"n": 64, "counts": {"all-reduce": 10}},
    ("run_sweep+shard", "delta", 2): {"n": 64, "counts": {}},
}


def collective_budget(entry: str, backend: str, mesh: int) -> dict | None:
    return COLLECTIVE_BUDGETS.get((entry, backend, mesh))


# (entry, backend, n) -> {"ticks": pinned tick count, then the
# obs.ledger.memory_row byte fields}.  Compared only when the audited
# (n, ticks) match the pin, within BYTE_TOLERANCE (compile scheduling
# wiggle; the interesting regressions are way outside the band).
# cpu-platform numbers: the audit always runs on the CPU host, and
# relative movement there tracks the compiled program's shape — the
# TPU-absolute numbers live in mem_census/BENCH rows.  Pinned via
# tools/pin_budgets.py; the n=65,536 delta row is the ROADMAP item 2
# flagship ledger (the program that killed the round-5 worker), pinned
# in the slow lane.
BYTE_BUDGETS: dict[tuple[str, str, int], dict[str, int]] = {
    # the fast gate: dense pays ~890 MB peak at n=4096 (the [N, N]
    # planes) vs delta's ~36 MB — the 25x gap IS the reason delta is
    # the scale flagship
    ("run_scenario", "dense", 4096): {
        "ticks": 4, "argument_bytes": 100687936,
        "output_bytes": 100688256, "temp_bytes": 789049144,
        "peak_bytes": 889737460,
    },
    # r06 re-pin: peak 56446768 -> 35991920 (-36.2%) from the
    # two-key-sort claim-row rewrite + gather-based insert merge +
    # bit-packed planes (was {"ticks": 4, "argument_bytes": 2715716,
    # "output_bytes": 2716116, "temp_bytes": 53730592,
    # "peak_bytes": 56446768})
    ("run_scenario", "delta", 4096): {
        "ticks": 4, "argument_bytes": 2712132, "output_bytes": 2712532,
        "temp_bytes": 33279328, "peak_bytes": 35991920,
    },
    # the flagship ledger (slow lane): the n=65,536 delta program that
    # killed the round-5 TPU worker pinned at ~903 MB derived peak on
    # the CPU analysis through r05; the r06 pass (killed [N, C+K+1]
    # concat-sort temps, gather merges, packed planes, narrowed
    # carries) re-pins it at ~576 MB — ROADMAP item 2a's ">=30% peak
    # reduction / <= ~632 MB" target, met at -36.2%.  Pre-r06 row for
    # the record: {"ticks": 4, "argument_bytes": 43450436,
    # "output_bytes": 43450836, "temp_bytes": 859516192,
    # "peak_bytes": 902967088}
    ("run_scenario", "delta", 65536): {
        "ticks": 4, "argument_bytes": 43393092,
        "output_bytes": 43393492, "temp_bytes": 532295008,
        "peak_bytes": 575688560,
    },
}

# Fractional tolerance band around every pinned byte field.
BYTE_TOLERANCE = 0.10


def byte_budget(entry: str, backend: str, n: int) -> dict[str, int] | None:
    return BYTE_BUDGETS.get((entry, backend, n))


def format_multiset(ms: Counter | dict[str, int]) -> str:
    items = sorted(dict(ms).items())
    return "{" + ", ".join(f"{k}: {v}" for k, v in items) + "}"

"""Pinned carry dtype budgets per (entry point, backend).

The multiset of primary-scan carry dtypes each compiled program is
ALLOWED to hold.  The auditor (``contracts.check_carry_dtypes``)
compares the traced carries against this table: a widened slot (int32
where int8 was pinned), a new carry leaf, or a dropped one fails the
audit until the change is justified and the row here re-pinned — the
review gate ROADMAP item 2(a)'s footprint hunt needs (carry bytes are
the resident-HBM floor of every streamed soak).

Regenerate a row after an intentional carry change with:

    python -m ringpop_tpu audit --entry NAME --backend B --print-budget

The counts are shape-independent (dtype multiset only), so one pin
covers every n.  ``run_scenario+traffic`` rows include the serving
plane's counters; the plain ``run_scenario`` row is the protocol-only
program.
"""

from __future__ import annotations

from collections import Counter

# (entry, backend) -> {dtype name: carry-leaf count}.  Pinned from the
# audit of the seed fixtures (n is immaterial; the multiset is
# shape-independent).  The dense carry is view_key int32[N, N] + the
# int8 lattice planes + the scan-threaded net bits; the delta carry is
# the windowed claim state (int32 slots + uint32 hash row);
# run_scenario adds the net carry (up/responsive bool, gid/period
# int32); run_scenario+traffic is carry-identical to run_scenario (the
# serving plane stacks ys, it carries nothing); recv_merge_pallas's
# two int32 scans are the searchsorted lowering inside the wrapper.
CARRY_BUDGETS: dict[tuple[str, str], dict[str, int]] = {
    ("swim_run", "dense"): {"int32": 2, "int8": 2},
    ("delta_run", "delta"): {"bool": 1, "int32": 7, "int8": 2, "uint32": 1},
    ("run_scenario", "dense"): {"bool": 2, "int32": 3, "int8": 2},
    ("run_scenario", "delta"): {"bool": 3, "int32": 8, "int8": 2,
                                "uint32": 1},
    ("run_scenario+traffic", "dense"): {"bool": 2, "int32": 3, "int8": 2},
    ("run_scenario+traffic", "delta"): {"bool": 3, "int32": 8, "int8": 2,
                                        "uint32": 1},
    # the incident shape adds the overload feedback carry on top of
    # run_scenario+traffic — ov_gray (bool[N]), ov_cnt (int32[N]) —
    # plus the period row the overload fixture always materializes
    ("run_scenario+incident", "dense"): {"bool": 3, "int32": 5, "int8": 2},
    ("run_scenario+incident", "delta"): {"bool": 4, "int32": 10, "int8": 2,
                                         "uint32": 1},
    ("run_sweep", "dense"): {"bool": 2, "int32": 3, "int8": 2},
    ("run_sweep", "delta"): {"bool": 3, "int32": 8, "int8": 2, "uint32": 1},
    ("recv_merge_pallas", "dense"): {"int32": 2},
}


def expected(entry: str, backend: str) -> dict[str, int] | None:
    return CARRY_BUDGETS.get((entry, backend))


def format_multiset(ms: Counter | dict[str, int]) -> str:
    items = sorted(dict(ms).items())
    return "{" + ", ".join(f"{k}: {v}" for k, v in items) + "}"

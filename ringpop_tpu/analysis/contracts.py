"""The five trace contracts, machine-checked per entry point.

``audit_entry`` lowers one registered entry point (a small concrete
fixture from ``registry``) and runs every check against its jaxpr and
StableHLO; ``audit_all`` sweeps the registry.  Checks:

1. **host-transfer** — no ``callback`` / ``io_callback`` /
   ``pure_callback`` / infeed/outfeed primitives anywhere in the
   program (a host round-trip inside the scan serializes every
   dispatch), and the whole trace runs under
   ``jax.transfer_guard("disallow")`` so an implicit device↔host copy
   at trace time raises instead of silently syncing;
2. **donation** — programs that declare ``donate_argnums`` must
   actually alias: the lowered module carries ``tf.aliasing_output``
   parameter attributes and lowering emitted no donation-dropped
   warning (a dropped donation doubles the carry's HBM);
3. **carry-dtype** — no 8-byte dtype in any primary scan carry, and
   the carry dtype multiset matches the pinned budget table
   (``budgets.py``) — a silently widened slot fails the audit instead
   of eating HBM at n=65,536;
4. **prng-lineage** — static dataflow over the key-derivation
   primitives proving the declared streams (protocol schedule,
   workload key, and the workload key's tagged latency sub-stream)
   never mix and no key value is drawn from twice
   (``jaxpr_walk.KeyLineageAnalysis``);
5. **temp-census** — every intermediate at or above the entry's
   ``[N, C]``-class element threshold (or shaped ``[N, N]`` /
   ``[..., N, N]``), with dtype and producing primitive — the
   machine-readable target list for the footprint hunt (ROADMAP item
   2a), also surfaced via ``benchmarks/hlo_census.py --temps``.

Contracts 6–8 — the PARTITIONING contracts (``partitioning.py``:
collective-census, sharding-propagation, byte-budget) — operate one
layer lower, on the compiled executable: they activate for sharded
entries and for shapes with a pinned byte budget, and are the only
checks that pay a ``.compile()``.  Everything else is trace/lower-level
only; the StableHLO lowering (donation attributes + donation-dropped
warnings both surface there) is skippable with
``compile_programs=False`` for big-n census runs where only the jaxpr
checks are wanted.
"""

from __future__ import annotations

import contextlib
import dataclasses
import math
import re
import warnings as _warnings
from collections import Counter
from typing import Any

import jax

from ringpop_tpu.analysis import budgets, partitioning
from ringpop_tpu.analysis.findings import Finding
from ringpop_tpu.analysis.jaxpr_walk import (
    all_avals,
    iter_eqns,
    key_lineage,
    primary_scans,
    scan_carry_avals,
)
from ringpop_tpu.analysis.registry import (
    Built,
    EntryUnavailable,
    build_entry,
    iter_entries,
)

# Primitive names that imply a host round-trip inside the compiled
# program.  Matched exactly or as a substring ("callback" covers
# pure_callback / io_callback / debug_callback and future variants).
_HOST_PRIM_EXACT = frozenset({"infeed", "outfeed", "host_local_array"})
_HOST_PRIM_SUBSTR = ("callback",)

_ALIAS_RE = re.compile(r"tf\.aliasing_output")
# Multi-device lowerings carry donation in the COMPILED module's
# input_output_alias table instead of StableHLO parameter attributes.
_HLO_ALIAS_RE = re.compile(r"(?:may|must)-alias")
_DONATION_WARNING_RE = re.compile(
    r"donated buffer|buffers were not usable", re.IGNORECASE
)

# 4-byte lanes are the repo-wide carry budget: int64/float64/complex
# in a scan carry double the resident HBM for no modeled benefit.
MAX_CARRY_ITEMSIZE = 4


@dataclasses.dataclass
class EntryReport:
    """One audited (entry, backend): findings plus report material."""

    entry: str
    backend: str
    n: int
    findings: list[Finding]
    census: list[dict[str, Any]]
    prng: dict[str, Any]
    carries: dict[str, list[str]]  # scan path -> carry "dtype[shape]" list
    aliased_outputs: int
    host_prims: int
    # partitioning-contract material (sharded / byte-budgeted entries)
    mesh_size: int = 0
    collectives: list[dict[str, Any]] = dataclasses.field(
        default_factory=list
    )
    mem_bytes: dict[str, Any] | None = None

    def to_json(self) -> dict[str, Any]:
        d = dataclasses.asdict(self)
        d["findings"] = [f.to_json() for f in self.findings]
        return d


# ---------------------------------------------------------------------------
# the individual checks
# ---------------------------------------------------------------------------


def check_host_transfers(closed, entry: str) -> tuple[list[Finding], int]:
    """Contract 1: the jaxpr walker half (the transfer-guard half wraps
    the trace itself in ``_trace``)."""
    findings = []
    hits = 0
    for path, eqn in iter_eqns(closed):
        name = eqn.primitive.name
        if name in _HOST_PRIM_EXACT or any(
            s in name for s in _HOST_PRIM_SUBSTR
        ):
            hits += 1
            in_scan = "scan" in path.split("/") if path else False
            findings.append(
                Finding(
                    contract="host-transfer",
                    severity="error",
                    entry=entry,
                    message=(
                        f"host round-trip primitive '{name}' in the "
                        f"compiled program"
                        + (" (inside the scan body: every tick pays a "
                           "host sync)" if in_scan else "")
                    ),
                    where=path or "<top>",
                )
            )
    return findings, hits


def check_donation(
    built: Built, lowered_text: str | None, warning_msgs: list[str],
    compiled_text: str | None = None,
) -> tuple[list[Finding], int]:
    """Contract 2: donation declared must be donation applied.  A
    multi-device lowering drops the StableHLO ``tf.aliasing_output``
    attrs and records donation in the compiled module's
    ``input_output_alias`` table instead, so sharded entries pass the
    optimized HLO as the fallback evidence."""
    findings: list[Finding] = []
    aliased = (
        len(_ALIAS_RE.findall(lowered_text)) if lowered_text is not None else 0
    )
    if not aliased and compiled_text is not None:
        aliased = len(_HLO_ALIAS_RE.findall(compiled_text))
    dropped = [m for m in warning_msgs if _DONATION_WARNING_RE.search(m)]
    if not built.donates:
        return findings, aliased
    for msg in dropped:
        findings.append(
            Finding(
                contract="donation",
                severity="error",
                entry=built.name,
                message=f"donation dropped at lowering: {msg.splitlines()[0]}",
            )
        )
    if lowered_text is not None and aliased < built.min_aliased:
        findings.append(
            Finding(
                contract="donation",
                severity="error",
                entry=built.name,
                message=(
                    f"program declares donate_argnums but the lowered "
                    f"module aliases only {aliased} parameter(s) "
                    f"(pinned floor {built.min_aliased}) — the carry "
                    "is being copied, not reused"
                ),
            )
        )
    return findings, aliased


def check_carry_dtypes(
    closed, built: Built
) -> tuple[list[Finding], dict[str, list[str]]]:
    """Contract 3: wide dtypes and the pinned per-entry budget."""
    findings: list[Finding] = []
    carries: dict[str, list[str]] = {}
    multiset: Counter = Counter()
    for path, eqn in primary_scans(closed):
        avals = scan_carry_avals(eqn)
        label = path or "<top>"
        # several primary scans can share one path (the sharded step's
        # inner sort/fori kernels all sit under "pjit"); disambiguate
        # so the report — and the --print-budget multiset derived from
        # it — keeps every scan instead of silently overwriting
        if label in carries:
            k = 2
            while f"{label}#{k}" in carries:
                k += 1
            label = f"{label}#{k}"
        carries[label] = [f"{a.dtype}{list(a.shape)}" for a in avals]
        for a in avals:
            multiset[str(a.dtype)] += 1
            if a.dtype.itemsize > MAX_CARRY_ITEMSIZE:
                findings.append(
                    Finding(
                        contract="carry-dtype",
                        severity="error",
                        entry=built.name,
                        message=(
                            f"scan carry leaf {a.dtype}{list(a.shape)} is "
                            f"{a.dtype.itemsize} bytes/elem — over the "
                            f"{MAX_CARRY_ITEMSIZE}-byte carry budget "
                            "(silent promotion?)"
                        ),
                        where=label,
                    )
                )
    pinned = budgets.expected(built.name, built.backend)
    if pinned is None:
        findings.append(
            Finding(
                contract="carry-dtype",
                severity="warning",
                entry=built.name,
                message=(
                    f"no pinned carry budget for "
                    f"({built.name}, {built.backend}); actual "
                    f"{budgets.format_multiset(multiset)} — pin it in "
                    "analysis/budgets.py"
                ),
            )
        )
    elif Counter(pinned) != multiset:
        findings.append(
            Finding(
                contract="carry-dtype",
                severity="error",
                entry=built.name,
                message=(
                    "carry dtype budget drift: pinned "
                    f"{budgets.format_multiset(Counter(pinned))} but the "
                    f"trace carries {budgets.format_multiset(multiset)} — "
                    "a widened/added slot must be justified and re-pinned "
                    "in analysis/budgets.py"
                ),
            )
        )
    # program-wide f64 anywhere (x64 creeping in) — weaker than the
    # carry rule, but a float64 temporary is still 2x HBM for nothing
    for path, prim, aval in all_avals(closed):
        if str(aval.dtype) in ("float64", "complex128"):
            findings.append(
                Finding(
                    contract="carry-dtype",
                    severity="warning",
                    entry=built.name,
                    message=f"float64 intermediate {list(aval.shape)} "
                            f"produced by '{prim}'",
                    where=path or "<top>",
                )
            )
            break  # one representative is enough; the census has the rest
    return findings, carries


def check_key_lineage(closed, built: Built) -> tuple[list[Finding], dict]:
    """Contract 4: declared streams never mix; no key drawn twice."""
    if not built.key_roots:
        return [], {"roots": {}}
    return key_lineage(closed, built.key_roots, built.name)


def _dim_name(d: int, dims: dict[str, int]) -> str:
    """Named-dim tag for a size; when several named dims share the
    size (n == capacity at small fixture shapes) the tag keeps every
    candidate ("N|C") instead of silently picking one — the census's
    whole point is telling [N, C] claim tables from [N, N] planes."""
    matches = [name for name, val in dims.items() if d == val]
    return "|".join(matches) if matches else str(d)


def temp_census(
    closed, *, dims: dict[str, int], min_elems: int, entry: str = ""
) -> list[dict[str, Any]]:
    """Contract 5: the temporary-tensor census rows (info/report, not
    findings): every equation output at or above ``min_elems`` elements
    or shaped ``[..., N, N]``, with dtype and producing primitive,
    grouped and sorted by footprint."""
    n = dims.get("N", 0)
    grouped: dict[tuple, dict[str, Any]] = {}
    for path, prim, aval in all_avals(closed):
        shape = tuple(int(d) for d in aval.shape)
        elems = math.prod(shape) if shape else 1
        nxn = n > 1 and sum(1 for d in shape if d == n) >= 2
        if elems < min_elems and not nxn:
            continue
        key = (shape, str(aval.dtype), prim, path)
        row = grouped.get(key)
        if row is None:
            grouped[key] = row = {
                "entry": entry,
                "shape": list(shape),
                "tag": "x".join(_dim_name(d, dims) for d in shape),
                "dtype": str(aval.dtype),
                "primitive": prim,
                "path": path or "<top>",
                "count": 0,
                "elems_each": elems,
                "bytes_each": elems * aval.dtype.itemsize,
            }
        row["count"] += 1
    return sorted(
        grouped.values(),
        key=lambda r: (-r["bytes_each"] * r["count"], r["primitive"]),
    )


# ---------------------------------------------------------------------------
# per-entry driver
# ---------------------------------------------------------------------------


def _trace(built: Built):
    """The entry point's closed jaxpr, traced under a disallow
    transfer guard (an implicit device↔host copy during tracing —
    e.g. a concretized traced bool — raises here instead of silently
    serializing dispatches on a real accelerator)."""

    def fn(*args):
        return built.jitted(*args, **built.statics)

    with jax.transfer_guard("disallow"):
        return jax.make_jaxpr(fn)(*built.args)


def _trace_and_lower(
    built: Built, *, lower: bool, compile_hlo: bool = False
) -> tuple[Any, str | None, list[str], Any]:
    """One trace serves every layer: the AOT ``.trace`` yields the
    closed jaxpr AND (optionally) the StableHLO lowering AND
    (optionally) the compiled executable — the partitioning contracts
    need the post-SPMD optimized HLO and the memory analysis, which
    only exist after ``.compile()``.  The entry point is traced exactly
    once per audit; the disallow transfer guard covers the whole
    trace→lower span, and the entry's ``trace_context`` (e.g. the mesh
    path's SPMD-safe recv-merge form) wraps all of it.  Returns
    ``(closed_jaxpr, lowered_text | None, warning messages,
    compiled | None)`` — donation-dropped warnings surface at
    lowering."""
    ctx = (built.trace_context() if built.trace_context is not None
           else contextlib.nullcontext())
    with _warnings.catch_warnings(record=True) as caught:
        _warnings.simplefilter("always")
        with ctx:
            with jax.transfer_guard("disallow"):
                traced = built.jitted.trace(*built.args, **built.statics)
                lowered = (traced.lower() if (lower or compile_hlo)
                           else None)
                text = lowered.as_text() if lower else None
            compiled = _compile_cached(built, lowered) if compile_hlo else None
    return (traced.jaxpr, text, [str(w.message) for w in caught], compiled)


def _compile_cached(built: Built, lowered: Any) -> Any:
    """Compile through the dispatch ledger's AOT executable cache.

    ``audit --print-budget`` forces byte-row compiles for entries the
    same process already compiled (the audit pass itself, a prior
    audit_entry call, a pin_budgets loop) — each a full XLA compile of
    an identical program.  Keying the executable on the entry plus the
    ledger's argument signature makes every repeat a cache hit: one
    compile per signature per process, the same contract the
    obs_smoke.sh one-cold-compile gate pins for dispatch.  The cache
    lives on the process-global ledger (populated even when event
    recording is disabled); audit keys carry an ``audit:`` prefix so
    they can never alias a dispatch program's executables.
    """
    from ringpop_tpu.obs.ledger import _signature, default_ledger, memory_row

    ledger = default_ledger()
    key = (
        f"audit:{built.name}:{built.backend}",
        _signature(built.args, built.statics),
    )
    hit = ledger._compiled.get(key)
    if hit is not None:
        return hit[0]
    compiled = lowered.compile()
    ledger._compiled[key] = (compiled, memory_row(compiled))
    return compiled


def _lower_text(built: Built) -> tuple[str | None, list[str]]:
    """The lowered StableHLO text plus any warnings lowering emitted
    (donation-dropped warnings appear here) — the fixture-level helper
    ``tests/test_analysis.py`` drives the donation check through."""
    with _warnings.catch_warnings(record=True) as caught:
        _warnings.simplefilter("always")
        lowered = built.jitted.lower(*built.args, **built.statics)
        text = lowered.as_text()
    return text, [str(w.message) for w in caught]


def audit_entry(
    name: str,
    backend: str,
    *,
    n: int = 64,
    ticks: int = 4,
    capacity: int = 64,
    replicas: int = 2,
    compile_programs: bool = True,
    census_min_elems: int | None = None,
    force_compile: bool = False,
    **extra: Any,
) -> EntryReport:
    """Run every trace contract against one (entry, backend) at the
    given fixture shape; ``compile_programs=False`` skips the StableHLO
    lowering (donation check degrades to a skip) for big-n census-only
    runs.  The program is additionally COMPILED — the partitioning
    contracts' layer — when it is sharded, when a byte budget is
    pinned at this (n,), or under ``force_compile`` (the budget-pinning
    path)."""
    built = build_entry(
        name, backend, n=n, ticks=ticks, capacity=capacity,
        replicas=replicas, **extra,
    )
    findings: list[Finding] = []
    compile_hlo = compile_programs and (
        force_compile
        or built.mesh_size > 0
        or budgets.byte_budget(built.name, built.backend, n) is not None
    )
    closed, text, warns, compiled = _trace_and_lower(
        built, lower=compile_programs, compile_hlo=compile_hlo
    )
    compiled_text = compiled.as_text() if compiled is not None else None

    host_findings, host_hits = check_host_transfers(closed, built.name)
    findings += host_findings

    donation_findings, aliased = check_donation(
        built, text, warns, compiled_text
    )
    findings += donation_findings

    carry_findings, carries = check_carry_dtypes(closed, built)
    findings += carry_findings

    prng_findings, prng = check_key_lineage(closed, built)
    findings += prng_findings

    collectives: list[dict[str, Any]] = []
    mem: dict[str, Any] | None = None
    if compiled is not None:
        from ringpop_tpu.obs.ledger import memory_row

        mem = memory_row(compiled)
        findings += partitioning.check_byte_budget(
            built, mem, n=n, ticks=ticks
        )
        if built.mesh_size:
            collectives = partitioning.collective_census(
                compiled_text, dims=built.dims
            )
            findings += partitioning.check_collectives(
                built, collectives, n=n
            )
            findings += partitioning.check_sharding_propagation(
                built, compiled, closed
            )

    census = temp_census(
        closed,
        dims=built.dims,
        min_elems=(census_min_elems if census_min_elems is not None
                   else built.census_min_elems),
        entry=built.name,
    )
    return EntryReport(
        entry=built.name,
        backend=backend,
        n=n,
        findings=findings,
        census=census,
        prng=prng,
        carries=carries,
        aliased_outputs=aliased,
        host_prims=host_hits,
        mesh_size=built.mesh_size,
        collectives=collectives,
        mem_bytes=mem,
    )


def audit_all(
    names=None, backends=None, **kw: Any
) -> tuple[list[EntryReport], list[Finding]]:
    """Audit every registered (entry, backend); returns the reports
    and the concatenated findings.  A fixture that cannot build in
    this environment (a mesh entry on a 1-device host) yields an info
    finding, not a crash — the audit still fails CLOSED on real
    violations while degrading visibly on capability gaps."""
    reports = []
    findings: list[Finding] = []
    for name, backend in iter_entries(names, backends):
        try:
            report = audit_entry(name, backend, **kw)
        except EntryUnavailable as e:
            findings.append(
                Finding(
                    contract="registry",
                    severity="info",
                    entry=name,
                    message=f"skipped [{backend}]: {e}",
                )
            )
            continue
        reports.append(report)
        findings += report.findings
    return reports, findings

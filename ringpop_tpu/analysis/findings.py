"""Audit findings: one violation (or note) per instance, severity-ranked.

A ``Finding`` is deliberately flat and JSON-trivial: the audit CLI's
``--json`` mode must be diffable in CI, and the test suite asserts on
``contract`` + ``entry`` pairs without parsing prose.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Iterable

# Severity gates (cli --fail-on): an "error" is a broken trace
# contract; a "warning" is a contract the auditor could not positively
# prove (e.g. an unpinned budget row); "info" is report material.
SEVERITY_RANK = {"info": 0, "warning": 1, "error": 2}


@dataclasses.dataclass
class Finding:
    contract: str  # e.g. "host-transfer", "donation", "carry-dtype",
    #                "prng-lineage", "collective-census",
    #                "sharding-propagation", "byte-budget", "lint:RPL001"
    severity: str  # "error" | "warning" | "info"
    entry: str  # entry-point name, or file path for lint findings
    message: str
    where: str = ""  # jaxpr path ("scan/cond") or "file:line"

    def to_json(self) -> dict[str, Any]:
        return dataclasses.asdict(self)

    def __str__(self) -> str:
        loc = f" @ {self.where}" if self.where else ""
        return f"[{self.severity}] {self.entry}: {self.contract}{loc} — {self.message}"


def max_severity(findings: Iterable[Finding]) -> str | None:
    """The highest severity present, or None for an empty list."""
    best: str | None = None
    for f in findings:
        if best is None or SEVERITY_RANK[f.severity] > SEVERITY_RANK[best]:
            best = f.severity
    return best


def at_least(findings: Iterable[Finding], severity: str) -> list[Finding]:
    """Findings at or above ``severity``."""
    floor = SEVERITY_RANK[severity]
    return [f for f in findings if SEVERITY_RANK[f.severity] >= floor]

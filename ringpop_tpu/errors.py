"""Typed errors (reference: lib/errors.js plus per-module TypedErrors).

Each error carries a ``type`` string matching the reference's error types so
drivers/tests can dispatch on them the same way.
"""

from __future__ import annotations

from typing import Any


class RingpopError(Exception):
    type = "ringpop.error"

    def __init__(self, message: str = "", **fields: Any):
        super().__init__(message or self.__doc__ or self.type)
        self.fields = fields
        for key, value in fields.items():
            setattr(self, key, value)


class OptionsRequiredError(RingpopError):
    """Expected `options` argument to be passed."""

    type = "ringpop.options-required"

    def __init__(self, method: str = ""):
        super().__init__(f"Expected `options` to be passed for method {method}", method=method)


class AppRequiredError(RingpopError):
    """Expected `options.app` to be a non-empty string."""

    type = "ringpop.options-app.required"


class HostPortRequiredError(RingpopError):
    """Expected `options.hostPort` to be valid."""

    type = "ringpop.options-host-port.required"

    def __init__(self, host_port: Any = None, reason: str = ""):
        super().__init__(
            f"Expected `options.hostPort` to be {reason}; got {host_port!r}",
            hostPort=host_port,
            reason=reason,
        )


class ArgumentRequiredError(RingpopError):
    type = "ringpop.argument-required"

    def __init__(self, argument: str = ""):
        super().__init__(f"Expected `{argument}` to be passed", argument=argument)


class FieldRequiredError(RingpopError):
    type = "ringpop.field-required"

    def __init__(self, argument: str = "", field: str = ""):
        super().__init__(
            f"Expected `{field}` to be defined on `{argument}`",
            argument=argument, field=field,
        )


class MethodRequiredError(RingpopError):
    type = "ringpop.method-required"

    def __init__(self, argument: str = "", method: str = ""):
        super().__init__(
            f"Expected `{method}` to be implemented by `{argument}`",
            argument=argument, method=method,
        )


class DuplicateHookError(RingpopError):
    type = "ringpop.duplicate-hook"

    def __init__(self, name: str = ""):
        super().__init__(f"Hook {name} already registered", name=name)


class PropertyRequiredError(RingpopError):
    type = "ringpop.options-property-required"

    def __init__(self, property: str = ""):
        super().__init__(f"Expected `{property}` to be defined", property=property)


class InvalidLocalMemberError(RingpopError):
    type = "ringpop.invalid-local-member"

    def __init__(self) -> None:
        super().__init__("Operation requires a local member")


class OptionRequiredError(RingpopError):
    type = "ringpop.option-required"

    def __init__(self, option: str = ""):
        super().__init__(f"Expected option `{option}`", option=option)


class InvalidOptionError(RingpopError):
    type = "ringpop.invalid-option"

    def __init__(self, option: str = "", reason: str = ""):
        super().__init__(f"Invalid option `{option}`: {reason}", option=option, reason=reason)


# -- join (lib/swim/join-sender.js:30-49) -----------------------------------


class JoinAbortedError(RingpopError):
    type = "ringpop.join-aborted"

    def __init__(self, reason: str = ""):
        super().__init__(f"Join aborted because `{reason}`", reason=reason)


class JoinDurationExceededError(RingpopError):
    type = "ringpop.join-duration-exceeded"

    def __init__(self, duration: float = 0, max: float = 0):
        super().__init__(
            f"Join duration of `{duration}` exceeded max `{max}`",
            duration=duration, max=max,
        )


class JoinAttemptsExceededError(RingpopError):
    type = "ringpop.join-attempts-exceeded"

    def __init__(self, join_attempts: int = 0, max_join_attempts: int = 0):
        super().__init__(
            f"Join attempts of `{join_attempts}` exceeded max `{max_join_attempts}`",
            joinAttempts=join_attempts,
            maxJoinAttempts=max_join_attempts,
        )


# -- join handler (server/join-handler.js:24-42) ----------------------------


class DenyJoinError(RingpopError):
    type = "ringpop.deny-join"

    def __init__(self) -> None:
        super().__init__("Node is currently configured to deny joins")


class InvalidJoinAppError(RingpopError):
    type = "ringpop.invalid-join.app"

    def __init__(self, expected: str = "", actual: str = ""):
        super().__init__(
            f"A node tried joining a different app cluster. "
            f"Expected ({expected}) actual ({actual}).",
            expected=expected,
            actual=actual,
        )


class InvalidJoinSourceError(RingpopError):
    type = "ringpop.invalid-join.source"

    def __init__(self, actual: str = ""):
        super().__init__(
            f"A node tried joining a cluster by attempting to join itself ({actual}).",
            actual=actual,
        )


class RedundantLeaveError(RingpopError):
    type = "ringpop.invalid-leave.redundant"

    def __init__(self) -> None:
        super().__init__("A node cannot leave its cluster when it has already left.")


# -- ping-req (lib/swim/ping-req-sender.js:25-55) ---------------------------


class BadPingReqPingStatusError(RingpopError):
    type = "ringpop.ping-req.bad-ping-status"

    def __init__(self, selected: str = "", target: str = "", ping_status: Any = None):
        super().__init__(
            f"Bad ping status from ping-req ping: {ping_status}",
            selected=selected,
            target=target,
            pingStatus=ping_status,
        )


class BadPingReqRespBodyError(RingpopError):
    type = "ringpop.ping-req.bad-resp-body"

    def __init__(self, selected: str = "", target: str = "", body: Any = None):
        super().__init__("Bad response from ping-req", selected=selected, target=target, body=body)


class NoMembersError(RingpopError):
    type = "ringpop.ping-req.no-members"

    def __init__(self) -> None:
        super().__init__("No selectable ping-req members")


class PingReqInconclusiveError(RingpopError):
    type = "ringpop.ping-req.inconclusive"

    def __init__(self) -> None:
        super().__init__("Ping-req is inconclusive")


class PingReqPingError(RingpopError):
    type = "ringpop.ping-req.ping"

    def __init__(self, err_message: str = ""):
        super().__init__(
            f"An error occurred on ping-req ping: {err_message}",
            errMessage=err_message,
        )


# -- request proxy (lib/request-proxy/{index,send}.js) ----------------------


class InvalidCheckSumError(RingpopError):
    type = "ringpop.request-proxy.invalid-checksum"

    def __init__(self, expected: Any = None, actual: Any = None):
        super().__init__(
            f"Expected the remote checksum to match local checksum. "
            f"Expected {expected} actual {actual}.",
            expected=expected,
            actual=actual,
        )


class MaxRetriesExceededError(RingpopError):
    type = "ringpop.request-proxy.max-retries-exceeded"

    def __init__(self, max_retries: int = 0):
        super().__init__(f"Max number of retries ({max_retries}) exceeded", maxRetries=max_retries)


class KeysDivergedError(RingpopError):
    type = "ringpop.request-proxy.keys-diverged"

    def __init__(self, keys: Any = None):
        super().__init__("Keys diverged during retry", keys=keys)


class ChannelDestroyedError(RingpopError):
    type = "ringpop.request-proxy.channel-destroyed"

    def __init__(self) -> None:
        super().__init__("Channel was destroyed")

"""Red-black tree keyed by uint32 hash with a (val, name) payload.

Reference: lib/rbtree.js — a top-down red-black tree specialized for the
hash ring, with ``lowerBound``/``upperBound`` (rbtree.js:235-271), ``min``
(:274-285) and an in-order iterator holding an explicit ancestor stack
(:291-342).  The behavior contract reproduced here:

* ``lower_bound(v)`` — iterator positioned at the first node with
  ``val >= v`` (cursor ``None`` when every node is smaller);
* ``upper_bound(v)`` — the reference's upperBound advances its lowerBound
  only past nodes strictly smaller than ``v``, so it lands on the first
  node ``>= v`` too (equality-inclusive — this is what ring.js:139-140
  relies on for ``lookup``);
* ``remove`` of a two-child node replaces it with its in-order successor's
  val AND name — copying only one field was the reference's "payload copy
  bug" regression (test/rbtree_test.js:594);
* duplicate ``val`` inserts are rejected (insert returns False).

The balancing scheme is a left-leaning red-black tree (recursive
insert/delete with fix-ups) rather than the reference's top-down
double-rotation scheme — same O(log n) bounds, considerably less code;
the tree shape is an implementation detail the contract doesn't cover.

The default ``HashRing`` (hashring.py) uses a sorted array instead, which
maps directly onto the device ``searchsorted`` kernel; ``RBRing`` below is
the tree-backed equivalent used to cross-check lookup semantics.
"""

from __future__ import annotations

from typing import Iterator, Optional


class RingNode:
    """Payload node: replica hash value + owning server name."""

    __slots__ = ("val", "name", "left", "right", "red")

    def __init__(self, val: int, name: str):
        self.val = val
        self.name = name
        self.left: Optional["RingNode"] = None
        self.right: Optional["RingNode"] = None
        self.red = True


def _is_red(node: Optional[RingNode]) -> bool:
    return node is not None and node.red


def _rotate_left(h: RingNode) -> RingNode:
    x = h.right
    h.right = x.left
    x.left = h
    x.red = h.red
    h.red = True
    return x


def _rotate_right(h: RingNode) -> RingNode:
    x = h.left
    h.left = x.right
    x.right = h
    x.red = h.red
    h.red = True
    return x


def _flip_colors(h: RingNode) -> None:
    h.red = not h.red
    h.left.red = not h.left.red
    h.right.red = not h.right.red


def _fix_up(h: RingNode) -> RingNode:
    if _is_red(h.right) and not _is_red(h.left):
        h = _rotate_left(h)
    if _is_red(h.left) and _is_red(h.left.left):
        h = _rotate_right(h)
    if _is_red(h.left) and _is_red(h.right):
        _flip_colors(h)
    return h


def _move_red_left(h: RingNode) -> RingNode:
    _flip_colors(h)
    if _is_red(h.right.left):
        h.right = _rotate_right(h.right)
        h = _rotate_left(h)
        _flip_colors(h)
    return h


def _move_red_right(h: RingNode) -> RingNode:
    _flip_colors(h)
    if _is_red(h.left.left):
        h = _rotate_right(h)
        _flip_colors(h)
    return h


def _min_node(h: RingNode) -> RingNode:
    while h.left is not None:
        h = h.left
    return h


class RBIterator:
    """In-order iterator with an explicit ancestor stack (rbtree.js:291-342).

    ``cursor`` is None both before the first ``next()`` and past the end;
    ``val()``/``name()`` return None at those positions.
    """

    def __init__(self, tree: "RBTree"):
        self.tree = tree
        self.ancestors: list[RingNode] = []
        self.cursor: Optional[RingNode] = None

    def val(self) -> Optional[int]:
        return self.cursor.val if self.cursor is not None else None

    def name(self) -> Optional[str]:
        return self.cursor.name if self.cursor is not None else None

    def _descend_min(self, node: RingNode) -> None:
        while node.left is not None:
            self.ancestors.append(node)
            node = node.left
        self.cursor = node

    def next(self) -> Optional[RingNode]:
        if self.cursor is None:
            self.ancestors = []
            if self.tree.root is not None:
                self._descend_min(self.tree.root)
        elif self.cursor.right is not None:
            self.ancestors.append(self.cursor)
            self._descend_min(self.cursor.right)
        else:
            came_from = self.cursor
            self.cursor = None
            while self.ancestors:
                parent = self.ancestors.pop()
                if parent.left is came_from:
                    self.cursor = parent
                    break
                came_from = parent
        return self.cursor


class RBTree:
    def __init__(self) -> None:
        self.root: Optional[RingNode] = None
        self.size = 0
        self._flag = False

    # -- queries -------------------------------------------------------------

    def find(self, val: int) -> Optional[RingNode]:
        node = self.root
        while node is not None:
            if val == node.val:
                return node
            node = node.left if val < node.val else node.right
        return None

    def min(self) -> Optional[RingNode]:
        return _min_node(self.root) if self.root is not None else None

    def iterator(self) -> RBIterator:
        return RBIterator(self)

    def lower_bound(self, val: int) -> RBIterator:
        """Iterator at the first node with ``val >= val`` (rbtree.js:234-259)."""
        it = RBIterator(self)
        node = self.root
        while node is not None:
            if val == node.val:
                it.cursor = node
                return it
            it.ancestors.append(node)
            node = node.right if val > node.val else node.left
        # No exact match: unwind to the deepest ancestor still >= val.
        for i in range(len(it.ancestors) - 1, -1, -1):
            node = it.ancestors[i]
            if val < node.val:
                it.cursor = node
                del it.ancestors[i:]
                return it
        it.ancestors.clear()
        return it

    def upper_bound(self, val: int) -> RBIterator:
        """First node ``>= val`` — equality-INCLUSIVE, matching the
        reference's upperBound (rbtree.js:261-270), whose advance loop only
        skips nodes strictly below ``val``.  ring.js lookup depends on a key
        hashing exactly onto a replica point owning itself."""
        return self.lower_bound(val)

    def __iter__(self) -> Iterator[RingNode]:
        it = self.iterator()
        while it.next() is not None:
            yield it.cursor

    # -- insert --------------------------------------------------------------

    def insert(self, val: int, name: str) -> bool:
        """Insert; reject duplicate vals (returns False)."""
        self._flag = False
        self.root = self._insert(self.root, val, name)
        self.root.red = False
        if self._flag:
            self.size += 1
        return self._flag

    def _insert(self, h: Optional[RingNode], val: int, name: str) -> RingNode:
        if h is None:
            self._flag = True
            return RingNode(val, name)
        if val == h.val:
            return h
        if val < h.val:
            h.left = self._insert(h.left, val, name)
        else:
            h.right = self._insert(h.right, val, name)
        return _fix_up(h)

    # -- remove --------------------------------------------------------------

    def remove(self, val: int) -> bool:
        if self.find(val) is None:
            return False
        if not _is_red(self.root.left) and not _is_red(self.root.right):
            self.root.red = True
        self.root = self._remove(self.root, val)
        if self.root is not None:
            self.root.red = False
        self.size -= 1
        return True

    def _remove(self, h: RingNode, val: int) -> Optional[RingNode]:
        if val < h.val:
            if not _is_red(h.left) and not _is_red(h.left.left):
                h = _move_red_left(h)
            h.left = self._remove(h.left, val)
        else:
            if _is_red(h.left):
                h = _rotate_right(h)
            if val == h.val and h.right is None:
                return None
            if not _is_red(h.right) and not _is_red(h.right.left):
                h = _move_red_right(h)
            if val == h.val:
                successor = _min_node(h.right)
                # Copy the WHOLE payload — val and name together
                # (the reference's payload-copy regression,
                # test/rbtree_test.js:594).
                h.val = successor.val
                h.name = successor.name
                h.right = self._remove_min(h.right)
            else:
                h.right = self._remove(h.right, val)
        return _fix_up(h)

    def _remove_min(self, h: RingNode) -> Optional[RingNode]:
        if h.left is None:
            return None
        if not _is_red(h.left) and not _is_red(h.left.left):
            h = _move_red_left(h)
        h.left = self._remove_min(h.left)
        return _fix_up(h)

    # -- invariants (for tests) ----------------------------------------------

    def check_invariants(self) -> int:
        """Validate BST order + red-black invariants; return black height."""
        def walk(node: Optional[RingNode],
                 lo: float, hi: float) -> int:
            if node is None:
                return 1
            assert lo < node.val < hi, "BST order violated"
            if node.red:
                assert not _is_red(node.left) and not _is_red(node.right), \
                    "red node with red child"
            lh = walk(node.left, lo, node.val)
            rh = walk(node.right, node.val, hi)
            assert lh == rh, "unequal black heights"
            return lh + (0 if node.red else 1)

        assert not _is_red(self.root), "red root"
        return walk(self.root, float("-inf"), float("inf"))


class RBRing:
    """Tree-backed consistent-hash ring core: the reference's exact shape
    (lib/ring.js over lib/rbtree.js).  Used to cross-check the default
    sorted-array ``HashRing``; same lookup/lookupN contract."""

    def __init__(self, hash_func, replica_points: int = 100):
        self.tree = RBTree()
        self.hash_func = hash_func
        self.replica_points = replica_points
        self.servers: set[str] = set()

    def add_server(self, name: str) -> None:
        if name in self.servers:
            return
        self.servers.add(name)
        for i in range(self.replica_points):
            self.tree.insert(self.hash_func(f"{name}{i}"), name)

    def remove_server(self, name: str) -> None:
        if name not in self.servers:
            return
        self.servers.discard(name)
        for i in range(self.replica_points):
            self.tree.remove(self.hash_func(f"{name}{i}"))

    def lookup(self, key: str) -> Optional[str]:
        if self.tree.size == 0:
            return None
        it = self.tree.upper_bound(self.hash_func(key))
        if it.cursor is None:
            return self.tree.min().name  # wraparound (ring.js:142-145)
        return it.cursor.name

    def lookup_n(self, key: str, n: int) -> list[str]:
        """Successive unique owners with wraparound (ring.js:150-182)."""
        n = min(n, len(self.servers))
        if n <= 0 or self.tree.size == 0:
            return []
        result: list[str] = []
        seen: set[str] = set()
        it = self.tree.upper_bound(self.hash_func(key))
        visited = 0
        while len(result) < n and visited < self.tree.size:
            if it.cursor is None:
                it = self.tree.iterator()
                it.next()  # wrap to min
                if it.cursor is None:
                    break
            if it.cursor.name not in seen:
                seen.add(it.cursor.name)
                result.append(it.cursor.name)
            it.next()
            visited += 1
        return result

"""Simulation checkpoint / resume.

The reference has NO persistence: membership state is in-memory and
reconstructed by re-joining (full sync) after a restart (SURVEY §5.4 —
bootstrap hosts file + wall-clock incarnation numbers are the only
restart aids).  For a 65k-node simulation that answer is wasteful, so
checkpointing the state tensors is a new capability of this rebuild.

Format: one ``.npz`` per checkpoint holding every ``ClusterState`` /
``NetState`` leaf plus the PRNG key, params, address book and base
incarnation — everything needed to continue the run bit-identically.
(.npz instead of orbax: a single small self-describing file, no async
machinery; the arrays are the checkpoint.)

Determinism contract (tested): ``save -> load -> tick(k)`` produces the
same state as ``tick(k)`` on the original, because the PRNG key is part
of the checkpoint and ``SimCluster`` splits it identically.
"""

from __future__ import annotations

import json
import os
from typing import Any

import jax
import numpy as np

from ringpop_tpu.models.cluster import SimCluster
from ringpop_tpu.models.swim_delta import DeltaState
from ringpop_tpu.models.swim_sim import ClusterState, NetState, SwimParams

# v2: packed view_key/pb/suspect_left state layout
# v3: + delta backend (DeltaState leaves, resource caps in meta)
# v4: + telemetry (metrics_log in meta, scenario traces as trace{i}.*)
# v5: + streaming cursor ("stream" in meta: spec, segment cursor, PRNG
#     schedule position, traffic cursor — scenarios/stream.py resumes
#     a killed chunked-scan soak bit-exactly from it)
FORMAT_VERSION = 5
_READABLE_VERSIONS = (2, 3, 4, 5)


def save(
    cluster: SimCluster,
    path: str,
    *,
    stream: dict[str, Any] | None = None,
    state: Any | None = None,
    net: Any | None = None,
) -> None:
    """Write a self-contained checkpoint of the simulation.

    ``stream`` (a JSON-able cursor dict, scenarios/stream.py) marks
    the checkpoint as a mid-soak segment boundary.  ``state``/``net``
    override the cluster's own tensors: the streaming runner donates
    ``cluster.state`` into the in-flight segment (the buffers are gone
    from the host's point of view) and checkpoints from the host
    snapshot it took at the boundary instead."""
    state = cluster.state if state is None else state
    net = cluster.net if net is None else net
    meta = {
        "version": FORMAT_VERSION,
        "params": cluster.params._asdict(),
        "base_inc": cluster.base_inc,
        "n": cluster.n,
        "backend": cluster.backend,
        "caps": {
            "capacity": (
                state.capacity if cluster.backend == "delta" else 0
            ),
            "wire_cap": cluster.dparams.wire_cap,
            "claim_grid": cluster.dparams.claim_grid,
        },
        # telemetry rides along (v4): a resumed run keeps its time
        # series instead of restarting blind
        "metrics_log": cluster.metrics_log,
        "traces": [t.meta() for t in cluster.traces],
    }
    if stream is not None:
        meta["stream"] = stream
    arrays: dict[str, np.ndarray] = {
        "meta": np.frombuffer(json.dumps(meta).encode(), dtype=np.uint8),
        "key": np.asarray(cluster.key),
        "addresses": np.asarray(cluster.book.addresses, dtype=np.str_),
    }
    for i, trace in enumerate(cluster.traces):
        arrays.update(trace.to_arrays(prefix=f"trace{i}."))
    for name, leaf in state._asdict().items():
        if leaf is None:  # optional extension tensors (damping)
            continue
        arrays[f"state.{name}"] = np.asarray(leaf)
    for name, leaf in net._asdict().items():
        if leaf is None:  # adj=None: healthy fully-connected network
            continue
        arrays[f"net.{name}"] = np.asarray(leaf)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez_compressed(f, **arrays)
    os.replace(tmp, path)  # atomic: never leave a torn checkpoint


def load(path: str, device: Any | None = None) -> SimCluster:
    """Reconstruct a ``SimCluster`` that continues the run exactly."""
    with np.load(path, allow_pickle=False) as data:
        meta = json.loads(bytes(data["meta"]).decode())
        if meta["version"] not in _READABLE_VERSIONS:
            raise ValueError(f"unsupported checkpoint version {meta['version']}")
        param_dict = dict(meta["params"])
        if meta["version"] == 2:
            # fields added after v2 must resume with the defaults that
            # were in force when the checkpoint ran, not today's (the
            # probe default flipped uniform -> sweep in round 3; letting
            # it float would silently change the resumed trajectory)
            param_dict.setdefault("probe", "uniform")
        params = SwimParams(**param_dict)
        addresses = [str(a) for a in data["addresses"]]
        backend = meta.get("backend", "dense")  # v2 checkpoints are dense
        caps = meta.get("caps", {})
        kw = {}
        if backend == "delta":
            kw = {
                "capacity": caps["capacity"],
                "wire_cap": caps["wire_cap"],
                "claim_grid": caps["claim_grid"],
            }
        cluster = SimCluster(
            meta["n"],
            params,
            addresses=addresses,
            base_inc=meta["base_inc"],
            backend=backend,
            **kw,
        )
        # Optional (None-default) fields may be absent from the archive —
        # derived from the NamedTuple defaults so save/load stay in lockstep.
        def load_tuple(cls, prefix):
            optional = {
                name
                for name, default in cls._field_defaults.items()
                if default is None
            }
            leaves = {}
            for name in cls._fields:
                key_name = f"{prefix}.{name}"
                if key_name in data:
                    leaves[name] = jax.numpy.asarray(data[key_name])
                elif name in optional:
                    leaves[name] = None
                else:
                    raise KeyError(f"checkpoint missing required array {key_name}")
            return cls(**leaves)

        state_cls = DeltaState if backend == "delta" else ClusterState
        cluster.state = load_tuple(state_cls, "state")
        if backend == "delta":
            # The boolean lattice planes are bit-packed at rest (PR 16,
            # ops/bitpack.py); checkpoints written before the packing
            # store them as bool tensors under the same names — detect
            # by dtype and pack once at load (still format v5: the .npz
            # is self-describing, the names did not change)
            from ringpop_tpu.ops import bitpack

            st = cluster.state
            if st.bp_mask.dtype == np.bool_:
                st = st._replace(bp_mask=bitpack.pack_bits(st.bp_mask))
            if st.d_bpmask is not None and st.d_bpmask.dtype == np.bool_:
                st = st._replace(d_bpmask=bitpack.pack_bits(st.d_bpmask))
            cluster.state = st
        if backend == "delta" and cluster.state.digest is None:
            # checkpoints predating the carried derivatives (optional
            # fields absent): backfill from the oracles once at load
            from ringpop_tpu.models.swim_delta import refresh_carried

            cluster.state = refresh_carried(cluster.state)
        elif backend == "delta" and (
            os.environ.get("RINGPOP_CARRY_SLOTBASE", "0") == "1"
            and cluster.state.d_bpmask is None
        ):
            # digest already carried; the operator asked for the
            # slot-base carry this process — populate just that
            from ringpop_tpu.models.swim_delta import compute_slot_base
            from ringpop_tpu.ops import bitpack

            bpm, bpr = compute_slot_base(cluster.state)
            cluster.state = cluster.state._replace(
                d_bpmask=bitpack.pack_bits(bpm), d_bprank=bpr
            )
        cluster.net = load_tuple(NetState, "net")
        cluster.key = jax.numpy.asarray(data["key"])
        # telemetry (v4); older checkpoints backfill empty — same
        # optional-field pattern as the delta carried derivatives above
        cluster.metrics_log = [
            {k: int(v) for k, v in entry.items()}
            for entry in meta.get("metrics_log", [])
        ]
        from ringpop_tpu.scenarios.trace import Trace

        cluster.traces = [
            Trace.from_arrays(data, tmeta, prefix=f"trace{i}.")
            for i, tmeta in enumerate(meta.get("traces", []))
        ]
        # streaming cursor (v5); pre-v5 checkpoints have none — the
        # attribute defaults to None in SimCluster.__init__
        cluster.stream_cursor = meta.get("stream")
    if device is not None:
        cluster.state = jax.device_put(cluster.state, device)
        cluster.net = jax.device_put(cluster.net, device)
    return cluster

"""Small helpers (reference: lib/util.js)."""

from __future__ import annotations

import json
import os
import re
import sys
from typing import Any

_HOST_CAPTURE = re.compile(r"(\d+\.\d+\.\d+\.\d+):\d+")


def capture_host(host_port: str) -> str | None:
    """IP portion of an ip:port identity (lib/util.js:27-30)."""
    m = _HOST_CAPTURE.search(host_port or "")
    return m.group(1) if m else None


def num_or_default(value: Any, default: float) -> float:
    return value if isinstance(value, (int, float)) and not isinstance(value, bool) else default


def safe_parse(text: Any) -> Any:
    """JSON parse returning None on failure (lib/util.js:74-80)."""
    if text is None:
        return None
    if isinstance(text, (bytes, bytearray)):
        try:
            text = text.decode("utf-8")
        except UnicodeDecodeError:
            return None
    try:
        return json.loads(text)
    except (ValueError, TypeError):
        return None


def parse_arg(argv: list[str], name: str) -> str | None:
    """Extract ``--name=value`` from argv (lib/util.js:62-72)."""
    pattern = re.compile(r"^" + re.escape(name) + r"=(.*)$")
    for arg in argv:
        m = pattern.match(arg)
        if m:
            return m.group(1)
    return None


def is_empty_array(value: Any) -> bool:
    """True when not a list or an empty list (lib/util.js isEmptyArray)."""
    return not isinstance(value, list) or len(value) == 0


def map_uniq(values: list[Any]) -> list[Any]:
    seen: set[Any] = set()
    out = []
    for v in values:
        if v not in seen:
            seen.add(v)
            out.append(v)
    return out


def to_json(obj: Any) -> str:
    """Compact JSON like JS JSON.stringify."""
    return json.dumps(obj, separators=(",", ":"))


def pin_cpu_if_requested() -> None:
    """Honor ``JAX_PLATFORMS=cpu`` at the jax-config level.

    The env var alone is not enough on this runtime: the ambient TPU
    plugin still contacts its (possibly hung) tunnel during backend
    init.  CPU-capable entry points (bench.py children, the benchmark
    harnesses) call this before any jax computation so a dead
    accelerator never blocks host-only work.  (tick-cluster keeps its
    own richer variant: it honors arbitrary JAX_PLATFORMS values and
    reverts the pin, cli/tick_cluster.py.)  No-op unless the operator
    set ``JAX_PLATFORMS=cpu``."""
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")


def enable_compilation_cache() -> None:
    """Persist compiled executables across processes (<repo>/.jax_cache).

    On the tunneled TPU platform a large program's first compile can
    take minutes; the persistent cache means a warm-up run (or an
    earlier round) pays it once and later processes — the driver's
    bench, the profilers — reuse the executable.  Best-effort:
    platforms whose executables don't serialize just compile live
    (JAX logs a warning)."""
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # CPU-pinned runs skip the cache: its purpose is amortizing the
        # tunneled TPU's minutes-long remote compiles, CPU compiles are
        # cheap — and XLA:CPU AOT cache loads log a spurious
        # machine-feature-mismatch error ("could lead to SIGILL", the
        # embedded feature list carries internal +prefer-no-scatter/
        # -gather flags the runtime probe never reports) on EVERY warm
        # start, even on the machine that wrote the entry.
        return
    try:
        import jax

        cache_dir = os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
            ".jax_cache",
            _host_cpu_tag(),
        )
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 10.0)
    except Exception as e:  # noqa: BLE001 — the cache is an optimization only
        print(f"# compilation cache unavailable: {e!r}", file=sys.stderr)


def _host_cpu_tag() -> str:
    """Cache subdirectory keyed by the host CPU identity.

    CPU executables embed host ISA extensions; loading one cached by a
    different machine trips JAX's feature-mismatch warning ("could lead
    to SIGILL").  Keying the directory per (arch, cpu model) makes
    cross-machine reuse structurally impossible while TPU executables
    (keyed the same way) still hit whenever the same host re-runs."""
    import hashlib
    import platform

    model = ""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.lower().startswith(("model name", "hardware", "cpu model")):
                    model = line.split(":", 1)[1].strip()
                    break
    except OSError:
        pass
    raw = f"{platform.machine()}|{model}"
    return f"host-{hashlib.sha1(raw.encode()).hexdigest()[:12]}"


def provision_virtual_devices(count: int = 4) -> None:
    """Ask XLA for ``count`` virtual CPU devices, if nobody asked yet.

    Appends ``--xla_force_host_platform_device_count=count`` to
    ``XLA_FLAGS`` unless the flag is already present (an operator's or
    conftest's explicit choice always wins).  Must run before the CPU
    backend initializes — XLA reads the flags once; afterwards the call
    is a harmless no-op and multi-device callers (the partitioning
    auditor's mesh entries) degrade with their own capability message.
    The flag only shapes the CPU platform, so setting it under a real
    accelerator is safe."""
    if "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""
    ):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={int(count)}"
        ).strip()

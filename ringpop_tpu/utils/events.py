"""Minimal synchronous event emitter (Node's EventEmitter, as used
throughout the reference, e.g. index.js:156, lib/membership.js:39)."""

from __future__ import annotations

from typing import Any, Callable


class EventEmitter:
    def __init__(self) -> None:
        self._listeners: dict[str, list[Callable[..., Any]]] = {}

    def on(self, event: str, listener: Callable[..., Any]) -> None:
        self._listeners.setdefault(event, []).append(listener)

    def once(self, event: str, listener: Callable[..., Any]) -> None:
        def wrapper(*args: Any) -> None:
            self.remove_listener(event, wrapper)
            listener(*args)

        self.on(event, wrapper)

    def remove_listener(self, event: str, listener: Callable[..., Any]) -> None:
        handlers = self._listeners.get(event)
        if handlers and listener in handlers:
            handlers.remove(listener)

    def remove_all_listeners(self, event: str | None = None) -> None:
        if event is None:
            self._listeners.clear()
        else:
            self._listeners.pop(event, None)

    def emit(self, event: str, *args: Any) -> bool:
        handlers = list(self._listeners.get(event, ()))
        for handler in handlers:
            handler(*args)
        return bool(handlers)

    def listener_count(self, event: str) -> int:
        return len(self._listeners.get(event, ()))

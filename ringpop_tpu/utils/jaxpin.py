"""The pinned jax version every bit-exact lane assumes.

The repo's golden lanes are reproducible only under one jax build:
the incident goldens and the seeded golden traces replay CPU threefry
draws bit-for-bit (PR 14 note), and the analysis budget tables —
carry-dtype multisets, collective censuses of the partitioned HLO,
compiled byte footprints — pin what ONE version of the tracer and the
SPMD partitioner emits.  A jax bump does not make any of them wrong,
it makes them STALE: the right response is "re-pin", not a wall of
bit-diff failures.

``tests/test_jax_pin.py`` asserts the pin itself (one loud, fast
failure naming everything to re-pin); the golden-lane tests call
``golden_skip_reason()`` and SKIP with the re-pin instruction instead
of exploding one assert at a time; the partitioning auditor downgrades
its budget comparisons to a warning on mismatch
(``analysis/partitioning.py``).

On an intentional bump: update ``PINNED_JAX_VERSION``, then re-pin
goldens (``tools/pin_incidents.py``) and budgets
(``tools/pin_budgets.py``).
"""

from __future__ import annotations

PINNED_JAX_VERSION = "0.4.37"


def jax_version() -> str:
    import jax

    return jax.__version__


def jax_version_matches() -> bool:
    """True when the running jax is the pinned build."""
    return jax_version() == PINNED_JAX_VERSION


def golden_skip_reason() -> str | None:
    """None under the pinned jax; otherwise the skip message the
    golden-lane tests surface (explicit re-pin instruction, not a
    bit-diff explosion)."""
    if jax_version_matches():
        return None
    return (
        f"jax {jax_version()} != pinned {PINNED_JAX_VERSION}: PRNG- and "
        "partitioner-dependent goldens are stale, not wrong — re-pin "
        "(tools/pin_incidents.py, tools/pin_budgets.py) and bump "
        "ringpop_tpu/utils/jaxpin.py before trusting bit-exact lanes"
    )

"""No-op logger and stats sink defaults (reference: lib/nulls.js)."""

from __future__ import annotations

from typing import Any


class NullLogger:
    def debug(self, msg: str, extra: Any = None) -> None: ...

    def info(self, msg: str, extra: Any = None) -> None: ...

    def warn(self, msg: str, extra: Any = None) -> None: ...

    def error(self, msg: str, extra: Any = None) -> None: ...

    def trace(self, msg: str, extra: Any = None) -> None: ...


class NullStatsd:
    def increment(self, key: str, value: Any = None) -> None: ...

    def gauge(self, key: str, value: Any = None) -> None: ...

    def timing(self, key: str, value: Any = None) -> None: ...


class CapturingStatsd:
    """Records every stat call; used by tests and /admin/stats."""

    def __init__(self) -> None:
        self.calls: list[tuple[str, str, Any]] = []

    def increment(self, key: str, value: Any = None) -> None:
        self.calls.append(("increment", key, value))

    def gauge(self, key: str, value: Any = None) -> None:
        self.calls.append(("gauge", key, value))

    def timing(self, key: str, value: Any = None) -> None:
        self.calls.append(("timing", key, value))

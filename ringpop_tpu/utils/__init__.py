"""Cross-cutting utilities (reference: lib/util.js, lib/nulls.js)."""

from ringpop_tpu.utils.events import EventEmitter
from ringpop_tpu.utils.misc import (
    capture_host,
    num_or_default,
    parse_arg,
    enable_compilation_cache,
    pin_cpu_if_requested,
    safe_parse,
)
from ringpop_tpu.utils.nulls import NullLogger, NullStatsd

__all__ = [
    "EventEmitter",
    "capture_host",
    "num_or_default",
    "parse_arg",
    "enable_compilation_cache",
    "pin_cpu_if_requested",
    "safe_parse",
    "NullLogger",
    "NullStatsd",
]

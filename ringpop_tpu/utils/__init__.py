"""Cross-cutting utilities (reference: lib/util.js, lib/nulls.js)."""

from ringpop_tpu.utils.events import EventEmitter
from ringpop_tpu.utils.jaxpin import (
    PINNED_JAX_VERSION,
    golden_skip_reason,
    jax_version_matches,
)
from ringpop_tpu.utils.misc import (
    capture_host,
    num_or_default,
    parse_arg,
    enable_compilation_cache,
    pin_cpu_if_requested,
    provision_virtual_devices,
    safe_parse,
)
from ringpop_tpu.utils.nulls import NullLogger, NullStatsd

__all__ = [
    "EventEmitter",
    "PINNED_JAX_VERSION",
    "golden_skip_reason",
    "jax_version_matches",
    "capture_host",
    "num_or_default",
    "parse_arg",
    "enable_compilation_cache",
    "pin_cpu_if_requested",
    "provision_virtual_devices",
    "safe_parse",
    "NullLogger",
    "NullStatsd",
]

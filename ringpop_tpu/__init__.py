"""ringpop_tpu — a TPU-native membership / sharding / forwarding framework.

A ground-up rebuild of the capabilities of charliezhang/ringpop (Uber's SWIM
gossip membership + consistent-hash sharding + request forwarding library)
designed TPU-first:

* ``ringpop_tpu.RingPop`` — the host-side library: full API parity with the
  reference facade (index.js): bootstrap, lookup/lookupN, handleOrProxy(All),
  proxyReq, getStats, whoami, admin ops, events.  Python/asyncio, pluggable
  transports (in-process for tests, TCP JSON-RPC for real clusters).
* ``ringpop_tpu.models.swim_sim`` — the TPU simulation backend: the SWIM
  membership/dissemination layer as vmapped epidemic-broadcast kernels over
  dense N x N view/state tensors, simulating tens of thousands of virtual
  nodes per chip with membership checksums identical to the host library.
* ``ringpop_tpu.ops`` — bit-exact FarmHash32 (C / Python / JAX), checksum and
  hash-ring kernels.
* ``ringpop_tpu.traffic`` — the serving plane: compiled key workloads
  (uniform / Zipf / per-tenant) resolved through per-viewer device rings
  with handle-or-forward simulation, co-run with scenario timelines.
* ``ringpop_tpu.parallel`` — jax.sharding mesh layouts for multi-chip scale.
"""

from ringpop_tpu.ops.farmhash import farmhash32

__version__ = "0.1.0"


def __getattr__(name):
    # Lazy to keep `import ringpop_tpu` light (jax-free) for hashing-only use.
    if name == "RingPop":
        from ringpop_tpu.ringpop import RingPop

        return RingPop
    raise AttributeError(name)


__all__ = ["farmhash32", "RingPop", "__version__"]

"""The one-dispatch scenario scan, and its host-loop twin.

``run_compiled`` executes an entire compiled fault timeline —
kill / revive / suspend / resume, partitions, loss schedule — plus the
per-tick telemetry inside ONE jitted ``lax.scan`` per backend: the
event tensors ride in HBM and each tick applies its events as masked
out-of-bounds-dropped scatters before the protocol step, so a
1000-tick chaos experiment costs one dispatch instead of a host
round-trip per fault boundary (``cluster.py``'s tick/kill/partition
sequence, which remains available as ``run_host_loop`` — the parity
baseline and the benchmark's comparison arm).

Event-application convention (shared with the host loop): all events
of tick t apply before tick t's protocol period; node-bit edits first,
then revives, then partition rows.  Conflicting same-tick events are
rejected at spec validation.

``dispatch_count()`` counts jitted scenario invocations — the CPU
test asserts a whole kill+partition+heal+loss-ramp run increments it
exactly once while dispatching no ``swim_step``/``swim_run`` at all.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ringpop_tpu.models import swim_delta as sdelta
from ringpop_tpu.models import swim_sim as sim
from ringpop_tpu.ops import bitpack
from ringpop_tpu.models.swim_delta import DeltaParams, DeltaState
from ringpop_tpu.obs.ledger import default_ledger
from ringpop_tpu.models.swim_sim import NetState, SwimParams
from ringpop_tpu.scenarios.compile import (
    EV_KILL,
    EV_RESUME,
    EV_REVIVE,
    EV_SUSPEND,
    CompiledScenario,
    expand_events,
)
from ringpop_tpu.obs import provenance as pvn
from ringpop_tpu.policies import core as pol
from ringpop_tpu.scenarios import faults as sfaults
from ringpop_tpu.scenarios.spec import ScenarioSpec
from ringpop_tpu.traffic import engine as traffic_engine

_dispatches = 0


def dispatch_count() -> int:
    """Jitted scenario-scan invocations so far (test instrumentation)."""
    return _dispatches


def _normalize_adj(net: NetState, n: int) -> jax.Array:
    """The scan carries the int32[N] group-id adjacency form (the only
    form both backends compile).  ``adj=None`` and an all-True mask (a
    healed mask-form partition — ``heal_partition`` keeps the mask
    layout on purpose) are both fully connected and lower to the
    all-one-group zeros; a genuine partial mask partition has no
    group-id equivalent and is rejected."""
    if net.adj is None:
        return jnp.zeros((n,), jnp.int32)
    if net.adj.ndim == 1:
        return net.adj
    if bool(np.asarray(net.adj).all()):
        return jnp.zeros((n,), jnp.int32)
    raise ValueError(
        "scenario runs take the group-id adjacency form shared by both "
        "backends; heal the dense bool[N, N] mask partition first"
    )


def precheck(
    state: Any,
    net: NetState,
    compiled: CompiledScenario,
    params: Any | None = None,
    *,
    standing_ok: bool = False,
) -> jax.Array:
    """Every static rejection of ``run_compiled``, callable before any
    PRNG key is drawn — a failed run must not advance the cluster key
    (``SimCluster.run_scenario`` builds the key schedule only after
    this passes).  Returns the normalized group-id adjacency so the
    caller can pass it back through ``run_compiled(adj=...)``: the
    mask-form check costs a host sync (``np.asarray(adj).all()``), and
    it must run once per run — not once per dispatch, which a streamed
    soak turns into thousands (scenarios/stream.py)."""
    if compiled.has_revive and isinstance(state, DeltaState):
        raise NotImplementedError(
            "in-scan revive is dense-backend-only (the delta backend's "
            "revive/join are host-side row ops); use run_host_loop or "
            "backend='dense'"
        )
    if compiled.has_delay:
        sw = getattr(params, "swim", params)
        if sw is not None and getattr(sw, "sparse_cap", 0):
            raise NotImplementedError(
                "per-link delay does not compose with sparse_cap"
            )
        if isinstance(state, DeltaState):
            # the delta in-flight representation: per-arrival-slot claim
            # lanes (swim_delta.install_pending) instead of the dense
            # [D, N, N] claim matrix
            if state.pend_subj is not None:
                if state.pend_subj.shape[0] != compiled.delay_depth:
                    raise ValueError(
                        f"the cluster carries delta in-flight lanes of "
                        f"depth {state.pend_subj.shape[0]} but this "
                        f"scenario needs {compiled.delay_depth}; drain "
                        "them or start from a fresh cluster"
                    )
                w_eff = min(
                    getattr(params, "wire_cap", 16), state.capacity
                )
                if state.pend_subj.shape[-1] != w_eff:
                    raise ValueError(
                        f"delta in-flight lanes are {state.pend_subj.shape[-1]} "
                        f"claims wide but wire_cap lowers {w_eff}-wide "
                        "messages; re-install the buffer"
                    )
        elif (
            state.pending is not None
            and state.pending.shape[0] != compiled.delay_depth
        ):
            raise ValueError(
                f"the cluster carries an in-flight buffer of depth "
                f"{state.pending.shape[0]} but this scenario needs "
                f"{compiled.delay_depth}; drain it (tick past the old "
                "horizon) or start from a fresh cluster"
            )
    if compiled.has_gray or compiled.overload is not None:
        sw = getattr(params, "swim", params)
        if sw is not None and getattr(sw, "phase_mod", 1) > 1:
            raise ValueError(
                "gray/overload events (per-node periods) do not compose "
                "with the static phase_mod stagger: a period row of P "
                "subsumes it"
            )
    if not standing_ok:
        # The compiled scan derives its per-tick network configuration
        # from the SPEC alone: operator-installed standing config that
        # the spec does not model would be silently ignored in-scan
        # (while the host-loop oracle keeps applying it) — reject the
        # ambiguity instead of diverging.  ``standing_ok=True`` is the
        # resume path's opt-out: a resumed run's net carries this very
        # spec's own mirrored rules / mid-window period row.
        if net.link_src is not None:
            active = np.asarray(net.link_p).any() or (
                net.link_d is not None
                and (np.asarray(net.link_d).any() or np.asarray(net.link_j).any())
            )
            if bool(active):
                raise ValueError(
                    "the cluster carries active standing link rules "
                    "(set_link_rules): a compiled scenario applies only "
                    "spec-declared link_loss/delay events — "
                    "clear_link_rules() first, or express the rules as "
                    "spec events (run_host_loop drives standing rules)"
                )
        if (
            compiled.has_gray
            and net.period is not None
            and bool((np.asarray(net.period) != 1).any())
        ):
            raise ValueError(
                "gray events rebuild the period plane from lockstep, "
                "which would clobber the standing set_period row mid-run "
                "— set_period(None) first, or encode the standing row "
                "as gray events"
            )
    return _normalize_adj(net, compiled.n)


def precheck_overload(
    compiled: CompiledScenario,
    traffic: Any | None,
    net: NetState,
    *,
    standing_ok: bool = False,
) -> None:
    """Static rejections of the overload feedback loop, callable before
    any PRNG key is drawn (the ``precheck`` contract).  Overload meters
    serve-plane sends, so a spec carrying it needs a traffic workload
    in the same scan; and a net carrying leftover feedback state from
    a previous overload run would silently seed the new run's pressure
    — reject unless resuming (``standing_ok``), whose net carries this
    very run's own mid-window state."""
    if compiled.overload is None:
        return
    if traffic is None:
        raise ValueError(
            "overload events meter the serve plane's per-node sends: "
            "pass a traffic workload (run_scenario(spec, traffic=...))"
        )
    if not standing_ok and net.ov_cnt is not None:
        if bool(np.asarray(net.ov_cnt).any() or np.asarray(net.ov_gray).any()):
            raise ValueError(
                "the cluster carries overload feedback state from a "
                "previous run (net.ov_cnt/ov_gray): clear_overload() "
                "first, or resume the run that wrote it"
            )


def overload_traffic(traffic: Any | None, compiled: CompiledScenario) -> Any:
    """The traffic statics a scenario actually compiles: an overload
    spec needs the serve plane's per-node send accounting, so its
    workload compiles with ``track_load`` on (everything else ships the
    exact program the workload was lowered with)."""
    if traffic is None or compiled.overload is None:
        return traffic
    if traffic.static.track_load:
        return traffic
    return traffic._replace(static=traffic.static._replace(track_load=1))


def precheck_policy(
    policy: Any | None,
    traffic: Any | None,
    net: NetState,
    *,
    standing_ok: bool = False,
) -> None:
    """Static rejections of the remediation policy plane, callable
    before any PRNG key is drawn (the ``precheck`` contract).  A policy
    meters serve-plane sends and delivered counts, so it needs a
    traffic workload in the same scan; and a net carrying leftover
    policy state from a previous run would silently seed the new run's
    pressure/windows — reject unless resuming (``standing_ok``), whose
    net carries this very run's own mid-window state."""
    if policy is None:
        return
    if traffic is None:
        raise ValueError(
            "policies meter the serve plane (per-node sends + delivered): "
            "pass a traffic workload (run_scenario(spec, traffic=..., "
            "policy=...))"
        )
    if not standing_ok and net.po_press is not None:
        leftover = (
            np.asarray(net.po_press).any()
            or np.asarray(net.po_shed).any()
            or np.asarray(net.po_quar).any()
            or np.asarray(net.po_sends_w).any()
            or np.asarray(net.po_deliv_w).any()
        )
        if bool(leftover):
            raise ValueError(
                "the cluster carries policy state from a previous run "
                "(net.po_*): clear_policy() first, or resume the run "
                "that wrote it"
            )


def policy_traffic(traffic: Any | None, policy: Any | None) -> Any:
    """The traffic statics a policy-armed scenario compiles: the policy
    fold needs per-node send accounting (``track_load``) and the serve
    chains need the policy hooks + the ``policy_shed`` counter
    (``track_policy``)."""
    if traffic is None or policy is None:
        return traffic
    st = traffic.static
    if st.track_load and st.track_policy:
        return traffic
    return traffic._replace(
        static=st._replace(track_load=1, track_policy=1)
    )


def precheck_prov(
    compiled: CompiledScenario,
    net: NetState,
    params: Any | None = None,
    *,
    standing_ok: bool = False,
) -> None:
    """Static rejections of the provenance plane, callable before any
    PRNG key is drawn (the ``precheck`` contract).  The plane folds the
    dense delivery-evidence bundle, which the sparse-dissemination
    program never materializes; and a net carrying tracked-rumor state
    from a previous run would silently extend the old wavefronts —
    reject unless resuming (``standing_ok``), whose net carries this
    very run's own mid-flight planes."""
    if not compiled.trace_rumors:
        return
    sw = getattr(params, "swim", params)
    if sw is not None and getattr(sw, "sparse_cap", 0):
        raise NotImplementedError(
            "trace_rumors needs the dense delivery evidence; run traced "
            "scenarios with sparse_cap=0"
        )
    if not standing_ok and net.pv_slot is not None:
        if bool((np.asarray(net.pv_slot)[:, 0] >= 0).any()):
            raise ValueError(
                "the cluster carries tracked-rumor state from a previous "
                "run (net.pv_*): clear_provenance() first, or resume the "
                "run that wrote it"
            )


def prepare_prov(
    compiled: CompiledScenario, net: NetState, params: Any | None = None
) -> tuple[Any, jax.Array | None, jax.Array | None]:
    """The initial provenance carry + track-reservation tensors —
    all-unarmed slots for a fresh run, or the net's checkpointed
    mid-flight planes on resume (the prepare_faults/prepare_policy
    contract).  Returns ``(ProvCarry | None, pv_at, pv_node)``."""
    if not compiled.trace_rumors:
        return None, None, None
    k = compiled.trace_rumors
    sw = getattr(params, "swim", params)
    kk = int(getattr(sw, "ping_req_size", 3))
    if net.pv_slot is not None:
        if net.pv_slot.shape[0] != k:
            raise ValueError(
                f"the cluster carries {net.pv_slot.shape[0]} tracked-rumor "
                f"slots but this scenario compiles {k}; clear_provenance() "
                "or match trace_rumors"
            )
        pvc = pvn.ProvCarry(
            slot=jnp.asarray(net.pv_slot, jnp.int32),
            tickv=jnp.asarray(net.pv_tickv, jnp.int16),
            wits=jnp.asarray(net.pv_wits, jnp.int32),
            first=jnp.asarray(net.pv_first, jnp.int16),
            parent=jnp.asarray(net.pv_parent, jnp.int32),
            knows=jnp.asarray(net.pv_knows, jnp.uint32),
        )
    else:
        pvc = pvn.init_carry(compiled.n, k, kk)
    pv_at, pv_node = pvn.track_tensors(compiled.tracks, k)
    return pvc, pv_at, pv_node


def prepare_policy(
    policy: Any | None, net: NetState, n: int, max_retries: int
) -> tuple | None:
    """The initial policy carry (unpacked form) — zeros for a fresh
    run, or the net's checkpointed mid-window state on resume."""
    if policy is None:
        return None
    cfg = policy.config
    if net.po_sends_w is not None and (
        net.po_sends_w.shape[-1] != cfg.amp_window
    ):
        raise ValueError(
            f"the cluster carries a policy amp window of "
            f"{net.po_sends_w.shape[-1]} ticks but this policy uses "
            f"{cfg.amp_window}; clear_policy() or match amp_window"
        )
    return pol.init_policy_state(n, cfg, max_retries, net=net)


def _apply_revives(state, up, resp, m, ev_kind, ev_node):
    """Dense-backend in-scan revive: the scan twin of
    ``SimCluster.revive(i)`` — fresh incarnation past the cluster
    maximum, row wipe, net bits up, bootstrap join against the first
    live node (none live -> stays unjoined, like the host path).
    Sequential over the (few) events: each revive's join reads the
    state the previous one wrote."""
    ids = jnp.arange(state.n, dtype=jnp.int32)

    def one(i, carry):
        def do(args):
            st, u, r = args
            node = ev_node[i]
            inc = (jnp.max(st.view_key) >> 3) + 1000
            st = sim.revive(st, node, inc)
            u = u.at[node].set(True)
            r = r.at[node].set(True)
            own = jnp.diagonal(st.view_key) & 7
            cand = (
                u & r & ((own == sim.ALIVE) | (own == sim.SUSPECT)) & (ids != node)
            )
            joined = sim.admin_join(st, node, jnp.argmax(cand))
            has_seed = jnp.any(cand)
            st = jax.tree_util.tree_map(
                lambda a, b: jnp.where(has_seed, b, a), st, joined
            )
            return st, u, r

        return jax.lax.cond(
            m[i] & (ev_kind[i] == EV_REVIVE), do, lambda args: args, carry
        )

    return jax.lax.fori_loop(0, ev_node.shape[0], one, (state, up, resp))


def _scenario_scan_impl(
    state,
    up,
    responsive,
    adj,
    period,
    ev_tick,
    ev_kind,
    ev_node,
    p_tick,
    p_gid,
    loss,
    keys,
    tr_tensors=None,
    tick0=None,
    faults=None,
    ov=None,
    po=None,
    po_knobs=None,
    sw_knobs=None,
    pv=None,
    pv_at=None,
    pv_node=None,
    *,
    params,
    has_revive: bool,
    traffic=None,
    overload=None,
    policy=None,
    prov: int | None = None,
):
    # ``tick0`` (traced int32 scalar, or None for 0) offsets the tick
    # counter the event/partition/traffic comparisons see: a streamed
    # soak (scenarios/stream.py) runs this same program once per
    # S-tick segment with tick0 = segment start, so ONE compiled
    # executable serves the whole run and the in-scan tick numbering
    # matches the unsegmented scan bit-for-bit.
    n = up.shape[0]
    ticks = keys.shape[0]
    is_delta = isinstance(state, DeltaState)
    ids = jnp.arange(n, dtype=jnp.int32)
    oob = jnp.int32(n)  # masked events scatter out of bounds -> dropped

    def body(carry, xs):
        # node-bit planes ride the carry bit-packed (uint32 words, 1
        # bit/node); all in-tick work runs on the unpacked bool form
        st, pu, pr, gid, per, ovc, poc, pvc = carry
        u = bitpack.unpack_bits(pu, n)
        r = bitpack.unpack_bits(pr, n)
        if overload is not None:
            ovc = (ovc[0], bitpack.unpack_bits(ovc[1], n))
        if policy is not None:
            # the remediation plane from LAST tick's fold (causal, like
            # the overload gray bit): shed/quarantine flags ride the
            # carry bit-packed next to the node-bit planes
            po_press, po_sends_w, po_deliv_w, po_cap = (
                poc[0], poc[3], poc[4], poc[5]
            )
            po_shed = bitpack.unpack_bits(poc[1], n)
            po_quar = bitpack.unpack_bits(poc[2], n)
        t, key, loss_t = xs
        if ev_tick.shape[0]:
            m = ev_tick == t
            u = u.at[jnp.where(m & (ev_kind == EV_KILL), ev_node, oob)].set(
                False, mode="drop"
            )
            r = r.at[jnp.where(m & (ev_kind == EV_SUSPEND), ev_node, oob)].set(
                False, mode="drop"
            )
            r = r.at[jnp.where(m & (ev_kind == EV_RESUME), ev_node, oob)].set(
                True, mode="drop"
            )
            if has_revive:
                st, u, r = _apply_revives(st, u, r, m, ev_kind, ev_node)
        if p_tick.shape[0]:
            pm = p_tick == t
            gid = jnp.where(jnp.any(pm), p_gid[jnp.argmax(pm)], gid)
        # failure-model events (scenarios/faults.py): period-row
        # switches ride the carry like partitions; link rules need no
        # carry at all — each rule's [start, end) window is evaluated
        # against the (tick0-offset) tick, so the same program streams
        if faults is not None and faults.pe_tick.shape[0]:
            gm = faults.pe_tick == t
            per = jnp.where(jnp.any(gm), faults.pe_row[jnp.argmax(gm)], per)
        link_kw = {}
        if faults is not None and faults.lr_p.shape[0]:
            active = (t >= faults.lr_start) & (t < faults.lr_end)
            link_kw = dict(
                link_src=faults.lr_src,
                link_dst=faults.lr_dst,
                link_p=jnp.where(active, faults.lr_p, jnp.float32(0)),
            )
            if faults.lr_d is not None:
                link_kw.update(
                    link_d=jnp.where(active, faults.lr_d, 0),
                    link_j=jnp.where(active, faults.lr_j, 0),
                )
        # load-coupled gray degradation (faults.OverloadConfig): a node
        # the feedback flagged last tick runs at the degraded period
        # THIS tick — for its protocol step and its serve duty phase
        # alike — so retry pressure causes gray and gray attracts the
        # retries the latency plane's duty timeouts generate
        # the carry holds the period row int16 (periods are small tick
        # multipliers; prepare_faults validates the range) — consumers
        # see the historical int32 form
        per_eff = None if per is None else per.astype(jnp.int32)
        if overload is not None:
            ov_cnt, ov_fl = ovc
            per_eff = jnp.where(
                ov_fl, jnp.maximum(per_eff, jnp.int32(overload.factor)), per_eff
            )
        net = NetState(up=u, responsive=r, adj=gid, period=per_eff, **link_kw)
        # traced protocol knobs (sim.SwimKnobs) close over the scan body
        # as constants, not carry entries — the per-tick loss override
        # stays on the params pytree exactly as before
        if is_delta:
            sp = params._replace(swim=params.swim._replace(loss=loss_t))
            st, metrics = sdelta.delta_step_impl(st, net, key, sp,
                                                 knobs=sw_knobs,
                                                 prov=prov is not None)
            conv = sdelta._converged_impl(st, u, r)
            own = sdelta.view_lookup(st, ids) & 7
        else:
            sp = params._replace(loss=loss_t)
            st, metrics = sim.swim_step_impl(st, net, key, sp, sw_knobs,
                                             prov is not None)
            conv = sim.converged_impl(st, net)
            own = jnp.diagonal(st.view_key) & 7
        live = jnp.sum(
            u & r & ((own == sim.ALIVE) | (own == sim.SUSPECT)),
            dtype=jnp.int32,
        )
        y = dict(metrics)
        y["converged"] = conv
        y["live"] = live
        y["loss"] = loss_t
        if prov is not None:
            # the provenance fold consumes the step's delivery-evidence
            # bundle in place (never stacked into the telemetry) and
            # emits the per-slot heard count as its one [K] plane
            ev = {k: y.pop(k) for k in pvn.EVIDENCE_KEYS}
            if is_delta:
                view_post = lambda q: sdelta.view_lookup(st, q)  # noqa: E731
            else:
                view_post = lambda q: jnp.take_along_axis(  # noqa: E731
                    st.view_key, q, axis=1
                )
            pvc, heard = pvn.prov_update(
                pvc, ev, t, view_post, pv_at, pv_node, n
            )
            y["pv_heard"] = heard
        if traffic is not None:
            # serve this tick's key batch against the views the protocol
            # period just produced: lookups under churn, in the same
            # compiled program (workload PRNG is its own stream, so the
            # protocol trajectory is bit-identical with traffic off).
            # Rows are passed as a thunk so the delta backend's O(N^2)
            # materialize only traces inside the on-cadence branch.
            y.update(
                traffic_engine.serve_tick(
                    (lambda s=st: sdelta.materialize_rows(s, ids))
                    if is_delta
                    else st.view_key,
                    u, r, tr_tensors, t, static=traffic,
                    damped=getattr(st, "damped", None),
                    # the SLO latency plane reads the tick's ACTIVE link
                    # rules and the EFFECTIVE period row (overload-
                    # degraded; ignored when the plane is off)
                    net=net, period=per_eff,
                    policy=(po_shed, po_quar, po_cap)
                    if policy is not None else None,
                )
            )
        # this tick's send load closes the feedback loops: both the
        # overload meter and the policy fold consume the SAME per-node
        # vector, which is popped once and never stacked
        sends = None
        if overload is not None or policy is not None:
            sends = y.pop("node_sends")
        if overload is not None:
            # pressure and the hysteresis gray bit update AFTER serving
            # (the flag the serve/step above read is last tick's —
            # causal)
            in_win = (t >= overload.start) & (t < overload.end)
            ov_cnt, ov_fl = sfaults.overload_update(
                overload, in_win, ov_cnt, ov_fl, sends
            )
            y["ov_gray_nodes"] = jnp.sum(ov_fl, dtype=jnp.int32)
            y["ov_pressure_max"] = jnp.max(ov_cnt)
            ovc = (ov_cnt, bitpack.pack_bits(ov_fl))
        if policy is not None:
            # the policy fold runs POST-serve with the same causality:
            # the planes serve_tick consulted above were last tick's
            (po_press, po_shed, po_quar, po_sends_w, po_deliv_w,
             po_cap, amp_x16) = pol.policy_update(
                policy, po_knobs, po_press, po_shed, po_quar,
                po_sends_w, po_deliv_w, sends,
                jnp.sum(sends, dtype=jnp.int32), y["delivered"], t,
                traffic.max_retries,
            )
            y["policy_shed_nodes"] = jnp.sum(po_shed, dtype=jnp.int32)
            y["policy_quarantined"] = jnp.sum(po_quar, dtype=jnp.int32)
            y["policy_pressure_max"] = jnp.max(po_press)
            y["policy_retry_cap"] = po_cap
            y["policy_amp_x16"] = amp_x16
            poc = (po_press, bitpack.pack_bits(po_shed),
                   bitpack.pack_bits(po_quar), po_sends_w, po_deliv_w,
                   po_cap)
        return (st, bitpack.pack_bits(u), bitpack.pack_bits(r), gid, per,
                ovc, poc, pvc), y

    t_idx = jnp.arange(ticks, dtype=jnp.int32)
    if tick0 is not None:
        t_idx = t_idx + tick0
    xs = (t_idx, keys, loss)
    ov_c = None if ov is None else (ov[0], bitpack.pack_bits(ov[1]))
    po_c = None if po is None else (
        po[0], bitpack.pack_bits(po[1]), bitpack.pack_bits(po[2]),
        po[3], po[4], po[5],
    )
    # the provenance carry arrives pre-packed (ProvCarry: the knows
    # planes are uint32 words at rest, no bool leaves) — no boundary
    # pack/unpack like the node-bit planes
    (state, pu, pr, adj, period, ov_c, po_c, pv), ys = jax.lax.scan(
        body,
        (state, bitpack.pack_bits(up), bitpack.pack_bits(responsive), adj,
         period, ov_c, po_c, pv),
        xs,
    )
    up = bitpack.unpack_bits(pu, n)
    responsive = bitpack.unpack_bits(pr, n)
    ov = None if ov_c is None else (ov_c[0], bitpack.unpack_bits(ov_c[1], n))
    po = None if po_c is None else (
        po_c[0], bitpack.unpack_bits(po_c[1], n),
        bitpack.unpack_bits(po_c[2], n), po_c[3], po_c[4], po_c[5],
    )
    # period stays int16 on exit: the streamed runner threads this
    # return straight into the next segment's dispatch, so widening
    # here would retrace the one compiled executable
    return state, up, responsive, adj, period, ov, po, pv, ys


_scenario_scan = jax.jit(
    _scenario_scan_impl,
    static_argnames=(
        "params", "has_revive", "traffic", "overload", "policy", "prov"
    ),
    donate_argnums=(0, 1, 2, 3),
)

_DAMP_KNOBS = ("damp_penalty", "damp_decay_per_tick",
               "damp_suppress", "damp_reuse")


def validate_param_knobs(
    n: int,
    swim_params: SwimParams,
    knob_values: dict[str, Any],
    *,
    backend: str,
    period_active: bool,
    damping: bool,
) -> None:
    """Host-side composition guards for traced protocol knobs, shared by
    the one-dispatch runner (singleton values) and the sweep's
    ``param_axes`` (one list per knob).  Traced values cannot be checked
    in-trace, so every constraint a compile-time knob used to enforce
    statically is re-checked here, against EVERY value the knob will
    take, before anything is device-ified:

    - range + int8 digit budgets at the axis max (``_validate_params``);
    - ``phase_mod`` must stay 1 when the scenario carries per-node
      period rows (gray/overload): the period row subsumes the stagger
      divisor, so a swept phase_mod would be silently ignored;
    - the delta backend has no damping plane and statically rejects
      ``relay_full_sync`` — knob values that would silently no-op raise
      instead;
    - damp-threshold knobs need the damping plane armed on the dense
      backend (``init_state(..., damping=True)``).
    """
    for name, vals in knob_values.items():
        for v in vals:
            sim.check_knob_value(name, v, swim_params)
    sim._validate_params(n, swim_params, knob_values=knob_values)
    if period_active:
        for i, v in enumerate(knob_values.get("phase_mod", ())):
            if int(v) != 1:
                raise ValueError(
                    f"phase_mod={int(v)} (axis value {i}): scenarios with "
                    "per-node period rows (gray degradation / overload) "
                    "subsume the stagger divisor, so the knob would be "
                    "silently ignored; pin phase_mod to 1 here"
                )
    if backend == "delta":
        for i, v in enumerate(knob_values.get("relay_full_sync", ())):
            if int(v) != 0:
                raise ValueError(
                    f"relay_full_sync={int(v)} (axis value {i}): the delta "
                    "backend has no full-sync exchange arm; sweep this "
                    "knob on the dense backend"
                )
        bad = sorted(set(knob_values) & set(_DAMP_KNOBS))
        if bad:
            raise ValueError(
                f"damp knob(s) {bad}: the delta backend has no damping "
                "plane; sweep damp thresholds on the dense backend"
            )
    elif not damping:
        bad = sorted(set(knob_values) & set(_DAMP_KNOBS))
        if bad:
            raise ValueError(
                f"damp knob(s) {bad} need the damping plane armed: "
                "init the dense cluster with damping=True"
            )


def run_compiled(
    state: Any,
    net: NetState,
    keys: jax.Array,
    compiled: CompiledScenario,
    params: SwimParams | DeltaParams,
    traffic: Any | None = None,
    adj: jax.Array | None = None,
    policy: Any | None = None,
    param_knobs: dict[str, float | int] | None = None,
) -> tuple[Any, NetState, dict[str, jax.Array]]:
    """One jitted call: (state, net, per-tick telemetry stacks [ticks]).

    ``params`` is ``SwimParams`` for a dense ``ClusterState`` and
    ``DeltaParams`` for a ``DeltaState``; its ``loss`` is overridden
    per tick by the compiled schedule.  ``keys`` is the segment-exact
    uint32[ticks, 2] schedule from ``compile.key_schedule``.

    ``traffic`` (a ``traffic.CompiledTraffic``) co-runs a key workload
    inside the same scan: every tick's batch is served against the
    views that tick produced, adding the serving counters
    (``traffic.engine.counter_names``) to the telemetry stacks without
    touching the protocol key schedule.

    ``adj`` is the normalized group-id adjacency a caller that already
    ran ``precheck`` passes back in, skipping the repeat host sync of
    the mask-form check.

    ``policy`` (a ``policies.CompiledPolicy``) arms the remediation
    plane: its knobs ride as traced scalars, its state rides the scan
    carry, and the post-run net round-trips it (``net.po_*``).

    ``param_knobs`` overrides traced protocol knobs (``sim.SwimKnobs``
    names) as host values for this run — same compiled program as the
    defaults, different scalar operands.  Values are validated host-side
    against the backend/scenario composition rules
    (``validate_param_knobs``) before the dispatch.
    """
    global _dispatches
    if keys.shape[0] != compiled.ticks:
        raise ValueError(
            f"key schedule has {keys.shape[0]} rows for {compiled.ticks} ticks"
        )
    if adj is None:
        adj = precheck(state, net, compiled, params)
        precheck_overload(compiled, traffic, net)
        precheck_policy(policy, traffic, net)
        precheck_prov(compiled, net, params)
    traffic = overload_traffic(traffic, compiled)
    traffic = policy_traffic(traffic, policy)
    state, period, ov = prepare_faults(state, net, compiled, params)
    pv, pv_at, pv_node = prepare_prov(compiled, net, params)
    po = None
    knobs = None
    if policy is not None:
        po = prepare_policy(policy, net, compiled.n,
                            traffic.static.max_retries)
        knobs = pol.knob_arrays(policy)
    sw_knobs = None
    if param_knobs is not None:
        is_delta = isinstance(state, DeltaState)
        swp = params.swim if is_delta else params
        validate_param_knobs(
            compiled.n, swp, {k: [v] for k, v in param_knobs.items()},
            backend="delta" if is_delta else "dense",
            period_active=(period is not None),
            damping=getattr(state, "damp", None) is not None,
        )
        sw_knobs = sim.swim_knob_arrays(swp, param_knobs)
    _dispatches += 1
    meta = {
        "backend": "delta" if isinstance(state, DeltaState) else "dense",
        "n": compiled.n,
        "ticks": compiled.ticks,
        "replicas": 1,
    }
    if traffic is not None:
        meta["traffic_m"] = traffic.static.m
    if policy is not None:
        meta["policy"] = policy.name
    if param_knobs is not None:
        meta["param_knobs"] = sorted(param_knobs)
    if compiled.trace_rumors:
        meta["trace_rumors"] = compiled.trace_rumors
    # ledger-off (the default): dispatch() is a plain call-through; on,
    # the dispatch is recorded with its compile/execute split and AOT
    # memory footprint (obs/ledger.py)
    state, up, resp, adj, period, ov, po, pv, ys = default_ledger().dispatch(
        "run_scenario",
        _scenario_scan,
        state,
        net.up,
        net.responsive,
        adj,
        period,
        compiled.ev_tick,
        compiled.ev_kind,
        compiled.ev_node,
        compiled.p_tick,
        compiled.p_gid,
        compiled.loss,
        keys,
        traffic.tensors if traffic is not None else None,
        None,
        compiled.faults,
        ov,
        po,
        knobs,
        sw_knobs,
        pv,
        pv_at,
        pv_node,
        params=params,
        has_revive=compiled.has_revive,
        traffic=traffic.static if traffic is not None else None,
        overload=compiled.overload,
        policy=policy.config if policy is not None else None,
        prov=compiled.trace_rumors or None,
        _meta=meta,
    )
    return (
        state,
        final_net(up, resp, adj, period, compiled, ov=ov, po=po, pv=pv),
        ys,
    )


def prepare_faults(
    state: Any, net: NetState, compiled: CompiledScenario,
    params: Any | None = None,
) -> tuple[Any, jax.Array | None, tuple[jax.Array, jax.Array] | None]:
    """Pre-scan failure-model setup shared by the one-dispatch runner,
    the sweep, and the streamed runner: install the in-flight claim
    buffer when the spec delays messages (from tick 0 — its presence
    widens the step's key split, mirroring ``HostPlan.prepare``),
    produce the initial per-node period carry row (the cluster's
    standing row, or all-ones when the scenario introduces gray
    periods to a lockstep cluster), and the overload feedback carry
    ``(pressure int32[N], gray bool[N])`` — zeros for a fresh run, or
    the net's checkpointed mid-window state on resume.  ``params``
    sizes the delta backend's in-flight lanes (wire_cap)."""
    if compiled.has_delay:
        if isinstance(state, DeltaState):
            if state.pend_subj is None:
                state = sdelta.install_pending(
                    state,
                    compiled.delay_depth,
                    getattr(params, "wire_cap", 16),
                )
        elif state.pending is None:
            state = state._replace(
                pending=jnp.zeros(
                    (compiled.delay_depth, compiled.n, compiled.n), jnp.int32
                )
            )
    period = net.period
    if (compiled.has_gray or compiled.overload is not None) and period is None:
        period = jnp.ones((compiled.n,), jnp.int16)
    elif period is not None and period.dtype != jnp.int16:
        # the scan carries the period row int16 (a narrowed slot in
        # CARRY_BUDGETS); rows are concrete host data here, so the
        # range check is free and loud instead of a silent wrap
        pmax = int(np.asarray(period).max()) if period.size else 0
        if pmax > np.iinfo(np.int16).max:
            raise ValueError(
                f"per-node period {pmax} exceeds the int16 carry range"
            )
        period = jnp.asarray(period, jnp.int16)
    ov = None
    if compiled.overload is not None:
        if net.ov_cnt is not None:
            ov = (
                jnp.asarray(net.ov_cnt, jnp.int32),
                jnp.asarray(net.ov_gray, bool),
            )
        else:
            ov = (
                jnp.zeros((compiled.n,), jnp.int32),
                jnp.zeros((compiled.n,), bool),
            )
    return state, period, ov


def final_net(
    up: jax.Array,
    resp: jax.Array,
    adj: jax.Array,
    period: jax.Array | None,
    compiled: CompiledScenario,
    ov: tuple[jax.Array, jax.Array] | None = None,
    po: tuple | None = None,
    pv: Any | None = None,
) -> NetState:
    """The post-run NetState, link rules mirrored to their state at the
    final tick — exactly what the host loop's last ``faultcfg`` apply
    leaves in force, so the parity contract covers the net too and
    follow-on ``tick()`` calls keep the end-of-scenario configuration."""
    kw = {}
    ft = compiled.faults
    if ft is not None and ft.lr_p.shape[0]:
        last = jnp.int32(compiled.ticks - 1)
        active = (last >= ft.lr_start) & (last < ft.lr_end)
        kw = dict(
            link_src=ft.lr_src,
            link_dst=ft.lr_dst,
            link_p=jnp.where(active, ft.lr_p, jnp.float32(0)),
        )
        if ft.lr_d is not None:
            kw.update(
                link_d=jnp.where(active, ft.lr_d, 0),
                link_j=jnp.where(active, ft.lr_j, 0),
            )
    if ov is not None:
        # the feedback carry persists on the net so checkpoints (and a
        # stream resume) continue the pressure/hysteresis state exactly
        kw.update(ov_cnt=ov[0], ov_gray=ov[1])
    if po is not None:
        # same contract for the policy carry (unpacked form)
        kw.update(
            po_press=po[0], po_shed=po[1], po_quar=po[2],
            po_sends_w=po[3], po_deliv_w=po[4], po_retry_cap=po[5],
        )
    if pv is not None:
        # and for the provenance carry (ProvCarry leaf order; knows
        # stays packed — it is packed words at rest everywhere)
        kw.update(
            pv_slot=pv.slot, pv_tickv=pv.tickv, pv_wits=pv.wits,
            pv_first=pv.first, pv_parent=pv.parent, pv_knows=pv.knows,
        )
    return NetState(up=up, responsive=resp, adj=adj, period=period, **kw)


def run_host_loop(cluster, spec: ScenarioSpec):
    """The equivalent host-driven fault sequence, via the public
    ``SimCluster`` surface: apply each tick's events, ``tick()`` the
    segment to the next boundary.  Consumes the cluster key exactly as
    ``compile.key_schedule`` does, so from equal starting state and
    key the trajectory is bit-identical to ``run_compiled`` — the
    parity oracle (tests/test_scenario.py, test_faults.py) and the
    many-dispatch arm of ``benchmarks/bench_scenario.py``.

    Intra-tick events apply in the canonical order the scan uses
    (``compile._OP_RANK``): node bit edits, then revives (whose
    bootstrap join reads the post-edit live set, in expansion order),
    then partitions/loss/fault configuration."""
    from ringpop_tpu.scenarios import compile as scompile

    spec.validate(cluster.n)
    if any(e.op == "overload" for e in spec.events):
        raise NotImplementedError(
            "run_host_loop does not serve traffic, so it cannot drive "
            "the overload feedback loop; the per-tick host oracle for "
            "overload lives in tests/test_overload.py (run_scenario "
            "with traffic= is the compiled path)"
        )
    plan = sfaults.HostPlan(spec, cluster.n)
    plan.prepare(cluster)
    by_tick: dict[int, list[tuple[str, Any]]] = defaultdict(list)
    for at, op, arg in expand_events(spec, cluster.params.loss):
        by_tick[at].append((op, arg))
    boundaries = sorted(t for t in by_tick if 0 < t < spec.ticks)
    pts = [0, *boundaries, spec.ticks]
    for a, b in zip(pts, pts[1:]):
        ops = sorted(
            by_tick.get(a, ()), key=lambda x: scompile._OP_RANK[x[0]]
        )
        cfg_done = False
        for op, arg in ops:
            if op == "kill":
                cluster.kill(arg)
            elif op == "suspend":
                cluster.suspend(arg)
            elif op == "resume":
                cluster.resume(arg)
            elif op == "revive":
                cluster.revive(arg)
            elif op == "partition":
                cluster.partition([list(g) for g in arg])
            elif op == "heal":
                cluster.heal_partition()
            elif op == "loss":
                cluster.set_loss(arg)
            elif op == "faultcfg" and not cfg_done:
                plan.apply(cluster, a)
                cfg_done = True
        cluster.tick(b - a)
    return cluster

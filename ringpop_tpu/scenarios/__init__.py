"""Scenario engine: compiled fault timelines + per-tick telemetry.

The subsystem that finishes what the reference stubbed out
(test/lib/partition-cluster.js:59-61 — scripted netsplits) and goes
past it: a declarative fault timeline (kill / revive / suspend /
resume at tick t, partition / heal, stepwise loss schedules) compiles
into device-resident event tensors applied *inside* the
``swim_run``/``delta_run`` scan, so an entire chaos experiment runs as
ONE jitted call per backend — no host round-trips at fault boundaries
— while the same scan stacks a per-tick telemetry time series
(protocol metrics, converged flag, live count) into a ``Trace``.

Layers:

* ``spec``    — the declarative ``ScenarioSpec`` (JSON-loadable) and
  the ``--script`` mini-DSL compiler into it.
* ``compile`` — ``ScenarioSpec -> CompiledScenario`` event tensors +
  the segment-exact PRNG key schedule.
* ``faults``  — the failure-model compiler: asymmetric per-link loss,
  latency/jitter (an in-flight claim ring buffer), flap storms,
  gray-failure per-node periods, and rolling restarts as compiled
  scenario events, with the host plan the parity oracle applies.
* ``runner``  — the single-dispatch jitted scan over both backends,
  plus the host-loop equivalent (the parity/benchmark baseline).
* ``trace``   — the stacked telemetry, npz export, and the
  ``stats.py``-key-compatible summary.
* ``sweep``   — R replicas of a compiled scenario vmapped into ONE
  jitted dispatch (batch axes: PRNG seed, per-replica loss scale,
  kill-tick jitter), with the stacked ``SweepTrace`` telemetry.
* ``stream``  — the chunked-scan soak runner: pipelined S-tick
  segment dispatches of one compiled executable, per-segment
  ``SegmentStore`` slabs + stats bridging (O(segment) host trace
  memory), checkpoint-every-segment and bit-exact ``resume``.
* ``library`` — the incident library: named real-world outages
  (region partitions with asymmetric heals, cascading overload,
  deploys-during-partition, ...) as parameterized spec+workload
  builders, with the golden detect/heal/serve summary the regression
  lane pins (``tick-cluster --incident NAME`` / ``--list-incidents``).

Entry points: ``SimCluster.run_scenario(spec[, segment_ticks=S])``,
``SimCluster.run_sweep(spec, replicas)``, and
``tick-cluster --backend tpu-sim --scenario FILE [--sweep R]
[--segment-ticks S --checkpoint C | --resume C]``.
"""

from ringpop_tpu.scenarios.spec import Event, ScenarioSpec, script_to_spec
from ringpop_tpu.scenarios.compile import CompiledScenario, compile_spec
from ringpop_tpu.scenarios.faults import (
    FaultTensors,
    HostPlan,
    LinkRule,
    compile_faults,
    delay_depth,
    link_rules,
    period_switches,
)
from ringpop_tpu.scenarios.trace import Trace
from ringpop_tpu.scenarios.runner import run_compiled, run_host_loop
from ringpop_tpu.scenarios.sweep import (
    CompiledSweep,
    SweepTrace,
    compile_sweep,
    replica_spec,
    run_sweep_compiled,
)
from ringpop_tpu.scenarios.stream import (
    SegmentStore,
    StreamInterrupted,
    resume,
    run_streamed,
    run_sweep_streamed,
)
from ringpop_tpu.scenarios.library import (
    INCIDENTS,
    Incident,
    build_incident,
    incident_names,
    incident_summary,
)

__all__ = [
    "Event",
    "ScenarioSpec",
    "script_to_spec",
    "CompiledScenario",
    "compile_spec",
    "FaultTensors",
    "HostPlan",
    "LinkRule",
    "compile_faults",
    "delay_depth",
    "link_rules",
    "period_switches",
    "Trace",
    "run_compiled",
    "run_host_loop",
    "CompiledSweep",
    "SweepTrace",
    "compile_sweep",
    "replica_spec",
    "run_sweep_compiled",
    "SegmentStore",
    "StreamInterrupted",
    "resume",
    "run_streamed",
    "run_sweep_streamed",
    "INCIDENTS",
    "Incident",
    "build_incident",
    "incident_names",
    "incident_summary",
]

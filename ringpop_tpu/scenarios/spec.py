"""Declarative scenario specs (JSON) + the ``--script`` DSL compiler.

A scenario is a tick count plus a list of timed fault events.  Events
apply at the START of their tick, before that tick's protocol period —
the same convention as the host sequence ``apply fault; tick()``.

JSON shape (``ScenarioSpec.from_json`` / ``to_json``)::

    {
      "ticks": 120,
      "events": [
        {"at": 10, "op": "kill",      "node": 3},
        {"at": 12, "op": "suspend",   "node": 4},
        {"at": 30, "op": "resume",    "node": 4},
        {"at": 20, "op": "partition", "groups": [[0,1,2,3], [4,5,6,7]]},
        {"at": 60, "op": "heal"},
        {"at": 40, "op": "loss",      "p": 0.2},
        {"at": 70, "op": "loss_ramp", "until": 90, "to": 0.0},
        {"at": 95, "op": "revive",    "node": 3}
      ]
    }

Ops:

* ``kill`` / ``suspend`` / ``resume`` — the ``NetState.up`` /
  ``responsive`` bit edits (tick-cluster.js:432-462 signal surface).
* ``revive`` — a killed process restarts fresh with a higher
  incarnation and re-joins against the first live node
  (tick-cluster.js:418-430); dense backend only inside the scan (the
  delta backend's join is a host-side row op — use the host loop).
* ``partition`` — block netsplit in the group-id adjacency form;
  ``groups`` must cover every node exactly once (the only form both
  backends accept inside one compiled program).  ``heal`` restores
  full connectivity.
* ``loss`` — set the iid packet-loss probability from this tick on.
* ``loss_ramp`` — stepwise-linear ramp from the loss in force at
  ``at`` to ``to``, reaching ``to`` at tick ``until - 1`` (compiled
  into one per-tick ``loss`` step per tick of the ramp).
"""

from __future__ import annotations

import json
from typing import Any, NamedTuple

_NODE_OPS = ("kill", "revive", "suspend", "resume")
_OPS = _NODE_OPS + ("partition", "heal", "loss", "loss_ramp")


class Event(NamedTuple):
    at: int
    op: str
    node: int | None = None
    groups: tuple[tuple[int, ...], ...] | None = None
    p: float | None = None
    until: int | None = None  # loss_ramp end tick (exclusive)

    def to_dict(self) -> dict[str, Any]:
        d: dict[str, Any] = {"at": self.at, "op": self.op}
        if self.node is not None:
            d["node"] = self.node
        if self.groups is not None:
            d["groups"] = [list(g) for g in self.groups]
        if self.p is not None:
            d["p" if self.op == "loss" else "to"] = self.p
        if self.until is not None:
            d["until"] = self.until
        return d

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "Event":
        op = d.get("op")
        if op not in _OPS:
            raise ValueError(f"unknown scenario op {op!r} (one of {_OPS})")
        groups = d.get("groups")
        return cls(
            at=int(d["at"]),
            op=op,
            node=int(d["node"]) if "node" in d else None,
            groups=tuple(tuple(int(m) for m in g) for g in groups)
            if groups is not None
            else None,
            p=float(d["p"]) if "p" in d else (
                float(d["to"]) if "to" in d else None
            ),
            until=int(d["until"]) if "until" in d else None,
        )


class ScenarioSpec(NamedTuple):
    ticks: int
    events: tuple[Event, ...] = ()

    def to_dict(self) -> dict[str, Any]:
        return {"ticks": self.ticks, "events": [e.to_dict() for e in self.events]}

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2)

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "ScenarioSpec":
        return cls(
            ticks=int(d["ticks"]),
            events=tuple(Event.from_dict(e) for e in d.get("events", [])),
        )

    @classmethod
    def from_json(cls, text: str) -> "ScenarioSpec":
        return cls.from_dict(json.loads(text))

    @classmethod
    def load(cls, path: str) -> "ScenarioSpec":
        with open(path) as f:
            return cls.from_dict(json.load(f))

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.to_json() + "\n")

    def validate(self, n: int) -> "ScenarioSpec":
        """Static validation against a cluster size; raises ValueError."""
        if self.ticks < 1:
            raise ValueError(f"ticks must be >= 1 (got {self.ticks})")
        seen_node_tick: set[tuple[int, int]] = set()
        seen_part_tick: set[int] = set()
        node_event_ticks: set[int] = set()
        revive_ticks: set[int] = set()
        for e in self.events:
            if not 0 <= e.at < self.ticks:
                raise ValueError(
                    f"event {e.op!r} at tick {e.at} outside [0, {self.ticks})"
                )
            if e.op in _NODE_OPS:
                if e.node is None or not 0 <= e.node < n:
                    raise ValueError(
                        f"event {e.op!r} needs a node in [0, {n}) (got {e.node})"
                    )
                if (e.at, e.node) in seen_node_tick:
                    raise ValueError(
                        f"conflicting node events at tick {e.at} on node "
                        f"{e.node}: apply order inside one tick is undefined"
                    )
                seen_node_tick.add((e.at, e.node))
                if e.op == "revive":
                    revive_ticks.add(e.at)
                else:
                    node_event_ticks.add(e.at)
        # a revive's bootstrap join reads the live set, so same-tick
        # kill/suspend/resume (any node) would make the outcome depend
        # on intra-tick apply order — the scan applies bit edits before
        # revives while the host oracle applies spec order; reject the
        # ambiguity instead of silently breaking the parity contract
        clash = revive_ticks & node_event_ticks
        if clash:
            raise ValueError(
                f"revive shares tick {min(clash)} with another node event: "
                "a revive's join reads the live set, so same-tick apply "
                "order would be ambiguous — put the revive on its own tick"
            )
        for e in self.events:
            if e.op == "partition":
                if not e.groups:
                    raise ValueError("partition event needs non-empty groups")
                flat = [m for g in e.groups for m in g]
                if sorted(flat) != list(range(n)):
                    raise ValueError(
                        "partition groups must cover every node exactly once "
                        "(the group-id adjacency form both backends compile)"
                    )
            if e.op in ("partition", "heal"):
                if e.at in seen_part_tick:
                    raise ValueError(
                        f"two partition/heal events at tick {e.at}: apply "
                        "order inside one tick is undefined"
                    )
                seen_part_tick.add(e.at)
            if e.op == "loss" and not (e.p is not None and 0.0 <= e.p < 1.0):
                raise ValueError(f"loss event needs p in [0, 1) (got {e.p})")
            if e.op == "loss_ramp":
                if e.p is None or not 0.0 <= e.p < 1.0:
                    raise ValueError(f"loss_ramp needs 'to' in [0, 1) (got {e.p})")
                if e.until is None or not e.at < e.until <= self.ticks:
                    raise ValueError(
                        f"loss_ramp needs at < until <= ticks "
                        f"(got at={e.at}, until={e.until})"
                    )
        return self


def script_to_spec(
    script: str, n: int, *, period_ms: int = 200
) -> ScenarioSpec:
    """Compile a ``tick-cluster --script`` command list into a spec.

    The mini-DSL is linear in wall/virtual time; the compiler replays it
    against a host-side liveness model to resolve the relative targets
    (``k`` kills the highest-indexed not-yet-killed node, ``K`` revives
    the oldest kill, ``l``/``L`` suspend/resume — the TpuSimCluster
    driver's selection rule, minus protocol-state gating the compiler
    cannot know).  ``t`` is one tick; ``wN`` is ``max(1, N // period_ms)``
    ticks; reporting commands (``j g s p d D``) carry no protocol effect
    and compile to nothing; ``q`` ends the scenario.

    The live driver applies back-to-back commands instantly; the
    compiled form needs a defined per-tick order, so a command that
    would collide with an earlier same-tick event (same node twice, or
    a revive mixing with other node events — the combinations
    ``ScenarioSpec.validate`` rejects) is placed one tick later,
    advancing the clock for everything after it (``k,K`` compiles to
    kill at t, revive at t+1).
    """
    events: list[Event] = []
    tick = 0
    killed: list[int] = []
    suspended: list[int] = []
    node_ticks: set[tuple[int, int]] = set()
    tick_kinds: dict[int, set[str]] = {}

    def place(op: str, node: int) -> None:
        nonlocal tick
        kind = "revive" if op == "revive" else "other"
        other = "other" if kind == "revive" else "revive"
        while (tick, node) in node_ticks or other in tick_kinds.get(tick, ()):
            tick += 1
        events.append(Event(at=tick, op=op, node=node))
        node_ticks.add((tick, node))
        tick_kinds.setdefault(tick, set()).add(kind)

    for op in script.split(","):
        op = op.strip()
        if not op:
            continue
        if op == "q":
            break
        if op[0] == "w":
            tick += max(1, int(float(op[1:]) / period_ms))
        elif op == "t":
            tick += 1
        elif op == "k":
            live = [i for i in range(n) if i not in killed and i not in suspended]
            if live:
                place("kill", live[-1])
                killed.append(live[-1])
        elif op == "K":
            if killed:
                place("revive", killed.pop(0))
        elif op == "l":
            live = [i for i in range(n) if i not in killed and i not in suspended]
            if live:
                place("suspend", live[-1])
                suspended.append(live[-1])
        elif op == "L":
            for node in suspended:
                place("resume", node)
            suspended.clear()
        elif op in ("j", "g", "s", "p", "d", "D"):
            pass  # reporting / no protocol effect in the compiled form
        else:
            raise ValueError(f"unknown script command {op!r}")
    # trailing events need a tick to act in; a bare fault list gets one
    ticks = max(tick, max((e.at for e in events), default=0) + 1, 1)
    return ScenarioSpec(ticks=ticks, events=tuple(events)).validate(n)

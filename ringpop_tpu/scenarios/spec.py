"""Declarative scenario specs (JSON) + the ``--script`` DSL compiler.

A scenario is a tick count plus a list of timed fault events.  Events
apply at the START of their tick, before that tick's protocol period —
the same convention as the host sequence ``apply fault; tick()``.

JSON shape (``ScenarioSpec.from_json`` / ``to_json``)::

    {
      "ticks": 120,
      "events": [
        {"at": 10, "op": "kill",      "node": 3},
        {"at": 12, "op": "suspend",   "node": 4},
        {"at": 30, "op": "resume",    "node": 4},
        {"at": 20, "op": "partition", "groups": [[0,1,2,3], [4,5,6,7]]},
        {"at": 60, "op": "heal"},
        {"at": 40, "op": "loss",      "p": 0.2},
        {"at": 70, "op": "loss_ramp", "until": 90, "to": 0.0},
        {"at": 95, "op": "revive",    "node": 3}
      ]
    }

Ops:

* ``kill`` / ``suspend`` / ``resume`` — the ``NetState.up`` /
  ``responsive`` bit edits (tick-cluster.js:432-462 signal surface).
* ``revive`` — a killed process restarts fresh with a higher
  incarnation and re-joins against the first live node
  (tick-cluster.js:418-430); dense backend only inside the scan (the
  delta backend's join is a host-side row op — use the host loop).
* ``partition`` — block netsplit in the group-id adjacency form;
  ``groups`` must cover every node exactly once (the only form both
  backends accept inside one compiled program).  ``heal`` restores
  full connectivity.
* ``loss`` — set the iid packet-loss probability from this tick on.
* ``loss_ramp`` — stepwise-linear ramp from the loss in force at
  ``at`` to ``to``, reaching ``to`` at tick ``until - 1`` (compiled
  into one per-tick ``loss`` step per tick of the ramp).

Failure-model ops (the asymmetric-incident families; scenarios/faults.py
compiles them, docs/simulation.md documents the host conventions):

* ``link_loss`` — DIRECTED extra drop probability ``p`` on every link
  from a ``src`` node set to a ``dst`` node set during ``[at, until)``
  (``until`` defaults to the end of the run): ``{"op": "link_loss",
  "at": 10, "src": [0,1], "dst": [4,5], "p": 0.9}`` makes dst hear src
  only 10% of the time while src still hears dst perfectly — the
  one-way-loss incident a symmetric ``loss`` cannot express.
* ``delay`` — per-link message latency: claims sent over src->dst
  links land ``delay + U{0..jitter}`` ticks later (0 = immediate)
  during ``[at, until)``; the ping/ack RTT itself still completes
  in-tick (the simulation's time-compression convention — latency
  slows information, not liveness).  Both backends: the dense
  ``[D, N, N]`` in-flight claim matrix, or the delta backend's
  per-arrival-slot claim lanes (``swim_delta.install_pending``).
* ``flap`` — kill/revive duty cycles: each node in ``nodes`` (offset
  ``stagger`` ticks apart) is killed for ``down`` ticks then up for
  ``up`` ticks, cycling while the kill tick is < ``until``; every kill
  emits its matching revive, so the storm always heals itself.
* ``gray`` — slow-process failure: the node's protocol period becomes
  ``factor`` ticks during ``[at, until)`` — it still answers pings and
  witness duties every tick (stays alive in others' views) but
  initiates its own probes only every ``factor``-th tick.
* ``rolling_restart`` — a staggered deploy wave: node k of ``nodes``
  is killed at ``at + k * every`` and revived (fresh incarnation,
  bootstrap re-join) ``down`` ticks later.
* ``overload`` — the load-coupled gray feedback loop (needs a
  ``traffic`` workload co-running in the scan): during ``[at, until)``
  every node accumulates overload pressure ``max(0, pressure + sends
  - capacity)`` from the serve plane's per-tick sends landing on it;
  at ``pressure >= threshold`` the node's protocol period degrades to
  ``factor`` (it goes gray — and with the SLO latency plane on, gray
  holders time out off their duty phase, attracting the retry storms
  that feed the pressure back), recovering with hysteresis only once
  pressure drains to ``<= recover``.  At most one per spec.

``flap``/``rolling_restart`` expand to the kill/revive primitives at
compile time (one shared expansion, so the compiled scan and the host
loop see identical timelines).  Same-tick mixes of revives and other
node events apply in a canonical order — kill/suspend/resume bit edits
first, then revives in (tick, node-expansion) order, then partitions —
on both the scan and the host loop; only two events on the same
(tick, node) remain rejected as ambiguous.
"""

from __future__ import annotations

import json
from typing import Any, NamedTuple

_NODE_OPS = ("kill", "revive", "suspend", "resume")
_FAULT_OPS = ("link_loss", "delay", "flap", "gray", "rolling_restart",
              "overload")
# observation ops: no protocol effect, no event tensor — compile-time
# configuration for the provenance plane (obs/provenance.py).  ``track``
# reserves a tracked-rumor slot for ``node``: the slot arms at the first
# qualifying suspect declaration about that subject at tick >= ``at``.
# Requires ``trace_rumors > 0`` on the spec.
_OBS_OPS = ("track",)
_OPS = (
    _NODE_OPS + ("partition", "heal", "loss", "loss_ramp")
    + _FAULT_OPS + _OBS_OPS
)

# ops that take a p value under the JSON key "p" (loss_ramp uses "to")
_P_OPS = ("loss", "link_loss", "delay")


class Event(NamedTuple):
    at: int
    op: str
    node: int | None = None
    groups: tuple[tuple[int, ...], ...] | None = None
    p: float | None = None
    until: int | None = None  # window end tick (exclusive)
    # failure-model fields (None unless the op uses them)
    nodes: tuple[int, ...] | None = None  # flap/gray/rolling targets
    src: tuple[int, ...] | None = None  # link rule: sender set
    dst: tuple[int, ...] | None = None  # link rule: receiver set
    down: int | None = None  # flap/rolling: ticks spent dead
    up: int | None = None  # flap: ticks spent alive per cycle
    every: int | None = None  # rolling: ticks between node starts
    stagger: int | None = None  # flap: per-node cycle offset
    factor: int | None = None  # gray/overload: protocol-period multiplier
    delay: int | None = None  # delay: base latency ticks
    jitter: int | None = None  # delay: uniform extra latency bound
    capacity: int | None = None  # overload: sends absorbed per tick
    threshold: int | None = None  # overload: pressure that flips gray
    recover: int | None = None  # overload: pressure that clears gray

    def to_dict(self) -> dict[str, Any]:
        d: dict[str, Any] = {"at": self.at, "op": self.op}
        if self.node is not None:
            d["node"] = self.node
        if self.groups is not None:
            d["groups"] = [list(g) for g in self.groups]
        if self.p is not None:
            d["p" if self.op in _P_OPS else "to"] = self.p
        if self.until is not None:
            d["until"] = self.until
        for name in ("nodes", "src", "dst"):
            v = getattr(self, name)
            if v is not None:
                d[name] = list(v)
        for name in ("down", "up", "every", "stagger", "factor",
                     "delay", "jitter", "capacity", "threshold", "recover"):
            v = getattr(self, name)
            if v is not None:
                d[name] = v
        return d

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "Event":
        op = d.get("op")
        if op not in _OPS:
            raise ValueError(f"unknown scenario op {op!r} (one of {_OPS})")
        groups = d.get("groups")

        def _ints(name):
            return (
                tuple(int(m) for m in d[name]) if name in d else None
            )

        return cls(
            at=int(d["at"]),
            op=op,
            node=int(d["node"]) if "node" in d else None,
            groups=tuple(tuple(int(m) for m in g) for g in groups)
            if groups is not None
            else None,
            p=float(d["p"]) if "p" in d else (
                float(d["to"]) if "to" in d else None
            ),
            until=int(d["until"]) if "until" in d else None,
            nodes=_ints("nodes"),
            src=_ints("src"),
            dst=_ints("dst"),
            down=int(d["down"]) if "down" in d else None,
            up=int(d["up"]) if "up" in d else None,
            every=int(d["every"]) if "every" in d else None,
            stagger=int(d["stagger"]) if "stagger" in d else None,
            factor=int(d["factor"]) if "factor" in d else None,
            delay=int(d["delay"]) if "delay" in d else None,
            jitter=int(d["jitter"]) if "jitter" in d else None,
            capacity=int(d["capacity"]) if "capacity" in d else None,
            threshold=int(d["threshold"]) if "threshold" in d else None,
            recover=int(d["recover"]) if "recover" in d else None,
        )

    def target_nodes(self) -> tuple[int, ...]:
        """The node set of a flap/gray/rolling event (``nodes`` or the
        singular ``node``)."""
        if self.nodes is not None:
            return self.nodes
        if self.node is not None:
            return (self.node,)
        return ()


def expand_fault_primitives(e: Event, ticks: int) -> list[Event]:
    """``flap``/``rolling_restart`` as their primitive kill/revive
    events — the ONE expansion shared by the event-tensor compiler and
    the host-loop oracle (``compile.expand_events``), so both sides see
    identical timelines by construction.  Emission order (per node, per
    cycle) is deterministic; it is the intra-tick revive order."""
    out: list[Event] = []
    if e.op == "flap":
        cycle = e.down + e.up
        for idx, node in enumerate(e.target_nodes()):
            t = e.at + idx * (e.stagger or 0)
            while t < e.until:
                out.append(Event(at=t, op="kill", node=node))
                out.append(Event(at=t + e.down, op="revive", node=node))
                t += cycle
    elif e.op == "rolling_restart":
        for k, node in enumerate(e.target_nodes()):
            t = e.at + k * e.every
            out.append(Event(at=t, op="kill", node=node))
            out.append(Event(at=t + e.down, op="revive", node=node))
    return out


class ScenarioSpec(NamedTuple):
    ticks: int
    events: tuple[Event, ...] = ()
    # provenance plane (obs/provenance.py): number of tracked-rumor
    # slots to carry through the scan.  0 (the default) compiles the
    # exact legacy program — the plane doesn't exist.
    trace_rumors: int = 0

    def to_dict(self) -> dict[str, Any]:
        d: dict[str, Any] = {
            "ticks": self.ticks,
            "events": [e.to_dict() for e in self.events],
        }
        if self.trace_rumors:
            d["trace_rumors"] = self.trace_rumors
        return d

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2)

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "ScenarioSpec":
        return cls(
            ticks=int(d["ticks"]),
            events=tuple(Event.from_dict(e) for e in d.get("events", [])),
            trace_rumors=int(d.get("trace_rumors", 0)),
        )

    @classmethod
    def from_json(cls, text: str) -> "ScenarioSpec":
        return cls.from_dict(json.loads(text))

    @classmethod
    def load(cls, path: str) -> "ScenarioSpec":
        with open(path) as f:
            return cls.from_dict(json.load(f))

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.to_json() + "\n")

    def validate(self, n: int) -> "ScenarioSpec":
        """Static validation against a cluster size; raises ValueError."""
        from ringpop_tpu.obs import provenance as _prov

        if self.ticks < 1:
            raise ValueError(f"ticks must be >= 1 (got {self.ticks})")
        if self.trace_rumors < 0 or self.trace_rumors > _prov.MAX_RUMORS:
            raise ValueError(
                f"trace_rumors must be in [0, {_prov.MAX_RUMORS}] "
                f"(got {self.trace_rumors})"
            )
        if self.trace_rumors and self.ticks > _prov.MAX_TICKS:
            raise ValueError(
                f"the provenance plane carries int16 ticks: trace_rumors "
                f"needs ticks <= {_prov.MAX_TICKS} (got {self.ticks})"
            )
        n_track = sum(1 for e in self.events if e.op == "track")
        if n_track and not self.trace_rumors:
            raise ValueError(
                "track events need trace_rumors > 0 on the spec (the "
                "slot count is the compiled plane's static width)"
            )
        if n_track > self.trace_rumors:
            raise ValueError(
                f"{n_track} track events exceed trace_rumors="
                f"{self.trace_rumors} slots"
            )
        seen_node_tick: set[tuple[int, int]] = set()
        seen_part_tick: set[int] = set()

        def claim_node_tick(at: int, node: int, op: str) -> None:
            # two events touching one (tick, node) are genuinely
            # ambiguous (kill+revive of the same node, say); same-tick
            # events on DIFFERENT nodes apply in the canonical order
            # shared by the scan and the host loop (module docstring)
            if (at, node) in seen_node_tick:
                raise ValueError(
                    f"conflicting node events at tick {at} on node "
                    f"{node} ({op}): apply order inside one tick on one "
                    "node is undefined"
                )
            seen_node_tick.add((at, node))

        def check_window(e: Event, what: str) -> int:
            until = e.until if e.until is not None else self.ticks
            if not e.at < until <= self.ticks:
                raise ValueError(
                    f"{what} needs at < until <= ticks "
                    f"(got at={e.at}, until={until}, ticks={self.ticks})"
                )
            return until

        def check_nodes(e: Event, what: str) -> tuple[int, ...]:
            targets = e.target_nodes()
            if not targets or not all(0 <= m < n for m in targets):
                raise ValueError(
                    f"{what} needs nodes in [0, {n}) (got {targets})"
                )
            return targets

        gray_windows: dict[int, list[tuple[int, int]]] = {}
        overload_seen = False
        for e in self.events:
            if not 0 <= e.at < self.ticks:
                raise ValueError(
                    f"event {e.op!r} at tick {e.at} outside [0, {self.ticks})"
                )
            if e.op in _NODE_OPS:
                if e.node is None or not 0 <= e.node < n:
                    raise ValueError(
                        f"event {e.op!r} needs a node in [0, {n}) (got {e.node})"
                    )
                claim_node_tick(e.at, e.node, e.op)
            elif e.op == "flap":
                if not (e.down and e.down >= 1 and e.up and e.up >= 1):
                    raise ValueError(
                        f"flap needs down >= 1 and up >= 1 "
                        f"(got down={e.down}, up={e.up})"
                    )
                if (e.stagger or 0) < 0:
                    raise ValueError(f"flap stagger must be >= 0 (got {e.stagger})")
                until = check_window(e, "flap")
                check_nodes(e, "flap")
                if until + e.down > self.ticks:
                    raise ValueError(
                        f"flap window ending at {until} needs until + down "
                        f"<= ticks so its last revive lands inside the run "
                        f"(down={e.down}, ticks={self.ticks})"
                    )
            elif e.op == "rolling_restart":
                if not (e.down and e.down >= 1 and e.every and e.every >= 1):
                    raise ValueError(
                        f"rolling_restart needs down >= 1 and every >= 1 "
                        f"(got down={e.down}, every={e.every})"
                    )
                targets = check_nodes(e, "rolling_restart")
                last = e.at + (len(targets) - 1) * e.every + e.down
                if last >= self.ticks:
                    raise ValueError(
                        f"rolling_restart's last revive at tick {last} falls "
                        f"outside [0, {self.ticks})"
                    )
            elif e.op == "gray":
                if not (e.factor and e.factor >= 1):
                    raise ValueError(f"gray needs factor >= 1 (got {e.factor})")
                until = check_window(e, "gray")
                for node in check_nodes(e, "gray"):
                    for a, b in gray_windows.get(node, ()):
                        if e.at < b and a < until:
                            raise ValueError(
                                f"gray windows overlap on node {node} "
                                f"([{a}, {b}) and [{e.at}, {until})): which "
                                "factor wins would be order-dependent"
                            )
                    gray_windows.setdefault(node, []).append((e.at, until))
            elif e.op == "overload":
                if overload_seen:
                    raise ValueError(
                        "at most one overload event per spec (which "
                        "capacity/threshold wins would be order-dependent)"
                    )
                overload_seen = True
                check_window(e, "overload")
                if not (e.capacity and e.capacity >= 1):
                    raise ValueError(
                        f"overload needs capacity >= 1 (got {e.capacity})"
                    )
                if not (e.threshold and e.threshold >= 1):
                    raise ValueError(
                        f"overload needs threshold >= 1 (got {e.threshold})"
                    )
                rec = e.recover if e.recover is not None else 0
                if not 0 <= rec < e.threshold:
                    raise ValueError(
                        f"overload needs 0 <= recover < threshold (got "
                        f"recover={e.recover}, threshold={e.threshold})"
                    )
                if not (e.factor and e.factor >= 2):
                    raise ValueError(
                        f"overload needs factor >= 2 (got {e.factor}; "
                        "1 would degrade nothing)"
                    )
            elif e.op == "track":
                if e.node is None or not 0 <= e.node < n:
                    raise ValueError(
                        f"track needs a node in [0, {n}) (got {e.node})"
                    )
                if sum(
                    1 for o in self.events
                    if o.op == "track" and o.node == e.node
                ) > 1:
                    raise ValueError(
                        f"duplicate track reservations for node {e.node}: "
                        "a subject's rumor slot arms once"
                    )
            elif e.op in ("link_loss", "delay"):
                check_window(e, e.op)
                for name in ("src", "dst"):
                    side = getattr(e, name)
                    if not side or not all(0 <= m < n for m in side):
                        raise ValueError(
                            f"{e.op} needs {name} nodes in [0, {n}) (got {side})"
                        )
                if e.op == "link_loss":
                    if e.p is None or not 0.0 <= e.p < 1.0:
                        raise ValueError(
                            f"link_loss needs p in [0, 1) (got {e.p})"
                        )
                else:
                    d, j = e.delay or 0, e.jitter or 0
                    if d < 0 or j < 0 or d + j < 1:
                        raise ValueError(
                            f"delay needs delay >= 0, jitter >= 0 and "
                            f"delay + jitter >= 1 (got delay={e.delay}, "
                            f"jitter={e.jitter})"
                        )
                    if e.p is not None and not 0.0 <= e.p < 1.0:
                        raise ValueError(
                            f"delay's optional p must be in [0, 1) (got {e.p})"
                        )
        # the expanded flap/rolling kill/revive primitives join the
        # (tick, node) conflict check — two flaps on one node, or a flap
        # colliding with an explicit kill, are caught here
        for e in self.events:
            if e.op in ("flap", "rolling_restart"):
                for pe in expand_fault_primitives(e, self.ticks):
                    if not 0 <= pe.at < self.ticks:  # pragma: no cover
                        raise ValueError(
                            f"{e.op} expansion places {pe.op!r} at tick "
                            f"{pe.at} outside [0, {self.ticks})"
                        )
                    claim_node_tick(pe.at, pe.node, f"{e.op} expansion")
        for e in self.events:
            if e.op == "partition":
                if not e.groups:
                    raise ValueError("partition event needs non-empty groups")
                flat = [m for g in e.groups for m in g]
                if sorted(flat) != list(range(n)):
                    raise ValueError(
                        "partition groups must cover every node exactly once "
                        "(the group-id adjacency form both backends compile)"
                    )
            if e.op in ("partition", "heal"):
                if e.at in seen_part_tick:
                    raise ValueError(
                        f"two partition/heal events at tick {e.at}: apply "
                        "order inside one tick is undefined"
                    )
                seen_part_tick.add(e.at)
            if e.op == "loss" and not (e.p is not None and 0.0 <= e.p < 1.0):
                raise ValueError(f"loss event needs p in [0, 1) (got {e.p})")
            if e.op == "loss_ramp":
                if e.p is None or not 0.0 <= e.p < 1.0:
                    raise ValueError(f"loss_ramp needs 'to' in [0, 1) (got {e.p})")
                if e.until is None or not e.at < e.until <= self.ticks:
                    raise ValueError(
                        f"loss_ramp needs at < until <= ticks "
                        f"(got at={e.at}, until={e.until})"
                    )
        return self


def script_to_spec(
    script: str, n: int, *, period_ms: int = 200
) -> ScenarioSpec:
    """Compile a ``tick-cluster --script`` command list into a spec.

    The mini-DSL is linear in wall/virtual time; the compiler replays it
    against a host-side liveness model to resolve the relative targets
    (``k`` kills the highest-indexed not-yet-killed node, ``K`` revives
    the oldest kill, ``l``/``L`` suspend/resume — the TpuSimCluster
    driver's selection rule, minus protocol-state gating the compiler
    cannot know).  ``t`` is one tick; ``wN`` is ``max(1, N // period_ms)``
    ticks; reporting commands (``j g s p d D``) carry no protocol effect
    and compile to nothing; ``q`` ends the scenario.

    The live driver applies back-to-back commands instantly; the
    compiled form needs a defined per-tick order, so a command that
    would collide with an earlier same-tick event (same node twice, or
    a revive mixing with other node events — the combinations
    ``ScenarioSpec.validate`` rejects) is placed one tick later,
    advancing the clock for everything after it (``k,K`` compiles to
    kill at t, revive at t+1).
    """
    events: list[Event] = []
    tick = 0
    killed: list[int] = []
    suspended: list[int] = []
    node_ticks: set[tuple[int, int]] = set()
    tick_kinds: dict[int, set[str]] = {}

    def place(op: str, node: int) -> None:
        nonlocal tick
        kind = "revive" if op == "revive" else "other"
        other = "other" if kind == "revive" else "revive"
        while (tick, node) in node_ticks or other in tick_kinds.get(tick, ()):
            tick += 1
        events.append(Event(at=tick, op=op, node=node))
        node_ticks.add((tick, node))
        tick_kinds.setdefault(tick, set()).add(kind)

    for op in script.split(","):
        op = op.strip()
        if not op:
            continue
        if op == "q":
            break
        if op[0] == "w":
            tick += max(1, int(float(op[1:]) / period_ms))
        elif op == "t":
            tick += 1
        elif op == "k":
            live = [i for i in range(n) if i not in killed and i not in suspended]
            if live:
                place("kill", live[-1])
                killed.append(live[-1])
        elif op == "K":
            if killed:
                place("revive", killed.pop(0))
        elif op == "l":
            live = [i for i in range(n) if i not in killed and i not in suspended]
            if live:
                place("suspend", live[-1])
                suspended.append(live[-1])
        elif op == "L":
            for node in suspended:
                place("resume", node)
            suspended.clear()
        elif op in ("j", "g", "s", "p", "d", "D"):
            pass  # reporting / no protocol effect in the compiled form
        else:
            raise ValueError(f"unknown script command {op!r}")
    # trailing events need a tick to act in; a bare fault list gets one
    ticks = max(tick, max((e.at for e in events), default=0) + 1, 1)
    return ScenarioSpec(ticks=ticks, events=tuple(events)).validate(n)

"""Failure-model compiler: asymmetric links, latency, gray periods.

The scenario engine's first-generation events (kill / revive / suspend /
partition / loss) are all SYMMETRIC: the network drops every message
with one scalar probability and a partition severs both directions.
Real SWIM incidents are not — one-way link loss (A hears B, B never
hears A), per-link latency and jitter, and lagging-but-alive processes
are exactly the failure modes the reference stack dies from in
production.  This module lowers those families into device tensors the
compiled scenario scan evaluates per tick, plus the host-side plan the
parity oracle (``runner.run_host_loop``) applies at segment boundaries.

Three representations, all O(N) or O(K * N) — never an [N, N] matrix:

* **Link rules** (``link_loss`` / ``delay`` events): K directed block
  rules, each ``(src bool[N], dst bool[N], p, delay, jitter)`` active
  during ``[start, end)``.  A message from s to r is governed by every
  active rule with ``src[s] & dst[r]``: drop probabilities compose as
  ``1 - prod(1 - p_k)`` and delays take the per-pair maximum.  The
  scan evaluates activity from the traced tick (``start <= t < end``),
  so rules cost no carry and stream (tick0-offset segments) for free.
* **Period rows** (``gray`` events): an int32[N] per-node protocol
  period, switched at event boundaries exactly like partition gid rows
  (``pe_tick``/``pe_row``) and carried through the scan.  A gray node
  answers pings and witness duties every tick but initiates its own
  probes once per ``factor`` ticks — the per-node generalization of
  ``SwimParams.phase_mod`` (and its delta-backend port: a constant row
  of P reproduces phase_mod bit for bit on both backends).
* **Delay depth**: the static ring-buffer length ``max(delay + jitter)
  + 1`` for the in-flight claim buffer (``ClusterState.pending``,
  models/swim_sim.py) that carries delayed messages across ticks.

``flap``/``rolling_restart`` need nothing here: they expand to the
existing kill/revive primitives in ``spec.expand_fault_primitives``
(shared by the tensor compiler and the host loop).
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ringpop_tpu.scenarios.spec import ScenarioSpec


class LinkRule(NamedTuple):
    """One directed block rule (host form; windows in spec ticks)."""

    start: int
    end: int
    src: tuple[int, ...]
    dst: tuple[int, ...]
    p: float  # extra drop probability on the link
    delay: int  # base latency in ticks
    jitter: int  # uniform extra latency in {0..jitter}


class FaultTensors(NamedTuple):
    """Device tensors for the scan (shapes static per compile).

    ``lr_d``/``lr_j`` are None when the spec has no delay rules — their
    presence is the static fact that routes the step through the
    in-flight buffer (and widens the per-tick key split), so a
    loss-only scenario compiles the exact non-delay program.
    """

    lr_src: jax.Array  # bool[K, N]
    lr_dst: jax.Array  # bool[K, N]
    lr_p: jax.Array  # float32[K]
    lr_start: jax.Array  # int32[K]
    lr_end: jax.Array  # int32[K]
    lr_d: jax.Array | None  # int32[K] | None (no delay rules)
    lr_j: jax.Array | None  # int32[K] | None
    pe_tick: jax.Array  # int32[G] period-switch ticks
    pe_row: jax.Array  # int16[G, N] per-node period rows (narrowed carry)


class OverloadConfig(NamedTuple):
    """The load-coupled gray feedback loop's static knobs (all ints —
    hashable, so the scan jit-specializes on them like its other
    static facts).  Per tick ``t`` in ``[start, end)``, with
    ``sends[i]`` the serve plane's send attempts landing on node i
    (``traffic/engine.py`` ``node_sends``)::

        pressure[i] = max(0, pressure[i] + sends[i] - capacity)
        gray[i]     = pressure[i] >= threshold
                      or (gray[i] and pressure[i] > recover)

    and node i's EFFECTIVE protocol period at tick t+1 is
    ``max(period[i], factor)`` while ``gray[i]`` — so retry storms can
    *cause* gray, gray attracts more retries (the SLO latency plane's
    duty-phase timeouts), and the backoff schedule is what must arrest
    the cascade.  Outside the window pressure and gray are pinned to
    zero (the feedback disarms and the cluster recovers its period).
    The update is exact int32 arithmetic, which is what makes the
    compiled scan and the host-loop oracle bit-identical
    (tests/test_overload.py).
    """

    start: int  # window start tick (inclusive)
    end: int  # window end tick (exclusive)
    capacity: int  # sends a node absorbs per tick without pressure
    threshold: int  # pressure at which the node degrades to gray
    recover: int  # hysteresis: gray clears only at pressure <= recover
    factor: int  # the degraded protocol period while gray


def overload_config(spec: ScenarioSpec) -> OverloadConfig | None:
    """The spec's (at most one) ``overload`` event as its static
    config, or None — mirrors ``link_rules``/``period_switches`` as
    the host-side single source of truth for both the compiler and
    the parity oracle."""
    for e in spec.events:
        if e.op == "overload":
            return OverloadConfig(
                start=e.at,
                end=e.until if e.until is not None else spec.ticks,
                capacity=int(e.capacity),
                threshold=int(e.threshold),
                recover=int(e.recover) if e.recover is not None else 0,
                factor=int(e.factor),
            )
    return None


def overload_update(
    cfg: OverloadConfig, in_window, pressure, gray, sends
):
    """One tick of the feedback-loop state update — shared arithmetic
    for the compiled scan (jnp arrays) and the host oracle (numpy):
    returns ``(pressure', gray')``.  Works elementwise on either array
    namespace because it is pure ``maximum``/compare/bool algebra."""
    np_like = jnp if isinstance(pressure, jax.Array) else np
    cnt = np_like.maximum(pressure + sends - cfg.capacity, 0)
    cnt = np_like.where(in_window, cnt, 0)
    new_gray = in_window & (
        (cnt >= cfg.threshold) | (gray & (cnt > cfg.recover))
    )
    return cnt, new_gray


def link_rules(spec: ScenarioSpec) -> list[LinkRule]:
    """The spec's link_loss/delay events as rules, in (at, spec-order)
    — the deterministic order both the compiler and the host plan use
    (rule order matters only for float reproducibility of the composed
    drop product, so it must simply be THE SAME everywhere)."""
    rules = []
    for e in sorted(
        (e for e in spec.events if e.op in ("link_loss", "delay")),
        key=lambda e: e.at,
    ):
        until = e.until if e.until is not None else spec.ticks
        rules.append(
            LinkRule(
                start=e.at,
                end=until,
                src=tuple(e.src),
                dst=tuple(e.dst),
                p=float(e.p) if e.p is not None else 0.0,
                delay=int(e.delay or 0) if e.op == "delay" else 0,
                jitter=int(e.jitter or 0) if e.op == "delay" else 0,
            )
        )
    return rules


def delay_depth(spec: ScenarioSpec) -> int:
    """Static ring-buffer depth for the in-flight claim buffer: the
    largest possible per-message latency plus one (slot ``t % D`` is
    maturing while ``t + d`` lands ahead of it), or 0 without delay.

    Overlapping rules combine as ``max_k(delay) + U{0..max_k(jitter)}``
    (``swim_sim._link_delay_bounds`` takes the maxima SEPARATELY), so
    the bound must too — a per-rule ``max(d + j)`` would under-size the
    buffer when one rule contributes the base and another the jitter,
    wrapping the ring and delivering early."""
    rules = [r for r in link_rules(spec) if r.delay + r.jitter]
    if not rules:
        return 0
    return max(r.delay for r in rules) + max(r.jitter for r in rules) + 1


def period_switches(spec: ScenarioSpec, n: int) -> list[tuple[int, np.ndarray]]:
    """``(tick, int32[N] period row)`` at every tick the per-node
    period vector changes, in tick order (gray windows set the factor
    at ``at`` and restore 1 at ``until``; validate rejects overlapping
    windows per node, so the fold is order-free)."""
    edits: list[tuple[int, tuple[int, ...], int]] = []
    for e in spec.events:
        if e.op != "gray":
            continue
        until = e.until if e.until is not None else spec.ticks
        edits.append((e.at, e.target_nodes(), int(e.factor)))
        if until < spec.ticks:
            edits.append((until, e.target_nodes(), 1))
    if not edits:
        return []
    period = np.ones(n, dtype=np.int32)
    out = []
    # same-tick restores apply BEFORE sets: adjacent windows on one
    # node ([10, 20) factor 4, then [20, 30) factor 6) share tick 20 as
    # one window's end and the next's start — the new factor must win
    # regardless of the order the spec lists the events (everywhere
    # else in the engine, event-list order is immaterial)
    edits.sort(key=lambda e: e[2] != 1)
    for tick in sorted({t for t, _, _ in edits}):
        for t, nodes, val in edits:
            if t == tick:
                period[list(nodes)] = val
        out.append((tick, period.copy()))
    return out


def fault_marker_ticks(spec: ScenarioSpec) -> list[int]:
    """Every tick at which the network/timing configuration changes —
    link-rule window edges and period switches.  These become key-
    schedule segment boundaries (``compile.expand_events`` emits a
    ``faultcfg`` op per tick) so the host loop can re-apply the
    configuration between ``tick()`` segments."""
    ticks: set[int] = set()
    for r in link_rules(spec):
        ticks.add(r.start)
        if r.end < spec.ticks:
            ticks.add(r.end)
    for e in spec.events:
        if e.op == "gray":
            ticks.add(e.at)
            until = e.until if e.until is not None else spec.ticks
            if until < spec.ticks:
                ticks.add(until)
    return sorted(t for t in ticks if 0 <= t < spec.ticks)


def rules_arrays(
    rules: list[LinkRule], n: int, at: int | None = None
) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """The rule table as ``(src[K, N], dst[K, N], p[K], d[K], j[K])``
    numpy arrays.  ``at`` masks p/d/j of rules inactive at that tick to
    zero — the host-loop form: the step then computes byte-identical
    drop products to the scan's in-program activity mask (inactive
    rules contribute an exact 1.0 factor either way)."""
    k = len(rules)
    src = np.zeros((k, n), dtype=bool)
    dst = np.zeros((k, n), dtype=bool)
    p = np.zeros(k, dtype=np.float32)
    d = np.zeros(k, dtype=np.int32)
    j = np.zeros(k, dtype=np.int32)
    for i, r in enumerate(rules):
        src[i, list(r.src)] = True
        dst[i, list(r.dst)] = True
        active = at is None or r.start <= at < r.end
        if active:
            p[i] = r.p
            d[i] = r.delay
            j[i] = r.jitter
    return src, dst, p, d, j


def compile_faults(spec: ScenarioSpec, n: int) -> FaultTensors | None:
    """Lower the spec's failure-model events to device tensors, or
    None when the spec has none (the compiled program is then exactly
    the pre-failure-model one)."""
    rules = link_rules(spec)
    switches = period_switches(spec, n)
    if not rules and not switches:
        return None
    src, dst, p, d, j = rules_arrays(rules, n)
    has_delay = bool((d + j).any())
    return FaultTensors(
        lr_src=jnp.asarray(src),
        lr_dst=jnp.asarray(dst),
        lr_p=jnp.asarray(p),
        lr_start=jnp.asarray(
            np.array([r.start for r in rules], dtype=np.int32)
        ),
        lr_end=jnp.asarray(np.array([r.end for r in rules], dtype=np.int32)),
        lr_d=jnp.asarray(d) if has_delay else None,
        lr_j=jnp.asarray(j) if has_delay else None,
        pe_tick=jnp.asarray(
            np.array([t for t, _ in switches], dtype=np.int32)
        ),
        pe_row=jnp.asarray(_narrow_period_rows(switches, n)),
    )


def _narrow_period_rows(switches, n: int) -> np.ndarray:
    """Period-switch rows in the scan carry's int16 form (periods are
    small tick multipliers; the range check is host-side and loud —
    runner.prepare_faults applies the same narrowing to the standing
    row)."""
    rows = (
        np.stack([row for _, row in switches])
        if switches
        else np.zeros((0, n), np.int32)
    )
    if rows.size and rows.max() > np.iinfo(np.int16).max:
        raise ValueError(
            f"set_period row value {rows.max()} exceeds the int16 "
            "carry range"
        )
    return rows.astype(np.int16)


class HostPlan:
    """The host-loop side of the failure model: what ``run_host_loop``
    applies at each ``faultcfg`` boundary so that ``cluster.tick()``
    steps see the same per-tick network/timing configuration the
    compiled scan computes in-program."""

    def __init__(self, spec: ScenarioSpec, n: int):
        self.spec = spec
        self.n = n
        self.rules = link_rules(spec)
        self.switches = period_switches(spec, n)
        self.delay_depth = delay_depth(spec)
        self.has_delay = self.delay_depth > 0

    def prepare(self, cluster: Any) -> None:
        """Pre-run setup: install the in-flight buffer when the spec
        delays messages (it must exist from tick 0 on BOTH sides — its
        presence widens the per-tick key split)."""
        if self.has_delay:
            cluster.enable_delay(self.delay_depth)

    def apply(self, cluster: Any, at: int) -> None:
        """Install the configuration in force at spec tick ``at``."""
        if self.rules:
            src, dst, p, d, j = rules_arrays(self.rules, self.n, at=at)
            cluster.set_link_rules(
                src, dst, p,
                d=d if self.has_delay else None,
                j=j if self.has_delay else None,
            )
        if self.switches:
            row = np.ones(self.n, dtype=np.int32)
            for t, r in self.switches:
                if t <= at:
                    row = r
            cluster.set_period(row)

"""Streaming chunked-scan runner: pipelined dispatch/drain, O(segment)
traces, restartable soaks.

The one-dispatch scenario scan (``runner.run_compiled``) made a chaos
experiment cheap per dispatch, but the runner AROUND it became the
bottleneck for long horizons: it blocks on every dispatch, materializes
the whole ``[ticks]`` telemetry stack on host (a 1M-tick soak cannot
fit), and a killed multi-hour run restarts from zero.  This module
restructures that runner around S-tick segments:

* **One compile serves the whole soak.**  A T-tick run becomes
  ``ceil(T / S)`` dispatches of ONE compiled executable: the segment
  scan is the same ``runner._scenario_scan`` program with a traced
  ``tick0`` offset, so every segment shares the [S]-shaped signature
  (the ragged tail, when ``T % S != 0``, is its own shape) — the
  dispatch ledger shows exactly one cold row per (backend, segment
  shape).  The carry (state / net bits / adjacency) is **donated**
  straight back into the next segment: no host round trip, no
  per-segment re-allocation.

* **Bit-identical to the unsegmented run.**  The PRNG key schedule is
  derived ONCE for the full horizon by the same
  ``compile.key_schedule`` the one-dispatch run uses, and segments just
  slice it — so a streamed run of ANY segment size reproduces the
  unsegmented ``run_scenario`` trajectory and trace bit-for-bit
  (tests/test_stream.py pins it).  Segmentation is an execution
  strategy, not a semantic change.

* **Pipelined dispatch/drain.**  Segment k+1 is dispatched (jax's
  async dispatch) BEFORE segment k's telemetry is pulled to host, so
  device compute and host-side trace conversion / store writes /
  stats bridging run concurrently.  Per-segment ledger rows (shared
  ``run_id``) record ``drain_s`` and ``drain_overlap_s`` — the
  ``obs-ledger`` summarizer reports pipelining efficiency per soak,
  and ``benchmarks/bench_stream.py`` measures the win over the
  blocking loop (``pipeline=False``).

* **Stream, don't hoard.**  Each segment's telemetry lands as an
  S-tick ``Trace`` slab: appended to a ``SegmentStore`` (one ``.npz``
  per segment + a JSONL manifest — appendable and crash-tolerant) and
  replayed incrementally through the Trace→stats bridge.  Host-resident
  trace memory is O(segment); the store's loader lazily iterates or
  reassembles the full series on demand.

* **Checkpoint every segment.**  ``checkpoint.py`` v5 records the
  stream cursor — spec, segment size, ticks done, the PRNG key the
  schedule derives from, and the traffic workload — next to the host
  snapshot of the carry, so a SIGKILL'd soak resumes from its last
  completed segment and produces bit-identical final checksums and
  traces to the uninterrupted run (``resume``; the CI
  ``soak-resume-smoke`` job kills a live run to prove it).

Entry points: ``SimCluster.run_scenario(spec, segment_ticks=S, ...)``,
``SimCluster.run_sweep(spec, R, segment_ticks=S, ...)``,
``tick-cluster --scenario F --segment-ticks S [--checkpoint C
--checkpoint-every K | --resume C]``.
"""

from __future__ import annotations

import json
import os
import time
import uuid
from typing import Any, Iterator

import jax
import jax.numpy as jnp
import numpy as np

from ringpop_tpu.models.swim_sim import NetState
from ringpop_tpu.obs import bridge as obs_bridge
from ringpop_tpu.obs.ledger import default_ledger
from ringpop_tpu.policies import core as pol
from ringpop_tpu.scenarios import compile as scompile
from ringpop_tpu.scenarios import runner as srunner
from ringpop_tpu.scenarios import sweep as ssweep
from ringpop_tpu.scenarios.spec import ScenarioSpec
from ringpop_tpu.scenarios.trace import Trace

STORE_VERSION = 1
CURSOR_VERSION = 1


class StreamInterrupted(RuntimeError):
    """Raised by the ``interrupt_after`` test/smoke hook: the run stops
    exactly as a SIGKILL at that segment boundary would — the
    checkpoint and segment store are left on disk as a crash leaves
    them, and the cluster object is NOT reusable (its device buffers
    were donated into the abandoned in-flight segment).  Resume from
    the checkpoint."""


def segment_bounds(ticks: int, segment_ticks: int) -> list[tuple[int, int]]:
    """[(a, b)) tick ranges of each segment; the tail may be ragged."""
    if segment_ticks < 1:
        raise ValueError(f"segment_ticks must be >= 1 (got {segment_ticks})")
    return [
        (a, min(a + segment_ticks, ticks))
        for a in range(0, ticks, segment_ticks)
    ]


# ---------------------------------------------------------------------------
# SegmentStore: the appendable on-disk slab sequence
# ---------------------------------------------------------------------------


class SegmentStore:
    """Appendable on-disk store of per-segment telemetry slabs.

    Layout (one directory per streamed run)::

        store.json       # run meta: kind, n, backend, spec, run_id, ...
        manifest.jsonl   # one line per slab: {segment, tick0, ticks, file}
        seg-00000.npz    # Trace/SweepTrace slab (atomic .tmp+rename)

    Each slab write is atomic and the manifest is append-only, so a
    crash mid-run leaves a readable prefix; ``truncate`` drops slabs
    past a resume cursor (a crash between a slab append and its
    checkpoint leaves one extra slab, which the resumed run rewrites).
    ``iter_traces`` holds ONE slab in memory at a time — the
    O(segment) reader a million-tick soak is analyzed through;
    ``assemble`` is the explicit opt-in to a full [T] series.
    """

    MANIFEST = "manifest.jsonl"
    METAFILE = "store.json"

    def __init__(self, path: str, meta: dict[str, Any],
                 rows: list[dict[str, Any]]):
        self.path = path
        self.meta = meta
        self.rows = list(rows)

    # -- lifecycle ----------------------------------------------------------

    @classmethod
    def create(cls, path: str, meta: dict[str, Any]) -> "SegmentStore":
        os.makedirs(path, exist_ok=True)
        meta_path = os.path.join(path, cls.METAFILE)
        if os.path.exists(meta_path):
            with open(meta_path) as f:
                old = json.load(f)
            if old.get("run_id") != meta.get("run_id"):
                raise ValueError(
                    f"segment store {path} already holds run "
                    f"{old.get('run_id')!r}; refusing to mix runs — pick a "
                    f"fresh directory or resume from that run's checkpoint"
                )
        meta = {"version": STORE_VERSION, **meta}
        tmp = meta_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(meta, f, indent=2)
        os.replace(tmp, meta_path)
        # fresh manifest: a create() is tick 0 of a new run
        with open(os.path.join(path, cls.MANIFEST), "w"):
            pass
        return cls(path, meta, [])

    @classmethod
    def open(cls, path: str) -> "SegmentStore":
        with open(os.path.join(path, cls.METAFILE)) as f:
            meta = json.load(f)
        if meta.get("version") != STORE_VERSION:
            raise ValueError(
                f"unsupported segment store version {meta.get('version')}"
            )
        rows = []
        manifest = os.path.join(path, cls.MANIFEST)
        if os.path.exists(manifest):
            with open(manifest) as f:
                lines = [ln.strip() for ln in f if ln.strip()]
            for i, line in enumerate(lines):
                try:
                    rows.append(json.loads(line))
                except json.JSONDecodeError:
                    if i == len(lines) - 1:
                        # a power loss mid-append can tear the final
                        # line; its slab was never checkpointed, so
                        # resume would truncate it anyway — drop it
                        # and keep the readable prefix
                        break
                    raise
        rows.sort(key=lambda r: r["tick0"])
        return cls(path, meta, rows)

    # -- facts --------------------------------------------------------------

    @property
    def kind(self) -> str:
        return self.meta.get("kind", "trace")

    @property
    def segments(self) -> int:
        return len(self.rows)

    @property
    def ticks_stored(self) -> int:
        return sum(int(r["ticks"]) for r in self.rows)

    # -- writing ------------------------------------------------------------

    def append(self, slab: Any, *, segment: int, tick0: int) -> dict[str, Any]:
        """Write one slab (atomic) and its manifest line (append)."""
        fname = f"seg-{segment:05d}.npz"
        slab.save(os.path.join(self.path, fname))
        row = {
            "segment": int(segment),
            "tick0": int(tick0),
            "ticks": int(slab.ticks),
            "file": fname,
        }
        with open(os.path.join(self.path, self.MANIFEST), "a") as f:
            f.write(json.dumps(row) + "\n")
        self.rows.append(row)
        return row

    def truncate(self, ticks_done: int) -> None:
        """Drop slabs extending past ``ticks_done`` (the checkpoint
        cursor a resume continues from): a crash between a slab append
        and its checkpoint write leaves one uncommitted slab, which the
        resumed run recomputes and rewrites."""
        keep = [r for r in self.rows if r["tick0"] + r["ticks"] <= ticks_done]
        if len(keep) == len(self.rows):
            return
        manifest = os.path.join(self.path, self.MANIFEST)
        tmp = manifest + ".tmp"
        with open(tmp, "w") as f:
            for row in keep:
                f.write(json.dumps(row) + "\n")
        os.replace(tmp, manifest)
        self.rows = keep

    # -- reading ------------------------------------------------------------

    def load_segment(self, i: int) -> Any:
        row = self.rows[i]
        path = os.path.join(self.path, row["file"])
        if self.kind == "sweep":
            return ssweep.SweepTrace.load(path)
        return Trace.load(path)

    def iter_traces(self) -> Iterator[Any]:
        """Lazy slab iterator: one segment resident at a time — the
        O(segment)-memory way to scan a whole soak's telemetry."""
        for i in range(len(self.rows)):
            yield self.load_segment(i)

    def assemble(self) -> Any:
        """The full concatenated series (explicitly O(total ticks))."""
        if self.kind == "sweep":
            return ssweep.SweepTrace.concat_ticks(
                self.iter_traces(), spec=self.meta.get("spec")
            ).validate()
        return Trace.concat(
            self.iter_traces(), spec=self.meta.get("spec")
        ).validate()


# ---------------------------------------------------------------------------
# the streamed scenario run
# ---------------------------------------------------------------------------


def _schedule_from_start_key(
    start_key: Any, compiled: scompile.CompiledScenario
) -> jax.Array:
    """Re-derive the full key schedule from the cluster key as it was
    at run start — the identical chained-split sequence
    ``SimCluster._split`` produced, so a resumed soak replays the very
    keys the killed run would have used (threefry splits are a pure
    function of the key)."""
    kstate = {"key": jnp.asarray(np.asarray(start_key, dtype=np.uint32))}

    def split() -> jax.Array:
        kstate["key"], sub = jax.random.split(kstate["key"])
        return sub

    return scompile.key_schedule(split, compiled)


def _to_host(tree: Any) -> Any:
    return jax.tree_util.tree_map(np.asarray, tree)


def run_streamed(
    cluster: Any,
    spec: Any,
    *,
    segment_ticks: int,
    traffic: Any | None = None,
    store: str | None = None,
    checkpoint_path: str | None = None,
    checkpoint_every: int = 1,
    assemble: bool = True,
    pipeline: bool = True,
    interrupt_after: int | None = None,
    policy: Any | None = None,
) -> Any:
    """Run a scenario as pipelined S-tick segment dispatches.

    Bit-identical to ``cluster.run_scenario(spec)`` — same key
    schedule, same trajectory, same trace — but the telemetry streams
    out per segment and the run checkpoints / resumes at segment
    granularity.  Returns the assembled ``Trace`` (and performs
    ``run_scenario``'s bookkeeping: ``cluster.traces`` /
    ``metrics_log`` / stats bridging), or the ``SegmentStore`` when
    ``assemble=False`` (host trace memory stays O(segment); requires a
    store).

    ``checkpoint_path`` writes a v5 checkpoint every
    ``checkpoint_every`` completed segments (and at completion); the
    segment slabs then also persist (default store:
    ``checkpoint_path + ".segments"``) so ``resume`` can finish the
    trace.  ``pipeline=False`` is the blocking comparison arm
    (``benchmarks/bench_stream.py``): drain fully before the next
    dispatch.  ``interrupt_after=k`` simulates a SIGKILL right after
    the k-th checkpoint is written (tests + the CI smoke).
    """
    if isinstance(spec, str):
        spec = ScenarioSpec.load(spec)
    elif isinstance(spec, dict):
        spec = ScenarioSpec.from_dict(spec)
    spec.validate(cluster.n)
    if checkpoint_every < 1:
        raise ValueError(f"checkpoint_every must be >= 1 (got {checkpoint_every})")
    if traffic is not None:
        traffic = cluster.compile_traffic(traffic)
    compiled = scompile.compile_spec(
        spec, cluster.n, base_loss=cluster.params.loss
    )
    # static rejections + the ONE per-run host sync of the adjacency
    # check (satellite of the streaming rework: never per segment)
    params_pre = (
        cluster.dparams if cluster.backend == "delta" else cluster.params
    )
    adj = srunner.precheck(cluster.state, cluster.net, compiled, params_pre)
    srunner.precheck_overload(compiled, traffic, cluster.net)
    if policy is not None and traffic is not None:
        policy = pol.compile_policy(policy, n=cluster.n, m=traffic.static.m)
    srunner.precheck_policy(policy, traffic, cluster.net)
    srunner.precheck_prov(compiled, cluster.net, params_pre)
    if checkpoint_path and store is None:
        # resume must be able to reassemble the full trace, so a
        # checkpointed run always persists its slabs
        store = checkpoint_path + ".segments"
    if not assemble and store is None:
        raise ValueError(
            "assemble=False discards nothing only with a segment store "
            "(pass store=... or checkpoint_path=...)"
        )
    spec_dict = spec.to_dict()
    if traffic is not None:
        spec_dict["traffic"] = traffic.spec.to_dict()
    # everything that can raise must precede the key draw: a failed
    # call may not advance cluster.key (runner.precheck's invariant),
    # or the next run on this cluster would silently desynchronize
    # from a cluster that never hit the error
    segment_bounds(compiled.ticks, int(segment_ticks))
    start_key = np.asarray(cluster.key).copy()
    cursor = {
        "version": CURSOR_VERSION,
        "run_id": uuid.uuid4().hex[:12],
        "spec": spec.to_dict(),
        "traffic": traffic.spec.to_dict() if traffic is not None else None,
        "policy": pol.to_dict(policy) if policy is not None else None,
        "segment_ticks": int(segment_ticks),
        "ticks": compiled.ticks,
        "ticks_done": 0,
        "start_key": [int(x) for x in np.asarray(start_key).ravel()],
        "start_tick": int(cluster.state.tick),
        "base_loss": float(cluster.params.loss),
        "store": store,
        "checkpoint_every": int(checkpoint_every),
        "prev_live": None,
        "backend": cluster.backend,
    }
    store_obj = None
    if store is not None:
        store_obj = SegmentStore.create(
            store,
            {
                "kind": "trace",
                "run_id": cursor["run_id"],
                "n": cluster.n,
                "backend": cluster.backend,
                "segment_ticks": int(segment_ticks),
                "ticks": compiled.ticks,
                "start_tick": cursor["start_tick"],
                "spec": spec_dict,
            },
        )
    keys = scompile.key_schedule(cluster._split, compiled)
    return _drive(
        cluster,
        compiled,
        keys,
        traffic,
        adj,
        cursor,
        store_obj,
        spec_dict,
        checkpoint_path=checkpoint_path,
        assemble=assemble,
        pipeline=pipeline,
        interrupt_after=interrupt_after,
        policy=policy,
    )


def resume(
    checkpoint_path: str,
    *,
    device: Any | None = None,
    assemble: bool = True,
    pipeline: bool = True,
    interrupt_after: int | None = None,
) -> tuple[Any, Any]:
    """Continue a killed streamed soak from its last checkpoint.

    Loads the v5 checkpoint, re-derives the key schedule from the
    recorded start key (so the remaining segments consume the exact
    keys the uninterrupted run would have), truncates the segment
    store to the checkpoint cursor, and finishes the run.  Returns
    ``(cluster, result)`` where ``result`` is the assembled full
    ``Trace`` (bit-identical to the uninterrupted run's) or the
    ``SegmentStore`` with ``assemble=False``.  A checkpoint whose
    cursor is already complete just reopens the store."""
    from ringpop_tpu import checkpoint as ckpt

    cluster = ckpt.load(checkpoint_path, device=device)
    cur = cluster.stream_cursor
    if cur is None:
        raise ValueError(
            f"{checkpoint_path} has no stream cursor (not a streamed-run "
            "checkpoint; plain checkpoints resume via checkpoint.load)"
        )
    if cur.get("store") is None:
        raise ValueError("stream cursor has no segment store to resume into")
    store_obj = SegmentStore.open(cur["store"])
    spec = ScenarioSpec.from_dict(cur["spec"])
    if cur["ticks_done"] >= cur["ticks"]:
        # the soak already finished; nothing to recompute
        return cluster, (store_obj.assemble() if assemble else store_obj)
    store_obj.truncate(cur["ticks_done"])
    traffic = (
        cluster.compile_traffic(cur["traffic"])
        if cur.get("traffic") is not None
        else None
    )
    compiled = scompile.compile_spec(
        spec, cluster.n, base_loss=cur["base_loss"]
    )
    adj = srunner.precheck(
        cluster.state, cluster.net, compiled,
        cluster.dparams if cluster.backend == "delta" else cluster.params,
        # the checkpointed net carries this spec's OWN mirrored link
        # rules / mid-window period row — standing-config rejection is
        # for fresh runs
        standing_ok=True,
    )
    # same opt-out for the overload feedback carry: the checkpointed
    # net's ov_cnt/ov_gray ARE this run's mid-window state, and
    # prepare_faults resumes the pressure from them
    srunner.precheck_overload(compiled, traffic, cluster.net, standing_ok=True)
    # ... and for the policy carry: the cursor's exact knob set (never
    # rederived from scale) resumes the net's po_* mid-window state
    policy = (
        pol.from_dict(cur["policy"])
        if cur.get("policy") is not None
        else None
    )
    srunner.precheck_policy(policy, traffic, cluster.net, standing_ok=True)
    # ... and for the provenance carry: the checkpointed net's pv_*
    # planes ARE this run's mid-flight wavefronts, resumed verbatim
    srunner.precheck_prov(
        compiled, cluster.net,
        cluster.dparams if cluster.backend == "delta" else cluster.params,
        standing_ok=True,
    )
    # cluster.key already holds the post-schedule key (the schedule was
    # fully drawn before the first segment); derive the schedule again
    # from the recorded start key without touching it
    keys = _schedule_from_start_key(cur["start_key"], compiled)
    spec_dict = dict(store_obj.meta.get("spec") or spec.to_dict())
    result = _drive(
        cluster,
        compiled,
        keys,
        traffic,
        adj,
        dict(cur),
        store_obj,
        spec_dict,
        checkpoint_path=checkpoint_path,
        assemble=assemble,
        pipeline=pipeline,
        interrupt_after=interrupt_after,
        policy=policy,
    )
    return cluster, result


def _drive(
    cluster: Any,
    compiled: scompile.CompiledScenario,
    keys: jax.Array,
    traffic: Any | None,
    adj: jax.Array,
    cursor: dict[str, Any],
    store_obj: SegmentStore | None,
    spec_dict: dict[str, Any],
    *,
    checkpoint_path: str | None,
    assemble: bool,
    pipeline: bool,
    interrupt_after: int | None,
    policy: Any | None = None,
) -> Any:
    """The segment loop shared by fresh runs and resumes."""
    S = int(cursor["segment_ticks"])
    T = compiled.ticks
    bounds = segment_bounds(T, S)
    if cursor["ticks_done"] % S not in (0,) and cursor["ticks_done"] != T:
        raise ValueError(
            f"cursor ticks_done={cursor['ticks_done']} is not a segment "
            f"boundary of S={S}"
        )
    start_seg = cursor["ticks_done"] // S
    led = default_ledger()
    is_delta = cluster.backend == "delta"
    params = cluster.dparams if is_delta else cluster.params
    traffic = srunner.overload_traffic(traffic, compiled)
    traffic = srunner.policy_traffic(traffic, policy)
    tr_tensors = traffic.tensors if traffic is not None else None
    static_traffic = traffic.static if traffic is not None else None
    sink = cluster.stats_sink
    f_state, period0, ov0 = srunner.prepare_faults(
        cluster.state, cluster.net, compiled, params
    )
    po0 = srunner.prepare_policy(
        policy, cluster.net, cluster.n,
        static_traffic.max_retries if static_traffic is not None else 0,
    )
    knobs = pol.knob_arrays(policy) if policy is not None else None
    pv0, pv_at, pv_node = srunner.prepare_prov(compiled, cluster.net, params)
    carry = (f_state, cluster.net.up, cluster.net.responsive, adj, period0,
             ov0, po0, pv0)
    pending: tuple | None = None
    slabs: list[Trace] = []  # only populated when there is no store
    state = {"prev_live": cursor.get("prev_live"), "last_slab": None,
             "ckpts": 0}

    def _launch(seg: int, a: int, b: int, carry: tuple):
        meta = {
            "backend": cluster.backend,
            "n": cluster.n,
            "ticks": b - a,
            "replicas": 1,
            "run_id": cursor["run_id"],
            "segment": seg,
            "tick0": a,
            "segment_ticks": S,
            "total_ticks": T,
        }
        if static_traffic is not None:
            meta["traffic_m"] = static_traffic.m
        if policy is not None:
            meta["policy"] = policy.name
        args = (
            *carry[:5],
            compiled.ev_tick,
            compiled.ev_kind,
            compiled.ev_node,
            compiled.p_tick,
            compiled.p_gid,
            compiled.loss[a:b],
            keys[a:b],
            tr_tensors,
            jnp.int32(a),
            compiled.faults,
            carry[5],  # the overload feedback carry (or None)
            carry[6],  # the remediation policy carry (or None)
            knobs,
            None,  # sw_knobs: param_knobs is not wired streamed
            carry[7],  # the provenance carry (ProvCarry or None)
            pv_at,
            pv_node,
        )
        statics = dict(
            params=params,
            has_revive=compiled.has_revive,
            traffic=static_traffic,
            overload=compiled.overload,
            policy=policy.config if policy is not None else None,
            prov=compiled.trace_rumors or None,
        )
        srunner._dispatches += 1
        t0 = time.perf_counter()
        if led.enabled:
            out, row = led.launch(
                "run_scenario", srunner._scenario_scan, *args,
                _meta=meta, **statics,
            )
        else:
            out, row = srunner._scenario_scan(*args, **statics), None
        if row is not None:
            row["dispatch_s"] = round(time.perf_counter() - t0, 6)
        return out, row

    def _drain(p: tuple, *, overlapped: bool) -> None:
        seg, a, b, ys, row = p
        t0 = time.perf_counter()
        stacks = {k: np.asarray(v) for k, v in ys.items()}
        slab = Trace(
            metrics={
                k: v
                for k, v in stacks.items()
                if k not in ("converged", "live", "loss") and v.ndim == 1
            },
            planes={k: v for k, v in stacks.items() if v.ndim == 2},
            converged=stacks["converged"],
            live=stacks["live"],
            loss=stacks["loss"],
            n=cluster.n,
            backend=cluster.backend,
            start_tick=cursor["start_tick"] + a,
            spec=None,
        )
        if store_obj is not None:
            store_obj.append(slab, segment=seg, tick0=a)
        else:
            slabs.append(slab)
        if sink is not None:
            obs_bridge.replay_trace(
                slab,
                sink.emitter,
                prefix=sink.prefix,
                checksum=None,
                declare_namespace=(seg == start_seg),
                prev_live=state["prev_live"],
                checksum_pending=True,
            )
        state["prev_live"] = int(stacks["live"][-1])
        state["last_slab"] = slab
        drain_s = time.perf_counter() - t0
        if row is not None:
            row["drain_s"] = round(drain_s, 6)
            row["drain_overlap_s"] = round(drain_s if overlapped else 0.0, 6)
            led.record(row)

    def _write_ckpt(snap_state: Any, snap_net: NetState,
                    ticks_done: int) -> None:
        from ringpop_tpu import checkpoint as ckpt

        ckpt.save(
            cluster,
            checkpoint_path,
            stream=dict(
                cursor, ticks_done=int(ticks_done),
                prev_live=state["prev_live"],
            ),
            state=snap_state,
            net=snap_net,
        )

    for seg in range(start_seg, len(bounds)):
        a, b = bounds[seg]
        due_prev = (
            checkpoint_path is not None
            and seg > start_seg
            and (seg % cursor["checkpoint_every"] == 0)
        )
        snap = None
        if due_prev:
            # snapshot BEFORE the carry is donated onward (blocks until
            # the previous segment's compute lands — the one pipeline
            # bubble durability costs; drain + checkpoint write below
            # still overlap this segment's compute)
            ov_snap = carry[5]
            po_snap = carry[6]
            pv_snap = carry[7]
            po_kw = {}
            if po_snap is not None:
                po_kw = dict(
                    po_press=np.asarray(po_snap[0]),
                    po_shed=np.asarray(po_snap[1]),
                    po_quar=np.asarray(po_snap[2]),
                    po_sends_w=np.asarray(po_snap[3]),
                    po_deliv_w=np.asarray(po_snap[4]),
                    po_retry_cap=np.asarray(po_snap[5]),
                )
            if pv_snap is not None:
                # knows stays packed in the checkpoint too — it is
                # uint32 words at rest everywhere (ops/bitpack)
                po_kw.update(
                    pv_slot=np.asarray(pv_snap.slot),
                    pv_tickv=np.asarray(pv_snap.tickv),
                    pv_wits=np.asarray(pv_snap.wits),
                    pv_first=np.asarray(pv_snap.first),
                    pv_parent=np.asarray(pv_snap.parent),
                    pv_knows=np.asarray(pv_snap.knows),
                )
            snap = (
                _to_host(carry[0]),
                NetState(
                    up=np.asarray(carry[1]),
                    responsive=np.asarray(carry[2]),
                    adj=np.asarray(carry[3]),
                    period=(
                        np.asarray(carry[4]) if carry[4] is not None else None
                    ),
                    ov_cnt=(
                        np.asarray(ov_snap[0]) if ov_snap is not None else None
                    ),
                    ov_gray=(
                        np.asarray(ov_snap[1]) if ov_snap is not None else None
                    ),
                    **po_kw,
                ),
            )
        out, row = _launch(seg, a, b, carry)
        carry, ys = out[:8], out[8]
        if pending is not None:
            _drain(pending, overlapped=True)
            pending = None
        if due_prev:
            _write_ckpt(snap[0], snap[1], bounds[seg - 1][1])
            state["ckpts"] += 1
            if interrupt_after is not None and state["ckpts"] >= interrupt_after:
                raise StreamInterrupted(
                    f"simulated kill after checkpoint {state['ckpts']} "
                    f"(ticks_done={bounds[seg - 1][1]})"
                )
        pending = (seg, a, b, ys, row)
        if not pipeline:
            # the unpipelined comparison arm: blocking here IS the
            # mode's contract (bench_stream's baseline)
            jax.block_until_ready(carry)  # audit: allow=RPL001
            _drain(pending, overlapped=False)
            pending = None
    if pending is not None:
        _drain(pending, overlapped=False)

    # the run is whole again: hand the final carry back to the cluster
    f_state, f_up, f_resp, f_adj, f_per, f_ov, f_po, f_pv = carry
    cluster.state = f_state
    cluster.net = srunner.final_net(
        f_up, f_resp, f_adj, f_per, compiled, ov=f_ov, po=f_po, pv=f_pv
    )
    cluster.set_loss(float(compiled.loss[-1]))  # host mirror (run_scenario)
    if checkpoint_path is not None:
        # final checkpoint: cursor complete, final state — written
        # BEFORE the assembled trace is attached so a soak's checkpoint
        # stays O(state), not O(ticks); the trace lives in the store
        _write_ckpt(_to_host(cluster.state), _to_host(cluster.net), T)

    result: Any
    if assemble:
        trace = (
            store_obj.assemble()
            if store_obj is not None
            else Trace.concat(slabs, spec=spec_dict)
        ).validate()
        cluster.traces.append(trace)
        entry = {k: int(v[-1]) for k, v in trace.metrics.items()}
        result = trace
    else:
        last = state["last_slab"]
        entry = {k: int(v[-1]) for k, v in last.metrics.items()}
        result = store_obj
    entry["ticks"] = T
    cluster.metrics_log.append(entry)
    if sink is not None:
        # the per-slab replays already streamed the series; close with
        # the post-run membership checksum gauge like run_scenario does
        live = cluster.live_indices()
        if live.size:
            first = int(live[0])
            checksum = cluster.checksums(indices=[first])[
                cluster.book.addresses[first]
            ]
            sink.gauge("checksum", int(checksum))
        else:
            # every node dead: keep the namespace total (the slab
            # replays deferred the sentinel via checksum_pending)
            sink.gauge("checksum", 0)
    return result


# ---------------------------------------------------------------------------
# the streamed sweep (R replicas x S-tick segments)
# ---------------------------------------------------------------------------


def run_sweep_streamed(
    cluster: Any,
    spec: Any,
    replicas: int,
    *,
    segment_ticks: int,
    loss_scales: Any | None = None,
    kill_jitter: Any | None = None,
    flap_jitter: Any | None = None,
    traffic: Any | None = None,
    store: str | None = None,
    assemble: bool = True,
    pipeline: bool = True,
    shard: bool = False,
    policy: Any | None = None,
    policy_axes: dict[str, Any] | None = None,
) -> Any:
    """R replicas of a scenario, streamed segment by segment.

    The [R, S] telemetry slabs flow out per segment (SegmentStore kind
    ``sweep``), so host-resident sweep telemetry is O(R x segment)
    instead of O(R x ticks) — and every replica stays bit-identical to
    the whole-horizon ``run_sweep`` (same replica keys, same vmapped
    scan body, tick0-offset segments slicing the same schedules).
    Like ``run_sweep``, the cluster does not advance (only its key
    moves); sweeps do not checkpoint (re-run them — they are
    measurement fan-outs, not trajectories).  ``shard=True`` splits
    the replica axis across the local devices exactly like the
    unstreamed sweep — the sharded carry stays device-resident across
    segments, so a streamed sharded sweep is bit-identical to the
    unsegmented sharded (and unsharded) run."""
    if isinstance(spec, str):
        spec = ScenarioSpec.load(spec)
    elif isinstance(spec, dict):
        spec = ScenarioSpec.from_dict(spec)
    spec.validate(cluster.n)
    if not assemble and store is None:
        raise ValueError(
            "assemble=False discards nothing only with a segment store"
        )
    if traffic is not None:
        traffic = cluster.compile_traffic(traffic)
    cs = ssweep.compile_sweep(
        spec,
        cluster.n,
        replicas=replicas,
        base_loss=cluster.params.loss,
        loss_scales=loss_scales,
        kill_jitter=kill_jitter,
        flap_jitter=flap_jitter,
    )
    params = cluster.dparams if cluster.backend == "delta" else cluster.params
    adj = srunner.precheck(cluster.state, cluster.net, cs.base, params)
    srunner.precheck_overload(cs.base, traffic, cluster.net)
    if policy is not None and traffic is not None:
        policy = pol.compile_policy(policy, n=cluster.n, m=traffic.static.m)
    srunner.precheck_policy(policy, traffic, cluster.net)
    srunner.precheck_prov(cs.base, cluster.net, params)
    traffic = srunner.overload_traffic(traffic, cs.base)
    traffic = srunner.policy_traffic(traffic, policy)
    tr_tensors = traffic.tensors if traffic is not None else None
    static_traffic = traffic.static if traffic is not None else None
    # raising validation/IO precedes the replica-key draws: a failed
    # call may not advance cluster.key (see run_streamed)
    if shard:
        ssweep.precheck_shard(replicas)
    S = int(segment_ticks)
    T = cs.base.ticks
    bounds = segment_bounds(T, S)
    run_id = uuid.uuid4().hex[:12]
    start_tick = int(cluster.state.tick)
    led = default_ledger()
    r = cs.replicas
    f_state, period0, ov0 = srunner.prepare_faults(
        cluster.state, cluster.net, cs.base, params
    )
    po0 = srunner.prepare_policy(
        policy, cluster.net, cluster.n,
        static_traffic.max_retries if static_traffic is not None else 0,
    )
    knobs = ssweep.policy_knob_axes(policy, policy_axes, r)
    pv0, pv_at, pv_node = srunner.prepare_prov(cs.base, cluster.net, params)
    carry = (
        ssweep._broadcast_replicas(f_state, r),
        ssweep._broadcast_replicas(cluster.net.up, r),
        ssweep._broadcast_replicas(cluster.net.responsive, r),
        ssweep._broadcast_replicas(adj, r),
        ssweep._broadcast_replicas(period0, r),
        ssweep._broadcast_replicas(ov0, r),
        ssweep._broadcast_replicas(po0, r),
        ssweep._broadcast_replicas(pv0, r),
    )
    sharding = ssweep._replica_sharding() if shard else None
    if sharding is not None:
        # the carry is device_put ONCE; segment outputs inherit the
        # sharding, so every later segment stays sharded for free
        carry = tuple(
            jax.tree_util.tree_map(lambda a: jax.device_put(a, sharding), t)
            for t in carry
        )
        knobs = jax.tree_util.tree_map(
            lambda a: jax.device_put(a, sharding), knobs
        )
    store_obj = None
    if store is not None:
        store_obj = SegmentStore.create(
            store,
            {
                "kind": "sweep",
                "run_id": run_id,
                "n": cluster.n,
                "backend": cluster.backend,
                "segment_ticks": S,
                "ticks": T,
                "start_tick": start_tick,
                "spec": spec.to_dict(),
            },
        )
    replica_keys = [cluster._split() for _ in range(replicas)]
    keys = ssweep.sweep_key_schedule(replica_keys, cs)
    if sharding is not None:
        keys = jax.device_put(keys, sharding)
    rkeys_np = np.stack([np.asarray(k) for k in replica_keys])
    slabs: list[Any] = []
    pending: tuple | None = None

    def _launch(seg: int, a: int, b: int, carry: tuple):
        meta = {
            "backend": cluster.backend,
            "n": cs.base.n,
            "ticks": b - a,
            "replicas": r,
            "run_id": run_id,
            "segment": seg,
            "tick0": a,
            "segment_ticks": S,
            "total_ticks": T,
        }
        if policy is not None:
            meta["policy"] = policy.name
        args = (
            *carry[:5],
            cs.ev_tick,
            cs.ev_kind,
            cs.ev_node,
            cs.base.p_tick,
            cs.base.p_gid,
            cs.loss[:, a:b],
            keys[:, a:b],
            jnp.int32(a),
            cs.base.faults,
            tr_tensors,
            carry[5],  # the overload feedback carry (or None)
            carry[6],  # the remediation policy carry (or None)
            knobs,
            None,  # sw_knobs: param_knobs is not wired streamed
            carry[7],  # the provenance carry (ProvCarry or None)
            pv_at,
            pv_node,
        )
        statics = dict(
            params=params,
            has_revive=cs.base.has_revive,
            traffic=static_traffic,
            overload=cs.base.overload,
            policy=policy.config if policy is not None else None,
            prov=cs.base.trace_rumors or None,
        )
        ssweep._dispatches += 1
        t0 = time.perf_counter()
        if led.enabled:
            out, row = led.launch(
                "run_sweep", ssweep._sweep_scan, *args, _meta=meta, **statics
            )
        else:
            out, row = ssweep._sweep_scan(*args, **statics), None
        if row is not None:
            row["dispatch_s"] = round(time.perf_counter() - t0, 6)
        return out, row

    def _drain(p: tuple, *, overlapped: bool) -> None:
        seg, a, b, ys, row = p
        t0 = time.perf_counter()
        stacks = {k: np.asarray(v) for k, v in ys.items()}
        slab = ssweep.SweepTrace(
            metrics={
                k: v
                for k, v in stacks.items()
                if k not in ("converged", "live", "loss") and v.ndim == 2
            },
            planes={k: v for k, v in stacks.items() if v.ndim == 3},
            converged=stacks["converged"],
            live=stacks["live"],
            loss=stacks["loss"],
            n=cluster.n,
            backend=cluster.backend,
            replica_keys=rkeys_np,
            loss_scales=cs.loss_scales,
            kill_jitter=cs.kill_jitter,
            flap_jitter=cs.flap_jitter,
            start_tick=start_tick + a,
            spec=None,
        )
        if store_obj is not None:
            store_obj.append(slab, segment=seg, tick0=a)
        else:
            slabs.append(slab)
        drain_s = time.perf_counter() - t0
        if row is not None:
            row["drain_s"] = round(drain_s, 6)
            row["drain_overlap_s"] = round(drain_s if overlapped else 0.0, 6)
            led.record(row)

    for seg, (a, b) in enumerate(bounds):
        out, row = _launch(seg, a, b, carry)
        carry, ys = out[:8], out[8]
        if pending is not None:
            _drain(pending, overlapped=True)
            pending = None
        pending = (seg, a, b, ys, row)
        if not pipeline:
            # the unpipelined comparison arm: blocking here IS the
            # mode's contract (bench_stream's baseline)
            jax.block_until_ready(carry)  # audit: allow=RPL001
            _drain(pending, overlapped=False)
            pending = None
    if pending is not None:
        _drain(pending, overlapped=False)

    states, up, resp, adj_out, per_out, ov_out, po_out, pv_out = carry
    net_kw = {}
    if ov_out is not None:
        net_kw = dict(ov_cnt=ov_out[0], ov_gray=ov_out[1])
    if po_out is not None:
        net_kw.update(
            po_press=po_out[0], po_shed=po_out[1], po_quar=po_out[2],
            po_sends_w=po_out[3], po_deliv_w=po_out[4],
            po_retry_cap=po_out[5],
        )
    if pv_out is not None:
        net_kw.update(
            pv_slot=pv_out.slot, pv_tickv=pv_out.tickv, pv_wits=pv_out.wits,
            pv_first=pv_out.first, pv_parent=pv_out.parent,
            pv_knows=pv_out.knows,
        )
    nets = NetState(up=up, responsive=resp, adj=adj_out, period=per_out,
                    **net_kw)
    if not assemble:
        return store_obj
    trace = (
        store_obj.assemble()
        if store_obj is not None
        else ssweep.SweepTrace.concat_ticks(slabs, spec=spec.to_dict())
    ).validate()
    trace.final_states = states
    trace.final_nets = nets
    return trace

"""Incident library: the golden real-world outage suite.

The scenario engine grew every fault primitive a production SWIM
deployment dies from — kills, partitions, asymmetric links,
delay/jitter, flap storms, gray failures, rolling deploys, loss ramps,
latency-coupled traffic, and (this module's sibling, the ``overload``
op) load-coupled gray degradation.  This module composes them into the
NAMED incidents operators actually debate: each incident is a
parameterized builder producing a ``(ScenarioSpec, WorkloadSpec)``
pair for any cluster size, runnable on either backend (the two
incidents built on in-scan revive are dense-only and say so, the
bench_faults precedent), streamed like any scenario, and replayable
with one command::

    python -m ringpop_tpu tick-cluster --backend tpu-sim -n 64 \
        --incident cascading_overload

Reference-size JSON renderings live in ``scenarios/specs/`` (kept in
sync by tests), and each incident's detect/heal/serve summary is
pinned per backend under ``tests/golden/incidents/`` — the regression
lane every future perf or protocol PR is judged against
(``incident_summary`` is all exact ints, so the pin is bit-equality,
not tolerance).

Naming the incidents is the point: "did your change help
``deploy_during_partition``?" is a question both a reviewer and a CI
job can answer.
"""

from __future__ import annotations

import os
from typing import Any, Callable, NamedTuple

import numpy as np

from ringpop_tpu.scenarios.spec import Event, ScenarioSpec
from ringpop_tpu.traffic.workloads import WorkloadSpec

# every incident serves traffic with the SLO latency plane on: the
# detect/heal story is only half an outage — the golden summaries pin
# goodput, tail latency, and retry amplification too
LATENCY_BUCKETS = 16


class Incident(NamedTuple):
    """One named outage: a documented builder over (n, ticks)."""

    name: str
    title: str
    about: str  # one paragraph: composition + what to expect
    backends: tuple[str, ...]  # ("dense", "delta") or ("dense",)
    default_ticks: int
    build: Callable[[int, int], tuple[ScenarioSpec, WorkloadSpec]]


def _halves(n: int) -> tuple[list[int], list[int]]:
    return list(range(n // 2)), list(range(n // 2, n))


def _wl(n: int, **kw: Any) -> WorkloadSpec:
    base = dict(
        keys_per_tick=8 * n,
        pool=max(32 * n, 256),
        latency_buckets=LATENCY_BUCKETS,
    )
    base.update(kw)
    return WorkloadSpec(**base)


# ---------------------------------------------------------------------------
# builders (each returns a VALIDATED spec + workload for cluster size n)
# ---------------------------------------------------------------------------


def _region_partition_asym_heal(n: int, ticks: int):
    """Region split, then an asymmetric heal: the backbone comes back
    one direction first.

    The partition window (18 ticks) deliberately straddles the default
    25-tick suspicion timeout REACHED THROUGH the lossy heal: when the
    groups reconnect, region A still hears region B at only 15%
    delivery, so A's suspicion timers keep running out (one-sided
    faulty declarations) while B clears its view of A immediately —
    the lopsided remerge a symmetric loss cannot express.  (A partition
    that simply outlives suspicion splits the brain PERMANENTLY — both
    sides declare each other faulty and SWIM never probes faulty
    members again; the reference grew admin heal for exactly that.
    This incident pins the recoverable-but-lopsided regime.)"""
    a, b = _halves(n)
    t_part = ticks // 14 + 2
    t_heal = t_part + 18  # suspicions running, faulty not yet declared
    t_clean = int(ticks * 0.6)
    spec = ScenarioSpec(
        ticks=ticks,
        events=(
            Event(at=t_part, op="partition", groups=(tuple(a), tuple(b))),
            Event(at=t_heal, op="heal"),
            # after the heal, region A hears region B through a lossy
            # rehomed path (15% delivery) until t_clean — the one-way
            # brownout a symmetric loss cannot express
            Event(at=t_heal, op="link_loss", until=t_clean,
                  src=tuple(b), dst=tuple(a), p=0.85),
        ),
    )
    return spec, _wl(n)


def _cascading_overload(n: int, ticks: int):
    """The feedback loop: hot-key traffic overloads ring owners past
    capacity, they degrade gray, gray holders time out off their duty
    phase so retries amplify the send load, and more nodes cross the
    threshold — the suite's measurement of whether RETRY_SCHEDULE
    backoff arrests or amplifies the cascade (BASELINE.md)."""
    wl = _wl(n, kind="zipf", zipf_s=1.2)
    m = wl.keys_per_tick
    capacity = max(3, (3 * m) // (2 * n))  # ~1.5x the fair-share load
    spec = ScenarioSpec(
        ticks=ticks,
        events=(
            Event(at=ticks // 12 + 1, op="overload",
                  until=int(ticks * 0.92),
                  capacity=capacity, threshold=6 * capacity,
                  recover=2 * capacity, factor=6),
        ),
    )
    return spec, wl


def _deploy_during_partition(n: int, ticks: int):
    """A rolling restart wave that keeps deploying while a netsplit is
    in force — rejoining nodes can only bootstrap against their own
    side, and the heal lands mid-wave.  Dense-only (in-scan revive)."""
    a, b = _halves(n)
    wave = list(range(max(2, n // 4)))  # the deploy order: first quarter
    every, down = 4, 6
    t_part = ticks // 10 + 1
    t_deploy = t_part + 6
    t_heal = min(int(ticks * 0.7),
                 t_deploy + (len(wave) - 1) * every + down + 4)
    last = t_deploy + (len(wave) - 1) * every + down
    if last >= ticks:
        raise ValueError(
            f"deploy_during_partition needs ticks > {last} at n={n}"
        )
    spec = ScenarioSpec(
        ticks=ticks,
        events=(
            Event(at=t_part, op="partition", groups=(tuple(a), tuple(b))),
            Event(at=t_deploy, op="rolling_restart", nodes=tuple(wave),
                  every=every, down=down),
            Event(at=t_heal, op="heal"),
        ),
    )
    return spec, _wl(n)


def _slow_network_hot_key(n: int, ticks: int):
    """Cross-rack latency plus a hot-key tenant: every cross-half
    message crawls (asymmetric delay/jitter), while a zipf workload
    hammers a handful of owners — the tail-latency incident."""
    a, b = _halves(n)
    t0, t1 = ticks // 12 + 1, int(ticks * 0.83)
    spec = ScenarioSpec(
        ticks=ticks,
        events=(
            Event(at=t0, op="delay", until=t1, src=tuple(a), dst=tuple(b),
                  delay=2, jitter=3),
            Event(at=t0, op="delay", until=t1, src=tuple(b), dst=tuple(a),
                  delay=1, jitter=2),
        ),
    )
    return spec, _wl(n, kind="zipf", zipf_s=1.3)


def _thundering_rejoin(n: int, ticks: int):
    """Half the cluster dies at once (a power event), then every node
    revives in the SAME tick — the mass-rejoin stampede against the
    survivors' dissemination budget.  Dense-only (in-scan revive)."""
    dead = list(range(n // 2, n))
    t_kill = ticks // 8 + 1
    t_revive = int(ticks * 0.45)
    spec = ScenarioSpec(
        ticks=ticks,
        events=tuple(
            Event(at=t_kill, op="kill", node=i) for i in dead
        ) + tuple(
            Event(at=t_revive, op="revive", node=i) for i in dead
        ),
    )
    return spec, _wl(n)


def _gray_failure_storm(n: int, ticks: int):
    """The insidious mix: a clique of gray (slow but alive) nodes, a
    storm of process stalls (suspend/resume duty cycles — the
    SIGSTOP analog of a flap, so the incident stays delta-runnable),
    and one-way loss FROM the gray clique — detectors see silence one
    way while the gray nodes keep answering the other."""
    gray = list(range(max(2, n // 8)))
    stall = [i for i in range(n // 2, n // 2 + max(2, n // 8))]
    t0 = ticks // 14 + 1
    t1 = int(ticks * 0.86)
    events: list[Event] = [
        Event(at=t0, op="gray", nodes=tuple(gray), factor=5, until=t1),
        Event(at=t0 + 8, op="link_loss", until=int(ticks * 0.71),
              src=tuple(gray), dst=tuple(i for i in range(n) if i not in gray),
              p=0.5),
    ]
    # hand-rolled stall cycles (4 down, 6 up, staggered): suspend keeps
    # state and needs no re-join, so the storm runs on both backends
    down, up = 4, 6
    for k, node in enumerate(stall):
        t = t0 + 4 + 2 * k
        while t + down < int(ticks * 0.8):
            events.append(Event(at=t, op="suspend", node=node))
            events.append(Event(at=t + down, op="resume", node=node))
            t += down + up
    spec = ScenarioSpec(ticks=ticks, events=tuple(events))
    return spec, _wl(n)


def _brownout_loss_ramp(n: int, ticks: int):
    """A whole-fabric brownout: packet loss ramps toward 45% and back
    down while a few nodes run gray — the slow rot where nothing is
    down but everything is late."""
    gray = list(range(2, 2 + max(1, n // 10)))
    t0 = ticks // 14 + 1
    mid = ticks // 2
    t1 = int(ticks * 0.79)
    spec = ScenarioSpec(
        ticks=ticks,
        events=(
            Event(at=t0, op="loss_ramp", until=mid, p=0.45),
            Event(at=mid, op="loss_ramp", until=t1, p=0.0),
            Event(at=t0 + 5, op="gray", nodes=tuple(gray), factor=4,
                  until=int(ticks * 0.64)),
        ),
    )
    return spec, _wl(n)


def _hot_tenant_blackhole(n: int, ticks: int):
    """One rack goes one-way dark exactly while a skewed tenant is
    hammering it: the rest of the cluster stops hearing the rack (90%
    one-way loss) and its replies crawl — requests keep routing to
    owners the mesh can no longer agree about."""
    rack = list(range(n - max(2, n // 8), n))
    rest = [i for i in range(n) if i not in rack]
    t0, t1 = ticks // 9 + 1, int(ticks * 0.69)
    spec = ScenarioSpec(
        ticks=ticks,
        events=(
            Event(at=t0, op="link_loss", until=t1, src=tuple(rack),
                  dst=tuple(rest), p=0.9),
            Event(at=t0, op="delay", until=t1, src=tuple(rack),
                  dst=tuple(rest), delay=1, jitter=1),
        ),
    )
    return spec, _wl(n, kind="tenant", tenants=8, zipf_s=1.4)


INCIDENTS: dict[str, Incident] = {
    i.name: i
    for i in (
        Incident(
            "region_partition_asym_heal",
            "Region partition with asymmetric healing",
            "A clean half/half netsplit whose heal is one-directional "
            "first: after the partition lifts, region A hears region B "
            "at 15% delivery for another window.  Pins how long the "
            "remerge takes when the backbone comes back lopsided.",
            ("dense", "delta"), 140, _region_partition_asym_heal,
        ),
        Incident(
            "cascading_overload",
            "Cascading overload feedback loop",
            "Zipf traffic pushes hot ring owners past their capacity "
            "knob; the overload op degrades them gray; gray holders "
            "miss their duty phase, so requests time out and retry "
            "with RETRY_SCHEDULE backoff — each retry is another send "
            "landing on an overloaded inbox.  The golden summary pins "
            "whether backoff arrests the cascade (peak gray count, "
            "goodput, amplification) — the no-feedback control run is "
            "the BASELINE.md comparison.",
            ("dense", "delta"), 120, _cascading_overload,
        ),
        Incident(
            "deploy_during_partition",
            "Rolling deploy overlapping a netsplit",
            "A quarter of the fleet rolls (kill + fresh-incarnation "
            "rejoin, staggered) while a half/half partition is in "
            "force, and the heal lands mid-wave: rejoining nodes "
            "bootstrap against whichever side they can see.  "
            "Dense-backend only (in-scan revive).",
            ("dense",), 160, _deploy_during_partition,
        ),
        Incident(
            "slow_network_hot_key",
            "Slow cross-rack network under a hot key",
            "Asymmetric cross-half delay/jitter (2+U{0..3} ticks one "
            "way, 1+U{0..2} the other) while a zipf workload hammers "
            "a few owners: dissemination crawls, rings diverge, and "
            "the latency histogram grows a real tail.",
            ("dense", "delta"), 120, _slow_network_hot_key,
        ),
        Incident(
            "thundering_rejoin",
            "50% kill, then a thundering same-tick rejoin",
            "Half the cluster dies in one tick (power event) and every "
            "node revives in the SAME later tick with fresh "
            "incarnations — the mass bootstrap stampede against the "
            "survivors' piggyback budget.  Dense-backend only "
            "(in-scan revive).",
            ("dense",), 150, _thundering_rejoin,
        ),
        Incident(
            "gray_failure_storm",
            "Gray clique + stall storm + one-way silence",
            "A clique of gray nodes (5x period, still answering), a "
            "staggered SIGSTOP stall storm on another eighth of the "
            "fleet, and 50% one-way loss FROM the gray clique: the "
            "failure detector hears silence in one direction while "
            "the gray nodes keep refuting suspicion in the other.",
            ("dense", "delta"), 140, _gray_failure_storm,
        ),
        Incident(
            "brownout_loss_ramp",
            "Fabric brownout: loss ramp + gray rot",
            "Packet loss ramps 0 -> 45% -> 0 across the whole fabric "
            "while a tenth of the fleet runs gray: nothing is down, "
            "everything is late — the incident where false-faulty "
            "declarations are the thing to watch.",
            ("dense", "delta"), 140, _brownout_loss_ramp,
        ),
        Incident(
            "hot_tenant_blackhole",
            "Hot tenant vs a one-way-dark rack",
            "The rack owning a skewed tenant's keys goes 90% one-way "
            "dark (cluster stops hearing it; it still hears the "
            "cluster) with crawling replies: requests keep routing to "
            "owners the mesh cannot agree about, and the tenant eats "
            "the misroutes.",
            ("dense", "delta"), 130, _hot_tenant_blackhole,
        ),
    )
}


def incident_names() -> list[str]:
    return list(INCIDENTS)


def build_incident(
    name: str, n: int, *, ticks: int | None = None, backend: str = "dense",
    overload: bool = True,
) -> tuple[ScenarioSpec, WorkloadSpec]:
    """Materialize incident ``name`` for a cluster of ``n`` nodes
    (validated).  ``overload=False`` strips the feedback loop from
    incidents that carry one — the no-feedback CONTROL arm the
    BASELINE comparison runs."""
    if name not in INCIDENTS:
        raise ValueError(
            f"unknown incident {name!r}; one of {', '.join(INCIDENTS)}"
        )
    inc = INCIDENTS[name]
    if backend not in inc.backends:
        raise ValueError(
            f"incident {name!r} runs on {'/'.join(inc.backends)} only "
            f"(got {backend}): in-scan revive is dense-backend-only"
        )
    if n < 8:
        raise ValueError(f"incidents need n >= 8 (got {n})")
    t = int(ticks) if ticks is not None else inc.default_ticks
    spec, wl = inc.build(n, t)
    if not overload:
        spec = ScenarioSpec(
            ticks=spec.ticks,
            events=tuple(e for e in spec.events if e.op != "overload"),
        )
    return spec.validate(n), wl.validate(n)


def format_catalog() -> str:
    """The ``--list-incidents`` text."""
    lines = []
    for inc in INCIDENTS.values():
        back = "both backends" if len(inc.backends) == 2 else "dense only"
        lines.append(f"{inc.name}  ({back}, default {inc.default_ticks} "
                     f"ticks)\n  {inc.title}\n  {inc.about}\n")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# the golden detect/heal/serve summary (exact ints -> bit-equality pins)
# ---------------------------------------------------------------------------

SUMMARY_SCHEMA = 1


def incident_summary(trace: Any, prov: Any | None = None) -> dict[str, int]:
    """One incident run's detect/heal/serve summary — every value an
    exact int so the golden files under ``tests/golden/incidents/``
    pin bit-equality, not tolerances.

    Keys: ``detect_tick`` (first faulty declaration, -1 if none),
    ``heal_tick`` (first tick from which ``converged`` holds through
    the end, -1 if never), ``final_live``, the serving totals
    (``sends`` = handled_local + proxy_sends + proxy_retries, the
    amplification numerator), the latency percentile floors in ms,
    and the overload peaks when the feedback loop ran.

    ``prov`` (a ``obs.provenance.build_report`` dict from a traced
    run) embeds the plane's all-int aggregate as ``pv_*`` keys — the
    dissemination scorecard (infection depth / percentiles vs the
    paper's log2(N) bound) pinned right next to detect/heal."""
    m = trace.metrics
    hits = np.flatnonzero(m["faulty_declared"] > 0)
    detect = int(hits[0]) if hits.size else -1
    rev = trace.converged[::-1]
    suffix = trace.ticks if rev.all() else int(np.argmax(~rev))
    heal = trace.ticks - suffix if suffix > 0 else -1
    out: dict[str, int] = {
        "schema": SUMMARY_SCHEMA,
        "ticks": int(trace.ticks),
        "n": int(trace.n),
        "detect_tick": detect,
        "heal_tick": heal,
        "final_live": int(trace.live[-1]),
        "faulty_declared": int(m["faulty_declared"].sum()),
        "suspects_declared": int(m["suspects_declared"].sum()),
    }
    if "lookups" in m:
        from ringpop_tpu.traffic.engine import total_sends

        out.update(
            lookups=int(m["lookups"].sum()),
            delivered=int(m["delivered"].sum()),
            dropped=int(m["dropped"].sum()),
            misroutes=int(m["misroutes"].sum()),
            proxy_failed=int(m["proxy_failed"].sum()),
            sends=total_sends(m),
        )
    for key in ("send_errors", "gray_timeouts", "retry_succeeded"):
        if key in m:
            out[key] = int(m[key].sum())
    if "lat_hist_ms" in trace.planes:
        from ringpop_tpu.traffic.latency import hist_stats

        agg = hist_stats(trace.planes["lat_hist_ms"].sum(axis=0))
        out["lat_p50_ms"] = int(agg["median"])
        out["lat_p95_ms"] = int(agg["p95"])
        out["lat_p99_ms"] = int(agg["p99"])
    if "ov_gray_nodes" in m:
        out["ov_gray_peak"] = int(m["ov_gray_nodes"].max())
        out["ov_pressure_peak"] = int(m["ov_pressure_max"].max())
    if "policy_shed" in m:
        # the remediation plane ran: its sheds are already inside
        # ``sends`` (total_sends counts them — amplification stays
        # honest), and the peaks pin how hard each mechanism engaged
        out["policy_shed"] = int(m["policy_shed"].sum())
        out["policy_quar_peak"] = int(m["policy_quarantined"].max())
        out["policy_shed_peak"] = int(m["policy_shed_nodes"].max())
        out["policy_retry_cap_min"] = int(m["policy_retry_cap"].min())
        out["policy_amp_peak_x16"] = int(m["policy_amp_x16"].max())
    if prov is not None:
        from ringpop_tpu.obs.provenance import summary_block

        for key, value in summary_block(prov).items():
            out[f"pv_{key}"] = int(value)
    return out


def format_summary(name: str, summary: dict[str, int]) -> str:
    """The human line the CLI prints under an ``--incident`` run."""
    s = summary
    parts = [
        f"incident {name}: detect tick "
        f"{s['detect_tick'] if s['detect_tick'] >= 0 else '-'}",
        f"heal tick {s['heal_tick'] if s['heal_tick'] >= 0 else '-'}",
        f"live {s['final_live']}/{s['n']}",
    ]
    if "lookups" in s and s["lookups"]:
        goodput = 100.0 * s["delivered"] / s["lookups"]
        amp = s["sends"] / max(s["delivered"], 1)
        parts.append(f"goodput {goodput:.1f}%")
        parts.append(f"amplification {amp:.2f}")
    if "lat_p99_ms" in s:
        parts.append(f"lat p50/p95/p99 {s['lat_p50_ms']}/"
                     f"{s['lat_p95_ms']}/{s['lat_p99_ms']}ms")
    if "gray_timeouts" in s:
        parts.append(f"{s['gray_timeouts']} gray timeouts")
    if "ov_gray_peak" in s:
        parts.append(f"peak overload-gray {s['ov_gray_peak']}")
    if "policy_shed" in s:
        parts.append(f"shed {s['policy_shed']}")
        parts.append(f"peak quarantine {s['policy_quar_peak']}")
    if s.get("pv_rumors"):
        parts.append(
            f"rumors {s['pv_rumors']} (depth {s['pv_depth_max']}, "
            f"infect p99 {s['pv_p99_max']}t)"
        )
    return ", ".join(parts)


# ---------------------------------------------------------------------------
# the golden run configuration (tests/golden/incidents/*.json)
# ---------------------------------------------------------------------------

# Every golden summary is produced by EXACTLY this configuration —
# n/seed/params/segmenting are part of the pin (the summaries are
# exact ints of a deterministic seeded run, so a mismatch is a real
# behavior change, not noise).  Regenerate after an intentional
# protocol/serving change with ``python tools/pin_incidents.py``.
GOLDEN_N = 16
GOLDEN_SEED = 3
GOLDEN_SEGMENT = 32


def golden_cluster(backend: str = "dense"):
    """The cluster every golden (and the incident smoke) runs on."""
    from ringpop_tpu.models.cluster import SimCluster
    from ringpop_tpu.models.swim_sim import SwimParams

    kw = (
        {}
        if backend == "dense"
        else dict(capacity=GOLDEN_N, wire_cap=GOLDEN_N,
                  claim_grid=3 * GOLDEN_N * GOLDEN_N)
    )
    return SimCluster(
        GOLDEN_N, SwimParams(), seed=GOLDEN_SEED, backend=backend, **kw
    )


def run_golden(
    name: str, backend: str = "dense", policy: str | None = None
) -> dict[str, int]:
    """One incident at the golden configuration, streamed (the CLI's
    default segmenting — bit-identical to the one-dispatch run), down
    to its summary dict.  ``policy`` arms a remediation policy at its
    default operating point (``ringpop_tpu.policies``) — the
    policy-armed goldens pinned next to the bare incident pins."""
    spec, wl = build_incident(name, GOLDEN_N, backend=backend)
    cluster = golden_cluster(backend)
    trace = cluster.run_scenario(
        spec, traffic=wl, segment_ticks=min(GOLDEN_SEGMENT, spec.ticks),
        policy=policy,
    )
    return incident_summary(trace)


def golden_path(
    name: str, backend: str, directory: str, policy: str | None = None
) -> str:
    stem = f"{name}+{policy}" if policy else name
    return os.path.join(directory, f"{stem}.{backend}.json")


# The winning operating point (BASELINE.md round 9) and the pinned
# policy-armed grid: cascading_overload under EVERY policy on both
# backends (the incident the plane exists to beat), plus every other
# incident under the winner (the no-regression scorecard — a policy
# must not win cascading_overload by tanking a different outage).
GOLDEN_POLICY = "combined"


def policy_golden_grid() -> list[tuple[str, str, str]]:
    """(incident, policy, backend) triples pinned under
    ``tests/golden/incidents/`` (``tools/pin_incidents.py --policies``)."""
    grid: list[tuple[str, str, str]] = []
    from ringpop_tpu.policies import core as pol

    for p in pol.list_policies():
        for b in ("dense", "delta"):
            grid.append(("cascading_overload", p, b))
    for name, inc in INCIDENTS.items():
        if name != "cascading_overload":
            grid.append((name, GOLDEN_POLICY, "dense"))
    return grid


# ---------------------------------------------------------------------------
# reference JSON specs (scenarios/specs/*.json, kept in sync by tests)
# ---------------------------------------------------------------------------

SPEC_DIR = os.path.join(os.path.dirname(__file__), "specs")
SPEC_N = 64  # the reference rendering's cluster size


def spec_document(name: str, n: int = SPEC_N) -> dict[str, Any]:
    """The self-describing JSON form of one incident at size ``n``."""
    inc = INCIDENTS[name]
    spec, wl = build_incident(name, n)
    return {
        "incident": name,
        "title": inc.title,
        "about": inc.about,
        "backends": list(inc.backends),
        "n": n,
        "scenario": spec.to_dict(),
        "workload": wl.to_dict(),
    }


def write_specs(directory: str = SPEC_DIR, n: int = SPEC_N) -> list[str]:
    """(Re)render every incident's reference JSON spec; returns the
    paths written.  ``tests/test_incidents.py`` pins that the checked-
    in files match this rendering, so the library is the single source
    of truth and the JSON is its durable, diffable artifact."""
    import json

    os.makedirs(directory, exist_ok=True)
    paths = []
    for name in INCIDENTS:
        path = os.path.join(directory, f"{name}.json")
        with open(path, "w") as f:
            json.dump(spec_document(name, n), f, indent=2)
            f.write("\n")
        paths.append(path)
    return paths

"""ScenarioSpec -> device-resident event tensors + the key schedule.

The compiled form is what the one-dispatch runner scans over:

* node events as flat ``(tick, kind, node)`` arrays — applied per tick
  by masked out-of-bounds-dropped scatters (O(E) per tick, no [T, N]
  timeline tensor);
* partition/heal events as ``(tick, gid_row)`` — each row an int32[N]
  group-id adjacency (``swim_sim._adj``; heal = all-one-group zeros);
* the loss schedule as a dense float32[ticks] (stepwise events and
  ramps are both just per-tick values here);
* the segment boundaries: every tick at which any event fires.  The
  PRNG **key schedule** derives from them so the compiled run is
  bit-identical to the equivalent host-side sequence of
  ``apply-faults; tick(segment)`` calls — ``SimCluster.tick(k)`` draws
  one split of the cluster key per call and fans it into k per-tick
  keys, so the schedule replays exactly that (``key_schedule``).
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ringpop_tpu.models.cluster import groups_to_gid
from ringpop_tpu.scenarios import faults as sfaults
from ringpop_tpu.scenarios.spec import ScenarioSpec, expand_fault_primitives

# node-event kinds (ev_kind values)
EV_KILL = 0
EV_SUSPEND = 1
EV_RESUME = 2
EV_REVIVE = 3
_KIND = {"kill": EV_KILL, "suspend": EV_SUSPEND, "resume": EV_RESUME,
         "revive": EV_REVIVE}

# Canonical intra-tick apply order, shared by the scan and the host
# loop: bit edits first (order-free among themselves), then revives
# (whose bootstrap join reads the post-edit live set), then partition
# rows; loss/faultcfg touch neither substrate, so their rank only
# needs to be deterministic.  The sort is stable, so same-kind ops
# keep their expansion order (the revive-vs-revive order).
_OP_RANK = {"kill": 0, "suspend": 1, "resume": 2, "revive": 3,
            "partition": 4, "heal": 4, "loss": 5, "faultcfg": 6}


class CompiledScenario(NamedTuple):
    """Device tensors + static shape facts for one scenario."""

    ticks: int
    n: int
    ev_tick: jax.Array  # int32[E] node-event ticks
    ev_kind: jax.Array  # int32[E] EV_* codes
    ev_node: jax.Array  # int32[E] target node
    p_tick: jax.Array  # int32[P] partition/heal ticks
    p_gid: jax.Array  # int32[P, N] group-id rows (heal = zeros)
    loss: jax.Array  # float32[ticks] per-tick loss in force
    has_revive: bool  # static: trace the in-scan revive path at all?
    boundaries: tuple[int, ...]  # distinct event ticks in (0, ticks)
    # failure-model extension (scenarios/faults.py); None = the spec
    # has no link/gray/delay events and the program is the legacy one
    faults: Any | None = None  # faults.FaultTensors | None
    has_delay: bool = False  # static: route through the in-flight buffer?
    has_gray: bool = False  # static: carry the per-node period row?
    delay_depth: int = 0  # static ring-buffer depth (0 = no delay)
    # load-coupled gray feedback (faults.OverloadConfig, all-int and
    # hashable -> a jit-static of the scan); None = no overload event
    # and the compiled program carries no overload state at all
    overload: Any | None = None
    # provenance plane (obs/provenance.py): tracked-rumor slot count
    # (the plane's static width; 0 = legacy program, no plane) and the
    # track-op reservations as (at, node) pairs in slot order
    trace_rumors: int = 0
    tracks: tuple[tuple[int, int], ...] = ()


def expand_events(
    spec: ScenarioSpec, base_loss: float
) -> list[tuple[int, str, Any]]:
    """The spec as concrete per-tick ops, ramps unrolled to stepwise
    ``loss`` ops, flap/rolling-restart cycles unrolled to kill/revive
    primitives, and a ``faultcfg`` marker at every tick the link-rule /
    period configuration changes — the single source of truth shared by
    the tensor compiler and the host-loop equivalent
    (``runner.run_host_loop``)."""
    out: list[tuple[int, str, Any]] = []
    loss = float(base_loss)
    for e in sorted(spec.events, key=lambda e: e.at):
        if e.op == "loss":
            loss = float(e.p)
            out.append((e.at, "loss", loss))
        elif e.op == "loss_ramp":
            start, span = loss, e.until - e.at
            for tau in range(e.at, e.until):
                loss = start + (float(e.p) - start) * (tau - e.at + 1) / span
                out.append((tau, "loss", loss))
        elif e.op == "partition":
            out.append((e.at, "partition", e.groups))
        elif e.op == "heal":
            out.append((e.at, "heal", None))
        elif e.op in ("flap", "rolling_restart"):
            out.extend(
                (pe.at, pe.op, pe.node)
                for pe in expand_fault_primitives(e, spec.ticks)
            )
        elif e.op in ("link_loss", "delay", "gray"):
            pass  # lowered below via the marker ticks (faults.py)
        elif e.op == "overload":
            pass  # static config (faults.overload_config); the update
            # is per-tick in-scan state, not a timeline op, and the
            # host oracle carries it tick-by-tick itself — no marker
        elif e.op == "track":
            pass  # observation op: a compile-time slot reservation
            # (CompiledScenario.tracks), never a timeline op — no
            # boundary, so the key schedule is untouched (host parity)
        else:
            out.append((e.at, e.op, e.node))
    out.extend(
        (t, "faultcfg", None) for t in sfaults.fault_marker_ticks(spec)
    )
    return out


def compile_spec(
    spec: ScenarioSpec, n: int, *, base_loss: float = 0.0
) -> CompiledScenario:
    """Lower a validated spec to the tensors the jitted runner scans."""
    spec.validate(n)
    ops = expand_events(spec, base_loss)

    ev_tick, ev_kind, ev_node = [], [], []
    p_tick, p_gid = [], []
    loss_tl = np.full(spec.ticks, float(base_loss), dtype=np.float32)
    # tick order, NOT event order: a ramp's unrolled ops interleave
    # with later loss events, and each loss write covers [at:] — the
    # host loop applies them per tick, so the timeline must too.
    # Within a tick, the canonical _OP_RANK order (stable, so same-kind
    # ops keep their expand order, like the host loop's sequential
    # set_loss calls / revive order).
    for at, op, arg in sorted(ops, key=lambda x: (x[0], _OP_RANK[x[1]])):
        if op == "loss":
            loss_tl[at:] = arg
        elif op == "partition":
            p_tick.append(at)
            p_gid.append(groups_to_gid(arg, n))
        elif op == "heal":
            p_tick.append(at)
            p_gid.append(np.zeros(n, dtype=np.int32))
        elif op == "faultcfg":
            pass  # boundary marker only; tensors come from compile_faults
        else:
            ev_tick.append(at)
            ev_kind.append(_KIND[op])
            ev_node.append(arg)
    boundaries = tuple(sorted({at for at, _, _ in ops if 0 < at < spec.ticks}))
    ft = sfaults.compile_faults(spec, n)
    return CompiledScenario(
        ticks=spec.ticks,
        n=n,
        ev_tick=jnp.asarray(ev_tick, dtype=jnp.int32),
        ev_kind=jnp.asarray(ev_kind, dtype=jnp.int32),
        ev_node=jnp.asarray(ev_node, dtype=jnp.int32),
        p_tick=jnp.asarray(p_tick, dtype=jnp.int32),
        p_gid=jnp.asarray(
            np.stack(p_gid) if p_gid else np.zeros((0, n), np.int32)
        ),
        loss=jnp.asarray(loss_tl),
        has_revive=any(k == EV_REVIVE for k in ev_kind),
        boundaries=boundaries,
        faults=ft,
        has_delay=ft is not None and ft.lr_d is not None,
        has_gray=ft is not None and bool(ft.pe_tick.shape[0]),
        delay_depth=sfaults.delay_depth(spec),
        overload=sfaults.overload_config(spec),
        trace_rumors=spec.trace_rumors,
        tracks=tuple(
            (e.at, e.node) for e in spec.events if e.op == "track"
        ),
    )


def key_schedule(
    split: Callable[[], jax.Array], compiled: CompiledScenario
) -> jax.Array:
    """uint32[ticks, 2] per-tick step keys, segment-exact.

    ``split`` is the cluster's key draw (``SimCluster._split``).  One
    draw per segment between event boundaries; a length-1 segment uses
    the draw directly and a length-k segment fans it with
    ``jax.random.split(sub, k)`` — exactly what the host-side
    ``tick(1)`` / ``tick(k)`` calls of the equivalent fault sequence
    would consume, which is what makes the compiled run bit-identical
    to the host loop (tested in tests/test_scenario.py).
    """
    pts = [0, *compiled.boundaries, compiled.ticks]
    parts = []
    for a, b in zip(pts, pts[1:]):
        sub = split()
        parts.append(sub[None] if b - a == 1 else jax.random.split(sub, b - a))
    return jnp.concatenate(parts, axis=0)

"""ScenarioSpec -> device-resident event tensors + the key schedule.

The compiled form is what the one-dispatch runner scans over:

* node events as flat ``(tick, kind, node)`` arrays — applied per tick
  by masked out-of-bounds-dropped scatters (O(E) per tick, no [T, N]
  timeline tensor);
* partition/heal events as ``(tick, gid_row)`` — each row an int32[N]
  group-id adjacency (``swim_sim._adj``; heal = all-one-group zeros);
* the loss schedule as a dense float32[ticks] (stepwise events and
  ramps are both just per-tick values here);
* the segment boundaries: every tick at which any event fires.  The
  PRNG **key schedule** derives from them so the compiled run is
  bit-identical to the equivalent host-side sequence of
  ``apply-faults; tick(segment)`` calls — ``SimCluster.tick(k)`` draws
  one split of the cluster key per call and fans it into k per-tick
  keys, so the schedule replays exactly that (``key_schedule``).
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ringpop_tpu.models.cluster import groups_to_gid
from ringpop_tpu.scenarios.spec import ScenarioSpec

# node-event kinds (ev_kind values)
EV_KILL = 0
EV_SUSPEND = 1
EV_RESUME = 2
EV_REVIVE = 3
_KIND = {"kill": EV_KILL, "suspend": EV_SUSPEND, "resume": EV_RESUME,
         "revive": EV_REVIVE}


class CompiledScenario(NamedTuple):
    """Device tensors + static shape facts for one scenario."""

    ticks: int
    n: int
    ev_tick: jax.Array  # int32[E] node-event ticks
    ev_kind: jax.Array  # int32[E] EV_* codes
    ev_node: jax.Array  # int32[E] target node
    p_tick: jax.Array  # int32[P] partition/heal ticks
    p_gid: jax.Array  # int32[P, N] group-id rows (heal = zeros)
    loss: jax.Array  # float32[ticks] per-tick loss in force
    has_revive: bool  # static: trace the in-scan revive path at all?
    boundaries: tuple[int, ...]  # distinct event ticks in (0, ticks)


def expand_events(
    spec: ScenarioSpec, base_loss: float
) -> list[tuple[int, str, Any]]:
    """The spec as concrete per-tick ops, ramps unrolled to stepwise
    ``loss`` ops — the single source of truth shared by the tensor
    compiler and the host-loop equivalent (``runner.run_host_loop``)."""
    out: list[tuple[int, str, Any]] = []
    loss = float(base_loss)
    for e in sorted(spec.events, key=lambda e: e.at):
        if e.op == "loss":
            loss = float(e.p)
            out.append((e.at, "loss", loss))
        elif e.op == "loss_ramp":
            start, span = loss, e.until - e.at
            for tau in range(e.at, e.until):
                loss = start + (float(e.p) - start) * (tau - e.at + 1) / span
                out.append((tau, "loss", loss))
        elif e.op == "partition":
            out.append((e.at, "partition", e.groups))
        elif e.op == "heal":
            out.append((e.at, "heal", None))
        else:
            out.append((e.at, e.op, e.node))
    return out


def compile_spec(
    spec: ScenarioSpec, n: int, *, base_loss: float = 0.0
) -> CompiledScenario:
    """Lower a validated spec to the tensors the jitted runner scans."""
    spec.validate(n)
    ops = expand_events(spec, base_loss)

    ev_tick, ev_kind, ev_node = [], [], []
    p_tick, p_gid = [], []
    loss_tl = np.full(spec.ticks, float(base_loss), dtype=np.float32)
    # tick order, NOT event order: a ramp's unrolled ops interleave
    # with later loss events, and each loss write covers [at:] — the
    # host loop applies them per tick, so the timeline must too
    # (stable, so same-tick ops keep their expand order, like the
    # host loop's sequential set_loss calls)
    for at, op, arg in sorted(ops, key=lambda x: x[0]):
        if op == "loss":
            loss_tl[at:] = arg
        elif op == "partition":
            p_tick.append(at)
            p_gid.append(groups_to_gid(arg, n))
        elif op == "heal":
            p_tick.append(at)
            p_gid.append(np.zeros(n, dtype=np.int32))
        else:
            ev_tick.append(at)
            ev_kind.append(_KIND[op])
            ev_node.append(arg)
    boundaries = tuple(sorted({at for at, _, _ in ops if 0 < at < spec.ticks}))
    return CompiledScenario(
        ticks=spec.ticks,
        n=n,
        ev_tick=jnp.asarray(ev_tick, dtype=jnp.int32),
        ev_kind=jnp.asarray(ev_kind, dtype=jnp.int32),
        ev_node=jnp.asarray(ev_node, dtype=jnp.int32),
        p_tick=jnp.asarray(p_tick, dtype=jnp.int32),
        p_gid=jnp.asarray(
            np.stack(p_gid) if p_gid else np.zeros((0, n), np.int32)
        ),
        loss=jnp.asarray(loss_tl),
        has_revive=any(k == EV_REVIVE for k in ev_kind),
        boundaries=boundaries,
    )


def key_schedule(
    split: Callable[[], jax.Array], compiled: CompiledScenario
) -> jax.Array:
    """uint32[ticks, 2] per-tick step keys, segment-exact.

    ``split`` is the cluster's key draw (``SimCluster._split``).  One
    draw per segment between event boundaries; a length-1 segment uses
    the draw directly and a length-k segment fans it with
    ``jax.random.split(sub, k)`` — exactly what the host-side
    ``tick(1)`` / ``tick(k)`` calls of the equivalent fault sequence
    would consume, which is what makes the compiled run bit-identical
    to the host loop (tested in tests/test_scenario.py).
    """
    pts = [0, *compiled.boundaries, compiled.ticks]
    parts = []
    for a, b in zip(pts, pts[1:]):
        sub = split()
        parts.append(sub[None] if b - a == 1 else jax.random.split(sub, b - a))
    return jnp.concatenate(parts, axis=0)

"""Per-tick telemetry time series stacked by the scenario scan.

A ``Trace`` is the cure for ``swim_run`` discarding everything but the
last tick's metrics: one row per tick of every protocol counter, plus
the converged flag, the live-node count, and the loss in force.  It
round-trips through ``.npz`` (self-describing: the spec rides along)
and summarizes in the same key shape as ``stats.Histogram.print_obj``
(count/min/max/sum/mean/median/p75/p95/p99), so existing stat
consumers can read a scenario the way they read a meter dump.
"""

from __future__ import annotations

import json
import os
from typing import Any

import numpy as np

from ringpop_tpu.stats import Histogram

FORMAT_VERSION = 1

# arrays every trace must carry (schema_valid contract)
_REQUIRED = ("converged", "live", "loss")


class Trace:
    """Stacked per-tick telemetry of one scenario run."""

    def __init__(
        self,
        *,
        metrics: dict[str, np.ndarray],
        converged: np.ndarray,
        live: np.ndarray,
        loss: np.ndarray,
        n: int,
        backend: str,
        start_tick: int = 0,
        spec: dict[str, Any] | None = None,
        planes: dict[str, np.ndarray] | None = None,
    ):
        self.metrics = {k: np.asarray(v) for k, v in metrics.items()}
        # histogram planes: [ticks, B] per-tick counter ROWS (the SLO
        # latency plane's log2 buckets, traffic/latency.py) — vector
        # series next to the scalar metrics, same tick axis
        self.planes = {
            k: np.asarray(v) for k, v in (planes or {}).items()
        }
        self.converged = np.asarray(converged, dtype=bool)
        self.live = np.asarray(live, dtype=np.int32)
        self.loss = np.asarray(loss, dtype=np.float32)
        self.n = int(n)
        self.backend = str(backend)
        self.start_tick = int(start_tick)
        self.spec = spec

    @property
    def ticks(self) -> int:
        return int(self.converged.shape[0])

    def first_converged_tick(self) -> int:
        """0-based tick index of the first converged sample, or -1."""
        hits = np.flatnonzero(self.converged)
        return int(hits[0]) if hits.size else -1

    def validate(self) -> "Trace":
        """Schema check: every series is 1-D with one row per tick."""
        t = self.ticks
        if t < 1:
            raise ValueError("trace has no ticks")
        for name in _REQUIRED:
            arr = getattr(self, name)
            if arr.ndim != 1 or arr.shape[0] != t:
                raise ValueError(f"trace series {name!r} is not [{t}]-shaped")
        for name, arr in self.metrics.items():
            if arr.ndim != 1 or arr.shape[0] != t:
                raise ValueError(f"trace metric {name!r} is not [{t}]-shaped")
        for name, arr in self.planes.items():
            if arr.ndim != 2 or arr.shape[0] != t:
                raise ValueError(
                    f"trace plane {name!r} is not [{t}, B]-shaped"
                )
        if not np.all((self.live >= 0) & (self.live <= self.n)):
            raise ValueError("trace live counts outside [0, n]")
        return self

    def summary(self) -> dict[str, dict[str, float]]:
        """Per-series stats in ``stats.Histogram.print_obj`` key shape."""
        out: dict[str, dict[str, float]] = {}
        series: dict[str, np.ndarray] = {
            **self.metrics,
            "live": self.live,
            "loss": self.loss,
        }
        for name, arr in series.items():
            # sample_size >= ticks: the reservoir holds every value, so
            # the percentiles are exact, not sampled
            hist = Histogram(sample_size=max(len(arr), 1))
            for v in arr:
                hist.update(float(v))
            out[name] = hist.print_obj()
        out["converged"] = {
            "count": self.ticks,
            "sum": int(self.converged.sum()),
            "final": bool(self.converged[-1]),
            "first_tick": self.first_converged_tick(),
        }
        if self.planes:
            # histogram planes summarize as percentile estimates of
            # their whole-run bucket aggregate (bucket-floor values);
            # provenance planes (pv_*) are per-slot counters, not
            # bucket rows — their stats come from the host report
            # (obs.provenance.build_report), not a bucket aggregate
            from ringpop_tpu.traffic.latency import hist_stats

            for name, arr in self.planes.items():
                if name.startswith("pv_"):
                    continue
                out[name] = hist_stats(arr.sum(axis=0))
        return out

    @classmethod
    def concat(cls, slabs, *, spec: dict[str, Any] | None = None) -> "Trace":
        """Reassemble contiguous per-segment slabs (a streamed run's
        segment-store content, scenarios/stream.py) into one
        full-series trace — bit-identical to the trace the unsegmented
        scan would have stacked.  Slabs must be tick-contiguous
        (``start_tick`` ordering) and agree on n/backend/series."""
        slabs = list(slabs)
        if not slabs:
            raise ValueError("no slabs to concatenate")
        first = slabs[0]
        expect = first.start_tick
        for s in slabs:
            if s.n != first.n or s.backend != first.backend:
                raise ValueError("slabs disagree on n/backend")
            if set(s.metrics) != set(first.metrics):
                raise ValueError("slabs disagree on metric series")
            if set(s.planes) != set(first.planes):
                raise ValueError("slabs disagree on histogram planes")
            if s.start_tick != expect:
                raise ValueError(
                    f"slab at start_tick {s.start_tick} is not contiguous "
                    f"(expected {expect})"
                )
            expect += s.ticks
        return cls(
            metrics={
                k: np.concatenate([s.metrics[k] for s in slabs])
                for k in first.metrics
            },
            planes={
                k: np.concatenate([s.planes[k] for s in slabs])
                for k in first.planes
            },
            converged=np.concatenate([s.converged for s in slabs]),
            live=np.concatenate([s.live for s in slabs]),
            loss=np.concatenate([s.loss for s in slabs]),
            n=first.n,
            backend=first.backend,
            start_tick=first.start_tick,
            spec=spec if spec is not None else first.spec,
        )

    # -- npz round trip (shared with checkpoint.py via the dict forms) ------

    def to_arrays(self, prefix: str = "") -> dict[str, np.ndarray]:
        arrays = {
            f"{prefix}converged": self.converged,
            f"{prefix}live": self.live,
            f"{prefix}loss": self.loss,
        }
        for name, arr in self.metrics.items():
            arrays[f"{prefix}m.{name}"] = arr
        for name, arr in self.planes.items():
            arrays[f"{prefix}p.{name}"] = arr
        return arrays

    def meta(self) -> dict[str, Any]:
        return {
            "version": FORMAT_VERSION,
            "n": self.n,
            "backend": self.backend,
            "start_tick": self.start_tick,
            "spec": self.spec,
        }

    @classmethod
    def from_arrays(
        cls, data: Any, meta: dict[str, Any], prefix: str = ""
    ) -> "Trace":
        keys = list(getattr(data, "files", data.keys()))
        metrics = {
            key[len(prefix) + 2:]: np.asarray(data[key])
            for key in keys
            if key.startswith(f"{prefix}m.")
        }
        planes = {
            key[len(prefix) + 2:]: np.asarray(data[key])
            for key in keys
            if key.startswith(f"{prefix}p.")
        }
        return cls(
            metrics=metrics,
            planes=planes,
            converged=np.asarray(data[f"{prefix}converged"]),
            live=np.asarray(data[f"{prefix}live"]),
            loss=np.asarray(data[f"{prefix}loss"]),
            n=meta["n"],
            backend=meta["backend"],
            start_tick=meta.get("start_tick", 0),
            spec=meta.get("spec"),
        )

    def save(self, path: str) -> None:
        arrays = self.to_arrays()
        arrays["meta"] = np.frombuffer(
            json.dumps(self.meta()).encode(), dtype=np.uint8
        )
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            np.savez_compressed(f, **arrays)
        os.replace(tmp, path)  # atomic, like checkpoint.save

    @classmethod
    def load(cls, path: str) -> "Trace":
        with np.load(path, allow_pickle=False) as data:
            meta = json.loads(bytes(data["meta"]).decode())
            if meta["version"] != FORMAT_VERSION:
                raise ValueError(f"unsupported trace version {meta['version']}")
            return cls.from_arrays(data, meta)

"""Vmapped scenario sweeps: R chaos replicas in ONE jitted dispatch.

Every statistical experiment in the repo used to run one compiled
dispatch per seed in a host loop — R replicas paid the dispatch +
host-sync tax R times (the SWIM paper's own evaluation method is
multi-trial distributions of detection/dissemination time, produced
serially).  This module batches the replicas into the device: the
single-scenario scan (``runner._scenario_scan_impl``) is ``vmap``-ed
over a leading replica axis and jitted ONCE, so R replicas of a
compiled fault timeline cost one dispatch and one compile.

What may vary per replica (the restricted batch axes):

* the PRNG seed — each replica draws its own segment-exact key
  schedule from its own replica key, so replica r is bit-identical to
  a standalone ``run_scenario`` started from that key (the parity
  contract, tests/test_sweep.py);
* a **loss scale** — replica r's loss schedule is the spec compiled
  with every loss value (base + events + ramp targets) scaled by
  ``loss_scales[r]``;
* a **kill-tick jitter** — replica r's ``kill`` events shift by
  ``kill_jitter[r]`` ticks;
* a **flap jitter** — replica r's ``flap`` windows (at AND until, so
  the duty cycle keeps its expansion count) shift by
  ``flap_jitter[r]`` ticks: R storm phases in one compiled program.

Everything else (tick count, partitions, suspend/resume/revive
timing, cluster size, protocol params) is shared: those change tensor
shapes or static lowering facts and would force one compile per
variant, which is exactly the tax the sweep exists to amortize.

Memory model: the donated scan carry gains a leading replica axis, so
peak HBM is R x state (plus per-tick temporaries, also R-wide inside
one tick) — NOT R separately-resident programs.
``benchmarks/mem_census.py`` measures this shape.

Per-replica parity is by construction: each replica's event tensors,
loss schedule, and key schedule are produced by the SAME
``compile_spec``/``key_schedule`` path a standalone ``run_scenario``
of ``replica_spec(spec, ...)`` would use, and the vmapped scan body is
the same ``_scenario_scan_impl``.
"""

from __future__ import annotations

import functools
import json
import os
from typing import Any, NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ringpop_tpu.models import swim_sim as sim
from ringpop_tpu.obs.ledger import default_ledger
from ringpop_tpu.scenarios import runner
from ringpop_tpu.scenarios.compile import (
    CompiledScenario,
    compile_spec,
    key_schedule,
)
from ringpop_tpu.scenarios.spec import ScenarioSpec
from ringpop_tpu.scenarios.trace import Trace
from ringpop_tpu.stats import Histogram

_dispatches = 0


def dispatch_count() -> int:
    """Jitted sweep-scan invocations so far (test/bench instrumentation)."""
    return _dispatches


def _register_optimization_barrier_batcher() -> None:
    """jax 0.4.37 ships no vmap rule for ``lax.optimization_barrier``
    (the dense step's HBM lifetime fence, swim_sim._phase01_select);
    newer jax added the obvious identity batcher upstream.  Register
    the same rule here (guarded) so the sweep can vmap the step —
    the barrier is semantically the identity, so batching it is just
    binding the primitive on the batched operands."""
    try:
        from jax._src.lax.lax import optimization_barrier_p
        from jax.interpreters import batching
    except ImportError:  # pragma: no cover - future jax moved it: it
        return  # will only do so once the upstream rule exists
    if optimization_barrier_p in batching.primitive_batchers:
        return

    def _batcher(batched_args, batch_dims, **params):
        return optimization_barrier_p.bind(*batched_args), batch_dims

    batching.primitive_batchers[optimization_barrier_p] = _batcher


_register_optimization_barrier_batcher()


# ---------------------------------------------------------------------------
# per-replica spec derivation (the host-side single source of truth)
# ---------------------------------------------------------------------------


def replica_spec(
    spec: ScenarioSpec,
    *,
    kill_jitter: int = 0,
    loss_scale: float = 1.0,
    flap_jitter: int = 0,
) -> ScenarioSpec:
    """Replica r's effective spec: ``kill`` events shifted by
    ``kill_jitter`` ticks, ``flap`` windows (at AND until, so the duty
    cycle keeps its length and expansion count) shifted by
    ``flap_jitter`` ticks, every loss value scaled by ``loss_scale``.

    This is the spec a standalone ``run_scenario`` must be given to
    reproduce replica r bit-for-bit (together with the replica key and
    a base loss of ``params.loss * loss_scale``) — the sweep compiles
    each replica THROUGH this function, so parity is by construction,
    not by re-implementation.
    """
    if kill_jitter == 0 and loss_scale == 1.0 and flap_jitter == 0:
        return spec
    events = []
    for e in spec.events:
        if e.op == "kill" and kill_jitter:
            at = e.at + kill_jitter
            if not 0 <= at < spec.ticks:
                raise ValueError(
                    f"kill jitter {kill_jitter:+d} pushes the kill at tick "
                    f"{e.at} outside [0, {spec.ticks})"
                )
            e = e._replace(at=at)
        if e.op == "flap" and flap_jitter:
            at = e.at + flap_jitter
            until = (e.until if e.until is not None else spec.ticks) + flap_jitter
            if not 0 <= at < until <= spec.ticks:
                raise ValueError(
                    f"flap jitter {flap_jitter:+d} pushes the flap window "
                    f"[{e.at}, {e.until}) outside [0, {spec.ticks})"
                )
            e = e._replace(at=at, until=until)
        if e.op in ("loss", "loss_ramp") and loss_scale != 1.0:
            e = e._replace(p=e.p * loss_scale)
        events.append(e)
    return ScenarioSpec(ticks=spec.ticks, events=tuple(events))


class CompiledSweep(NamedTuple):
    """R per-replica compiled scenarios stacked for one vmapped scan.

    ``base`` carries the static facts shared by construction (ticks, n,
    partition rows, has_revive); the node-event tensors and the loss
    schedule gain a leading replica axis (jitter reorders the
    tick-sorted event rows and scaling changes the loss values —
    everything else is asserted identical at compile time).
    """

    base: CompiledScenario
    replicas: int
    ev_tick: jax.Array  # int32[R, E]
    ev_kind: jax.Array  # int32[R, E]
    ev_node: jax.Array  # int32[R, E]
    loss: jax.Array  # float32[R, ticks]
    # host-side facts for the key schedule and the trace meta
    boundaries: tuple[tuple[int, ...], ...]  # per-replica segment ticks
    loss_scales: tuple[float, ...]
    kill_jitter: tuple[int, ...]
    flap_jitter: tuple[int, ...] = ()


def _norm_axis(
    name: str, values: Sequence[float] | None, replicas: int, default: Any
) -> tuple:
    if values is None:
        return (default,) * replicas
    out = tuple(values)
    if len(out) != replicas:
        raise ValueError(
            f"{name} must have one entry per replica "
            f"(got {len(out)} for {replicas})"
        )
    return out


def compile_sweep(
    spec: ScenarioSpec,
    n: int,
    *,
    replicas: int,
    base_loss: float = 0.0,
    loss_scales: Sequence[float] | None = None,
    kill_jitter: Sequence[int] | None = None,
    flap_jitter: Sequence[int] | None = None,
) -> CompiledSweep:
    """Lower a spec to R stacked replica timelines (host-side, no keys
    drawn — like ``compile_spec``, a failed compile must not advance
    any PRNG)."""
    if replicas < 1:
        raise ValueError(f"replicas must be >= 1 (got {replicas})")
    scales = _norm_axis("loss_scales", loss_scales, replicas, 1.0)
    jitters = _norm_axis("kill_jitter", kill_jitter, replicas, 0)
    fjitters = _norm_axis("flap_jitter", flap_jitter, replicas, 0)
    for s in scales:
        if s < 0.0:
            raise ValueError(f"loss scales must be >= 0 (got {s})")
    if all(s == 1.0 for s in scales) and not any(jitters) and not any(fjitters):
        # the common path (seed-only sweep): every replica's tensors are
        # byte-identical — compile once, broadcast the replica axis
        base = compile_spec(spec, n, base_loss=base_loss)

        def _b(a: jax.Array) -> jax.Array:
            return jnp.broadcast_to(a[None], (replicas,) + a.shape)

        return CompiledSweep(
            base=base,
            replicas=replicas,
            ev_tick=_b(base.ev_tick),
            ev_kind=_b(base.ev_kind),
            ev_node=_b(base.ev_node),
            loss=_b(base.loss),
            boundaries=(base.boundaries,) * replicas,
            loss_scales=scales,
            kill_jitter=jitters,
            flap_jitter=fjitters,
        )
    per: list[CompiledScenario] = []
    for r in range(replicas):
        try:
            spec_r = replica_spec(
                spec, kill_jitter=jitters[r], loss_scale=scales[r],
                flap_jitter=fjitters[r],
            )
            per.append(compile_spec(spec_r, n, base_loss=base_loss * scales[r]))
        except ValueError as e:
            raise ValueError(f"replica {r}: {e}") from e
    base = per[0]
    for r, c in enumerate(per[1:], start=1):
        # jitter/scale may not change shapes or static lowering facts
        if (
            c.ticks != base.ticks
            or c.has_revive != base.has_revive
            or c.ev_tick.shape != base.ev_tick.shape
            or c.has_delay != base.has_delay
            or c.delay_depth != base.delay_depth
        ):
            raise ValueError(f"replica {r} diverges in static scenario shape")
        if not (
            np.array_equal(np.asarray(c.p_tick), np.asarray(base.p_tick))
            and np.array_equal(np.asarray(c.p_gid), np.asarray(base.p_gid))
        ):  # pragma: no cover - jitter/scale cannot touch partitions
            raise ValueError(f"replica {r} diverges in partition rows")
        if (c.faults is None) != (base.faults is None):  # pragma: no cover
            raise ValueError(f"replica {r} diverges in failure-model events")
    return CompiledSweep(
        base=base,
        replicas=replicas,
        ev_tick=jnp.stack([c.ev_tick for c in per]),
        ev_kind=jnp.stack([c.ev_kind for c in per]),
        ev_node=jnp.stack([c.ev_node for c in per]),
        loss=jnp.stack([c.loss for c in per]),
        boundaries=tuple(c.boundaries for c in per),
        loss_scales=scales,
        kill_jitter=jitters,
        flap_jitter=fjitters,
    )


def _schedule_from_key(rkey: jax.Array, compiled: CompiledScenario):
    """One replica's segment-exact schedule as a pure function of its
    replica key: the ``SimCluster._split`` discipline (chained
    ``jax.random.split`` draws, one per segment, fanned per tick) that
    ``compile.key_schedule`` consumes — traceable, so R replicas can
    derive their schedules in ONE vmapped dispatch instead of R x
    (segments + 1) host-looped splits.  Bit-identical per replica to
    ``key_schedule`` over a cluster whose key IS ``rkey`` (threefry is
    elementwise in the key), which is what per-replica parity needs."""
    state = {"key": rkey}

    def split():
        state["key"], sub = jax.random.split(state["key"])
        return sub

    return key_schedule(split, compiled)


@functools.partial(jax.jit, static_argnames=("boundaries", "ticks"))
def _sweep_schedules(rkeys: jax.Array, *, boundaries, ticks) -> jax.Array:
    return jax.vmap(
        lambda k: _schedule_from_key(
            k,
            CompiledScenario(
                ticks=ticks, n=0, ev_tick=None, ev_kind=None, ev_node=None,
                p_tick=None, p_gid=None, loss=None, has_revive=False,
                boundaries=boundaries,
            ),
        )
    )(rkeys)


def sweep_key_schedule(
    replica_keys: Sequence[jax.Array], cs: CompiledSweep
) -> jax.Array:
    """uint32[R, ticks, 2]: replica r's segment-exact schedule, derived
    from replica key r exactly as a standalone cluster whose key IS
    that replica key would derive it (``SimCluster._split`` discipline
    over ``compile.key_schedule``) — the basis of per-replica parity.

    When every replica shares the segment boundaries (no kill jitter,
    or jitter that lands on existing boundaries) all R schedules are
    derived in one vmapped dispatch; per-replica boundaries fall back
    to one schedule per replica."""
    if len(replica_keys) != cs.replicas:
        raise ValueError(
            f"{len(replica_keys)} replica keys for {cs.replicas} replicas"
        )
    if all(b == cs.boundaries[0] for b in cs.boundaries[1:]):
        return _sweep_schedules(
            jnp.stack(list(replica_keys)),
            boundaries=cs.boundaries[0],
            ticks=cs.base.ticks,
        )
    return jnp.stack(
        [
            _schedule_from_key(
                rkey, cs.base._replace(boundaries=cs.boundaries[r])
            )
            for r, rkey in enumerate(replica_keys)
        ]
    )


# ---------------------------------------------------------------------------
# the vmapped scan (one jitted dispatch for all R replicas)
# ---------------------------------------------------------------------------


def _sweep_scan_impl(
    state,
    up,
    responsive,
    adj,
    period,
    ev_tick,
    ev_kind,
    ev_node,
    p_tick,
    p_gid,
    loss,
    keys,
    tick0=None,
    faults=None,
    tr_tensors=None,
    ov=None,
    po=None,
    po_knobs=None,
    sw_knobs=None,
    pv=None,
    pv_at=None,
    pv_node=None,
    *,
    params,
    has_revive: bool,
    traffic=None,
    overload=None,
    policy=None,
    prov: int | None = None,
):
    # ``tick0`` (traced int32 scalar shared by every replica, or None
    # for 0) is the segment offset of the streamed sweep
    # (scenarios/stream.py): closed over rather than batched, so the
    # vmapped body sees the same global tick numbering per segment.
    # ``pv_at``/``pv_node`` (the track-op reservations) are likewise
    # closed over: the spec's slot plan is shared by every replica —
    # only the provenance CARRY batches (each replica infects its own
    # wavefronts from its own chaos).
    def one(state, up, responsive, adj, period, ev_tick, ev_kind, ev_node,
            p_tick, p_gid, loss, keys, faults, tr_tensors, ov, po,
            po_knobs, sw_knobs, pv):
        return runner._scenario_scan_impl(
            state, up, responsive, adj, period,
            ev_tick, ev_kind, ev_node, p_tick, p_gid, loss, keys,
            tr_tensors, tick0, faults, ov, po, po_knobs, sw_knobs,
            pv, pv_at, pv_node,
            params=params, has_revive=has_revive, traffic=traffic,
            overload=overload, policy=policy, prov=prov,
        )

    return jax.vmap(
        one,
        # batched: state/net (leading replica axis, period + overload +
        # policy carries included), node events (jitter reorders rows),
        # loss (scaled), keys, the POLICY KNOBS, and the PROTOCOL KNOBS
        # (sim.SwimKnobs) — traced [R] axes, so a knob sweep is one
        # compile (ROADMAP item 4's frozen-knob refactor: protocol
        # parameters batch exactly like the policy operating points).
        # Shared: partition rows, failure-model tensors, and the traffic
        # workload (one key stream — every replica serves the identical
        # key batches against its own trajectory, exactly what a
        # standalone run_scenario with this workload would serve).
        in_axes=(0, 0, 0, 0, 0, 0, 0, 0, None, None, 0, 0, None, None, 0,
                 0, 0, 0, 0),
    )(
        state,
        up,
        responsive,
        adj,
        period,
        ev_tick,
        ev_kind,
        ev_node,
        p_tick,
        p_gid,
        loss,
        keys,
        faults,
        tr_tensors,
        ov,
        po,
        po_knobs,
        sw_knobs,
        pv,
    )


# The donated scan state carries the leading replica axis: peak HBM is
# R x state plus one tick's R-wide temporaries, measured by
# benchmarks/mem_census.py.
_sweep_scan = jax.jit(
    _sweep_scan_impl,
    static_argnames=(
        "params", "has_revive", "traffic", "overload", "policy", "prov"
    ),
    donate_argnums=(0, 1, 2, 3),
)


def _broadcast_replicas(tree, replicas: int):
    """R stacked copies of every array leaf (fresh device buffers —
    eager broadcast_to materializes, so the copies are donatable and
    the caller's originals stay valid)."""
    return jax.tree_util.tree_map(
        lambda a: jnp.broadcast_to(a[None], (replicas,) + jnp.shape(a)), tree
    )


def _replica_sharding() -> Any | None:
    """A NamedSharding that splits the leading replica axis across the
    local devices, or None on a single device."""
    devices = jax.devices()
    if len(devices) < 2:
        return None
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    return NamedSharding(Mesh(devices, ("replicas",)), PartitionSpec("replicas"))


def precheck_shard(replicas: int) -> None:
    """Static shard-mode rejection, callable before any PRNG key is
    drawn — like ``runner.precheck``, a failed ``run_sweep`` must not
    advance the cluster key (on a single device shard mode is an
    accepted no-op, so there is nothing to reject)."""
    n_dev = len(jax.devices())
    if n_dev > 1 and replicas % n_dev:
        raise ValueError(
            f"shard=True needs replicas ({replicas}) divisible by the "
            f"device count ({n_dev})"
        )


def policy_knob_axes(
    policy: Any, policy_axes: dict[str, Sequence[int]] | None, replicas: int
):
    """The [R]-batched knob arrays the vmapped scan takes: swept knobs
    come from ``policy_axes`` (one int per replica), everything else
    broadcasts the compiled policy's operating point — knobs are traced
    batch axes, never compile-time statics."""
    from ringpop_tpu.policies import core as pol

    if policy is None:
        if policy_axes:
            raise ValueError("policy_axes requires policy=")
        return None
    axes = dict(policy_axes or {})
    vals = {}
    for field in pol.PolicyKnobs._fields:
        if field in axes:
            v = np.asarray(axes.pop(field), np.int32)
            if v.shape != (replicas,):
                raise ValueError(
                    f"policy axis {field!r} must have one value per "
                    f"replica (got shape {v.shape} for {replicas})"
                )
            vals[field] = jnp.asarray(v)
        else:
            vals[field] = jnp.full(
                (replicas,), int(getattr(policy.knobs, field)), jnp.int32
            )
    if axes:
        raise ValueError(
            f"unknown policy axes {sorted(axes)} "
            f"(knobs: {', '.join(pol.PolicyKnobs._fields)})"
        )
    return pol.PolicyKnobs(**vals)


def replica_policy(
    policy: Any, policy_axes: dict[str, Sequence[int]] | None, r: int
):
    """Replica r's effective policy — the spec a standalone
    ``run_scenario(policy=...)`` must be given to reproduce replica r
    bit-for-bit (the ``replica_spec`` contract, extended to the policy
    plane)."""
    if policy is None:
        return None
    knobs = policy.knobs._asdict()
    for key, vals in (policy_axes or {}).items():
        knobs[key] = int(vals[r])
    return policy._replace(knobs=type(policy.knobs)(**knobs))


def param_knob_axes(
    params: Any,
    param_axes: dict[str, Sequence[float | int]] | None,
    replicas: int,
    *,
    n: int,
    backend: str,
    period_active: bool,
    damping: bool,
):
    """The [R]-batched protocol-knob arrays the vmapped scan takes —
    the ``policy_knob_axes`` template applied to ``sim.SwimKnobs``:
    swept knobs come from ``param_axes`` (one host value per replica),
    everything else broadcasts the ``params`` default, each cast to its
    per-site dtype (``sim.SWIM_KNOB_DTYPES``).  Every axis value is
    validated host-side (range, int8 digit budgets at the axis max,
    backend/scenario composition) before a trace sees it."""
    if not param_axes:
        return None
    swp = params.swim if backend == "delta" else params
    axes = dict(param_axes)
    defaults = sim.swim_knob_values(swp)
    knob_values: dict[str, list] = {}
    vals = {}
    for field in sim.SwimKnobs._fields:
        dt = sim.SWIM_KNOB_DTYPES[field]
        if field in axes:
            v = np.asarray(axes.pop(field))
            if v.shape != (replicas,):
                raise ValueError(
                    f"param axis {field!r} must have one value per "
                    f"replica (got shape {v.shape} for {replicas})"
                )
            knob_values[field] = [x.item() for x in v]
            vals[field] = jnp.asarray(v, dt)
        else:
            vals[field] = jnp.full((replicas,), defaults[field], dt)
    if axes:
        raise ValueError(
            f"unknown param axes {sorted(axes)} "
            f"(knobs: {', '.join(sim.SwimKnobs._fields)})"
        )
    runner.validate_param_knobs(
        n, swp, knob_values, backend=backend,
        period_active=period_active, damping=damping,
    )
    return sim.SwimKnobs(**vals)


def replica_param_knobs(
    param_axes: dict[str, Sequence[float | int]] | None, r: int
) -> dict[str, float | int] | None:
    """Replica r's effective knob overrides — the ``param_knobs`` dict a
    standalone ``run_scenario`` must be given to reproduce replica r
    bit-for-bit (the ``replica_spec`` contract, extended to the traced
    protocol knobs)."""
    if not param_axes:
        return None
    out: dict[str, float | int] = {}
    for key, vals in param_axes.items():
        v = vals[r]
        kind = jnp.dtype(sim.SWIM_KNOB_DTYPES[key]).kind
        out[key] = float(v) if kind == "f" else int(v)
    return out


def run_sweep_compiled(
    state: Any,
    net: Any,
    keys: jax.Array,
    cs: CompiledSweep,
    params: Any,
    *,
    shard: bool = False,
    traffic: Any | None = None,
    policy: Any | None = None,
    policy_axes: dict[str, Sequence[int]] | None = None,
    param_axes: dict[str, Sequence[float | int]] | None = None,
    program_tag: str | None = None,
) -> tuple[Any, Any, dict[str, jax.Array]]:
    """One jitted call: R replicas of the compiled scenario.

    Returns (final states [R, ...], final nets [R, ...], telemetry
    stacks [R, ticks]).  ``state``/``net`` are the UNBATCHED starting
    point; they are broadcast to R fresh device copies here (the
    copies are donated to the scan; the caller's state is untouched).

    ``traffic`` (a pre-lowered ``CompiledTraffic``) co-runs the key
    workload in every replica — tensors shared across the replica axis
    (one workload stream), so replica r's serving counters are exactly
    what a standalone ``run_scenario(spec_r, traffic=ct)`` from its
    replica key would report: incident sweeps emit R serving
    scorecards in one dispatch (``SweepTrace.serving_summary``).

    ``shard=True`` splits the replica axis across the local devices
    (replicas are data-parallel by construction — no cross-replica
    communication exists in the scan), so a multi-chip mesh runs
    R / n_devices replicas per chip; ignored on a single device.
    Requires R divisible by the device count.

    ``param_axes`` batches traced PROTOCOL knobs (``sim.SwimKnobs``
    names) one value per replica, next to the seed/loss/jitter/policy
    axes: an R-point knob grid compiles once and runs in this one
    dispatch.  Replica r reproduces a standalone
    ``run_scenario(param_knobs=replica_param_knobs(param_axes, r))``
    bit-for-bit.

    ``program_tag`` renames this dispatch's ledger program to
    ``run_sweep:<tag>``: a tuner running several incident arms (whose
    event tensors differ in shape, so they are distinct programs by
    construction) tags each arm so the ledger's ``recompile_cause``
    attribution stays scoped to WITHIN-arm drift instead of flagging
    the arms against each other.
    """
    global _dispatches
    if keys.shape[:2] != (cs.replicas, cs.base.ticks):
        raise ValueError(
            f"key schedule is {keys.shape[:2]} for "
            f"({cs.replicas} replicas, {cs.base.ticks} ticks)"
        )
    adj = runner.precheck(state, net, cs.base, params)
    runner.precheck_policy(policy, traffic, net)
    runner.precheck_prov(cs.base, net, params)
    traffic = runner.overload_traffic(traffic, cs.base)
    traffic = runner.policy_traffic(traffic, policy)
    state, period, ov = runner.prepare_faults(state, net, cs.base, params)
    pv, pv_at, pv_node = runner.prepare_prov(cs.base, net, params)
    r = cs.replicas
    po = None
    knobs = policy_knob_axes(policy, policy_axes, r)
    if policy is not None:
        po = runner.prepare_policy(
            policy, net, cs.base.n, traffic.static.max_retries
        )
    sw_knobs = param_knob_axes(
        params, param_axes, r,
        n=cs.base.n,
        backend="delta" if hasattr(params, "wire_cap") else "dense",
        period_active=period is not None,
        damping=getattr(state, "damp", None) is not None,
    )
    batched = [
        _broadcast_replicas(state, r),
        _broadcast_replicas(net.up, r),
        _broadcast_replicas(net.responsive, r),
        _broadcast_replicas(adj, r),
        _broadcast_replicas(period, r),
    ]
    ov_b = _broadcast_replicas(ov, r)
    po_b = _broadcast_replicas(po, r)
    pv_b = _broadcast_replicas(pv, r)
    if shard:
        precheck_shard(r)
        sharding = _replica_sharding()
        if sharding is not None:
            batched = [
                jax.tree_util.tree_map(
                    lambda a: jax.device_put(a, sharding), t
                )
                for t in batched
            ]
            keys = jax.device_put(keys, sharding)
            ov_b = jax.tree_util.tree_map(
                lambda a: jax.device_put(a, sharding), ov_b
            )
            po_b = jax.tree_util.tree_map(
                lambda a: jax.device_put(a, sharding), po_b
            )
            pv_b = jax.tree_util.tree_map(
                lambda a: jax.device_put(a, sharding), pv_b
            )
            knobs = jax.tree_util.tree_map(
                lambda a: jax.device_put(a, sharding), knobs
            )
            sw_knobs = jax.tree_util.tree_map(
                lambda a: jax.device_put(a, sharding), sw_knobs
            )
    _dispatches += 1
    meta = {
        "backend": "delta" if hasattr(params, "wire_cap") else "dense",
        "n": cs.base.n,
        "ticks": cs.base.ticks,
        "replicas": r,
    }
    if traffic is not None:
        meta["traffic_m"] = traffic.static.m
    if policy is not None:
        meta["policy"] = policy.name
    if param_axes:
        meta["param_axes"] = sorted(param_axes)
    if cs.base.trace_rumors:
        meta["trace_rumors"] = cs.base.trace_rumors
    # routed through the dispatch ledger (obs/ledger.py): a call-through
    # when disabled, a recorded compile/execute + footprint row when on
    states, up, resp, adj, period, ov, po, pv, ys = default_ledger().dispatch(
        "run_sweep" if program_tag is None else f"run_sweep:{program_tag}",
        _sweep_scan,
        *batched,
        cs.ev_tick,
        cs.ev_kind,
        cs.ev_node,
        cs.base.p_tick,
        cs.base.p_gid,
        cs.loss,
        keys,
        None,
        cs.base.faults,
        traffic.tensors if traffic is not None else None,
        ov_b,
        po_b,
        knobs,
        sw_knobs,
        pv_b,
        pv_at,
        pv_node,
        params=params,
        has_revive=cs.base.has_revive,
        traffic=traffic.static if traffic is not None else None,
        overload=cs.base.overload,
        policy=policy.config if policy is not None else None,
        prov=cs.base.trace_rumors or None,
        _meta=meta,
    )
    net_kw = {}
    if ov is not None:
        net_kw = dict(ov_cnt=ov[0], ov_gray=ov[1])
    if po is not None:
        net_kw.update(
            po_press=po[0], po_shed=po[1], po_quar=po[2],
            po_sends_w=po[3], po_deliv_w=po[4], po_retry_cap=po[5],
        )
    if pv is not None:
        net_kw.update(
            pv_slot=pv.slot, pv_tickv=pv.tickv, pv_wits=pv.wits,
            pv_first=pv.first, pv_parent=pv.parent, pv_knows=pv.knows,
        )
    nets = type(net)(up=up, responsive=resp, adj=adj, period=period, **net_kw)
    return states, nets, ys


# ---------------------------------------------------------------------------
# SweepTrace: R stacked per-replica telemetry series
# ---------------------------------------------------------------------------

SWEEP_FORMAT_VERSION = 1

_REQUIRED = ("converged", "live", "loss")


class SweepTrace:
    """Per-tick telemetry of R replicas: every ``Trace`` series with a
    leading replica axis, plus the per-replica sweep parameters and
    replica keys (enough to re-run any replica standalone)."""

    def __init__(
        self,
        *,
        metrics: dict[str, np.ndarray],
        converged: np.ndarray,
        live: np.ndarray,
        loss: np.ndarray,
        n: int,
        backend: str,
        replica_keys: np.ndarray,
        loss_scales: Sequence[float],
        kill_jitter: Sequence[int],
        flap_jitter: Sequence[int] | None = None,
        start_tick: int = 0,
        spec: dict[str, Any] | None = None,
        planes: dict[str, np.ndarray] | None = None,
    ):
        self.metrics = {k: np.asarray(v) for k, v in metrics.items()}
        # histogram planes: [R, ticks, B] rows (scenarios/trace.py)
        self.planes = {k: np.asarray(v) for k, v in (planes or {}).items()}
        self.converged = np.asarray(converged, dtype=bool)
        self.live = np.asarray(live, dtype=np.int32)
        self.loss = np.asarray(loss, dtype=np.float32)
        self.n = int(n)
        self.backend = str(backend)
        self.replica_keys = np.asarray(replica_keys)
        self.loss_scales = tuple(float(s) for s in loss_scales)
        self.kill_jitter = tuple(int(j) for j in kill_jitter)
        self.flap_jitter = tuple(
            int(j) for j in (flap_jitter if flap_jitter else (0,) * len(self.kill_jitter))
        )
        self.start_tick = int(start_tick)
        self.spec = spec
        # in-memory only (run_sweep attaches them; not serialized)
        self.final_states: Any = None
        self.final_nets: Any = None

    @property
    def replicas(self) -> int:
        return int(self.converged.shape[0])

    @property
    def ticks(self) -> int:
        return int(self.converged.shape[1])

    def validate(self) -> "SweepTrace":
        r, t = self.converged.shape if self.converged.ndim == 2 else (0, 0)
        if r < 1 or t < 1:
            raise ValueError("sweep trace needs [R, ticks]-shaped series")
        for name in _REQUIRED:
            arr = getattr(self, name)
            if arr.shape != (r, t):
                raise ValueError(f"sweep series {name!r} is not [{r}, {t}]-shaped")
        for name, arr in self.metrics.items():
            if arr.shape != (r, t):
                raise ValueError(f"sweep metric {name!r} is not [{r}, {t}]-shaped")
        for name, arr in self.planes.items():
            if arr.ndim != 3 or arr.shape[:2] != (r, t):
                raise ValueError(
                    f"sweep plane {name!r} is not [{r}, {t}, B]-shaped"
                )
        if self.replica_keys.shape[0] != r:
            raise ValueError("replica_keys does not cover every replica")
        if (
            len(self.loss_scales) != r
            or len(self.kill_jitter) != r
            or len(self.flap_jitter) != r
        ):
            raise ValueError("sweep params do not cover every replica")
        if not np.all((self.live >= 0) & (self.live <= self.n)):
            raise ValueError("sweep live counts outside [0, n]")
        return self

    def replica(self, r: int) -> Trace:
        """Replica r as a standalone ``Trace`` (same series, spec =
        that replica's effective spec when derivable)."""
        spec = self.spec
        if spec is not None and (
            self.kill_jitter[r]
            or self.flap_jitter[r]
            or self.loss_scales[r] != 1.0
        ):
            spec = replica_spec(
                ScenarioSpec.from_dict(spec),
                kill_jitter=self.kill_jitter[r],
                loss_scale=self.loss_scales[r],
                flap_jitter=self.flap_jitter[r],
            ).to_dict()
        return Trace(
            metrics={k: v[r] for k, v in self.metrics.items()},
            planes={k: v[r] for k, v in self.planes.items()},
            converged=self.converged[r],
            live=self.live[r],
            loss=self.loss[r],
            n=self.n,
            backend=self.backend,
            start_tick=self.start_tick,
            spec=spec,
        )

    @classmethod
    def concat_ticks(
        cls, slabs, *, spec: dict[str, Any] | None = None
    ) -> "SweepTrace":
        """Reassemble contiguous per-segment sweep slabs (a streamed
        sweep's segment-store content, scenarios/stream.py) along the
        tick axis — bit-identical to the [R, T] stacks the unsegmented
        vmapped scan would have produced.  Slabs must share the replica
        axis (same replica keys and sweep parameters) and be
        tick-contiguous."""
        slabs = list(slabs)
        if not slabs:
            raise ValueError("no slabs to concatenate")
        first = slabs[0]
        expect = first.start_tick
        for s in slabs:
            if s.n != first.n or s.backend != first.backend:
                raise ValueError("slabs disagree on n/backend")
            if set(s.metrics) != set(first.metrics):
                raise ValueError("slabs disagree on metric series")
            if set(s.planes) != set(first.planes):
                raise ValueError("slabs disagree on histogram planes")
            if (
                s.replicas != first.replicas
                or not np.array_equal(s.replica_keys, first.replica_keys)
                or s.loss_scales != first.loss_scales
                or s.kill_jitter != first.kill_jitter
                or s.flap_jitter != first.flap_jitter
            ):
                raise ValueError("slabs disagree on the replica axis")
            if s.start_tick != expect:
                raise ValueError(
                    f"slab at start_tick {s.start_tick} is not contiguous "
                    f"(expected {expect})"
                )
            expect += s.ticks
        return cls(
            metrics={
                k: np.concatenate([s.metrics[k] for s in slabs], axis=1)
                for k in first.metrics
            },
            planes={
                k: np.concatenate([s.planes[k] for s in slabs], axis=1)
                for k in first.planes
            },
            converged=np.concatenate([s.converged for s in slabs], axis=1),
            live=np.concatenate([s.live for s in slabs], axis=1),
            loss=np.concatenate([s.loss for s in slabs], axis=1),
            n=first.n,
            backend=first.backend,
            replica_keys=first.replica_keys,
            loss_scales=first.loss_scales,
            kill_jitter=first.kill_jitter,
            flap_jitter=first.flap_jitter,
            start_tick=first.start_tick,
            spec=spec if spec is not None else first.spec,
        )

    # -- per-replica outcome ticks (the sweep's headline statistics) --------

    def detect_ticks(self, metric: str = "faulty_declared") -> np.ndarray:
        """int[R]: first tick with a faulty declaration, or -1."""
        hits = self.metrics[metric] > 0
        any_ = hits.any(axis=1)
        return np.where(any_, hits.argmax(axis=1), -1).astype(np.int64)

    def heal_ticks(self) -> np.ndarray:
        """int[R]: first tick from which ``converged`` holds through the
        end of the run (the cluster healed and stayed healed), or -1."""
        # length of the all-True suffix, per replica
        rev = self.converged[:, ::-1]
        suffix = np.where(
            rev.all(axis=1), self.ticks, (~rev).argmax(axis=1)
        )
        return np.where(suffix > 0, self.ticks - suffix, -1).astype(np.int64)

    def summary(self) -> dict[str, dict[str, float]]:
        """Sweep-level stats in ``stats.Histogram.print_obj`` key shape:
        the detection- and heal-tick distributions across replicas
        (undetected/unhealed replicas are excluded from the histograms
        and counted separately)."""
        out: dict[str, dict[str, Any]] = {}
        for name, ticks in (
            ("detect_tick", self.detect_ticks()),
            ("heal_tick", self.heal_ticks()),
        ):
            got = ticks[ticks >= 0]
            hist = Histogram(sample_size=max(len(got), 1))
            for v in got:
                hist.update(float(v))
            out[name] = hist.print_obj()
        out["replicas"] = {
            "count": self.replicas,
            "detected": int((self.detect_ticks() >= 0).sum()),
            "healed": int((self.heal_ticks() >= 0).sum()),
            "converged_final": int(self.converged[:, -1].sum()),
        }
        return out

    def serving_summary(self) -> list[dict[str, Any]] | None:
        """Per-replica serving scorecards (traffic-coupled sweeps; None
        when the sweep served no workload): goodput, retry
        amplification, latency percentiles from the replica's histogram
        plane when the SLO plane ran, and the overload peaks when the
        feedback loop ran — one row per replica, the incident sweep's
        one-dispatch answer to "how did serving fare per seed"."""
        if "lookups" not in self.metrics:
            return None
        rows = []
        for r in range(self.replicas):
            from ringpop_tpu.traffic.engine import total_sends

            m = {k: v[r] for k, v in self.metrics.items()}
            lookups = int(m["lookups"].sum())
            delivered = int(m["delivered"].sum())
            sends = total_sends(m)
            row: dict[str, Any] = {
                "replica": r,
                "lookups": lookups,
                "delivered": delivered,
                "goodput": delivered / lookups if lookups else 0.0,
                "misroutes": int(m["misroutes"].sum()),
                "amplification": sends / delivered if delivered else 0.0,
            }
            if "gray_timeouts" in m:
                row["gray_timeouts"] = int(m["gray_timeouts"].sum())
            if "ov_gray_nodes" in m:
                row["ov_gray_peak"] = int(m["ov_gray_nodes"].max())
                row["ov_pressure_peak"] = int(m["ov_pressure_max"].max())
            if "policy_shed" in m:
                row["policy_shed"] = int(m["policy_shed"].sum())
                row["policy_quarantine_peak"] = int(
                    m["policy_quarantined"].max()
                )
                row["policy_retry_cap_min"] = int(
                    m["policy_retry_cap"].min()
                )
            if "lat_hist_ms" in self.planes:
                from ringpop_tpu.traffic.latency import hist_stats

                agg = hist_stats(self.planes["lat_hist_ms"][r].sum(axis=0))
                row["lat_p50_ms"] = agg["median"]
                row["lat_p95_ms"] = agg["p95"]
                row["lat_p99_ms"] = agg["p99"]
            rows.append(row)
        return rows

    # -- npz round trip ------------------------------------------------------

    def to_arrays(self, prefix: str = "") -> dict[str, np.ndarray]:
        arrays = {
            f"{prefix}converged": self.converged,
            f"{prefix}live": self.live,
            f"{prefix}loss": self.loss,
            f"{prefix}replica_keys": self.replica_keys,
        }
        for name, arr in self.metrics.items():
            arrays[f"{prefix}m.{name}"] = arr
        for name, arr in self.planes.items():
            arrays[f"{prefix}p.{name}"] = arr
        return arrays

    def meta(self) -> dict[str, Any]:
        return {
            "version": SWEEP_FORMAT_VERSION,
            "kind": "sweep",
            "n": self.n,
            "backend": self.backend,
            "start_tick": self.start_tick,
            "loss_scales": list(self.loss_scales),
            "kill_jitter": list(self.kill_jitter),
            "flap_jitter": list(self.flap_jitter),
            "spec": self.spec,
        }

    @classmethod
    def from_arrays(
        cls, data: Any, meta: dict[str, Any], prefix: str = ""
    ) -> "SweepTrace":
        keys = list(getattr(data, "files", data.keys()))
        metrics = {
            key[len(prefix) + 2:]: np.asarray(data[key])
            for key in keys
            if key.startswith(f"{prefix}m.")
        }
        planes = {
            key[len(prefix) + 2:]: np.asarray(data[key])
            for key in keys
            if key.startswith(f"{prefix}p.")
        }
        return cls(
            metrics=metrics,
            planes=planes,
            converged=np.asarray(data[f"{prefix}converged"]),
            live=np.asarray(data[f"{prefix}live"]),
            loss=np.asarray(data[f"{prefix}loss"]),
            n=meta["n"],
            backend=meta["backend"],
            replica_keys=np.asarray(data[f"{prefix}replica_keys"]),
            loss_scales=meta["loss_scales"],
            kill_jitter=meta["kill_jitter"],
            flap_jitter=meta.get("flap_jitter"),
            start_tick=meta.get("start_tick", 0),
            spec=meta.get("spec"),
        )

    def save(self, path: str) -> None:
        arrays = self.to_arrays()
        arrays["meta"] = np.frombuffer(
            json.dumps(self.meta()).encode(), dtype=np.uint8
        )
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            np.savez_compressed(f, **arrays)
        os.replace(tmp, path)  # atomic, like Trace.save

    @classmethod
    def load(cls, path: str) -> "SweepTrace":
        with np.load(path, allow_pickle=False) as data:
            meta = json.loads(bytes(data["meta"]).decode())
            if meta.get("kind") != "sweep":
                raise ValueError("not a sweep trace (use scenarios.Trace.load)")
            if meta["version"] != SWEEP_FORMAT_VERSION:
                raise ValueError(
                    f"unsupported sweep trace version {meta['version']}"
                )
            return cls.from_arrays(data, meta)

"""Jittable FarmHash32 (Fingerprint32) on padded byte buffers.

Bit-identical to ``ops/farmhash.py`` / ``ops/_farmhash.c`` (and therefore to
the reference's farmhash checksums, lib/membership.js:57, lib/ring.js:29).
All arithmetic is uint32 with natural wraparound; rotations are right-rotates.

The kernel hashes a *variable-length* byte string stored in a *fixed-shape*
uint8 buffer (padded), with the true length passed separately — the XLA-
friendly shape discipline.  ``farmhash32_jax`` is vmappable over a batch of
buffers, which is how per-node membership-checksum batches are computed on
device (see ops/checksum.py).

Design notes (TPU):
 - no data-dependent Python control flow: the three small-length variants and
   the long path are all computed branchlessly and selected by length;
 - the long-path main loop is a ``lax.fori_loop`` over the *static* maximum
   iteration count with predicated updates, so one compiled kernel serves all
   lengths up to the buffer size;
 - byte fetches are gathers; for batched use XLA fuses them into a handful of
   vectorized loads.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

_C1 = jnp.uint32(0xCC9E2D51)
_C2 = jnp.uint32(0x1B873593)
_MIX1 = jnp.uint32(0x85EBCA6B)
_MIX2 = jnp.uint32(0xC2B2AE35)
_MAGIC = jnp.uint32(0xE6546B64)


def _rotr(v, s: int):
    if s == 0:
        return v
    return (v >> jnp.uint32(s)) | (v << jnp.uint32(32 - s))


def _fmix(h):
    h = h ^ (h >> jnp.uint32(16))
    h = h * _MIX1
    h = h ^ (h >> jnp.uint32(13))
    h = h * _MIX2
    h = h ^ (h >> jnp.uint32(16))
    return h


def _mur(a, h):
    a = a * _C1
    a = _rotr(a, 17)
    a = a * _C2
    h = h ^ a
    h = _rotr(h, 19)
    return h * jnp.uint32(5) + _MAGIC


def _fetch32(buf, i):
    """Little-endian uint32 load at dynamic byte offset ``i`` (clamped)."""
    i = jnp.clip(i, 0, buf.shape[0] - 4)
    w = lax.dynamic_slice(buf, (i,), (4,)).astype(jnp.uint32)
    return w[0] | (w[1] << 8) | (w[2] << 16) | (w[3] << 24)


def _hash_len_0_to_4(buf, n):
    # b = b * c1 + signed(s[i]); c ^= b  -- for i < n (n <= 4)
    b = jnp.uint32(0)
    c = jnp.uint32(9)
    for i in range(4):
        v = buf[i].astype(jnp.int8).astype(jnp.int32).astype(jnp.uint32)
        nb = b * _C1 + v
        nc = c ^ nb
        take = i < n
        b = jnp.where(take, nb, b)
        c = jnp.where(take, nc, c)
    return _fmix(_mur(b, _mur(n.astype(jnp.uint32), c)))


def _hash_len_5_to_12(buf, n):
    nu = n.astype(jnp.uint32)
    a = nu + _fetch32(buf, 0)
    b = nu * jnp.uint32(5) + _fetch32(buf, n - 4)
    c = jnp.uint32(9) + _fetch32(buf, (n >> 1) & 4)
    d = nu * jnp.uint32(5)
    return _fmix(_mur(c, _mur(b, _mur(a, d))))


def _hash_len_13_to_24(buf, n):
    a = _fetch32(buf, (n >> 1) - 4)
    b = _fetch32(buf, 4)
    c = _fetch32(buf, n - 8)
    d = _fetch32(buf, n >> 1)
    e = _fetch32(buf, 0)
    f = _fetch32(buf, n - 4)
    h = d * _C1 + n.astype(jnp.uint32)
    a = _rotr(a, 12) + f
    h = _mur(c, h) + a
    a = _rotr(a, 3) + c
    h = _mur(e, h) + a
    a = _rotr(a + f, 12) + d
    h = _mur(b, h) + a
    return _fmix(h)


def _hash_len_gt_24(buf, n):
    nu = n.astype(jnp.uint32)
    h = nu
    g = _C1 * nu
    f = g
    a0 = _rotr(_fetch32(buf, n - 4) * _C1, 17) * _C2
    a1 = _rotr(_fetch32(buf, n - 8) * _C1, 17) * _C2
    a2 = _rotr(_fetch32(buf, n - 16) * _C1, 17) * _C2
    a3 = _rotr(_fetch32(buf, n - 12) * _C1, 17) * _C2
    a4 = _rotr(_fetch32(buf, n - 20) * _C1, 17) * _C2
    h = h ^ a0
    h = _rotr(h, 19)
    h = h * jnp.uint32(5) + _MAGIC
    h = h ^ a2
    h = _rotr(h, 19)
    h = h * jnp.uint32(5) + _MAGIC
    g = g ^ a1
    g = _rotr(g, 19)
    g = g * jnp.uint32(5) + _MAGIC
    g = g ^ a3
    g = _rotr(g, 19)
    g = g * jnp.uint32(5) + _MAGIC
    f = f + a4
    f = _rotr(f, 19) + jnp.uint32(113)
    iters = (n - 1) // 20
    max_iters = (buf.shape[0] - 1) // 20

    def body(i, state):
        h, g, f = state
        off = i * 20
        a = _fetch32(buf, off)
        b = _fetch32(buf, off + 4)
        c = _fetch32(buf, off + 8)
        d = _fetch32(buf, off + 12)
        e = _fetch32(buf, off + 16)
        nh = h + a
        ng = g + b
        nf = f + c
        nh = _mur(d, nh) + e
        ng = _mur(c, ng) + a
        nf = _mur(b + e * _C1, nf) + d
        nf = nf + ng
        ng = ng + nf
        take = i < iters
        return (
            jnp.where(take, nh, h),
            jnp.where(take, ng, g),
            jnp.where(take, nf, f),
        )

    h, g, f = lax.fori_loop(0, max_iters, body, (h, g, f))
    g = _rotr(g, 11) * _C1
    g = _rotr(g, 17) * _C1
    f = _rotr(f, 11) * _C1
    f = _rotr(f, 17) * _C1
    h = _rotr(h + g, 19)
    h = h * jnp.uint32(5) + _MAGIC
    h = _rotr(h, 17) * _C1
    h = _rotr(h + f, 19)
    h = h * jnp.uint32(5) + _MAGIC
    h = _rotr(h, 17) * _C1
    return h


def farmhash32_jax(buf: jax.Array, n: jax.Array) -> jax.Array:
    """Fingerprint32 of ``buf[:n]``; ``buf`` is uint8[L] (L static, >= 25)."""
    if buf.shape[0] < 25:
        raise ValueError("pad buffer to at least 25 bytes")
    n = n.astype(jnp.int32)
    h04 = _hash_len_0_to_4(buf, n)
    h512 = _hash_len_5_to_12(buf, n)
    h1324 = _hash_len_13_to_24(buf, n)
    hlong = _hash_len_gt_24(buf, n)
    return jnp.where(
        n <= 4, h04, jnp.where(n <= 12, h512, jnp.where(n <= 24, h1324, hlong))
    )


@jax.jit
def farmhash32_batch_jax(bufs: jax.Array, lens: jax.Array) -> jax.Array:
    """Vmapped Fingerprint32: bufs uint8[B, L], lens int32[B] -> uint32[B]."""
    return jax.vmap(farmhash32_jax)(bufs, lens)

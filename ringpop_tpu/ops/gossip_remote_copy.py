"""Point-to-point gossip plane: ring collectives for the sharded step.

The mesh-2 sharded dense step's collective census (PR 15) counts 75
full member-plane all-gathers per step — 30 in ``swim.recv_merge``
alone, where the sorted merge's [N, N] row permutation is re-replicated
on every call.  This module replaces those gathers with
neighbor-exchange ring steps inside ``shard_map``: each shard holds a
contiguous slice of the member axis, and inter-shard claims/acks hop
around the ring device-to-device instead of being broadcast.  In the
post-SPMD HLO the member plane never appears as an ``all-gather``
operand again — the partitioning auditor's ``p2p_only`` fence
(analysis/partitioning.py) pins that forever.

Three primitives, all exact (bit-identical to the gather forms they
replace — every one is a permutation/selection, never a re-association
of floating point):

* ``ring_recv_merge(t_safe, fwd_ok, claim_rows)`` — the receiver merge
  (``swim_sim._receiver_merge``).  Claim rows circulate the ring; each
  hop every shard folds the rows addressed to its own receivers with a
  local scatter-max, so the [N, N] permutation/merge intermediates of
  the sorted form stay shard-local ([N/D, N]) instead of being
  re-replicated 30x per step.
* ``ring_fetch_rows(plane, idx)`` — a row gather ``plane[idx]`` where
  ``plane`` is row-sharded and ``idx`` is aligned with the member axis
  (one fetch per local row).  The plane's shard blocks circulate; each
  shard picks its rows out of the passing block.
* ``ring_fetch_global(plane, idx)`` — same, but ``idx`` is replicated
  and so is the output (the traffic plane's ``mask_all[viewer]``
  lookups, which every host serves identically).

The per-hop transport is swappable at trace time via
``RINGPOP_GOSSIP_HOP``:

* ``ppermute`` — ``lax.ppermute`` (lowers to ``collective-permute``,
  which the census already classifies as point-to-point).  The only
  executable form on CPU virtual meshes, hence the default off-TPU.
* ``pallas`` — a Pallas kernel built on
  ``pltpu.make_async_remote_copy`` with paired send/recv DMA
  semaphores (the SNIPPETS right-permute pattern): each shard starts
  one async copy of its block into its right neighbor's output buffer
  and waits both semaphores.  Lowers to a ``tpu_custom_call`` the
  census reports as a DMA custom-call, not a collective at all.
  Remote DMA has no interpret-mode emulation on CPU in the pinned
  jax, so off-TPU coverage is structural: the kernel must lower for
  the TPU platform (tests/test_gossip_remote_copy.py) while the
  padding math is exercised through a local ``make_async_copy``
  kernel in interpret mode.
* ``auto`` (default) — ``pallas`` iff ``jax.default_backend()`` is
  TPU, else ``ppermute``.

Like the ``RINGPOP_RECV_MERGE`` knob, the env var is read at trace
time; changing it requires ``jax.clear_caches()``.

The mesh/axis the primitives run over comes from an ambient trace-time
context, not an argument: ``parallel/mesh.py`` wraps its traces in
``ring_mesh(mesh)`` and the models ask ``active_ring()``.  This keeps
models/ free of any parallel/ import (the same layering rule that puts
this file in ops/).
"""

from __future__ import annotations

import contextlib
import functools
import os
from typing import Any, Iterator

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from ringpop_tpu.obs import annotate

# ---------------------------------------------------------------------------
# Ambient ring context (trace-time, same stack idiom as _RECV_MERGE_FORCE)
# ---------------------------------------------------------------------------

_RING_STACK: list[tuple[Mesh, str]] = []


@contextlib.contextmanager
def ring_mesh(mesh: Mesh, axis: str | None = None) -> Iterator[None]:
    """Make ``mesh`` the ambient gossip ring for traces in this block.

    ``axis`` defaults to the mesh's (single) axis name.  Re-entrant:
    the innermost context wins, so a nested trace over a different
    mesh (e.g. the audit CLI compiling mesh-2 and mesh-4 entries back
    to back) never leaks.
    """
    if axis is None:
        (axis,) = mesh.axis_names
    _RING_STACK.append((mesh, axis))
    try:
        yield
    finally:
        _RING_STACK.pop()


def active_ring() -> tuple[Mesh, str] | None:
    """The innermost ``ring_mesh`` context, or None outside any."""
    return _RING_STACK[-1] if _RING_STACK else None


def ring_devices() -> int:
    """Ring size of the active context (0 when no ring is active)."""
    ring = active_ring()
    if ring is None:
        return 0
    mesh, axis = ring
    return mesh.shape[axis]


# ---------------------------------------------------------------------------
# Hop transport: one rightward ring shift of each shard's block
# ---------------------------------------------------------------------------


def hop_mode() -> str:
    """Resolve RINGPOP_GOSSIP_HOP to the transport for this trace."""
    raw = os.environ.get("RINGPOP_GOSSIP_HOP", "auto").strip().lower()
    if raw not in ("auto", "pallas", "ppermute"):
        raise ValueError(
            f"RINGPOP_GOSSIP_HOP={raw!r}: want auto, pallas or ppermute"
        )
    if raw == "auto":
        return "pallas" if jax.default_backend() == "tpu" else "ppermute"
    return raw


def ring_perm(d: int) -> list[tuple[int, int]]:
    """The rightward ring permutation: shard i's block goes to i+1."""
    return [(i, (i + 1) % d) for i in range(d)]


def block_origin(me: int, hop: int, d: int) -> int:
    """Which shard's block ``me`` holds after ``hop`` rightward shifts.

    Host-side mirror of the traced arithmetic in the fetch primitives;
    the unit tests pin both against each other.
    """
    return (me - hop) % d


def hop_schedule(d: int) -> list[list[tuple[int, int]]]:
    """Per-hop (sender, receiver) pairs for a full D-1-hop circulation.

    Every hop is the same rightward permutation; the schedule form
    exists so tests can assert the pairing invariants (each shard
    sends exactly once and receives exactly once per hop — one send
    semaphore and one recv semaphore satisfied per kernel launch —
    and over the full schedule each shard has seen every block).
    """
    return [ring_perm(d) for _ in range(d - 1)]


# -- Pallas transport -------------------------------------------------------

_SUBLANE = 8  # int32 sublane tile
_LANE = 128


def _pad_tile(r: int, c: int) -> tuple[int, int]:
    """Mosaic-aligned (rows, cols) for an int32 [r, c] block.

    The ragged last-shard case (block dims not tile-aligned, e.g.
    n=48 over 4 shards at lane width 128) pads up; the wrapper slices
    the pad back off after the copy.
    """
    return -(-r // _SUBLANE) * _SUBLANE, -(-c // _LANE) * _LANE


_CompilerParams = getattr(pltpu, "TPUCompilerParams", None) or getattr(
    pltpu, "CompilerParams", None
)
_MEMSPACE_ANY = getattr(pltpu.TPUMemorySpace, "ANY", None) or getattr(
    pltpu, "ANY"
)


def _hop_kernel(d: int, axis: str, in_ref, out_ref, send_sem, recv_sem):
    """Send my block to the right neighbor; wait for the left's.

    One ``make_async_remote_copy`` per launch: ``send_sem`` tracks my
    outbound DMA, ``recv_sem`` the inbound one the left neighbor
    started, and ``wait()`` blocks on both — the pairing the unit
    tests assert on the schedule.  The barrier semaphore up front
    keeps a fast shard from writing into a neighbor still in a prior
    kernel (pallas guide ring idiom).
    """
    me = jax.lax.axis_index(axis)
    right = jax.lax.rem(me + 1, d)
    left = jax.lax.rem(me + d - 1, d)

    barrier = pltpu.get_barrier_semaphore()
    pltpu.semaphore_signal(
        barrier, 1, device_id=left, device_id_type=pltpu.DeviceIdType.LOGICAL
    )
    pltpu.semaphore_signal(
        barrier, 1, device_id=right, device_id_type=pltpu.DeviceIdType.LOGICAL
    )
    pltpu.semaphore_wait(barrier, 2)

    copy = pltpu.make_async_remote_copy(
        src_ref=in_ref,
        dst_ref=out_ref,
        send_sem=send_sem,
        recv_sem=recv_sem,
        device_id=right,
        device_id_type=pltpu.DeviceIdType.LOGICAL,
    )
    copy.start()
    copy.wait()


def _hop_pallas_2d(x2: jax.Array, axis: str, d: int) -> jax.Array:
    """Rightward shift of an int32 [r, c] block via remote DMA."""
    r, c = x2.shape
    pr, pc = _pad_tile(r, c)
    if (pr, pc) != (r, c):
        x2 = jnp.pad(x2, ((0, pr - r), (0, pc - c)))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=0,
        in_specs=[pl.BlockSpec(memory_space=_MEMSPACE_ANY)],
        out_specs=pl.BlockSpec(memory_space=_MEMSPACE_ANY),
        scratch_shapes=[pltpu.SemaphoreType.DMA] * 2,
    )
    params: dict[str, Any] = {}
    if _CompilerParams is not None:
        # collective_id pairs the barrier semaphore across the
        # participating cores; the DMA/semaphore ops themselves mark the
        # kernel effectful (the pinned TPUCompilerParams has no
        # has_side_effects field)
        params["compiler_params"] = _CompilerParams(collective_id=0)
    out = pl.pallas_call(
        functools.partial(_hop_kernel, d, axis),
        out_shape=jax.ShapeDtypeStruct((pr, pc), jnp.int32),
        grid_spec=grid_spec,
        **params,
    )(x2)
    return out[:r, :c]


def _hop_pallas_one(x: jax.Array, axis: str, d: int) -> jax.Array:
    """Shift one block of any rank/dtype: flatten to int32 2-D, copy,
    restore.  Hop payloads are int32/bool member-plane slices, so the
    widening is at most 4x on the [n_loc] vectors — noise next to the
    [n_loc, N] rows that dominate the hop."""
    orig_dtype = x.dtype
    orig_shape = x.shape
    lead = orig_shape[0] if x.ndim >= 1 else 1
    x2 = x.astype(jnp.int32).reshape(lead, -1)
    out = _hop_pallas_2d(x2, axis, d)
    return out.reshape(orig_shape).astype(orig_dtype)


def _hop(blocks: tuple[jax.Array, ...], axis: str, d: int) -> tuple[jax.Array, ...]:
    """One rightward ring shift of every array in ``blocks``."""
    if hop_mode() == "pallas":
        return tuple(_hop_pallas_one(b, axis, d) for b in blocks)
    perm = ring_perm(d)
    return tuple(jax.lax.ppermute(b, axis, perm) for b in blocks)


# ---------------------------------------------------------------------------
# Ring primitives
# ---------------------------------------------------------------------------


def _bcast(mask: jax.Array, ndim: int) -> jax.Array:
    """Right-pad ``mask`` with singleton dims up to ``ndim``."""
    return mask.reshape(mask.shape + (1,) * (ndim - mask.ndim))


def _require_ring(n: int) -> tuple[Mesh, str, int, int]:
    ring = active_ring()
    if ring is None:
        raise RuntimeError(
            "ring primitive called outside a ring_mesh() context"
        )
    mesh, axis = ring
    d = mesh.shape[axis]
    if n % d != 0:
        raise ValueError(f"member axis {n} not divisible by ring size {d}")
    return mesh, axis, d, n // d


@annotate.scoped("swim.recv_merge")
def ring_recv_merge(
    t_safe: jax.Array, fwd_ok: jax.Array, claim_rows: jax.Array
) -> tuple[jax.Array, jax.Array]:
    """(in_key int32[N, N], inbound int32[N]): the receiver merge as a
    D-1-hop ring exchange — bit-identical to the sorted/scatter forms.

    ``t_safe[s]`` is sender s's receiver, ``fwd_ok[s]`` delivery,
    ``claim_rows[s]`` its already-masked (>= 0) claim row.  Sender
    blocks circulate; at each hop a shard scatter-maxes the passing
    rows addressed to its own receiver range and counts them.  Max and
    add are commutative over the hop order and the rows are
    non-negative int32, so the fold equals the global sorted merge
    exactly — while the [*, N] merge state stays [N/D, N] per shard.
    """
    n = t_safe.shape[0]
    mesh, axis, d, n_loc = _require_ring(n)

    def body(dest: jax.Array, ok: jax.Array, rows: jax.Array):
        me = jax.lax.axis_index(axis)
        off = me * n_loc
        acc = jnp.zeros((n_loc, n), jnp.int32)
        inb = jnp.zeros((n_loc,), jnp.int32)
        blk = (dest, ok, rows)
        for h in range(d):
            bdest, bok, brows = blk
            tgt = bdest - off
            # out-of-range (another shard's receiver) or undelivered
            # senders fold into the dropped n_loc slot
            tgt = jnp.where(
                (bok > 0) & (tgt >= 0) & (tgt < n_loc), tgt, n_loc
            )
            acc = acc.at[tgt].max(
                jnp.where((bok > 0)[:, None], brows, 0), mode="drop"
            )
            inb = inb.at[tgt].add(1, mode="drop")
            if h < d - 1:
                blk = _hop(blk, axis, d)
        in_key = jnp.where((inb > 0)[:, None], acc, 0)
        return in_key, inb

    return shard_map(
        body,
        mesh=mesh,
        in_specs=(P(axis), P(axis), P(axis, None)),
        out_specs=(P(axis, None), P(axis)),
        check_rep=False,
    )(
        t_safe.astype(jnp.int32),
        fwd_ok.astype(jnp.int32),
        claim_rows.astype(jnp.int32),
    )


@annotate.scoped("gossip.ring_fetch")
def ring_fetch_rows(plane: jax.Array, idx: jax.Array) -> jax.Array:
    """``plane[idx]`` with ``plane`` row-sharded and ``idx`` aligned to
    the member axis (``idx.shape[0] == plane.shape[0]``, global row
    ids, any trailing index shape).  Output shape
    ``idx.shape + plane.shape[1:]``, row-sharded like the inputs.

    The plane's shard blocks circulate the ring; at hop h a shard
    holds the block of ``block_origin(me, h, d)`` and resolves every
    local index pointing into that range.  A pure gather — exact.
    """
    n = plane.shape[0]
    mesh, axis, d, n_loc = _require_ring(n)

    def body(blk: jax.Array, il: jax.Array) -> jax.Array:
        me = jax.lax.axis_index(axis)
        out = jnp.zeros(il.shape + blk.shape[1:], blk.dtype)
        cur = (blk,)
        for h in range(d):
            src = jax.lax.rem(me - h + d, d)
            sel = (il // n_loc) == src
            loc = jnp.clip(il - src * n_loc, 0, n_loc - 1)
            got = cur[0][loc]
            out = jnp.where(_bcast(sel, got.ndim), got, out)
            if h < d - 1:
                cur = _hop(cur, axis, d)
        return out

    plane_spec = P(axis, *([None] * (plane.ndim - 1)))
    idx_spec = P(axis, *([None] * (idx.ndim - 1)))
    out_spec = P(axis, *([None] * (idx.ndim - 1 + plane.ndim - 1)))
    return shard_map(
        body,
        mesh=mesh,
        in_specs=(plane_spec, idx_spec),
        out_specs=out_spec,
        check_rep=False,
    )(plane, idx.astype(jnp.int32))


@annotate.scoped("gossip.per_row")
def ring_take_per_row(plane: jax.Array, col: jax.Array) -> jax.Array:
    """``plane[arange(N), col]`` — each viewer row reads one of its own
    columns (the diagonal when ``col = arange(N)``).  Row-local under
    viewer-row sharding, so the shard_map body does NO communication;
    the point is to stop XLA from materializing (and re-replicating)
    the [N, 2] gather-index tensor the fused form all-gathers."""
    n = plane.shape[0]
    mesh, axis, d, n_loc = _require_ring(n)

    def body(blk: jax.Array, cl: jax.Array) -> jax.Array:
        r = jnp.arange(n_loc, dtype=jnp.int32)
        return blk[r, jnp.clip(cl, 0, n - 1)]

    return shard_map(
        body,
        mesh=mesh,
        in_specs=(P(axis, None), P(axis)),
        out_specs=P(axis),
        check_rep=False,
    )(plane, col.astype(jnp.int32))


@annotate.scoped("gossip.per_row")
def ring_update_per_row(
    plane: jax.Array, col: jax.Array, values: jax.Array, op: str = "set"
) -> jax.Array:
    """``plane.at[arange(N), col].set/max(values)`` — each viewer row
    writes one of its own columns.  Row-local like
    ``ring_take_per_row``; ``op`` picks the scatter combiner."""
    if op not in ("set", "max"):
        raise ValueError(f"op={op!r}: set|max")
    n = plane.shape[0]
    mesh, axis, d, n_loc = _require_ring(n)

    def body(blk: jax.Array, cl: jax.Array, vl: jax.Array) -> jax.Array:
        r = jnp.arange(n_loc, dtype=jnp.int32)
        upd = blk.at[r, jnp.clip(cl, 0, n - 1)]
        if op == "set":
            return upd.set(vl, unique_indices=True)
        return upd.max(vl, unique_indices=True)

    return shard_map(
        body,
        mesh=mesh,
        in_specs=(P(axis, None), P(axis), P(axis)),
        out_specs=P(axis, None),
        check_rep=False,
    )(plane, col.astype(jnp.int32), values)


@annotate.scoped("gossip.ring_fetch")
def ring_fetch_global(plane: jax.Array, idx: jax.Array) -> jax.Array:
    """``plane[idx]`` with ``plane`` row-sharded and ``idx`` (any
    shape of global row ids) replicated; the output is replicated too.

    Every shard watches all D blocks pass and resolves the full index
    set identically, so the replicated output needs no final gather —
    the traffic plane's ``mask_all[viewer]`` lookups served from
    sharded membership truth.
    """
    n = plane.shape[0]
    mesh, axis, d, n_loc = _require_ring(n)

    def body(blk: jax.Array, il: jax.Array) -> jax.Array:
        me = jax.lax.axis_index(axis)
        out = jnp.zeros(il.shape + blk.shape[1:], blk.dtype)
        cur = (blk,)
        for h in range(d):
            src = jax.lax.rem(me - h + d, d)
            sel = (il // n_loc) == src
            loc = jnp.clip(il - src * n_loc, 0, n_loc - 1)
            got = cur[0][loc]
            out = jnp.where(_bcast(sel, got.ndim), got, out)
            if h < d - 1:
                cur = _hop(cur, axis, d)
        return out

    plane_spec = P(axis, *([None] * (plane.ndim - 1)))
    idx_spec = P(*([None] * idx.ndim))
    out_spec = P(*([None] * (idx.ndim + plane.ndim - 1)))
    return shard_map(
        body,
        mesh=mesh,
        in_specs=(plane_spec, idx_spec),
        out_specs=out_spec,
        check_rep=False,
    )(plane, idx.astype(jnp.int32))

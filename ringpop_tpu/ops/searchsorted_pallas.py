"""Pallas TPU kernel: batched row-wise searchsorted (rank by counting).

The delta backend's hot lookups are "positions of K queries in each
row's sorted C-wide table" (swim_delta._row_searchsorted).  The XLA
lowerings each have a failure mode on TPU: ``method="sort"`` pays an
O(log^2 (C+K)) row sort PLUS a query argsort per call;
``compare_all`` can materialize the [N, K, C] compare cube to HBM when
embedded in a large program; ``scan_unrolled`` leans on batched
take_along_axis gathers of data-dependent positions.

For a *sorted* row the insertion index is just a count:

    pos[k] = #{c : table[c] < q[k]}     (side="left";  <= for "right")

so this kernel tiles rows into VMEM and computes the count as a
broadcast compare + sum entirely on the VPU — one pass over the table
block per query block, no sorts, no gathers, and the compare cube only
ever exists as a [ROWS, K, C] VMEM tile (bounded by the block shape,
fused by Mosaic).  Traffic is the information-theoretic floor: read
the tables and queries once, write the positions once.

Bit-parity with jnp.searchsorted is pinned by
tests/test_searchsorted_pallas.py (interpret mode on CPU), and
benchmarks/profile_searchsorted.py races it against the XLA lowerings
on the live backend.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

ROW_BLOCK = 256


def _kernel(side_is_right: bool, t_ref, q_ref, o_ref):
    t = t_ref[...]  # [B, C] int32, rows sorted ascending
    q = q_ref[...]  # [B, K] int32
    if side_is_right:
        cmp = t[:, None, :] <= q[:, :, None]  # [B, K, C]
    else:
        cmp = t[:, None, :] < q[:, :, None]
    o_ref[...] = jnp.sum(cmp.astype(jnp.int32), axis=-1)


@functools.partial(jax.jit, static_argnames=("side", "interpret"))
def row_searchsorted_pallas(
    table: jax.Array,
    queries: jax.Array,
    side: str = "left",
    interpret: bool = False,
) -> jax.Array:
    """int32[N, K] insertion positions of ``queries`` in sorted ``table``
    rows; exact match for jax.vmap(jnp.searchsorted)(table, queries)."""
    if side not in ("left", "right"):
        raise ValueError(f"side must be 'left' or 'right', got {side!r}")
    n, c = table.shape
    k = queries.shape[1]
    # Row count rounded up to a multiple of 8 keeps the block shape
    # sublane-aligned — Mosaic may reject odd row blocks (e.g. 130) on
    # real TPU even though interpret mode accepts them.
    block = min(ROW_BLOCK, -(-max(8, n) // 8) * 8)
    padded = -(-n // block) * block
    if padded != n:
        # padding rows never influence real rows (row-independent math)
        table = jnp.pad(table, ((0, padded - n), (0, 0)))
        queries = jnp.pad(queries, ((0, padded - n), (0, 0)))
    out = pl.pallas_call(
        functools.partial(_kernel, side == "right"),
        grid=(padded // block,),
        in_specs=[
            pl.BlockSpec((block, c), lambda i: (i, 0)),
            pl.BlockSpec((block, k), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((block, k), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((padded, k), jnp.int32),
        interpret=interpret,
    )(table.astype(jnp.int32), queries.astype(jnp.int32))
    return out[:n]

"""Bit-packed boolean planes for the delta backend's at-rest masks.

The delta ``DeltaState`` carries several boolean lattice planes —
``bp_mask`` (base-protocol liveness, ``bool[N]`` per base row) and the
optional carried slot-base snapshot ``d_bpmask`` (``bool[N, C]``) — at
one byte per element in HBM, the scan carry, and checkpoint v5
tensors.  This module packs them 32 bits to a ``uint32`` word at rest
(an 8x footprint cut per plane) and provides the three access shapes
the consuming sites actually need, so unpacking stays lazy and local:

* ``unpack_bits``   — full-plane expansion where a site genuinely
  consumes the whole mask (phase-0 ``ping_base``, insert reorders);
* ``bit_gather``    — point lookups ``mask[idx]`` without expanding
  anything (``bp_mask_at``: one word gather + shift per query);
* ``popcount_bits`` — set-bit totals (phase-0 ``p_total``) straight
  off the words via ``lax.population_count``.

Layout convention (pinned by tests/test_bitpack.py): the plane is
packed along its LAST axis, bit ``j`` of word ``i`` holds element
``i * 32 + j`` (little-endian within the word), and a ragged tail
(``length % 32 != 0``) pads with zero bits — so ``popcount_bits``
needs no tail masking and packed planes compare equal iff the
underlying masks do.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# Bits per packed word.  uint32 (not uint8) keeps the packed planes in
# the 4-byte lane granularity TPUs natively tile, and one word covers a
# whole claim-capacity row (C = 64 -> 2 words).
WORD_BITS = 32


def packed_width(length: int) -> int:
    """Number of uint32 words covering ``length`` bits."""
    return -(-length // WORD_BITS)


def pack_bits(mask: jax.Array) -> jax.Array:
    """bool[..., L] -> uint32[..., ceil(L/32)] along the last axis.

    Pad bits (beyond L in the final word) are zero.
    """
    length = mask.shape[-1]
    words = packed_width(length)
    pad = words * WORD_BITS - length
    bits = mask.astype(jnp.uint32)
    if pad:
        bits = jnp.pad(
            bits, [(0, 0)] * (bits.ndim - 1) + [(0, pad)]
        )
    bits = bits.reshape(*mask.shape[:-1], words, WORD_BITS)
    shifts = jnp.arange(WORD_BITS, dtype=jnp.uint32)
    return jnp.sum(bits << shifts, axis=-1, dtype=jnp.uint32)


def unpack_bits(packed: jax.Array, length: int) -> jax.Array:
    """uint32[..., W] -> bool[..., length] (inverse of pack_bits)."""
    shifts = jnp.arange(WORD_BITS, dtype=jnp.uint32)
    bits = (packed[..., None] >> shifts) & jnp.uint32(1)
    bits = bits.reshape(*packed.shape[:-1], packed.shape[-1] * WORD_BITS)
    return bits[..., :length].astype(bool)


def bit_gather(
    packed: jax.Array, idx: jax.Array, row: jax.Array | None = None
) -> jax.Array:
    """Point lookups ``mask[idx]`` / ``mask[row, idx]`` on a packed plane.

    ``packed`` is uint32[W] (or uint32[G, W] with ``row`` an int array
    broadcastable against ``idx`` selecting the leading axis — the
    sided-plane form); ``idx`` int[...] indexes the unpacked last axis:

        bit_gather(p, q)        ==  mask[q]        (p = pack_bits(mask))
        bit_gather(p, q, s)     ==  mask[s, q]     (sided planes)

    ``idx`` may be any shape; out-of-range indices follow jnp's gather
    clamping (callers pass pre-clamped "safe" indices, same contract as
    the unpacked ``mask[q]`` form).
    """
    if row is None:
        word = packed[idx >> 5]
    else:
        word = packed[row, idx >> 5]
    bit = (word >> (idx & 31).astype(jnp.uint32)) & jnp.uint32(1)
    return bit.astype(bool)


def popcount_bits(packed: jax.Array, axis=None, dtype=jnp.int32) -> jax.Array:
    """Total set bits of a packed plane (pad bits are zero by layout)."""
    return jnp.sum(
        jax.lax.population_count(packed).astype(dtype), axis=axis, dtype=dtype
    )

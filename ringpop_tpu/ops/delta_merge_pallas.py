"""Pallas TPU kernel: fused sorted-insert merge for the delta tables.

The delta backend's hottest structural op is ``_merge_claims``'s
insert step: each viewer row folds its sorted [K+1] insert list into
its sorted [C] divergence table.  The XLA lowerings each
over-materialize at n=65,536:

* concat + argsort (pre-r06): a [N, C+K+1] two-key sort — the biggest
  temp class the r05 census blamed for the flagship's derived peak;
* searchsorted + gathers (the r06 default, ``sorted``): no concat, but
  still ~6 [N, C]-wide gather temps between HBM round trips.

This kernel streams row blocks through VMEM once (the PR 1
``recv_merge_pallas`` shape): each grid step loads a [RB, C] tile of
the four table channels plus the row's [RB, K+1] insert list, computes
the merge inversion entirely in registers/VMEM, and writes each output
channel exactly once.  The merge math is the gather path's, re-expressed
gather-free so Mosaic can lower it:

* insert k's merged position ``pos_k = k + |{j: d_subj[j] < ins[k]}|``
  (a compare-reduce per k — K+1 VPU passes over the tile);
* ``e[j] = |{k: pos_k < j}|`` accumulates over the same loop;
* the insert-side payload at slot j is a masked select over k
  (``pos_k == j`` fires for at most one k);
* the existing-side payload is ``channel[j - e[j]]``, a select over the
  static shift distance ``s = e[j] <= K+1`` of lane-rolled tiles —
  rolls replace the data-dependent gather (wrapped lanes land only at
  ``j < s``, which ``e <= j`` proves unselectable).

Inserted pb/sl are pure functions of the merged key (pb 0; sl only for
fresh suspects), recomputed in-kernel, so only subj/key ride the insert
list.  Bit-parity with the ``sorted`` path is pinned by
tests/test_swim_delta.py's merge-method grid (plain and streamed);
``interpret=True`` runs the same program on every non-TPU backend.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ringpop_tpu.obs import annotate

# int32 lattice-key pad for empty slots (swim_delta.SENTINEL — kept
# numerically identical here so the kernel stays import-cycle-free)
SENTINEL = jnp.iinfo(jnp.int32).max

# Rows per grid step.  VMEM cost ~ RB * (4C + 2(K+1)) int32 in + 4C
# out; at RB=256, C=256, K=64 that is ~1.4 MB — well inside one core.
ROW_BLOCK = 256


def _pick_row_block(n: int) -> int:
    """Largest power-of-two divisor of n up to ROW_BLOCK (no pad copy;
    delta fixtures are power-of-two-heavy, odd n degrades to 1 block)."""
    rb = ROW_BLOCK
    while rb > 1 and n % rb:
        rb //= 2
    return rb


def _kernel(ki, cap, sl_start, suspect,
            dsub_ref, dkey_ref, dpb_ref, dsl_ref, isub_ref, ikey_ref,
            osub_ref, okey_ref, opb_ref, osl_ref):
    dsub = dsub_ref[...]
    dkey = dkey_ref[...]
    dpb = dpb_ref[...].astype(jnp.int32)
    dsl = dsl_ref[...].astype(jnp.int32)
    isub = isub_ref[...]
    ikey = ikey_ref[...]

    out_j = jax.lax.broadcasted_iota(jnp.int32, dsub.shape, 1)
    # pass 1: merged insert positions; e[j] = inserts landing before j
    e = jnp.zeros(dsub.shape, jnp.int32)
    pos = []
    for k in range(ki):
        pos_k = jnp.sum(
            (dsub < isub[:, k:k + 1]).astype(jnp.int32),
            axis=1, keepdims=True,
        ) + k
        pos.append(pos_k)
        e = e + (pos_k < out_j).astype(jnp.int32)
    # pass 2: insert-side payload — pos_k == j fires for at most one k
    # (positions are strictly increasing in k)
    is_ins = jnp.zeros(dsub.shape, bool)
    m_isub = jnp.zeros(dsub.shape, jnp.int32)
    m_ikey = jnp.zeros(dsub.shape, jnp.int32)
    for k in range(ki):
        sel = pos[k] == out_j
        is_ins = is_ins | sel
        m_isub = jnp.where(sel, isub[:, k:k + 1], m_isub)
        m_ikey = jnp.where(sel, ikey[:, k:k + 1], m_ikey)
    # pass 3: existing-side payload channel[j - e] via static lane
    # rolls selected on the shift distance (e <= min(j, ki))
    m_dsub = dsub
    m_dkey = dkey
    m_dpb = dpb
    m_dsl = dsl
    for s in range(1, min(ki, cap - 1) + 1):
        sel = e == s
        m_dsub = jnp.where(sel, jnp.roll(dsub, s, axis=1), m_dsub)
        m_dkey = jnp.where(sel, jnp.roll(dkey, s, axis=1), m_dkey)
        m_dpb = jnp.where(sel, jnp.roll(dpb, s, axis=1), m_dpb)
        m_dsl = jnp.where(sel, jnp.roll(dsl, s, axis=1), m_dsl)

    m_subj = jnp.where(is_ins, m_isub, m_dsub)
    m_key = jnp.where(is_ins, m_ikey, m_dkey)
    ins_at_j = is_ins & (m_subj < SENTINEL)
    m_pb = jnp.where(
        is_ins, jnp.where(ins_at_j, 0, -1), m_dpb
    )
    m_sl = jnp.where(
        is_ins,
        jnp.where(ins_at_j & ((m_key & 7) == suspect), sl_start, -1),
        m_dsl,
    )
    osub_ref[...] = m_subj
    okey_ref[...] = m_key
    opb_ref[...] = m_pb.astype(jnp.int8)
    osl_ref[...] = m_sl.astype(jnp.int8)


@functools.partial(
    jax.jit, static_argnames=("sl_start", "suspect", "interpret")
)
@annotate.scoped("delta.merge_insert_pallas")
def merge_insert_pallas(
    d_subj: jax.Array,
    d_key: jax.Array,
    d_pb: jax.Array,
    d_sl: jax.Array,
    ins_subj: jax.Array,
    ins_key: jax.Array,
    *,
    sl_start: int,
    suspect: int,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Merged (subj, key, pb, sl) [N, C] tables: each row's sorted
    insert list (SENTINEL-padded, subjects disjoint from the row's
    live slots) folded into its sorted table — bit-identical to
    ``swim_delta._merge_claims``'s sorted lowering."""
    n, cap = d_subj.shape
    ki = ins_subj.shape[1]
    rb = _pick_row_block(n)
    row = lambda i: (i, 0)  # noqa: E731 — one-line index map
    out = pl.pallas_call(
        functools.partial(_kernel, ki, cap, sl_start, suspect),
        grid=(n // rb,),
        in_specs=[
            pl.BlockSpec((rb, cap), row),
            pl.BlockSpec((rb, cap), row),
            pl.BlockSpec((rb, cap), row),
            pl.BlockSpec((rb, cap), row),
            pl.BlockSpec((rb, ki), row),
            pl.BlockSpec((rb, ki), row),
        ],
        out_specs=[
            pl.BlockSpec((rb, cap), row),
            pl.BlockSpec((rb, cap), row),
            pl.BlockSpec((rb, cap), row),
            pl.BlockSpec((rb, cap), row),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, cap), jnp.int32),
            jax.ShapeDtypeStruct((n, cap), jnp.int32),
            jax.ShapeDtypeStruct((n, cap), jnp.int8),
            jax.ShapeDtypeStruct((n, cap), jnp.int8),
        ],
        interpret=interpret,
    )(d_subj, d_key, d_pb, d_sl, ins_subj, ins_key)
    return tuple(out)

"""Portable FarmHash32 (Fingerprint32) for membership/ring checksums.

The reference (charliezhang/ringpop) hashes with the `farmhash` Node addon
(`lib/membership.js:24,57`, `lib/ring.js:21,29`).  That addon's ``hash32``
dispatches on CPU features; this rebuild pins the portable, seed-stable
``Fingerprint32`` variant (== ``farmhashmk::Hash32``) so checksums are
identical across hosts, TPUs and the pure-Python fallback.

Three implementations, all bit-identical (cross-checked in
tests/test_farmhash.py):

* C (``_farmhash.c``, loaded via ctypes)  -- host hot path
* pure Python                             -- fallback / oracle
* JAX uint32 kernel (``farmhash_jax.py``) -- on-device batched hashing
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import sys

import numpy as np

_C1 = 0xCC9E2D51
_C2 = 0x1B873593
_M32 = 0xFFFFFFFF

# ---------------------------------------------------------------------------
# pure Python implementation
# ---------------------------------------------------------------------------


def _rotr32(v: int, s: int) -> int:
    if s == 0:
        return v
    return ((v >> s) | (v << (32 - s))) & _M32


def _fmix(h: int) -> int:
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & _M32
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & _M32
    h ^= h >> 16
    return h


def _mur(a: int, h: int) -> int:
    a = (a * _C1) & _M32
    a = _rotr32(a, 17)
    a = (a * _C2) & _M32
    h ^= a
    h = _rotr32(h, 19)
    return (h * 5 + 0xE6546B64) & _M32


def _fetch32(s: bytes, i: int) -> int:
    return int.from_bytes(s[i : i + 4], "little")


def _hash32_len_0_to_4(s: bytes, seed: int = 0) -> int:
    b = seed
    c = 9
    for ch in s:
        v = ch - 256 if ch >= 128 else ch  # signed char semantics
        b = (b * _C1 + v) & _M32
        c ^= b
    return _fmix(_mur(b, _mur(len(s), c)))


def _hash32_len_5_to_12(s: bytes, seed: int = 0) -> int:
    n = len(s)
    a = (n + _fetch32(s, 0)) & _M32
    b = (n * 5 + _fetch32(s, n - 4)) & _M32
    c = (9 + _fetch32(s, (n >> 1) & 4)) & _M32
    d = (n * 5 + seed) & _M32
    return _fmix(seed ^ _mur(c, _mur(b, _mur(a, d))))


def _hash32_len_13_to_24(s: bytes, seed: int = 0) -> int:
    n = len(s)
    a = _fetch32(s, (n >> 1) - 4)
    b = _fetch32(s, 4)
    c = _fetch32(s, n - 8)
    d = _fetch32(s, n >> 1)
    e = _fetch32(s, 0)
    f = _fetch32(s, n - 4)
    h = (d * _C1 + n + seed) & _M32
    a = (_rotr32(a, 12) + f) & _M32
    h = (_mur(c, h) + a) & _M32
    a = (_rotr32(a, 3) + c) & _M32
    h = (_mur(e, h) + a) & _M32
    a = (_rotr32((a + f) & _M32, 12) + d) & _M32
    h = (_mur(b ^ seed, h) + a) & _M32
    return _fmix(h)


def _farmhash32_py(s: bytes) -> int:
    n = len(s)
    if n <= 24:
        if n <= 12:
            return _hash32_len_0_to_4(s) if n <= 4 else _hash32_len_5_to_12(s)
        return _hash32_len_13_to_24(s)

    h = n
    g = (_C1 * n) & _M32
    f = g
    a0 = (_rotr32((_fetch32(s, n - 4) * _C1) & _M32, 17) * _C2) & _M32
    a1 = (_rotr32((_fetch32(s, n - 8) * _C1) & _M32, 17) * _C2) & _M32
    a2 = (_rotr32((_fetch32(s, n - 16) * _C1) & _M32, 17) * _C2) & _M32
    a3 = (_rotr32((_fetch32(s, n - 12) * _C1) & _M32, 17) * _C2) & _M32
    a4 = (_rotr32((_fetch32(s, n - 20) * _C1) & _M32, 17) * _C2) & _M32
    h ^= a0
    h = _rotr32(h, 19)
    h = (h * 5 + 0xE6546B64) & _M32
    h ^= a2
    h = _rotr32(h, 19)
    h = (h * 5 + 0xE6546B64) & _M32
    g ^= a1
    g = _rotr32(g, 19)
    g = (g * 5 + 0xE6546B64) & _M32
    g ^= a3
    g = _rotr32(g, 19)
    g = (g * 5 + 0xE6546B64) & _M32
    f = (f + a4) & _M32
    f = (_rotr32(f, 19) + 113) & _M32
    iters = (n - 1) // 20
    off = 0
    while iters > 0:
        a = _fetch32(s, off)
        b = _fetch32(s, off + 4)
        c = _fetch32(s, off + 8)
        d = _fetch32(s, off + 12)
        e = _fetch32(s, off + 16)
        h = (h + a) & _M32
        g = (g + b) & _M32
        f = (f + c) & _M32
        h = (_mur(d, h) + e) & _M32
        g = (_mur(c, g) + a) & _M32
        f = (_mur((b + e * _C1) & _M32, f) + d) & _M32
        f = (f + g) & _M32
        g = (g + f) & _M32
        off += 20
        iters -= 1
    g = (_rotr32(g, 11) * _C1) & _M32
    g = (_rotr32(g, 17) * _C1) & _M32
    f = (_rotr32(f, 11) * _C1) & _M32
    f = (_rotr32(f, 17) * _C1) & _M32
    h = _rotr32((h + g) & _M32, 19)
    h = (h * 5 + 0xE6546B64) & _M32
    h = (_rotr32(h, 17) * _C1) & _M32
    h = _rotr32((h + f) & _M32, 19)
    h = (h * 5 + 0xE6546B64) & _M32
    h = (_rotr32(h, 17) * _C1) & _M32
    return h


# ---------------------------------------------------------------------------
# C fast path (built on demand, ctypes)
# ---------------------------------------------------------------------------

_C_SRC = os.path.join(os.path.dirname(__file__), "_farmhash.c")
_C_LIB_DIR = os.path.join(os.path.dirname(__file__), "_build")
_C_LIB = os.path.join(_C_LIB_DIR, "libringpop_farmhash.so")

_lib = None
_lib_tried = False


def _build_c_lib() -> str | None:
    if sys.byteorder != "little":  # fetch32 assumes LE
        return None
    cc = os.environ.get("CC", "cc")
    cmd = [cc, "-O2", "-shared", "-fPIC", "-pthread", "-o", _C_LIB, _C_SRC]
    try:
        os.makedirs(_C_LIB_DIR, exist_ok=True)
        subprocess.run(cmd, check=True, capture_output=True, timeout=60)
    except (subprocess.SubprocessError, OSError):
        return None
    return _C_LIB


def _c_lib_fresh() -> bool:
    try:
        return os.path.getmtime(_C_LIB) >= os.path.getmtime(_C_SRC)
    except OSError:
        return False


def _load_c_lib():
    global _lib, _lib_tried
    if _lib_tried:
        return _lib
    _lib_tried = True
    path = _C_LIB if _c_lib_fresh() else _build_c_lib()
    if path is None:
        return None
    try:
        lib = ctypes.CDLL(path)
    except OSError:
        return None
    lib.rp_farmhash32.restype = ctypes.c_uint32
    lib.rp_farmhash32.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
    lib.rp_farmhash32_batch.restype = None
    lib.rp_farmhash32_batch.argtypes = [
        ctypes.c_void_p,
        ctypes.c_void_p,
        ctypes.c_void_p,
        ctypes.c_void_p,
        ctypes.c_int64,
    ]
    lib.rp_membership_checksum.restype = ctypes.c_int64
    lib.rp_membership_checksum.argtypes = [
        ctypes.c_char_p,
        ctypes.c_int64,
        ctypes.c_int64,
    ]
    lib.rp_view_checksums.restype = ctypes.c_int
    lib.rp_view_checksums.argtypes = [
        ctypes.c_void_p,  # status int8[N*N]
        ctypes.c_void_p,  # inc_rel int32[N*N]
        ctypes.c_int64,  # base_inc
        ctypes.c_void_p,  # sorted int64[N]
        ctypes.c_char_p,  # addr_buf
        ctypes.c_void_p,  # addr_off int64[N+1]
        ctypes.c_char_p,  # status_buf
        ctypes.c_void_p,  # status_off int64[codes+1]
        ctypes.c_int64,  # n_statuses (codes)
        ctypes.c_int64,  # n_nodes
        ctypes.c_int8,  # none_code
        ctypes.c_void_p,  # rows int64[n_rows]
        ctypes.c_int64,  # n_rows
        ctypes.c_void_p,  # out uint32[n_rows]
        ctypes.c_int64,  # n_threads
    ]
    _lib = lib
    return _lib


def has_native() -> bool:
    """True when the C fast path is available."""
    return _load_c_lib() is not None


def farmhash32(data: bytes | str) -> int:
    """Portable FarmHash Fingerprint32 of ``data`` (str encoded as UTF-8)."""
    if isinstance(data, str):
        data = data.encode("utf-8")
    lib = _load_c_lib()
    if lib is not None:
        return lib.rp_farmhash32(data, len(data))
    return _farmhash32_py(data)


def farmhash32_py(data: bytes | str) -> int:
    """Pure-Python reference path (always available)."""
    if isinstance(data, str):
        data = data.encode("utf-8")
    return _farmhash32_py(data)


def farmhash32_batch(buf: np.ndarray, offsets: np.ndarray, lens: np.ndarray) -> np.ndarray:
    """Hash ``n`` substrings of ``buf`` described by (offset, len) pairs."""
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    lens = np.ascontiguousarray(lens, dtype=np.int64)
    buf = np.ascontiguousarray(buf, dtype=np.uint8)
    n = len(offsets)
    out = np.empty(n, dtype=np.uint32)
    lib = _load_c_lib()
    if lib is not None:
        lib.rp_farmhash32_batch(
            buf.ctypes.data, offsets.ctypes.data, lens.ctypes.data, out.ctypes.data, n
        )
        return out
    raw = buf.tobytes()
    for i in range(n):
        out[i] = _farmhash32_py(raw[offsets[i] : offsets[i] + lens[i]])
    return out


def view_checksums_native(
    status: np.ndarray,  # int8[N, N]
    inc_rel: np.ndarray,  # int32[N, N]
    base_inc: int,
    sorted_order: np.ndarray,  # int64[N]
    addr_buf: bytes,
    addr_off: np.ndarray,  # int64[N+1]
    status_buf: bytes,
    status_off: np.ndarray,  # int64[codes+1]
    none_code: int,
    rows: np.ndarray,  # int64[n_rows]
    n_threads: int = 0,
) -> np.ndarray | None:
    """Reference-format checksum per requested view row, entirely in C.

    Returns None when the native library is unavailable (caller falls
    back to the pure path)."""
    lib = _load_c_lib()
    if lib is None:
        return None
    status = np.ascontiguousarray(status, dtype=np.int8)
    inc_rel = np.ascontiguousarray(inc_rel, dtype=np.int32)
    sorted_order = np.ascontiguousarray(sorted_order, dtype=np.int64)
    addr_off = np.ascontiguousarray(addr_off, dtype=np.int64)
    status_off = np.ascontiguousarray(status_off, dtype=np.int64)
    rows = np.ascontiguousarray(rows, dtype=np.int64)
    out = np.empty(len(rows), dtype=np.uint32)
    if n_threads <= 0:
        n_threads = min(8, os.cpu_count() or 1)
    rc = lib.rp_view_checksums(
        status.ctypes.data,
        inc_rel.ctypes.data,
        int(base_inc),
        sorted_order.ctypes.data,
        addr_buf,
        addr_off.ctypes.data,
        status_buf,
        status_off.ctypes.data,
        len(status_off) - 1,
        # n_nodes is the member/column count — NOT the row count: callers
        # may pass a row subset (rows x n_nodes), e.g. live views only.
        status.shape[1] if status.ndim == 2 else status.shape[0],
        int(none_code),
        rows.ctypes.data,
        len(rows),
        out.ctypes.data,
        n_threads,
    )
    if rc != 0:
        return None
    return out


def membership_checksum_packed(packed: bytes, n_members: int) -> int:
    """Checksum of pre-sorted members packed as ``addr\\0status\\0inc\\0`` x n.

    Equivalent to farmhash32 of the reference's checksum string
    (lib/membership.js:70-93): ``addr+status+inc`` joined by ``;``.
    """
    lib = _load_c_lib()
    if lib is not None:
        result = lib.rp_membership_checksum(packed, len(packed), n_members)
        if result >= 0:
            return result
    # Pure path, mirroring the C concatenation exactly (including its
    # behavior when fewer members are packed than n_members claims).
    parts = packed.split(b"\x00")
    n_packed = min(n_members, len(parts) // 3)
    out = bytearray()
    for i in range(n_packed):
        out += parts[3 * i] + parts[3 * i + 1] + parts[3 * i + 2]
        if i + 1 < n_members:
            out += b";"
    return _farmhash32_py(bytes(out))

"""Pallas TPU kernel: one-pass per-receiver lattice-max merge.

The dense ``swim_step``'s hottest primitive is the receiver merge
(``swim_sim._receiver_merge``): every delivering sender contributes its
[N]-wide claim row, and each receiver folds its inbound rows with an
elementwise int32 max.  The primitive runs many times per tick (dense
phase 3 plus every ping-req slot of stages 5a-5c), so its HBM traffic
is the step's bandwidth bill.  The XLA lowerings each over-materialize:

* ``scatter``: ``zeros.at[t_safe].max(rows)`` — colliding receiver
  indices, so the TPU scatter serializes;
* ``sorted``: a flat [N] argsort (cheap), then a full [N, N] row
  permutation of the claim matrix, ~log2(max inbound) Hillis–Steele
  combine passes each touching the whole [N, N] tensor, and a final
  [N, N] row gather — 4–6 full HBM passes over a ~4 GB tensor at 32k.

This kernel keeps the cheap flat sort (senders ordered by receiver, so
each receiver's senders form one contiguous run) and replaces every
[N, N] pass with a single stream: the grid walks sorted sender
positions with the claim row for position ``p`` fetched by a
scalar-prefetch index map (``order[p]``), and max-accumulates into the
receiver's output block, which Pallas keeps resident in VMEM while
consecutive positions share a receiver (the matmul-K revisiting
contract — the output flushes only when the block index changes, and
``recv_sorted`` is non-decreasing, so every receiver's row is written
back exactly once per column block).  Every claim row is read from HBM
exactly once and every merged row written exactly once — the
information-theoretic floor.

Mechanics and caveats:

* The three index vectors (``recv_sorted``, ``starts``, ``order``) ride
  in SMEM as scalar-prefetch operands: 3N+1 int32, ~384 KB at n=32k —
  fine for the single-chip dense regime this kernel serves; the sharded
  mesh path falls back to the sorted lowering (parallel/mesh.py).
* Senders with nothing delivered sort to the tail (key ``n``); their
  steps clamp to row n-1 but are guarded off, so at most one dead row's
  buffer is flushed with garbage — receivers with no inbound ping are
  masked to 0 outside the kernel, same contract as the other forms.
* Block shapes are (1, 1, cb) over a [N, 1, padded] view: Mosaic
  requires the sublane dim of the last two block dims be 8-divisible or
  the full array dim, and the middle singleton satisfies that while
  keeping single-row fetches (the row stream is a permutation, so rows
  cannot be block-fetched).  ``cb`` prefers a divisor of N (no padding
  copy); the lane tile keeps it a multiple of 128.
* ``interpret=True`` runs the same program on CPU for tier-1 parity
  (tests/test_recv_merge_pallas.py and the trajectory grid in
  tests/test_sim_core.py); benchmarks/profile_step.py races the
  compiled form against sorted/scatter on a live backend.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ringpop_tpu.obs import annotate
from ringpop_tpu.obs.ledger import default_ledger

# Lane width of one grid step's fetch/accumulate tile (int32 lanes; a
# multiple of 128).  Larger blocks amortize per-step overhead and grow
# DMA granularity at 4 bytes/lane; VMEM cost is ~4 tiles of cb int32.
COL_BLOCK = 2048


def _kernel(n, recv_ref, starts_ref, order_ref, claims_ref, out_ref):
    p = pl.program_id(1)
    r = recv_ref[p]
    valid = r < n  # delivered senders sort before the key-n tail
    r_c = jnp.minimum(r, n - 1)
    # the first sorted position of receiver r initializes its block
    first = valid & (p == starts_ref[r_c])

    @pl.when(first)
    def _():
        out_ref[...] = claims_ref[...]

    @pl.when(valid & jnp.logical_not(first))
    def _():
        out_ref[...] = jnp.maximum(out_ref[...], claims_ref[...])


def _pick_col_block(n: int) -> tuple[int, int]:
    """(cb, padded): prefer a 128-multiple divisor of n (no pad copy)."""
    for c in range(min(COL_BLOCK, n) // 128, 0, -1):
        if n % (c * 128) == 0:
            return c * 128, n
    cb = min(COL_BLOCK, -(-n // 128) * 128)
    return cb, -(-n // cb) * cb


@functools.partial(jax.jit, static_argnames=("interpret",))
@annotate.scoped("swim.recv_merge_pallas")
def _recv_merge_pallas_jit(
    t_safe: jax.Array,
    fwd_ok: jax.Array,
    claim_rows: jax.Array,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array]:
    n = t_safe.shape[0]
    recv = jnp.where(fwd_ok, t_safe, n).astype(jnp.int32)
    order = jnp.argsort(recv).astype(jnp.int32)  # flat [N]: cheap
    recv_s = recv[order]
    starts = jnp.searchsorted(
        recv_s, jnp.arange(n + 1, dtype=jnp.int32)
    ).astype(jnp.int32)
    inbound = starts[1:] - starts[:-1]

    cb, padded = _pick_col_block(n)
    claims = claim_rows
    if padded != n:
        claims = jnp.pad(claims, ((0, 0), (0, padded - n)))
    claims = claims.reshape(n, 1, padded)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        # sender position innermost: consecutive steps share a receiver,
        # so the output block accumulates in VMEM between flushes
        grid=(padded // cb, n),
        in_specs=[
            pl.BlockSpec(
                (1, 1, cb),
                lambda j, p, recv_ref, starts_ref, order_ref: (
                    order_ref[p],
                    0,
                    j,
                ),
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, cb),
            lambda j, p, recv_ref, starts_ref, order_ref: (
                jnp.minimum(recv_ref[p], n - 1),
                0,
                j,
            ),
        ),
    )
    out = pl.pallas_call(
        functools.partial(_kernel, n),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n, 1, padded), jnp.int32),
        interpret=interpret,
    )(recv_s, starts, order, claims)
    in_key = jnp.where((inbound > 0)[:, None], out[:, 0, :n], 0)
    return in_key, inbound


def recv_merge_pallas(
    t_safe: jax.Array,
    fwd_ok: jax.Array,
    claim_rows: jax.Array,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array]:
    """(in_key int32[N, N], inbound int32[N]): per-receiver lattice max
    of the delivered claim rows and the delivered-ping count —
    bit-identical to swim_sim._receiver_merge's sorted/scatter forms.

    ``t_safe[s]`` is sender s's receiver, ``fwd_ok[s]`` whether its ping
    was delivered, ``claim_rows[s]`` its (already masked, >= 0) claims.

    A host-level call (concrete arrays) with the dispatch ledger
    enabled is recorded there (compile/execute split + footprint);
    traced calls — the kernel inlined into ``swim_step`` — go straight
    through, as do ledger-off calls.
    """
    ledger = default_ledger()
    if ledger.enabled and not isinstance(t_safe, jax.core.Tracer):
        return ledger.dispatch(
            "recv_merge_pallas",
            _recv_merge_pallas_jit,
            t_safe,
            fwd_ok,
            claim_rows,
            interpret=interpret,
            _meta={"backend": "dense", "n": int(t_safe.shape[0])},
        )
    return _recv_merge_pallas_jit(t_safe, fwd_ok, claim_rows, interpret=interpret)

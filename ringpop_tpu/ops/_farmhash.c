/* Portable FarmHash32 (Fingerprint32 == farmhashmk::Hash32).
 *
 * This is the stable, architecture-independent 32-bit FarmHash used for
 * membership checksums and hash-ring replica placement.  The reference
 * implementation (charliezhang/ringpop) uses the `farmhash` Node.js addon
 * (lib/membership.js:57, lib/ring.js:29); that addon's `hash32` dispatches on
 * CPU features and is NOT stable across machines, so this rebuild pins the
 * portable Fingerprint32 variant (identical to `hash32` on non-SSE4.1 hosts
 * and to `fingerprint32` everywhere).
 *
 * Algorithm: public-domain-style FarmHash by Geoff Pike (Google), MIT
 * licensed.  Implemented from the published algorithm; verified bit-exact
 * against the farmhash copy vendored by TensorFlow (see
 * tools/verify_farmhash.cc and tests/test_farmhash.py).
 *
 * Exposed via ctypes (no pybind11 in this environment):
 *   rp_farmhash32(buf, len) -> uint32
 *   rp_farmhash32_batch(buf, offsets, lens, out, n)  -- n independent hashes
 */

#include <stdint.h>
#include <stddef.h>
#include <string.h>

#define C1 0xcc9e2d51u
#define C2 0x1b873593u

static inline uint32_t fetch32(const uint8_t *p) {
    /* little-endian 32-bit load (x86/ARM LE only, asserted in loader) */
    uint32_t v;
    memcpy(&v, p, 4);
    return v;
}

/* FarmHash's Rotate32 is a rotate RIGHT. */
static inline uint32_t rotr32(uint32_t v, int s) {
    return s == 0 ? v : ((v >> s) | (v << (32 - s)));
}

static inline uint32_t fmix(uint32_t h) {
    h ^= h >> 16;
    h *= 0x85ebca6bu;
    h ^= h >> 13;
    h *= 0xc2b2ae35u;
    h ^= h >> 16;
    return h;
}

static inline uint32_t mur(uint32_t a, uint32_t h) {
    a *= C1;
    a = rotr32(a, 17);
    a *= C2;
    h ^= a;
    h = rotr32(h, 19);
    return h * 5 + 0xe6546b64u;
}

static uint32_t hash32_len_0_to_4(const uint8_t *s, size_t len, uint32_t seed) {
    uint32_t b = seed;
    uint32_t c = 9;
    for (size_t i = 0; i < len; i++) {
        /* signed char: bytes >= 0x80 subtract */
        int8_t v = (int8_t)s[i];
        b = b * C1 + (uint32_t)(int32_t)v;
        c ^= b;
    }
    return fmix(mur(b, mur((uint32_t)len, c)));
}

static uint32_t hash32_len_5_to_12(const uint8_t *s, size_t len, uint32_t seed) {
    uint32_t a = (uint32_t)len, b = (uint32_t)len * 5, c = 9, d = b + seed;
    a += fetch32(s);
    b += fetch32(s + len - 4);
    c += fetch32(s + ((len >> 1) & 4));
    return fmix(seed ^ mur(c, mur(b, mur(a, d))));
}

static uint32_t hash32_len_13_to_24(const uint8_t *s, size_t len, uint32_t seed) {
    uint32_t a = fetch32(s - 4 + (len >> 1));
    uint32_t b = fetch32(s + 4);
    uint32_t c = fetch32(s + len - 8);
    uint32_t d = fetch32(s + (len >> 1));
    uint32_t e = fetch32(s);
    uint32_t f = fetch32(s + len - 4);
    uint32_t h = d * C1 + (uint32_t)len + seed;
    a = rotr32(a, 12) + f;
    h = mur(c, h) + a;
    a = rotr32(a, 3) + c;
    h = mur(e, h) + a;
    a = rotr32(a + f, 12) + d;
    h = mur(b ^ seed, h) + a;
    return fmix(h);
}

uint32_t rp_farmhash32(const uint8_t *s, size_t len) {
    if (len <= 24) {
        return len <= 12
                   ? (len <= 4 ? hash32_len_0_to_4(s, len, 0)
                               : hash32_len_5_to_12(s, len, 0))
                   : hash32_len_13_to_24(s, len, 0);
    }

    /* len > 24 */
    uint32_t h = (uint32_t)len, g = C1 * (uint32_t)len, f = g;
    uint32_t a0 = rotr32(fetch32(s + len - 4) * C1, 17) * C2;
    uint32_t a1 = rotr32(fetch32(s + len - 8) * C1, 17) * C2;
    uint32_t a2 = rotr32(fetch32(s + len - 16) * C1, 17) * C2;
    uint32_t a3 = rotr32(fetch32(s + len - 12) * C1, 17) * C2;
    uint32_t a4 = rotr32(fetch32(s + len - 20) * C1, 17) * C2;
    h ^= a0;
    h = rotr32(h, 19);
    h = h * 5 + 0xe6546b64u;
    h ^= a2;
    h = rotr32(h, 19);
    h = h * 5 + 0xe6546b64u;
    g ^= a1;
    g = rotr32(g, 19);
    g = g * 5 + 0xe6546b64u;
    g ^= a3;
    g = rotr32(g, 19);
    g = g * 5 + 0xe6546b64u;
    f += a4;
    f = rotr32(f, 19) + 113;
    size_t iters = (len - 1) / 20;
    do {
        uint32_t a = fetch32(s);
        uint32_t b = fetch32(s + 4);
        uint32_t c = fetch32(s + 8);
        uint32_t d = fetch32(s + 12);
        uint32_t e = fetch32(s + 16);
        h += a;
        g += b;
        f += c;
        h = mur(d, h) + e;
        g = mur(c, g) + a;
        f = mur(b + e * C1, f) + d;
        f += g;
        g += f;
        s += 20;
    } while (--iters != 0);
    g = rotr32(g, 11) * C1;
    g = rotr32(g, 17) * C1;
    f = rotr32(f, 11) * C1;
    f = rotr32(f, 17) * C1;
    h = rotr32(h + g, 19);
    h = h * 5 + 0xe6546b64u;
    h = rotr32(h, 17) * C1;
    h = rotr32(h + f, 19);
    h = h * 5 + 0xe6546b64u;
    h = rotr32(h, 17) * C1;
    return h;
}

void rp_farmhash32_batch(const uint8_t *buf, const int64_t *offsets,
                         const int64_t *lens, uint32_t *out, int64_t n) {
    for (int64_t i = 0; i < n; i++) {
        out[i] = rp_farmhash32(buf + offsets[i], (size_t)lens[i]);
    }
}

/* Build a ringpop membership checksum string and hash it.
 *
 * The reference builds `addr + status + incarnationNumber` per member, sorted
 * by address, joined with ';' (lib/membership.js:70-93), then farmhash32s the
 * result (lib/membership.js:57).  This helper does the concatenation in C for
 * the host-side hot path.  Caller passes members pre-sorted by address as
 * NUL-separated strings "addr\0status\0incarnation_decimal\0" x n.
 */
#include <stdlib.h>

int64_t rp_membership_checksum(const uint8_t *packed, int64_t packed_len,
                               int64_t n_members) {
    /* Returns the uint32 checksum, or -1 on allocation failure (the Python
     * caller falls back to the pure path).  Concatenated length is
     * < packed_len (3 NULs per member drop, up to n-1 ';' are added). */
    uint8_t *heapbuf = (uint8_t *)malloc((size_t)packed_len + 1);
    if (heapbuf == NULL) {
        return -1;
    }
    uint8_t *dst = heapbuf;
    const uint8_t *p = packed;
    const uint8_t *end = packed + packed_len;
    int64_t m = 0;
    while (p < end && m < n_members) {
        int fields = 0;
        while (p < end && fields < 3) {
            if (*p == 0) {
                fields++;
                p++;
            } else {
                *dst++ = *p++;
            }
        }
        m++;
        if (m < n_members) {
            *dst++ = ';';
        }
    }
    uint32_t h = rp_farmhash32(heapbuf, (size_t)(dst - heapbuf));
    free(heapbuf);
    return (int64_t)h;
}

/* Batched reference-format checksums of simulation view rows.
 *
 * For each requested viewer row, builds the checksum string of its view —
 * members sorted by address, `addr + status + incarnation` joined by ';'
 * (lib/membership.js:70-93) — and farmhash32s it, entirely in C with one
 * worker thread per row shard.  This replaces a Python per-entry loop
 * that made whole-cluster checksum parity O(N^2) interpreter work.
 *
 * Layout:
 *   status      int8 [n_nodes * n_nodes]  row-major view_status
 *   inc_rel     int32[n_nodes * n_nodes]  incarnation - base_inc
 *   base_inc    int64                     added back before formatting
 *   sorted      int64[n_nodes]            address sort permutation
 *   addr_buf    concatenated address bytes
 *   addr_off    int64[n_nodes + 1]        addr j = addr_buf[off[j]:off[j+1]]
 *   status_buf / status_off                same encoding for status names,
 *                                          indexed by status code 0..n_codes-1
 *   none_code   the status meaning "member does not exist" (skipped)
 *   rows        int64[n_rows]             which viewer rows to checksum
 *   out         uint32[n_rows]
 */
#include <pthread.h>
#include <stdio.h>

typedef struct {
    const int8_t *status;
    const int32_t *inc_rel;
    int64_t base_inc;
    const int64_t *sorted;
    const uint8_t *addr_buf;
    const int64_t *addr_off;
    const uint8_t *status_buf;
    const int64_t *status_off;
    int64_t n_nodes;
    int8_t none_code;
    const int64_t *rows;
    uint32_t *out;
    int64_t row_begin, row_end;
    size_t scratch_len;
    int failed;
} vc_task;

static inline uint8_t *write_i64(uint8_t *dst, int64_t v) {
    char tmp[24];
    int len = snprintf(tmp, sizeof(tmp), "%lld", (long long)v);
    memcpy(dst, tmp, (size_t)len);
    return dst + len;
}

static void *vc_worker(void *arg) {
    vc_task *t = (vc_task *)arg;
    uint8_t *scratch = (uint8_t *)malloc(t->scratch_len);
    if (scratch == NULL) {
        t->failed = 1;
        return NULL;
    }
    for (int64_t r = t->row_begin; r < t->row_end; r++) {
        const int64_t row = t->rows[r];
        const int8_t *st = t->status + row * t->n_nodes;
        const int32_t *inc = t->inc_rel + row * t->n_nodes;
        uint8_t *dst = scratch;
        int first = 1;
        for (int64_t k = 0; k < t->n_nodes; k++) {
            const int64_t j = t->sorted[k];
            const int8_t s = st[j];
            if (s == t->none_code) {
                continue;
            }
            if (!first) {
                *dst++ = ';';
            }
            first = 0;
            {
                const int64_t a0 = t->addr_off[j], a1 = t->addr_off[j + 1];
                memcpy(dst, t->addr_buf + a0, (size_t)(a1 - a0));
                dst += a1 - a0;
            }
            {
                const int64_t s0 = t->status_off[s], s1 = t->status_off[s + 1];
                memcpy(dst, t->status_buf + s0, (size_t)(s1 - s0));
                dst += s1 - s0;
            }
            dst = write_i64(dst, t->base_inc + (int64_t)inc[j]);
        }
        t->out[r] = rp_farmhash32(scratch, (size_t)(dst - scratch));
    }
    free(scratch);
    return NULL;
}

int rp_view_checksums(const int8_t *status, const int32_t *inc_rel,
                      int64_t base_inc, const int64_t *sorted,
                      const uint8_t *addr_buf, const int64_t *addr_off,
                      const uint8_t *status_buf, const int64_t *status_off,
                      int64_t n_statuses, int64_t n_nodes, int8_t none_code,
                      const int64_t *rows, int64_t n_rows, uint32_t *out,
                      int64_t n_threads) {
    /* Worst-case per-row string: every member present.  The status budget
     * is derived from the table, not hard-coded: a longer status name
     * added Python-side must widen the scratch, not overflow it. */
    size_t max_status = 0;
    for (int64_t s = 0; s < n_statuses; s++) {
        size_t len = (size_t)(status_off[s + 1] - status_off[s]);
        if (len > max_status) {
            max_status = len;
        }
    }
    size_t scratch = 1;
    for (int64_t j = 0; j < n_nodes; j++) {
        size_t addr_len = (size_t)(addr_off[j + 1] - addr_off[j]);
        scratch += addr_len + max_status + 21 /* inc */ + 1 /* ';' */;
    }
    if (n_threads < 1) {
        n_threads = 1;
    }
    if (n_threads > n_rows) {
        n_threads = n_rows > 0 ? n_rows : 1;
    }
    vc_task tasks[64];
    pthread_t threads[64];
    if (n_threads > 64) {
        n_threads = 64;
    }
    int64_t per = (n_rows + n_threads - 1) / n_threads;
    int64_t started = 0;
    for (int64_t t = 0; t < n_threads; t++) {
        vc_task *task = &tasks[t];
        task->status = status;
        task->inc_rel = inc_rel;
        task->base_inc = base_inc;
        task->sorted = sorted;
        task->addr_buf = addr_buf;
        task->addr_off = addr_off;
        task->status_buf = status_buf;
        task->status_off = status_off;
        task->n_nodes = n_nodes;
        task->none_code = none_code;
        task->rows = rows;
        task->out = out;
        task->row_begin = t * per;
        task->row_end = (t + 1) * per < n_rows ? (t + 1) * per : n_rows;
        task->scratch_len = scratch;
        task->failed = 0;
        if (task->row_begin >= task->row_end) {
            task->row_begin = task->row_end = 0;
        }
        if (pthread_create(&threads[t], NULL, vc_worker, task) != 0) {
            /* Fall back to running the remaining shards inline. */
            vc_worker(task);
            threads[t] = 0;
        }
        started++;
    }
    int failed = 0;
    for (int64_t t = 0; t < started; t++) {
        if (threads[t] != 0) {
            pthread_join(threads[t], NULL);
        }
        failed |= tasks[t].failed;
    }
    return failed ? -1 : 0;
}

"""Reference-format membership checksums computed entirely on device.

The checksum (lib/membership.js:41-93) is farmhash32 of
``addr + status + str(incarnation)`` per member, sorted by address,
joined by ';'.  The host/C path (models/checksum.py, ops/_farmhash.c)
builds that string per view row on the host; this module builds it — and
hashes it — on device, so whole-cluster checksum sweeps of a large
simulation never leave HBM.

String assembly is pure tensor work:

* static per-book tables (padded address bytes, lengths, sorted order,
  status-name table) are computed once per ``DeviceBook``;
* the decimal rendering of ``base_inc + inc_rel`` avoids int64 entirely:
  the static base splits into (hi, lo) around 1e9 and the dynamic
  offset (< 2**27) only touches ``lo`` plus one carry;
* each member entry scatters its bytes at an offset from an exclusive
  cumsum of entry lengths; a ';' is written before every entry and the
  first one lands at position -1, which ``mode="drop"`` discards — the
  join needs no data-dependent "is first present member" logic;
* one batched jittable farmhash32 (ops/farmhash_jax.py) hashes the rows.

Cross-checked bit-identical against the threaded C kernel in
tests/test_checksum_device.py and (at 10k nodes on real hardware) via
the bench entry.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ringpop_tpu.models.swim_sim import STATUS_NAMES
from ringpop_tpu.ops.farmhash_jax import farmhash32_batch_jax

_POW10 = tuple(10**i for i in range(10))


class DeviceBook:
    """Static device tables for one address book (addresses never change
    during a simulation; see models/checksum.py AddressBook)."""

    def __init__(self, addresses: Sequence[str], base_inc: int):
        raw = [a.encode() for a in addresses]
        self.n = len(raw)
        self.base_inc = int(base_inc)
        self.max_addr = max(len(b) for b in raw)
        addr = np.zeros((self.n, self.max_addr), dtype=np.uint8)
        alen = np.zeros((self.n,), dtype=np.int32)
        for i, b in enumerate(raw):
            addr[i, : len(b)] = np.frombuffer(b, dtype=np.uint8)
            alen[i] = len(b)
        order = np.argsort(np.array(addresses, dtype=object), kind="stable")
        # tables pre-permuted into checksum (address-sorted) order
        self.addr = jnp.asarray(addr[order])
        self.alen = jnp.asarray(alen[order])
        self.order = jnp.asarray(order.astype(np.int32))

        codes = sorted(STATUS_NAMES)
        self.max_status = max(len(v) for v in STATUS_NAMES.values())
        sbytes = np.zeros((max(codes) + 1, self.max_status), dtype=np.uint8)
        slen = np.zeros((max(codes) + 1,), dtype=np.int32)
        for code, name in STATUS_NAMES.items():
            b = name.encode()
            sbytes[code, : len(b)] = np.frombuffer(b, dtype=np.uint8)
            slen[code] = len(b)
        self.status_bytes = jnp.asarray(sbytes)
        self.status_len = jnp.asarray(slen)

        # decimal split of the static base around 1e9 (see module doc)
        self.base_hi = self.base_inc // 10**9
        self.base_lo = self.base_inc % 10**9
        from ringpop_tpu.models.swim_sim import INC_MAX

        self.max_inc_digits = len(str(self.base_inc + INC_MAX))
        # worst-case row string: every member present
        self.entry_width = 1 + self.max_addr + self.max_status + self.max_inc_digits
        self.row_width = max(self.n * self.entry_width, 25)


def _digit_count(x: jax.Array) -> jax.Array:
    """Decimal digits of a non-negative int32 (0 -> 1)."""
    d = jnp.ones_like(x)
    for p in _POW10[1:]:
        d = d + (x >= p).astype(x.dtype)
    return d


def row_strings(
    book: DeviceBook, view_key_rows: jax.Array
) -> tuple[jax.Array, jax.Array]:
    """Checksum strings of view rows: (bufs uint8[R, W], lens int32[R]).

    ``view_key_rows``: int32[R, N] packed lattice keys (swim_sim layout).
    """
    r = view_key_rows.shape[0]
    # subjects gathered in address-sorted order (the checksum order)
    keys = view_key_rows[:, book.order]  # [R, N]
    status = keys & 7
    inc = keys >> 3
    present = keys > 0

    # absolute incarnation decimal = (hi, lo) around 1e9
    lo = book.base_lo + inc
    carry = lo >= 10**9
    lo = jnp.where(carry, lo - 10**9, lo)
    hi = book.base_hi + carry.astype(jnp.int32)
    inc_len = jnp.where(hi > 0, _digit_count(hi) + 9, _digit_count(lo))

    slen = book.status_len[status]  # [R, N]
    alen = book.alen[None, :]  # [1, N]
    entry_len = jnp.where(present, 1 + alen + slen + inc_len, 0)  # [R, N]
    csum = jnp.cumsum(entry_len, axis=1)
    offsets = csum - entry_len  # exclusive
    lens = jnp.maximum(csum[:, -1] - 1, 0)  # minus the leading ';'

    e = book.entry_width
    b = jnp.arange(e, dtype=jnp.int32)[None, None, :]  # [1, 1, E]
    # content position within the entry, after the leading ';'
    q = b - 1
    in_addr = (q >= 0) & (q < alen[:, :, None])
    q_s = q - alen[:, :, None]
    in_status = (q_s >= 0) & (q_s < slen[:, :, None])
    q_i = q_s - slen[:, :, None]
    in_inc = (q_i >= 0) & (q_i < inc_len[:, :, None])

    addr_b = book.addr[None, :, :]  # [1, N, max_addr]
    addr_byte = jnp.take_along_axis(
        jnp.broadcast_to(addr_b, (r, book.n, book.max_addr)),
        jnp.clip(q, 0, book.max_addr - 1),
        axis=2,
    )
    status_byte = jnp.take_along_axis(
        book.status_bytes[status],  # [R, N, max_status]
        jnp.clip(q_s, 0, book.max_status - 1),
        axis=2,
    )
    # decimal digit at exponent e10 = inc_len-1-q_i (from LSB); exponents
    # >= 9 read hi, below read lo — never touching int64
    e10 = inc_len[:, :, None] - 1 - q_i
    hi_exp = jnp.clip(e10 - 9, 0, 9)
    lo_exp = jnp.clip(e10, 0, 8)
    pow_hi = jnp.asarray(_POW10, dtype=jnp.int32)[hi_exp]
    pow_lo = jnp.asarray(_POW10, dtype=jnp.int32)[lo_exp]
    digit = jnp.where(
        e10 >= 9,
        (hi[:, :, None] // pow_hi) % 10,
        (lo[:, :, None] // pow_lo) % 10,
    )
    inc_byte = (digit + ord("0")).astype(jnp.uint8)

    val = jnp.where(
        b == 0,
        jnp.uint8(ord(";")),
        jnp.where(
            in_addr,
            addr_byte,
            jnp.where(in_status, status_byte, inc_byte),
        ),
    )
    valid = present[:, :, None] & (b < entry_len[:, :, None])
    # scatter into the row buffer; the first entry's ';' lands at -1 and
    # mode="drop" discards it (the join trick, see module doc).  The
    # scatter runs in int32: the TPU runtime rejects scatters of
    # unsigned element types ("Reductions over unsigned integers not
    # implemented"), and byte values fit int32 exactly.
    pos = jnp.where(valid, offsets[:, :, None] + b - 1, -1)
    rows_idx = jnp.broadcast_to(
        jnp.arange(r, dtype=jnp.int32)[:, None, None], pos.shape
    )
    out = jnp.zeros((r, book.row_width), dtype=jnp.int32)
    out = out.at[rows_idx, pos].set(
        jnp.where(valid, val, jnp.uint8(0)).astype(jnp.int32), mode="drop"
    )
    return out.astype(jnp.uint8), lens


def view_checksums_device(
    book: DeviceBook,
    view_key_rows: jax.Array,
    max_elements: int = 64 * 1024 * 1024,
) -> jax.Array:
    """Reference-format checksum per view row, uint32[R], all on device.

    Rows are processed in chunks: string assembly materializes
    [rows, N, entry_width] intermediates, so the chunk size is bounded to
    ``max_elements`` of that product (default keeps the peak footprint a
    few hundred MB regardless of cluster size)."""
    r = view_key_rows.shape[0]
    per_row = max(1, book.n * book.entry_width)
    chunk = max(1, min(r, max_elements // per_row))
    if chunk >= r:
        bufs, lens = row_strings(book, view_key_rows)
        return farmhash32_batch_jax(bufs, lens)
    outs = []
    for start in range(0, r, chunk):
        bufs, lens = row_strings(book, view_key_rows[start : start + chunk])
        outs.append(farmhash32_batch_jax(bufs, lens))
    return jnp.concatenate(outs)

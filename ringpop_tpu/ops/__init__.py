"""Hashing / checksum / ring kernels (host C fast paths + JAX device ops)."""

from ringpop_tpu.ops.farmhash import farmhash32, farmhash32_py, has_native

__all__ = ["farmhash32", "farmhash32_py", "has_native"]

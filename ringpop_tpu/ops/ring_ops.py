"""Device-side consistent-hash ring: build + batched lookup kernels.

The reference resolves one key at a time through a red-black tree
(lib/ring.js:138-182).  The TPU-native form is data-parallel: the ring is
a sorted ``uint32[R]`` replica-hash array with an ``int32[R]`` owner
table, ``lookup`` of M keys is one ``searchsorted`` (same O(log R) per
key, vectorized across the whole batch), and wraparound to the minimum
replica (ring.js:142-145) is ``idx % R``.

Replica placement is bit-identical to the host ring (hashring.py):
``farmhash32(f"{server}{i}")`` for i in 0..replica_points-1 — on device
via the jittable farmhash kernel — so a ring built from the same server
set yields the same owners as the reference.

Everything is shape-static and jittable; the lookup kernels compose with
pjit/shard_map (the keys dimension shards freely — the ring tables are
tiny and replicate).
"""

from __future__ import annotations

from typing import NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ringpop_tpu.hashring import DEFAULT_REPLICA_POINTS
from ringpop_tpu.ops.farmhash_jax import farmhash32_batch_jax


class DeviceRing(NamedTuple):
    """Sorted replica table: the device form of lib/ring.js state."""

    hashes: jax.Array  # uint32[R], sorted ascending
    owners: jax.Array  # int32[R], owner index per replica

    @property
    def size(self) -> int:
        return self.hashes.shape[0]


def build_ring(
    servers: Sequence[str], replica_points: int = DEFAULT_REPLICA_POINTS
) -> DeviceRing:
    """Host-side build (one batched C farmhash call): a sorted table
    shipped to device.  Owner ids index into ``servers``."""
    from ringpop_tpu.ops.farmhash import farmhash32_batch

    names = [
        f"{server}{i}".encode()
        for server in servers
        for i in range(replica_points)
    ]
    buf = np.frombuffer(b"".join(names), dtype=np.uint8)
    lens = np.array([len(b) for b in names], dtype=np.int64)
    offsets = np.zeros(len(names), dtype=np.int64)
    np.cumsum(lens[:-1], out=offsets[1:])
    hashes = farmhash32_batch(buf, offsets, lens)
    owners = np.repeat(
        np.arange(len(servers), dtype=np.int32), replica_points
    )
    # Hash ties break by server NAME, matching the host ring's
    # (hash, server) tuple order — not by position in `servers`.
    name_rank = np.argsort(np.argsort(np.array(servers, dtype=object)))
    order = np.lexsort((name_rank[owners], hashes))
    return DeviceRing(
        hashes=jnp.asarray(hashes[order]), owners=jnp.asarray(owners[order])
    )


def encode_strings(
    strings: Sequence[str], pad_to: int | None = None
) -> tuple[np.ndarray, np.ndarray]:
    """Pack strings into the (padded uint8 buffer, length) form the
    device hash kernels consume."""
    raw = [s.encode() for s in strings]
    # the jittable farmhash kernel requires buffers of at least 25 bytes
    if pad_to is not None and pad_to < 25:
        raise ValueError("pad_to must be >= 25 (farmhash kernel minimum)")
    width = pad_to or max(max((len(b) for b in raw), default=1), 25)
    bufs = np.zeros((len(raw), width), dtype=np.uint8)
    lens = np.zeros((len(raw),), dtype=np.int32)
    for i, b in enumerate(raw):
        bufs[i, : len(b)] = np.frombuffer(b, dtype=np.uint8)
        lens[i] = len(b)
    return bufs, lens


def build_ring_on_device(
    server_bufs: jax.Array,  # uint8[S, L] padded server-name bytes
    server_lens: jax.Array,  # int32[S]
    replica_points: int = DEFAULT_REPLICA_POINTS,
    name_rank: jax.Array | None = None,  # int32[S] lexicographic rank
) -> DeviceRing:
    """Fully on-device build: hash every ``server + str(i)`` replica name
    (ring.js:54-57 concatenation) with the jittable farmhash kernel, then
    sort.  Useful when the server set derives from simulation state.

    Replica-hash ties break by ``name_rank`` (each server's rank in
    name-sorted order — what the host ring's (hash, server) tuple order
    does).  Without it, ties break by position in ``server_bufs``; pass
    name-sorted servers or supply ``name_rank`` for bit-parity with the
    host ring on 32-bit hash collisions."""
    if replica_points > 1000:
        raise ValueError(
            "device ring build supports at most 1000 replica points"
            " (3-decimal-digit replica suffixes)"
        )
    s, max_len = server_bufs.shape
    digit_bytes = np.zeros((replica_points, 3), dtype=np.uint8)
    digit_lens = np.zeros((replica_points,), dtype=np.int32)
    for i in range(replica_points):
        b = str(i).encode()
        digit_bytes[i, : len(b)] = np.frombuffer(b, dtype=np.uint8)
        digit_lens[i] = len(b)
    digit_bytes = jnp.asarray(digit_bytes)
    digit_lens = jnp.asarray(digit_lens)

    out_len = max(max_len + 3, 25)  # farmhash kernel's minimum buffer
    col = jnp.arange(out_len)
    srv_pad = jnp.pad(server_bufs, ((0, 0), (0, out_len - max_len)))
    rel = col[None, None, :] - server_lens[:, None, None]  # [S, 1, out_len]
    rel = jnp.broadcast_to(rel, (s, replica_points, out_len))
    in_server = col[None, None, :] < server_lens[:, None, None]
    in_digit = (rel >= 0) & (rel < digit_lens[None, :, None])
    digit_vals = jnp.take_along_axis(
        jnp.broadcast_to(digit_bytes[None, :, :], (s, replica_points, 3)),
        jnp.clip(rel, 0, 2),
        axis=2,
    )
    names = jnp.where(
        jnp.broadcast_to(in_server, rel.shape),
        jnp.broadcast_to(srv_pad[:, None, :], rel.shape),
        jnp.where(in_digit, digit_vals, 0),
    ).astype(jnp.uint8)
    lens = (server_lens[:, None] + digit_lens[None, :]).astype(jnp.int32)

    hashes = farmhash32_batch_jax(
        names.reshape(s * replica_points, out_len),
        lens.reshape(s * replica_points),
    )
    owners = jnp.repeat(jnp.arange(s, dtype=jnp.int32), replica_points)
    tie = owners if name_rank is None else jnp.asarray(name_rank)[owners]
    order = jnp.lexsort((tie, hashes))
    return DeviceRing(hashes=hashes[order], owners=owners[order])


def lookup_idx(ring: DeviceRing, key_hashes: jax.Array) -> jax.Array:
    """Owner index per key hash — ``searchsorted`` with wraparound.

    ``side='left'`` makes an exact hash hit own itself (the reference's
    equality-inclusive upperBound, rbtree.js:262-271).

    The ring must be non-empty: the host ``HashRing.lookup`` returns
    ``None`` on an empty ring (ring.js:139-147), but a fixed-shape device
    lookup has no None — callers gate on membership before building."""
    if ring.size == 0:
        raise ValueError("lookup on an empty DeviceRing (no servers)")
    idx = jnp.searchsorted(ring.hashes, key_hashes, side="left")
    idx = idx % ring.size  # wrap to min (ring.js:142-145)
    return ring.owners[idx]


def lookup_keys(ring: DeviceRing, key_bufs: jax.Array, key_lens: jax.Array) -> jax.Array:
    """Hash keys on device (farmhash32) then resolve owners."""
    return lookup_idx(ring, farmhash32_batch_jax(key_bufs, key_lens))


def lookup_n_idx(
    ring: DeviceRing,
    key_hashes: jax.Array,
    n: int,
    window: int | None = None,
    in_ring: jax.Array | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Preference list per key: the first ``n`` distinct owners walking
    the ring clockwise with wraparound (ring.js:150-182 lookupN).

    Scans a static window of successive replicas (the probability that
    ``n`` distinct owners span more than W replicas decays geometrically
    with W).  Returns ``(owners int32[M, n], complete bool[M])``:
    ``complete[m]`` is False when the window ended before finding
    ``min(n, server_count)`` distinct owners — callers re-resolve those
    rows with a larger window (or the host ring) rather than trusting
    the -1 padding.

    ``in_ring`` (bool[M, S], optional) restricts key m's walk to the
    masked server subset — the traffic plane's per-viewer rings
    (bit-identical to a host ring built from exactly that subset; equal
    owners share a mask value, so the first-occurrence dedup is
    unchanged).  The completeness floor then counts each key's in-mask
    servers instead of the global server count."""
    if ring.size == 0:
        raise ValueError("lookupN on an empty DeviceRing (no servers)")
    if window is None:
        window = min(ring.size, 32 + 8 * n)
    window = min(window, ring.size)
    start = jnp.searchsorted(ring.hashes, key_hashes, side="left")
    offs = (start[:, None] + jnp.arange(window)[None, :]) % ring.size
    owners = ring.owners[offs]  # int32[M, W]
    # first (in-mask) occurrence of each owner within the walk
    eq = owners[:, :, None] == owners[:, None, :]
    earlier = jnp.tril(jnp.ones((window, window), dtype=bool), k=-1)
    first = ~jnp.any(eq & earlier[None, :, :], axis=2)
    if in_ring is not None:
        first = first & jnp.take_along_axis(in_ring, owners, axis=1)
    rank = jnp.cumsum(first.astype(jnp.int32), axis=1) - 1
    m = key_hashes.shape[0]
    rows = jnp.broadcast_to(jnp.arange(m)[:, None], owners.shape)
    # invalid slots scatter to column n, which mode="drop" discards
    cols = jnp.where(first & (rank < n), rank, n)
    out = jnp.full((m, n), -1, dtype=jnp.int32)
    out = out.at[rows, cols].set(owners, mode="drop")
    if in_ring is None:
        server_count: jax.Array = jnp.max(ring.owners) + 1
    else:
        server_count = jnp.sum(in_ring.astype(jnp.int32), axis=1)
    found = jnp.sum(first.astype(jnp.int32), axis=1)
    complete = (found >= jnp.minimum(n, server_count)) | (window >= ring.size)
    return out, complete

"""Pallas TPU kernel: batched FarmHash32 (Fingerprint32).

The jnp implementation (farmhash_jax.py) expresses each dynamic byte
fetch as a per-row ``dynamic_slice`` under ``vmap``, which XLA lowers to
general gathers — serialized scalar traffic on TPU.  This kernel
restructures the algorithm for the VPU:

* one VMEM tile holds a block of rows; a **word plane** ``W[:, i]`` =
  little-endian uint32 at byte offset ``i`` is built once from four
  shifted static slices;
* every *data-dependent* fetch (the head/tail reads whose offsets depend
  on the string length) becomes a **masked reduction** over the word
  plane — an 8x128 vector op, no gather;
* the main >24-byte loop reads at *static* offsets (it always starts at
  byte 0), so it unrolls into plain slices;
* all four length variants are computed branchlessly and selected per
  row, exactly like the jnp version.

Bit-identical to ops/farmhash.py (C / Python) and farmhash_jax.py —
cross-checked in tests/test_farmhash_pallas.py, which also runs the
kernel in interpret mode so CPU CI covers it.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

# Constants above 2**31 cannot appear as raw Python ints (x64-disabled
# canonicalization overflows) nor as module-level jnp arrays (pallas
# rejects captured consts) — _u32 creates scalar literals at trace time.
def _u32(x: int):
    return jnp.uint32(x)


_C2 = 0x1B873593  # < 2**31: safe as a weak Python int


def _c1():
    return _u32(0xCC9E2D51)


def _magic():
    return _u32(0xE6546B64)

ROW_BLOCK = 128


def _rotr(v, s: int):
    if s == 0:
        return v
    return (v >> s) | (v << (32 - s))


def _fmix(h):
    h = h ^ (h >> 16)
    h = h * _u32(0x85EBCA6B)
    h = h ^ (h >> 13)
    h = h * _u32(0xC2B2AE35)
    h = h ^ (h >> 16)
    return h


def _mur(a, h):
    a = a * _c1()
    a = _rotr(a, 17)
    a = a * _C2
    h = h ^ a
    h = _rotr(h, 19)
    return h * 5 + _magic()


def _kernel(bufs_ref, lens_ref, out_ref):
    bytes_u32 = bufs_ref[:].astype(jnp.uint32)  # [RB, L]
    rb, L = bytes_u32.shape
    n = lens_ref[:].astype(jnp.int32)  # [RB, 1]
    nu = n.astype(jnp.uint32)

    zero_col = jnp.zeros((rb, 1), dtype=jnp.uint32)

    def shifted(k: int):
        return jnp.concatenate(
            [bytes_u32[:, k:]] + [zero_col] * k, axis=1
        )

    # word plane: W[:, i] = le-uint32 at byte offset i (i > L-4: garbage,
    # never selected — offsets are clamped to L-4 like _fetch32's clip)
    W = (
        bytes_u32
        | (shifted(1) << 8)
        | (shifted(2) << 16)
        | (shifted(3) << 24)
    )
    col = lax.broadcasted_iota(jnp.int32, (rb, L), 1)

    def fetch(off):  # off int32[RB, 1] -> uint32[RB, 1]
        off = jnp.clip(off, 0, L - 4)
        return jnp.sum(
            jnp.where(col == off, W, 0),
            axis=1,
            keepdims=True,
            dtype=jnp.uint32,
        )

    def static_fetch(i: int):  # compile-time offset
        return W[:, i : i + 1]

    # -- len 0..4 ----------------------------------------------------------
    b = jnp.zeros((rb, 1), dtype=jnp.uint32)
    c = jnp.full((rb, 1), 9, dtype=jnp.uint32)
    for i in range(4):
        byte = bytes_u32[:, i : i + 1]
        v = jnp.where(byte >= 128, byte - 256, byte)  # signed char
        nb = b * _c1() + v
        nc = c ^ nb
        take = i < n
        b = jnp.where(take, nb, b)
        c = jnp.where(take, nc, c)
    h04 = _fmix(_mur(b, _mur(nu, c)))

    # -- len 5..12 ---------------------------------------------------------
    a5 = nu + static_fetch(0)
    b5 = nu * 5 + fetch(n - 4)
    c5 = 9 + fetch((n >> 1) & 4)
    d5 = nu * 5
    h512 = _fmix(_mur(c5, _mur(b5, _mur(a5, d5))))

    # -- len 13..24 --------------------------------------------------------
    a = fetch((n >> 1) - 4)
    bb = static_fetch(4)
    cc = fetch(n - 8)
    d = fetch(n >> 1)
    e = static_fetch(0)
    f = fetch(n - 4)
    h = d * _c1() + nu
    a = _rotr(a, 12) + f
    h = _mur(cc, h) + a
    a = _rotr(a, 3) + cc
    h = _mur(e, h) + a
    a = _rotr(a + f, 12) + d
    h = _mur(bb, h) + a
    h1324 = _fmix(h)

    # -- len > 24 ----------------------------------------------------------
    h = nu
    g = _c1() * nu
    f = g
    a0 = _rotr(fetch(n - 4) * _c1(), 17) * _C2
    a1 = _rotr(fetch(n - 8) * _c1(), 17) * _C2
    a2 = _rotr(fetch(n - 16) * _c1(), 17) * _C2
    a3 = _rotr(fetch(n - 12) * _c1(), 17) * _C2
    a4 = _rotr(fetch(n - 20) * _c1(), 17) * _C2
    h = h ^ a0
    h = _rotr(h, 19)
    h = h * 5 + _magic()
    h = h ^ a2
    h = _rotr(h, 19)
    h = h * 5 + _magic()
    g = g ^ a1
    g = _rotr(g, 19)
    g = g * 5 + _magic()
    g = g ^ a3
    g = _rotr(g, 19)
    g = g * 5 + _magic()
    f = f + a4
    f = _rotr(f, 19) + 113
    iters = (n - 1) // 20
    for i in range((L - 1) // 20):  # static max; predicated per row
        off = i * 20
        a = static_fetch(off)
        bq = static_fetch(off + 4)
        cq = static_fetch(off + 8)
        dq = static_fetch(off + 12)
        eq = static_fetch(off + 16)
        nh = h + a
        ng = g + bq
        nf = f + cq
        nh = _mur(dq, nh) + eq
        ng = _mur(cq, ng) + a
        nf = _mur(bq + eq * _c1(), nf) + dq
        nf = nf + ng
        ng = ng + nf
        take = i < iters
        h = jnp.where(take, nh, h)
        g = jnp.where(take, ng, g)
        f = jnp.where(take, nf, f)
    g = _rotr(g, 11) * _c1()
    g = _rotr(g, 17) * _c1()
    f = _rotr(f, 11) * _c1()
    f = _rotr(f, 17) * _c1()
    h = _rotr(h + g, 19)
    h = h * 5 + _magic()
    h = _rotr(h, 17) * _c1()
    h = _rotr(h + f, 19)
    h = h * 5 + _magic()
    hlong = _rotr(h, 17) * _c1()

    out_ref[:] = jnp.where(
        n <= 4, h04, jnp.where(n <= 12, h512, jnp.where(n <= 24, h1324, hlong))
    )


@functools.partial(jax.jit, static_argnames=("interpret",))
def farmhash32_batch_pallas(
    bufs: jax.Array, lens: jax.Array, interpret: bool = False
) -> jax.Array:
    """Fingerprint32 per row: bufs uint8[B, L] (L >= 25), lens int32[B].

    Bit-identical to ``farmhash32_batch_jax``; rows are processed in
    VMEM blocks of ``ROW_BLOCK``.  ``interpret=True`` runs the kernel in
    the Pallas interpreter (CPU testing)."""
    if bufs.shape[1] < 25:
        raise ValueError("pad buffers to at least 25 bytes")
    b, L = bufs.shape
    padded = pl.cdiv(b, ROW_BLOCK) * ROW_BLOCK
    if padded != b:
        bufs = jnp.pad(bufs, ((0, padded - b), (0, 0)))
        lens = jnp.pad(lens, (0, padded - b))
    out = pl.pallas_call(
        _kernel,
        out_shape=jax.ShapeDtypeStruct((padded, 1), jnp.uint32),
        grid=(padded // ROW_BLOCK,),
        in_specs=[
            pl.BlockSpec((ROW_BLOCK, L), lambda i: (i, 0)),
            pl.BlockSpec((ROW_BLOCK, 1), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((ROW_BLOCK, 1), lambda i: (i, 0)),
        interpret=interpret,
    )(bufs, lens.astype(jnp.int32)[:, None])
    return out[:b, 0]

"""Transport layer — the TChannel replacement.

The reference injects a TChannel subchannel and calls
``channel.request({host, timeout}).send(endpoint, head, body, cb)``
(lib/swim/ping-sender.js:57-99), with 14 endpoints registered server-side
(server/index.js:32-75).  This rebuild defines a minimal transport
interface with two implementations:

* ``InProcessNetwork`` / ``InProcessChannel`` — deterministic in-process
  message passing on the shared scheduler, with latency and fault
  injection (drop/partition/pause/kill) — the test/sim harness transport.
* ``TcpChannel`` (transport/tcp.py) — newline-delimited JSON frames over
  asyncio TCP for real multi-process clusters (CLI mode).
"""

from ringpop_tpu.transport.inproc import InProcessChannel, InProcessNetwork, TimeoutError_

__all__ = ["InProcessChannel", "InProcessNetwork", "TimeoutError_"]

"""Deterministic in-process transport with fault injection.

Replaces the reference's TChannel for single-process multi-node clusters
(the shape of test/lib/test-ringpop-cluster.js) and doubles as the fault
injector that tick-cluster.js implements with SIGSTOP/SIGKILL
(tick-cluster.js:418-471): ``pause`` = black-hole (timeouts), ``kill`` =
fast connection errors, ``partition`` = block-structured reachability.
"""

from __future__ import annotations

from typing import Any, Callable

Handler = Callable[[Any, Any, str, Callable[..., None]], None]


class TimeoutError_(Exception):
    type = "ringpop.transport.timeout"


class ConnectionRefusedError_(Exception):
    type = "ringpop.transport.connection-refused"


class InProcessNetwork:
    """Registry + message scheduler shared by all in-process channels."""

    def __init__(self, scheduler, latency_ms: float = 1.0, rng=None):
        self.scheduler = scheduler
        self.latency_ms = latency_ms
        self.rng = rng
        self.endpoints: dict[str, dict[str, Handler]] = {}
        self.paused: set[str] = set()
        self.killed: set[str] = set()
        self.partition_of: dict[str, int] = {}
        self.drop_rate = 0.0
        self.message_count = 0

    # -- fault injection -----------------------------------------------------

    def pause(self, host: str) -> None:
        """SIGSTOP analog: messages to/from host vanish (requests time out)."""
        self.paused.add(host)

    def resume(self, host: str) -> None:
        self.paused.discard(host)

    def kill(self, host: str) -> None:
        """SIGKILL analog: requests fail fast with connection refused."""
        self.killed.add(host)
        self.endpoints.pop(host, None)

    def revive(self, host: str) -> None:
        self.killed.discard(host)

    def partition(self, groups: dict[str, int]) -> None:
        """Assign hosts to partition ids; cross-partition traffic is dropped."""
        self.partition_of = dict(groups)

    def heal_partition(self) -> None:
        self.partition_of = {}

    def set_drop_rate(self, rate: float) -> None:
        """Random packet loss applied per request round-trip."""
        self.drop_rate = rate

    # -- registry ------------------------------------------------------------

    def register(self, host: str, endpoints: dict[str, Handler]) -> None:
        self.endpoints[host] = endpoints

    def unregister(self, host: str) -> None:
        self.endpoints.pop(host, None)

    # -- delivery ------------------------------------------------------------

    def _reachable(self, src: str, dst: str) -> bool:
        if src in self.paused or dst in self.paused:
            return False
        if self.partition_of:
            if self.partition_of.get(src, 0) != self.partition_of.get(dst, 0):
                return False
        if self.drop_rate > 0 and self.rng is not None:
            if self.rng.random() < self.drop_rate:
                return False
        return True

    def request(
        self,
        src: str,
        dst: str,
        endpoint: str,
        head: Any,
        body: Any,
        timeout_ms: float,
        callback: Callable[..., None],
    ) -> None:
        self.message_count += 1
        state = {"done": False}

        def finish(err: Any, res1: Any = None, res2: Any = None) -> None:
            if state["done"]:
                return
            state["done"] = True
            self.scheduler.cancel(timeout_timer)
            callback(err, res1, res2)

        def on_timeout() -> None:
            finish(TimeoutError_(f"request to {dst} {endpoint} timed out"))

        timeout_timer = self.scheduler.call_later(timeout_ms, on_timeout)

        if src in self.killed:
            # A killed process cannot send; swallow the request entirely.
            return
        if dst in self.killed:
            self.scheduler.call_later(
                self.latency_ms,
                lambda: finish(ConnectionRefusedError_(f"connection refused: {dst}")),
            )
            return

        if not self._reachable(src, dst):
            # Black hole: let the timeout fire.
            return

        def deliver() -> None:
            table = self.endpoints.get(dst)
            if table is None or endpoint not in table:
                finish(ConnectionRefusedError_(f"no handler at {dst} {endpoint}"))
                return

            def respond(err: Any, res1: Any = None, res2: Any = None) -> None:
                # Response leg is subject to the same reachability rules.
                if not self._reachable(dst, src):
                    return
                self.scheduler.call_later(
                    self.latency_ms, lambda: finish(err, res1, res2)
                )

            table[endpoint](head, body, src, respond)

        self.scheduler.call_later(self.latency_ms, deliver)


class InProcessChannel:
    """Per-node channel bound to an InProcessNetwork (TChannel stand-in)."""

    def __init__(self, network: InProcessNetwork, host_port: str):
        self.network = network
        self.host_port = host_port
        self.destroyed = False

    def register(self, endpoints: dict[str, Handler]) -> None:
        self.network.register(self.host_port, endpoints)

    def request(
        self,
        host: str,
        endpoint: str,
        head: Any,
        body: Any,
        timeout_ms: float,
        callback: Callable[..., None],
    ) -> None:
        if self.destroyed:
            self.network.scheduler.call_soon(
                lambda: callback(ConnectionRefusedError_("channel destroyed"))
            )
            return
        self.network.request(
            self.host_port, host, endpoint, head, body, timeout_ms, callback
        )

    def close(self) -> None:
        self.destroyed = True
        self.network.unregister(self.host_port)

"""Asyncio TCP transport: newline-delimited JSON frames.

The TChannel replacement for real multi-process clusters (SURVEY §5.8).
The reference's wire pattern — ``channel.request({host, timeout,
serviceName:'ringpop'}).send(endpoint, head, body, cb)`` with JSON-string
bodies (lib/swim/ping-sender.js:57-99) and 14 server endpoints
(server/index.js:32-75) — maps to:

* one persistent TCP connection per peer (dialed lazily, like TChannel's
  ``waitForIdentified`` — ping-sender.js:81-90),
* request frame  ``{"t":"req","id":N,"ep":endpoint,"src":hostPort,
  "head":str|null,"body":str|null}``,
* response frame ``{"t":"res","id":N,"err":{type,message}|null,
  "res1":str|null,"res2":str|null}``,

each JSON-encoded on a single ``\n``-terminated line (JSON escapes interior
newlines, so the framing is unambiguous).

``TcpChannel`` implements the same channel interface as
``InProcessChannel`` (register/request/close/destroyed), so ``RingPop``
code is transport-agnostic.  It must run inside an asyncio event loop —
pair it with ``clock.AsyncioScheduler``.
"""

from __future__ import annotations

import asyncio
import json
from typing import Any, Callable

from ringpop_tpu.errors import RingpopError

Handler = Callable[[Any, Any, str, Callable[..., None]], None]

MAX_FRAME_BYTES = 16 * 1024 * 1024


class TransportTimeoutError(RingpopError):
    """Request timed out waiting for a response frame."""

    type = "ringpop.transport.timeout"


class TransportConnectionError(RingpopError):
    """Peer unreachable / connection refused or dropped."""

    type = "ringpop.transport.connection-refused"


class RemoteError(RingpopError):
    """An error returned by the remote handler, reconstructed locally."""

    type = "ringpop.remote-error"

    def __init__(self, type_: str, message: str):
        super().__init__(message)
        self.type = type_ or "ringpop.remote-error"


def _err_to_wire(err: Any) -> dict | None:
    if err is None:
        return None
    return {"type": getattr(err, "type", "error"), "message": str(err)}


def _err_from_wire(obj: Any) -> Any:
    if not obj:
        return None
    return RemoteError(obj.get("type"), obj.get("message") or "")


def parse_host_port(host_port: str) -> tuple[str, int]:
    host, port = host_port.rsplit(":", 1)
    return host, int(port)


class _Conn:
    """One live TCP connection (either direction) with frame dispatch."""

    def __init__(self, channel: "TcpChannel", reader, writer):
        self.channel = channel
        self.reader = reader
        self.writer = writer
        self.closed = False
        self.reader_task = asyncio.ensure_future(self._read_loop())

    def send_frame(self, frame: dict) -> None:
        if self.closed:
            return
        try:
            self.writer.write(json.dumps(frame).encode() + b"\n")
        except Exception:
            self.close()

    async def _read_loop(self) -> None:
        try:
            while True:
                # readline raises (LimitOverrunError wrapped in ValueError)
                # past the stream limit — set to MAX_FRAME_BYTES at
                # connection setup; the default 64 KiB would kill the conn
                # on any full-sync/stats body of a few hundred members.
                line = await self.reader.readline()
                if not line:
                    break
                if len(line) > MAX_FRAME_BYTES:
                    break
                try:
                    frame = json.loads(line)
                except ValueError:
                    break
                self.channel._on_frame(self, frame)
        except (asyncio.CancelledError, ConnectionError, OSError, ValueError):
            pass  # ValueError: oversized/garbage frame — close deliberately
        finally:
            self.close()

    def close(self) -> None:
        if self.closed:
            return
        self.closed = True
        try:
            self.writer.close()
        except Exception:
            pass
        self.channel._on_conn_closed(self)


class TcpChannel:
    """Per-node TCP channel.  Call ``await listen()`` before bootstrap."""

    def __init__(self, host_port: str, loop=None):
        self.host_port = host_port
        self.loop = loop or asyncio.get_event_loop()
        self.destroyed = False
        self.endpoints: dict[str, Handler] = {}
        self.server: asyncio.AbstractServer | None = None
        self._next_id = 1
        # id -> (callback, timeout_handle, dest)
        self._pending: dict[int, tuple[Callable[..., None], Any, str]] = {}
        self._conns: set[_Conn] = set()
        self._peer_conn: dict[str, _Conn] = {}
        self._dialing: dict[str, list[tuple[dict, float]]] = {}

    # -- lifecycle -----------------------------------------------------------

    async def listen(self) -> None:
        host, port = parse_host_port(self.host_port)
        self.server = await asyncio.start_server(
            self._on_accept, host, port, limit=MAX_FRAME_BYTES
        )

    def _on_accept(self, reader, writer) -> None:
        if self.destroyed:
            writer.close()
            return
        self._conns.add(_Conn(self, reader, writer))

    def close(self) -> None:
        self.destroyed = True
        if self.server is not None:
            self.server.close()
            self.server = None
        for conn in list(self._conns):
            conn.close()
        for req_id in list(self._pending):
            self._fail_pending(req_id, TransportConnectionError("channel destroyed"))

    # -- channel interface ---------------------------------------------------

    def register(self, endpoints: dict[str, Handler]) -> None:
        self.endpoints.update(endpoints)

    def request(
        self,
        host: str,
        endpoint: str,
        head: Any,
        body: Any,
        timeout_ms: float,
        callback: Callable[..., None],
    ) -> None:
        if self.destroyed:
            self.loop.call_soon(
                lambda: callback(TransportConnectionError("channel destroyed"))
            )
            return
        req_id = self._next_id
        self._next_id += 1
        frame = {
            "t": "req",
            "id": req_id,
            "ep": endpoint,
            "src": self.host_port,
            "head": head,
            "body": body,
        }
        timeout_handle = self.loop.call_later(
            max(0.0, timeout_ms) / 1000.0,
            lambda: self._fail_pending(
                req_id, TransportTimeoutError(f"request to {host} {endpoint} timed out")
            ),
        )
        self._pending[req_id] = (callback, timeout_handle, host)
        conn = self._peer_conn.get(host)
        if conn is not None and not conn.closed:
            conn.send_frame(frame)
        elif host in self._dialing:
            self._dialing[host].append((frame, timeout_ms))
        else:
            self._dialing[host] = [(frame, timeout_ms)]
            asyncio.ensure_future(self._dial(host))

    async def _dial(self, host: str) -> None:
        try:
            h, p = parse_host_port(host)
            reader, writer = await asyncio.open_connection(h, p, limit=MAX_FRAME_BYTES)
        except (ConnectionError, OSError, ValueError) as e:
            queued = self._dialing.pop(host, [])
            for frame, _ in queued:
                self._fail_pending(
                    frame["id"],
                    TransportConnectionError(f"connection refused: {host} ({e})"),
                )
            return
        if self.destroyed:  # closed while the dial was in flight
            writer.close()
            self._dialing.pop(host, None)
            return
        conn = _Conn(self, reader, writer)
        self._conns.add(conn)
        self._peer_conn[host] = conn
        for frame, _ in self._dialing.pop(host, []):
            conn.send_frame(frame)

    # -- frame dispatch ------------------------------------------------------

    def _on_frame(self, conn: _Conn, frame: dict) -> None:
        if frame.get("t") == "req":
            self._handle_request(conn, frame)
        elif frame.get("t") == "res":
            self._handle_response(frame)

    def _handle_request(self, conn: _Conn, frame: dict) -> None:
        endpoint = frame.get("ep")
        req_id = frame.get("id")
        src = frame.get("src") or "?"
        # Learn the reverse route: the dialer's listening address serves
        # as its identity (TChannel "identified" semantics).
        if src != "?" and src not in self._peer_conn:
            self._peer_conn[src] = conn
        handler = self.endpoints.get(endpoint)
        state = {"done": False}

        def respond(err: Any = None, res1: Any = None, res2: Any = None) -> None:
            if state["done"]:
                return
            state["done"] = True
            conn.send_frame(
                {
                    "t": "res",
                    "id": req_id,
                    "err": _err_to_wire(err),
                    "res1": res1,
                    "res2": res2,
                }
            )

        if handler is None:
            respond(TransportConnectionError(f"no handler for {endpoint}"))
            return
        try:
            handler(frame.get("head"), frame.get("body"), src, respond)
        except Exception as e:  # handler bug: surface, don't kill the loop
            respond(RingpopError(f"handler error on {endpoint}: {e!r}"))

    def _handle_response(self, frame: dict) -> None:
        entry = self._pending.pop(frame.get("id"), None)
        if entry is None:
            return
        callback, timeout_handle, _ = entry
        timeout_handle.cancel()
        callback(_err_from_wire(frame.get("err")), frame.get("res1"), frame.get("res2"))

    def _fail_pending(self, req_id: int, err: Exception) -> None:
        entry = self._pending.pop(req_id, None)
        if entry is None:
            return
        callback, timeout_handle, _ = entry
        timeout_handle.cancel()
        callback(err)

    def _on_conn_closed(self, conn: _Conn) -> None:
        self._conns.discard(conn)
        dead_hosts = {host for host, peer in self._peer_conn.items() if peer is conn}
        for host in dead_hosts:
            del self._peer_conn[host]
        # Fail requests that were in flight to those peers.
        if not self.destroyed:
            for req_id, (_, _, host) in list(self._pending.items()):
                if host in dead_hosts:
                    self._fail_pending(
                        req_id, TransportConnectionError(f"connection lost: {host}")
                    )

"""SWIM incarnation-precedence lattice (reference: lib/membership-update-rules.js).

Six pure predicates deciding whether a gossiped change overrides the local
view of a member.  These exact rules are also implemented as vectorized
boolean algebra in the TPU simulation kernel (models/swim_sim.py) — the two
must stay in lockstep (tested in tests/test_sim_parity.py).
"""

from __future__ import annotations

from typing import Any

from ringpop_tpu.member import Member, Status


def is_alive_override(member: Member, change: dict[str, Any]) -> bool:
    """Alive beats any status with a strictly newer incarnation (:25-29)."""
    return (
        change.get("status") == Status.alive
        and member.status in Status.ALL
        and change.get("incarnationNumber") > member.incarnation_number
    )


def is_faulty_override(member: Member, change: dict[str, Any]) -> bool:
    """Faulty beats suspect/alive at >= incarnation, faulty at > (:31-36)."""
    if change.get("status") != Status.faulty:
        return False
    inc = change.get("incarnationNumber")
    return (
        (member.status == Status.suspect and inc >= member.incarnation_number)
        or (member.status == Status.faulty and inc > member.incarnation_number)
        or (member.status == Status.alive and inc >= member.incarnation_number)
    )


def is_leave_override(member: Member, change: dict[str, Any]) -> bool:
    """Leave beats any non-leave at >= incarnation (:38-42)."""
    return (
        change.get("status") == Status.leave
        and member.status != Status.leave
        and change.get("incarnationNumber") >= member.incarnation_number
    )


def is_suspect_override(member: Member, change: dict[str, Any]) -> bool:
    """Suspect beats alive at >=, suspect/faulty at > (:54-59)."""
    if change.get("status") != Status.suspect:
        return False
    inc = change.get("incarnationNumber")
    return (
        (member.status == Status.suspect and inc > member.incarnation_number)
        or (member.status == Status.faulty and inc > member.incarnation_number)
        or (member.status == Status.alive and inc >= member.incarnation_number)
    )


def is_local_faulty_override(local_address: str, member: Member, change: dict[str, Any]) -> bool:
    """Any faulty rumor about self triggers refutation (:44-47)."""
    return local_address == member.address and change.get("status") == Status.faulty


def is_local_suspect_override(local_address: str, member: Member, change: dict[str, Any]) -> bool:
    """Any suspect rumor about self triggers refutation (:49-52)."""
    return local_address == member.address and change.get("status") == Status.suspect

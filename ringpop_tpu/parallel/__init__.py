"""Multi-chip scale-out for the SWIM simulation (jax.sharding over a Mesh).

The reference scales by adding processes connected over TChannel
(docs/architecture_design.md:87-105); the TPU build scales by sharding the
N and N x N state tensors across a device mesh and letting XLA place the
cross-chip exchanges on ICI — see ``ringpop_tpu.parallel.mesh``.
"""

from ringpop_tpu.parallel.mesh import (
    delta_state_sharding,
    make_mesh,
    net_sharding,
    shard_cluster,
    shard_delta,
    sharded_delta_run,
    sharded_delta_step,
    sharded_step,
    sharded_run,
    state_sharding,
)

__all__ = [
    "delta_state_sharding",
    "make_mesh",
    "net_sharding",
    "shard_cluster",
    "shard_delta",
    "sharded_delta_run",
    "sharded_delta_step",
    "sharded_step",
    "sharded_run",
    "state_sharding",
]

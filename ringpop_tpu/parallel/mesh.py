"""Device-mesh sharding of the SWIM simulation state.

Layout ("viewer-row" sharding over a 1-D mesh axis ``nodes``):

* every N x N view/buffer tensor is sharded along axis 0 — each chip owns
  the complete *views of* a contiguous block of virtual nodes (all state a
  real node would own locally lives on one chip, like the reference's
  process-per-node ownership, lib/membership.js);
* per-node vectors (``up``, ``responsive``) are replicated — O(N) bools,
  read by arbitrary-index gathers on every step;
* ``adj`` (N x N connectivity) is row-sharded like the views;
* the PRNG key and the tick counter are replicated.

Cross-chip traffic is exactly the simulated network traffic: a probe from
viewer block A to a target on block B is a scatter into another chip's
rows, which XLA lowers to collectives over ICI. This mirrors how the real
cluster's gossip rides the physical network, except the "network" here is
the TPU interconnect. (The reference's TChannel/NCCL-style point-to-point
RPC — SURVEY §5.8 — has no place in an SPMD program; collectives are the
TPU-native equivalent.)

Scaling: one chip's HBM bounds N at roughly sqrt(HBM / ~6 bytes); row
sharding across D chips raises the bound by sqrt(D) at fixed per-chip
memory, which is how the 65k-node BASELINE config is reached on a pod
slice.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ringpop_tpu.models.swim_sim import (
    ClusterState,
    NetState,
    SwimParams,
    swim_run_impl,
    swim_step_impl,
)

AXIS = "nodes"


def make_mesh(n_devices: int | None = None, devices: Any = None) -> Mesh:
    """A 1-D mesh over ``n_devices`` (default: all) devices."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"requested {n_devices} devices, only {len(devices)} available"
            )
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (AXIS,))


def state_sharding(mesh: Mesh, damping: bool = False) -> ClusterState:
    """Pytree of NamedShardings matching ClusterState.  ``damping``
    must match whether the state carries damp tensors (init_state)."""
    row = NamedSharding(mesh, P(AXIS, None))
    rep = NamedSharding(mesh, P())
    return ClusterState(
        view_key=row,
        pb=row,
        suspect_left=row,
        tick=rep,
        damp=row if damping else None,
        damped=row if damping else None,
    )


def net_sharding(mesh: Mesh, like: NetState | None = None) -> NetState:
    """Shardings for ``NetState``; default assumes the healthy network
    (``adj=None``, the ``make_net`` default) — pass ``like=net`` when the
    net carries a materialized adjacency mask."""
    rep = NamedSharding(mesh, P())
    has_adj = like is not None and like.adj is not None
    if not has_adj:
        adj = None
    elif like.adj.ndim == 1:  # group-id vector: O(N), replicate
        adj = rep
    else:
        adj = NamedSharding(mesh, P(AXIS, None))
    return NetState(up=rep, responsive=rep, adj=adj)


def shard_cluster(
    state: ClusterState, net: NetState, mesh: Mesh
) -> tuple[ClusterState, NetState]:
    """Place an (unsharded) simulation onto the mesh."""
    n = state.n
    d = mesh.devices.size
    if n % d != 0:
        raise ValueError(f"n={n} must be divisible by mesh size {d}")
    damping = state.damp is not None
    return (
        jax.device_put(state, state_sharding(mesh, damping)),
        jax.device_put(net, net_sharding(mesh, like=net)),
    )


def sharded_step(
    mesh: Mesh,
    damping: bool = False,
    like: ClusterState | None = None,
    net_like: NetState | None = None,
) -> Callable:
    """``swim_step`` compiled for the mesh: (state, net, key, params) ->
    (state, metrics), state rows pinned to their owning chips.

    Pass ``like=state`` / ``net_like=net`` to infer the damping/adjacency
    layout from the values themselves (a mismatched manual flag fails
    deep inside jit with an opaque pytree-structure error)."""
    if like is not None:
        damping = like.damp is not None
    rep = NamedSharding(mesh, P())
    return jax.jit(
        swim_step_impl,
        static_argnames=("params",),
        in_shardings=(
            state_sharding(mesh, damping),
            net_sharding(mesh, like=net_like),
            rep,
        ),
        out_shardings=(state_sharding(mesh, damping), rep),
        donate_argnums=(0,),
    )


def sharded_run(
    mesh: Mesh,
    damping: bool = False,
    like: ClusterState | None = None,
    net_like: NetState | None = None,
) -> Callable:
    """``swim_run`` (lax.scan over ticks) compiled for the mesh.  See
    ``sharded_step`` for ``like``/``net_like``."""
    if like is not None:
        damping = like.damp is not None
    rep = NamedSharding(mesh, P())
    return jax.jit(
        swim_run_impl,
        static_argnames=("params", "ticks"),
        in_shardings=(
            state_sharding(mesh, damping),
            net_sharding(mesh, like=net_like),
            rep,
        ),
        out_shardings=(state_sharding(mesh, damping), rep),
        donate_argnums=(0,),
    )
